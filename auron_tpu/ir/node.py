"""Base machinery for IR nodes: a registry + reflective dict serde.

Every expr/plan node is a frozen dataclass subclassing `Node` with a unique
`kind` tag; `to_dict`/`from_dict` recurse over dataclass fields, handling
nested nodes, DataType/Field/Schema, tuples and scalars.  This gives the IR
a canonical JSON form (the wire format a front-end targets), mirroring what
auron.proto's protobuf encoding provides in the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Any, ClassVar, Dict, Type

from auron_tpu.ir.schema import DataType, Field, Schema, TypeId

_REGISTRY: Dict[str, Type["Node"]] = {}


def register(cls: Type["Node"]) -> Type["Node"]:
    kind = cls.kind
    if kind in _REGISTRY:
        raise ValueError(f"duplicate IR node kind {kind!r}")
    _REGISTRY[kind] = cls
    return cls


def _encode(v: Any) -> Any:
    if isinstance(v, Node):
        return v.to_dict()
    if isinstance(v, DataType):
        out: Dict[str, Any] = {"@type": v.id.name}
        if v.id == TypeId.DECIMAL:
            out["precision"], out["scale"] = v.precision, v.scale
        if v.children:
            out["children"] = [_encode(f) for f in v.children]
        return out
    if isinstance(v, Field):
        return {"@field": v.name, "dtype": _encode(v.dtype), "nullable": v.nullable}
    if isinstance(v, Schema):
        return {"@schema": [_encode(f) for f in v.fields]}
    if isinstance(v, tuple):
        return [_encode(x) for x in v]
    if isinstance(v, (list,)):
        return [_encode(x) for x in v]
    if isinstance(v, bytes):
        import base64
        return {"@bytes": base64.b64encode(v).decode("ascii")}
    import decimal
    if isinstance(v, decimal.Decimal):
        # decimal literals (p>18 hybrid plans) have no JSON form; tag the
        # exact string representation
        return {"@decimal": str(v)}
    if isinstance(v, float):
        # JSON has no inf/nan literal; tag them
        import math
        if math.isnan(v):
            return {"@float": "nan"}
        if math.isinf(v):
            return {"@float": "inf" if v > 0 else "-inf"}
        return v
    return v


def _decode(v: Any) -> Any:
    if isinstance(v, dict):
        if "@kind" in v:
            return Node.from_dict(v)
        if "@type" in v:
            tid = TypeId[v["@type"]]
            children = tuple(_decode(c) for c in v.get("children", []))
            return DataType(tid, precision=v.get("precision", 0),
                            scale=v.get("scale", 0), children=children)
        if "@field" in v:
            return Field(v["@field"], _decode(v["dtype"]), v.get("nullable", True))
        if "@schema" in v:
            return Schema(tuple(_decode(f) for f in v["@schema"]))
        if "@bytes" in v:
            import base64
            return base64.b64decode(v["@bytes"])
        if "@decimal" in v:
            import decimal
            return decimal.Decimal(v["@decimal"])
        if "@float" in v:
            # exact tag set _encode emits; anything else is a corrupt
            # document and must not silently decode to nan
            tag = v["@float"]
            special = {"nan": float("nan"), "inf": float("inf"),
                       "-inf": float("-inf")}
            if tag not in special:
                raise ValueError(f"bad @float tag {tag!r} "
                                 f"(expected nan/inf/-inf)")
            return special[tag]
        return {k: _decode(x) for k, x in v.items()}
    if isinstance(v, list):
        return tuple(_decode(x) for x in v)
    return v


@dataclasses.dataclass(frozen=True)
class Node:
    kind: ClassVar[str] = "node"

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"@kind": self.kind}
        for f in dataclasses.fields(self):
            out[f.name] = _encode(getattr(self, f.name))
        return out

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Node":
        cls = _REGISTRY[d["@kind"]]
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name in d:
                kwargs[f.name] = _decode(d[f.name])
        return cls(**kwargs)  # type: ignore[call-arg]

    def children_nodes(self):
        """All direct child Nodes (exprs or plans), for tree walks."""
        out = []
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Node):
                out.append(v)
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, Node):
                        out.append(x)
                    elif isinstance(x, tuple):
                        out.extend(y for y in x if isinstance(y, Node))
        return out

    def transform_up(self, fn):
        """Bottom-up rewrite: rebuild with transformed children, then apply fn.

        Handles Nodes nested arbitrarily deep inside tuples (e.g.
        Expand.projections is a tuple of tuples of exprs).

        Depth bound: the rewrite is inherently recursive (a rebuilt child
        must exist before its parent is rebuilt), so tree depth is limited
        by the Python recursion limit minus caller headroom — comfortably
        thousands of plan levels, far past any real TPC-DS plan.  Pure
        traversals must NOT be built on transform_up: use ir.plan.walk /
        plan_children, which are iterative and unbounded.  A plan too deep
        for the limit raises RecursionError annotated with the node kind
        instead of an anonymous stack overflow."""

        def rec(v: Any) -> Any:
            if isinstance(v, Node):
                return v.transform_up(fn)
            if isinstance(v, tuple):
                return tuple(rec(x) for x in v)
            return v

        changes = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, (Node, tuple)):
                try:
                    nv = rec(v)
                except RecursionError as e:
                    if e.args and "transform_up" in str(e.args[0]):
                        raise
                    raise RecursionError(
                        f"transform_up exceeded the recursion limit below "
                        f"a {self.kind!r} node; use ir.plan.walk for "
                        f"traversals of very deep plans") from e
                if nv != v:
                    changes[f.name] = nv
        node = dataclasses.replace(self, **changes) if changes else self
        return fn(node)


def tree_has_kind(node: "Node", kinds) -> bool:
    """True when any node of a kind in `kinds` appears in the (sub)tree,
    recursing through Node fields and tuples (arbitrarily nested)."""
    if getattr(node, "kind", None) in kinds:
        return True

    def rec(v: Any) -> bool:
        if isinstance(v, Node):
            return tree_has_kind(v, kinds)
        if isinstance(v, tuple):
            return any(rec(x) for x in v)
        return False

    for f in dataclasses.fields(node):
        if rec(getattr(node, f.name)):
            return True
    return False
