"""IR serialization: canonical JSON + compressed binary envelope.

This is the wire format a front-end ships `TaskDefinition`s in — the
analogue of the protobuf bytes the reference fetches from the JVM
(rt.rs:79-84 getRawTaskDefinition / AuronCallNativeWrapper.java:170-183).
Binary envelope: magic "ATPU" + u8 version + u8 codec + zstd/zlib/raw JSON.
"""

from __future__ import annotations

import json
import struct
from typing import Any

from auron_tpu.ir.node import Node

MAGIC = b"ATPU"
VERSION = 1
_CODEC_RAW, _CODEC_ZSTD, _CODEC_ZLIB = 0, 1, 2


def to_json(node: Node) -> str:
    return json.dumps(node.to_dict(), separators=(",", ":"), sort_keys=True)


def from_json(s: str) -> Node:
    return Node.from_dict(json.loads(s))


def _zstd():
    """The zstandard module, or None when not installed (the envelope
    gates on it: zstd degrades to zlib, recorded in the codec byte, so
    deserialize stays self-describing)."""
    try:
        import zstandard
        return zstandard
    except ImportError:
        return None


def serialize(node: Node, codec: str = "zstd") -> bytes:
    payload = to_json(node).encode("utf-8")
    if codec == "zstd" and _zstd() is None:
        codec = "zlib"
    if codec == "zstd":
        body, cid = _zstd().ZstdCompressor(level=3).compress(payload), _CODEC_ZSTD
    elif codec == "zlib":
        import zlib
        body, cid = zlib.compress(payload, 6), _CODEC_ZLIB
    elif codec == "raw":
        body, cid = payload, _CODEC_RAW
    else:
        raise ValueError(f"unknown codec {codec!r}")
    return MAGIC + struct.pack("<BB", VERSION, cid) + body


def deserialize(data: bytes) -> Node:
    if data[:4] != MAGIC:
        raise ValueError("bad IR envelope magic")
    version, cid = struct.unpack_from("<BB", data, 4)
    if version != VERSION:
        raise ValueError(f"unsupported IR version {version}")
    body = data[6:]
    if cid == _CODEC_ZSTD:
        zstandard = _zstd()
        if zstandard is None:
            raise RuntimeError("zstd-compressed IR envelope but the "
                               "zstandard module is not installed")
        payload = zstandard.ZstdDecompressor().decompress(body)
    elif cid == _CODEC_ZLIB:
        import zlib
        payload = zlib.decompress(body)
    elif cid == _CODEC_RAW:
        payload = body
    else:
        raise ValueError(f"unknown codec id {cid}")
    return from_json(payload.decode("utf-8"))


def roundtrip(node: Node) -> Node:
    """Serialize+deserialize (used by golden tests)."""
    return deserialize(serialize(node))
