"""QueryScheduler: concurrent query lifecycles over one process.

The serving tier's state machine.  Each submission is a foreign plan +
a per-query conf map + a priority; the scheduler drives admitted
submissions on their own driver threads (one `AuronSession` per query —
sessions are single-execute objects; the PROCESS-level pools they share
are lock-protected), while the memory admission controller
(serving/admission.py) and the fair-share task pool
(runtime/task_pool.py) arbitrate the shared resources underneath.

States::

    queued -> running -> succeeded | failed | cancelled
    queued ----------------------------------^ (cancel while waiting)
    (submit) -> shed      (admission queue full — never started)
    running -> queued     (PREEMPTED: kill-and-requeue under overload)

Overload survival (this layer's half; memmgr/manager.py owns the
per-query budgets): the scheduler installs a memory PRESSURE HOOK —
when pool usage crosses `auron.serving.preempt.watermark` of the
effective budget it selects a running victim (lowest effective
priority, most over forecast), cancels it through the task pool's
fast-fail path and REQUEUES the submission with its original conf
overlay; re-execution is bit-identical to a solo run (the chaos
contract).  Requeued and long-queued submissions age
(`auron.admission.aging.seconds` bumps effective priority per waited
interval, clamped) so a stream of high-priority arrivals cannot
starve them.

Isolation per query: the driver enters `conf.query_scoped(submission
conf)` (contextvar overlay — other queries never see it) and executes
under the submission's query id, so trace spans, log prefixes, the
`/queries` history row and the per-query attribution counters
(tracing.QueryStats) all key on the id `/status/<id>` answers for.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from auron_tpu import config
from auron_tpu.frontend.foreign import ForeignNode
from auron_tpu.runtime import lockcheck, task_pool
from auron_tpu.serving.admission import ADMIT, AdmissionController
from auron_tpu.serving.forecast import plan_signature

log = logging.getLogger("auron_tpu.serving")

QUEUED = "queued"
RUNNING = "running"
SUCCEEDED = "succeeded"
FAILED = "failed"
CANCELLED = "cancelled"
SHED_STATE = "shed"


class SubmissionRejected(RuntimeError):
    """Raised by submit() when the submission is shed (queue full)."""


@dataclass
class Submission:
    query_id: str
    plan: ForeignNode
    conf: Dict[str, Any]
    priority: int
    signature: str
    state: str = QUEUED
    seq: int = 0
    submitted_at: float = field(default_factory=time.time)
    # queue-entry time: == submitted_at at first, reset on requeue —
    # the clock priority aging and the queue timeout run against
    queued_since: float = 0.0
    started_at: Optional[float] = None
    finished_at: Optional[float] = None
    forecast_bytes: int = 0
    serial: bool = False          # degraded-to-serial admission
    admission_reason: str = ""
    error: Optional[str] = None
    rows: int = 0
    wall_s: float = 0.0
    result: Optional[object] = None   # pa.Table on success
    mem_peak: int = 0
    num_preemptions: int = 0      # kill-and-requeue count
    done: threading.Event = field(default_factory=threading.Event)
    # lifecycle timeline: ordered state transitions with wall times
    # (submitted -> queued -> admitted -> dispatched -> running ->
    # preempted/requeued -> resumed -> terminal), surfaced with
    # per-state durations on /status/<id> and the QueryRecord
    timeline: List[Dict[str, Any]] = field(default_factory=list)
    # wall time of the first blocked admission offer (feeds the
    # auron_query_admission_wait_seconds histogram); reset on requeue
    admission_blocked_at: Optional[float] = None

    def __post_init__(self) -> None:
        if not self.queued_since:
            self.queued_since = self.submitted_at
        from auron_tpu.runtime.tracing import timeline_mark
        timeline_mark(self.timeline, "submitted", self.submitted_at)
        timeline_mark(self.timeline, "queued", self.queued_since)

    def mark(self, state: str, t: Optional[float] = None) -> None:
        from auron_tpu.runtime.tracing import timeline_mark
        timeline_mark(self.timeline, state, t)

    def effective_priority(self, aging_s: float,
                           now: Optional[float] = None) -> int:
        """Declared priority plus one per full `aging_s` interval waited
        in the queue (clamped to the task pool's weight ceiling of 64);
        aging off (<= 0) returns the declared priority."""
        if aging_s <= 0:
            return self.priority
        waited = (now if now is not None else time.time()) \
            - self.queued_since
        return min(64, self.priority + max(0, int(waited / aging_s)))

    def status(self) -> Dict[str, Any]:
        from auron_tpu.runtime.tracing import timeline_durations
        waited = (self.started_at or self.finished_at or time.time()) \
            - self.submitted_at
        aging = float(config.conf.get("auron.admission.aging.seconds"))
        return {"query_id": self.query_id, "state": self.state,
                "priority": self.priority,
                "effective_priority": self.effective_priority(aging),
                "signature": self.signature,
                "submitted_at": self.submitted_at,
                "queue_wait_s": round(max(0.0, waited), 4),
                "forecast_bytes": self.forecast_bytes,
                "degraded_serial": self.serial,
                "admission": self.admission_reason,
                "rows": self.rows, "wall_s": round(self.wall_s, 4),
                "mem_peak": self.mem_peak,
                "preemptions": self.num_preemptions,
                "timeline": list(self.timeline),
                "state_durations": {
                    k: round(v, 4) for k, v in
                    timeline_durations(self.timeline).items()},
                "error": self.error}

    def mark_started(self) -> None:
        """Timeline + latency-histogram bookkeeping at the queued ->
        running transition: queue wait (and the admission-blocked slice
        of it) land in the /metrics histograms; a submission that was
        preempted or requeued re-enters as `resumed`."""
        from auron_tpu.runtime import counters
        now = self.started_at or time.time()
        counters.observe("query_queue_wait_seconds",
                         max(0.0, now - self.queued_since))
        if self.admission_blocked_at is not None:
            counters.observe("query_admission_wait_seconds",
                             max(0.0, now - self.admission_blocked_at))
            self.admission_blocked_at = None
        resumed = any(e["state"] in ("preempted", "requeued")
                      for e in self.timeline)
        self.mark("admitted", now)
        if self.dispatched_marker:
            self.mark("dispatched", now)
        self.mark("resumed" if resumed else "running", now)

    # fleet submissions insert a `dispatched` state between admission
    # and running (the RPC hop to a worker process); the in-process
    # scheduler has no such hop
    dispatched_marker = False


def default_session_factory():
    """One AuronSession per query, the host oracle attached for any
    residual foreign sections (the IT runner's wiring)."""
    from auron_tpu.frontend.session import AuronSession
    from auron_tpu.it.oracle import PyArrowEngine
    return AuronSession(foreign_engine=PyArrowEngine())


class QueryScheduler:
    """Submission registry + admission queue + driver threads."""

    def __init__(self,
                 session_factory: Optional[Callable[[], Any]] = None,
                 admission: Optional[AdmissionController] = None):
        self._session_factory = session_factory or default_session_factory
        self.admission = admission or AdmissionController()
        self._lock = lockcheck.Lock("serving.scheduler")
        self._subs: Dict[str, Submission] = {}
        self._queue: List[Submission] = []    # admission wait line
        self._running = 0
        self._seq = 0
        self._shutdown = False
        self._last_preempt = 0.0   # monotonic; preemption cooldown
        # watermark preemption: the memory manager calls _on_pressure
        # (outside its lock) whenever an accounting update leaves pool
        # usage above watermark * effective budget; <= 0 disables.
        # Last-constructed scheduler wins the hook; shutdown() releases
        # it (only if still ours).
        frac = float(config.conf.get("auron.serving.preempt.watermark"))
        if frac > 0:
            from auron_tpu.memmgr import manager as mem_manager
            mem_manager.set_pressure_hook(self._on_pressure, frac)

    # -- submission --------------------------------------------------------

    def submit(self, plan: ForeignNode,
               conf: Optional[Dict[str, Any]] = None,
               priority: Optional[int] = None,
               query_id: Optional[str] = None) -> str:
        """Register a query; returns its id immediately (poll `status`/
        `wait`).  Raises SubmissionRejected when shed."""
        from auron_tpu.runtime import counters, tracing
        if self._shutdown:
            raise SubmissionRejected("scheduler is shut down")
        overrides = dict(conf or {})
        # validate the per-query conf NOW (the _QueryScoped constructor
        # parses against option types): a bad submission conf is a 400
        # at submit, never a failed query minutes later
        config.conf.query_scoped(overrides)
        if priority is None:
            priority = int(overrides.get("auron.query.priority",
                                         config.conf.get(
                                             "auron.query.priority")))
        qid = query_id or tracing.new_query_id()
        sub = Submission(query_id=qid, plan=plan, conf=overrides,
                         priority=int(priority),
                         signature=plan_signature(plan))
        with self._lock:
            if qid in self._subs:
                raise SubmissionRejected(f"duplicate query id {qid!r}")
            if len(self._queue) >= \
                    int(config.conf.get("auron.admission.queue.max")):
                sub.state = SHED_STATE
                sub.error = "shed: admission queue full"
                sub.mark(SHED_STATE)
                sub.done.set()
                self._subs[qid] = sub
                counters.bump("admission_shed")
                self.admission.events["shed"] += 1
                from auron_tpu.runtime import events
                events.emit("query.shed", sub.error, [qid],
                            queue_len=len(self._queue))
                exc = SubmissionRejected(sub.error)
                # Retry-After hint for the 429: how long until the
                # admission ledger has likely drained one wave
                exc.retry_after_s = self.admission.drain_estimate_s(
                    len(self._queue))
                raise exc
            self._seq += 1
            sub.seq = self._seq
            self._subs[qid] = sub
            self._queue.append(sub)
        counters.bump("queries_submitted")
        self._pump()
        return qid

    # -- the pump: start whatever fits -------------------------------------

    def _pump(self) -> None:
        while True:
            to_start: Optional[Submission] = None
            with self._lock:
                if self._shutdown or not self._queue:
                    return
                # expire BEFORE the concurrency check: a queued
                # submission times out on schedule even while every
                # driver slot is busy (its /status and /result flip to
                # the timeout failure immediately, not when a slot
                # happens to free up)
                self._expire_locked()
                if not self._queue:
                    return
                max_conc = int(config.conf.get(
                    "auron.serving.max.concurrent"))
                if self._running >= max_conc:
                    return
                # highest EFFECTIVE priority first (declared priority +
                # aging, so requeued/long-queued submissions climb past
                # fresher high-priority arrivals), FIFO within a level
                aging = float(config.conf.get(
                    "auron.admission.aging.seconds"))
                now = time.time()
                head = min(self._queue,
                           key=lambda s: (-s.effective_priority(aging,
                                                                now),
                                          s.seq))
                decision = self.admission.offer(
                    head.query_id, head.signature,
                    queue_len=len(self._queue) - 1,
                    count_queue_event=head.admission_reason == "")
                head.admission_reason = decision.reason
                head.forecast_bytes = decision.forecast_bytes
                if decision.action != ADMIT:
                    # head-of-line blocking is deliberate: starting a
                    # smaller later query over the head forever would
                    # starve big queries (FIFO fairness within the gate)
                    if head.admission_blocked_at is None:
                        head.admission_blocked_at = now
                    return
                head.serial = decision.serial
                self._queue.remove(head)
                head.state = RUNNING
                head.started_at = time.time()
                head.mark_started()
                self._running += 1
                to_start = head
            t = threading.Thread(target=self._drive, args=(to_start,),
                                 name=f"auron-driver-{to_start.query_id}",
                                 daemon=True)
            t.start()

    def _expire_locked(self) -> None:
        timeout = float(config.conf.get(
            "auron.admission.queue.timeout.seconds"))
        if timeout <= 0:
            return
        now = time.time()
        for sub in list(self._queue):
            if now - sub.queued_since > timeout:
                self._queue.remove(sub)
                sub.state = FAILED
                sub.error = f"admission timeout after {timeout:g}s"
                sub.finished_at = now
                sub.mark(FAILED, now)
                sub.done.set()

    # -- driver thread -----------------------------------------------------

    def _drive(self, sub: Submission) -> None:
        from auron_tpu.runtime import counters, tracing
        from auron_tpu.runtime.explain_analyze import metric_max
        overlay = dict(sub.conf)
        overlay["auron.query.priority"] = sub.priority
        if sub.serial:
            # admission degraded the query: shrink its instantaneous
            # footprint (one partition at a time, no SPMD program)
            overlay["auron.task.parallelism"] = 1
            overlay["auron.spmd.singleDevice.enable"] = False
        requeue = False
        # stage-boundary admission re-forecast (runtime/adaptive.py):
        # when adaptive execution observes an exchange's real size, the
        # session routes its estimate through this hook into the SAME
        # reforecast path heartbeat telemetry feeds — a query that
        # turns out light releases reservation before it finishes
        from auron_tpu.runtime import adaptive
        adaptive.set_reforecast_hook(
            sub.query_id,
            lambda est, age, _q=sub.query_id:
            self.admission.reforecast(_q, est, age))
        # durable stats: the session-level record fires with a minimal
        # running->terminal timeline; defer its fold so the ONE store
        # entry carries the full queued->admitted->... machine this
        # driver patches in below (no-op with the store unarmed)
        from auron_tpu.runtime import statshist
        statshist.defer(sub.query_id)
        try:
            # session construction INSIDE the overlay: the per-query
            # conf governs construction-time choices too (e.g. the
            # fleet's durable-shuffle routing selects the session's
            # shuffle service)
            with config.conf.query_scoped(overlay):
                if bool(config.conf.get(
                        "auron.serving.result.stream.enable")):
                    # arm (or RESET, on a requeued attempt — a
                    # preempted run's partial frames must never leak
                    # into the re-execution) the incremental result
                    # stream the /result/<id>?format=arrow drain serves
                    from auron_tpu.runtime import result_stream
                    result_stream.register(sub.query_id)
                session = self._session_factory()
                res = session.execute(sub.plan, query_id=sub.query_id)
            sub.result = res.table
            sub.rows = res.table.num_rows
            sub.wall_s = res.wall_s
            sub.mem_peak = metric_max(res.metrics, "mem_peak")
            sub.state = SUCCEEDED
            if sub.mem_peak:
                self.admission.observe(sub.signature, sub.mem_peak)
        except task_pool.QueryCancelled:
            reason = task_pool.preempt_reason(sub.query_id)
            if reason is not None:
                # PREEMPTED (watermark pressure / over-budget kill) —
                # requeue with the ORIGINAL conf overlay and priority:
                # the re-execution is a fresh session over the same
                # plan, bit-identical to a solo run.  Past the per-
                # query cap the kill is final (forward progress).
                sub.num_preemptions += 1
                sub.mark("preempted")
                cap = int(config.conf.get(
                    "auron.serving.preempt.max.per.query"))
                if sub.num_preemptions <= cap:
                    requeue = True
                    log.info("query %s preempted (%d/%d): %s — "
                             "requeueing", sub.query_id,
                             sub.num_preemptions, cap, reason)
                else:
                    sub.state = FAILED
                    sub.error = (f"killed after {sub.num_preemptions} "
                                 f"preemptions: {reason}")
                    log.warning("query %s %s", sub.query_id, sub.error)
                    from auron_tpu.runtime import events
                    events.emit("query.kill", sub.error,
                                [sub.query_id],
                                preemptions=sub.num_preemptions)
            else:
                sub.state = CANCELLED
                sub.error = "cancelled"
                counters.bump("queries_cancelled")
        except BaseException as e:  # noqa: BLE001 - one red row
            sub.state = FAILED
            sub.error = f"{type(e).__name__}: {str(e)[:500]}"
            log.warning("query %s failed: %s", sub.query_id, sub.error)
        finally:
            # reservation released and the cancel/preempt mark cleared
            # BEFORE a requeue makes the submission runnable again —
            # a requeued run must start with a clean slate
            from auron_tpu.runtime import result_stream
            adaptive.clear_reforecast_hook(sub.query_id)
            if sub.state == SUCCEEDED:
                result_stream.mark_done(sub.query_id)
            elif not requeue:
                # failed/cancelled: nothing further will drain it
                result_stream.discard(sub.query_id)
            self.admission.release(sub.query_id)
            task_pool.clear_cancelled(sub.query_id)
            started = sub.started_at
            with self._lock:
                self._running -= 1
                if requeue and not self._shutdown:
                    sub.state = QUEUED
                    sub.started_at = None
                    sub.error = None
                    sub.admission_reason = ""   # fresh admission pass
                    sub.admission_blocked_at = None
                    sub.queued_since = time.time()
                    sub.mark("requeued", sub.queued_since)
                    self._queue.append(sub)
                elif requeue:
                    # shut down between kill and requeue: terminal
                    requeue = False
                    sub.state = CANCELLED
                    sub.error = "scheduler shut down during requeue"
            if requeue:
                counters.bump("requeues")
                from auron_tpu.runtime import events
                events.emit("query.requeue",
                            f"preempted query {sub.query_id} requeued",
                            [sub.query_id],
                            preemptions=sub.num_preemptions)
            else:
                sub.finished_at = time.time()
                sub.mark(sub.state, sub.finished_at)
                if started is not None:
                    counters.observe("query_exec_seconds",
                                     max(0.0, sub.finished_at - started))
            rec = tracing.find_query(sub.query_id)
            if rec is not None:
                # surface the kill-and-requeue count + the lifecycle
                # timeline on the /queries row
                rec.preemptions = sub.num_preemptions
                rec.timeline = list(sub.timeline)
                if not rec.signature:
                    rec.signature = sub.signature
            if not requeue:
                # the deferred durable-stats fold, now that the record
                # carries the full lifecycle timeline (a requeued run
                # re-defers and folds at its own terminal).  done.set()
                # strictly AFTER: a client observing terminal must find
                # the fold (and any regression verdict) already landed.
                try:
                    statshist.observe_deferred(sub.query_id, rec)
                finally:
                    sub.done.set()
            self._pump()

    # -- watermark preemption ----------------------------------------------

    def _on_pressure(self, total_used: int, effective_budget: int) -> None:
        """Memory-manager pressure hook (called OUTSIDE the manager
        lock on whatever thread's accounting update crossed the
        watermark): select a running victim — lowest effective
        priority first, most over forecast within a level — and
        preempt it through the task pool's fast-fail path.  The
        driver thread turns the resulting QueryCancelled into a
        requeue (_drive)."""
        if self._shutdown:
            return
        now = time.monotonic()
        cooldown = float(config.conf.get(
            "auron.serving.preempt.cooldown.seconds"))
        if now - self._last_preempt < cooldown:
            return   # cheap early-out before taking any lock
        victim: Optional[Submission] = None
        with self._lock:
            if self._shutdown or now - self._last_preempt < cooldown:
                return
            running = [s for s in self._subs.values()
                       if s.state == RUNNING and not s.done.is_set()]
            if len(running) < 2:
                # preempting the only running query cannot relieve
                # pressure — it would restart into the same pool
                return
            cap = int(config.conf.get(
                "auron.serving.preempt.max.per.query"))
            eligible = [s for s in running if s.num_preemptions < cap]
            if not eligible:
                return
            from auron_tpu.memmgr import get_manager
            ledger = get_manager().query_ledger()

            def overage(s: Submission) -> int:
                return ledger.get(s.query_id, {}).get("used", 0) \
                    - s.forecast_bytes

            aging = float(config.conf.get(
                "auron.admission.aging.seconds"))
            victim = min(eligible,
                         key=lambda s: (s.effective_priority(aging),
                                        -overage(s), -s.seq))
            self._last_preempt = now
        # outside the scheduler lock: preempt_query takes the pool's
        # cancellation lock and kicks the workers
        reason = (f"memory pressure: pool {total_used}B over watermark "
                  f"of effective budget {effective_budget}B")
        from auron_tpu.runtime import events
        events.emit("query.preempt", reason, [victim.query_id],
                    pool_used=total_used,
                    effective_budget=effective_budget)
        task_pool.preempt_query(victim.query_id, reason)

    # -- client surface ----------------------------------------------------

    def get(self, query_id: str) -> Optional[Submission]:
        with self._lock:
            return self._subs.get(query_id)

    def queued_ids(self) -> List[str]:
        """Query ids still waiting in the admission queue (oldest
        first) — the executor-server drain RPC reports these so a
        FleetManager can move them to another executor without
        touching running queries."""
        with self._lock:
            return [sub.query_id for sub in self._queue]

    def status(self, query_id: str) -> Optional[Dict[str, Any]]:
        sub = self.get(query_id)
        if sub is None:
            return None
        self._pump()   # piggyback: expire stale queue entries lazily
        return sub.status()

    def result(self, query_id: str):
        """The result table (pa.Table) of a succeeded query, else None."""
        sub = self.get(query_id)
        return sub.result if sub is not None else None

    def wait(self, query_id: str, timeout: Optional[float] = None) -> bool:
        """Block until the query finishes (True) or `timeout` elapses
        (False).  Polls the pump so queue timeouts expire even when no
        other submission/completion event fires."""
        sub = self.get(query_id)
        if sub is None:
            return False
        deadline = None if timeout is None else time.time() + timeout
        while True:
            remaining = None if deadline is None \
                else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return sub.done.is_set()
            slice_s = 0.2 if remaining is None else min(0.2, remaining)
            if sub.done.wait(slice_s):
                return True
            self._pump()

    def cancel(self, query_id: str) -> bool:
        """Cancel a queued (immediate) or running (fail-fast tasks)
        query; False once it already finished or is unknown."""
        from auron_tpu.runtime import counters
        with self._lock:
            sub = self._subs.get(query_id)
            if sub is None or sub.done.is_set():
                return False
            if sub.state == QUEUED:
                if sub in self._queue:
                    self._queue.remove(sub)
                sub.state = CANCELLED
                sub.error = "cancelled while queued"
                sub.finished_at = time.time()
                sub.mark(CANCELLED, sub.finished_at)
                sub.done.set()
                counters.bump("queries_cancelled")
                return True
        # running: the task pool fails its remaining tasks fast; the
        # driver thread ferries QueryCancelled and finishes the record
        task_pool.cancel_query(query_id)
        return True

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            states: Dict[str, int] = {}
            preemptions = 0
            for sub in self._subs.values():
                states[sub.state] = states.get(sub.state, 0) + 1
                preemptions += sub.num_preemptions
            queued = len(self._queue)
            running = self._running
        pool = task_pool._POOL
        return {"queued": queued, "running": running, "states": states,
                "preemptions": preemptions,
                "admission": self.admission.snapshot(),
                "task_queues": pool.queue_snapshot()
                if pool is not None else {}}

    def shutdown(self, wait: bool = False,
                 timeout: float = 30.0) -> None:
        from auron_tpu.memmgr import manager as mem_manager
        mem_manager.clear_pressure_hook(self._on_pressure)
        with self._lock:
            self._shutdown = True
            for sub in self._queue:
                sub.state = CANCELLED
                sub.error = "scheduler shut down"
                sub.finished_at = time.time()
                sub.mark(CANCELLED, sub.finished_at)
                sub.done.set()
            self._queue.clear()
            running = [s for s in self._subs.values()
                       if s.state == RUNNING]
        if wait:
            deadline = time.time() + timeout
            for sub in running:
                sub.done.wait(max(0.0, deadline - time.time()))
