"""Memory admission control for concurrent query serving.

Sparkle's observation (arXiv:1708.05746) is that on big-memory machines
the contended resource is the shared pool, not compute — so the serving
tier gates query START on memory, not on a thread count alone.  Each
submission gets a forecast (serving/forecast.py: recorded `mem_peak`
history for its plan signature, else the configured default) and the
controller keeps a ledger of forecasts reserved for currently-running
queries, enforced through `MemManager.add_reservation`: an admitted
query's forecast is carved out of the budget every OTHER consumer sees,
so concurrent queries spill toward their fair share instead of
over-committing the pool (conservative by construction — a reservation
also pressures its own query, which is safe: spills preserve results).

Decisions (`auron.admission.*` knobs):

- **admit** — ledger + forecast fits `memory.fraction * budget` (or the
  pool is idle: one query is always allowed, clamped to the cap).
- **degrade to serial** — a forecast above `degrade.serial.fraction *
  budget` runs with task parallelism 1 and no SPMD stage program, so
  its instantaneous footprint (concurrent partitions) shrinks instead
  of the query being refused.
- **queue** — does not fit now; waits for a running query to release
  its reservation (bounded by `queue.timeout.seconds`).
- **shed** — the queue itself is full (`queue.max`): reject with a
  structured error (HTTP 429 at the server) — bounded overload.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional

from auron_tpu.config import conf
from auron_tpu.runtime import lockcheck
from auron_tpu.serving.forecast import MemForecaster

ADMIT = "admit"
QUEUE = "queue"
SHED = "shed"


@dataclass
class AdmissionDecision:
    action: str            # admit | queue | shed
    forecast_bytes: int
    serial: bool = False   # degrade-to-serial overlay on admit
    reason: str = ""


class AdmissionController:
    """Forecast ledger + MemManager reservations for running queries.

    One controller is the SINGLE front-door ledger however many
    executors sit behind it: the fleet tier (serving/fleet.py) passes
    `budget_fn` (the federated total of the per-process MemManager
    budgets) and `executors_fn` (healthy executor count, so drain
    estimates account for fleet-wide wave width); the defaults — the
    local manager's budget and one executor — are the single-process
    serving shape."""

    def __init__(self, forecaster: Optional[MemForecaster] = None,
                 budget_fn: Optional[Callable[[], int]] = None,
                 executors_fn: Optional[Callable[[], int]] = None,
                 inflight_fn: Optional[Callable[[], int]] = None):
        self.forecaster = forecaster or MemForecaster()
        self._budget_fn = budget_fn
        self._executors_fn = executors_fn
        # live fleet-wide running count (heartbeat telemetry) — drain
        # estimates prefer it over the ledger when it is larger
        self._inflight_fn = inflight_fn
        self._lock = lockcheck.Lock("serving.admission")
        self._held: Dict[str, int] = {}    # query id -> reserved bytes
        # event counters (the serve_check gate asserts queue events)
        self.events: Dict[str, int] = {"admitted": 0, "queued": 0,
                                       "shed": 0, "degraded": 0,
                                       "reforecast": 0}
        # cross-restart admission: a fresh controller forecasts from
        # the durable stats store (no-op with the store unarmed)
        from auron_tpu.runtime import statshist
        statshist.seed_forecaster(self.forecaster)

    def _budget(self) -> int:
        if self._budget_fn is not None:
            return max(1, int(self._budget_fn()))
        from auron_tpu.memmgr import get_manager
        return max(1, get_manager().budget)

    def _executors(self) -> int:
        if self._executors_fn is not None:
            return max(1, int(self._executors_fn()))
        return 1

    # -- forecasting -------------------------------------------------------

    def forecast_for(self, signature: str) -> int:
        hist = self.forecaster.forecast(signature)
        if hist is None:
            return int(conf.get("auron.admission.default.forecast.bytes"))
        margin = float(conf.get("auron.admission.forecast.margin"))
        return int(hist * max(margin, 1.0))

    def observe(self, signature: str, peak_bytes: int) -> None:
        self.forecaster.record(signature, peak_bytes)

    # -- the decision ------------------------------------------------------

    def held_bytes(self) -> int:
        with self._lock:
            return sum(self._held.values())

    def offer(self, query_id: str, signature: str, queue_len: int,
              count_queue_event: bool = True) -> AdmissionDecision:
        """Decide for one submission; on ADMIT the forecast is reserved
        (release() MUST run when the query finishes).  The scheduler's
        pump re-offers QUEUED submissions as capacity frees up and
        passes count_queue_event=False so one submission counts one
        queue event, however often it is re-evaluated."""
        from auron_tpu.memmgr import get_manager
        from auron_tpu.runtime import counters

        if not conf.get("auron.admission.enable"):
            return AdmissionDecision(ADMIT, 0, reason="admission off")
        mgr = get_manager()
        budget = self._budget()
        forecast = self.forecast_for(signature)
        serial_frac = float(
            conf.get("auron.admission.degrade.serial.fraction"))
        serial = bool(serial_frac > 0 and
                      forecast > serial_frac * budget)
        cap = float(conf.get("auron.admission.memory.fraction")) * budget
        # a lone oversized query is admitted (clamped) rather than
        # queued forever: the pool can only help it by letting it run
        # and spill
        reserve = min(forecast, int(cap))
        with self._lock:
            held = sum(self._held.values())
            fits = held + reserve <= cap or not self._held
            if fits:
                self._held[query_id] = reserve
        if fits:
            mgr.add_reservation(f"admission:{query_id}", reserve)
            counters.bump("admission_admitted")
            self.events["admitted"] += 1
            if serial:
                counters.bump("admission_degraded")
                self.events["degraded"] += 1
            return AdmissionDecision(
                ADMIT, forecast, serial=serial,
                reason="fits" if not serial else
                "fits; degraded to serial (forecast "
                f"{forecast} > {serial_frac:g} * budget)")
        if queue_len >= int(conf.get("auron.admission.queue.max")):
            counters.bump("admission_shed")
            self.events["shed"] += 1
            return AdmissionDecision(
                SHED, forecast,
                reason=f"admission queue full ({queue_len})")
        if count_queue_event:
            counters.bump("admission_queued")
            self.events["queued"] += 1
        return AdmissionDecision(
            QUEUE, forecast,
            reason=f"ledger {held} + forecast {reserve} > cap {int(cap)}")

    def reforecast(self, query_id: str, live_peak_bytes: int,
                   age_s: float = 0.0) -> Optional[int]:
        """Adjust a RUNNING query's reservation from live heartbeat
        memory telemetry (the fleet calls this per probe) instead of
        only learning at completion: growth applies immediately (its
        neighbors must stop over-admitting against a forecast the
        query already exceeded), a shrink waits until the query is at
        least `auron.admission.reforecast.min.age.seconds` old (its
        peak may not have happened yet) and never drops below the
        observed live peak.  Returns the new reservation, or None when
        nothing changed."""
        if not conf.get("auron.admission.reforecast.enable") or \
                live_peak_bytes <= 0:
            return None
        margin = max(1.0, float(
            conf.get("auron.admission.forecast.margin")))
        target = int(live_peak_bytes * margin)
        cap = int(float(conf.get("auron.admission.memory.fraction"))
                  * self._budget())
        target = min(target, cap)
        min_age = float(
            conf.get("auron.admission.reforecast.min.age.seconds"))
        with self._lock:
            held = self._held.get(query_id)
            if held is None:
                return None            # finished/released concurrently
            if target <= held and age_s < min_age:
                return None
            if target == held:
                return None
            self._held[query_id] = target
            self.events["reforecast"] += 1
        from auron_tpu.memmgr import get_manager
        from auron_tpu.runtime import counters
        mgr = get_manager()
        mgr.release_reservations(f"admission:{query_id}")
        mgr.add_reservation(f"admission:{query_id}", target)
        counters.bump("admission_reforecasts")
        return target

    def drain_estimate_s(self, queue_len: int = 0) -> float:
        """Seconds until the ledger has plausibly drained enough to
        admit one more submission — the `Retry-After` hint on shed and
        queue-timeout HTTP responses.  Estimate: the average wall time
        of recently completed queries times the number of scheduling
        'waves' ahead of the caller (running reservations + queue
        depth over the concurrency), clamped to [1, 600].  A wave is
        `auron.serving.max.concurrent` slots on EVERY healthy executor
        — with N executors behind the front door a single-worker wave
        width would make the hint ~N× pessimistic."""
        import math

        from auron_tpu.runtime import tracing
        recent = [r.wall_s for r in tracing.query_history()[-8:]
                  if r.wall_s > 0]
        avg = sum(recent) / len(recent) if recent else 2.0
        with self._lock:
            held = len(self._held)
        if self._inflight_fn is not None:
            # live heartbeat telemetry beats the ledger when it sees
            # more work in flight (e.g. pass-through executor queues)
            try:
                held = max(held, int(self._inflight_fn()))
            except Exception:
                pass
        slots = max(1, int(conf.get("auron.serving.max.concurrent"))) \
            * self._executors()
        waves = math.ceil((held + max(0, queue_len) + 1) / slots)
        return max(1.0, min(600.0, avg * waves))

    def release(self, query_id: str) -> None:
        """Return the query's reservation to the pool (idempotent)."""
        from auron_tpu.memmgr import get_manager
        with self._lock:
            held = self._held.pop(query_id, None)
        if held is not None:
            get_manager().release_reservations(f"admission:{query_id}")

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"held_bytes": sum(self._held.values()),
                    "held_queries": len(self._held),
                    "events": dict(self.events),
                    "forecasts": self.forecaster.snapshot()}


class PassThroughAdmission(AdmissionController):
    """Admit everything, reserve nothing: the controller a per-executor
    QueryScheduler runs with when a FleetManager's controller is the
    single front-door ledger — gating (and reserving) a second time
    inside the executor would double-count every forecast."""

    def offer(self, query_id: str, signature: str, queue_len: int,
              count_queue_event: bool = True) -> AdmissionDecision:
        return AdmissionDecision(ADMIT, 0,
                                 reason="fleet front-door admission")

    def release(self, query_id: str) -> None:
        pass
