"""ExecutorEndpoint: the process-boundary seam of the serving tier.

Everything through PR 10 ran queries in ONE process, and three
process-local assumptions leaked into the serving code: the memory
manager's kill/pressure hooks, the ONE SharedTaskPool, and the
process-global counters.  This module hides all of them behind one
interface so the fleet tier (serving/fleet.py) can schedule across
process boundaries the way the reference schedules across JVM executors
(PAPER.md: NativeRDD rides Spark's task retry; executor death is a
routine event the driver plans around):

- ``ExecutorEndpoint`` — what a FleetManager needs from one executor:
  dispatch / heartbeat / status / result / cancel / drain / close.
- ``LocalExecutor`` — today's in-process path: a QueryScheduler driven
  directly (the default; bit-identical to pre-fleet serving).
- ``ExecutorServer`` — the slim executor server a worker process runs:
  the same QueryScheduler exposed over the existing framed-TCP wire
  (shuffle_rss.server framing, the service/ protocol family).  Run one
  with ``python -m auron_tpu.serving.executor_endpoint``.
- ``ProcessExecutor`` — the driver-side client for one worker process
  (spawn + supervise, or connect to an already-running server).

Every client RPC is classified and retried through the ONE retry policy
(runtime/retry.py) with a named ``fault_point`` per RPC family
(``fleet.dispatch`` / ``fleet.heartbeat`` / ``fleet.status`` /
``fleet.result`` / ``fleet.cancel`` / ``fleet.drain`` /
``fleet.shutdown``) so the chaos harness can exercise the process
boundary like any other recovery site.  Transport failures (a dead or
restarting worker) are retryable-IO; an answered-but-failed RPC ferries
an ``EndpointError`` that is DETERMINISTIC by classification — the
executor processed the request, replaying the transport cannot change
the answer — and the ``auron_retry_exhausted`` marker crosses the
process boundary with it, so an outer retry site never multiplies a
budget the worker already spent.
"""

from __future__ import annotations

import io
import json
import logging
import os
import socket
import socketserver
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import pyarrow as pa

from auron_tpu.config import conf
from auron_tpu.faults import fault_point
from auron_tpu.runtime import lockcheck, wirecheck
from auron_tpu.runtime.retry import RetryPolicy, call_with_retry
from auron_tpu.shuffle_rss.server import recv_msg, send_msg

log = logging.getLogger("auron_tpu.serving.fleet")

# server-ingress frame cap (untrusted); client receive is unbounded —
# result tables can legitimately be large
MAX_REQUEST_PAYLOAD = 1 << 31


class EndpointError(RuntimeError):
    """Structured failure ferried from an executor over the wire.

    Deterministic by default (`auron_deterministic`): the RPC reached
    the executor and was answered, so the shared retry policy must not
    replay the transport.  `exhausted` mirrors the worker-side
    ``auron_retry_exhausted`` marker across the process boundary;
    `draining` marks the graceful-decommission refusal (the fleet
    reroutes instead of failing the query)."""

    def __init__(self, message: str, deterministic: bool = True,
                 exhausted: bool = False, draining: bool = False):
        super().__init__(message)
        self.auron_deterministic = bool(deterministic)
        self.draining = bool(draining)
        if exhausted:
            self.auron_retry_exhausted = True


def _table_ipc(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def _table_from_ipc(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        return r.read_all()


# worker-side process counters mirrored into heartbeat loads: the fleet
# aggregates them for /metrics (the driver cannot read another
# process's counter registry) and tests assert resume-vs-recompute
# through them
_REPORTED_COUNTERS = (
    "rss_stage_skips", "rss_map_tasks_skipped", "rss_map_tasks_run",
    "rss_fetch_regens", "rss_degrades", "tasks_retried",
    "trace_dropped_events", "shuffle_bytes_pushed",
    "shuffle_bytes_fetched",
)


def endpoint_load(scheduler, draining: bool = False) -> Dict[str, Any]:
    """One executor's live load telemetry: scheduler queue depth +
    running count, memory-pool usage, per-query memory peaks (the
    admission re-forecast feed) and the mirrored process counters."""
    from auron_tpu.memmgr import get_manager
    from auron_tpu.runtime import counters
    stats = scheduler.stats()
    mgr = get_manager()
    mem = mgr.stats()
    return {"running": stats.get("running", 0),
            "queued": stats.get("queued", 0),
            "states": stats.get("states", {}),
            "draining": draining,
            "mem": {"used": mem.get("total_used", 0),
                    "budget": mem.get("budget", 0)},
            "query_mem": {qid: int(ent.get("peak") or
                                   ent.get("used") or 0)
                          for qid, ent in mgr.query_ledger().items()},
            "counters": {k: counters.get(k)
                         for k in _REPORTED_COUNTERS}}


def _serial_overlay(conf_map: Dict[str, Any],
                    serial: bool) -> Dict[str, Any]:
    """The degrade-to-serial conf the admission controller decided,
    applied as part of the per-query overlay (the executor-side
    scheduler runs with pass-through admission, so the fleet's decision
    has to travel with the dispatch)."""
    if not serial:
        return dict(conf_map)
    out = dict(conf_map)
    out["auron.task.parallelism"] = 1
    out["auron.spmd.singleDevice.enable"] = False
    return out


class ExecutorEndpoint:
    """One executor as the fleet sees it.  Implementations hide where
    the work runs; the fleet only ever talks in query ids."""

    executor_id: str
    # True when harvest() actually crosses a process boundary (the
    # fleet only stitches/records driver-side QueryRecords for remote
    # executors — an in-process LocalExecutor already records into the
    # driver's own history ring)
    supports_harvest = False

    def dispatch(self, query_id: str, plan, conf_map: Dict[str, Any],
                 priority: Optional[int], serial: bool = False) -> None:
        """Hand the executor a submission under `query_id` (unique per
        executor).  Raises on refusal (EndpointError) or transport
        failure after retries."""
        raise NotImplementedError

    def heartbeat(self, ids: Optional[List[str]] = None
                  ) -> Dict[str, Any]:
        """Liveness probe; returns ``{"load": {...}, "queries": {id:
        status-dict-or-None for each requested id}}``."""
        raise NotImplementedError

    def status(self, query_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def result(self, query_id: str) -> pa.Table:
        """The result table of a SUCCEEDED query (raises otherwise)."""
        raise NotImplementedError

    def cancel(self, query_id: str) -> bool:
        raise NotImplementedError

    def harvest(self, ids: List[str]) -> Dict[str, Any]:
        """Trace/record harvest riding the heartbeat cadence: for each
        requested query id, the executor's span increments (a running
        traced query is DRAINED — runtime/tracing.harvest_query) or its
        finished QueryRecord summary with residual spans.  Default: no
        cross-process state to ship ({})."""
        return {}

    def drain(self) -> List[str]:
        """Stop accepting dispatches and hand back the queued (never
        started) query ids so the caller can reroute them; running
        queries keep running."""
        raise NotImplementedError

    def kill(self) -> None:
        """Fence a dead-declared executor (best effort, idempotent):
        its in-flight queries are being requeued elsewhere, so a
        half-alive incarnation must not keep executing them."""

    def close(self) -> None:
        """Graceful teardown (shutdown RPC / scheduler shutdown)."""
        raise NotImplementedError

    def describe(self) -> Dict[str, Any]:
        return {"executor_id": self.executor_id,
                "kind": type(self).__name__}


# ---------------------------------------------------------------------------
# in-process endpoint (the default path — bit-identical to pre-fleet)
# ---------------------------------------------------------------------------

class LocalExecutor(ExecutorEndpoint):
    """Today's in-process execution behind the endpoint interface: a
    QueryScheduler with pass-through admission (the fleet's controller
    is the front door).  No sockets, no subprocesses — a fleet of one
    LocalExecutor is the single-process serving tier with a thin
    routing layer on top."""

    def __init__(self, executor_id: str = "local-0",
                 session_factory=None, scheduler=None):
        from auron_tpu.serving.admission import PassThroughAdmission
        from auron_tpu.serving.scheduler import QueryScheduler
        self.executor_id = executor_id
        self.scheduler = scheduler or QueryScheduler(
            session_factory=session_factory,
            admission=PassThroughAdmission())

    def dispatch(self, query_id: str, plan, conf_map: Dict[str, Any],
                 priority: Optional[int], serial: bool = False) -> None:
        from auron_tpu.serving.scheduler import SubmissionRejected
        try:
            self.scheduler.submit(plan,
                                  conf=_serial_overlay(conf_map, serial),
                                  priority=priority, query_id=query_id)
        except SubmissionRejected as e:
            raise EndpointError(str(e)) from e

    def heartbeat(self, ids: Optional[List[str]] = None
                  ) -> Dict[str, Any]:
        return {"executor_id": self.executor_id, "pid": os.getpid(),
                "now": time.time(),
                "load": endpoint_load(self.scheduler),
                "queries": {i: self.scheduler.status(i)
                            for i in (ids or [])}}

    def status(self, query_id: str) -> Optional[Dict[str, Any]]:
        return self.scheduler.status(query_id)

    def result(self, query_id: str) -> pa.Table:
        table = self.scheduler.result(query_id)
        if table is None:
            raise EndpointError(f"no result for query {query_id!r}")
        return table

    def cancel(self, query_id: str) -> bool:
        return self.scheduler.cancel(query_id)

    def drain(self) -> List[str]:
        moved = []
        for qid in self.scheduler.queued_ids():
            if self.scheduler.cancel(qid):
                moved.append(qid)
        return moved

    def close(self) -> None:
        self.scheduler.shutdown()


# ---------------------------------------------------------------------------
# the slim executor server (worker-process side)
# ---------------------------------------------------------------------------

class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _ExecHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: "ExecutorServer" = \
            self.server.exec_server  # type: ignore[attr-defined]
        sock = self.request
        from auron_tpu.shuffle_rss.server import read_timeout
        sock.settimeout(read_timeout())
        while True:
            try:
                header, payload = recv_msg(sock, MAX_REQUEST_PAYLOAD)
            except (ConnectionError, OSError, ValueError):
                return
            # version handshake (fix-forward, always on): refuse a
            # newer-major peer with a structured frame, then close
            refusal = wirecheck.peer_refusal(header)
            if refusal is not None:
                try:
                    send_msg(sock, wirecheck.refusal_frame(
                        "executor", refusal,
                        peer=f"{self.client_address[0]}:"
                             f"{self.client_address[1]}"))
                except (BrokenPipeError, ConnectionError, OSError):
                    pass
                return
            # shared-secret wire auth (since 1.1, always on like the
            # handshake): missing/wrong token -> structured
            # deterministic refusal, then close
            denied = wirecheck.auth_refusal(header)
            if denied is not None:
                try:
                    send_msg(sock, wirecheck.refusal_frame(
                        "executor", denied,
                        peer=f"{self.client_address[0]}:"
                             f"{self.client_address[1]}"))
                except (BrokenPipeError, ConnectionError, OSError):
                    pass
                return
            # frame conformance (enabled-only): answered in-band, the
            # connection survives
            problem = wirecheck.request_problem("executor", header)
            if problem is not None:
                try:
                    send_msg(sock, {"ok": False, "deterministic": True,
                                    "error": problem})
                except (BrokenPipeError, ConnectionError, OSError):
                    return
                continue
            wirecheck.note_frame("executor", header.get("cmd"))
            try:
                if not self._dispatch(server, sock, header, payload):
                    return
            except (BrokenPipeError, ConnectionError):
                return
            except BaseException as e:  # noqa: BLE001 - ferried in-band
                # an answered failure is DETERMINISTIC for the client's
                # retry policy; the exhausted marker crosses the wire
                try:
                    send_msg(sock, {
                        "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                        "deterministic": not getattr(
                            e, "auron_retryable", False),
                        "exhausted": bool(getattr(
                            e, "auron_retry_exhausted", False))})
                except (BrokenPipeError, ConnectionError, OSError):
                    return

    def _dispatch(self, server: "ExecutorServer", sock, header: dict,
                  payload: bytes) -> bool:
        cmd = header.get("cmd")
        sched = server.scheduler
        if cmd in ("ping", "hello"):
            send_msg(sock, {"ok": True,
                            "executor_id": server.executor_id,
                            "pid": os.getpid(),
                            "proto_version": wirecheck.proto_version()})
            return True
        if cmd == "heartbeat":
            ids = header.get("ids") or []
            send_msg(sock, {"ok": True,
                            "executor_id": server.executor_id,
                            "pid": os.getpid(),
                            "now": time.time(),
                            "load": server.load(),
                            "queries": {i: sched.status(i)
                                        for i in ids}})
            return True
        if cmd == "harvest":
            from auron_tpu.runtime import tracing
            traces = {}
            for qid in header.get("ids") or []:
                doc = tracing.harvest_query(str(qid))
                if doc is not None:
                    traces[qid] = doc
            # span batches ride the PAYLOAD: a traced query can carry
            # far more span JSON than the (untrusted-ingress) 1 MiB
            # header cap allows
            body = json.dumps(traces).encode()
            send_msg(sock, {"ok": True, "pid": os.getpid(),
                            "now": time.time(), "len": len(body)}, body)
            return True
        if cmd == "dispatch":
            if server.draining:
                send_msg(sock, {"ok": False, "draining": True,
                                "deterministic": True,
                                "error": "executor draining"})
                return True
            from auron_tpu.frontend.foreign import ForeignNode
            from auron_tpu.serving.scheduler import SubmissionRejected
            plan = ForeignNode.from_dict(json.loads(payload))
            try:
                sched.submit(plan, conf=header.get("conf") or {},
                             priority=header.get("priority"),
                             query_id=str(header.get("query_id")))
            except SubmissionRejected as e:
                send_msg(sock, {"ok": False, "deterministic": True,
                                "error": str(e)})
                return True
            send_msg(sock, {"ok": True})
            return True
        if cmd == "status":
            send_msg(sock, {"ok": True,
                            "status": sched.status(
                                str(header.get("query_id")))})
            return True
        if cmd == "result":
            qid = str(header.get("query_id"))
            sub = sched.get(qid)
            if sub is None or sub.result is None:
                state = sub.state if sub is not None else "unknown"
                send_msg(sock, {"ok": False, "deterministic": True,
                                "error": f"query {qid!r} has no result "
                                         f"(state {state})"})
                return True
            data = _table_ipc(sub.result)
            send_msg(sock, {"ok": True, "len": len(data),
                            "rows": sub.result.num_rows}, data)
            return True
        if cmd == "cancel":
            send_msg(sock, {"ok": True,
                            "cancelled": sched.cancel(
                                str(header.get("query_id")))})
            return True
        if cmd == "drain":
            server.set_draining()
            moved = []
            for qid in sched.queued_ids():
                if sched.cancel(qid):
                    moved.append(qid)
            send_msg(sock, {"ok": True, "moved": moved})
            return True
        if cmd == "shutdown":
            send_msg(sock, {"ok": True})
            threading.Thread(target=server.stop, daemon=True).start()
            return False
        send_msg(sock, {"ok": False, "deterministic": True,
                        "error": f"unknown cmd {cmd!r}"})
        return True


class ExecutorServer:
    """One worker process's serve loop: a QueryScheduler (pass-through
    admission — the fleet's controller is the front door) behind the
    framed-TCP wire.  Binds loopback by default; non-loopback
    deployments set `auron.net.auth.secret` so every frame carries a
    shared-secret token the server verifies."""

    def __init__(self, scheduler=None, session_factory=None,
                 executor_id: str = "exec-0",
                 host: str = "127.0.0.1", port: int = 0):
        from auron_tpu.serving.admission import PassThroughAdmission
        from auron_tpu.serving.scheduler import QueryScheduler
        self.executor_id = executor_id
        self.scheduler = scheduler or QueryScheduler(
            session_factory=session_factory,
            admission=PassThroughAdmission())
        self._draining = False
        self._lock = lockcheck.Lock("fleet.executor.server")
        self._tcp = _TCPServer((host, port), _ExecHandler)
        self._tcp.exec_server = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._tcp.server_address[:2]

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def set_draining(self) -> None:
        with self._lock:
            self._draining = True

    def load(self) -> Dict[str, Any]:
        return endpoint_load(self.scheduler, draining=self.draining)

    def start(self) -> "ExecutorServer":
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, daemon=True,
            name=f"auron-fleet-server-{self.executor_id}")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._tcp.serve_forever()

    def stop(self) -> None:
        self.scheduler.shutdown()
        self._tcp.shutdown()
        self._tcp.server_close()


# ---------------------------------------------------------------------------
# driver-side client for one worker process
# ---------------------------------------------------------------------------

class ProcessExecutor(ExecutorEndpoint):
    """Client for one ExecutorServer, optionally owning the worker
    process it spawned.  Connections are per-RPC (no shared socket
    state to corrupt when the worker dies mid-call), and every RPC
    rides the shared retry policy behind its named fault point."""

    supports_harvest = True

    def __init__(self, executor_id: str, host: str, port: int,
                 proc: Optional[subprocess.Popen] = None,
                 log_path: Optional[str] = None):
        self.executor_id = executor_id
        self.host, self.port = host, int(port)
        self.proc = proc
        self.log_path = log_path
        self._log_file = None        # spawn() attaches the stderr sink

    # -- process supervision ------------------------------------------------

    @classmethod
    def spawn(cls, executor_id: str,
              conf_map: Optional[Dict[str, Any]] = None,
              budget_bytes: int = 0,
              log_dir: Optional[str] = None,
              launcher=None) -> "ProcessExecutor":
        """Launch a worker process running `python -m
        auron_tpu.serving.executor_endpoint` and wait for its listening
        line (`auron.fleet.boot.timeout.seconds`).  `launcher` (a
        serving.fleet.WorkerLauncher) may wrap the argv — the
        ssh/k8s-shaped remote seam; None spawns locally as before."""
        from auron_tpu import config
        cmd = [sys.executable, "-m",
               "auron_tpu.serving.executor_endpoint",
               "--executor-id", executor_id, "--port", "0"]
        if conf_map:
            # redacted keys (the wire secret) never ride argv — they
            # are visible in /proc cmdline; workers read their own env
            cmd += ["--conf", json.dumps(
                config.redact_overlay(conf_map))]
        if budget_bytes:
            cmd += ["--budget", str(int(budget_bytes))]
        if launcher is not None:
            cmd = launcher.wrap(cmd)
        if log_dir is None:
            log_dir = tempfile.mkdtemp(prefix="auron-fleet-")
        log_path = os.path.join(log_dir, f"{executor_id}.log")
        log_file = open(log_path, "wb")  # noqa: SIM115 - worker lifetime
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=log_file, text=True,
                                env=dict(os.environ))
        timeout = float(conf.get("auron.fleet.boot.timeout.seconds"))
        info = cls._await_listening(proc, timeout, executor_id, log_path)
        ep = cls(executor_id, info["host"], info["port"], proc=proc,
                 log_path=log_path)
        ep._log_file = log_file
        # keep draining stdout so the worker can never block on a full
        # pipe (it prints almost nothing after the listening line)
        threading.Thread(target=cls._drain_stdout, args=(proc,),
                         daemon=True,
                         name=f"auron-fleet-stdout-{executor_id}").start()
        return ep

    @staticmethod
    def _await_listening(proc: subprocess.Popen, timeout: float,
                         executor_id: str, log_path: str) -> dict:
        box: Dict[str, Any] = {}

        def _read():
            for line in proc.stdout:   # scan past any stray output
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if doc.get("event") == "listening":
                    box["info"] = doc
                    return

        t = threading.Thread(target=_read, daemon=True)
        t.start()
        t.join(timeout)
        if "info" not in box:
            proc.kill()
            tail = ""
            try:
                with open(log_path, "rb") as f:
                    tail = f.read()[-2000:].decode("utf-8", "replace")
            except OSError:
                pass
            raise RuntimeError(
                f"executor {executor_id!r} did not report listening "
                f"within {timeout:g}s; log tail:\n{tail}")
        return box["info"]

    @staticmethod
    def _drain_stdout(proc: subprocess.Popen) -> None:
        try:
            for _ in proc.stdout:
                pass
        except (OSError, ValueError):
            pass

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    # -- the RPC spine ------------------------------------------------------

    def _timeout(self) -> Optional[float]:
        t = float(conf.get("auron.net.timeout.seconds"))
        return t if t > 0 else None

    def _rpc(self, site: str, header: dict, payload: bytes = b"",
             max_attempts: Optional[int] = None) -> Tuple[dict, bytes]:
        """One request/response over a fresh connection, retried
        through the shared policy.  Transport errors are retryable-IO;
        an answered failure raises EndpointError (deterministic, with
        the worker's exhausted marker mirrored)."""
        wirecheck.attach_token(header)
        wirecheck.check_request("executor", header)

        def _once():
            fault_point(f"fleet.{site}")
            s = socket.create_connection((self.host, self.port),
                                         timeout=self._timeout())
            try:
                send_msg(s, header, payload)
                resp, data = recv_msg(s)
            finally:
                try:
                    s.close()
                except OSError:
                    pass
            if not resp.get("ok", False):
                raise EndpointError(
                    resp.get("error", "rpc failed"),
                    deterministic=resp.get("deterministic", True),
                    exhausted=resp.get("exhausted", False),
                    draining=resp.get("draining", False))
            return resp, data

        resp, data = call_with_retry(
            _once, policy=RetryPolicy.from_conf(max_attempts),
            label=f"fleet {site} -> {self.executor_id}")
        wirecheck.check_response("executor", str(header.get("cmd")),
                                 resp)
        return resp, data

    # -- endpoint surface ---------------------------------------------------

    def hello(self) -> dict:
        """First contact: assert this client's protocol version and
        check the server's advertisement — a newer-major server is
        refused with a structured EndpointError (flight-recorder
        `wire.refusal` event), never a garbled decode later."""
        resp, _ = self._rpc("status", {
            "cmd": "hello", "proto": wirecheck.proto_version()})
        refusal = wirecheck.advertised_refusal(resp)
        if refusal is not None:
            from auron_tpu.runtime import counters, events
            counters.bump("wire_rejects")
            events.emit("wire.refusal", refusal, wire="executor",
                        peer=f"{self.host}:{self.port}",
                        proto_version=wirecheck.proto_version())
            raise EndpointError(refusal)
        return resp

    def dispatch(self, query_id: str, plan, conf_map: Dict[str, Any],
                 priority: Optional[int], serial: bool = False) -> None:
        data = json.dumps(plan.to_dict()).encode()
        self._rpc("dispatch",
                  {"cmd": "dispatch", "query_id": query_id,
                   "conf": _serial_overlay(conf_map, serial),
                   "priority": priority, "len": len(data)}, data)

    def heartbeat(self, ids: Optional[List[str]] = None
                  ) -> Dict[str, Any]:
        resp, _ = self._rpc("heartbeat",
                            {"cmd": "heartbeat", "ids": list(ids or [])})
        return resp

    def status(self, query_id: str) -> Optional[Dict[str, Any]]:
        resp, _ = self._rpc("status",
                            {"cmd": "status", "query_id": query_id})
        return resp.get("status")

    def result(self, query_id: str) -> pa.Table:
        _, data = self._rpc("result",
                            {"cmd": "result", "query_id": query_id})
        return _table_from_ipc(data)

    def cancel(self, query_id: str) -> bool:
        resp, _ = self._rpc("cancel",
                            {"cmd": "cancel", "query_id": query_id})
        return bool(resp.get("cancelled"))

    def harvest(self, ids: List[str]) -> Dict[str, Any]:
        _, data = self._rpc("harvest",
                            {"cmd": "harvest", "ids": list(ids)})
        return json.loads(data) if data else {}

    def drain(self) -> List[str]:
        resp, _ = self._rpc("drain", {"cmd": "drain"})
        return list(resp.get("moved") or [])

    def kill(self) -> None:
        """SIGKILL the worker (fence against double execution after a
        death declaration); no-op for an unowned connection."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
        self._reap()

    def _reap(self) -> None:
        if self.proc is not None:
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        if self._log_file is not None:
            try:
                self._log_file.close()
            except OSError:
                pass
            self._log_file = None

    def close(self) -> None:
        """Graceful teardown: shutdown RPC (best effort, one attempt),
        then terminate/kill the owned process."""
        try:
            self._rpc("shutdown", {"cmd": "shutdown"}, max_attempts=1)
        except BaseException:  # noqa: BLE001 - already dying is fine
            pass
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self._reap()

    def describe(self) -> Dict[str, Any]:
        return {"executor_id": self.executor_id,
                "kind": type(self).__name__,
                "host": self.host, "port": self.port, "pid": self.pid,
                "log": self.log_path}


# ---------------------------------------------------------------------------
# worker entry point
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    """`python -m auron_tpu.serving.executor_endpoint` — run one
    executor server (the FleetManager's spawn target)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m auron_tpu.serving.executor_endpoint",
        description="Auron TPU fleet executor server")
    ap.add_argument("--host", default=None,
                    help="bind address (default: auron.net.bind.host)")
    ap.add_argument("--advertise-host", default=None,
                    help="host the driver should dial (default: "
                         "auron.net.advertise.host, else the bind "
                         "host; wildcard binds advertise loopback)")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--executor-id", default="exec-0")
    ap.add_argument("--conf", default="",
                    help="JSON map of process-wide conf overrides")
    ap.add_argument("--budget", type=int, default=0,
                    help="MemManager budget bytes (the fleet's "
                         "per-worker slice of the federated budget)")
    args = ap.parse_args(argv)

    platform = os.environ.get("JAX_PLATFORMS")
    if platform:
        # some TPU platform plugins override the env var; pin the
        # requested backend through the config API before first use
        try:
            import jax
            jax.config.update("jax_platforms", platform)
        except Exception:
            pass
    if args.conf:
        for key, value in json.loads(args.conf).items():
            conf.set(key, value)
    # worker records ship to the driver over harvest and the DRIVER
    # owns the durable stats store — disarm it here so a conf overlay
    # leaking auron.stats.store.dir cannot double-fold every query
    from auron_tpu.runtime import statshist
    statshist.mark_worker()
    if args.budget:
        from auron_tpu.memmgr.manager import reset_manager
        reset_manager(int(args.budget))
    from auron_tpu import config
    bind_host = args.host if args.host is not None \
        else config.net_bind_host()
    srv = ExecutorServer(executor_id=args.executor_id,
                         host=bind_host, port=args.port)
    host, port = srv.address
    adv = args.advertise_host if args.advertise_host is not None \
        else config.net_advertise_host(host)
    print(json.dumps({"event": "listening", "host": adv, "port": port,
                      "executor_id": args.executor_id,
                      "pid": os.getpid(),
                      "proto_version": wirecheck.proto_version()}),
          flush=True)
    srv.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
