"""Executor fleet: crash-surviving multi-process serving behind one
admission ledger.

The reference runs its native engine inside many JVM executor processes
and treats executor death as routine — the driver schedules around it
(PAPER.md: NativeRDD rides Spark's task retry and shuffle-service
side-cars).  This module is that driver tier for the TPU engine: a
``FleetManager`` supervises N ``ExecutorEndpoint``s
(serving/executor_endpoint.py — LocalExecutor in-process, or
ProcessExecutor worker processes it spawned), keeps ONE
AdmissionController as the front-door ledger (per-process MemManager
budgets federate under one global budget via `budget_fn`), routes each
admitted submission to the least-loaded healthy executor, and survives
crashes:

- **Heartbeats** (`auron.fleet.heartbeat.seconds`): a monitor thread
  probes every executor on a fixed cadence; the reply carries the
  executor's in-flight query states, so completion/result handling
  rides the same RPC.
- **Health state machine** (``ExecutorHealth``): alive -> suspect ->
  dead.  Only heartbeat probes move an executor toward death
  (`auron.fleet.death.probes` consecutive failures, re-probed with
  capped exponential backoff); a non-heartbeat RPC failure marks it
  SUSPECT and pulls the next probe forward but never kills on its own
  — that is the heartbeat-vs-RPC precedence contract.  DEAD is sticky:
  a late heartbeat from a restarted incarnation must not resurrect an
  id whose queries were already requeued elsewhere.
- **Cross-process kill-and-requeue**: on executor death (including
  ``kill -9``) every in-flight query on it is requeued on a DIFFERENT
  executor — the dead id joins the submission's
  ``excluded_executors``, its admission reservation is released and
  its fleet marks cleared BEFORE it re-enters the queue, and no
  `auron.task.retries` budget is consumed (the re-dispatch is a fresh
  execution, the PR 10 deterministic-cancel contract generalized
  across the process boundary).  Re-execution is bit-identical to a
  solo run.
- **Flap damping**: an executor that oscillates alive/suspect is
  circuit-broken out of routing (`auron.fleet.flap.*`,
  `auron.fleet.circuit.break.seconds`).
- **Graceful drain** (``decommission``): the executor stops accepting
  dispatches, its queued-but-not-started work is rerouted, running
  queries finish where they are.

The FleetManager presents the QueryScheduler surface (submit / status /
result / wait / cancel / stats / shutdown), so `QueryServer(scheduler=
FleetManager(...))` serves the same HTTP routes over a fleet.
"""

from __future__ import annotations

import itertools
import logging
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set

from auron_tpu import config
from auron_tpu.runtime import counters, events, lockcheck, tracing
from auron_tpu.serving.admission import ADMIT, AdmissionController
from auron_tpu.serving.executor_endpoint import (
    EndpointError, ExecutorEndpoint, LocalExecutor, ProcessExecutor,
)
from auron_tpu.serving.forecast import plan_signature
from auron_tpu.serving.scheduler import (
    CANCELLED, FAILED, QUEUED, RUNNING, SHED_STATE, SUCCEEDED,
    Submission, SubmissionRejected,
)

log = logging.getLogger("auron_tpu.serving.fleet")

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"


class ExecutorHealth:
    """Per-executor liveness state machine (alive -> suspect -> dead).

    Evidence rules (the heartbeat-vs-RPC-failure precedence contract):

    - only HEARTBEAT probe outcomes move the machine toward death:
      `death_probes` consecutive probe failures declare DEAD, with the
      re-probe delay backing off exponentially from a quarter of the
      heartbeat interval up to `backoff_max_s` — fast confirmation,
      bounded probe pressure, death within ~3 heartbeat intervals at
      the defaults;
    - a non-heartbeat RPC failure makes an ALIVE executor SUSPECT and
      pulls the next probe forward to NOW, but never counts toward
      death on its own — a transport blip on a busy data path must not
      kill an executor whose heartbeats still answer;
    - a successful heartbeat outranks everything except death: it
      clears the failure count and restores ALIVE;
    - DEAD is STICKY: the fleet already requeued the executor's
      in-flight queries, so a late heartbeat (a half-dead or restarted
      incarnation) must not resurrect the id — that would double-run
      queries.  Replace the endpoint to rejoin the fleet;
    - flap damping: more than `flap_max` alive->suspect transitions
      inside `flap_window_s` opens a routing circuit breaker for
      `circuit_s` (`routable()` goes False while the state may still
      be ALIVE).

    `clock` is injectable so the transitions are unit-testable without
    wall-clock sleeps.
    """

    def __init__(self, heartbeat_s: float = 2.0, death_probes: int = 3,
                 backoff_max_s: float = 0.0, flap_max: int = 3,
                 flap_window_s: float = 60.0, circuit_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.heartbeat_s = max(0.01, float(heartbeat_s))
        self.death_probes = max(1, int(death_probes))
        self.backoff_max_s = float(backoff_max_s) \
            if backoff_max_s > 0 else self.heartbeat_s
        self.flap_max = max(1, int(flap_max))
        self.flap_window_s = float(flap_window_s)
        self.circuit_s = float(circuit_s)
        self._clock = clock
        self.state = ALIVE
        self.failures = 0              # consecutive failed probes
        self.last_ok: Optional[float] = None
        self.next_probe_at = self._clock() + self.heartbeat_s
        self.circuit_until = 0.0
        self.circuit_opens = 0
        self._suspect_times: deque = deque()

    @classmethod
    def from_conf(cls,
                  clock: Callable[[], float] = time.monotonic
                  ) -> "ExecutorHealth":
        conf = config.conf
        return cls(
            heartbeat_s=float(conf.get("auron.fleet.heartbeat.seconds")),
            death_probes=int(conf.get("auron.fleet.death.probes")),
            backoff_max_s=float(
                conf.get("auron.fleet.probe.backoff.max.seconds")),
            flap_max=int(conf.get("auron.fleet.flap.max")),
            flap_window_s=float(
                conf.get("auron.fleet.flap.window.seconds")),
            circuit_s=float(
                conf.get("auron.fleet.circuit.break.seconds")),
            clock=clock)

    def due(self) -> bool:
        return self.state != DEAD and self._clock() >= self.next_probe_at

    def probe_ok(self) -> str:
        """A heartbeat answered.  Heartbeat success outranks RPC
        suspicion — but never death (sticky)."""
        if self.state == DEAD:
            return DEAD
        now = self._clock()
        self.failures = 0
        self.state = ALIVE
        self.last_ok = now
        self.next_probe_at = now + self.heartbeat_s
        return self.state

    def probe_failed(self) -> str:
        """A heartbeat probe failed (after its RPC retry budget)."""
        if self.state == DEAD:
            return DEAD
        now = self._clock()
        self.failures += 1
        self._mark_suspect(now)
        if self.failures >= self.death_probes:
            self.state = DEAD
        else:
            # capped exponential backoff between confirmation probes:
            # base = heartbeat/4 (suspicion is confirmed FASTER than
            # the healthy cadence), doubled per consecutive failure
            delay = min(self.heartbeat_s / 4.0
                        * (2 ** (self.failures - 1)),
                        self.backoff_max_s)
            self.next_probe_at = now + delay
        return self.state

    def rpc_failed(self) -> str:
        """A non-heartbeat RPC failed: suspicion, an immediate probe —
        but by itself never a step toward death (heartbeat precedence)."""
        if self.state == DEAD:
            return DEAD
        now = self._clock()
        self._mark_suspect(now)
        self.next_probe_at = now
        return self.state

    def _mark_suspect(self, now: float) -> None:
        if self.state != ALIVE:
            return
        self.state = SUSPECT
        self._suspect_times.append(now)
        horizon = now - self.flap_window_s
        while self._suspect_times and self._suspect_times[0] < horizon:
            self._suspect_times.popleft()
        if len(self._suspect_times) >= self.flap_max:
            self.circuit_until = now + self.circuit_s
            self.circuit_opens += 1
            self._suspect_times.clear()

    def routable(self) -> bool:
        return self.state == ALIVE and \
            self._clock() >= self.circuit_until

    def snapshot(self) -> Dict[str, Any]:
        now = self._clock()
        return {"state": self.state, "failures": self.failures,
                "routable": self.routable(),
                "circuit_open": now < self.circuit_until,
                "circuit_opens": self.circuit_opens,
                "last_ok_age_s": (round(now - self.last_ok, 3)
                                  if self.last_ok is not None else None)}


class WorkerLauncher:
    """Where worker and side-car processes RUN: the remote seam behind
    the fleet's spawn template.  `wrap(argv)` receives the local spawn
    argv (`python -m auron_tpu...`) and returns the argv the driver
    actually executes — identity for local children, or a prefix
    command (ssh/kubectl/srun-shaped) that carries the worker to
    another host.  The worker's listening line advertises a reachable
    host:port back (`auron.net.advertise.host`), so the driver never
    assumes loopback."""

    name = "abstract"

    def wrap(self, argv: List[str]) -> List[str]:
        raise NotImplementedError


class LocalLauncher(WorkerLauncher):
    """Today's behavior: spawn the argv as a local child, unchanged."""

    name = "local"

    def wrap(self, argv: List[str]) -> List[str]:
        return list(argv)


class CommandLauncher(WorkerLauncher):
    """Command-template launcher (`auron.fleet.launcher=command`):
    `auron.fleet.launcher.command` is a whitespace-split argv template;
    `{argv}` expands in place to the worker argv (appended when the
    template never names it) and `{python}` to this interpreter —
    e.g. ``ssh -o BatchMode=yes worker-2 {argv}``."""

    name = "command"

    def __init__(self, template: str):
        if not str(template or "").strip():
            raise ValueError(
                "auron.fleet.launcher=command requires a non-empty "
                "auron.fleet.launcher.command argv template")
        self.template = str(template).split()

    def wrap(self, argv: List[str]) -> List[str]:
        out: List[str] = []
        expanded = False
        for part in self.template:
            if part == "{argv}":
                out.extend(argv)
                expanded = True
            elif part == "{python}":
                out.append(sys.executable)
            else:
                out.append(part)
        if not expanded:
            out.extend(argv)
        return out


def launcher_from_conf() -> WorkerLauncher:
    """The spawn-time launcher selection (`auron.fleet.launcher`)."""
    kind = str(config.conf.get("auron.fleet.launcher") or "local")
    if kind == "local":
        return LocalLauncher()
    if kind == "command":
        return CommandLauncher(
            config.conf.get("auron.fleet.launcher.command"))
    raise ValueError(f"unknown auron.fleet.launcher {kind!r} "
                     f"(expected 'local' or 'command')")


@dataclass
class FleetSubmission(Submission):
    """A Submission plus its fleet placement: which executor holds it,
    under which dispatch id (unique per attempt, so a rerouted query
    can never collide with its own terminal record on a scheduler that
    saw an earlier attempt), and which executors are excluded after a
    death/drain requeue.

    Observability state (the distributed tracing plane): with tracing
    armed the driver keeps a per-query TraceRecorder for its OWN lane
    (dispatch spans, requeue/death instants) plus one harvested span
    lane per executor the query touched; `harvest_record` is the
    worker-side QueryRecord summary (metric trees, retries, memory
    columns) the terminal harvest ships back so `/queries/<id>` works
    for fleet-executed queries."""

    executor_id: Optional[str] = None
    dispatch_id: Optional[str] = None
    excluded_executors: Set[str] = field(default_factory=set)
    requeues: int = 0
    recorder: Optional[Any] = None           # tracing.TraceRecorder
    # executor id -> {"label", "pid", "spans", "dropped", "anchor_us",
    # "complete"}; guarded by the fleet lock
    lanes: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    lane_final: Set[str] = field(default_factory=set)  # harvested dids
    harvest_record: Optional[Dict[str, Any]] = None
    recorded: bool = False      # driver-side QueryRecord emitted

    # fleet placement inserts a `dispatched` state in the lifecycle
    # timeline (the RPC hop to the worker process)
    dispatched_marker = True

    def status(self) -> Dict[str, Any]:
        doc = super().status()
        doc.update({"executor": self.executor_id,
                    "requeues": self.requeues,
                    "excluded_executors":
                        sorted(self.excluded_executors)})
        return doc


@dataclass
class _ExecHandle:
    """Fleet-side bookkeeping for one endpoint (guarded by the fleet
    lock except where noted; RPCs always run outside it)."""

    endpoint: ExecutorEndpoint
    health: ExecutorHealth
    inflight: Dict[str, str] = field(default_factory=dict)
    # ^ dispatch id -> fleet query id; statuses for ids not in here are
    # stale by definition (requeued away) and are ignored
    dispatched: int = 0
    draining: bool = False
    dead: bool = False
    retired: bool = False          # idle scale-down, not a death
    last_active: float = 0.0       # monotonic; last time it held work
    load: Dict[str, Any] = field(default_factory=dict)
    pid: Optional[int] = None      # worker os pid (from heartbeats)
    # wall-clock offset (worker - driver) estimated at heartbeat RTT
    # midpoints; the minimum-RTT sample wins (least queueing skew) —
    # the trace stitcher aligns harvested span lanes with it
    clock_off: float = 0.0
    clock_rtt: float = float("inf")
    last_circuit: int = 0          # circuit_opens already event-logged

    def snapshot(self) -> Dict[str, Any]:
        doc = {"inflight": len(self.inflight),
               "dispatched": self.dispatched,
               "draining": self.draining, "dead": self.dead,
               "retired": self.retired,
               "load": dict(self.load)}
        doc.update(self.health.snapshot())
        if self.dead:
            doc["state"] = DEAD
            doc["routable"] = False
        doc.update(self.endpoint.describe())
        return doc


@dataclass
class _SidecarState:
    """Fleet-side supervision of ONE durable-shuffle side-car shard:
    the process handle (anything with .address/.kill/.close), the
    control client (shuffle_rss.durable.DurableShuffleClient) and its
    own health machine — the same alive/suspect/dead evidence rules as
    an executor, with DEAD equally sticky.  A dead shard degrades ONLY
    the shuffle ids the shard map routes to it (the address list in the
    dispatch overlay never changes, so the map never shifts); nothing
    is requeued."""

    proc: Any
    control: Any
    health: ExecutorHealth
    shard: int = 0
    dead: bool = False
    clock_off: float = 0.0         # ping RTT-midpoint estimate
    clock_rtt: float = float("inf")

    def snapshot(self) -> Dict[str, Any]:
        doc = {"dead": self.dead, "shard": self.shard}
        doc.update(self.health.snapshot())
        if self.dead:
            doc["state"] = DEAD
            doc["routable"] = False
        describe = getattr(self.proc, "describe", None)
        doc.update(describe() if callable(describe)
                   else {"address": getattr(self.proc, "address", None)})
        return doc


class FleetManager:
    """Submission registry + front-door admission + executor routing +
    failure supervision.  Presents the QueryScheduler client surface so
    QueryServer/profiling serve it unchanged."""

    def __init__(self, endpoints: Optional[List[ExecutorEndpoint]] = None,
                 session_factory=None,
                 admission: Optional[AdmissionController] = None,
                 budget_bytes: int = 0,
                 rss_sidecar: Any = None,
                 worker_factory: Optional[
                     Callable[[str], ExecutorEndpoint]] = None):
        if endpoints is None:
            endpoints = [LocalExecutor(session_factory=session_factory)]
        self._budget_bytes = int(budget_bytes)
        self.admission = admission or AdmissionController(
            budget_fn=self._fleet_budget,
            executors_fn=self._routable_count,
            inflight_fn=self._live_running)
        self._lock = lockcheck.Lock("fleet.manager")
        self._handles: Dict[str, _ExecHandle] = {}
        now = time.monotonic()
        for ep in endpoints:
            if ep.executor_id in self._handles:
                raise ValueError(
                    f"duplicate executor id {ep.executor_id!r}")
            self._handles[ep.executor_id] = _ExecHandle(
                endpoint=ep, health=ExecutorHealth.from_conf(),
                last_active=now)
        # durable-shuffle side-car shard(s) (anything with .address
        # (host, port) + best-effort .kill()/.close(), or a list of
        # them); each shard is supervised by its OWN health machine and
        # the ordered address list is consulted by every dispatch
        # overlay — its order IS the shard map (shard_map.py)
        self._sidecars: List[_SidecarState] = []
        if rss_sidecar is not None:
            from auron_tpu.shuffle_rss.durable import DurableShuffleClient
            procs = rss_sidecar if isinstance(rss_sidecar, (list, tuple)) \
                else [rss_sidecar]
            for i, proc in enumerate(procs):
                host, port = proc.address
                self._sidecars.append(_SidecarState(
                    proc=proc,
                    control=DurableShuffleClient(host, port),
                    health=ExecutorHealth.from_conf(), shard=i))
        # elastic sizing (auron.fleet.scale.*): only active when the
        # fleet knows how to build a worker
        self._worker_factory = worker_factory
        self._scale_seq = itertools.count()
        self._last_scale = 0.0
        self._subs: Dict[str, FleetSubmission] = {}
        self._queue: List[FleetSubmission] = []
        self._seq = 0
        self._shutdown = False
        self._wake = threading.Event()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="auron-fleet-monitor")
        self._monitor.start()

    # -- construction helpers ----------------------------------------------

    @property
    def _sidecar(self) -> Optional[_SidecarState]:
        """Single-shard compatibility view (shard 0)."""
        return self._sidecars[0] if self._sidecars else None

    @classmethod
    def spawn(cls, n: int, conf_map: Optional[Dict[str, Any]] = None,
              budget_bytes: int = 0,
              log_dir: Optional[str] = None,
              rss_sidecar: Optional[bool] = None,
              rss_shards: Optional[int] = None,
              launcher: Optional[WorkerLauncher] = None
              ) -> "FleetManager":
        """Launch N worker processes, each with an equal slice of the
        federated memory budget (`auron.fleet.memory.budget.bytes`,
        else the driver manager's budget).  With `rss_sidecar` (default
        `auron.rss.sidecar.enable`) durable-shuffle side-car shard
        process(es) launch first (`rss_shards`, default
        `auron.rss.shards`) and every dispatch routes its exchanges
        through them via the consistent shard map.  `launcher` (default
        `auron.fleet.launcher`) decides WHERE the children run — local
        spawn, or a command template carrying them to other hosts.  The
        spawn template doubles as the elastic-scaling worker factory
        (`auron.fleet.scale.*`)."""
        from auron_tpu.memmgr import get_manager
        n = max(1, int(n))
        total = int(budget_bytes) or \
            int(config.conf.get("auron.fleet.memory.budget.bytes")) or \
            get_manager().budget
        if rss_sidecar is None:
            rss_sidecar = bool(
                config.conf.get("auron.rss.sidecar.enable"))
        if rss_shards is None:
            rss_shards = int(config.conf.get("auron.rss.shards"))
        rss_shards = max(1, int(rss_shards))
        if launcher is None:
            launcher = launcher_from_conf()
        watermark = int(config.conf.get(
            "auron.rss.committed.spill.watermark"))
        sidecars: List[Any] = []
        endpoints: List[ExecutorEndpoint] = []
        try:
            if rss_sidecar:
                from auron_tpu.shuffle_rss.sidecar import SidecarProcess
                for i in range(rss_shards):
                    sidecars.append(SidecarProcess.spawn(
                        log_dir=log_dir,
                        shard=i if rss_shards > 1 else None,
                        committed_watermark=watermark,
                        launcher=launcher))
            slice_bytes = max(1, total // n)
            for i in range(n):
                endpoints.append(ProcessExecutor.spawn(
                    f"exec-{i}", conf_map=conf_map,
                    budget_bytes=slice_bytes, log_dir=log_dir,
                    launcher=launcher))
        except BaseException:
            for ep in endpoints:
                ep.kill()
            for sc in sidecars:
                sc.kill()
            raise

        def factory(executor_id: str) -> ExecutorEndpoint:
            return ProcessExecutor.spawn(
                executor_id, conf_map=conf_map,
                budget_bytes=slice_bytes, log_dir=log_dir,
                launcher=launcher)

        return cls(endpoints=endpoints, budget_bytes=total,
                   rss_sidecar=sidecars or None, worker_factory=factory)

    def _fleet_budget(self) -> int:
        if self._budget_bytes:
            return self._budget_bytes
        from auron_tpu.memmgr import get_manager
        return get_manager().budget

    def _routable_count(self) -> int:
        with self._lock:
            return max(1, len(self._routable_locked()))

    def _live_running(self) -> int:
        """Fleet-wide running count from the last heartbeat loads (the
        live half of the drain estimate)."""
        with self._lock:
            return sum(int(h.load.get("running") or 0)
                       for h in self._handles.values() if not h.dead)

    # -- submission (the QueryScheduler surface) ---------------------------

    def submit(self, plan, conf: Optional[Dict[str, Any]] = None,
               priority: Optional[int] = None,
               query_id: Optional[str] = None) -> str:
        if self._shutdown:
            raise SubmissionRejected("fleet is shut down")
        overrides = dict(conf or {})
        # validate the per-query conf NOW (400 at submit, the
        # scheduler.submit contract) — it also travels to the executor
        with config.conf.query_scoped(overrides):
            traced = bool(config.conf.get("auron.trace.enable"))
        if priority is None:
            priority = int(overrides.get(
                "auron.query.priority",
                config.conf.get("auron.query.priority")))
        qid = query_id or tracing.new_query_id()
        sub = FleetSubmission(query_id=qid, plan=plan, conf=overrides,
                              priority=int(priority),
                              signature=plan_signature(plan))
        if traced:
            # the driver-lane recorder: dispatch spans and
            # requeue/death instants land here; worker and side-car
            # lanes are harvested and stitched in at terminal states
            sub.recorder = tracing.TraceRecorder(qid)
            sub.recorder.add("fleet.submit", "fleet",
                             time.perf_counter_ns(), -1,
                             {"priority": sub.priority})
        with self._lock:
            if qid in self._subs:
                raise SubmissionRejected(f"duplicate query id {qid!r}")
            if len(self._queue) >= \
                    int(config.conf.get("auron.admission.queue.max")):
                sub.state = SHED_STATE
                sub.error = "shed: admission queue full"
                sub.mark(SHED_STATE)
                sub.done.set()
                self._subs[qid] = sub
                self.admission.events["shed"] += 1
                queue_len = len(self._queue)
            else:
                self._seq += 1
                sub.seq = self._seq
                self._subs[qid] = sub
                self._queue.append(sub)
                queue_len = -1
        if queue_len >= 0:
            counters.bump("admission_shed")
            events.emit("query.shed", sub.error, [qid],
                        queue_len=queue_len)
            exc = SubmissionRejected(sub.error)
            exc.retry_after_s = self.admission.drain_estimate_s(queue_len)
            raise exc
        counters.bump("fleet_submissions")
        self._pump()
        return qid

    # -- the pump: admit + route + dispatch --------------------------------

    def _pump(self) -> None:
        while True:
            target: Optional[_ExecHandle] = None
            head: Optional[FleetSubmission] = None
            with self._lock:
                if self._shutdown or not self._queue:
                    return
                self._expire_locked()
                if not self._queue:
                    return
                cands = self._routable_locked()
                if not cands:
                    self._fail_if_fleet_dead_locked()
                    return
                # fleet-wide slot cap: max.concurrent driver slots on
                # every routable executor.  Only ROUTABLE executors'
                # in-flight work counts — queries finishing on a
                # draining executor must not starve dispatches to
                # healthy ones
                slots = max(1, int(config.conf.get(
                    "auron.serving.max.concurrent"))) * len(cands)
                inflight = sum(len(h.inflight) for h in cands)
                if inflight >= slots:
                    return
                aging = float(config.conf.get(
                    "auron.admission.aging.seconds"))
                now = time.time()
                head = min(self._queue,
                           key=lambda s: (-s.effective_priority(aging,
                                                                now),
                                          s.seq))
                decision = self.admission.offer(
                    head.query_id, head.signature,
                    queue_len=len(self._queue) - 1,
                    count_queue_event=head.admission_reason == "")
                head.admission_reason = decision.reason
                head.forecast_bytes = decision.forecast_bytes
                if decision.action != ADMIT:
                    if head.admission_blocked_at is None:
                        head.admission_blocked_at = now
                    return
                head.serial = decision.serial
                # requeued queries go to a DIFFERENT executor; if every
                # routable executor is excluded, progress beats
                # placement preference (documented fallback)
                preferred = [h for h in cands
                             if h.endpoint.executor_id
                             not in head.excluded_executors]
                pool = preferred or cands
                target = min(pool,
                             key=lambda h: (len(h.inflight),
                                            h.dispatched,
                                            h.endpoint.executor_id))
                self._queue.remove(head)
                head.state = RUNNING
                head.started_at = time.time()
                head.mark_started()
                head.executor_id = target.endpoint.executor_id
                head.dispatch_id = head.query_id if not head.requeues \
                    else f"{head.query_id}~r{head.requeues}"
                target.inflight[head.dispatch_id] = head.query_id
                target.dispatched += 1
                target.last_active = time.monotonic()
                if head.recorder is not None:
                    # the wire-parent anchor: harvested worker spans of
                    # this lane are clamped to start no earlier than
                    # the dispatch that caused them
                    lane = self._lane_locked(head, target)
                    if lane.get("anchor_us") is None:
                        lane["anchor_us"] = \
                            (time.perf_counter_ns()
                             - head.recorder.epoch_ns) / 1e3
                dispatch_conf = self._dispatch_conf_locked(head)
            # RPC outside the lock
            t0p = time.perf_counter_ns()
            try:
                target.endpoint.dispatch(
                    head.dispatch_id, head.plan, dispatch_conf,
                    head.priority, serial=head.serial)
                counters.bump("fleet_dispatches")
            except BaseException as e:  # noqa: BLE001 - classified below
                if head.recorder is not None:
                    head.recorder.add(
                        "fleet.dispatch", "fleet", t0p,
                        time.perf_counter_ns() - t0p,
                        {"executor": target.endpoint.executor_id,
                         "dispatch_id": head.dispatch_id,
                         "error": f"{type(e).__name__}: {e}"})
                self._dispatch_failed(target, head, e)
            else:
                if head.recorder is not None:
                    head.recorder.add(
                        "fleet.dispatch", "fleet", t0p,
                        time.perf_counter_ns() - t0p,
                        {"executor": target.endpoint.executor_id,
                         "dispatch_id": head.dispatch_id})

    def _dispatch_conf_locked(self, sub: FleetSubmission
                              ) -> Dict[str, Any]:
        """The per-dispatch conf overlay: the submission's own conf
        plus durable-shuffle routing while the side-car is healthy.
        The tag is the FLEET query id (stable across requeues — the
        executor-side id carries a ~rN suffix) so a requeued attempt
        finds its predecessor's committed map outputs; cleanup is
        deferred to the fleet's terminal-state hook.  The ordered
        shard address list is the SERIALIZED SHARD MAP (shard_map.py):
        it never changes while any shard lives — a dead shard stays in
        the list (removing it would remap every shuffle id), and the
        worker degrades exactly the shuffle ids that route to it.  Only
        with EVERY shard dead does the durable overlay stop appearing.
        Redacted keys (auron.net.auth.secret) never ride the overlay —
        workers read their own environment."""
        conf_map = config.redact_overlay(dict(sub.conf))
        if sub.recorder is not None:
            # trace-context propagation: the dispatch overlay arms the
            # worker's recorder for this query (the worker's
            # trace_scope reads per-query conf), so its spans exist to
            # harvest back over heartbeats
            conf_map["auron.trace.enable"] = True
        if self._sidecars and not all(sc.dead for sc in self._sidecars):
            address = ",".join(
                "{}:{}".format(*sc.proc.address)
                for sc in self._sidecars)
            conf_map.update({
                "auron.shuffle.service": "durable",
                "auron.shuffle.service.address": address,
                "auron.rss.tag": sub.query_id,
                "auron.rss.defer.cleanup": True,
            })
        return conf_map

    def _lane_locked(self, sub: FleetSubmission,
                     handle: _ExecHandle) -> Dict[str, Any]:
        """The harvested-span lane of one executor for one submission
        (fleet lock held)."""
        eid = handle.endpoint.executor_id
        lane = sub.lanes.get(eid)
        if lane is None:
            lane = sub.lanes[eid] = {
                "label": eid, "pid": 0, "spans": [], "dropped": 0,
                "anchor_us": None, "complete": False}
        if handle.pid:
            lane["pid"] = int(handle.pid)
            lane["label"] = f"{eid} (pid {handle.pid})"
        elif not lane["pid"]:
            # a stable synthetic lane pid distinct from the driver's
            lane["pid"] = 100000 + abs(hash(eid)) % 100000
        return lane

    def _routable_locked(self) -> List[_ExecHandle]:
        return [h for h in self._handles.values()
                if not h.dead and not h.draining
                and h.health.routable()]

    def _fail_if_fleet_dead_locked(self) -> None:
        """With EVERY executor dead there is nothing to wait for —
        queued submissions fail loudly instead of aging forever.
        (Suspect/circuit-broken executors can recover; dead cannot.)"""
        if any(not h.dead for h in self._handles.values()):
            return
        for sub in list(self._queue):
            self._queue.remove(sub)
            sub.state = FAILED
            sub.error = "no live executors in the fleet"
            sub.finished_at = time.time()
            sub.mark(FAILED, sub.finished_at)
            sub.done.set()

    def _expire_locked(self) -> None:
        timeout = float(config.conf.get(
            "auron.admission.queue.timeout.seconds"))
        if timeout <= 0:
            return
        now = time.time()
        for sub in list(self._queue):
            if now - sub.queued_since > timeout:
                self._queue.remove(sub)
                sub.state = FAILED
                sub.error = f"admission timeout after {timeout:g}s"
                sub.finished_at = now
                sub.mark(FAILED, now)
                sub.done.set()

    def _dispatch_failed(self, handle: _ExecHandle,
                         sub: FleetSubmission, exc: BaseException) -> None:
        draining = isinstance(exc, EndpointError) and exc.draining
        deterministic = isinstance(exc, EndpointError) \
            and exc.auron_deterministic and not draining
        with self._lock:
            handle.inflight.pop(sub.dispatch_id, None)
            if draining:
                handle.draining = True
            elif not deterministic:
                # transport trouble: suspicion + an immediate probe —
                # the health machine (not this dispatch) decides death
                handle.health.rpc_failed()
        self._note_circuit(handle)
        if deterministic:
            # the executor answered and refused (bad plan, duplicate):
            # rerouting cannot change the answer — one red row
            sub.state = FAILED
            sub.error = f"{type(exc).__name__}: {exc}"
            self.admission.release(sub.query_id)
            sub.finished_at = time.time()
            sub.mark(FAILED, sub.finished_at)
            sub.done.set()
            log.warning("fleet dispatch of %s to %s refused: %s",
                        sub.query_id, handle.endpoint.executor_id,
                        sub.error)
            self._rss_cleanup(sub.query_id)
            return
        log.warning("fleet dispatch of %s to %s failed (%s); requeueing",
                    sub.query_id, handle.endpoint.executor_id, exc)
        self._requeue(sub, handle, exclude=False)

    # -- requeue (the cross-process kill-and-requeue arm) ------------------

    def _requeue(self, sub: FleetSubmission, handle: _ExecHandle,
                 exclude: bool = True) -> None:
        """Move a submission back to the fleet queue.  Order is
        load-bearing (the PR 10 contract): reservation released and
        marks cleared BEFORE the submission becomes runnable again, so
        a requeued run starts with a clean slate.  Requeues never
        consume `auron.task.retries` budgets — the re-dispatch is a
        fresh execution on a fresh scheduler."""
        with self._lock:
            handle.inflight.pop(sub.dispatch_id, None)
            if sub.done.is_set() or sub.state not in (RUNNING, QUEUED):
                return
            if sub in self._queue:
                return
            sub.state = "requeueing"   # invisible outside the lock
        self.admission.release(sub.query_id)
        with self._lock:
            if self._shutdown:
                sub.state = CANCELLED
                sub.error = "fleet shut down during requeue"
                sub.finished_at = time.time()
                sub.mark(CANCELLED, sub.finished_at)
                sub.done.set()
                return
            if exclude:
                sub.excluded_executors.add(handle.endpoint.executor_id)
            sub.requeues += 1
            sub.state = QUEUED
            sub.started_at = None
            sub.error = None
            sub.admission_reason = ""
            sub.admission_blocked_at = None
            sub.executor_id = None
            sub.queued_since = time.time()
            sub.mark("requeued", sub.queued_since)
            self._queue.append(sub)
        counters.bump("fleet_requeues")
        events.emit("query.requeue",
                    f"query {sub.query_id} requeued off "
                    f"{handle.endpoint.executor_id}",
                    [sub.query_id],
                    executor=handle.endpoint.executor_id,
                    requeues=sub.requeues)
        if sub.recorder is not None:
            sub.recorder.add("event.query.requeue", "event",
                             time.perf_counter_ns(), -1,
                             {"executor": handle.endpoint.executor_id,
                              "requeues": sub.requeues})
        self._pump()

    # -- the monitor: heartbeats, status absorption, death -----------------

    def _tick_s(self) -> float:
        hb = min((h.health.heartbeat_s
                  for h in self._handles.values()), default=2.0)
        return max(0.02, min(0.5, hb / 4.0))

    def _monitor_loop(self) -> None:
        while True:
            self._wake.wait(self._tick_s())
            self._wake.clear()
            if self._shutdown:
                return
            for handle in list(self._handles.values()):
                if self._shutdown:
                    return
                with self._lock:
                    due = not handle.dead and handle.health.due()
                if due:
                    self._probe(handle)
            self._probe_sidecar()
            self._autoscale()
            # timeouts/aging/late capacity make progress even when no
            # submit/completion event fires
            self._pump()

    def _probe(self, handle: _ExecHandle) -> None:
        with self._lock:
            ids = list(handle.inflight)
        t0_wall = time.time()
        try:
            resp = handle.endpoint.heartbeat(ids)
        except BaseException as e:  # noqa: BLE001 - health-classified
            with self._lock:
                state = handle.health.probe_failed()
            self._note_circuit(handle)
            if state == DEAD:
                self._on_executor_death(handle, reason=str(e))
            return
        t1_wall = time.time()
        now = time.monotonic()
        with self._lock:
            handle.health.probe_ok()
            handle.load = dict(resp.get("load") or {})
            if resp.get("pid"):
                handle.pid = int(resp["pid"])
            remote_now = resp.get("now")
            if remote_now is not None:
                # clock-offset sample at the RTT midpoint; the
                # minimum-RTT sample wins (least queueing skew in the
                # midpoint assumption) — trace stitching aligns the
                # worker's harvested span lanes with it
                rtt = max(0.0, t1_wall - t0_wall)
                if rtt <= handle.clock_rtt:
                    handle.clock_rtt = rtt
                    handle.clock_off = \
                        float(remote_now) - (t0_wall + t1_wall) / 2.0
            if handle.inflight:
                handle.last_active = now
            if handle.load.get("draining"):
                handle.draining = True
            inflight = dict(handle.inflight)
        self._harvest_running(handle, inflight)
        queries = resp.get("queries") or {}
        # live admission re-forecast: the heartbeat carries per-query
        # memory peaks, so the front-door ledger learns DURING a run
        # instead of only at completion
        query_mem = handle.load.get("query_mem") or {}
        for did, qid in inflight.items():
            peak = int(query_mem.get(did) or 0)
            if peak <= 0:
                continue
            with self._lock:
                sub = self._subs.get(qid)
                started = sub.started_at if sub is not None else None
            if started is None:
                continue
            self.admission.reforecast(qid, peak,
                                      age_s=time.time() - started)
        for did in ids:
            self._absorb_status(handle, did, queries.get(did))

    # -- the harvest plane: spans + records back from the workers ----------

    def _note_circuit(self, handle: _ExecHandle) -> None:
        """Flight-recorder visibility for flap circuit-breaking: emit
        once per circuit the health machine opened."""
        with self._lock:
            opens = handle.health.circuit_opens
            if opens <= handle.last_circuit:
                return
            handle.last_circuit = opens
        events.emit("executor.circuit.break",
                    f"executor {handle.endpoint.executor_id} circuit-"
                    f"broken out of routing (flap damping)",
                    executor=handle.endpoint.executor_id, opens=opens)

    def _harvest_running(self, handle: _ExecHandle,
                         inflight: Dict[str, str]) -> None:
        """The harvest RPC riding the heartbeat cadence: drain span
        increments of traced in-flight queries, so a worker killed
        mid-query loses only the spans since the last beat.  Harvest
        loss is tolerated (suspicion, never a hang): the stitched
        trace is flagged incomplete instead."""
        if not handle.endpoint.supports_harvest or \
                not bool(config.conf.get("auron.trace.stitch.enable")):
            return
        with self._lock:
            dids = []
            for did, qid in inflight.items():
                sub = self._subs.get(qid)
                if sub is not None and sub.recorder is not None \
                        and did not in sub.lane_final:
                    dids.append(did)
        if not dids:
            return
        try:
            traces = handle.endpoint.harvest(dids)
        except BaseException as e:  # noqa: BLE001 - loss-tolerant
            with self._lock:
                handle.health.rpc_failed()
            log.warning("trace harvest from %s failed: %s",
                        handle.endpoint.executor_id, e)
            return
        with self._lock:
            for did, doc in traces.items():
                qid = inflight.get(did)
                sub = self._subs.get(qid) if qid is not None else None
                if sub is None or did in sub.lane_final:
                    continue
                self._absorb_harvest_locked(handle, sub, did, doc)

    def _absorb_harvest_locked(self, handle: _ExecHandle,
                               sub: FleetSubmission, did: str,
                               doc: Dict[str, Any]) -> None:
        lane = self._lane_locked(sub, handle)
        lane["spans"].extend(doc.get("spans") or [])
        lane["dropped"] = max(int(lane["dropped"]),
                              int(doc.get("dropped") or 0))
        if doc.get("complete"):
            lane["complete"] = True
            sub.lane_final.add(did)
            if doc.get("record") is not None:
                sub.harvest_record = doc["record"]

    def _harvest_final(self, handle: _ExecHandle,
                       sub: FleetSubmission) -> None:
        """One terminal harvest for the finished dispatch: the worker's
        QueryRecord summary (metric trees — EXPLAIN ANALYZE for fleet
        queries) plus residual spans.  Runs for every remote dispatch,
        traced or not; failure marks the lane incomplete."""
        with self._lock:
            did = sub.dispatch_id
            needed = did is not None and did not in sub.lane_final
        if not needed:
            return
        try:
            traces = handle.endpoint.harvest([did])
        except BaseException as e:  # noqa: BLE001 - loss-tolerant
            with self._lock:
                handle.health.rpc_failed()
            log.warning("final harvest of %s from %s failed: %s",
                        sub.query_id, handle.endpoint.executor_id, e)
            return
        doc = traces.get(did)
        if doc is None:
            return
        with self._lock:
            if did not in sub.lane_final:
                self._absorb_harvest_locked(handle, sub, did, doc)

    def _record_fleet_query(self, handle: _ExecHandle,
                            sub: FleetSubmission,
                            status: Dict[str, Any]) -> None:
        """Driver-side QueryRecord for a fleet-executed query: the
        worker's harvested metric trees/attribution plus — when traced —
        ONE stitched Chrome trace with per-process lanes (driver,
        executors, RSS side-car), clock-aligned and clamped so no span
        precedes its dispatch.  Lands in the driver's history ring, so
        `/queries/<id>`, `/queries/diff` and trace download work
        identically to local execution."""
        if not handle.endpoint.supports_harvest or sub.recorded:
            return
        sub.recorded = True
        self._harvest_final(handle, sub)
        hr = sub.harvest_record or {}
        trace_doc = None
        incomplete: List[str] = []
        if sub.recorder is not None and \
                bool(config.conf.get("auron.trace.stitch.enable")):
            # terminal lifecycle instant on the driver lane
            sub.recorder.add(f"query.{sub.state}", "fleet",
                             time.perf_counter_ns(), -1, None)
            sidecar_lanes = self._sidecar_lanes(sub)
            with self._lock:
                lanes = []
                for eid, lane in sub.lanes.items():
                    h = self._handles.get(eid)
                    lanes.append({
                        "label": lane["label"], "pid": lane["pid"],
                        "spans": lane["spans"],
                        "dropped": lane["dropped"],
                        "anchor_us": lane["anchor_us"],
                        "offset_s": h.clock_off if h is not None
                        else 0.0})
                    if not lane["complete"]:
                        incomplete.append(eid)
            lanes.extend(sidecar_lanes)
            trace_doc = tracing.stitch_traces(
                sub.recorder.to_chrome_trace(), lanes,
                incomplete=incomplete)
        totals = hr.get("metric_totals") or {}
        rec = tracing.QueryRecord(
            query_id=sub.query_id,
            wall_s=float(status.get("wall_s") or hr.get("wall_s")
                         or sub.wall_s or 0.0),
            signature=sub.signature or str(hr.get("signature") or ""),
            rows=int(status.get("rows") or hr.get("rows") or 0),
            spmd=bool(hr.get("spmd", False)),
            attempts=int(hr.get("attempts") or 0),
            retries=int(hr.get("retries") or 0),
            fallbacks=int(hr.get("fallbacks") or 0),
            preemptions=sub.num_preemptions,
            error=sub.error,
            started_at=sub.started_at or hr.get("started_at") or 0.0,
            metric_totals=dict(totals),
            mem_peak=int(status.get("mem_peak")
                         or hr.get("mem_peak") or 0),
            mem_spills=int(hr.get("mem_spills") or 0),
            mem_spill_bytes=int(hr.get("mem_spill_bytes") or 0),
            metric_trees=hr.get("metric_trees"),
            timeline=list(sub.timeline),
            aqe_decisions=hr.get("aqe_decisions"),
            exchange_stats=hr.get("exchange_stats"),
            trace=trace_doc)
        tracing.record_query(rec)

    def _sidecar_lanes(self, sub: FleetSubmission
                       ) -> List[Dict[str, Any]]:
        """Harvest each live shard's server-side spans for this query
        tag (before terminal cleanup deletes them) — one trace lane per
        shard that saw work."""
        lanes: List[Dict[str, Any]] = []
        for sc in self._sidecars:
            if sc.dead:
                continue
            try:
                ts = sc.control.trace_spans(sub.query_id)
            except BaseException as e:  # noqa: BLE001 - loss-tolerant
                log.warning("side-car shard %d span harvest for %s "
                            "failed: %s", sc.shard, sub.query_id, e)
                continue
            if not ts["spans"]:
                continue
            pid = getattr(sc.proc, "pid", None) or 0
            with self._lock:
                off = sc.clock_off
                # anchor on the earliest executor dispatch: the
                # side-car only sees work that some dispatch caused
                anchors = [lane["anchor_us"]
                           for lane in sub.lanes.values()
                           if lane.get("anchor_us") is not None]
            name = "rss-sidecar" if len(self._sidecars) == 1 \
                else f"rss-sidecar-{sc.shard}"
            lanes.append({
                "label": f"{name} (pid {pid})" if pid else name,
                "pid": pid or 99999 - sc.shard, "spans": ts["spans"],
                "dropped": ts["dropped"], "offset_s": off,
                "anchor_us": min(anchors) if anchors else None})
        return lanes

    # -- the side-car: health, degrade, cleanup ----------------------------

    def _probe_sidecar(self) -> None:
        for sc in self._sidecars:
            self._probe_one_sidecar(sc)

    def _probe_one_sidecar(self, sc: _SidecarState) -> None:
        with self._lock:
            due = not sc.dead and sc.health.due()
        if not due:
            return
        t0_wall = time.time()
        try:
            resp = sc.control.ping_info()
        except BaseException as e:  # noqa: BLE001 - health-classified
            with self._lock:
                state = sc.health.probe_failed()
            if state == DEAD:
                self._on_sidecar_death(sc, reason=str(e))
            return
        t1_wall = time.time()
        with self._lock:
            sc.health.probe_ok()
            remote_now = resp.get("now")
            if remote_now is not None:
                rtt = max(0.0, t1_wall - t0_wall)
                if rtt <= sc.clock_rtt:
                    sc.clock_rtt = rtt
                    sc.clock_off = \
                        float(remote_now) - (t0_wall + t1_wall) / 2.0

    def _on_sidecar_death(self, sc: _SidecarState, reason: str) -> None:
        with self._lock:
            if sc.dead:
                return
            sc.dead = True
            shards = len(self._sidecars)
        counters.bump("rss_sidecar_deaths")
        scope = "new dispatches degrade to executor-local shuffle" \
            if shards == 1 else \
            f"only the shuffle ids shard {sc.shard} owns degrade " \
            f"(the shard map never shifts)"
        events.emit("sidecar.death",
                    f"rss side-car shard {sc.shard} declared dead: "
                    f"{reason}; {scope}")
        log.warning(
            "rss side-car shard %d declared DEAD (%s): %s; in-flight "
            "queries degrade through their own bounded RPC budgets "
            "(no requeue — executor state is intact)",
            sc.shard, reason, scope)
        # fence a half-alive incarnation, mirroring executor death
        try:
            sc.proc.kill()
        except BaseException as e:  # noqa: BLE001 - best effort
            log.warning("killing dead rss side-car failed: %s", e)

    def _rss_cleanup(self, query_id: str) -> None:
        """Terminal-state manifest/ledger cleanup: delete every durable
        shuffle the query's attempts committed (keyed by the fleet
        query tag).  Never called on requeue — resume depends on the
        blocks surviving the killed attempt.  Fans out across every
        LIVE shard — a query's exchanges spread over all of them."""
        cleaned = False
        for sc in self._sidecars:
            if sc.dead:
                continue
            try:
                sc.control.clear_prefix(f"{query_id}|")
                cleaned = True
            except BaseException as e:  # noqa: BLE001 - best effort
                log.warning("rss cleanup for %s on shard %d failed: %s",
                            query_id, sc.shard, e)
        if cleaned:
            counters.bump("rss_cleanups")

    # -- elastic sizing (auron.fleet.scale.*) ------------------------------

    def _autoscale(self) -> None:
        """Queue-depth scale-up / idle scale-down, one action per
        cooldown window.  Scale-up needs a worker factory (the spawn
        template); scale-down retires through the decommission drain —
        queued work rerouted, running queries never killed (only
        workers with NO in-flight work are eligible)."""
        if self._shutdown:
            return
        up_depth = int(config.conf.get(
            "auron.fleet.scale.up.queue.depth"))
        idle_s = float(config.conf.get("auron.fleet.scale.idle.seconds"))
        if up_depth <= 0 and idle_s <= 0:
            return
        now = time.monotonic()
        cooldown = float(config.conf.get(
            "auron.fleet.scale.cooldown.seconds"))
        victim: Optional[_ExecHandle] = None
        spawn_up = False
        with self._lock:
            if now - self._last_scale < cooldown:
                return
            alive = [h for h in self._handles.values() if not h.dead]
            routable = [h for h in alive if not h.draining]
            if up_depth > 0 and self._worker_factory is not None \
                    and len(self._queue) > up_depth \
                    and len(alive) < int(config.conf.get(
                        "auron.fleet.scale.max.workers")):
                spawn_up = True
            elif idle_s > 0 and len(routable) > int(config.conf.get(
                    "auron.fleet.scale.min.workers")):
                for h in routable:
                    if not h.inflight and \
                            now - h.last_active > idle_s:
                        victim = h
                        break
            if not spawn_up and victim is None:
                return
            self._last_scale = now
            if victim is not None:
                victim.draining = True   # out of routing immediately
        if spawn_up:
            eid = f"exec-s{next(self._scale_seq)}"
            try:
                ep = self._worker_factory(eid)
            except BaseException as e:  # noqa: BLE001 - scale is best effort
                log.warning("fleet scale-up spawn failed: %s", e)
                return
            with self._lock:
                if self._shutdown or ep.executor_id in self._handles:
                    stale = True
                else:
                    stale = False
                    self._handles[ep.executor_id] = _ExecHandle(
                        endpoint=ep, health=ExecutorHealth.from_conf(),
                        last_active=time.monotonic())
            if stale:
                try:
                    ep.close()
                except BaseException:  # noqa: BLE001 - best effort
                    pass
                return
            counters.bump("fleet_scale_ups")
            events.emit("fleet.scale.up",
                        f"spawned {ep.executor_id} (queue depth > "
                        f"{up_depth})", executor=ep.executor_id)
            log.info("fleet scaled UP: spawned %s (queue depth > %d)",
                     ep.executor_id, up_depth)
            self._pump()
            return
        # scale-down: drain (reroutes anything that raced in), close
        try:
            victim.endpoint.drain()
        except BaseException as e:  # noqa: BLE001 - already retiring
            log.warning("drain of idle executor %s failed: %s",
                        victim.endpoint.executor_id, e)
        try:
            victim.endpoint.close()
        except BaseException as e:  # noqa: BLE001 - best effort
            log.warning("close of idle executor %s failed: %s",
                        victim.endpoint.executor_id, e)
        with self._lock:
            victim.retired = True
            victim.dead = True
        counters.bump("fleet_scale_downs")
        events.emit("fleet.scale.down",
                    f"retired idle executor "
                    f"{victim.endpoint.executor_id} (idle > {idle_s:g}s)",
                    executor=victim.endpoint.executor_id)
        log.info("fleet scaled DOWN: retired idle executor %s "
                 "(idle > %.3gs)", victim.endpoint.executor_id, idle_s)

    def _absorb_status(self, handle: _ExecHandle, dispatch_id: str,
                       status: Optional[Dict[str, Any]]) -> None:
        with self._lock:
            qid = handle.inflight.get(dispatch_id)
            sub = self._subs.get(qid) if qid is not None else None
        if sub is None:
            return
        if status is None:
            # the executor does not know the query (a restarted
            # incarnation answering under the old address): lost work,
            # reroute it
            log.warning("executor %s lost query %s; requeueing",
                        handle.endpoint.executor_id, sub.query_id)
            self._requeue(sub, handle)
            return
        state = status.get("state")
        # executor-internal preemptions (PR 10 inside the worker)
        # surface on the fleet row
        sub.num_preemptions = int(status.get("preemptions") or 0)
        if state == SUCCEEDED:
            self._finish_success(handle, sub, status)
        elif state in (FAILED, CANCELLED, SHED_STATE):
            self._finish_failure(handle, sub, status, state)

    def _finish_success(self, handle: _ExecHandle, sub: FleetSubmission,
                        status: Dict[str, Any]) -> None:
        try:
            table = handle.endpoint.result(sub.dispatch_id)
        except BaseException as e:  # noqa: BLE001 - next round decides
            # transient: leave it in flight — the next heartbeat
            # retries, and a real death requeues (re-execution is
            # bit-identical, so fetch-vs-rerun cannot diverge)
            with self._lock:
                handle.health.rpc_failed()
            log.warning("result fetch for %s from %s failed: %s",
                        sub.query_id, handle.endpoint.executor_id, e)
            return
        self.admission.release(sub.query_id)
        mem_peak = int(status.get("mem_peak") or 0)
        if mem_peak:
            self.admission.observe(sub.signature, mem_peak)
        with self._lock:
            handle.inflight.pop(sub.dispatch_id, None)
            if sub.done.is_set():
                return
            sub.result = table
            sub.rows = table.num_rows
            sub.wall_s = float(status.get("wall_s") or 0.0)
            sub.mem_peak = mem_peak
            sub.state = SUCCEEDED
            sub.finished_at = time.time()
            started = sub.started_at
            sub.mark(SUCCEEDED, sub.finished_at)
        if started is not None:
            counters.observe("query_exec_seconds",
                             max(0.0, sub.finished_at - started))
        # stitch + driver-side record BEFORE the terminal side-car
        # cleanup deletes this query's server spans, and before done
        # flips (a client polling /queries/<id> right after /result
        # sees the record)
        self._record_fleet_query(handle, sub, status)
        sub.done.set()
        counters.bump("fleet_completions")
        self._rss_cleanup(sub.query_id)
        self._pump()

    def _finish_failure(self, handle: _ExecHandle, sub: FleetSubmission,
                        status: Dict[str, Any], state: str) -> None:
        self.admission.release(sub.query_id)
        with self._lock:
            handle.inflight.pop(sub.dispatch_id, None)
            if sub.done.is_set():
                return
            sub.state = state
            sub.error = status.get("error") or state
            sub.finished_at = time.time()
            started = sub.started_at
            sub.mark(state, sub.finished_at)
        if started is not None:
            counters.observe("query_exec_seconds",
                             max(0.0, sub.finished_at - started))
        self._record_fleet_query(handle, sub, status)
        sub.done.set()
        if state == CANCELLED:
            counters.bump("queries_cancelled")
        self._rss_cleanup(sub.query_id)
        self._pump()

    def _on_executor_death(self, handle: _ExecHandle,
                           reason: str) -> None:
        with self._lock:
            if handle.dead:
                return
            handle.dead = True
            victims = [(did, qid)
                       for did, qid in handle.inflight.items()]
            handle.inflight.clear()
        counters.bump("fleet_deaths")
        log.warning("executor %s declared DEAD (%s); requeueing %d "
                    "in-flight query(ies) on surviving executors",
                    handle.endpoint.executor_id, reason, len(victims))
        events.emit("worker.death",
                    f"executor {handle.endpoint.executor_id} declared "
                    f"dead: {reason}",
                    [qid for _did, qid in victims],
                    executor=handle.endpoint.executor_id,
                    inflight=len(victims))
        # fence: a half-alive incarnation must not keep executing work
        # that is about to run elsewhere
        handle.endpoint.kill()
        for _did, qid in victims:
            with self._lock:
                sub = self._subs.get(qid)
            if sub is not None:
                if sub.recorder is not None:
                    sub.recorder.add(
                        "event.worker.death", "event",
                        time.perf_counter_ns(), -1,
                        {"executor": handle.endpoint.executor_id,
                         "reason": str(reason)[:200]})
                self._requeue(sub, handle)
        self._pump()

    # -- decommission (graceful drain) -------------------------------------

    def decommission(self, executor_id: str) -> List[str]:
        """Drain an executor: stop routing to it, move its queued (not
        yet started) work to other executors, let running queries
        finish where they are.  Returns the rerouted query ids."""
        handle = self._handles.get(executor_id)
        if handle is None:
            raise KeyError(f"unknown executor {executor_id!r}")
        with self._lock:
            handle.draining = True
        moved_dispatch_ids = handle.endpoint.drain()
        rerouted = []
        for did in moved_dispatch_ids:
            with self._lock:
                qid = handle.inflight.get(did)
                sub = self._subs.get(qid) if qid is not None else None
            if sub is not None:
                self._requeue(sub, handle)
                rerouted.append(sub.query_id)
        self._pump()
        return rerouted

    # -- client surface ----------------------------------------------------

    def get(self, query_id: str) -> Optional[FleetSubmission]:
        with self._lock:
            return self._subs.get(query_id)

    def status(self, query_id: str) -> Optional[Dict[str, Any]]:
        sub = self.get(query_id)
        if sub is None:
            return None
        self._pump()
        return sub.status()

    def result(self, query_id: str):
        sub = self.get(query_id)
        return sub.result if sub is not None else None

    def wait(self, query_id: str,
             timeout: Optional[float] = None) -> bool:
        sub = self.get(query_id)
        if sub is None:
            return False
        deadline = None if timeout is None else time.time() + timeout
        while True:
            remaining = None if deadline is None \
                else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return sub.done.is_set()
            slice_s = 0.1 if remaining is None else min(0.1, remaining)
            if sub.done.wait(slice_s):
                return True
            self._wake.set()

    def cancel(self, query_id: str) -> bool:
        with self._lock:
            sub = self._subs.get(query_id)
            if sub is None or sub.done.is_set():
                return False
            if sub.state == QUEUED:
                if sub in self._queue:
                    self._queue.remove(sub)
                sub.state = CANCELLED
                sub.error = "cancelled while queued"
                sub.finished_at = time.time()
                sub.mark(CANCELLED, sub.finished_at)
                sub.done.set()
                counters.bump("queries_cancelled")
                return True
            handle = self._handles.get(sub.executor_id or "")
            dispatch_id = sub.dispatch_id
        if handle is None or dispatch_id is None:
            return False
        self.admission.release(query_id)
        try:
            handle.endpoint.cancel(dispatch_id)
        except BaseException as e:  # noqa: BLE001 - health-classified
            with self._lock:
                handle.health.rpc_failed()
            log.warning("cancel RPC for %s to %s failed: %s", query_id,
                        handle.endpoint.executor_id, e)
        # the terminal 'cancelled' state is absorbed from the next
        # heartbeat (or the executor's death requeues — and a
        # cancelled fleet row is never requeued: done wins)
        return True

    def executor_up(self) -> Dict[str, int]:
        """1/0 liveness per executor — the `auron_fleet_executor_up`
        gauge on /metrics."""
        with self._lock:
            return {eid: 0 if h.dead else 1
                    for eid, h in self._handles.items()}

    def rss_sidecar_up(self) -> Optional[bool]:
        """None without a side-car; else liveness — the
        `auron_rss_sidecar_up` gauge on /metrics.  With shards, True
        only while EVERY shard lives (one dead shard = degraded)."""
        if not self._sidecars:
            return None
        with self._lock:
            return not any(sc.dead for sc in self._sidecars)

    def fleet_counter_totals(self) -> Dict[str, int]:
        """Worker-process counters summed from the last heartbeat
        loads (dead executors keep their final numbers): the driver's
        view of worker-side resume/degrade evidence — `/metrics`
        `auron_fleet_worker_*_total`."""
        with self._lock:
            totals: Dict[str, int] = {}
            for h in self._handles.values():
                for key, val in (h.load.get("counters") or {}).items():
                    totals[key] = totals.get(key, 0) + int(val)
            return totals

    def fleet_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {eid: h.snapshot()
                    for eid, h in self._handles.items()}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            states: Dict[str, int] = {}
            requeues = 0
            preemptions = 0
            for sub in self._subs.values():
                states[sub.state] = states.get(sub.state, 0) + 1
                requeues += sub.requeues
                preemptions += sub.num_preemptions
            queued = len(self._queue)
            running = states.get(RUNNING, 0)
            sidecars = [sc.snapshot() for sc in self._sidecars]
        fleet: Dict[str, Any] = {"executors": self.fleet_snapshot(),
                                 "worker_counters":
                                     self.fleet_counter_totals()}
        if sidecars:
            # shard 0 keeps the legacy key; the full shard list rides
            # alongside for sharded deployments
            fleet["rss_sidecar"] = sidecars[0]
            fleet["rss_sidecars"] = sidecars
        return {"queued": queued, "running": running, "states": states,
                "preemptions": preemptions, "requeues": requeues,
                "admission": self.admission.snapshot(),
                "fleet": fleet,
                "task_queues": {}}

    def shutdown(self, wait: bool = False,
                 timeout: float = 30.0) -> None:
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            for sub in self._queue:
                sub.state = CANCELLED
                sub.error = "fleet shut down"
                sub.finished_at = time.time()
                sub.mark(CANCELLED, sub.finished_at)
                sub.done.set()
            self._queue.clear()
            handles = list(self._handles.values())
        self._wake.set()
        self._monitor.join(timeout=10)
        for handle in handles:
            try:
                handle.endpoint.close()
            except BaseException as e:  # noqa: BLE001 - best effort
                log.warning("closing executor %s failed: %s",
                            handle.endpoint.executor_id, e)
        for sc in self._sidecars:
            close = getattr(sc.proc, "close", None)
            try:
                if callable(close):
                    close()
            except BaseException as e:  # noqa: BLE001 - best effort
                log.warning("closing rss side-car shard %d failed: %s",
                            sc.shard, e)
        if wait:
            deadline = time.time() + timeout
            for handle in handles:
                proc = getattr(handle.endpoint, "proc", None)
                if proc is not None and proc.poll() is None:
                    try:
                        proc.wait(max(0.1, deadline - time.time()))
                    except Exception:
                        proc.kill()
