"""Concurrent multi-query serving runtime.

The tier above the executor: many queries against ONE process's memory
pool and task pool, reproducing the reference's isolation contract (one
tokio runtime per task inside a shared executor process, PAPER.md) in
session-server form.

- `serving.server.QueryServer` — the profiling HTTP server promoted to
  a submission endpoint (POST /submit, /status/<id>, /result/<id>,
  /cancel/<id>, /scheduler — same port as /metrics and /queries).
- `serving.scheduler.QueryScheduler` — submission states, driver
  threads, priority queue, cancellation.
- `serving.admission` — memory admission control: forecast-gated start
  (reservations through `MemManager.add_reservation`), queue / shed /
  degrade-to-serial under overload (`auron.admission.*`).
- `serving.forecast` — plan-signature keyed `mem_peak` history feeding
  the forecasts (PR 5's accounting layer closing its loop).
- fair-share task scheduling itself lives in `runtime/task_pool.py`
  (per-query queues, weighted round-robin by `auron.query.priority`).
- overload survival (PR 10): the scheduler preempts a running victim
  on memory-watermark pressure and REQUEUES it (kill-and-requeue,
  `auron.serving.preempt.*`), per-query budgets/kills live in
  `memmgr/manager.py`, queued submissions age
  (`auron.admission.aging.seconds`), and shed/timeout responses carry
  `Retry-After` drain estimates.
"""

from auron_tpu.serving.admission import AdmissionController
from auron_tpu.serving.forecast import MemForecaster, plan_signature
from auron_tpu.serving.scheduler import (
    QueryScheduler, Submission, SubmissionRejected,
)
from auron_tpu.serving.server import (
    QueryServer, active_scheduler, install_scheduler, parse_submission,
    register_catalog, uninstall_scheduler,
)

__all__ = [
    "AdmissionController", "MemForecaster", "plan_signature",
    "QueryScheduler", "Submission", "SubmissionRejected",
    "QueryServer", "active_scheduler", "install_scheduler",
    "parse_submission", "register_catalog", "uninstall_scheduler",
]
