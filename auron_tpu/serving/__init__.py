"""Concurrent multi-query serving runtime.

The tier above the executor: many queries against ONE process's memory
pool and task pool, reproducing the reference's isolation contract (one
tokio runtime per task inside a shared executor process, PAPER.md) in
session-server form.

- `serving.server.QueryServer` — the profiling HTTP server promoted to
  a submission endpoint (POST /submit, /status/<id>, /result/<id>,
  /cancel/<id>, /scheduler — same port as /metrics and /queries).
- `serving.scheduler.QueryScheduler` — submission states, driver
  threads, priority queue, cancellation.
- `serving.admission` — memory admission control: forecast-gated start
  (reservations through `MemManager.add_reservation`), queue / shed /
  degrade-to-serial under overload (`auron.admission.*`).
- `serving.forecast` — plan-signature keyed `mem_peak` history feeding
  the forecasts (PR 5's accounting layer closing its loop).
- fair-share task scheduling itself lives in `runtime/task_pool.py`
  (per-query queues, weighted round-robin by `auron.query.priority`).
- overload survival (PR 10): the scheduler preempts a running victim
  on memory-watermark pressure and REQUEUES it (kill-and-requeue,
  `auron.serving.preempt.*`), per-query budgets/kills live in
  `memmgr/manager.py`, queued submissions age
  (`auron.admission.aging.seconds`), and shed/timeout responses carry
  `Retry-After` drain estimates.
- crash-surviving multi-process serving (PR 11): `serving.fleet.
  FleetManager` supervises N executor processes behind the SAME
  front-door admission ledger — heartbeat-driven alive/suspect/dead
  health states, flap circuit-breaking, graceful drain, and the PR 10
  kill-and-requeue generalized across the process boundary (an
  executor killed with `kill -9` has its in-flight queries requeued on
  a different executor, bit-identically, without consuming retry
  budgets).  `serving.executor_endpoint` is the process seam: the
  `ExecutorEndpoint` interface, the in-process `LocalExecutor`
  (default — the fleet code stays dormant), the worker-side
  `ExecutorServer` and the driver-side `ProcessExecutor` client.
"""

from auron_tpu.serving.admission import (
    AdmissionController, PassThroughAdmission,
)
from auron_tpu.serving.executor_endpoint import (
    EndpointError, ExecutorEndpoint, ExecutorServer, LocalExecutor,
    ProcessExecutor,
)
from auron_tpu.serving.fleet import (
    ExecutorHealth, FleetManager, FleetSubmission,
)
from auron_tpu.serving.forecast import MemForecaster, plan_signature
from auron_tpu.serving.scheduler import (
    QueryScheduler, Submission, SubmissionRejected,
)
from auron_tpu.serving.server import (
    QueryServer, active_scheduler, install_scheduler, parse_submission,
    register_catalog, uninstall_scheduler,
)

__all__ = [
    "AdmissionController", "PassThroughAdmission", "MemForecaster",
    "plan_signature", "QueryScheduler", "Submission",
    "SubmissionRejected", "QueryServer", "active_scheduler",
    "install_scheduler", "parse_submission", "register_catalog",
    "uninstall_scheduler", "EndpointError", "ExecutorEndpoint",
    "ExecutorServer", "LocalExecutor", "ProcessExecutor",
    "ExecutorHealth", "FleetManager", "FleetSubmission",
]
