"""Memory-peak forecasting for admission control.

A submission's reservation has to be decided BEFORE the query runs, so
the only honest signal is history: PR 5's always-on accounting records
each query's largest per-operator `mem_peak`, and this module keys those
observations by a structural PLAN SIGNATURE so the next run of the same
plan shape is forecast from what it actually used.  Signatures cover
operator kinds, schemas, expressions and file groups but strip inline
table DATA (LocalTableScan rows), so two submissions of one query over
the same files share a history no matter how the literal payload was
ordered.  A signature with no history falls back to
`auron.admission.default.forecast.bytes`.
"""

from __future__ import annotations

import hashlib
import json
from collections import deque
from typing import Any, Dict, Optional

from auron_tpu.frontend.foreign import ForeignNode
from auron_tpu.runtime import lockcheck


def _strip_data(d: Any) -> Any:
    """Drop row payloads from a foreign-plan dict: the signature tracks
    plan SHAPE + inputs, not inline data volume (which LocalTableScan
    tests can make arbitrarily large)."""
    if isinstance(d, dict):
        return {k: (f"<{len(v)} rows>" if k == "rows"
                    and isinstance(v, list) else _strip_data(v))
                for k, v in d.items()}
    if isinstance(d, list):
        return [_strip_data(x) for x in d]
    return d


def plan_signature(plan: ForeignNode) -> str:
    """Stable structural hash of a foreign plan (op tree + schemas +
    attrs + file groups, minus inline row data)."""
    doc = _strip_data(plan.to_dict())
    blob = json.dumps(doc, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


class MemForecaster:
    """Bounded per-signature history of observed memory peaks."""

    def __init__(self, keep: int = 8):
        self._keep = keep
        self._lock = lockcheck.Lock("serving.forecast")
        self._history: Dict[str, deque] = {}
        # signatures whose history came from the durable stats store
        # rather than this process's own runs — surfaced as forecast
        # PROVENANCE on /scheduler, cleared on the first live peak
        self._seeded: set = set()

    def record(self, signature: str, peak_bytes: int) -> None:
        if peak_bytes <= 0:
            return   # SPMD stage programs report no per-operator peaks
        with self._lock:
            dq = self._history.get(signature)
            if dq is None:
                dq = self._history[signature] = deque(maxlen=self._keep)
            dq.append(int(peak_bytes))
            self._seeded.discard(signature)

    def seed(self, signature: str, peaks) -> bool:
        """Prime a signature's history from the durable stats store
        (cross-restart admission: a fresh process forecasts from what
        the plan ACTUALLY used last lifetime).  Live observations always
        win — a signature that already has history is left alone."""
        peaks = [int(p) for p in peaks if int(p) > 0][-self._keep:]
        if not peaks:
            return False
        with self._lock:
            if self._history.get(signature):
                return False
            dq = self._history[signature] = deque(maxlen=self._keep)
            dq.extend(peaks)
            self._seeded.add(signature)
            return True

    def forecast(self, signature: str) -> Optional[int]:
        """Max of the recent observations, or None with no history (the
        admission controller then applies the configured default)."""
        with self._lock:
            dq = self._history.get(signature)
            return max(dq) if dq else None

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {sig: {"runs": len(dq), "max_peak": max(dq),
                          "last_peak": dq[-1],
                          "provenance": ("store" if sig in self._seeded
                                         else "live")}
                    for sig, dq in self._history.items() if dq}
