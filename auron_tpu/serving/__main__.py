"""`python -m auron_tpu.serving` — run a standalone query server.

Starts a QueryServer (submission + observability on one port) and
blocks; with --demo it also generates a tiny catalog, submits a few
corpus queries and prints their status (a liveness smoke for operators;
the CI gate is tools/serve_check.sh)."""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m auron_tpu.serving",
        description="Auron TPU query-serving HTTP server")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = pick a free port (printed on stdout)")
    ap.add_argument("--demo", action="store_true",
                    help="submit a few tiny corpus queries and exit")
    ap.add_argument("--sf", type=float, default=0.002,
                    help="--demo catalog scale factor")
    ap.add_argument("--executors", type=int, default=None,
                    help="fleet mode: spawn N executor worker "
                         "processes behind one admission ledger "
                         "(default = auron.fleet.executors; 0 keeps "
                         "the in-process scheduler)")
    args = ap.parse_args(argv)

    from auron_tpu.config import conf
    from auron_tpu.serving import QueryServer
    n = args.executors if args.executors is not None \
        else int(conf.get("auron.fleet.executors"))
    if n > 0:
        from auron_tpu.serving.fleet import FleetManager
        fleet = FleetManager.spawn(n)
        srv = QueryServer(scheduler=fleet,
                          host=args.host, port=args.port).start()
        print(f"auron-tpu fleet server ({n} executors) listening on "
              f"{srv.url}", flush=True)
    else:
        srv = QueryServer(host=args.host, port=args.port).start()
        print(f"auron-tpu query server listening on {srv.url}",
              flush=True)
    try:
        if args.demo:
            from auron_tpu.serving.server import corpus_plan
            qids = [srv.scheduler.submit(corpus_plan(n, args.sf))
                    for n in ("q01", "q03", "q42")]
            for qid in qids:
                srv.scheduler.wait(qid, timeout=300)
                print(json.dumps(srv.scheduler.status(qid)), flush=True)
            bad = [q for q in qids
                   if srv.scheduler.status(q)["state"] != "succeeded"]
            return 1 if bad else 0
        while True:   # serve until interrupted
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0
    finally:
        srv.stop()


if __name__ == "__main__":
    sys.exit(main())
