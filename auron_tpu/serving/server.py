"""QueryServer: the profiling HTTP server promoted to a serving endpoint.

The reference ships a lazily-started HTTP service for profiling only;
production Auron serves queries through Spark.  This single-process
analogue promotes that same server (runtime/profiling.py — ONE port, one
handler) into a query-submission surface backed by a QueryScheduler:

- ``POST /submit``        — JSON body, either ``{"plan": <foreign-plan
  dict>}`` (frontend/foreign.py serde) or ``{"corpus": "q01", "sf":
  0.01}`` (an IT-corpus query over a process-cached generated catalog),
  plus optional ``"conf"`` (per-query overrides, applied context-locally)
  and ``"priority"``.  Replies ``{"query_id": ...}``; 429 when shed,
  carrying a ``Retry-After`` header from the admission ledger's drain
  estimate (queue-timeout ``/result`` 409s carry it too).
- ``GET /status/<id>``    — submission state + admission info.
- ``GET /result/<id>``    — result rows as JSON (capped by
  ``auron.serving.result.max.rows``).
- ``POST /cancel/<id>``   — cancel queued/running.
- ``GET /scheduler``      — scheduler + admission + task-queue snapshot.

The profiling endpoints (/metrics, /queries, /memory, ...) stay on the
same port, so one scrape target covers submission AND observability; the
serving routes answer 503 until a scheduler is installed (QueryServer
.start() or install_scheduler())."""

from __future__ import annotations

from typing import Any, Dict, Optional

from auron_tpu.frontend.foreign import ForeignNode
from auron_tpu.runtime import lockcheck
from auron_tpu.runtime.profiling import ProfilingServer
from auron_tpu.serving.scheduler import QueryScheduler

_ACTIVE: Optional[QueryScheduler] = None
_ACTIVE_LOCK = lockcheck.Lock("serving.active")


def install_scheduler(scheduler: QueryScheduler) -> QueryScheduler:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = scheduler
    return scheduler


def uninstall_scheduler(scheduler: Optional[QueryScheduler] = None) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        if scheduler is None or _ACTIVE is scheduler:
            _ACTIVE = None


def active_scheduler() -> Optional[QueryScheduler]:
    return _ACTIVE


# -- corpus submissions (the serve_check / demo path) -----------------------

_CATALOGS: Dict[float, object] = {}
_CATALOG_LOCK = lockcheck.Lock("serving.catalog")


def corpus_plan(name: str, sf: float = 0.002) -> ForeignNode:
    """Build an IT-corpus query plan over a generated catalog cached per
    scale factor for the process lifetime (tempdir-backed parquet)."""
    import tempfile

    from auron_tpu.it import datagen, queries
    with _CATALOG_LOCK:
        catalog = _CATALOGS.get(sf)
        if catalog is None:
            d = tempfile.mkdtemp(prefix=f"auron-serve-sf{sf}-")
            # catalog generation does file IO under the catalog lock ON
            # PURPOSE: concurrent first submissions for one scale factor
            # must wait for a single generation, not race two
            catalog = datagen.generate(d, sf=sf)  # lockcheck: waive (once-per-sf generation)
            _CATALOGS[sf] = catalog
    return queries.build(name, catalog)


def register_catalog(sf: float, catalog) -> None:
    """Pre-register a generated catalog (tests reuse their fixture
    instead of generating a second copy)."""
    with _CATALOG_LOCK:
        _CATALOGS[sf] = catalog


def parse_submission(body: Dict[str, Any]) -> ForeignNode:
    """Submission body -> foreign plan; ValueError on a bad body."""
    if not isinstance(body, dict):
        raise ValueError("submission body must be a JSON object")
    if "plan" in body:
        try:
            return ForeignNode.from_dict(body["plan"])
        except Exception as e:
            raise ValueError(f"bad plan document: {e}") from e
    if "corpus" in body:
        name = str(body["corpus"])
        from auron_tpu.it import queries
        if name not in queries.names():
            raise ValueError(f"unknown corpus query {name!r}")
        return corpus_plan(name, float(body.get("sf", 0.002)))
    raise ValueError("submission needs 'plan' or 'corpus'")


class QueryServer:
    """One port serving submissions + observability: a ProfilingServer
    with a QueryScheduler (or a serving.fleet.FleetManager — same
    client surface, multi-process execution) installed for the serving
    routes."""

    def __init__(self, scheduler=None, session_factory=None,
                 host: str = "127.0.0.1", port: int = 0):
        self.scheduler = scheduler or \
            QueryScheduler(session_factory=session_factory)
        self._http = ProfilingServer(host, port)

    @property
    def url(self) -> str:
        return self._http.url

    @property
    def address(self):
        return self._http.address

    def start(self) -> "QueryServer":
        install_scheduler(self.scheduler)
        self._http.start()
        return self

    def stop(self, wait: bool = True) -> None:
        self.scheduler.shutdown(wait=wait)
        uninstall_scheduler(self.scheduler)
        self._http.stop()
