"""Collective exchange kernels (called INSIDE shard_map bodies).

The shuffle redesign: where the reference writes per-partition sorted runs
to files fetched by the next stage (sort_repartitioner.rs + Spark block
store), an SPMD stage reshuffles rows in-flight with lax.all_to_all.

Shapes must be static, so the exchange uses a fixed per-destination quota
Q: each device scatters its rows into an [N, Q] send buffer grouped by
destination, all_to_all swaps blocks, and receivers compact the valid rows.

Quota sizing (round-3 fix: quota=capacity made every post-exchange buffer
GLOBAL sized, nullifying memory scaling): hash/round-robin exchanges use a
skew-margined per-destination quota ~ capacity/n_dev * margin, so the
received buffer is O(global/n_dev * margin); a single-partition exchange
keeps Q = capacity (one device legitimately receives everything).  Rows
beyond quota cannot be silently lost: every exchange returns an `overflow`
device flag that callers must surface (the SPMD stage compiler psums it
into its runtime guards, and the driver falls back to the serial engine —
the same escape hatch the reference's sort-based repartitioner never
needs because its buffers are dynamic, buffered_data.rs:285).
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def bounded_quota(capacity: int, n_dev: int,
                  margin: float | None = None) -> int:
    """Skew-margined per-destination quota for hash/round-robin exchanges:
    ceil(capacity / n_dev) * margin, rounded up to a multiple of 8.  The
    received buffer is then n_dev * quota ~= capacity * margin instead of
    n_dev * capacity."""
    if margin is None:
        from auron_tpu.config import conf
        margin = float(conf.get("auron.spmd.exchange.quota.margin"))
    per = -(-capacity // max(n_dev, 1))
    q = int(per * margin) + 8
    return min(capacity, -(-q // 8) * 8)


def _scatter_to_send(data, dest, valid, n_dev: int, quota: int):
    """data: [C, ...] row-major payload; dest int32 [C]; -> [N, Q, ...]."""
    cap = dest.shape[0]
    safe_dest = jnp.where(valid, dest, n_dev)          # invalid -> dropped
    # within-destination slot: stable rank of each row among its dest
    # group.  The key is tiny (values <= n_dev), so the radix strategy
    # packs it with the row-index carry into ONE value sort instead of a
    # full comparator argsort (same stable permutation either way).
    from auron_tpu.ops.strategy import sort_strategy
    if sort_strategy(cap) == "radix":
        from auron_tpu.ops.radix_sort import radix_sort_indices
        order = radix_sort_indices([safe_dest.astype(jnp.uint32)],
                                   [max(int(n_dev).bit_length(), 1)])
    else:
        order = jnp.argsort(safe_dest, stable=True)    # groups by dest
    sorted_dest = jnp.take(safe_dest, order)
    idx = jnp.arange(cap, dtype=jnp.int32)
    # start offset of each dest group in sorted order
    is_start = jnp.concatenate([jnp.ones(1, bool),
                                sorted_dest[1:] != sorted_dest[:-1]])
    group_start = lax.cummax(jnp.where(is_start, idx, -1))
    slot_sorted = idx - group_start
    # scatter into [N*Q] flat send buffer
    flat_pos = sorted_dest * quota + jnp.minimum(slot_sorted, quota - 1)
    ok = jnp.logical_and(sorted_dest < n_dev, slot_sorted < quota)
    flat_pos = jnp.where(ok, flat_pos, n_dev * quota)  # spill to scratch row
    payload = jnp.take(data, order, axis=0)
    out_shape = (n_dev * quota + quota,) + data.shape[1:]
    send = jnp.zeros(out_shape, data.dtype)
    send = send.at[flat_pos].set(payload, mode="drop")
    send_valid = jnp.zeros(n_dev * quota + quota, bool)
    send_valid = send_valid.at[flat_pos].set(ok, mode="drop")
    send = send[:n_dev * quota].reshape((n_dev, quota) + data.shape[1:])
    send_valid = send_valid[:n_dev * quota].reshape(n_dev, quota)
    # a valid row routed to a real destination but past its quota slot was
    # dropped from the buffer — flag it (callers must not ignore this)
    overflow = jnp.any(jnp.logical_and(
        jnp.logical_and(sorted_dest < n_dev, slot_sorted >= quota),
        jnp.take(valid, order)))
    return send, send_valid, overflow


def all_to_all_repartition(arrays: List[Any], dest, valid, axis: str,
                           n_dev: int, quota: int
                           ) -> Tuple[List[Any], Any]:
    """Repartition rows of `arrays` (each [C, ...]) by `dest` device ids.

    Returns (received_arrays each [N*Q, ...], received_valid [N*Q],
    overflow bool scalar — LOCAL to this device; psum/any-reduce it).
    Must run inside shard_map with named axis `axis`.
    """
    outs = []
    recv_valid = None
    overflow = None
    for a in arrays:
        send, send_valid, ovf = _scatter_to_send(a, dest, valid, n_dev,
                                                 quota)
        recv = lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                              tiled=False)
        outs.append(recv.reshape((n_dev * quota,) + a.shape[1:]))
        if recv_valid is None:
            overflow = ovf
            rv = lax.all_to_all(send_valid, axis, split_axis=0,
                                concat_axis=0, tiled=False)
            recv_valid = rv.reshape(n_dev * quota)
    if overflow is None:
        overflow = jnp.asarray(False)
    return outs, recv_valid, overflow


def broadcast_all_gather(arrays: List[Any], valid, axis: str
                         ) -> Tuple[List[Any], Any]:
    """Broadcast exchange: every device receives every device's rows
    (the BHJ build-side path: one all_gather instead of TorrentBroadcast).
    arrays: [C, ...] -> [N*C, ...]."""
    outs = []
    for a in arrays:
        g = lax.all_gather(a, axis, axis=0, tiled=False)
        outs.append(g.reshape((-1,) + a.shape[1:]))
    gv = lax.all_gather(valid, axis, axis=0, tiled=False).reshape(-1)
    return outs, gv


def global_sum(x, axis: str):
    return lax.psum(x, axis)


def hierarchical_repartition(arrays: List[Any], dest, valid,
                             ici_axis: str, dcn_axis: str,
                             n_ici: int, n_dcn: int, quota: int,
                             bound_stage2: bool = True):
    """Two-stage repartition for multi-slice meshes: rows first move
    WITHIN a slice (over the fast ICI axis) to the local chip whose ICI
    rank matches the destination chip, then cross slices over DCN in one
    aligned all_to_all.

    This is the standard hierarchical all-to-all: every row crosses DCN at
    most once and the DCN transfer is slice-to-slice aligned, instead of a
    flat all_to_all over N_ici*N_dcn devices whose traffic is dominated by
    the slow axis (SURVEY §2.5: "lay out shardings so collectives ride
    ICI, not DCN").

    `dest` is the GLOBAL destination device id laid out as
    dcn_rank * n_ici + ici_rank.  `quota` is the per-destination bound of
    stage 1, which spreads over the n_ici LOCAL chips — size it for
    n_ici destinations (bounded_quota(capacity, n_ici)), not n_dev.
    Must run inside shard_map with both named axes.  Returns
    ([n_dcn*q2, ...] arrays, valid mask, overflow flag) on each
    destination device, where q2 = n_ici*quota unbounded, or its
    n_dcn-margined bound when bound_stage2 (same row-layout contract as
    all_to_all_repartition).
    """
    # stage 1 (ICI): deliver each row to the local chip with ici_rank ==
    # dest_ici; rows keep their dcn destination as payload
    dest_ici = (dest % n_ici).astype(jnp.int32)
    dest_dcn = (dest // n_ici).astype(jnp.int32)
    stage1, v1, ovf1 = all_to_all_repartition(
        arrays + [dest_dcn], dest_ici, valid, ici_axis, n_ici, quota)
    payload1, dcn1 = stage1[:-1], stage1[-1]
    # stage 2 (DCN): every chip now holds only rows whose final chip has
    # its own ici_rank; swap across slices by dcn rank.  Stage-1 output
    # splits over n_dcn destinations, so the same margined bound applies
    # (n_ici*quota covers the worst case; the bound keeps receive buffers
    # O(global/n_dev))
    cap1 = n_ici * quota
    q2 = cap1 if (n_dcn <= 1 or not bound_stage2) \
        else min(cap1, bounded_quota(cap1, n_dcn))
    stage2, v2, ovf2 = all_to_all_repartition(
        payload1, dcn1, v1, dcn_axis, n_dcn, q2)
    return stage2, v2, jnp.logical_or(ovf1, ovf2)
