"""Distributed execution over a jax.sharding.Mesh.

The reference's distribution is Spark tasks + shuffle files/RSS push
(SURVEY §2.5); the TPU-native equivalent keeps the same logical exchanges
but rides ICI/DCN collectives inside SPMD programs:

- hash/round-robin/range repartition  -> lax.all_to_all (quota-based
  fixed-size blocks, shapes static)
- broadcast exchange / BHJ build side -> lax.all_gather
- global aggregates / metrics         -> lax.psum

`spmd.py` builds a fully jitted SPMD "query step" (filter -> project ->
exchange -> aggregate -> broadcast-join probe) over the mesh; `mesh.py`
holds mesh construction helpers; `exchange.py` the collective repartition
kernels.  Multi-host meshes compose the same way (jax initializes the
global mesh across hosts; collectives cross DCN transparently).
"""

from auron_tpu.parallel.mesh import data_mesh, device_count
from auron_tpu.parallel.exchange import (
    all_to_all_repartition, broadcast_all_gather,
)

__all__ = ["data_mesh", "device_count", "all_to_all_repartition",
           "broadcast_all_gather"]
