"""Fully-jitted SPMD query steps over a device mesh.

One compiled XLA program per stage shape: local expression kernels, hash
repartition over ICI all_to_all, sort-based local aggregation, broadcast
join probe via all_gather, global metrics via psum — the multi-chip
execution model of the framework (the dryrun_multichip entry exercises
exactly this path on a virtual mesh).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from auron_tpu.exprs import hashing as H
from auron_tpu.ops import segments
from auron_tpu.parallel.exchange import (
    all_to_all_repartition, broadcast_all_gather, global_sum,
)
from auron_tpu.runtime import jitcheck


class QueryStepOut(NamedTuple):
    group_keys: Any      # [N, G] per-device aggregated keys (padded -1)
    group_sums: Any      # [N, G] float sums per key
    group_joined: Any    # [N, G] dim value joined onto each key
    group_count: Any     # [N, G] per-key row counts
    total_rows: Any      # [] global filtered row count (psum)


def make_query_step(mesh: Mesh, axis: str = "parts",
                    capacity: int = 1024):
    """Build the jitted SPMD step.

    Per-device inputs (sharded along `axis`):
      key   int64  [n_dev*C]  - group/join key
      amount f32   [n_dev*C]  - measure
      disc   f32   [n_dev*C]  - discount fraction
      valid  bool  [n_dev*C]  - live-row mask
    Replicated inputs:
      dim_key int64 [D], dim_val f32 [D] - small broadcast-joined table
    """
    if axis not in mesh.shape:
        raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
    n_dev = mesh.shape[axis]
    quota = capacity

    def per_device(key, amount, disc, valid, dim_key_shard, dim_val_shard):
        # 1. filter: amount > 0 (data-dependent mask, static shapes)
        keep = jnp.logical_and(valid, amount > 0)
        # 2. project: net = amount * (1 - disc)
        net = jnp.where(keep, amount * (1.0 - disc), 0.0)
        # 3. hash repartition by key over ICI (spark murmur3 seed 42)
        kcol = _FakeCol(key, keep)
        h = H.hash_columns([kcol], seed=42)
        pid = H.pmod(h, n_dev)
        (rk, rnet), rvalid, _ovf = all_to_all_repartition(
            [key, net], pid, keep, axis, n_dev, quota)
        # 4. broadcast exchange: dim table arrives sharded; all_gather
        #    materializes the full build side on every device (the
        #    TorrentBroadcast/BHJ-build analogue riding ICI)
        (dim_key, dim_val), _ = broadcast_all_gather(
            [dim_key_shard, dim_val_shard],
            jnp.ones(dim_key_shard.shape[0], bool), axis)
        # 5. local sort-based aggregation + dim probe (shared kernel)
        gkeys, sums, joined, counts = local_group_aggregate(
            rk, rnet, rvalid, dim_key, dim_val)
        # 6. global metric over the mesh
        total = global_sum(jnp.sum(keep.astype(jnp.int64)), axis)
        return gkeys, sums, joined, counts, total

    shard = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(PS(axis), PS(axis), PS(axis), PS(axis), PS(axis), PS(axis)),
        out_specs=(PS(axis), PS(axis), PS(axis), PS(axis), PS()),
        check_vma=False)

    def step(key, amount, disc, valid, dim_key, dim_val) -> QueryStepOut:
        g, s, j, c, t = shard(key, amount, disc, valid, dim_key, dim_val)
        return QueryStepOut(g, s, j, c, t)

    return jitcheck.site("spmd.query_step").jit(step)


def local_group_aggregate(key, value, live, dim_key, dim_val):
    """Shared local kernel: sort-based group-sum over (key, value) rows,
    then probe the (replicated) sorted dim table.  Used identically by the
    SPMD per-device body and the single-chip step."""
    cap2 = key.shape[0]
    sort_key = jnp.where(live, key, jnp.int64(2**62))
    # multi-operand sort carries the payload through the sorting network
    # instead of argsort + 3 gathers — gathers are the expensive part on
    # TPU (random-access HBM), the sort itself is MXU-adjacent vector work
    sk, sv, slive_i = jax.lax.sort(
        (sort_key, value, live.astype(jnp.int32)), num_keys=1,
        is_stable=False)
    slive = slive_i.astype(bool)
    boundary = jnp.logical_and(
        jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]]), slive)
    seg = jnp.where(slive, jnp.cumsum(boundary.astype(jnp.int32)) - 1,
                    cap2 - 1)
    sums = segments.sorted_segment_sum(jnp.where(slive, sv, 0.0), seg,
                                       cap2)
    counts = segments.sorted_segment_sum(slive.astype(jnp.int64), seg,
                                         cap2)
    first_idx = jnp.nonzero(boundary, size=cap2, fill_value=cap2 - 1)[0]
    gkeys = jnp.where(jnp.arange(cap2, dtype=jnp.int32) < jnp.sum(boundary),
                      jnp.take(sk, first_idx), -1)
    # stable: with duplicate dim keys, the first-occurring row must win
    # deterministically (searchsorted probes the leftmost equal slot)
    dk, dv = jax.lax.sort((dim_key, dim_val), num_keys=1, is_stable=True)
    pos = jnp.clip(jnp.searchsorted(dk, gkeys), 0, dk.shape[0] - 1)
    hit = jnp.take(dk, pos) == gkeys
    joined = jnp.where(hit, jnp.take(dv, pos), jnp.nan)
    return gkeys, sums, joined, counts


def make_single_chip_step():
    """The single-chip forward step: same pipeline minus collectives
    (filter -> project -> hash -> sort-based group-sum -> dim-table probe);
    sized entirely by its input shapes.  Used for compile checks and as the
    bench kernel."""

    def step(key, amount, disc, valid, dim_key, dim_val):
        keep = jnp.logical_and(valid, amount > 0)
        net = jnp.where(keep, amount * (1.0 - disc), 0.0)
        gkeys, sums, joined, counts = local_group_aggregate(
            key, net, keep, dim_key, dim_val)
        return gkeys, sums, joined, counts, jnp.sum(keep.astype(jnp.int64))

    return jitcheck.site("spmd.single_chip").jit(step)


class _FakeCol:
    """Minimal duck-typed column for hashing inside SPMD bodies."""

    def __init__(self, data, validity):
        self.data = data
        self.validity = validity
        from auron_tpu.ir.schema import DataType
        self.dtype = DataType.int64()


def example_inputs(mesh: Mesh, axis: str = "parts", capacity: int = 1024,
                   seed: int = 0, dim_rows: int = 64):
    """Sharded example inputs sized for the mesh (dim table is sharded too
    — the step all_gathers it, exercising the broadcast exchange)."""
    n_dev = mesh.shape[axis]
    rng = np.random.default_rng(seed)
    n = n_dev * capacity
    key = rng.integers(0, 50, n).astype(np.int64)
    amount = rng.normal(10, 5, n).astype(np.float32)
    disc = rng.uniform(0, 0.5, n).astype(np.float32)
    valid = np.ones(n, bool)
    dim_rows = ((dim_rows + n_dev - 1) // n_dev) * n_dev  # shardable
    dim_key = np.arange(dim_rows, dtype=np.int64)
    dim_val = rng.normal(0, 1, dim_rows).astype(np.float32)
    sharded = NamedSharding(mesh, PS(axis))
    put = lambda a, s: jax.device_put(a, s)  # noqa: E731
    return (put(key, sharded), put(amount, sharded), put(disc, sharded),
            put(valid, sharded), put(dim_key, sharded),
            put(dim_val, sharded))
