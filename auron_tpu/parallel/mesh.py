"""Mesh helpers.

Queries are data-parallel over partitions, so the default mesh is 1-D
("parts").  Joins/aggregations that want a 2-D layout (partition x replica
for broadcast reuse) can build ("parts", "replica") meshes the same way.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def device_count() -> int:
    return len(jax.devices())


def data_mesh(n: Optional[int] = None, axis: str = "parts") -> Mesh:
    devs = jax.devices()
    n = n or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def grid_mesh(parts: int, replicas: int,
              axes: Sequence[str] = ("parts", "replica")) -> Mesh:
    devs = jax.devices()
    need = parts * replicas
    if need > len(devs):
        raise ValueError(f"requested {need} devices, have {len(devs)}")
    arr = np.array(devs[:need]).reshape(parts, replicas)
    return Mesh(arr, tuple(axes))


def hierarchical_mesh(n_dcn: int, n_ici: Optional[int] = None,
                      axes: Sequence[str] = ("dcn", "ici")) -> Mesh:
    """Multi-slice mesh: the slow (DCN) axis outermost, fast (ICI)
    innermost.  Exchanges over this mesh ride the two-stage hierarchical
    all-to-all (parallel/exchange.py:hierarchical_repartition) so every
    row crosses DCN at most once."""
    devs = jax.devices()
    n_ici = n_ici or len(devs) // n_dcn
    need = n_dcn * n_ici
    if need > len(devs):
        raise ValueError(f"requested {need} devices, have {len(devs)}")
    arr = np.array(devs[:need]).reshape(n_dcn, n_ici)
    return Mesh(arr, tuple(axes))
