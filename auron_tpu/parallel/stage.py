"""SPMD stage compiler: planner IR -> ONE jitted shard_map program.

This is the multi-chip execution path of the engine (SURVEY §2.5 rows
67-72; reference analogue: Spark schedules the reference's native tasks
per partition, rt.rs:76-139, with shuffle files between stages,
shuffle/mod.rs:112-189).  On TPU the whole pipeline compiles to one XLA
program over a `jax.sharding.Mesh`:

- partition (data) parallelism: every operator body runs per device on its
  shard of rows, shapes static, a `live` row mask carrying filtered-ness
  (no compaction between operators — the mask IS the selection vector);
- hash/round-robin/single repartitioning: murmur3(seed=42) partition ids
  computed on device, rows exchanged with `lax.all_to_all` riding ICI
  (parallel/exchange.py), replacing the reference's sort-based shuffle
  files;
- broadcast exchange: `lax.all_gather` materializes the build side on
  every device (NativeBroadcastExchangeBase.collectNative analogue);
- group aggregation: the same sort-based `_group_reduce_body` kernel the
  serial engine uses, traced inline;
- broadcast/hash join: sorted-hash build + searchsorted probe, restricted
  to probe-row-preserving shapes (single-match builds: the dim-table
  pattern) — multi-match joins fall back to the serial engine.

Anything the compiler cannot express raises `SpmdUnsupported`; callers
(AuronSession.execute with a mesh) fall back to the per-partition serial
path, mirroring how the reference falls back to JVM execution for
unconvertible plan sections (AuronConvertStrategy).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from auron_tpu.columnar.batch import (
    Batch, DeviceColumn, DeviceStringColumn, HostColumn, bucket_capacity,
)
from auron_tpu.exprs import hashing as H
from auron_tpu.exprs.compiler import EvalCtx, device_capable, evaluate
from auron_tpu.ir import plan as P
from auron_tpu.ir.expr import Expr
from auron_tpu.ir.node import Node
from auron_tpu.ir.schema import DataType, Field, Schema
from auron_tpu.parallel.exchange import (
    all_to_all_repartition, bounded_quota, broadcast_all_gather,
    hierarchical_repartition,
)
from auron_tpu.runtime import jitcheck

Array = Any


class SpmdUnsupported(Exception):
    """Plan shape the SPMD compiler cannot express; fall back to the
    serial per-partition engine."""


class SpmdGuardTripped(SpmdUnsupported):
    """A runtime guard invalidated the SPMD result.  `retryable` marks
    join duplicate-key trips a pair-expansion retry can fix; `shrink`
    marks agg capacity-shrink overflows the capacity LADDER retries
    (4x per step, then shrink off); `join_compact` marks join-chain
    compaction overflows a compaction-off retry fixes; hard trips
    (exchange quota overflow, dup keys past the factor or under a
    semi-like join) fall straight back to the serial engine."""

    def __init__(self, message: str, retryable: bool = False,
                 shrink: bool = False, join_compact: bool = False,
                 hard: bool = False):
        super().__init__(message)
        self.retryable = retryable
        self.shrink = shrink
        # the join-chain compaction overflowed: retry with compaction
        # disabled (independent of the agg shrink dimension)
        self.join_compact = join_compact
        # hard quota/dup-key trip: normally falls straight back to
        # serial, EXCEPT that while the agg capacity shrink is active the
        # downstream exchange quotas were sized from the SHRUNK capacity,
        # so a skewed routing that fit pre-shrink can overflow them — the
        # ladder gives such trips shrink climbs while cap_eff > 0 before
        # conceding (ADVICE r4)
        self.hard = hard


@dataclass
class DeviceTable:
    """Per-device value flowing between traced operator bodies."""
    schema: Schema
    cols: List[Any]     # DeviceColumn / DeviceStringColumn (capacity rows)
    live: Array         # bool[capacity]

    @property
    def capacity(self) -> int:
        return int(self.live.shape[0])


# ---------------------------------------------------------------------------
# plan walk (traced inside shard_map)
# ---------------------------------------------------------------------------

class _StageTracer:
    def __init__(self, conv_ctx, bindings: Dict[str, DeviceTable],
                 axis, n_dev: int,
                 shadow_sort: Optional[P.Sort] = None,
                 scan_rids: Optional[Dict[int, str]] = None,
                 axis_sizes: Optional[Tuple[int, ...]] = None,
                 match_factor: int = 1,
                 agg_cap_hint: int = 0,
                 hash_grouping: bool = False,
                 join_compact: bool = True):
        self.exchanges = getattr(conv_ctx, "exchanges", None) or {}
        self.broadcasts = getattr(conv_ctx, "broadcasts", None) or {}
        self.bindings = bindings
        self.axis = axis
        self.n_dev = n_dev
        # multi-axis mesh (dcn, ici): sizes aligned with the axis tuple
        self.axis_sizes = axis_sizes
        # the driver-side global sort that re-orders (and re-limits) the
        # gathered result; per-partition top-k sorts it shadows are
        # dropped (the TakeOrderedAndProject pattern: partition top-k ->
        # single exchange -> global top-k)
        self.shadow_sort = shadow_sort
        self.scan_rids = scan_rids or {}
        # runtime guards: device booleans that invalidate the SPMD result
        # post-run; the driver fetches them with the output.  `guards`
        # are HARD (quota overflow, dup keys past the match factor, dup
        # keys under a semi-like join): fall back to serial.
        # `retry_guards` are join dup-key trips a pair-expansion retry
        # can fix.
        self.guards: List[Any] = []
        self.retry_guards: List[Any] = []
        # `shrink_guards` trip when an agg's group count overflows the
        # shrunk static capacity (auron.spmd.agg.capacity.hint); the
        # driver retries once with shrinking disabled (full capacity).
        self.shrink_guards: List[Any] = []
        # `join_guards` trip when a K-expanded join's live output
        # overflows the compaction target; the driver retries with join
        # compaction disabled — an INDEPENDENT retry dimension so a
        # genuinely fanning-out join doesn't also lose the agg shrink
        self.join_guards: List[Any] = []
        # join pair-expansion factor (1 = single-candidate probe)
        self.match_factor = max(1, int(match_factor))
        # post-agg static capacity (rows/device); 0 keeps input capacity
        self.agg_cap_hint = max(0, int(agg_cap_hint))
        # compact K-expanded join outputs back to pre-expansion capacity
        self.join_compact = bool(join_compact)
        # hash-table group reduce (CPU mesh only — mirrors
        # AggExec._grouping_strategy: XLA's comparator sort is ~3x numpy
        # on CPU; on TPU scatters serialize and sort wins)
        self.hash_grouping = bool(hash_grouping)

    def _axis_index(self):
        """Global device id; for a (dcn, ici) mesh the layout is
        dcn_rank * n_ici + ici_rank (hierarchical_repartition contract)."""
        if isinstance(self.axis, tuple):
            a_dcn, a_ici = self.axis
            n_ici = self.axis_sizes[1]
            return (lax.axis_index(a_dcn) * n_ici +
                    lax.axis_index(a_ici)).astype(jnp.int32)
        return lax.axis_index(self.axis)

    # -- expression eval -------------------------------------------------

    def _eval_exprs(self, exprs, t: DeviceTable) -> List[Any]:
        for x in exprs:
            if not device_capable(x, t.schema, frozenset()):
                raise SpmdUnsupported(f"expr not device-capable: {x.kind}")
            if _tree_has(x, ("row_num", "monotonically_increasing_id",
                             "py_udf_wrapper", "scalar_subquery")):
                raise SpmdUnsupported(f"stateful expr in SPMD: {x.kind}")
        ctx = EvalCtx(cols=list(t.cols), schema=t.schema,
                      num_rows=jnp.sum(t.live.astype(jnp.int32)),
                      capacity=t.capacity,
                      partition_id=self._axis_index(),
                      row_base=jnp.int64(0))
        return [evaluate(x, ctx) for x in exprs]

    # -- node dispatch -----------------------------------------------------

    def eval_node(self, node) -> DeviceTable:
        if not isinstance(node, P.PlanNode):
            raise SpmdUnsupported(f"non-native section: {type(node).__name__}")
        handler = getattr(self, f"_do_{node.kind}", None)
        if handler is None:
            raise SpmdUnsupported(f"operator not SPMD-compilable: {node.kind}")
        return handler(node)

    # sources ---------------------------------------------------------------

    def _binding(self, rid: str, schema: Schema) -> DeviceTable:
        if rid not in self.bindings:
            raise SpmdUnsupported(f"unbound resource {rid!r}")
        return self.bindings[rid]

    def _do_ffi_reader(self, n: P.FFIReader) -> DeviceTable:
        return self._binding(n.resource_id, n.schema)

    def _do_parquet_scan(self, n: P.ParquetScan) -> DeviceTable:
        # scans were pre-materialized by the driver (host IO) and sharded
        # over the mesh under deterministic walk-order rids
        return self._binding(self.scan_rids.get(id(n), "?"), n.schema)

    def _do_orc_scan(self, n: P.OrcScan) -> DeviceTable:
        return self._binding(self.scan_rids.get(id(n), "?"), n.schema)

    def _do_ipc_reader(self, n: P.IpcReader) -> DeviceTable:
        # an IpcReader is how the converted plan references an exchange or
        # broadcast boundary; inline it as a collective
        rid = n.resource_id
        if rid in self.exchanges:
            job = self.exchanges[rid]
            child = self.eval_node(_require_native(job.child))
            return self._exchange(child, job.partitioning)
        if rid in self.broadcasts:
            job = self.broadcasts[rid]
            child = self.eval_node(_require_native(job.child))
            return self._broadcast(child)
        return self._binding(rid, n.schema)

    # exchanges --------------------------------------------------------------

    def _exchange(self, t: DeviceTable, part: P.Partitioning) -> DeviceTable:
        n_dev = self.n_dev
        if n_dev == 1:
            # single-device axis: every row already lives on its
            # destination — the exchange is an identity, and the quota
            # machinery would only DOUBLE the buffer (capacity x margin)
            # for nothing (a real cost at sf10 single-chip shapes)
            return t
        if part.mode == "hash":
            keys = self._eval_exprs(part.expressions, t)
            h = H.hash_columns(keys, seed=42)
            pid = H.pmod(h, n_dev).astype(jnp.int32)
        elif part.mode == "round_robin":
            base = self._axis_index().astype(jnp.int32)
            pid = (base + jnp.arange(t.capacity, dtype=jnp.int32)) % n_dev
        elif part.mode == "single":
            pid = jnp.zeros(t.capacity, jnp.int32)
        elif part.mode == "range":
            # sampled-bounds range ids (shared kernel with the serial
            # repartitioner), then bucket -> device by modulo: SPMD
            # bodies are order-insensitive, so range locality only
            # matters to the driver-side tail sort, not device placement
            from auron_tpu.ops.shuffle.partitioner import (
                encoded_range_bounds, range_ids_from_words,
            )
            from auron_tpu.ops.sort_keys import encode_sort_keys as _enc
            keys = self._eval_exprs(
                tuple(s.child for s in part.sort_orders), t)
            orders = tuple((s.asc, s.nulls_first)
                           for s in part.sort_orders)
            words = _enc(keys, orders)
            bounds = encoded_range_bounds(part.range_bounds,
                                          part.sort_orders, orders)
            pid = range_ids_from_words(words, bounds, t.capacity) % n_dev
        else:
            raise SpmdUnsupported(f"partitioning mode {part.mode!r}")
        flat, treedef = jax.tree.flatten(t.cols)
        # bounded quota for spreading modes (hash/rr): received buffers
        # stay O(global/n_dev * margin); a single-partition exchange
        # legitimately funnels everything to one device, so it keeps the
        # full-capacity quota.  Overflow (quota exceeded under skew) trips
        # a runtime guard -> driver falls back to the serial engine.
        # only single (and a degenerate 1-partition range — all ids 0)
        # actually funnel everything to one device; hash/round-robin
        # spread over n_dev regardless of the plan's num_partitions,
        # while range spreads over at most its num_partitions buckets
        funnel = part.mode == "single" or (
            part.mode == "range" and part.num_partitions <= 1)
        spread = part.num_partitions if part.mode == "range" else n_dev
        if isinstance(self.axis, tuple):
            # 2-D (dcn, ici) mesh: two-stage exchange so every row crosses
            # the slow DCN axis at most once (SURVEY 2.5 comm-backend
            # row).  Stage 1 spreads over only the n_ici LOCAL
            # destinations, so its quota is sized for n_ici — an
            # n_dev-sized quota would overflow on uniform data whenever
            # n_dcn > margin
            a_dcn, a_ici = self.axis
            n_dcn, n_ici = self.axis_sizes
            q1 = t.capacity if funnel \
                else bounded_quota(t.capacity, min(n_ici, spread))
            outs, live, ovf = hierarchical_repartition(
                flat, pid, t.live, a_ici, a_dcn, n_ici, n_dcn,
                quota=q1, bound_stage2=not funnel)
            any_ovf = lax.psum(
                lax.psum(ovf.astype(jnp.int32), a_ici), a_dcn) > 0
        else:
            quota = t.capacity if funnel \
                else bounded_quota(t.capacity, min(n_dev, spread))
            outs, live, ovf = all_to_all_repartition(flat, pid, t.live,
                                                     self.axis, n_dev,
                                                     quota=quota)
            any_ovf = lax.psum(ovf.astype(jnp.int32), self.axis) > 0
        self.guards.append(any_ovf)
        cols = jax.tree.unflatten(treedef, outs)
        return DeviceTable(t.schema, cols, live)

    def _broadcast(self, t: DeviceTable) -> DeviceTable:
        flat, treedef = jax.tree.flatten(t.cols)
        if isinstance(self.axis, tuple):
            live = t.live
            for ax in reversed(self.axis):    # gather ICI first, then DCN
                flat, live = broadcast_all_gather(flat, live, ax)
        else:
            flat, live = broadcast_all_gather(flat, t.live, self.axis)
        cols = jax.tree.unflatten(treedef, flat)
        return DeviceTable(t.schema, cols, live)

    # row ops -----------------------------------------------------------------

    def _concat_tables(self, schema: Schema,
                       tables: List[DeviceTable]) -> DeviceTable:
        from auron_tpu.columnar.batch import concat_device_columns
        cols = [concat_device_columns([t.cols[i] for t in tables])
                for i in range(len(schema))]
        live = jnp.concatenate([t.live for t in tables])
        return DeviceTable(schema, cols, live)

    def _do_union(self, n: P.Union) -> DeviceTable:
        # SPMD union: every device holds a shard of every child, so the
        # per-partition enumeration (proto:542-552 — one UnionInput per
        # child partition) collapses to ONE concat of the child; a child
        # whose partitions are each referenced m times contributes m
        # replicated copies (rows-twice semantics of duplicate inputs)
        by_child: Dict[int, Any] = {}
        order: List[int] = []
        for i in n.inputs:
            if id(i.child) not in by_child:
                by_child[id(i.child)] = (i.child, {})
                order.append(id(i.child))
            by_child[id(i.child)][1].setdefault(i.partition, 0)
            by_child[id(i.child)][1][i.partition] += 1
        tables: List[DeviceTable] = []
        for cid in order:
            child, part_counts = by_child[cid]
            counts = set(part_counts.values())
            if len(counts) != 1:
                raise SpmdUnsupported(
                    "union references a child's partitions unevenly")
            t = self.eval_node(child)
            for _ in range(counts.pop()):
                tables.append(t)
        return self._concat_tables(n.schema, tables)

    def _do_expand(self, n: P.Expand) -> DeviceTable:
        # grouping-sets: each projection contributes one replicated copy
        # of the child rows (expand_exec.rs:40)
        t = self.eval_node(n.child)
        schema = Schema(tuple(Field(nm, dt)
                              for nm, dt in zip(n.names, n.types)))
        parts = [DeviceTable(schema, self._eval_exprs(proj, t), t.live)
                 for proj in n.projections]
        return self._concat_tables(schema, parts)

    def _do_filter(self, n: P.Filter) -> DeviceTable:
        t = self.eval_node(n.child)
        live = t.live
        for p in n.predicates:
            [m] = self._eval_exprs((p,), t)
            live = jnp.logical_and(
                live, jnp.logical_and(m.validity, m.data.astype(bool)))
        return DeviceTable(t.schema, t.cols, live)

    def _do_projection(self, n: P.Projection) -> DeviceTable:
        t = self.eval_node(n.child)
        cols = self._eval_exprs(n.exprs, t)
        from auron_tpu.exprs.typing import infer_type
        fields = tuple(Field(nm, infer_type(x, t.schema))
                       for nm, x in zip(n.names, n.exprs))
        return DeviceTable(Schema(fields), cols, t.live)

    def _do_rename_columns(self, n: P.RenameColumns) -> DeviceTable:
        t = self.eval_node(n.child)
        return DeviceTable(t.schema.rename(tuple(n.names)), t.cols, t.live)

    def _do_coalesce_batches(self, n: P.CoalesceBatches) -> DeviceTable:
        return self.eval_node(n.child)

    def _do_debug(self, n: P.Debug) -> DeviceTable:
        return self.eval_node(n.child)

    # aggregation --------------------------------------------------------------

    def _agg_exec_meta(self, n: P.Agg, child_schema: Schema):
        """Instantiate AggExec purely for its spec/schema metadata."""
        from auron_tpu.ops.agg.exec import AggExec
        from auron_tpu.ops.agg.functions import HostAggSpec

        class _SchemaOp:
            def __init__(self, schema):
                self.schema = schema
                self.metrics = None
        dummy = _SchemaOp(child_schema)
        dummy.children = []
        from auron_tpu.runtime.metrics import MetricNode
        dummy.metrics = MetricNode("src")
        agg = AggExec(dummy, n.exec_mode, n.grouping, n.grouping_names,
                      n.aggs, n.agg_names, False)
        if any(isinstance(s, HostAggSpec) for s in agg.specs):
            raise SpmdUnsupported("host-path agg function in SPMD")
        return agg

    def _admitting_exchange_mode(self, agg) -> Optional[str]:
        part = _feeding_exchange(agg, self.exchanges)
        return part.mode if part is not None else None

    def _do_agg(self, n: P.Agg) -> DeviceTable:
        from auron_tpu.ops.agg.exec import (
            _group_reduce_body, _group_reduce_body_hash,
        )
        if self.hash_grouping:
            # downstream consumers never rely on key order: exchanges
            # hash keys, final aggs re-group, joins sort hashes, and the
            # driver-side shadow sort re-orders the gathered result
            _group_reduce_body = _group_reduce_body_hash
        if n.exec_mode == "single" and self.n_dev > 1 and \
                not _single_agg_ok(n, self.exchanges):
            # a single-mode agg is per-partition; on a sharded SOURCE its
            # device-local groups would diverge from the collapsed serial
            # oracle — but directly after an exchange the device IS the
            # partition, so per-device reduction is exactly the
            # per-partition semantics (empty devices emit zero groups)
            raise SpmdUnsupported(
                "single-mode agg needs an exchange (or partial/final "
                "shape) on a multi-device mesh")
        t = self.eval_node(n.child)
        agg = self._agg_exec_meta(n, t.schema)
        merge = n.exec_mode == "final"
        keys = self._eval_exprs(n.grouping, t)
        nk = len(n.grouping)
        if merge:
            vcols: List[List[Any]] = []
            off = nk
            for spec in agg.specs:
                k = len(spec.state_fields())
                vcols.append(t.cols[off:off + k])
                off += k
        else:
            vcols = []
            for a in n.aggs:
                vcols.append(self._eval_exprs(a.children, t)
                             if a.children else [])
        out_cols, n_groups = _group_reduce_body(
            keys, vcols, t.live, agg.specs, agg._key_orders(), merge)
        if nk == 0 and n.exec_mode in ("final", "single"):
            # a global agg over an empty input still emits the identity
            # row (count=0, sum=null — the serial _empty_global_agg
            # contract).  The clipped row-0 states are exactly the
            # identities: count's eval_final forces validity over the
            # zeroed data, every other agg finalizes to null.  Under a
            # round-robin exchange every device IS a live partition, so
            # each empty device owes its own identity row; otherwise
            # (single exchange / partial-final) only device 0 does.
            empty = n_groups == 0
            if n.exec_mode == "single" and \
                    self._admitting_exchange_mode(n) == "round_robin":
                force = empty
            else:
                force = jnp.logical_and(self._axis_index() == 0, empty)
            n_groups = jnp.where(force, 1, n_groups)
        live = jnp.arange(t.capacity, dtype=jnp.int32) < n_groups
        if n.exec_mode in ("final", "single"):
            final_cols = list(out_cols[:nk])
            off = nk
            for spec in agg.specs:
                k = len(spec.state_fields())
                final_cols.append(spec.eval_final(out_cols[off:off + k]))
                off += k
            return self._shrink_front(
                DeviceTable(agg.schema, final_cols, live), n_groups)
        return self._shrink_front(
            DeviceTable(agg._state_schema(), out_cols, live), n_groups)

    def _shrink_front(self, t: DeviceTable, n_live) -> DeviceTable:
        """Cut a front-compacted table (all live rows at indices
        [0, n_live)) down to the static capacity hint.  Aggs are the
        plan's cardinality reducers, but the mask-liveness model keeps
        their INPUT capacity — so without this every downstream exchange
        / join / sort pays input-scale cost for a handful of groups
        (round-4 root cause of the stage path losing to serial at bench
        scale).  Overflow (more groups than the hint) trips a
        shrink-guard; the driver climbs a capacity ladder (4x per
        retry, then shrink off)."""
        new_cap = bucket_capacity(self.agg_cap_hint) \
            if self.agg_cap_hint > 0 else 0
        if new_cap <= 0 or new_cap >= t.capacity:
            return t
        over = n_live > new_cap
        self.shrink_guards.append(
            lax.psum(over.astype(jnp.int32), self.axis) > 0)
        cols = [jax.tree.map(lambda x: x[:new_cap], c) for c in t.cols]
        return DeviceTable(t.schema, cols, t.live[:new_cap])

    # joins ---------------------------------------------------------------------

    def _do_broadcast_join(self, n: P.BroadcastJoin) -> DeviceTable:
        # build side is REPLICATED on every device: emitting unmatched
        # build rows (full/right) would duplicate them per device, so
        # those types are precheck-rejected for broadcast joins
        return self._join(n.left, n.right, n.on, n.join_type,
                          build_side=n.broadcast_side,
                          existence_name=n.existence_output_name)

    def _do_hash_join(self, n: P.HashJoin) -> DeviceTable:
        # colocation vetted by precheck_plan: a shuffled hash join is
        # only correct per-device when both sides were hash-exchanged on
        # the join keys
        return self._join(n.left, n.right, n.on, n.join_type,
                          build_side=n.build_side,
                          existence_name=n.existence_output_name,
                          colocated=True)

    def _do_broadcast_join_build_hash_map(self, n) -> DeviceTable:
        return self.eval_node(n.child)

    def _do_sort_merge_join(self, n: P.SortMergeJoin) -> DeviceTable:
        # SMJ in SPMD: both sides arrive hash-exchanged on their join
        # keys, so equal keys are COLOCATED and the per-device
        # sorted-hash probe kernel applies (the mid-plan sorts under an
        # SMJ are no-ops here — the kernel sorts hashes itself).
        # Duplicate build keys retry with K-way pair expansion; key runs
        # wider than the factor fall back to the streaming serial SMJ.
        # colocation was vetted by precheck_plan (the one authoritative
        # copy — it runs before any source materialization)
        return self._join(n.left, n.right, n.on, n.join_type,
                          build_side="right",
                          existence_name=n.existence_output_name,
                          colocated=True)

    _JOIN_TYPES = ("inner", "left", "left_semi", "left_anti", "existence")
    _JOIN_TYPES_COLOCATED = _JOIN_TYPES + ("full", "right")

    def _join(self, left_ir, right_ir, on, join_type: str,
              build_side: str, existence_name: str = "exists",
              colocated: bool = False) -> DeviceTable:
        from auron_tpu.ops.joins.exec import join_output_schema
        from auron_tpu.ops.joins.kernel import (
            _NULL_BUILD, _NULL_PROBE, join_key_hash,
        )
        allowed = self._JOIN_TYPES_COLOCATED if colocated \
            else self._JOIN_TYPES
        if join_type not in allowed:
            raise SpmdUnsupported(f"SPMD join type {join_type!r}")
        if build_side != "right":
            raise SpmdUnsupported("SPMD join requires build_side=right")
        probe = self.eval_node(left_ir)
        build = self.eval_node(right_ir)
        pkeys = self._eval_exprs(on.left_keys, probe)
        bkeys = self._eval_exprs(on.right_keys, build)
        bh, bvalid = join_key_hash(bkeys, build.capacity)
        bh = jnp.where(jnp.logical_and(build.live, bvalid), bh, _NULL_BUILD)
        from auron_tpu.ops.strategy import sort_strategy
        if sort_strategy(build.capacity) == "radix":
            from auron_tpu.ops.radix_sort import stable_argsort_u64
            order = stable_argsort_u64(bh)
        else:
            order = jnp.argsort(bh).astype(jnp.int32)
        sorted_bh = jnp.take(bh, order)
        ph, pvalid = join_key_hash(pkeys, probe.capacity)
        ph = jnp.where(jnp.logical_and(probe.live, pvalid), ph, _NULL_PROBE)
        semi_like = join_type in ("left_semi", "left_anti", "existence")
        K = 1 if semi_like else self.match_factor
        if K <= 1:
            return self._join_single(probe, build, pkeys, bkeys, order,
                                     sorted_bh, ph, join_type,
                                     existence_name)
        return self._join_expanded(probe, build, pkeys, bkeys, order,
                                   sorted_bh, ph, join_type,
                                   existence_name, K)

    @staticmethod
    def _cols_eq(a_cols, b_cols, ok):
        """AND of null-safe per-column equality over aligned column
        lists — THE key-equality rule (collision filter); every caller
        must go through here so string/decimal semantics can never
        diverge between the probe check and the build-run check."""
        for a, b in zip(a_cols, b_cols):
            if isinstance(a, DeviceStringColumn):
                from auron_tpu.exprs import strings_device as S
                eq = S.string_eq(a, b)
            else:
                eq = a.data == b.data
            ok = jnp.logical_and(ok, jnp.logical_and(
                eq, jnp.logical_and(a.validity, b.validity)))
        return ok

    def _exact_eq(self, pkeys, bkeys, bidx, hit):
        """Exact key equality for candidate pairs (hash-collision
        filter); pkeys are already pair-aligned."""
        return self._cols_eq(
            pkeys, [bk.gather(bidx, hit) for bk in bkeys], hit)

    def _join_outer_tail(self, schema, probe, build, out_cols, ok, bidx,
                         live1):
        """full/right tail: colocated builds, so unmatched build rows
        emit locally — probe segment + null-padded unmatched-build
        segment concatenated."""
        from auron_tpu.ops.joins.kernel import null_columns_like
        t1 = DeviceTable(schema, out_cols, live1)
        matched = jnp.zeros(build.capacity, bool).at[
            jnp.where(ok, bidx, build.capacity)].set(True, mode="drop")
        live2 = jnp.logical_and(build.live, jnp.logical_not(matched))
        null_probe = null_columns_like(probe.schema.fields,
                                       build.capacity)
        t2 = DeviceTable(schema, null_probe + list(build.cols), live2)
        return self._concat_tables(schema, [t1, t2])

    def _join_single(self, probe, build, pkeys, bkeys, order, sorted_bh,
                     ph, join_type, existence_name):
        """Single-candidate probe (match_factor=1): duplicate build keys
        would need pair expansion, so a runtime guard detects them
        (adjacent equal non-sentinel hashes after the sort).  For
        pair-emitting join types the trip is RETRYABLE (the driver
        re-traces with the expansion factor).  Semi/anti/existence are
        probe-preserving, so TRUE duplicate keys are harmless — the
        leftmost candidate of an equal-hash run carries the same key —
        and only a hash COLLISION (adjacent equal hashes whose exact
        keys differ) trips their (hard) guard.  This is what lets the
        TPC-DS semi/anti families (customer EXISTS over fact tables:
        massively duplicate build keys) ride the mesh at K=1."""
        from auron_tpu.ops.joins.exec import join_output_schema
        from auron_tpu.ops.joins.kernel import _NULL_BUILD
        adj = jnp.logical_and(sorted_bh[1:] == sorted_bh[:-1],
                              sorted_bh[1:] != _NULL_BUILD)
        if join_type in ("left_semi", "left_anti", "existence"):
            keys_eq = self._cols_eq(
                [bk.gather(order[:-1], adj) for bk in bkeys],
                [bk.gather(order[1:], adj) for bk in bkeys],
                jnp.ones(adj.shape, bool))
            collision = jnp.any(jnp.logical_and(
                adj, jnp.logical_not(keys_eq)))
            self.guards.append(
                lax.psum(collision.astype(jnp.int32), self.axis) > 0)
        else:
            dup = jnp.any(adj)
            self.retry_guards.append(
                lax.psum(dup.astype(jnp.int32), self.axis) > 0)
        pos = jnp.clip(jnp.searchsorted(sorted_bh, ph), 0,
                       build.capacity - 1)
        hit = jnp.take(sorted_bh, pos) == ph
        bidx = jnp.take(order, pos)
        ok = self._exact_eq(pkeys, bkeys, bidx, hit)
        schema = join_output_schema(probe.schema, build.schema, join_type,
                                    existence_name)
        if join_type in ("left_semi", "left_anti"):
            keep = ok if join_type == "left_semi" \
                else jnp.logical_not(ok)
            return DeviceTable(schema, list(probe.cols),
                               jnp.logical_and(probe.live, keep))
        if join_type == "existence":
            exists = DeviceColumn(
                DataType.bool_(), jnp.logical_and(ok, probe.live),
                jnp.ones(probe.capacity, bool))
            return DeviceTable(schema, list(probe.cols) + [exists],
                               probe.live)
        bcols = [c.gather(bidx, ok) for c in build.cols]
        out_cols = list(probe.cols) + bcols
        if join_type in ("full", "right"):
            live1 = probe.live if join_type == "full" \
                else jnp.logical_and(probe.live, ok)
            return self._join_outer_tail(schema, probe, build, out_cols,
                                         ok, bidx, live1)
        live = jnp.logical_and(probe.live, ok) if join_type == "inner" \
            else probe.live
        return DeviceTable(schema, out_cols, live)

    def _compact_live(self, t: DeviceTable, new_cap: int) -> DeviceTable:
        """Stable-compact live rows to the front and cut capacity to
        new_cap (a join-guard trips past it -> compaction-off retry).
        Applied after K-expanded joins so a JOIN CHAIN stays near the
        original probe capacity instead of growing K-fold per join
        (q85r's 5-join chain at K=4 otherwise pays 4^5 = 1024x row
        capacity — measured 107s warm for 10 output rows).  The stable
        sort preserves live-row order, so per-device limit prefixes are
        unchanged."""
        if not self.join_compact or new_cap >= t.capacity:
            return t
        n_live = jnp.sum(t.live.astype(jnp.int32))
        self.join_guards.append(
            lax.psum((n_live > new_cap).astype(jnp.int32),
                     self.axis) > 0)
        from auron_tpu.ops.strategy import sort_strategy
        if sort_strategy(t.capacity) == "radix":
            from auron_tpu.ops.radix_sort import stable_argsort_flags
            perm = stable_argsort_flags(
                jnp.logical_not(t.live))[:new_cap]
        else:
            perm = jnp.argsort(jnp.logical_not(t.live),
                               stable=True).astype(jnp.int32)[:new_cap]
        ok = jnp.take(t.live, perm)
        cols = [c.gather(perm, ok) for c in t.cols]
        return DeviceTable(t.schema, cols, ok)

    def _join_expanded(self, probe, build, pkeys, bkeys, order,
                       sorted_bh, ph, join_type, existence_name, K: int):
        """K-way pair expansion: every probe row probes its full hash
        range [lo, hi), emitting up to K pairs (static output capacity
        probe.cap * K).  Ranges wider than K trip a runtime guard and
        the driver falls back to the serial engine — the static-shape
        answer to the reference's dynamic pair batches
        (joins/bhj/full_join.rs)."""
        from auron_tpu.ops.joins.exec import join_output_schema
        cap = probe.capacity
        capk = cap * K
        lo = jnp.searchsorted(sorted_bh, ph, side="left") \
            .astype(jnp.int32)
        hi = jnp.searchsorted(sorted_bh, ph, side="right") \
            .astype(jnp.int32)
        count = hi - lo
        over = jnp.any(jnp.logical_and(probe.live, count > K))
        self.guards.append(
            lax.psum(over.astype(jnp.int32), self.axis) > 0)
        i = (jnp.arange(capk, dtype=jnp.int32) // K)
        j = jnp.arange(capk, dtype=jnp.int32) % K
        allv = jnp.ones(capk, bool)
        pair_has = j < jnp.minimum(jnp.take(count, i), K)
        bpos = jnp.clip(jnp.take(lo, i) + j, 0, build.capacity - 1)
        bidx = jnp.take(order, bpos)
        probe_live_r = jnp.take(probe.live, i)
        pkeys_r = [k.gather(i, allv) for k in pkeys]
        ok = self._exact_eq(pkeys_r, bkeys, bidx,
                            jnp.logical_and(pair_has, probe_live_r))
        matched_any = jnp.any(ok.reshape(cap, K), axis=1)
        schema = join_output_schema(probe.schema, build.schema, join_type,
                                    existence_name)
        probe_cols_r = [c.gather(i, allv) for c in probe.cols]
        bcols = [c.gather(bidx, ok) for c in build.cols]
        out_cols = probe_cols_r + bcols
        # unmatched probe rows emit exactly once (their j==0 slot)
        emit_unmatched = jnp.logical_and(
            jnp.logical_and(j == 0, probe_live_r),
            jnp.logical_not(jnp.take(matched_any, i)))
        # compact back to the pre-expansion capacity (join-guarded; a
        # genuine fan-out past it retries with compaction off)
        if join_type == "inner":
            return self._compact_live(
                DeviceTable(schema, out_cols, ok), cap)
        if join_type == "left":
            return self._compact_live(
                DeviceTable(schema, out_cols,
                            jnp.logical_or(ok, emit_unmatched)),
                cap)
        # full / right: the outer tail appends build.capacity unmatched
        # slots, so the target must cover probe + build rows
        live1 = jnp.logical_or(ok, emit_unmatched) \
            if join_type == "full" else ok
        return self._compact_live(
            self._join_outer_tail(schema, probe, build, out_cols, ok,
                                  bidx, live1),
            bucket_capacity(cap + build.capacity))

    # sort / limit -------------------------------------------------------
    #
    # SPMD operator bodies are order-insensitive (hash agg, hash join,
    # exchanges); ordering only matters at the driver-side emission, which
    # the peeled host tail re-establishes.  A mid-plan Sort with no fetch
    # limit is therefore a no-op here; one WITH a fetch limit is a
    # per-device top-k MASK (rows keep their positions, losers go dead —
    # the sort_exec.rs:86 FetchLimit analogue), skipped entirely when the
    # host tail's global sort shadows it (same key prefix, limit at least
    # as strict).

    def _do_sort(self, n: P.Sort) -> DeviceTable:
        from auron_tpu.ops.sort_keys import (
            encode_sort_keys, encode_sort_keys_bits, lexsort_indices_live,
        )
        if n.fetch_limit is None:
            return self.eval_node(n.child)
        s = self.shadow_sort
        if s is not None and s.fetch_limit is not None and \
                s.fetch_limit <= n.fetch_limit and \
                s.sort_exprs == n.sort_exprs[:len(s.sort_exprs)]:
            return self.eval_node(n.child)
        t = self.eval_node(n.child)
        keys = self._eval_exprs(tuple(x.child for x in n.sort_exprs), t)
        orders = tuple((x.asc, x.nulls_first) for x in n.sort_exprs)
        words = encode_sort_keys(keys, orders)
        perm = lexsort_indices_live(words, t.live,
                                    encode_sort_keys_bits(keys))
        rank = jnp.zeros(t.capacity, jnp.int32).at[perm].set(
            jnp.arange(t.capacity, dtype=jnp.int32))
        live = jnp.logical_and(t.live, rank < n.fetch_limit)
        return DeviceTable(t.schema, t.cols, live)

    def _do_limit(self, n: P.Limit) -> DeviceTable:
        # per-device limit+offset over the device's row order — exactly
        # the serial engine's per-partition stream semantics
        # (limit_exec.rs:42); the global CollectLimit shape puts a single
        # exchange + final limit above this.  A Sort anywhere below makes
        # the prefix ORDER-dependent (serial takes the sorted prefix; the
        # SPMD sort is a no-op/mask that leaves rows in place) — reject
        # so the serial engine computes the correct sorted prefix.
        for node in _walk_native(n.child, self):
            if node.kind == "sort":
                raise SpmdUnsupported(
                    "limit over a sorted input is order-sensitive")
        t = self.eval_node(n.child)
        live_rank = jnp.cumsum(t.live.astype(jnp.int32))  # 1-based
        live = jnp.logical_and(
            t.live, jnp.logical_and(live_rank > n.offset,
                                    live_rank <= n.offset + n.limit))
        return DeviceTable(t.schema, t.cols, live)

    # window -------------------------------------------------------------

    def _do_window(self, n: P.Window) -> DeviceTable:
        from auron_tpu.ops.sort_keys import (
            encode_sort_keys, encode_sort_keys_bits, lexsort_indices_live,
        )
        from auron_tpu.ops.window.exec import (
            _coerce_to, _default_window_type, compute_window_fn,
            group_limit_rank, segment_context,
        )
        if not _window_ok(n, self.exchanges):
            raise SpmdUnsupported(
                "window needs a colocating exchange (hash on a subset of "
                "its partition keys, or single) under it")
        # unsupported window fns surface as NotImplementedError from
        # compute_window_fn below — wrapped into SpmdUnsupported there,
        # so the supported set lives in ONE place (ops/window/exec.py)
        t = self.eval_node(n.child)
        cap = t.capacity
        pcols = self._eval_exprs(n.partition_by, t)
        ocols = self._eval_exprs(tuple(s.child for s in n.order_by), t)
        args_u = [self._eval_exprs(
            tuple(wf.args) + ((wf.agg.children if wf.agg else ())), t)
            for wf in n.window_funcs]
        orders = tuple((s.asc, s.nulls_first) for s in n.order_by)
        pwords = encode_sort_keys(
            pcols, tuple((True, True) for _ in n.partition_by))
        owords = encode_sort_keys(ocols, orders)
        perm = lexsort_indices_live(pwords + owords, t.live,
                                    encode_sort_keys_bits(pcols) +
                                    encode_sort_keys_bits(ocols))
        allv = jnp.ones(cap, bool)
        sorted_cols = [c.gather(perm, allv) for c in t.cols]
        sorted_args = [[a.gather(perm, allv) for a in args]
                       for args in args_u]
        n_live = jnp.sum(t.live.astype(jnp.int32))
        live = jnp.arange(cap, dtype=jnp.int32) < n_live
        sp = [jnp.take(w, perm) for w in pwords]
        so = [jnp.take(w, perm) for w in owords]

        # segment structure + per-fn kernels: the SAME helpers the serial
        # operator runs (single source of truth for boundary semantics)
        c = segment_context(sp, so, live, cap)
        out_cols = []
        for wf, args in zip(n.window_funcs, sorted_args):
            try:
                out_cols.append(_coerce_to(
                    wf, compute_window_fn(wf, args, c, n.order_by)))
            except NotImplementedError as e:
                raise SpmdUnsupported(str(e)) from e
        fields = list(t.schema.fields)
        cols = list(sorted_cols)
        if n.output_window_cols:
            cols += out_cols
            fields += [Field(wf.name or wf.fn,
                             wf.return_type or _default_window_type(wf))
                       for wf in n.window_funcs]
        if n.group_limit is not None:
            live = jnp.logical_and(
                live, group_limit_rank(n.group_limit.rank_fn, c)
                <= n.group_limit.k)
        return DeviceTable(Schema(tuple(fields)), cols, live)


def _feeding_exchange(node, exchanges):
    """The exchange Partitioning feeding `node`, looking through
    row-preserving pass-through ops (coalesce/debug); None otherwise."""
    child = node.child
    while isinstance(child, (P.CoalesceBatches, P.Debug)):
        child = child.child
    if isinstance(child, P.IpcReader) and child.resource_id in exchanges:
        return exchanges[child.resource_id].partitioning
    return None


def _colocating(part, keys) -> bool:
    """True when `part` guarantees rows with equal `keys` land on one
    device: a single-partition exchange, or a hash exchange whose
    expressions are a subset of `keys`."""
    if part is None:
        return False
    if part.mode == "single":
        return True
    if part.mode == "hash":
        ks = set(keys)
        return all(e in ks for e in (part.expressions or ()))
    return False


def _single_agg_ok(agg, exchanges) -> bool:
    """A single-mode agg is per-partition; in SPMD the device is the
    partition.  Admit it only when the exchange feeding it guarantees
    per-device groups are complete (colocating for its grouping keys),
    or — for an UNGROUPED agg — any exchange (per-partition global rows,
    the engine's per-partition contract)."""
    part = _feeding_exchange(agg, exchanges)
    if part is None:
        return False
    if _colocating(part, agg.grouping):
        return True
    if part.mode == "round_robin":
        return not agg.grouping
    return False


def _key_positions(part, keys):
    """The index set of `keys` a partitioning hashes on, or None when it
    gives no colocation guarantee for `keys`.  single -> empty set (all
    rows funnel to one device)."""
    if part is None:
        return None
    if part.mode == "single":
        return frozenset()
    if part.mode != "hash" or not part.expressions:
        return None
    keys = list(keys)
    try:
        return frozenset(keys.index(e) for e in part.expressions)
    except ValueError:
        return None


def _side_positions(node, keys, exchanges):
    """Colocation guarantee of one join side for `keys`, looked through
    distribution-preserving operators: fetch-less sorts, coalesce/debug,
    filters (row drops don't move rows), grouped aggs (a group's row
    stays where its exchange put the inputs; the feeding exchange's
    expressions name the agg's output attributes in the canonical
    partial/exchange/final shape), and joins (output rows keep the probe
    side's placement; pl == pr makes the build's appended rows agree)."""
    while True:
        if isinstance(node, (P.CoalesceBatches, P.Debug, P.Filter)):
            node = node.child
            continue
        if isinstance(node, P.Sort) and node.fetch_limit is None:
            node = node.child
            continue
        break
    if isinstance(node, P.IpcReader) and node.resource_id in exchanges:
        return _key_positions(exchanges[node.resource_id].partitioning,
                              keys)
    if isinstance(node, P.Agg):
        return _key_positions(_feeding_exchange(node, exchanges), keys)
    if isinstance(node, (P.HashJoin, P.SortMergeJoin)):
        return _side_positions(node.left, keys, exchanges)
    if isinstance(node, P.BroadcastJoin):
        probe = node.left if node.broadcast_side == "right" else node.right
        return _side_positions(probe, keys, exchanges)
    return None


def _smj_colocated(n, exchanges) -> bool:
    """Equal join keys must land on one device: both sides carry the
    same positional hash-key guarantee (so the partition hashes agree
    row-for-row), or both funnel through single exchanges."""
    pl = _side_positions(n.left, tuple(n.on.left_keys), exchanges)
    pr = _side_positions(n.right, tuple(n.on.right_keys), exchanges)
    return pl is not None and pl == pr


def _window_ok(win, exchanges) -> bool:
    """Window partitions must be device-complete: the feeding exchange
    must colocate the PARTITION BY keys (no partition keys -> only a
    single exchange qualifies)."""
    return _colocating(_feeding_exchange(win, exchanges),
                       win.partition_by)




def _require_native(node) -> P.PlanNode:
    if not isinstance(node, P.PlanNode):
        raise SpmdUnsupported("foreign subtree inside SPMD stage")
    return node


from auron_tpu.ir.node import tree_has_kind as _tree_has  # noqa: E402


# ---------------------------------------------------------------------------
# host driver: shard inputs, run the program, gather + compact
# ---------------------------------------------------------------------------

def _shard_table(table, mesh: Mesh, axis: str) -> Tuple[Schema, List[Any],
                                                        Array, int]:
    """Split an arrow table row-wise across the mesh: returns flat arrays
    of shape [n_dev*cap] (sharded along the axis) + live mask."""
    import pyarrow as pa
    from auron_tpu.ir.schema import from_arrow_schema
    n_dev = int(np.prod([mesh.shape[a] for a in axis])) \
        if isinstance(axis, tuple) else mesh.shape[axis]
    n = table.num_rows
    per_dev = -(-max(n, 1) // n_dev)
    cap = bucket_capacity(per_dev)
    schema = from_arrow_schema(table.schema)
    dev_batches = []
    for d in range(n_dev):
        chunk = table.slice(d * per_dev, per_dev)
        arrays = [c.combine_chunks() if c.num_chunks else
                  pa.array([], type=c.type) for c in chunk.columns]
        rb = pa.RecordBatch.from_arrays(arrays, schema=table.schema)
        b = Batch.from_arrow(rb, capacity=cap, schema=schema)
        if b.has_host_columns():
            raise SpmdUnsupported("host-resident column in SPMD source")
        dev_batches.append(b)
    # normalize string widths across shards, then stack host-side
    cols: List[Any] = []
    for ci, f in enumerate(schema):
        parts = [db.columns[ci] for db in dev_batches]
        if isinstance(parts[0], DeviceStringColumn):
            w = max(p.width for p in parts)
            data = np.concatenate([
                np.pad(np.asarray(p.data), ((0, 0), (0, w - p.width)))
                for p in parts])
            cols.append(DeviceStringColumn(
                f.dtype, jnp.asarray(data),
                jnp.asarray(np.concatenate(
                    [np.asarray(p.lengths) for p in parts])),
                jnp.asarray(np.concatenate(
                    [np.asarray(p.validity) for p in parts]))))
        else:
            bits = None
            if all(p.bits is not None for p in parts):
                # keep the exact-f64 sidecar across the shard stack (all
                # parts come from Batch.from_arrow, so presence is uniform)
                bits = jnp.asarray(np.concatenate(
                    [np.asarray(p.bits) for p in parts]))
            cols.append(DeviceColumn(
                f.dtype,
                jnp.asarray(np.concatenate(
                    [np.asarray(p.data) for p in parts])),
                jnp.asarray(np.concatenate(
                    [np.asarray(p.validity) for p in parts])), bits))
    live = np.zeros(n_dev * cap, bool)
    for d in range(n_dev):
        got = min(max(n - d * per_dev, 0), per_dev)
        live[d * cap: d * cap + got] = True
    return schema, cols, jnp.asarray(live), cap


# ---------------------------------------------------------------------------
# device-resident source shard cache (round-4: kill the per-execute
# re-materialize / re-pad / re-device_put cost that made the stage path
# lose to serial at bench scale — the reference's hot path does zero
# per-batch host work, rt.rs:141-238)
# ---------------------------------------------------------------------------

import collections  # noqa: E402
import weakref  # noqa: E402


def _mesh_fingerprint(mesh: Mesh) -> Tuple:
    devs = list(np.asarray(mesh.devices).flat)
    return (tuple(mesh.shape.items()),
            tuple((d.platform, d.id) for d in devs))


def _string_cfg_fingerprint() -> Tuple:
    from auron_tpu.config import conf as _conf
    return (int(_conf.get("auron.string.device.max.width")),
            str(_conf.get("auron.string.width.buckets")))


class _ByteBudgetLRU:
    """Byte-bounded LRU map: key -> (value, nbytes).  Eviction keeps at
    least one entry so a single oversized value still caches (it would
    thrash forever otherwise).  Subclasses supply the budget and layer
    their keying semantics on top."""

    def __init__(self):
        self._entries: "collections.OrderedDict[Any, Tuple[Any, int]]" = \
            collections.OrderedDict()
        self._bytes = 0

    def _budget(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    def _lookup(self, key):
        if self._entries and self._budget() <= 0:
            # budget lowered to 0 ("disables"): release everything —
            # serving retained entries would keep their device buffers
            # alive past the user's memory-pressure request
            self.clear()
            return None
        e = self._entries.get(key)
        if e is None:
            return None
        self._entries.move_to_end(key)
        return e[0]

    def _evict_key(self, key) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self._bytes -= e[1]

    def _store(self, key, value, nbytes: int) -> bool:
        budget = self._budget()
        if budget <= 0:
            return False
        self._evict_key(key)
        self._entries[key] = (value, nbytes)
        self._bytes += nbytes
        while self._bytes > budget and len(self._entries) > 1:
            old_key, (_v, b) = self._entries.popitem(last=False)
            self._bytes -= b
            self._dropped(old_key)
        return True

    def _dropped(self, key) -> None:
        """Hook: called for keys evicted by the byte budget."""

    def clear(self) -> None:
        self._entries.clear()
        self._bytes = 0


class _DeviceShardCache(_ByteBudgetLRU):
    """LRU cache of sharded, device-resident source tables.

    pyarrow Tables are immutable, so `id(table)` is a sound content key
    while the table object is alive; a weakref finalizer evicts every
    entry for a table the moment it is garbage collected (no stale-id
    reuse window).  Entries are bounded by device bytes
    (auron.spmd.source.cache.mb); eviction drops the JAX array
    references and XLA frees the buffers once no running program holds
    them."""

    def __init__(self):
        super().__init__()
        self._tid_keys: Dict[int, set] = {}

    def _budget(self) -> int:
        from auron_tpu.config import conf as _conf
        return int(_conf.get("auron.spmd.source.cache.mb")) << 20

    def _dropped(self, key) -> None:
        self._tid_keys.get(key[0], set()).discard(key)

    def _evict_tid(self, tid: int) -> None:
        for key in self._tid_keys.pop(tid, ()):
            self._evict_key(key)

    # the shard key (mesh/axis/string-config) is threaded through
    # explicitly: a process-global "current key" would interleave under
    # two concurrent sessions on different meshes and serve shards placed
    # for the other run's mesh (ADVICE r4)

    def get(self, table, shard_key: Tuple) -> Optional[dict]:
        key = (id(table), *shard_key)
        e = self._lookup(key)
        if e is None or e["ref"]() is not table:
            return None
        return e

    def put(self, table, entry: dict, shard_key: Tuple) -> None:
        tid = id(table)
        key = (tid, *shard_key)
        nbytes = sum(
            int(getattr(x, "nbytes", 0))
            for x in jax.tree.leaves((entry["cols"], entry["live"])))
        entry["ref"] = weakref.ref(
            table, lambda _r, tid=tid: self._evict_tid(tid))
        if self._store(key, entry, nbytes):
            self._tid_keys.setdefault(tid, set()).add(key)

    def clear(self) -> None:
        super().clear()
        self._tid_keys.clear()


_DEVICE_SHARDS = _DeviceShardCache()


def _scan_files_fp(node) -> Optional[Tuple]:
    """(path, mtime_ns, size) for every file under a scan node; None when
    any file is unstattable (such scans never cache)."""
    import os
    fp = []
    for g in getattr(node, "file_groups", ()) or ():
        for p in getattr(g, "paths", ()) or ():
            try:
                st = os.stat(p)
            except OSError:
                return None
            fp.append((p, st.st_mtime_ns, st.st_size))
    return tuple(fp)


class _ScanTableCache(_ByteBudgetLRU):
    """LRU cache of materialized scan leaves keyed by (scan node, file
    stat fingerprint): repeat executes of the same query re-read nothing
    from disk unless a file's (mtime_ns, size) changed.  The fingerprint
    is taken BEFORE the scan reads (no stat-after-read TOCTOU: a file
    rewritten mid-read changes the fingerprint the next get computes, so
    the stale entry never matches).  Bounded by arrow bytes
    (auron.spmd.scan.cache.mb)."""

    def _budget(self) -> int:
        from auron_tpu.config import conf as _conf
        return int(_conf.get("auron.spmd.scan.cache.mb")) << 20

    def get(self, node, fp: Optional[Tuple]):
        if fp is None:
            return None
        return self._lookup((node, fp))

    def put(self, node, fp: Optional[Tuple], table) -> None:
        if fp is None:
            return
        self._store((node, fp), table, int(table.nbytes))


_SCAN_TABLES = _ScanTableCache()


def clear_source_caches() -> None:
    """Drop all cached scan tables and device-resident shards (tests and
    memory-pressure handling)."""
    _DEVICE_SHARDS.clear()
    _SCAN_TABLES.clear()


def execute_plan_spmd(plan: P.PlanNode, conv_ctx, mesh: Mesh,
                      source_tables: Dict[str, Any], axis: str = "parts"):
    """Compile + run `plan` as one shard_map program over `mesh`.

    source_tables: rid -> pyarrow.Table for every FFI source the plan
    references (the C2N boundary inputs).  Returns a pyarrow.Table.
    Raises SpmdUnsupported when the plan shape cannot be expressed.

    A tripped join guard (duplicate build keys past the current match
    factor) retries ONCE with auron.spmd.join.match.factor pair
    expansion before giving up — multi-match joins pay the K-wide
    buffers only when the data actually needs them.  The factor that
    succeeded is remembered per (canonical program, mesh, configured k),
    so repeat executes of a duplicate-key query start at the right width
    instead of paying the trip-then-retry double execution every time;
    the config in the key means re-tuning the factor drops stale hints
    (a hint only ever widens buffers — correctness never depends on it).
    """
    from auron_tpu.config import conf as _conf
    # canonicalize ONCE: the hint lookup, program cache and tracer all
    # run on the rewritten (rid-token) views
    plan, conv_ctx, source_tables = _canonicalize_rids(
        plan, conv_ctx, source_tables)
    k = int(_conf.get("auron.spmd.join.match.factor"))
    hint_key = (
        plan,
        tuple(sorted((rid, job.child, job.partitioning)
                     for rid, job in conv_ctx.exchanges.items())),
        tuple(sorted((rid, job.child)
                     for rid, job in conv_ctx.broadcasts.items())),
        tuple(mesh.shape.items()), k)
    match = _MATCH_FACTOR_HINT.get(hint_key, 1)
    # agg-shrink capacity LADDER: start at the configured hint; each
    # overflow retries 4x wider (x16 max) before giving up the shrink
    # entirely — a high-cardinality agg (q21i at sf10: 1M groups/device)
    # then lands on a 1M-row buffer instead of reverting every
    # downstream op to full input capacity (the 135GB OOM shape).  The
    # key embeds the CONFIGURED cap so re-tuning it restarts the ladder.
    cap_hint = int(_conf.get("auron.spmd.agg.capacity.hint"))
    shrink_key = (hint_key, cap_hint)
    cap_eff = _SHRINK_HINT.get(shrink_key, cap_hint)
    # the hard-fail hint embeds the configs that size the hard guard
    # (quota margin + configured cap): re-tuning either restarts the
    # hard-climb eligibility, same discipline as the shrink ladder
    hard_key = (hint_key, cap_hint,
                float(_conf.get("auron.spmd.exchange.quota.margin")))
    join_compact = bool(_conf.get("auron.spmd.join.compact.enable")) \
        and not _JOIN_COMPACT_OFF_HINT.get(hint_key, False)
    # bounded retries across the independent guard dimensions (match
    # factor, shrink ladder, join compaction); hints remember the
    # working combination per canonical program so repeat executes skip
    # the trip-then-retry runs
    from auron_tpu.faults import InjectedDeviceFault
    from auron_tpu.runtime import retry as _retry
    device_budget = max(0, _retry.RetryPolicy.from_conf().max_attempts - 1)
    for _attempt in range(6):
        try:
            out = _execute_plan_spmd_once(plan, conv_ctx, mesh,
                                          source_tables, axis,
                                          match_factor=match,
                                          agg_cap_hint=cap_eff,
                                          join_compact=join_compact)
            if match > 1:
                _MATCH_FACTOR_HINT[hint_key] = match
            if cap_eff != cap_hint:
                _SHRINK_HINT[shrink_key] = cap_eff
            if bool(_conf.get("auron.spmd.join.compact.enable")) and \
                    not join_compact:
                _JOIN_COMPACT_OFF_HINT[hint_key] = True
            return out
        except InjectedDeviceFault as e:
            # device-fault tier: re-execute the stage program a bounded
            # number of times, then DEGRADE — raise SpmdUnsupported so
            # the session falls back to the serial per-partition path
            # (the session counts the fallback)
            if device_budget > 0:
                device_budget -= 1
                _retry.add_retry()
                continue
            raise SpmdUnsupported(
                f"device fault persisted past the retry budget: {e}"
            ) from e
        except SpmdGuardTripped as e:
            if e.join_compact and join_compact:
                join_compact = False
                _retry.add_retry()
                continue
            # the climb exists because post-agg exchange quotas are sized
            # from the SHRUNK capacity — a plan with no Agg anywhere was
            # never shrunk, so its hard trip is genuine (skew/dup keys)
            # and climbing would only re-execute a failing program 4 more
            # times before the serial fallback
            has_agg = any(isinstance(nn, P.Agg)
                          for nn in _walk_native(plan, conv_ctx))
            hard_climb = (e.hard and cap_eff > 0 and has_agg and
                          not _HARD_FAIL_HINT.get(hard_key, False))
            if (e.shrink or hard_climb) and cap_eff > 0:
                # hard trips climb too: post-agg exchange quotas are
                # sized from the SHRUNK capacity, so a routing skew that
                # fit pre-shrink can overflow the hard guard — the
                # ladder must get to try wider rungs (-> shrink off =
                # pre-shrink sizing) before falling back to serial.  A
                # genuine dup-key failure survives every rung; the hint
                # below makes repeat executes skip the climb entirely.
                cap_eff = cap_eff * 4 \
                    if cap_eff < cap_hint * 16 else 0
                _retry.add_retry()
                continue
            if e.retryable and match == 1 and k > 1:
                match = k
                _retry.add_retry()
                continue
            if e.hard:
                _HARD_FAIL_HINT[hard_key] = True
            raise
    raise SpmdGuardTripped("guard retries exhausted")


def _canonicalize_rids(plan, conv_ctx, source_tables):
    """Rewrite every `resource_id` in the plan/exchange/broadcast trees to
    a deterministic walk-order token ("#0", "#1", ...), returning
    (plan, shim_ctx, source_tables) with all three views rekeyed
    consistently.  Plans from different conversions of the same query then
    compare (and hash) equal, which is what the compiled-program cache
    keys on."""
    import dataclasses
    from types import SimpleNamespace

    exchanges = getattr(conv_ctx, "exchanges", None) or {}
    broadcasts = getattr(conv_ctx, "broadcasts", None) or {}
    mapping: Dict[str, str] = {}

    def tok(rid: str) -> str:
        got = mapping.get(rid)
        if got is None:
            got = mapping[rid] = f"#{len(mapping)}"
        return got

    def canon_val(v):
        if dataclasses.is_dataclass(v) and not isinstance(v, type) and \
                type(v).__module__ == P.__name__:
            return canon(v)
        if isinstance(v, tuple):
            vals = tuple(canon_val(x) for x in v)
            if any(a is not b for a, b in zip(vals, v)):
                return vals
        return v

    # fields that hold ConvertContext-minted ids (per-query uuid inside):
    # resource_id names exchange/broadcast/source blocks; the bhm cache
    # ids key the SERIAL engine's build-table registry, which the SPMD
    # tracer never consults — both are name-independent here
    _RID_FIELDS = ("resource_id", "cache_id", "cached_build_hash_map_id")

    # memoized by identity: shared subtrees MUST stay shared — the union
    # collapse (and any other id()-based dedup) distinguishes "same child
    # referenced per partition" from "distinct children", and a rebuild
    # that forks a shared node would replicate its rows
    memo: Dict[int, Any] = {}

    def canon(node):
        got = memo.get(id(node))
        if got is not None:
            return got
        changes = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            nv = tok(v) if f.name in _RID_FIELDS and v else canon_val(v)
            if nv is not v:
                changes[f.name] = nv
        out = dataclasses.replace(node, **changes) if changes else node
        memo[id(node)] = out
        return out

    new_plan = canon(plan)
    # boundary jobs in token-discovery order; a job's child may reference
    # further exchanges (chained stages), so iterate to a fixed point
    new_ex: Dict[str, Any] = {}
    new_bc: Dict[str, Any] = {}
    done: set = set()
    while True:
        pending = [r for r in mapping if r not in done]
        if not pending:
            break
        for rid in pending:
            done.add(rid)
            if rid in exchanges:
                job = exchanges[rid]
                new_ex[mapping[rid]] = dataclasses.replace(
                    job, rid=mapping[rid],
                    child=canon(job.child)
                    if isinstance(job.child, P.PlanNode) else job.child)
            elif rid in broadcasts:
                job = broadcasts[rid]
                new_bc[mapping[rid]] = dataclasses.replace(
                    job, rid=mapping[rid],
                    child=canon(job.child)
                    if isinstance(job.child, P.PlanNode) else job.child)
    new_sources = {}
    for rid in sorted(source_tables):
        new_sources[mapping[rid] if rid in mapping else tok(rid)] = \
            source_tables[rid]
    shim = SimpleNamespace(exchanges=new_ex, broadcasts=new_bc,
                           sources=getattr(conv_ctx, "sources", {}))
    return new_plan, shim, new_sources


# last execute's device->host gather footprint (the IT runner and bench
# record this per query: VERDICT r4 ask #2 "gather bytes logged")
GATHER_STATS = {"bytes": 0, "rows": 0, "capacity": 0}

_SLICER_CACHE: Dict[Tuple, Any] = {}


def _gather_slicer(mesh: Mesh, axis, K: int, out_cols, out_live):
    """Cached shard_map program slicing every output leaf to its shard's
    first K rows — the device-side half of the two-phase compact gather."""
    key = (_mesh_fingerprint(mesh),
           axis if not isinstance(axis, tuple) else tuple(axis), K,
           tuple((str(x.dtype), x.shape)
                 for x in jax.tree.leaves((out_cols, out_live))))
    got = _SLICER_CACHE.get(key)
    if got is None:
        def body(cols, live):
            return (jax.tree.map(lambda a: a[:K], cols), live[:K])
        got = jitcheck.site("spmd.slicer").jit(jax.shard_map(
            body, mesh=mesh, in_specs=(PS(axis), PS(axis)),
            out_specs=(PS(axis), PS(axis)), check_vma=False))
        _SLICER_CACHE[key] = got
    return got


def _execute_plan_spmd_once(plan: P.PlanNode, conv_ctx, mesh: Mesh,
                            source_tables: Dict[str, Any], axis,
                            match_factor: int,
                            agg_cap_hint: Optional[int] = None,
                            join_compact: bool = True):
    # one `spmd.launch` span per stage attempt, with the host-visible
    # internal phases (`spmd.ingest` scan IO, `spmd.shard` pad+transfer,
    # `spmd.compile`/`spmd.run` program execution, `spmd.gather` result
    # fetch) as child spans — stage time is decomposable in trace
    # summaries instead of one opaque block
    from auron_tpu.runtime import tracing
    with tracing.span("spmd.launch", cat="spmd"):
        # the SPMD stage is a hot path: any implicit device->host fetch
        # (the compact-gather contract routes them all through
        # host_sync) is an undeclared-transfer diagnostic when jitcheck
        # is on
        with jitcheck.transfer_guard("spmd.execute"):
            return _execute_plan_spmd_once_impl(
                plan, conv_ctx, mesh, source_tables, axis, match_factor,
                agg_cap_hint=agg_cap_hint, join_compact=join_compact)


def _execute_plan_spmd_once_impl(plan: P.PlanNode, conv_ctx, mesh: Mesh,
                                 source_tables: Dict[str, Any], axis,
                                 match_factor: int,
                                 agg_cap_hint: Optional[int] = None,
                                 join_compact: bool = True):
    import dataclasses

    import pyarrow as pa
    from auron_tpu.faults import fault_point
    from auron_tpu.ir.schema import to_arrow_schema

    # injected device fault for the whole stage program: the driver
    # (execute_plan_spmd) re-runs a bounded number of times, then
    # degrades to the serial per-partition path
    fault_point("stage.execute")

    # inputs arrive rid-canonicalized from execute_plan_spmd:
    # ConvertContext mints per-query-uuid resource ids, so byte-identical
    # plans from two conversions would never hit _PROGRAM_CACHE — every
    # execute re-traced + re-compiled the shard_map program (~seconds of
    # warm time per query).  Walk-order rid tokens make equal plans
    # cache-equal AND give the jitted program a stable input-pytree
    # structure.

    if isinstance(axis, tuple):
        axis_sizes = tuple(mesh.shape[a] for a in axis)
        n_dev = int(np.prod(axis_sizes))
    else:
        axis_sizes = None
        n_dev = mesh.shape[axis]
    exchanges = getattr(conv_ctx, "exchanges", None) or {}

    # 1. peel the driver-side tail: a root chain of single-partition ops
    # (projection / sort / limit / renames) replayed through the SERIAL
    # engine on the gathered table — the reference's equivalent is the
    # final collect on the driver (TakeOrderedAndProject)
    tail: List[P.PlanNode] = []
    shadow_sort: Optional[P.Sort] = None
    while isinstance(plan, (P.Projection, P.Sort, P.Limit,
                            P.RenameColumns)):
        tail.append(plan)
        if isinstance(plan, P.Sort) and shadow_sort is None:
            shadow_sort = plan
        plan = plan.child

    # 2. a root single-mode exchange feeding the tail is redundant: the
    # host gather itself is the "move everything to one place" step
    while isinstance(plan, P.IpcReader) and plan.resource_id in exchanges:
        job = exchanges[plan.resource_id]
        if job.partitioning.mode != "single":
            break
        plan = _require_native(job.child)

    # fast kind-level rejection BEFORE any source materialization (the
    # session materializes C2N sources only after this passes)
    precheck_plan(plan, conv_ctx)

    # 3. materialize scan leaves (host IO through the serial engine) and
    # FFI sources, then shard row-wise over the mesh
    from auron_tpu.runtime import tracing
    source_tables = dict(source_tables)
    with tracing.span("spmd.ingest", cat="spmd"):
        scan_rids, scan_tables = _materialize_scans(plan, conv_ctx)
    source_tables.update(scan_tables)

    # shard + device_put each source ONCE per (table, mesh, axis, string
    # config): repeat executes of the same query hit device-resident
    # shards and skip all host-side pad/concat/transfer work
    sharded = NamedSharding(mesh, PS(axis))
    shard_key = (_mesh_fingerprint(mesh), axis,
                 _string_cfg_fingerprint())
    host_inputs = {}
    schemas = {}
    with tracing.span("spmd.shard", cat="spmd",
                      sources=len(source_tables)):
        for rid, table in source_tables.items():
            e = _DEVICE_SHARDS.get(table, shard_key)
            if e is None:
                schema, cols, live, _cap = _shard_table(table, mesh, axis)
                e = {"schema": schema,
                     "cols": jax.tree.map(
                         lambda x: jax.device_put(x, sharded), cols),
                     "live": jax.device_put(live, sharded)}
                _DEVICE_SHARDS.put(table, e, shard_key)
            host_inputs[rid] = (e["cols"], e["live"])
            schemas[rid] = e["schema"]
    # program cache: repeat executions of the SAME converted plan over the
    # same input shapes reuse the compiled shard_map program (a fresh
    # jax.jit closure per call would re-trace+re-compile every time)
    from auron_tpu.config import conf as _conf
    from auron_tpu.ops.strategy import \
        strategy_fingerprint as _strategy_fingerprint
    if agg_cap_hint is None:
        agg_cap_hint = int(_conf.get("auron.spmd.agg.capacity.hint"))
    hash_grouping = (
        np.asarray(mesh.devices).flat[0].platform == "cpu" and
        str(_conf.get("auron.agg.grouping.strategy")) in ("auto", "hash"))
    _gmode = str(_conf.get("auron.spmd.gather.compact"))
    compact_gather = _gmode == "on" or (
        _gmode == "auto" and
        np.asarray(mesh.devices).flat[0].platform != "cpu")
    cache_key = (
        plan, axis, n_dev, match_factor, agg_cap_hint, join_compact,
        compact_gather,
        _mesh_fingerprint(mesh),
        # EVERY config the tracer (or kernels it calls) reads at trace
        # time must appear here: rid canonicalization makes equal plans
        # cache-equal across conversions, so a flag flip between runs
        # would otherwise reuse a program compiled under the old value
        float(_conf.get("auron.spmd.exchange.quota.margin")),
        bool(_conf.get("auron.string.ascii.case.enable")),
        bool(_conf.get("auron.case.sensitive")),
        bool(_conf.get("auron.segments.sorted.enable")),
        str(_conf.get("auron.sort.multipass.enable")),
        str(_conf.get("auron.sort.f64.exactbits")),
        bool(_conf.get("auron.pallas.enable")),
        str(_conf.get("auron.agg.grouping.strategy")),
        int(_conf.get("auron.string.device.max.width")),
        str(_conf.get("auron.string.width.buckets")),
        _strategy_fingerprint(),
        tuple(sorted((rid, job.child, job.partitioning)
                     for rid, job in (getattr(conv_ctx, "exchanges", None)
                                      or {}).items())),
        tuple(sorted((rid, job.child)
                     for rid, job in (getattr(conv_ctx, "broadcasts", None)
                                      or {}).items())),
        tuple(sorted((rid, schemas[rid],
                      tuple((str(x.dtype), x.shape)
                            for x in jax.tree.leaves(ci)))
                     for rid, ci in host_inputs.items())),
        shadow_sort)
    cached = _PROGRAM_CACHE.get(cache_key)

    if cached is None:
        schema_box: List[Schema] = []

        def program(bindings_flat):
            bindings = {
                rid: DeviceTable(schemas[rid], cols, live)
                for rid, (cols, live) in bindings_flat.items()}
            tracer = _StageTracer(conv_ctx, bindings, axis, n_dev,
                                  shadow_sort=shadow_sort,
                                  scan_rids=scan_rids,
                                  axis_sizes=axis_sizes,
                                  match_factor=match_factor,
                                  agg_cap_hint=agg_cap_hint,
                                  hash_grouping=hash_grouping,
                                  join_compact=join_compact)
            out = tracer.eval_node(plan)
            if not schema_box:
                schema_box.append(out.schema)
            guards = jnp.stack(tracer.guards) if tracer.guards else \
                jnp.zeros(0, bool)
            retry_guards = jnp.stack(tracer.retry_guards) \
                if tracer.retry_guards else jnp.zeros(0, bool)
            shrink_guards = jnp.stack(tracer.shrink_guards) \
                if tracer.shrink_guards else jnp.zeros(0, bool)
            join_guards = jnp.stack(tracer.join_guards) \
                if tracer.join_guards else jnp.zeros(0, bool)
            cols, live = out.cols, out.live
            count = jnp.sum(live.astype(jnp.int32))[None]
            if compact_gather:
                # compact live rows to the shard front so the host can
                # fetch ONLY a bucket_capacity(count) slice instead of the
                # full padded capacity — on a tunnel-attached TPU the
                # capacity-sized fetch dominated warm query time (VERDICT
                # r4 #2: "gather only final aggregated rows")
                from auron_tpu.ops.strategy import sort_strategy as _ss
                if _ss(int(live.shape[0])) == "radix":
                    from auron_tpu.ops.radix_sort import \
                        stable_argsort_flags
                    perm = stable_argsort_flags(jnp.logical_not(live))
                else:
                    perm = jnp.argsort(jnp.logical_not(live),
                                       stable=True).astype(jnp.int32)
                ok = jnp.take(live, perm)
                cols = [c.gather(perm, ok) for c in cols]
                live = ok
            return (cols, live, count, guards, retry_guards,
                    shrink_guards, join_guards)

        shard = jitcheck.site("spmd.stage").jit(jax.shard_map(
            program, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: PS(axis), host_inputs),),
            out_specs=(PS(axis), PS(axis), PS(axis), PS(), PS(), PS(),
                       PS()),
            check_vma=False))
    else:
        shard, schema_box = cached

    # jax.jit is lazy: on a cache miss the first call below traces +
    # compiles the whole stage program, so the span is the compile span
    # (first launch included); cache hits record a pure run span (both
    # are children of the enclosing spmd.launch)
    with tracing.span(
            "spmd.compile" if cached is None else "spmd.run",
            cat="spmd", devices=n_dev,
            first_launch_included=cached is None):
        (out_cols, out_live, counts, guards, retry_guards, shrink_guards,
         join_guards) = shard(host_inputs)
    if cached is None:
        _PROGRAM_CACHE[cache_key] = (shard, schema_box)
    out_schema = schema_box[0]

    from auron_tpu.ops.kernel_cache import host_sync
    with tracing.span("spmd.gather", cat="spmd",
                      compact=bool(compact_gather)), \
            jitcheck.declared_transfer("spmd.gather"):  # jitcheck: waive (THE per-stage result fetch: counts+guards first, compacted slice second)
        if compact_gather:
            # phase 1: a few BYTES decide everything — per-shard live
            # counts + guard bits.  A tripped guard never pays the
            # output fetch at all, and a clean run fetches only the
            # compacted slice below.
            (counts_np, guards_np, retry_np, shrink_np,
             join_np) = host_sync(
                (counts, guards, retry_guards, shrink_guards,
                 join_guards))
        else:
            # single batched fetch (CPU: transfers are memcpy-cheap, two
            # round trips would only add dispatch latency)
            (out_live_np, out_cols_np, counts_np, guards_np, retry_np,
             shrink_np, join_np) = host_sync(
                (out_live, out_cols, counts, guards, retry_guards,
                 shrink_guards, join_guards))
        if np.any(np.asarray(guards_np)):
            raise SpmdGuardTripped(
                "runtime guard tripped (exchange quota overflow, or "
                f"duplicate build keys past match factor {match_factor}): "
                "result discarded", retryable=False, hard=True)
        if np.any(np.asarray(join_np)):
            raise SpmdGuardTripped(
                "join output overflowed the compaction target (genuine "
                "fan-out): result discarded", join_compact=True)
        if np.any(np.asarray(shrink_np)):
            raise SpmdGuardTripped(
                f"agg group count overflowed the capacity hint "
                f"{agg_cap_hint}: result discarded", shrink=True)
        if np.any(np.asarray(retry_np)):
            raise SpmdGuardTripped(
                "duplicate-key build side at match factor 1: result "
                "discarded", retryable=True)
        if compact_gather:
            # phase 2: slice each shard to the smallest capacity bucket
            # that holds its rows (one tiny cached program), then fetch
            per_cap = out_live.shape[0] // n_dev
            kmax = max(int(np.max(np.asarray(counts_np))), 1)
            K = min(bucket_capacity(kmax), per_cap)
            if K < per_cap:
                slicer = _gather_slicer(mesh, axis, K, out_cols,
                                        out_live)
                out_cols, out_live = slicer(out_cols, out_live)
            out_live_np, out_cols_np = host_sync((out_live, out_cols))
        live_np = np.asarray(out_live_np)
        GATHER_STATS["rows"] = int(np.asarray(counts_np).sum())
        GATHER_STATS["capacity"] = int(live_np.shape[0])
        GATHER_STATS["bytes"] = int(sum(
            np.asarray(x).nbytes
            for x in jax.tree.leaves(out_cols_np))) + live_np.nbytes
        arrays = []
        for f, c in zip(out_schema, out_cols_np):
            from auron_tpu.columnar.arrow_interop import column_to_arrow
            total = live_np.shape[0]
            arr = column_to_arrow(f.dtype, c, total)
            arrays.append(arr.filter(pa.array(live_np)))
        table = pa.Table.from_arrays(
            arrays, schema=to_arrow_schema(out_schema))

    # 4. replay the peeled tail through the serial engine
    if tail:
        from auron_tpu.runtime.executor import execute_plan
        from auron_tpu.runtime.resources import ResourceRegistry
        from auron_tpu.ir.schema import from_arrow_schema
        replay: P.PlanNode = P.FFIReader(
            schema=from_arrow_schema(table.schema),
            resource_id="__spmd_gathered")
        for node in reversed(tail):
            replay = dataclasses.replace(node, child=replay)
        res = ResourceRegistry()
        res.put("__spmd_gathered", table.to_batches())
        table = execute_plan(replay, resources=res).to_table()
    return table


def _walk_native(node, conv_ctx):
    """Yield every native plan node reachable from `node`, following
    exchange/broadcast boundaries into their (native) children."""
    exchanges = getattr(conv_ctx, "exchanges", None) or {}
    broadcasts = getattr(conv_ctx, "broadcasts", None) or {}
    stack = [node]
    while stack:
        n = stack.pop()
        if not isinstance(n, P.PlanNode):
            continue
        yield n
        if isinstance(n, P.IpcReader):
            job = exchanges.get(n.resource_id) or \
                broadcasts.get(n.resource_id)
            if job is not None:
                stack.append(job.child)
            continue
        if isinstance(n, P.Union):
            pushed = set()           # one walk per child, not per partition
            for i in n.inputs:       # UnionInput wrappers are not plans
                if id(i.child) not in pushed:
                    pushed.add(id(i.child))
                    stack.append(i.child)
            continue
        for c in n.children_nodes():
            stack.append(c)


_PROGRAM_CACHE: Dict[Any, Any] = {}
# canonical plan -> join match factor that last succeeded (see
# execute_plan_spmd's retry)
_MATCH_FACTOR_HINT: Dict[Any, int] = {}
# canonical plan -> effective agg capacity hint that last succeeded on
# the shrink ladder (0 = shrink off); keyed with the configured hint
_SHRINK_HINT: Dict[Any, int] = {}
# canonical plan -> True when the join compaction overflowed and the
# compaction-off retry succeeded
_JOIN_COMPACT_OFF_HINT: Dict[Any, bool] = {}
# canonical plan -> True when a HARD trip survived the whole shrink
# ladder (genuine dup-key/quota failure, not shrink-induced): repeat
# executes then skip the expensive climb and fall straight to serial
_HARD_FAIL_HINT: Dict[Any, bool] = {}

# node kinds the tracer can (conditionally) express; anything else is
# rejected by precheck_plan before source materialization
_PRECHECK_OK = frozenset({
    "ffi_reader", "ipc_reader", "parquet_scan", "orc_scan", "filter",
    "projection", "rename_columns", "coalesce_batches", "debug", "agg",
    "broadcast_join", "hash_join", "broadcast_join_build_hash_map",
    "sort_merge_join", "sort", "limit", "union", "expand", "window",
})


def iter_spmd_rejections(plan, conv_ctx):
    """Yield (node, reason) for EVERY kind-level SPMD compilability
    problem in the tree — the enumerating form behind precheck_plan,
    and the source the analysis-side lint (analysis/spmd.py) turns into
    structured diagnostics instead of log lines."""
    exchanges = getattr(conv_ctx, "exchanges", None) or {}
    for node in _walk_native(plan, conv_ctx):
        if node.kind not in _PRECHECK_OK:
            yield node, f"operator not SPMD-compilable: {node.kind}"
            continue
        if node.kind == "broadcast_join" and \
                node.join_type not in _StageTracer._JOIN_TYPES:
            yield node, f"SPMD broadcast-join type {node.join_type!r}"
        if node.kind in ("hash_join", "sort_merge_join"):
            if node.join_type not in _StageTracer._JOIN_TYPES_COLOCATED:
                yield node, f"SPMD join type {node.join_type!r}"
            # shuffled joins are per-device correct only when both sides
            # were hash-exchanged on the join keys
            elif not _smj_colocated(node, exchanges):
                yield (node,
                       "join sides are not hash-colocated on the join "
                       "keys")
        if node.kind == "agg" and node.exec_mode == "single" and \
                not _single_agg_ok(node, exchanges):
            yield (node, "single-mode agg needs an exchange (or "
                         "partial/final shape)")
        if node.kind == "window" and not _window_ok(node, exchanges):
            yield node, "window needs a colocating exchange under it"
        # (limit-over-sort rejection lives in _do_limit — trace-time only,
        # one authoritative copy)


def precheck_plan(plan, conv_ctx) -> None:
    """Cheap kind-level SPMD compilability check (no tracing, no source
    materialization) — rejects the remaining fallbacks (smj, generate,
    sinks) up front; union/expand compile since round 2,
    window/limit/top-k-sort/range since round 3."""
    for _node, reason in iter_spmd_rejections(plan, conv_ctx):
        raise SpmdUnsupported(reason)


def _materialize_scans(plan, conv_ctx):
    """Run every Parquet/Orc scan leaf through the serial engine (host IO
    + pruning); rids are deterministic walk-order indexes so the compiled
    program's binding structure is stable across conversions.

    Scan PARTITIONS read in parallel on a thread pool (round-3 fix: one
    host thread serially materializing every split was the wall at
    sf100+; the reference streams scans per-task, parquet_exec.rs:70) —
    results reassemble in partition order so sharding stays
    deterministic."""
    import pyarrow as pa

    from auron_tpu.ir.schema import to_arrow_schema
    from auron_tpu.runtime.executor import execute_plan
    from auron_tpu.runtime.task_pool import run_tasks

    rids: Dict[int, str] = {}
    nodes: Dict[str, Any] = {}
    fps: Dict[str, Optional[Tuple]] = {}
    cached: Dict[str, Any] = {}
    jobs: List[Tuple[str, Any, int, int]] = []
    for node in _walk_native(plan, conv_ctx):
        if node.kind not in ("parquet_scan", "orc_scan"):
            continue
        if id(node) in rids:
            continue
        rid = f"scan:{len(rids)}"
        rids[id(node)] = rid
        nodes[rid] = node
        # fingerprint BEFORE reading (a rewrite during the read changes
        # the fp the next lookup computes -> stale entry never matches)
        fps[rid] = _scan_files_fp(node)
        hit = _SCAN_TABLES.get(node, fps[rid])
        if hit is not None:
            # same table OBJECT across executes -> the device shard
            # cache's id() key hits too, so a repeat execute reads no
            # files AND transfers nothing
            cached[rid] = hit
            continue
        n_parts = max(1, len(getattr(node, "file_groups", ()) or ()))
        for pid in range(n_parts):
            jobs.append((rid, node, pid, n_parts))

    def read(job):
        rid, node, pid, n_parts = job
        return rid, pid, execute_plan(node, partition_id=pid,
                                      num_partitions=n_parts).batches

    results = run_tasks(read, jobs, "auron-scan")

    per_rid: Dict[str, List[Tuple[int, List[Any]]]] = {}
    for rid, pid, batches in results:
        per_rid.setdefault(rid, []).append((pid, batches))
    tables: Dict[str, Any] = dict(cached)
    for rid, node in nodes.items():
        if rid in cached:
            continue
        batches = [b for _pid, bs in sorted(per_rid.get(rid, []))
                   for b in bs]
        schema = to_arrow_schema(node.schema)
        t = pa.Table.from_batches(batches, schema=schema) \
            if batches else pa.Table.from_batches([], schema=schema)
        tables[rid] = t
        _SCAN_TABLES.put(node, fps[rid], t)
    return rids, tables
