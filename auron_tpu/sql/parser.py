"""Recursive-descent SQL parser for the TPC-DS query class.

Grammar (v1): WITH CTEs, SELECT [DISTINCT], FROM with comma-joins and
explicit [INNER|LEFT|RIGHT|FULL] JOIN ... ON, WHERE, GROUP BY, HAVING,
ORDER BY [ASC|DESC] [NULLS FIRST|LAST], LIMIT, UNION ALL; expressions
with OR/AND/NOT, comparisons, BETWEEN, [NOT] IN (list|subquery),
[NOT] EXISTS, [NOT] LIKE, IS [NOT] NULL, arithmetic, CASE WHEN, CAST,
function calls, window functions (fn() OVER (PARTITION BY .. ORDER BY
..)), scalar subqueries, qualified column refs and `*`.

Pure syntax here — resolution/typing/planning live in sql/lower.py.
The reference delegates this layer to Spark's own parser; standalone,
the engine needs its own front door.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Expr:
    pass


@dataclass(frozen=True)
class Col(Expr):
    name: str
    table: Optional[str] = None


@dataclass(frozen=True)
class Star(Expr):
    table: Optional[str] = None


@dataclass(frozen=True)
class Lit(Expr):
    value: Any
    kind: str  # int | float | str | date | null | bool


@dataclass(frozen=True)
class Bin(Expr):
    op: str
    left: Expr
    right: Expr


@dataclass(frozen=True)
class Un(Expr):
    op: str            # not | neg
    child: Expr


@dataclass(frozen=True)
class IsNull(Expr):
    child: Expr
    negated: bool = False


@dataclass(frozen=True)
class Between(Expr):
    child: Expr
    lo: Expr
    hi: Expr
    negated: bool = False


@dataclass(frozen=True)
class InList(Expr):
    child: Expr
    values: Tuple[Expr, ...]
    negated: bool = False


@dataclass(frozen=True)
class InSubquery(Expr):
    child: Expr
    query: "Select"
    negated: bool = False


@dataclass(frozen=True)
class Exists(Expr):
    query: "Select"
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery(Expr):
    query: "Select"


@dataclass(frozen=True)
class Like(Expr):
    child: Expr
    pattern: Expr
    negated: bool = False


@dataclass(frozen=True)
class Case(Expr):
    branches: Tuple[Tuple[Expr, Expr], ...]
    else_expr: Optional[Expr] = None


@dataclass(frozen=True)
class Cast(Expr):
    child: Expr
    type_name: str


@dataclass(frozen=True)
class Call(Expr):
    name: str
    args: Tuple[Expr, ...]
    distinct: bool = False


@dataclass(frozen=True)
class WindowCall(Expr):
    call: Call
    partition_by: Tuple[Expr, ...]
    order_by: Tuple["SortItem", ...]


@dataclass(frozen=True)
class SortItem:
    expr: Expr
    asc: bool = True
    nulls_first: Optional[bool] = None


@dataclass(frozen=True)
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass(frozen=True)
class TableRef:
    pass


@dataclass(frozen=True)
class BaseTable(TableRef):
    name: str
    alias: Optional[str] = None


@dataclass(frozen=True)
class SubqueryTable(TableRef):
    query: "Select"
    alias: str


@dataclass(frozen=True)
class Join(TableRef):
    left: TableRef
    right: TableRef
    kind: str                 # inner | left | right | full | cross
    on: Optional[Expr] = None


@dataclass(frozen=True)
class Select:
    items: Tuple[SelectItem, ...]
    from_: Optional[TableRef]
    where: Optional[Expr] = None
    group_by: Tuple[Expr, ...] = ()
    having: Optional[Expr] = None
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    distinct: bool = False
    ctes: Tuple[Tuple[str, "Select"], ...] = ()
    union_all: Tuple["Select", ...] = ()   # additional UNION ALL branches
    # general set-op chain (left-assoc): ("union"|"union_all"|
    # "intersect"|"except", branch) — used when the chain is not pure
    # UNION ALL
    set_ops: Tuple[Tuple[str, "Select"], ...] = ()
    rollup: bool = False                   # GROUP BY ROLLUP(...)


# ---------------------------------------------------------------------------
# lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<num>\d+\.\d*|\.\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<qname>`[^`]*`)
  | (?P<name>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><>|!=|>=|<=|\|\||[(),.*+\-/%<>=;])
""", re.VERBOSE)


class SqlError(ValueError):
    pass


def _lex(sql: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise SqlError(f"lex error at {sql[pos:pos + 30]!r}")
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        if m.lastgroup == "qname":
            # backtick-quoted identifier (aliases with spaces): a plain
            # name token carrying the unquoted text
            out.append(("name", m.group()[1:-1]))
            continue
        out.append((m.lastgroup, m.group()))
    out.append(("eof", ""))
    return out


_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having",
    "order", "limit", "as", "and", "or", "not", "in", "is", "null",
    "like", "between", "case", "when", "then", "else", "end", "cast",
    "join", "inner", "left", "right", "full", "outer", "cross", "on",
    "union", "all", "exists", "with", "asc", "desc", "nulls", "first",
    "last", "over", "partition", "date", "interval", "true", "false",
    "intersect", "except", "rows", "range", "unbounded", "preceding",
    "following", "current",
}


class _P:
    def __init__(self, toks: List[Tuple[str, str]]):
        self.toks = toks
        self.i = 0

    # -- token helpers -----------------------------------------------------

    def peek(self) -> Tuple[str, str]:
        return self.toks[self.i]

    def kw(self, *words: str) -> bool:
        """Next token is one of these keywords (case-insensitive)?"""
        k, v = self.peek()
        return k == "name" and v.lower() in words

    def eat_kw(self, *words: str) -> bool:
        if self.kw(*words):
            self.i += 1
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.eat_kw(word):
            raise SqlError(f"expected {word.upper()} at {self._ctx()}")

    def op(self, *ops: str) -> bool:
        k, v = self.peek()
        return k == "op" and v in ops

    def eat_op(self, *ops: str) -> Optional[str]:
        if self.op(*ops):
            v = self.peek()[1]
            self.i += 1
            return v
        return None

    def expect_op(self, o: str) -> None:
        if not self.eat_op(o):
            raise SqlError(f"expected {o!r} at {self._ctx()}")

    def _ctx(self) -> str:
        return " ".join(v for _, v in self.toks[self.i:self.i + 6])

    def name(self) -> str:
        k, v = self.peek()
        if k != "name" or v.lower() in _KEYWORDS - {
                "date", "first", "last", "left", "right"}:
            raise SqlError(f"expected identifier at {self._ctx()}")
        self.i += 1
        return v

    # -- entry -------------------------------------------------------------

    def parse(self) -> Select:
        s = self.select_stmt()
        while self.eat_op(";"):
            pass
        if self.peek()[0] != "eof":
            raise SqlError(f"trailing input: {self._ctx()}")
        return s

    def select_stmt(self) -> Select:
        ctes: List[Tuple[str, Select]] = []
        if self.eat_kw("with"):
            while True:
                nm = self.name()
                self.expect_kw("as")
                self.expect_op("(")
                q = self.select_stmt()
                self.expect_op(")")
                ctes.append((nm.lower(), q))
                if not self.eat_op(","):
                    break
        # INTERSECT binds tighter than UNION/EXCEPT (standard SQL):
        # parse intersect-chains as terms of the outer chain
        first, first_paren = self.intersect_term()
        branches: List[Tuple[str, Select]] = []
        last_paren = first_paren
        while self.kw("union", "except"):
            kind = self.peek()[1].lower()
            self.i += 1
            if kind == "union":
                kind = "union_all" if self.eat_kw("all") else "union"
            arm, last_paren = self.intersect_term()
            branches.append((kind, arm))
        # ORDER BY / LIMIT after a union apply to the WHOLE union, but
        # select_core greedily parses them into the last branch — lift
        # (unless the last arm was parenthesized: its ORDER/LIMIT is
        # explicitly scoped to that arm)
        order, limit = self.order_limit()
        import dataclasses as _dc
        if branches and not last_paren and \
                (branches[-1][1].order_by or
                 branches[-1][1].limit is not None):
            kind, last = branches[-1]
            if order or limit is not None:
                raise SqlError("duplicate ORDER BY/LIMIT")
            order, limit = last.order_by, last.limit
            branches[-1] = (kind,
                            _dc.replace(last, order_by=(), limit=None))
        if branches:
            if all(k == "union_all" for k, _ in branches) and \
                    not first.set_ops:
                first = _dc.replace(
                    first, union_all=tuple(b for _, b in branches))
            else:
                # append to any INTERSECT entries intersect_term already
                # stored: the left-assoc set_ops fold then evaluates
                # (A INTERSECT B) UNION C in the correct order
                first = _dc.replace(
                    first, set_ops=first.set_ops + tuple(branches))
        if order or limit is not None:
            if first.order_by or first.limit is not None:
                # first's clause is arm-scoped (a parenthesized arm
                # carrying its own ORDER/LIMIT): wrap it as a subquery
                # so the chain-level clause applies to the whole chain
                first = _subquery_wrap(first)
            first = _dc.replace(first, order_by=order, limit=limit)
        if ctes:
            first = _dc.replace(first, ctes=tuple(ctes))
        return first

    def intersect_term(self) -> Tuple[Select, bool]:
        """One arm of a UNION/EXCEPT chain: a select core (or
        parenthesized statement) possibly INTERSECTed with more —
        INTERSECT binds tighter.  Returns (select, was_parenthesized)."""
        import dataclasses as _dc
        first, paren = self.select_core_or_paren()
        parts: List[Tuple[str, Select]] = []
        while self.kw("intersect"):
            self.i += 1
            arm, paren = self.select_core_or_paren()
            parts.append(("intersect", arm))
        if parts:
            # the last unparenthesized arm greedily parsed any trailing
            # ORDER BY/LIMIT; those scope to the whole chain — lift
            # them onto the chain's Select (select_stmt lifts further
            # if a UNION/EXCEPT follows)
            order: Tuple[SortItem, ...] = ()
            limit = None
            last = parts[-1][1]
            if not paren and (last.order_by or last.limit is not None):
                order, limit = last.order_by, last.limit
                parts[-1] = ("intersect",
                             _dc.replace(last, order_by=(), limit=None))
            first = _dc.replace(first, set_ops=first.set_ops +
                                tuple(parts))
            if order or limit is not None:
                if first.order_by or first.limit is not None:
                    first = _subquery_wrap(first)
                first = _dc.replace(first, order_by=order, limit=limit)
        return first, paren

    def select_core_or_paren(self) -> Tuple[Select, bool]:
        """A set-op arm: SELECT core, or a parenthesized select
        statement ((SELECT ...) UNION ALL (SELECT ...))."""
        if self.op("("):
            save = self.i
            self.i += 1
            if self.kw("select", "with") or self.op("("):
                s = self.select_stmt()
                self.expect_op(")")
                return s, True
            self.i = save
        return self.select_core(), False

    def order_limit(self):
        order: Tuple[SortItem, ...] = ()
        limit: Optional[int] = None
        if self.kw("order"):
            self.i += 1
            self.expect_kw("by")
            order = tuple(self.sort_items())
        if self.eat_kw("limit"):
            k, v = self.peek()
            if k != "num":
                raise SqlError(f"expected LIMIT count at {self._ctx()}")
            limit = int(v)
            self.i += 1
        return order, limit

    def sort_items(self) -> List[SortItem]:
        out = []
        while True:
            e = self.expr()
            asc = True
            if self.eat_kw("asc"):
                pass
            elif self.eat_kw("desc"):
                asc = False
            nf: Optional[bool] = None
            if self.eat_kw("nulls"):
                if self.eat_kw("first"):
                    nf = True
                elif self.eat_kw("last"):
                    nf = False
                else:
                    raise SqlError("expected FIRST|LAST after NULLS")
            out.append(SortItem(expr=e, asc=asc, nulls_first=nf))
            if not self.eat_op(","):
                return out

    def select_core(self) -> Select:
        self.expect_kw("select")
        distinct = self.eat_kw("distinct")
        items = [self.select_item()]
        while self.eat_op(","):
            items.append(self.select_item())
        from_: Optional[TableRef] = None
        if self.eat_kw("from"):
            from_ = self.table_expr()
        where = self.expr() if self.eat_kw("where") else None
        group: Tuple[Expr, ...] = ()
        rollup = False
        if self.kw("group"):
            self.i += 1
            self.expect_kw("by")
            if self.eat_kw("rollup"):
                rollup = True
                self.expect_op("(")
            g = [self.expr()]
            while self.eat_op(","):
                g.append(self.expr())
            if rollup:
                self.expect_op(")")
            group = tuple(g)
        having = self.expr() if self.eat_kw("having") else None
        order, limit = self.order_limit()
        return Select(items=tuple(items), from_=from_, where=where,
                      group_by=group, having=having, order_by=order,
                      limit=limit, distinct=distinct, rollup=rollup)

    def select_item(self) -> SelectItem:
        if self.op("*"):
            self.i += 1
            return SelectItem(expr=Star())
        e = self.expr()
        alias = None
        if self.eat_kw("as"):
            alias = self.name()
        elif self.peek()[0] == "name" and \
                self.peek()[1].lower() not in _KEYWORDS:
            alias = self.name()
        return SelectItem(expr=e, alias=alias)

    # -- FROM --------------------------------------------------------------

    def table_expr(self) -> TableRef:
        left = self.table_join()
        while self.eat_op(","):
            right = self.table_join()
            left = Join(left=left, right=right, kind="cross", on=None)
        return left

    def table_join(self) -> TableRef:
        left = self.table_primary()
        while True:
            kind = None
            if self.eat_kw("join") or self.eat_kw("inner"):
                if self.kw("join"):
                    self.i += 1
                kind = "inner"
            elif self.kw("left", "right", "full"):
                kind = self.peek()[1].lower()
                self.i += 1
                self.eat_kw("outer")
                self.expect_kw("join")
            elif self.eat_kw("cross"):
                self.expect_kw("join")
                kind = "cross"
            if kind is None:
                return left
            right = self.table_primary()
            on = None
            if kind != "cross":
                self.expect_kw("on")
                on = self.expr()
            left = Join(left=left, right=right, kind=kind, on=on)

    def table_primary(self) -> TableRef:
        if self.eat_op("("):
            q = self.select_stmt()
            self.expect_op(")")
            self.eat_kw("as")
            alias = self.name()
            return SubqueryTable(query=q, alias=alias.lower())
        nm = self.name()
        alias = None
        if self.eat_kw("as"):
            alias = self.name()
        elif self.peek()[0] == "name" and \
                self.peek()[1].lower() not in _KEYWORDS:
            alias = self.name()
        return BaseTable(name=nm.lower(),
                         alias=alias.lower() if alias else None)

    # -- expressions ---------------------------------------------------------

    def expr(self) -> Expr:
        return self.or_expr()

    def or_expr(self) -> Expr:
        e = self.and_expr()
        while self.eat_kw("or"):
            e = Bin(op="or", left=e, right=self.and_expr())
        return e

    def and_expr(self) -> Expr:
        e = self.not_expr()
        while self.eat_kw("and"):
            e = Bin(op="and", left=e, right=self.not_expr())
        return e

    def not_expr(self) -> Expr:
        if self.eat_kw("not"):
            return Un(op="not", child=self.not_expr())
        return self.predicate()

    def predicate(self) -> Expr:
        if self.kw("exists"):
            self.i += 1
            self.expect_op("(")
            q = self.select_stmt()
            self.expect_op(")")
            return Exists(query=q)
        e = self.add_expr()
        while True:
            if self.eat_kw("is"):
                neg = self.eat_kw("not")
                self.expect_kw("null")
                e = IsNull(child=e, negated=neg)
                continue
            negated = False
            save = self.i
            if self.eat_kw("not"):
                negated = True
            if self.eat_kw("between"):
                lo = self.add_expr()
                self.expect_kw("and")
                hi = self.add_expr()
                e = Between(child=e, lo=lo, hi=hi, negated=negated)
                continue
            if self.eat_kw("in"):
                self.expect_op("(")
                if self.kw("select", "with"):
                    q = self.select_stmt()
                    self.expect_op(")")
                    e = InSubquery(child=e, query=q, negated=negated)
                else:
                    vals = [self.expr()]
                    while self.eat_op(","):
                        vals.append(self.expr())
                    self.expect_op(")")
                    e = InList(child=e, values=tuple(vals),
                               negated=negated)
                continue
            if self.eat_kw("like"):
                e = Like(child=e, pattern=self.add_expr(),
                         negated=negated)
                continue
            if negated:
                self.i = save
            op = self.eat_op("=", "<>", "!=", "<", "<=", ">", ">=")
            if op:
                rhs = self.add_expr()
                e = Bin(op={"=": "==", "<>": "!=", "!=": "!="}.get(op, op),
                        left=e, right=rhs)
                continue
            return e

    def add_expr(self) -> Expr:
        e = self.mul_expr()
        while True:
            op = self.eat_op("+", "-", "||")
            if not op:
                return e
            e = Bin(op=op, left=e, right=self.mul_expr())

    def mul_expr(self) -> Expr:
        e = self.unary_expr()
        while True:
            op = self.eat_op("*", "/", "%")
            if not op:
                return e
            e = Bin(op=op, left=e, right=self.unary_expr())

    def unary_expr(self) -> Expr:
        if self.eat_op("-"):
            return Un(op="neg", child=self.unary_expr())
        if self.eat_op("+"):
            return self.unary_expr()
        return self.primary()

    def primary(self) -> Expr:
        k, v = self.peek()
        if self.eat_op("("):
            if self.kw("select", "with"):
                q = self.select_stmt()
                self.expect_op(")")
                return ScalarSubquery(query=q)
            e = self.expr()
            self.expect_op(")")
            return e
        if k == "num":
            self.i += 1
            if "." in v:
                return Lit(value=float(v), kind="float")
            return Lit(value=int(v), kind="int")
        if k == "str":
            self.i += 1
            return Lit(value=v[1:-1].replace("''", "'"), kind="str")
        if self.kw("null"):
            self.i += 1
            return Lit(value=None, kind="null")
        if self.kw("true", "false"):
            self.i += 1
            return Lit(value=v.lower() == "true", kind="bool")
        if self.kw("date"):
            # DATE 'yyyy-mm-dd'
            save = self.i
            self.i += 1
            nk, nv = self.peek()
            if nk == "str":
                self.i += 1
                return Lit(value=nv[1:-1], kind="date")
            self.i = save
        if self.kw("interval"):
            # INTERVAL n DAY[S]: a day-count literal the date +/- fold
            # in lowering consumes
            self.i += 1
            nk, nv = self.peek()
            if nk == "str":
                nv = nv[1:-1]          # INTERVAL '90' DAY
            elif nk != "num":
                raise SqlError(f"expected INTERVAL count at "
                               f"{self._ctx()}")
            self.i += 1
            unit = self.name().lower()
            if unit not in ("day", "days"):
                raise SqlError(f"unsupported INTERVAL unit {unit}")
            return Lit(value=int(nv), kind="interval_days")
        if self.kw("case"):
            return self.case_expr()
        if self.kw("cast"):
            self.i += 1
            self.expect_op("(")
            e = self.expr()
            self.expect_kw("as")
            tn = self.name().lower()
            # decimal(p,s) / varchar(n) style suffix
            if self.eat_op("("):
                args = [self.peek()[1]]
                self.i += 1
                while self.eat_op(","):
                    args.append(self.peek()[1])
                    self.i += 1
                self.expect_op(")")
                tn = f"{tn}({','.join(args)})"
            self.expect_op(")")
            return Cast(child=e, type_name=tn)
        if k == "name":
            nm = self.name()
            if self.eat_op("("):
                return self.call_tail(nm)
            if self.eat_op("."):
                if self.op("*"):
                    self.i += 1
                    return Star(table=nm.lower())
                col = self.name()
                return Col(name=col.lower(), table=nm.lower())
            return Col(name=nm.lower())
        raise SqlError(f"unexpected token at {self._ctx()}")

    def case_expr(self) -> Expr:
        self.expect_kw("case")
        operand: Optional[Expr] = None
        if not self.kw("when"):
            operand = self.expr()
        branches: List[Tuple[Expr, Expr]] = []
        while self.eat_kw("when"):
            cond = self.expr()
            if operand is not None:
                cond = Bin(op="==", left=operand, right=cond)
            self.expect_kw("then")
            branches.append((cond, self.expr()))
        else_e = self.expr() if self.eat_kw("else") else None
        self.expect_kw("end")
        return Case(branches=tuple(branches), else_expr=else_e)

    def call_tail(self, nm: str) -> Expr:
        name = nm.lower()
        distinct = self.eat_kw("distinct")
        args: Tuple[Expr, ...] = ()
        if self.op("*"):
            self.i += 1
            args = (Star(),)
        elif not self.op(")"):
            lst = [self.expr()]
            while self.eat_op(","):
                lst.append(self.expr())
            args = tuple(lst)
        self.expect_op(")")
        call = Call(name=name, args=args, distinct=distinct)
        if self.eat_kw("over"):
            self.expect_op("(")
            part: Tuple[Expr, ...] = ()
            order: Tuple[SortItem, ...] = ()
            if self.eat_kw("partition"):
                self.expect_kw("by")
                p = [self.expr()]
                while self.eat_op(","):
                    p.append(self.expr())
                part = tuple(p)
            if self.kw("order"):
                self.i += 1
                self.expect_kw("by")
                order = tuple(self.sort_items())
            if self.kw("rows", "range"):
                # only the running frame (UNBOUNDED PRECEDING ..
                # CURRENT ROW) is accepted — it is what the engine's
                # ordered agg-over-window computes
                self.i += 1
                self.expect_kw("between")
                if not (self.eat_kw("unbounded") and
                        self.eat_kw("preceding")):
                    raise SqlError("unsupported window frame start")
                self.expect_kw("and")
                if not (self.eat_kw("current") and self.eat_kw("row")):
                    raise SqlError("unsupported window frame end")
            self.expect_op(")")
            return WindowCall(call=call, partition_by=part,
                              order_by=order)
        return call


def _subquery_wrap(sel: Select) -> Select:
    """SELECT * FROM (sel) — scopes an arm's own ORDER BY/LIMIT inside
    a set-op chain so the chain-level clause can attach outside."""
    return Select(items=(SelectItem(expr=Star()),),
                  from_=SubqueryTable(query=sel, alias="__setop_arm"))


def parse_sql(sql: str) -> Select:
    return _P(_lex(sql)).parse()
