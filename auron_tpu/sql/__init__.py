"""SQL front-end: query text -> foreign (Spark-shaped) physical plans.

The engine's own front door.  The reference's L7 is a SparkSession
extension fed by Spark's SQL compiler (AuronSparkSessionExtension.scala:
41-99); this package plays both roles for standalone use: `parse` turns
a TPC-DS-class SQL string into an AST, `plan` resolves it against a
Catalog and emits the same ForeignNode physical shapes a Spark bridge
would hand `AuronConverters` (scans with pushdown, broadcast/sort-merge
joins, partial->exchange->final aggregates, TakeOrderedAndProject) — so
everything downstream of L7 is exercised by INDEPENDENT query text
rather than hand-built plan shapes (VERDICT r4 missing #5: the corpus
referee problem).
"""

from auron_tpu.sql.lower import plan_sql
from auron_tpu.sql.parser import parse_sql

__all__ = ["parse_sql", "plan_sql"]
