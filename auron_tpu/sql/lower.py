"""Resolve + plan: SQL AST -> foreign (Spark-shaped) physical plans.

Plays the role Spark's analyzer/optimizer/planner play in front of the
reference (the plans AuronConverters receives, AuronConverters.scala:
186-209): name resolution against the Catalog, filter pushdown to scan
sides, join strategy (broadcast for base dim tables, sort-merge
otherwise), the canonical partial->hash-exchange->final aggregate pair,
window repartitioning, and TakeOrderedAndProject at the root.  The
emitted trees use exactly the ForeignNode vocabulary the conversion
layer accepts, so a SQL string exercises the same full path as a plan a
real Spark bridge would ship.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field as dfield
from typing import Dict, List, Optional, Tuple

from auron_tpu.frontend.foreign import (ForeignExpr, ForeignNode, falias,
                                        fcall, fcol, flit)
from auron_tpu.ir.schema import DataType, Field, Schema

from auron_tpu.sql import parser as A
from auron_tpu.sql.parser import SqlError

I32 = DataType.int32()
I64 = DataType.int64()
F64 = DataType.float64()
STR = DataType.string()
BOOL = DataType.bool_()

# TPC-DS fact tables: never broadcast (everything else in the schema is
# a dimension — the heuristic Spark's size threshold lands on at the
# scales the corpus runs)
_FACTS = {"store_sales", "catalog_sales", "web_sales", "inventory",
          "store_returns", "catalog_returns", "web_returns"}

_AGG_FNS = {
    "sum": "Sum", "count": "Count", "avg": "Average", "min": "Min",
    "max": "Max", "stddev_samp": "StddevSamp", "stddev": "StddevSamp",
    "var_samp": "VarianceSamp", "variance": "VarianceSamp",
}

_WINDOW_FNS = {"rank", "dense_rank", "row_number"}

_SCALAR_FNS = {
    "substr": "Substring", "substring": "Substring",
    "coalesce": "Coalesce", "upper": "Upper", "lower": "Lower",
    "abs": "Abs", "round": "Round", "length": "Length",
    "concat": "Concat", "year": "Year", "month": "Month",
}

_CMP = {"==": "EqualTo", "!=": "NotEqual", "<": "LessThan",
        "<=": "LessThanOrEqual", ">": "GreaterThan",
        ">=": "GreaterThanOrEqual"}
_ARITH = {"+": "Add", "-": "Subtract", "*": "Multiply", "/": "Divide",
          "%": "Remainder"}


def _dt_of(fe: ForeignExpr) -> DataType:
    return fe.dtype if fe.dtype is not None else DataType.null()


def _num_promote(a: DataType, b: DataType) -> DataType:
    order = {"INT8": 0, "INT16": 1, "INT32": 2, "INT64": 3,
             "FLOAT32": 4, "FLOAT64": 5}
    ra = order.get(a.id.name, 5)
    rb = order.get(b.id.name, 5)
    return a if ra >= rb else b


@dataclass
class Scope:
    """Visible columns of one relation: (qualifier, Field) per column."""
    cols: List[Tuple[Optional[str], Field]]

    def schema(self) -> Schema:
        return Schema(tuple(f for _, f in self.cols))

    def resolve(self, name: str, table: Optional[str]) -> Field:
        hits = [f for q, f in self.cols
                if f.name.lower() == name.lower()
                and (table is None or q == table)]
        if not hits:
            raise SqlError(f"unknown column {table + '.' if table else ''}"
                           f"{name}")
        if len(hits) > 1 and not all(h is hits[0] for h in hits):
            raise SqlError(f"ambiguous column {name}")
        return hits[0]

    def has(self, name: str, table: Optional[str]) -> bool:
        try:
            self.resolve(name, table)
            return True
        except SqlError:
            return False


@dataclass
class _Ctx:
    catalog: object
    ctes: Dict[str, A.Select] = dfield(default_factory=dict)
    n_parts: int = 4
    counter: "itertools.count" = dfield(default_factory=itertools.count)
    # scalar subqueries evaluate eagerly at plan time (Spark computes
    # them as separate jobs before the main query the same way); the
    # executor is pluggable and results are memoized per subquery text
    subquery_exec: Optional[object] = None
    subquery_cache: Dict = dfield(default_factory=dict)

    def fresh(self, prefix: str) -> str:
        return f"__{prefix}{next(self.counter)}"

    def execute_subplan(self, node: ForeignNode):
        if self.subquery_exec is not None:
            return self.subquery_exec(node)
        from auron_tpu.frontend.session import AuronSession
        return AuronSession().execute(node).table


# ---------------------------------------------------------------------------
# expression lowering
# ---------------------------------------------------------------------------

def _lower_expr(e: A.Expr, scope: Scope, ctx: _Ctx) -> ForeignExpr:
    if isinstance(e, A.Col):
        f = scope.resolve(e.name, e.table)
        return fcol(f.name, f.dtype, f.nullable)
    if isinstance(e, A.Lit):
        return _lower_lit(e)
    if isinstance(e, A.Bin):
        return _lower_bin(e, scope, ctx)
    if isinstance(e, A.Un):
        if e.op == "not":
            return fcall("Not", _lower_expr(e.child, scope, ctx),
                         dtype=BOOL)
        c = _lower_expr(e.child, scope, ctx)
        return fcall("UnaryMinus", c, dtype=_dt_of(c))
    if isinstance(e, A.IsNull):
        name = "IsNotNull" if e.negated else "IsNull"
        return fcall(name, _lower_expr(e.child, scope, ctx), dtype=BOOL)
    if isinstance(e, A.Between):
        c = _lower_expr(e.child, scope, ctx)
        lo = _coerce(_lower_expr(e.lo, scope, ctx), _dt_of(c))
        hi = _coerce(_lower_expr(e.hi, scope, ctx), _dt_of(c))
        rng = fcall("And",
                    fcall("GreaterThanOrEqual", c, lo, dtype=BOOL),
                    fcall("LessThanOrEqual", c, hi, dtype=BOOL),
                    dtype=BOOL)
        return fcall("Not", rng, dtype=BOOL) if e.negated else rng
    if isinstance(e, A.InList):
        c = _lower_expr(e.child, scope, ctx)
        vals = [_coerce(_lower_expr(v, scope, ctx), _dt_of(c))
                for v in e.values]
        fe = fcall("In", c, *vals, dtype=BOOL)
        fe.attrs["negated"] = e.negated
        return fe
    if isinstance(e, A.Like):
        c = _lower_expr(e.child, scope, ctx)
        fe = fcall("Like", c, _lower_expr(e.pattern, scope, ctx),
                   dtype=BOOL)
        return fcall("Not", fe, dtype=BOOL) if e.negated else fe
    if isinstance(e, A.Case):
        kids: List[ForeignExpr] = []
        out_dt: DataType = DataType.null()
        for when, then in e.branches:
            kids.append(_lower_expr(when, scope, ctx))
            t = _lower_expr(then, scope, ctx)
            if out_dt.id.name == "NULL" and _dt_of(t).id.name != "NULL":
                out_dt = _dt_of(t)
            kids.append(t)
        if e.else_expr is not None:
            els = _lower_expr(e.else_expr, scope, ctx)
            if out_dt.id.name == "NULL" and \
                    _dt_of(els).id.name != "NULL":
                out_dt = _dt_of(els)
            kids.append(els)
        return fcall("CaseWhen", *kids, dtype=out_dt)
    if isinstance(e, A.Cast):
        return fcall("Cast", _lower_expr(e.child, scope, ctx),
                     dtype=_parse_type(e.type_name))
    if isinstance(e, A.Call):
        return _lower_call(e, scope, ctx)
    if isinstance(e, A.ScalarSubquery):
        value, dtype = _eval_scalar_subquery(e.query, ctx)
        return flit(value, dtype)
    raise SqlError(f"unsupported expression {type(e).__name__} here")


def _lower_lit(e: A.Lit) -> ForeignExpr:
    if e.kind == "int":
        return flit(e.value, I64 if abs(e.value) > 2 ** 31 else I32)
    if e.kind == "float":
        return flit(float(e.value), F64)
    if e.kind == "str":
        return flit(e.value, STR)
    if e.kind == "date":
        import datetime
        d = datetime.date.fromisoformat(e.value)
        return flit((d - datetime.date(1970, 1, 1)).days,
                    DataType.date32())
    if e.kind == "bool":
        return flit(bool(e.value), BOOL)
    return flit(None, DataType.null())


def _coerce(fe: ForeignExpr, target: Optional[DataType]) -> ForeignExpr:
    """Literal-side type alignment (IN lists, comparisons vs i64 cols)."""
    if fe.name == "Literal" and fe.dtype is not None and \
            target is not None and not target.is_stringlike and \
            fe.dtype.id != target.id and fe.value is not None and \
            fe.dtype.id.name in ("INT32", "INT64", "FLOAT64") and \
            target.id.name in ("INT8", "INT16", "INT32", "INT64",
                               "FLOAT32", "FLOAT64"):
        return flit(fe.value, target)
    return fe


def _lower_bin(e: A.Bin, scope: Scope, ctx: _Ctx) -> ForeignExpr:
    if e.op == "and":
        return fcall("And", _lower_expr(e.left, scope, ctx),
                     _lower_expr(e.right, scope, ctx), dtype=BOOL)
    if e.op == "or":
        return fcall("Or", _lower_expr(e.left, scope, ctx),
                     _lower_expr(e.right, scope, ctx), dtype=BOOL)
    if e.op == "||":
        return fcall("Concat", _lower_expr(e.left, scope, ctx),
                     _lower_expr(e.right, scope, ctx), dtype=STR)
    left = _lower_expr(e.left, scope, ctx)
    right = _lower_expr(e.right, scope, ctx)
    if e.op in _CMP or e.op == "!=":
        if left.name == "Literal":
            left = _coerce(left, _dt_of(right))
        if right.name == "Literal":
            right = _coerce(right, _dt_of(left))
        if e.op == "!=":
            return fcall("Not",
                         fcall("EqualTo", left, right, dtype=BOOL),
                         dtype=BOOL)
        return fcall(_CMP[e.op], left, right, dtype=BOOL)
    if e.op in _ARITH:
        if right.name == "Literal":
            right = _coerce(right, _dt_of(left))
        if left.name == "Literal":
            left = _coerce(left, _dt_of(right))
        if e.op == "/":
            out = F64      # Spark SQL: non-decimal division is double
        else:
            out = _num_promote(_dt_of(left), _dt_of(right))
        return fcall(_ARITH[e.op], left, right, dtype=out)
    raise SqlError(f"unsupported operator {e.op}")


def _lower_call(e: A.Call, scope: Scope, ctx: _Ctx) -> ForeignExpr:
    if e.name in _AGG_FNS:
        raise SqlError(f"aggregate {e.name}() outside aggregation "
                       f"context")
    if e.name in _WINDOW_FNS:
        raise SqlError(f"window function {e.name}() requires OVER")
    spark = _SCALAR_FNS.get(e.name)
    if spark is None:
        raise SqlError(f"unsupported function {e.name}()")
    args = [_lower_expr(a, scope, ctx) for a in e.args]
    dt = {"Substring": STR, "Upper": STR, "Lower": STR, "Concat": STR,
          "Length": I32, "Year": I32, "Month": I32}.get(
              spark, _dt_of(args[0]) if args else F64)
    if spark == "Coalesce":
        dt = _dt_of(args[0])
    return fcall(spark, *args, dtype=dt)


def _parse_type(name: str) -> DataType:
    base = name.split("(")[0]
    if base in ("int", "integer"):
        return I32
    if base == "bigint":
        return I64
    if base in ("double", "float8"):
        return F64
    if base in ("varchar", "char", "string", "text"):
        return STR
    if base == "date":
        return DataType.date32()
    if base == "decimal":
        inner = name[name.index("(") + 1:-1].split(",") \
            if "(" in name else ["10", "0"]
        return DataType.decimal(int(inner[0]),
                                int(inner[1]) if len(inner) > 1 else 0)
    raise SqlError(f"unsupported cast type {name!r}")


# ---------------------------------------------------------------------------
# relations
# ---------------------------------------------------------------------------

@dataclass
class Rel:
    node: ForeignNode
    scope: Scope
    broadcastable: bool = False


def _conjuncts(e: Optional[A.Expr]) -> List[A.Expr]:
    if e is None:
        return []
    if isinstance(e, A.Bin) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _walk(e: A.Expr):
    """Yield every sub-expression (pre-order), pruning subquery bodies
    (they resolve in their own scope).  The ONE reflection walker both
    column collection and aggregate discovery share."""
    yield e
    if isinstance(e, (A.Exists, A.ScalarSubquery)):
        return
    if isinstance(e, A.InSubquery):
        yield from _walk(e.child)
        return

    def rec_v(v):
        if isinstance(v, A.Expr):
            yield from _walk(v)
        elif isinstance(v, tuple):
            for y in v:
                yield from rec_v(y)

    for f in getattr(e, "__dataclass_fields__", {}):
        yield from rec_v(getattr(e, f))


def _expr_cols(e: A.Expr) -> List[A.Col]:
    return [x for x in _walk(e) if isinstance(x, A.Col)]


def _refs_only(e: A.Expr, scope: Scope) -> bool:
    if isinstance(e, (A.InSubquery, A.Exists, A.ScalarSubquery)):
        return False
    cols = _expr_cols(e)
    return all(scope.has(c.name, c.table) for c in cols)


def _lower_base(t: A.BaseTable, ctx: _Ctx,
                filters: List[A.Expr]) -> Rel:
    """Base table scan with every single-table conjunct pushed down."""
    if t.name in ctx.ctes:
        rel = _lower_select(ctx.ctes[t.name], ctx)
        qual = t.alias or t.name
        scope = Scope([(qual, f) for _, f in rel.scope.cols])
        return Rel(rel.node, scope, rel.broadcastable)
    cat = ctx.catalog
    if t.name not in cat.tables:
        raise SqlError(f"unknown table {t.name}")
    qual = t.alias or t.name
    tdef = cat.tables[t.name]
    scope = Scope([(qual, f) for f in tdef.schema.fields])
    mine = [f for f in filters if _refs_only(f, scope)]
    pushed = [_lower_expr(f, scope, ctx) for f in mine]
    for f in mine:
        filters.remove(f)
    node = cat.scan(t.name, pushed_filters=pushed)
    for p in pushed:
        node = ForeignNode("FilterExec", children=(node,),
                           output=node.output, attrs={"condition": p})
    return Rel(node, scope, broadcastable=t.name not in _FACTS)


def _equi_keys(cond: List[A.Expr], left: Scope, right: Scope,
               ctx: _Ctx):
    """Split conjuncts into (left_keys, right_keys, residual)."""
    lks: List[ForeignExpr] = []
    rks: List[ForeignExpr] = []
    rest: List[A.Expr] = []
    for c in cond:
        if isinstance(c, A.Bin) and c.op == "==":
            a, b = c.left, c.right
            if _refs_only(a, left) and _refs_only(b, right):
                lks.append(_lower_expr(a, left, ctx))
                rks.append(_lower_expr(b, right, ctx))
                continue
            if _refs_only(b, left) and _refs_only(a, right):
                lks.append(_lower_expr(b, left, ctx))
                rks.append(_lower_expr(a, right, ctx))
                continue
        rest.append(c)
    return lks, rks, rest


_JOIN_TYPES = {"inner": "Inner", "left": "LeftOuter",
               "right": "RightOuter", "full": "FullOuter"}


def _hash_exchange(child: ForeignNode, keys, ctx: _Ctx) -> ForeignNode:
    return ForeignNode(
        "ShuffleExchangeExec", children=(child,), output=child.output,
        attrs={"partitioning": {"mode": "hash",
                                "num_partitions": ctx.n_parts,
                                "expressions": list(keys)}})


def _join(left: Rel, right: Rel, kind: str, lks, rks, ctx: _Ctx) -> Rel:
    for _, fa in left.scope.cols:
        for _, fb in right.scope.cols:
            if fa.name.lower() == fb.name.lower():
                raise SqlError(
                    f"column {fa.name} appears on both join sides — "
                    f"alias one side through a subquery (self-join "
                    f"outputs need distinct names)")
    jt = _JOIN_TYPES[kind]
    out_scope = Scope(left.scope.cols + right.scope.cols)
    out = Schema(tuple(f for _, f in out_scope.cols))
    if right.broadcastable and kind in ("inner", "left"):
        bx = ForeignNode("BroadcastExchangeExec", children=(right.node,),
                         output=right.node.output)
        node = ForeignNode(
            "BroadcastHashJoinExec", children=(left.node, bx),
            output=out,
            attrs={"left_keys": lks, "right_keys": rks,
                   "join_type": jt, "build_side": "right"})
        return Rel(node, out_scope, left.broadcastable)
    if left.broadcastable and kind in ("inner", "right"):
        # broadcast the LEFT side by flipping the join orientation,
        # then restore the column order with a projection
        flip = {"inner": "inner", "right": "left"}[kind]
        swapped = _join(right, left, flip, rks, lks, ctx)
        ordered = [swapped.scope.cols[len(right.scope.cols) + i]
                   for i in range(len(left.scope.cols))] + \
                  [swapped.scope.cols[i]
                   for i in range(len(right.scope.cols))]
        proj = [fcol(f.name, f.dtype) for _, f in ordered]
        node = ForeignNode("ProjectExec", children=(swapped.node,),
                           output=out, attrs={"project_list": proj})
        return Rel(node, out_scope, False)
    node = ForeignNode(
        "SortMergeJoinExec",
        children=(_hash_exchange(left.node, lks, ctx),
                  _hash_exchange(right.node, rks, ctx)),
        output=out,
        attrs={"left_keys": lks, "right_keys": rks, "join_type": jt})
    return Rel(node, out_scope, False)


def _semi_anti_join(left: Rel, right: Rel, lks, rks, anti: bool,
                    ctx: _Ctx) -> Rel:
    jt = "LeftAnti" if anti else "LeftSemi"
    if right.broadcastable:
        bx = ForeignNode("BroadcastExchangeExec", children=(right.node,),
                         output=right.node.output)
        node = ForeignNode(
            "BroadcastHashJoinExec", children=(left.node, bx),
            output=left.scope.schema(),
            attrs={"left_keys": lks, "right_keys": rks,
                   "join_type": jt, "build_side": "right"})
        return Rel(node, left.scope, left.broadcastable)
    node = ForeignNode(
        "SortMergeJoinExec",
        children=(_hash_exchange(left.node, lks, ctx),
                  _hash_exchange(right.node, rks, ctx)),
        output=left.scope.schema(),
        attrs={"left_keys": lks, "right_keys": rks, "join_type": jt})
    return Rel(node, left.scope, False)


def _lower_from(t: Optional[A.TableRef], ctx: _Ctx,
                filters: List[A.Expr]) -> Rel:
    if t is None:
        raise SqlError("SELECT without FROM is not supported")
    if isinstance(t, A.BaseTable):
        return _lower_base(t, ctx, filters)
    if isinstance(t, A.SubqueryTable):
        rel = _lower_select(t.query, ctx)
        scope = Scope([(t.alias, f) for _, f in rel.scope.cols])
        return Rel(rel.node, scope, rel.broadcastable)
    if isinstance(t, A.Join):
        left = _lower_from(t.left, ctx, filters)
        right = _lower_from(t.right, ctx, filters)
        if t.kind == "cross":
            # comma-join: equi conditions live in WHERE
            both = Scope(left.scope.cols + right.scope.cols)
            pool = [f for f in filters if _refs_only(f, both)]
            lks, rks, rest = _equi_keys(pool, left.scope, right.scope,
                                        ctx)
            if not lks:
                raise SqlError("cross join without an equi condition "
                               "in WHERE is not supported")
            for f in pool:
                if f not in rest:
                    filters.remove(f)
            return _join(left, right, "inner", lks, rks, ctx)
        cond = _conjuncts(t.on)
        lks, rks, rest = _equi_keys(cond, left.scope, right.scope, ctx)
        if not lks:
            raise SqlError("JOIN without an equi key is not supported")
        rel = _join(left, right, t.kind, lks, rks, ctx)
        for f in rest:
            fe = _lower_expr(f, rel.scope, ctx)
            rel = Rel(ForeignNode("FilterExec", children=(rel.node,),
                                  output=rel.node.output,
                                  attrs={"condition": fe}),
                      rel.scope, rel.broadcastable)
        return rel
    raise SqlError(f"unsupported FROM element {type(t).__name__}")


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

def _find_aggs(e: A.Expr, out: List[A.Call]):
    for x in _walk(e):
        if isinstance(x, A.Call) and x.name in _AGG_FNS:
            out.append(x)


def _agg_out_dtype(fn: str, arg: Optional[ForeignExpr]) -> DataType:
    if fn == "Count":
        return I64
    if fn in ("Average", "StddevSamp", "VarianceSamp"):
        return F64
    dt = _dt_of(arg) if arg is not None else I64
    if fn == "Sum":
        if dt.id.name in ("INT8", "INT16", "INT32", "INT64"):
            return I64
        if dt.is_decimal:
            return dt
        return F64
    return dt


def _spark_agg(fn: str, arg: Optional[ForeignExpr], dt: DataType,
               distinct: bool) -> ForeignExpr:
    children = (arg,) if arg is not None else ()
    return ForeignExpr("AggregateExpression",
                       children=(fcall(fn, *children, dtype=dt),),
                       attrs={"distinct": distinct})


@dataclass
class _AggPlan:
    """Aggregate rewrite state: AST agg calls -> output column names."""
    names: List[Tuple[A.Call, str]] = dfield(default_factory=list)
    entries: List[Tuple[str, ForeignExpr, Field]] = \
        dfield(default_factory=list)

    def slot(self, call: A.Call, scope: Scope, ctx: _Ctx,
             preferred: Optional[str] = None) -> Tuple[str, DataType]:
        for seen, nm in self.names:
            if seen == call:
                dt = next(f.dtype for n, _, f in self.entries
                          if n == nm)
                return nm, dt
        fn = _AGG_FNS[call.name]
        arg = None
        if call.args and not isinstance(call.args[0], A.Star):
            arg = _lower_expr(call.args[0], scope, ctx)
        dt = _agg_out_dtype(fn, arg)
        nm = preferred or ctx.fresh("agg")
        self.names.append((call, nm))
        self.entries.append(
            (nm, _spark_agg(fn, arg, dt, call.distinct), Field(nm, dt)))
        return nm, dt


def _rewrite_post_agg(e: A.Expr, plan: "_AggPlan", scope: Scope,
                      group_names: List[Tuple[A.Expr, str]], ctx: _Ctx,
                      post_scope: Scope,
                      preferred: Optional[str] = None) -> ForeignExpr:
    """Lower an expression over the AGG OUTPUT: agg calls become their
    output columns, grouping expressions resolve to their output names,
    everything else must reference grouping columns."""
    for g, nm in group_names:
        if e == g:
            f = post_scope.resolve(nm, None)
            return fcol(f.name, f.dtype, f.nullable)
    if isinstance(e, A.Call) and e.name in _AGG_FNS:
        nm, dt = plan.slot(e, scope, ctx, preferred)
        return fcol(nm, dt)
    if isinstance(e, A.Col):
        f = post_scope.resolve(e.name, None)
        return fcol(f.name, f.dtype, f.nullable)
    if isinstance(e, A.Lit):
        return _lower_lit(e)
    if isinstance(e, A.Bin):
        le = _rewrite_post_agg(e.left, plan, scope, group_names, ctx,
                               post_scope)
        re_ = _rewrite_post_agg(e.right, plan, scope, group_names, ctx,
                                post_scope)
        if e.op in ("and", "or"):
            return fcall("And" if e.op == "and" else "Or", le, re_,
                         dtype=BOOL)
        if e.op in _CMP or e.op == "!=":
            if re_.name == "Literal":
                re_ = _coerce(re_, _dt_of(le))
            if le.name == "Literal":
                le = _coerce(le, _dt_of(re_))
            if e.op == "!=":
                return fcall("Not",
                             fcall("EqualTo", le, re_, dtype=BOOL),
                             dtype=BOOL)
            return fcall(_CMP[e.op], le, re_, dtype=BOOL)
        if e.op in _ARITH:
            out = F64 if e.op == "/" else _num_promote(_dt_of(le),
                                                       _dt_of(re_))
            return fcall(_ARITH[e.op], le, re_, dtype=out)
        raise SqlError(f"unsupported post-agg operator {e.op}")
    if isinstance(e, A.Case):
        kids: List[ForeignExpr] = []
        dt: DataType = DataType.null()
        for when, then in e.branches:
            kids.append(_rewrite_post_agg(when, plan, scope, group_names,
                                          ctx, post_scope))
            t = _rewrite_post_agg(then, plan, scope, group_names, ctx,
                                  post_scope)
            if dt.id.name == "NULL" and _dt_of(t).id.name != "NULL":
                dt = _dt_of(t)
            kids.append(t)
        if e.else_expr is not None:
            kids.append(_rewrite_post_agg(e.else_expr, plan, scope,
                                          group_names, ctx, post_scope))
        return fcall("CaseWhen", *kids, dtype=dt)
    if isinstance(e, A.Cast):
        return fcall("Cast",
                     _rewrite_post_agg(e.child, plan, scope, group_names,
                                       ctx, post_scope),
                     dtype=_parse_type(e.type_name))
    raise SqlError(
        f"post-aggregation expression {type(e).__name__} must reference "
        f"grouping columns or aggregates")


# ---------------------------------------------------------------------------
# SELECT
# ---------------------------------------------------------------------------

def _lower_select(sel: A.Select, ctx: _Ctx) -> Rel:
    if sel.ctes:
        ctx = _Ctx(catalog=ctx.catalog,
                   ctes={**ctx.ctes, **dict(sel.ctes)},
                   n_parts=ctx.n_parts, counter=ctx.counter)
    if sel.union_all:
        rels = [_lower_select(_strip(sel), ctx)] + \
               [_lower_select(b, ctx) for b in sel.union_all]
        out = rels[0].scope.schema()
        node = ForeignNode("UnionExec",
                           children=tuple(r.node for r in rels),
                           output=out)
        rel = Rel(node, Scope([(None, f) for f in out.fields]), False)
        return _order_limit(rel, sel, ctx)

    filters = _conjuncts(sel.where)
    rel = _lower_from(sel.from_, ctx, filters)

    # subquery predicates -> semi/anti joins; the rest filters normally
    residual: List[A.Expr] = []
    for f in filters:
        rel2 = _lower_subquery_pred(f, rel, ctx)
        if rel2 is not None:
            rel = rel2
        else:
            residual.append(f)
    for f in residual:
        fe = _lower_expr(f, rel.scope, ctx)
        rel = Rel(ForeignNode("FilterExec", children=(rel.node,),
                              output=rel.node.output,
                              attrs={"condition": fe}),
                  rel.scope, rel.broadcastable)

    has_aggs = bool(sel.group_by) or sel.having is not None or any(
        not isinstance(i.expr, (A.Star, A.WindowCall)) and
        _has_agg(i.expr) for i in sel.items)
    windows = [i for i in sel.items
               if isinstance(i.expr, A.WindowCall)]

    if has_aggs:
        rel = _lower_aggregate(sel, rel, ctx)
    elif sel.distinct:
        rel = _lower_distinct(sel, rel, ctx)
    elif not windows:
        rel = _lower_project(sel, rel, ctx)
    if windows:
        rel = _lower_windows(sel, rel, ctx)
    return _order_limit(rel, sel, ctx)


def _strip(sel: A.Select) -> A.Select:
    import dataclasses
    return dataclasses.replace(sel, order_by=(), limit=None, ctes=(),
                               union_all=())


def _has_agg(e: A.Expr) -> bool:
    found: List[A.Call] = []
    _find_aggs(e, found)
    return bool(found)


def _item_name(item: A.SelectItem, i: int) -> str:
    if item.alias:
        return item.alias.lower()
    if isinstance(item.expr, A.Col):
        return item.expr.name
    return f"col{i}"


def _lower_project(sel: A.Select, rel: Rel, ctx: _Ctx) -> Rel:
    if len(sel.items) == 1 and isinstance(sel.items[0].expr, A.Star):
        return rel
    exprs: List[ForeignExpr] = []
    fields: List[Field] = []
    for i, item in enumerate(sel.items):
        if isinstance(item.expr, A.Star):
            for _, f in rel.scope.cols:
                exprs.append(fcol(f.name, f.dtype, f.nullable))
                fields.append(f)
            continue
        nm = _item_name(item, i)
        fe = _lower_expr(item.expr, rel.scope, ctx)
        dt = _dt_of(fe)
        exprs.append(falias(fe, nm)
                     if (item.alias or not isinstance(item.expr, A.Col))
                     else fe)
        fields.append(Field(nm, dt))
    out = Schema(tuple(fields))
    node = ForeignNode("ProjectExec", children=(rel.node,), output=out,
                       attrs={"project_list": exprs})
    return Rel(node, Scope([(None, f) for f in out.fields]), False)


def _lower_distinct(sel: A.Select, rel: Rel, ctx: _Ctx) -> Rel:
    proj = _lower_project(sel, rel, ctx)
    fields = [f for _, f in proj.scope.cols]
    grouping = [fcol(f.name, f.dtype) for f in fields]
    node = _two_phase(proj.node, grouping, fields, [], ctx)
    return Rel(node, Scope([(None, f) for f in fields]), False)


def _two_phase(child: ForeignNode, grouping, group_fields, entries,
               ctx: _Ctx) -> ForeignNode:
    agg_exprs = [a for _, a, _ in entries]
    agg_names = [n for n, _, _ in entries]
    state_fields = list(group_fields)
    for name, a, out_f in entries:
        fn = a.children[0].name
        if fn == "Average":
            state_fields += [Field(f"{name}#sum", F64),
                             Field(f"{name}#count", I64)]
        elif fn in ("StddevSamp", "VarianceSamp"):
            state_fields += [Field(f"{name}#sum", F64),
                             Field(f"{name}#sumsq", F64),
                             Field(f"{name}#count", I64)]
        elif fn == "Count":
            state_fields.append(Field(f"{name}#count", I64))
        else:
            state_fields.append(Field(f"{name}#{fn.lower()}",
                                      out_f.dtype))
    partial = ForeignNode(
        "HashAggregateExec", children=(child,),
        output=Schema(tuple(state_fields)),
        attrs={"grouping": list(grouping), "aggs": agg_exprs,
               "agg_names": agg_names, "mode": "partial"})
    part_spec = {"mode": "hash", "num_partitions": ctx.n_parts,
                 "expressions": [fcol(f.name, f.dtype)
                                 for f in group_fields]} if grouping \
        else {"mode": "single", "num_partitions": 1}
    exchange = ForeignNode(
        "ShuffleExchangeExec", children=(partial,),
        output=partial.output, attrs={"partitioning": part_spec})
    final_out = Schema(tuple(group_fields) +
                       tuple(f for _, _, f in entries))
    final_grouping = [fcol(f.name, f.dtype) for f in group_fields]
    return ForeignNode(
        "HashAggregateExec", children=(exchange,), output=final_out,
        attrs={"grouping": final_grouping, "aggs": agg_exprs,
               "agg_names": agg_names, "mode": "final"})


def _lower_aggregate(sel: A.Select, rel: Rel, ctx: _Ctx) -> Rel:
    group_names: List[Tuple[A.Expr, str]] = []
    group_fields: List[Field] = []
    grouping: List[ForeignExpr] = []
    scope = rel.scope
    child = rel.node
    needs_pre = any(not isinstance(g, A.Col) for g in sel.group_by)
    if needs_pre:
        pre_exprs: List[ForeignExpr] = []
        pre_cols: List[Tuple[Optional[str], Field]] = []
        for g in sel.group_by:
            if isinstance(g, A.Col):
                continue
            fe = _lower_expr(g, scope, ctx)
            nm = None
            for item in sel.items:
                if item.expr == g and item.alias:
                    nm = item.alias.lower()
            nm = nm or ctx.fresh("grp")
            pre_exprs.append(falias(fe, nm))
            pre_cols.append((None, Field(nm, _dt_of(fe))))
            group_names.append((g, nm))
        for q, f in scope.cols:
            pre_exprs.append(fcol(f.name, f.dtype, f.nullable))
            # keep the qualifier: qualified grouping columns (d.d_year)
            # must still resolve after the pre-projection
            pre_cols.append((q, f))
        out = Schema(tuple(f for _, f in pre_cols))
        child = ForeignNode("ProjectExec", children=(child,),
                            output=out,
                            attrs={"project_list": pre_exprs})
        scope = Scope(pre_cols)
    for g in sel.group_by:
        nm = next((n for gg, n in group_names if gg == g), None)
        if nm is not None:
            f = scope.resolve(nm, None)
        else:
            assert isinstance(g, A.Col)
            f = scope.resolve(g.name, g.table)
            group_names.append((g, f.name))
        grouping.append(fcol(f.name, f.dtype, f.nullable))
        group_fields.append(Field(f.name, f.dtype))

    if sel.rollup:
        # GROUP BY ROLLUP(g1..gN): ExpandExec replicates every row once
        # per prefix, nulling the dropped suffix and tagging
        # spark_grouping_id with bit (n_g-1-j) set when column j is
        # nulled (Spark's convention: MSB = leftmost grouping column;
        # corpus q27r gids 0,1,3) — expand_exec.rs:40
        gset = {f.name for f in group_fields}
        agg_calls: List[A.Call] = []
        for item in sel.items:
            if not isinstance(item.expr, A.WindowCall):
                _find_aggs(item.expr, agg_calls)
        if sel.having is not None:
            _find_aggs(sel.having, agg_calls)
        needed: set = set()
        for c in agg_calls:
            for col_ref in c.args:
                if isinstance(col_ref, A.Star):
                    continue
                for cr in _expr_cols(col_ref):
                    if cr.name in gset:
                        raise SqlError(
                            "aggregating a ROLLUP grouping column "
                            "is not supported yet")
                    needed.add(cr.name.lower())
        n_g = len(group_fields)
        # replicate ONLY the columns the aggregates read — Expand
        # multiplies rows (n_g+1)x, so full-scope width here is pure
        # wasted bandwidth (the corpus narrows before Expand the same
        # way, q27r's pre-projection)
        others = [(q, f) for q, f in scope.cols
                  if f.name not in gset and f.name.lower() in needed]
        gid_field = Field("spark_grouping_id", I64, nullable=False)
        expand_fields = list(group_fields) + [f for _, f in others] + \
            [gid_field]
        projections = []
        for keep in range(n_g, -1, -1):
            gid = 0
            proj: List[ForeignExpr] = []
            for j, f in enumerate(group_fields):
                if j < keep:
                    proj.append(fcol(f.name, f.dtype))
                else:
                    proj.append(flit(None, f.dtype))
                    gid |= 1 << (n_g - 1 - j)
            for _, f in others:
                proj.append(fcol(f.name, f.dtype, f.nullable))
            proj.append(flit(gid, I64))
            projections.append(proj)
        expand_out = Schema(tuple(expand_fields))
        child = ForeignNode("ExpandExec", children=(child,),
                            output=expand_out,
                            attrs={"projections": projections})
        # keep qualifiers on the replicated columns (qualified agg args
        # like ss.ss_quantity must still resolve)
        scope = Scope([(None, f) for f in group_fields] + others +
                      [(None, gid_field)])
        grouping.append(fcol("spark_grouping_id", I64, False))
        group_fields.append(gid_field)

    plan = _AggPlan()
    final_items: List[Tuple[str, A.Expr]] = []
    for i, item in enumerate(sel.items):
        if isinstance(item.expr, A.WindowCall):
            continue
        nm = _item_name(item, i)
        if isinstance(item.expr, A.Call) and \
                item.expr.name in _AGG_FNS:
            plan.slot(item.expr, scope, ctx, preferred=nm)
        else:
            aggs_in: List[A.Call] = []
            _find_aggs(item.expr, aggs_in)
            for c in aggs_in:
                plan.slot(c, scope, ctx)
        final_items.append((nm, item.expr))
    if sel.having is not None:
        having_aggs: List[A.Call] = []
        _find_aggs(sel.having, having_aggs)
        for c in having_aggs:
            plan.slot(c, scope, ctx)

    node = _two_phase(child, grouping, group_fields, plan.entries, ctx)
    agg_scope = Scope([(None, f) for f in group_fields] +
                      [(None, f) for _, _, f in plan.entries])

    if sel.having is not None:
        fe = _rewrite_post_agg(sel.having, plan, scope, group_names,
                               ctx, agg_scope)
        node = ForeignNode("FilterExec", children=(node,),
                           output=node.output,
                           attrs={"condition": fe})

    exprs: List[ForeignExpr] = []
    fields: List[Field] = []
    trivial = True
    for nm, e in final_items:
        fe = _rewrite_post_agg(e, plan, scope, group_names, ctx,
                               agg_scope, preferred=nm)
        is_passthrough = fe.name == "AttributeReference" and \
            fe.value == nm
        if not is_passthrough:
            trivial = False
        exprs.append(fe if is_passthrough else falias(fe, nm))
        fields.append(Field(nm, _dt_of(fe)))
    agg_out_names = [f.name for f in group_fields] + \
        [f.name for _, _, f in plan.entries]
    if trivial and [f.name for f in fields] == agg_out_names:
        return Rel(node, agg_scope, False)
    out = Schema(tuple(fields))
    node = ForeignNode("ProjectExec", children=(node,), output=out,
                       attrs={"project_list": exprs})
    return Rel(node, Scope([(None, f) for f in out.fields]), False)


# ---------------------------------------------------------------------------
# windows / subquery predicates / order-limit
# ---------------------------------------------------------------------------

def _requal(e: A.Expr, scope: Scope) -> A.Expr:
    """Re-scope qualified column refs that an aggregation/projection
    stripped of their qualifier (d.d_year after GROUP BY d.d_year):
    when only the unqualified name survives, use it."""
    if isinstance(e, A.Col) and e.table is not None and \
            not scope.has(e.name, e.table) and scope.has(e.name, None):
        return A.Col(name=e.name)
    return e


def _lower_windows(sel: A.Select, rel: Rel, ctx: _Ctx) -> Rel:
    wins = [(i, item) for i, item in enumerate(sel.items)
            if isinstance(item.expr, A.WindowCall)]
    specs = {(w.expr.partition_by, w.expr.order_by) for _, w in wins}
    if len(specs) != 1:
        raise SqlError("multiple window specs in one SELECT")
    wc: A.WindowCall = wins[0][1].expr
    part = [_lower_expr(_requal(p, rel.scope), rel.scope, ctx)
            for p in wc.partition_by]
    order = [_so(_lower_expr(_requal(s.expr, rel.scope), rel.scope,
                             ctx), s)
             for s in wc.order_by]
    node = rel.node
    if part:
        node = ForeignNode(
            "ShuffleExchangeExec", children=(node,), output=node.output,
            attrs={"partitioning": {"mode": "hash",
                                    "num_partitions": ctx.n_parts,
                                    "expressions": part}})
    wexprs = []
    wfields = []
    for i, item in wins:
        w: A.WindowCall = item.expr
        if w.call.name not in _WINDOW_FNS:
            raise SqlError(f"unsupported window function "
                           f"{w.call.name}()")
        nm = _item_name(item, i)
        wexprs.append({"name": nm, "fn": w.call.name, "args": [],
                       "agg": None, "dtype": I32})
        wfields.append(Field(nm, I32))
    win_out = Schema(tuple(f for _, f in rel.scope.cols) +
                     tuple(wfields))
    node = ForeignNode(
        "WindowExec", children=(node,), output=win_out,
        attrs={"window_exprs": wexprs, "partition_spec": part,
               "order_spec": order})
    scope = Scope(rel.scope.cols + [(None, f) for f in wfields])
    rel = Rel(node, scope, False)
    exprs: List[ForeignExpr] = []
    fields: List[Field] = []
    for i, item in enumerate(sel.items):
        nm = _item_name(item, i)
        if isinstance(item.expr, A.WindowCall) or scope.has(nm, None):
            # window outputs AND items an upstream aggregate already
            # computed under this name (SELECT mixing sum(..) with
            # rank() OVER: the agg stage ran first) pass through
            f = scope.resolve(nm, None)
            exprs.append(fcol(f.name, f.dtype))
            fields.append(Field(nm, f.dtype))
        else:
            fe = _lower_expr(item.expr, scope, ctx)
            exprs.append(falias(fe, nm))
            fields.append(Field(nm, _dt_of(fe)))
    out = Schema(tuple(fields))
    node = ForeignNode("ProjectExec", children=(rel.node,), output=out,
                       attrs={"project_list": exprs})
    return Rel(node, Scope([(None, f) for f in out.fields]), False)


def _lower_subquery_pred(f: A.Expr, rel: Rel,
                         ctx: _Ctx) -> Optional[Rel]:
    neg = False
    inner = f
    if isinstance(inner, A.Un) and inner.op == "not":
        neg = True
        inner = inner.child
    if isinstance(inner, A.InSubquery):
        sub = _lower_select(inner.query, ctx)
        if len(sub.scope.cols) != 1:
            raise SqlError("IN subquery must produce one column")
        lk = _lower_expr(inner.child, rel.scope, ctx)
        rf = sub.scope.cols[0][1]
        anti = bool(inner.negated) != neg
        if anti:
            # SQL three-valued NOT IN: any NULL in the subquery makes
            # the predicate UNKNOWN for every row (zero rows out), and
            # a NULL probe key can never pass.  Eager null probe
            # (plan-time, like scalar subqueries), then a null-safe
            # anti join.
            probe = ForeignNode(
                "GlobalLimitExec",
                children=(ForeignNode(
                    "FilterExec", children=(sub.node,),
                    output=sub.node.output,
                    attrs={"condition": fcall(
                        "IsNull", fcol(rf.name, rf.dtype),
                        dtype=BOOL)}),),
                output=sub.node.output, attrs={"limit": 1})
            if ctx.execute_subplan(probe).num_rows > 0:
                false_node = ForeignNode(
                    "FilterExec", children=(rel.node,),
                    output=rel.node.output,
                    attrs={"condition": flit(False, BOOL)})
                return Rel(false_node, rel.scope, rel.broadcastable)
            notnull = ForeignNode(
                "FilterExec", children=(rel.node,),
                output=rel.node.output,
                attrs={"condition": fcall("IsNotNull", lk, dtype=BOOL)})
            rel = Rel(notnull, rel.scope, rel.broadcastable)
        return _semi_anti_join(rel, sub, [lk],
                               [fcol(rf.name, rf.dtype)], anti, ctx)
    if isinstance(inner, A.Exists):
        sub_sel = inner.query
        outer_eq: List[Tuple[A.Expr, A.Expr]] = []
        residual: List[A.Expr] = []
        sub_scope = _probe_scope(sub_sel, ctx)
        for c in _conjuncts(sub_sel.where):
            if isinstance(c, A.Bin) and c.op == "==":
                a, b = c.left, c.right
                if _refs_only(a, rel.scope) and _refs_only(b, sub_scope):
                    outer_eq.append((a, b))
                    continue
                if _refs_only(b, rel.scope) and _refs_only(a, sub_scope):
                    outer_eq.append((b, a))
                    continue
            residual.append(c)
        if not outer_eq:
            raise SqlError("EXISTS without a correlating equality is "
                           "not supported")
        inner_sel = A.Select(
            items=tuple(A.SelectItem(expr=b, alias=f"__ck{i}")
                        for i, (_, b) in enumerate(outer_eq)),
            from_=sub_sel.from_,
            where=_and_all(residual), ctes=sub_sel.ctes)
        sub = _lower_select(inner_sel, ctx)
        lks = [_lower_expr(a, rel.scope, ctx) for a, _ in outer_eq]
        rks = [fcol(f.name, f.dtype) for _, f in sub.scope.cols]
        anti = bool(inner.negated) != neg
        return _semi_anti_join(rel, sub, lks, rks, anti, ctx)
    return None


def _probe_scope(sel: A.Select, ctx: _Ctx) -> Scope:
    """Scope of a subquery's FROM for decorrelation classification —
    schema-only (no scan/join node construction, no fresh-name burn;
    the real lowering happens once the conjuncts are classified)."""
    return _scope_of_from(sel.from_, ctx)


def _scope_of_from(t: Optional[A.TableRef], ctx: _Ctx) -> Scope:
    if isinstance(t, A.BaseTable):
        if t.name in ctx.ctes or t.name not in ctx.catalog.tables:
            # CTE / unknown: fall back to full lowering (rare path)
            return _lower_from(t, _Ctx(catalog=ctx.catalog,
                                       ctes=ctx.ctes,
                                       n_parts=ctx.n_parts), []).scope
        qual = t.alias or t.name
        return Scope([(qual, f)
                      for f in ctx.catalog.tables[t.name].schema.fields])
    if isinstance(t, A.Join):
        left = _scope_of_from(t.left, ctx)
        right = _scope_of_from(t.right, ctx)
        return Scope(left.cols + right.cols)
    if isinstance(t, A.SubqueryTable):
        rel = _lower_select(t.query, _Ctx(catalog=ctx.catalog,
                                          ctes=ctx.ctes,
                                          n_parts=ctx.n_parts))
        return Scope([(t.alias, f) for _, f in rel.scope.cols])
    raise SqlError("unsupported FROM element in subquery")


def _and_all(cs: List[A.Expr]) -> Optional[A.Expr]:
    if not cs:
        return None
    e = cs[0]
    for c in cs[1:]:
        e = A.Bin(op="and", left=e, right=c)
    return e


def _so(fe: ForeignExpr, s: A.SortItem) -> ForeignExpr:
    return ForeignExpr(
        "SortOrder", children=(fe,),
        attrs={"asc": s.asc,
               "nulls_first": s.asc if s.nulls_first is None
               else s.nulls_first})


def _order_limit(rel: Rel, sel: A.Select, ctx: _Ctx) -> Rel:
    if not sel.order_by and sel.limit is None:
        return rel
    fields = [f for _, f in rel.scope.cols]

    def resolve_order(s: A.SortItem) -> ForeignExpr:
        e = s.expr
        if isinstance(e, A.Lit) and e.kind == "int":
            if not 1 <= e.value <= len(fields):
                raise SqlError(
                    f"ORDER BY ordinal {e.value} out of range 1.."
                    f"{len(fields)}")
            f = fields[e.value - 1]
            return _so(fcol(f.name, f.dtype), s)
        return _so(_lower_expr(_requal(e, rel.scope), rel.scope, ctx),
                   s)

    if sel.order_by and sel.limit is not None:
        orders = [resolve_order(s) for s in sel.order_by]
        node = ForeignNode(
            "TakeOrderedAndProjectExec", children=(rel.node,),
            output=rel.scope.schema(),
            attrs={"sort_order": orders, "limit": sel.limit,
                   "project_list": [fcol(f.name, f.dtype)
                                    for f in fields]})
        return Rel(node, rel.scope, False)
    if sel.order_by:
        orders = [resolve_order(s) for s in sel.order_by]
        ex = ForeignNode(
            "ShuffleExchangeExec", children=(rel.node,),
            output=rel.node.output,
            attrs={"partitioning": {"mode": "single",
                                    "num_partitions": 1}})
        node = ForeignNode("SortExec", children=(ex,),
                           output=rel.scope.schema(),
                           attrs={"sort_order": orders})
        return Rel(node, rel.scope, False)
    node = ForeignNode("GlobalLimitExec", children=(rel.node,),
                       output=rel.scope.schema(),
                       attrs={"limit": sel.limit})
    return Rel(node, rel.scope, False)


# ---------------------------------------------------------------------------
# scalar subqueries (uncorrelated): eager evaluation, Spark-style
# ---------------------------------------------------------------------------

def _eval_scalar_subquery(q: A.Select, ctx: _Ctx):
    key = ("scalar", q)
    if key in ctx.subquery_cache:
        return ctx.subquery_cache[key]
    rel = _lower_select(q, ctx)
    if len(rel.scope.cols) != 1:
        raise SqlError("scalar subquery must produce one column")
    table = ctx.execute_subplan(rel.node)
    if table.num_rows > 1:
        raise SqlError("scalar subquery returned more than one row")
    f = rel.scope.cols[0][1]
    value = table.column(0)[0].as_py() if table.num_rows else None
    ctx.subquery_cache[key] = (value, f.dtype)
    return value, f.dtype


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

def plan_sql(sql: str, catalog, n_parts: int = 4) -> ForeignNode:
    """SQL text -> foreign physical plan over `catalog` (it.datagen
    Catalog or any object with `.tables: {name: TableDef}` and
    `.scan(name, columns=None, pushed_filters=())`)."""
    ast = A.parse_sql(sql)
    ctx = _Ctx(catalog=catalog, n_parts=n_parts)
    return _lower_select(ast, ctx).node
