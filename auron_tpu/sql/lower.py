"""Resolve + plan: SQL AST -> foreign (Spark-shaped) physical plans.

Plays the role Spark's analyzer/optimizer/planner play in front of the
reference (the plans AuronConverters receives, AuronConverters.scala:
186-209): name resolution against the Catalog, filter pushdown to scan
sides, join strategy (broadcast for base dim tables, sort-merge
otherwise), the canonical partial->hash-exchange->final aggregate pair,
window repartitioning, and TakeOrderedAndProject at the root.  The
emitted trees use exactly the ForeignNode vocabulary the conversion
layer accepts, so a SQL string exercises the same full path as a plan a
real Spark bridge would ship.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field as dfield
from typing import Dict, List, Optional, Tuple

from auron_tpu.frontend.foreign import (ForeignExpr, ForeignNode, falias,
                                        fcall, fcol, flit)
from auron_tpu.ir.schema import DataType, Field, Schema

from auron_tpu.sql import parser as A
from auron_tpu.sql.parser import SqlError

I32 = DataType.int32()
I64 = DataType.int64()
F64 = DataType.float64()
STR = DataType.string()
BOOL = DataType.bool_()

# TPC-DS fact tables: never broadcast (everything else in the schema is
# a dimension — the heuristic Spark's size threshold lands on at the
# scales the corpus runs)
_FACTS = {"store_sales", "catalog_sales", "web_sales", "inventory",
          "store_returns", "catalog_returns", "web_returns"}

_AGG_FNS = {
    "sum": "Sum", "count": "Count", "avg": "Average", "min": "Min",
    "max": "Max", "stddev_samp": "StddevSamp", "stddev": "StddevSamp",
    "var_samp": "VarianceSamp", "variance": "VarianceSamp",
}

_WINDOW_FNS = {"rank", "dense_rank", "row_number"}

_SCALAR_FNS = {
    "substr": "Substring", "substring": "Substring",
    "coalesce": "Coalesce", "upper": "Upper", "lower": "Lower",
    "abs": "Abs", "round": "Round", "length": "Length",
    "concat": "Concat", "year": "Year", "month": "Month",
}

_CMP = {"==": "EqualTo", "!=": "NotEqual", "<": "LessThan",
        "<=": "LessThanOrEqual", ">": "GreaterThan",
        ">=": "GreaterThanOrEqual"}
_ARITH = {"+": "Add", "-": "Subtract", "*": "Multiply", "/": "Divide",
          "%": "Remainder"}


def _dt_of(fe: ForeignExpr) -> DataType:
    return fe.dtype if fe.dtype is not None else DataType.null()


def _num_promote(a: DataType, b: DataType) -> DataType:
    order = {"INT8": 0, "INT16": 1, "INT32": 2, "INT64": 3,
             "FLOAT32": 4, "FLOAT64": 5}
    ra = order.get(a.id.name, 5)
    rb = order.get(b.id.name, 5)
    return a if ra >= rb else b


@dataclass
class Scope:
    """Visible columns of one relation: (qualifier, Field) per column.

    `aliases` carries extra resolution entries (qualifier, logical name,
    physical Field) for columns a self-join disambiguation renamed: the
    SQL text still says d2.d_date_sk but the physical plan column is the
    fresh unique name."""
    cols: List[Tuple[Optional[str], Field]]
    aliases: List[Tuple[Optional[str], str, Field]] = \
        dfield(default_factory=list)

    def schema(self) -> Schema:
        return Schema(tuple(f for _, f in self.cols))

    def resolve(self, name: str, table: Optional[str]) -> Field:
        hits = [f for q, f in self.cols
                if f.name.lower() == name.lower()
                and (table is None or q == table)]
        hits += [f for q, ln, f in self.aliases
                 if ln == name.lower() and (table is None or q == table)]
        if not hits:
            raise SqlError(f"unknown column {table + '.' if table else ''}"
                           f"{name}")
        if len(hits) > 1 and not all(h is hits[0] for h in hits):
            raise SqlError(f"ambiguous column {name}")
        return hits[0]

    def has(self, name: str, table: Optional[str]) -> bool:
        try:
            self.resolve(name, table)
            return True
        except SqlError:
            return False


@dataclass
class _Ctx:
    catalog: object
    ctes: Dict[str, A.Select] = dfield(default_factory=dict)
    n_parts: int = 4
    counter: "itertools.count" = dfield(default_factory=itertools.count)
    # scalar subqueries evaluate eagerly at plan time (Spark computes
    # them as separate jobs before the main query the same way); the
    # executor is pluggable and results are memoized per subquery text
    subquery_exec: Optional[object] = None
    subquery_cache: Dict = dfield(default_factory=dict)
    # decorrelated scalar subqueries: id(AST node) -> joined column ref
    scalar_subst: Dict = dfield(default_factory=dict)
    # computed window outputs: id(WindowCall) -> column ref (for window
    # calls nested inside larger item expressions, q12's revenueratio)
    window_subst: Dict = dfield(default_factory=dict)

    def fresh(self, prefix: str) -> str:
        return f"__{prefix}{next(self.counter)}"

    def execute_subplan(self, node: ForeignNode):
        if self.subquery_exec is not None:
            return self.subquery_exec(node)
        from auron_tpu.frontend.session import AuronSession
        return AuronSession().execute(node).table


# ---------------------------------------------------------------------------
# expression lowering
# ---------------------------------------------------------------------------

def _lower_expr(e: A.Expr, scope: Scope, ctx: _Ctx) -> ForeignExpr:
    if isinstance(e, A.Col):
        f = scope.resolve(e.name, e.table)
        return fcol(f.name, f.dtype, f.nullable)
    if isinstance(e, A.Lit):
        return _lower_lit(e)
    if isinstance(e, A.Bin):
        return _lower_bin(e, scope, ctx)
    if isinstance(e, A.Un):
        if e.op == "not":
            return fcall("Not", _lower_expr(e.child, scope, ctx),
                         dtype=BOOL)
        c = _lower_expr(e.child, scope, ctx)
        return fcall("UnaryMinus", c, dtype=_dt_of(c))
    if isinstance(e, A.IsNull):
        name = "IsNotNull" if e.negated else "IsNull"
        return fcall(name, _lower_expr(e.child, scope, ctx), dtype=BOOL)
    if isinstance(e, A.Between):
        c = _lower_expr(e.child, scope, ctx)
        lo = _coerce(_lower_expr(e.lo, scope, ctx), _dt_of(c))
        hi = _coerce(_lower_expr(e.hi, scope, ctx), _dt_of(c))
        rng = fcall("And",
                    fcall("GreaterThanOrEqual", c, lo, dtype=BOOL),
                    fcall("LessThanOrEqual", c, hi, dtype=BOOL),
                    dtype=BOOL)
        return fcall("Not", rng, dtype=BOOL) if e.negated else rng
    if isinstance(e, A.InList):
        c = _lower_expr(e.child, scope, ctx)
        vals = [_coerce(_lower_expr(v, scope, ctx), _dt_of(c))
                for v in e.values]
        fe = fcall("In", c, *vals, dtype=BOOL)
        fe.attrs["negated"] = e.negated
        return fe
    if isinstance(e, A.Like):
        c = _lower_expr(e.child, scope, ctx)
        fe = fcall("Like", c, _lower_expr(e.pattern, scope, ctx),
                   dtype=BOOL)
        return fcall("Not", fe, dtype=BOOL) if e.negated else fe
    if isinstance(e, A.Case):
        kids: List[ForeignExpr] = []
        out_dt: DataType = DataType.null()
        for when, then in e.branches:
            kids.append(_lower_expr(when, scope, ctx))
            t = _lower_expr(then, scope, ctx)
            if out_dt.id.name == "NULL" and _dt_of(t).id.name != "NULL":
                out_dt = _dt_of(t)
            kids.append(t)
        if e.else_expr is not None:
            els = _lower_expr(e.else_expr, scope, ctx)
            if out_dt.id.name == "NULL" and \
                    _dt_of(els).id.name != "NULL":
                out_dt = _dt_of(els)
            kids.append(els)
        return fcall("CaseWhen", *kids, dtype=out_dt)
    if isinstance(e, A.Cast):
        child = _lower_expr(e.child, scope, ctx)
        target = _parse_type(e.type_name)
        if child.name == "Literal" and isinstance(child.value, str) \
                and target.id.name == "DATE32":
            # fold cast('yyyy-mm-dd' as date) so date +/- INTERVAL
            # arithmetic folds to plain literals
            import datetime
            try:
                d = datetime.date.fromisoformat(child.value)
            except ValueError as ex:
                raise SqlError(f"invalid date literal "
                               f"{child.value!r}: {ex}") from ex
            return flit((d - datetime.date(1970, 1, 1)).days,
                        DataType.date32())
        return fcall("Cast", child, dtype=target)
    if isinstance(e, A.Call):
        return _lower_call(e, scope, ctx)
    if isinstance(e, A.ScalarSubquery):
        sub = ctx.scalar_subst.get(id(e))
        if sub is not None:
            return sub
        value, dtype = _eval_scalar_subquery(e.query, ctx)
        return flit(value, dtype)
    if isinstance(e, A.WindowCall):
        w = ctx.window_subst.get(id(e))
        if w is not None:
            return w
    raise SqlError(f"unsupported expression {type(e).__name__} here")


def _lower_lit(e: A.Lit) -> ForeignExpr:
    if e.kind == "int":
        return flit(e.value, I64 if abs(e.value) > 2 ** 31 else I32)
    if e.kind == "float":
        return flit(float(e.value), F64)
    if e.kind == "str":
        return flit(e.value, STR)
    if e.kind == "date":
        import datetime
        d = datetime.date.fromisoformat(e.value)
        return flit((d - datetime.date(1970, 1, 1)).days,
                    DataType.date32())
    if e.kind == "bool":
        return flit(bool(e.value), BOOL)
    return flit(None, DataType.null())


def _coerce(fe: ForeignExpr, target: Optional[DataType]) -> ForeignExpr:
    """Literal-side type alignment (IN lists, comparisons vs i64 cols)."""
    if fe.name == "Literal" and fe.dtype is not None and \
            target is not None and not target.is_stringlike and \
            fe.dtype.id != target.id and fe.value is not None and \
            fe.dtype.id.name in ("INT32", "INT64", "FLOAT64") and \
            target.id.name in ("INT8", "INT16", "INT32", "INT64",
                               "FLOAT32", "FLOAT64"):
        return flit(fe.value, target)
    if fe.name == "Literal" and fe.dtype is not None and \
            fe.dtype.is_stringlike and target is not None and \
            target.id.name == "DATE32" and \
            isinstance(fe.value, str):
        # Spark coerces string literals against date columns
        import datetime
        try:
            d = datetime.date.fromisoformat(fe.value)
        except ValueError:
            return fe
        return flit((d - datetime.date(1970, 1, 1)).days,
                    DataType.date32())
    return fe


def _lower_bin(e: A.Bin, scope: Scope, ctx: _Ctx) -> ForeignExpr:
    # date +/- INTERVAL n days: fold when the date side is a literal,
    # else DateAdd/DateSub
    for a, b, flip in ((e.left, e.right, False), (e.right, e.left,
                                                  True)):
        if isinstance(b, A.Lit) and b.kind == "interval_days" and \
                e.op in ("+", "-") and not (flip and e.op == "-"):
            base = _lower_expr(a, scope, ctx)
            days = int(b.value)
            if base.name == "Literal" and base.dtype is not None and \
                    base.dtype.id.name == "DATE32":
                delta = days if e.op == "+" else -days
                return flit(base.value + delta, DataType.date32())
            return fcall("DateAdd" if e.op == "+" else "DateSub",
                         base, flit(days, I32),
                         dtype=DataType.date32())
    if e.op == "and":
        return fcall("And", _lower_expr(e.left, scope, ctx),
                     _lower_expr(e.right, scope, ctx), dtype=BOOL)
    if e.op == "or":
        return fcall("Or", _lower_expr(e.left, scope, ctx),
                     _lower_expr(e.right, scope, ctx), dtype=BOOL)
    if e.op == "||":
        return fcall("Concat", _lower_expr(e.left, scope, ctx),
                     _lower_expr(e.right, scope, ctx), dtype=STR)
    left = _lower_expr(e.left, scope, ctx)
    right = _lower_expr(e.right, scope, ctx)
    if e.op in _CMP or e.op == "!=":
        if left.name == "Literal":
            left = _coerce(left, _dt_of(right))
        if right.name == "Literal":
            right = _coerce(right, _dt_of(left))
        if e.op == "!=":
            return fcall("Not",
                         fcall("EqualTo", left, right, dtype=BOOL),
                         dtype=BOOL)
        return fcall(_CMP[e.op], left, right, dtype=BOOL)
    if e.op in _ARITH:
        if right.name == "Literal":
            right = _coerce(right, _dt_of(left))
        if left.name == "Literal":
            left = _coerce(left, _dt_of(right))
        if e.op == "/":
            out = F64      # Spark SQL: non-decimal division is double
        else:
            out = _num_promote(_dt_of(left), _dt_of(right))
        # constant folding (Spark's optimizer runs before the physical
        # plan, so `1999 + 1` never reaches the converter unfolded)
        if left.name == "Literal" and right.name == "Literal" and \
                left.value is not None and right.value is not None and \
                isinstance(left.value, (int, float)) and \
                isinstance(right.value, (int, float)):
            def _mod(a, b):
                # Spark %: sign of the DIVIDEND (the runtime kernel's
                # sign(a)*(|a| % |b|)), not Python's sign-of-divisor
                if b == 0:
                    return None
                m = abs(a) % abs(b)
                return -m if a < 0 else m
            try:
                v = {"+": lambda a, b: a + b,
                     "-": lambda a, b: a - b,
                     "*": lambda a, b: a * b,
                     "/": lambda a, b: a / b if b != 0 else None,
                     "%": _mod,
                     }[e.op](left.value, right.value)
            except (ArithmeticError, KeyError):
                v = None
            if v is not None:
                if out.id.name in ("INT8", "INT16", "INT32", "INT64"):
                    v = int(v)
                return flit(v, out)
        return fcall(_ARITH[e.op], left, right, dtype=out)
    raise SqlError(f"unsupported operator {e.op}")


def _lower_call(e: A.Call, scope: Scope, ctx: _Ctx) -> ForeignExpr:
    if e.name in _AGG_FNS:
        raise SqlError(f"aggregate {e.name}() outside aggregation "
                       f"context")
    if e.name in _WINDOW_FNS:
        raise SqlError(f"window function {e.name}() requires OVER")
    spark = _SCALAR_FNS.get(e.name)
    if spark is None:
        raise SqlError(f"unsupported function {e.name}()")
    args = [_lower_expr(a, scope, ctx) for a in e.args]
    dt = {"Substring": STR, "Upper": STR, "Lower": STR, "Concat": STR,
          "Length": I32, "Year": I32, "Month": I32}.get(
              spark, _dt_of(args[0]) if args else F64)
    if spark == "Coalesce":
        dt = _dt_of(args[0])
    return fcall(spark, *args, dtype=dt)


def _parse_type(name: str) -> DataType:
    base = name.split("(")[0]
    if base in ("int", "integer"):
        return I32
    if base == "bigint":
        return I64
    if base in ("double", "float8"):
        return F64
    if base in ("varchar", "char", "string", "text"):
        return STR
    if base == "date":
        return DataType.date32()
    if base == "decimal":
        inner = name[name.index("(") + 1:-1].split(",") \
            if "(" in name else ["10", "0"]
        return DataType.decimal(int(inner[0]),
                                int(inner[1]) if len(inner) > 1 else 0)
    raise SqlError(f"unsupported cast type {name!r}")


# ---------------------------------------------------------------------------
# relations
# ---------------------------------------------------------------------------

@dataclass
class Rel:
    node: ForeignNode
    scope: Scope
    broadcastable: bool = False
    # aggregate with no GROUP BY: guaranteed exactly one row (lets the
    # comma-join planner accept keyless joins against it)
    single_row: bool = False
    # leading visible columns; the rest are hidden ORDER BY carriers
    # (grouping columns sorted on but not selected) projected away by
    # _order_limit
    visible: Optional[int] = None


def _conjuncts(e: Optional[A.Expr]) -> List[A.Expr]:
    if e is None:
        return []
    if isinstance(e, A.Bin) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _walk(e: A.Expr):
    """Yield every sub-expression (pre-order), pruning subquery bodies
    (they resolve in their own scope).  The ONE reflection walker both
    column collection and aggregate discovery share."""
    yield e
    if isinstance(e, (A.Exists, A.ScalarSubquery)):
        return
    if isinstance(e, A.InSubquery):
        yield from _walk(e.child)
        return

    def rec_v(v):
        if isinstance(v, A.Expr):
            yield from _walk(v)
        elif isinstance(v, tuple):
            for y in v:
                yield from rec_v(y)

    for f in getattr(e, "__dataclass_fields__", {}):
        yield from rec_v(getattr(e, f))


def _expr_cols(e: A.Expr) -> List[A.Col]:
    return [x for x in _walk(e) if isinstance(x, A.Col)]


def _refs_only(e: A.Expr, scope: Scope) -> bool:
    """Every column ref resolves in `scope` AND no subquery hides
    anywhere inside (subquery predicates must reach the top-level
    classification, never a single-table pushdown — their bodies may
    correlate with other tables)."""
    if _has_subquery(e):
        return False
    cols = _expr_cols(e)
    return all(scope.has(c.name, c.table) for c in cols)


def _lower_base(t: A.BaseTable, ctx: _Ctx,
                filters: List[A.Expr]) -> Rel:
    """Base table scan with every single-table conjunct pushed down."""
    if t.name in ctx.ctes:
        rel = _lower_select(ctx.ctes[t.name], ctx)
        qual = t.alias or t.name
        scope = Scope([(qual, f) for _, f in rel.scope.cols])
        return Rel(rel.node, scope, rel.broadcastable,
                   single_row=rel.single_row)
    cat = ctx.catalog
    if t.name not in cat.tables:
        raise SqlError(f"unknown table {t.name}")
    qual = t.alias or t.name
    tdef = cat.tables[t.name]
    scope = Scope([(qual, f) for f in tdef.schema.fields])
    mine = [f for f in filters if _refs_only(f, scope)]
    pushed = [_lower_expr(f, scope, ctx) for f in mine]
    for f in mine:
        filters.remove(f)
    node = cat.scan(t.name, pushed_filters=pushed)
    for p in pushed:
        node = ForeignNode("FilterExec", children=(node,),
                           output=node.output, attrs={"condition": p})
    return Rel(node, scope, broadcastable=t.name not in _FACTS)


def _equi_keys(cond: List[A.Expr], left: Scope, right: Scope,
               ctx: _Ctx):
    """Split conjuncts into (left_keys, right_keys, residual)."""
    lks: List[ForeignExpr] = []
    rks: List[ForeignExpr] = []
    rest: List[A.Expr] = []
    def _edge_side(e: A.Expr, scope: Scope) -> bool:
        # a CROSS edge needs at least one actual column per side:
        # literals are vacuously scope-only, and `inv1.d_moy = 1` must
        # stay a filter, not become a literal join key (q39's CTE
        # self-join lost every row through the SMJ's constant key)
        return bool(_expr_cols(e)) and _refs_only(e, scope)

    for c in cond:
        if isinstance(c, A.Bin) and c.op == "==":
            a, b = c.left, c.right
            if _edge_side(a, left) and _edge_side(b, right):
                lks.append(_lower_expr(a, left, ctx))
                rks.append(_lower_expr(b, right, ctx))
                continue
            if _edge_side(b, left) and _edge_side(a, right):
                lks.append(_lower_expr(b, left, ctx))
                rks.append(_lower_expr(a, right, ctx))
                continue
        rest.append(c)
    return lks, rks, rest


_JOIN_TYPES = {"inner": "Inner", "left": "LeftOuter",
               "right": "RightOuter", "full": "FullOuter"}


def _hash_exchange(child: ForeignNode, keys, ctx: _Ctx) -> ForeignNode:
    return ForeignNode(
        "ShuffleExchangeExec", children=(child,), output=child.output,
        attrs={"partitioning": {"mode": "hash",
                                "num_partitions": ctx.n_parts,
                                "expressions": list(keys)}})


def _avoid_collisions(left_scope: Scope, right: Rel, ctx: _Ctx) -> Rel:
    """Self-join disambiguation: physically rename right-side columns
    whose names collide with the left side (a projection with fresh
    names), keeping SQL-level resolution working through Scope.aliases.
    The analogue of Spark's expression-ID attribute distinction that a
    name-keyed plan format has to make explicit."""
    taken = {f.name.lower() for _, f in left_scope.cols}
    if not any(f.name.lower() in taken for _, f in right.scope.cols):
        return right
    proj: List[ForeignExpr] = []
    new_cols: List[Tuple[Optional[str], Field]] = []
    aliases = list(right.scope.aliases)
    for q, f in right.scope.cols:
        if f.name.lower() in taken:
            nn = ctx.fresh(f"r_{f.name}")
            nf = Field(nn, f.dtype, f.nullable)
            proj.append(falias(fcol(f.name, f.dtype, f.nullable), nn))
            new_cols.append((q, nf))
            aliases.append((q, f.name.lower(), nf))
        else:
            proj.append(fcol(f.name, f.dtype, f.nullable))
            new_cols.append((q, f))
    out = Schema(tuple(f for _, f in new_cols))
    node = ForeignNode("ProjectExec", children=(right.node,), output=out,
                       attrs={"project_list": proj})
    return Rel(node, Scope(new_cols, aliases), right.broadcastable)


def _join(left: Rel, right: Rel, kind: str, lks, rks, ctx: _Ctx) -> Rel:
    for _, fa in left.scope.cols:
        for _, fb in right.scope.cols:
            if fa.name.lower() == fb.name.lower():
                raise SqlError(
                    f"column {fa.name} appears on both join sides — "
                    f"alias one side through a subquery (self-join "
                    f"outputs need distinct names)")
    jt = _JOIN_TYPES[kind]
    out_scope = Scope(left.scope.cols + right.scope.cols,
                      left.scope.aliases + right.scope.aliases)
    out = Schema(tuple(f for _, f in out_scope.cols))
    if right.broadcastable and kind in ("inner", "left"):
        bx = ForeignNode("BroadcastExchangeExec", children=(right.node,),
                         output=right.node.output)
        node = ForeignNode(
            "BroadcastHashJoinExec", children=(left.node, bx),
            output=out,
            attrs={"left_keys": lks, "right_keys": rks,
                   "join_type": jt, "build_side": "right"})
        return Rel(node, out_scope, left.broadcastable)
    if left.broadcastable and kind in ("inner", "right"):
        # broadcast the LEFT side by flipping the join orientation,
        # then restore the column order with a projection
        flip = {"inner": "inner", "right": "left"}[kind]
        swapped = _join(right, left, flip, rks, lks, ctx)
        ordered = [swapped.scope.cols[len(right.scope.cols) + i]
                   for i in range(len(left.scope.cols))] + \
                  [swapped.scope.cols[i]
                   for i in range(len(right.scope.cols))]
        proj = [fcol(f.name, f.dtype) for _, f in ordered]
        node = ForeignNode("ProjectExec", children=(swapped.node,),
                           output=out, attrs={"project_list": proj})
        return Rel(node, out_scope, False)
    node = ForeignNode(
        "SortMergeJoinExec",
        children=(_hash_exchange(left.node, lks, ctx),
                  _hash_exchange(right.node, rks, ctx)),
        output=out,
        attrs={"left_keys": lks, "right_keys": rks, "join_type": jt})
    return Rel(node, out_scope, False)


def _semi_anti_join(left: Rel, right: Rel, lks, rks, anti: bool,
                    ctx: _Ctx) -> Rel:
    jt = "LeftAnti" if anti else "LeftSemi"
    if right.broadcastable:
        bx = ForeignNode("BroadcastExchangeExec", children=(right.node,),
                         output=right.node.output)
        node = ForeignNode(
            "BroadcastHashJoinExec", children=(left.node, bx),
            output=left.scope.schema(),
            attrs={"left_keys": lks, "right_keys": rks,
                   "join_type": jt, "build_side": "right"})
        return Rel(node, left.scope, left.broadcastable)
    node = ForeignNode(
        "SortMergeJoinExec",
        children=(_hash_exchange(left.node, lks, ctx),
                  _hash_exchange(right.node, rks, ctx)),
        output=left.scope.schema(),
        attrs={"left_keys": lks, "right_keys": rks, "join_type": jt})
    return Rel(node, left.scope, False)


def _lower_from(t: Optional[A.TableRef], ctx: _Ctx,
                filters: List[A.Expr]) -> Rel:
    if t is None:
        raise SqlError("SELECT without FROM is not supported")
    if isinstance(t, A.BaseTable):
        return _lower_base(t, ctx, filters)
    if isinstance(t, A.SubqueryTable):
        rel = _lower_select(t.query, ctx)
        scope = Scope([(t.alias, f) for _, f in rel.scope.cols])
        return Rel(rel.node, scope, rel.broadcastable,
                   single_row=rel.single_row)
    if isinstance(t, A.Join):
        if t.kind == "cross":
            return _lower_comma_join(t, ctx, filters)
        # WHERE conjuncts must not push below an outer join's nullable
        # side: `ws LEFT JOIN wr ... WHERE wr_return_amt > 10000`
        # filters AFTER the join (null-rejecting semantics, q49's
        # inner-ization), not the wr scan
        null_left = t.kind in ("right", "full")
        null_right = t.kind in ("left", "full")
        left = _lower_from(t.left, ctx, [] if null_left else filters)
        right = _avoid_collisions(
            left.scope,
            _lower_from(t.right, ctx, [] if null_right else filters),
            ctx)
        cond = _conjuncts(t.on)
        lks, rks, rest = _equi_keys(cond, left.scope, right.scope, ctx)
        if not lks:
            raise SqlError("JOIN without an equi key is not supported")
        rel = _join(left, right, t.kind, lks, rks, ctx)
        for f in rest:
            fe = _lower_expr(f, rel.scope, ctx)
            rel = Rel(ForeignNode("FilterExec", children=(rel.node,),
                                  output=rel.node.output,
                                  attrs={"condition": fe}),
                      rel.scope, rel.broadcastable)
        return rel
    raise SqlError(f"unsupported FROM element {type(t).__name__}")


def _flatten_cross(t: A.TableRef) -> List[A.TableRef]:
    if isinstance(t, A.Join) and t.kind == "cross":
        return _flatten_cross(t.left) + _flatten_cross(t.right)
    return [t]


def _factored_equis(f: A.Expr, both: Scope) -> List[A.Expr]:
    """Equality conjuncts present in EVERY disjunct of an OR (q13/q48:
    the join keys live inside each arm of a disjunctive filter).
    Joining on them is sound — each arm implies them — and the OR
    itself still applies as a residual filter afterwards."""
    if not (isinstance(f, A.Bin) and f.op == "or"):
        return []
    per = [[c for c in _conjuncts(d)
            if isinstance(c, A.Bin) and c.op == "=="]
           for d in _disjuncts(f)]
    if not per or any(not p for p in per):
        return []
    common = [c for c in per[0] if all(c in p for p in per[1:])]
    return [c for c in common if _refs_only(c, both)]


def _lower_comma_join(t: A.Join, ctx: _Ctx,
                      filters: List[A.Expr]) -> Rel:
    """Comma-join list: equi conditions live in WHERE, and the textual
    FROM order need not be join-connected pairwise (TPC-DS lists dims
    and facts in arbitrary order).  Greedy join-graph walk: start from
    the first relation and repeatedly attach any relation that has an
    equi edge to the joined prefix — the connectivity-ordering half of
    what Spark's cost-based join reordering does.  Single-row
    aggregates (q28/q88's counting subqueries) may join keylessly on a
    constant key."""
    rels = [_lower_from(x, ctx, filters) for x in _flatten_cross(t)]
    joined = rels.pop(0)
    while rels:
        progressed = False
        for i, cand in enumerate(rels):
            cand = _avoid_collisions(joined.scope, cand, ctx)
            both = Scope(joined.scope.cols + cand.scope.cols,
                         joined.scope.aliases + cand.scope.aliases)
            pool = [f for f in filters if _refs_only(f, both)]
            factored: List[A.Expr] = []
            for f in filters:
                factored.extend(_factored_equis(f, both))
            lks, rks, rest = _equi_keys(pool + factored, joined.scope,
                                        cand.scope, ctx)
            if not lks:
                continue
            for f in pool:
                if f not in rest:
                    filters.remove(f)
            joined = _join(joined, cand, "inner", lks, rks, ctx)
            rels.pop(i)
            progressed = True
            break
        if progressed:
            continue
        # no equi edge anywhere: a single-row side joins on a constant
        # key (the 1x1 cartesian the reference plans as a broadcast
        # nested loop with no condition)
        i = next((i for i, r in enumerate(rels)
                  if r.single_row or joined.single_row), None)
        if i is None:
            raise SqlError("cross join without an equi condition "
                           "in WHERE is not supported")
        cand = _avoid_collisions(joined.scope, rels.pop(i), ctx)
        one = flit(1, I32)
        joined = _join(joined, cand, "inner", [one], [one], ctx)
    return joined


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

def _find_aggs(e: A.Expr, out: List[A.Call]):
    """Aggregate calls belonging to the GROUP BY stage — pruning window
    calls (their own fn runs in the window stage; their ARGS are
    slotted explicitly by _lower_aggregate)."""
    if isinstance(e, (A.WindowCall, A.Exists, A.ScalarSubquery)):
        return
    if isinstance(e, A.InSubquery):
        _find_aggs(e.child, out)
        return
    if isinstance(e, A.Call) and e.name in _AGG_FNS:
        out.append(e)

    def rec_v(v):
        if isinstance(v, A.Expr):
            _find_aggs(v, out)
        elif isinstance(v, tuple):
            for y in v:
                rec_v(y)

    for f in getattr(e, "__dataclass_fields__", {}):
        rec_v(getattr(e, f))


def _win_calls(e: A.Expr) -> List[A.WindowCall]:
    return [x for x in _walk(e) if isinstance(x, A.WindowCall)]


def _agg_out_dtype(fn: str, arg: Optional[ForeignExpr]) -> DataType:
    if fn == "Count":
        return I64
    if fn in ("Average", "StddevSamp", "VarianceSamp"):
        return F64
    dt = _dt_of(arg) if arg is not None else I64
    if fn == "Sum":
        if dt.id.name in ("INT8", "INT16", "INT32", "INT64"):
            return I64
        if dt.is_decimal:
            return dt
        return F64
    return dt


def _spark_agg(fn: str, arg: Optional[ForeignExpr], dt: DataType,
               distinct: bool) -> ForeignExpr:
    children = (arg,) if arg is not None else ()
    return ForeignExpr("AggregateExpression",
                       children=(fcall(fn, *children, dtype=dt),),
                       attrs={"distinct": distinct})


@dataclass
class _AggPlan:
    """Aggregate rewrite state: AST agg calls -> output column names."""
    names: List[Tuple[A.Call, str]] = dfield(default_factory=list)
    entries: List[Tuple[str, ForeignExpr, Field]] = \
        dfield(default_factory=list)

    def slot(self, call: A.Call, scope: Scope, ctx: _Ctx,
             preferred: Optional[str] = None) -> Tuple[str, DataType]:
        for seen, nm in self.names:
            if seen == call:
                dt = next(f.dtype for n, _, f in self.entries
                          if n == nm)
                return nm, dt
        fn = _AGG_FNS[call.name]
        arg = None
        if call.args and not isinstance(call.args[0], A.Star):
            arg = _lower_expr(call.args[0], scope, ctx)
        dt = _agg_out_dtype(fn, arg)
        nm = preferred or ctx.fresh("agg")
        self.names.append((call, nm))
        self.entries.append(
            (nm, _spark_agg(fn, arg, dt, call.distinct), Field(nm, dt)))
        return nm, dt


def _rewrite_post_agg(e: A.Expr, plan: "_AggPlan", scope: Scope,
                      group_names: List[Tuple[A.Expr, str]], ctx: _Ctx,
                      post_scope: Scope,
                      preferred: Optional[str] = None) -> ForeignExpr:
    """Lower an expression over the AGG OUTPUT: agg calls become their
    output columns, grouping expressions resolve to their output names,
    everything else must reference grouping columns."""
    for g, nm in group_names:
        if e == g:
            f = post_scope.resolve(nm, None)
            return fcol(f.name, f.dtype, f.nullable)
    if isinstance(e, A.Call) and e.name in _AGG_FNS:
        nm, dt = plan.slot(e, scope, ctx, preferred)
        return fcol(nm, dt)
    if isinstance(e, A.Col):
        f = post_scope.resolve(e.name, None)
        return fcol(f.name, f.dtype, f.nullable)
    if isinstance(e, A.Lit):
        return _lower_lit(e)
    if isinstance(e, A.Bin):
        le = _rewrite_post_agg(e.left, plan, scope, group_names, ctx,
                               post_scope)
        re_ = _rewrite_post_agg(e.right, plan, scope, group_names, ctx,
                                post_scope)
        if e.op in ("and", "or"):
            return fcall("And" if e.op == "and" else "Or", le, re_,
                         dtype=BOOL)
        if e.op in _CMP or e.op == "!=":
            if re_.name == "Literal":
                re_ = _coerce(re_, _dt_of(le))
            if le.name == "Literal":
                le = _coerce(le, _dt_of(re_))
            if e.op == "!=":
                return fcall("Not",
                             fcall("EqualTo", le, re_, dtype=BOOL),
                             dtype=BOOL)
            return fcall(_CMP[e.op], le, re_, dtype=BOOL)
        if e.op in _ARITH:
            out = F64 if e.op == "/" else _num_promote(_dt_of(le),
                                                       _dt_of(re_))
            return fcall(_ARITH[e.op], le, re_, dtype=out)
        raise SqlError(f"unsupported post-agg operator {e.op}")
    if isinstance(e, A.Case):
        kids: List[ForeignExpr] = []
        dt: DataType = DataType.null()
        for when, then in e.branches:
            kids.append(_rewrite_post_agg(when, plan, scope, group_names,
                                          ctx, post_scope))
            t = _rewrite_post_agg(then, plan, scope, group_names, ctx,
                                  post_scope)
            if dt.id.name == "NULL" and _dt_of(t).id.name != "NULL":
                dt = _dt_of(t)
            kids.append(t)
        if e.else_expr is not None:
            kids.append(_rewrite_post_agg(e.else_expr, plan, scope,
                                          group_names, ctx, post_scope))
        return fcall("CaseWhen", *kids, dtype=dt)
    if isinstance(e, A.Cast):
        return fcall("Cast",
                     _rewrite_post_agg(e.child, plan, scope, group_names,
                                       ctx, post_scope),
                     dtype=_parse_type(e.type_name))
    if isinstance(e, A.WindowCall):
        w = ctx.window_subst.get(id(e))
        if w is not None:
            return w
        raise SqlError("window call outside the window stage")
    if isinstance(e, A.ScalarSubquery):
        value, dtype = _eval_scalar_subquery(e.query, ctx)
        return flit(value, dtype)
    if isinstance(e, A.Call) and e.name == "grouping":
        # grouping(col) after ROLLUP: extract the column's bit from
        # spark_grouping_id (bit n_g-1-j for grouping column j, the
        # Spark/ExpandExec convention encoded in _lower_aggregate)
        nm = next((n for g, n in group_names if g == e.args[0]), None)
        if nm is None and isinstance(e.args[0], A.Col):
            nm = e.args[0].name
        gnames = [f.name for _, f in post_scope.cols]
        if "spark_grouping_id" not in gnames or nm is None:
            raise SqlError("grouping() requires ROLLUP grouping sets")
        lead = gnames[:gnames.index("spark_grouping_id")]
        if nm not in lead:
            raise SqlError(f"grouping() argument {nm} is not a "
                           f"grouping column")
        shift = len(lead) - 1 - lead.index(nm)
        gid = fcol("spark_grouping_id", I64, False)
        return fcall("BitwiseAnd",
                     fcall("ShiftRight", gid, flit(shift, I32),
                           dtype=I64),
                     flit(1, I64), dtype=I64)
    if isinstance(e, A.Call):
        args = [_rewrite_post_agg(a, plan, scope, group_names, ctx,
                                  post_scope) for a in e.args]
        spark = _SCALAR_FNS.get(e.name)
        if spark is None:
            raise SqlError(f"unsupported post-agg function {e.name}()")
        dt = {"Substring": STR, "Upper": STR, "Lower": STR,
              "Concat": STR, "Length": I32, "Year": I32,
              "Month": I32}.get(
                  spark, _dt_of(args[0]) if args else F64)
        return fcall(spark, *args, dtype=dt)
    if isinstance(e, A.IsNull):
        name = "IsNotNull" if e.negated else "IsNull"
        return fcall(name,
                     _rewrite_post_agg(e.child, plan, scope, group_names,
                                       ctx, post_scope), dtype=BOOL)
    if isinstance(e, A.Un) and e.op == "not":
        return fcall("Not",
                     _rewrite_post_agg(e.child, plan, scope, group_names,
                                       ctx, post_scope), dtype=BOOL)
    if isinstance(e, A.Between):
        c = _rewrite_post_agg(e.child, plan, scope, group_names, ctx,
                              post_scope)
        lo = _coerce(_rewrite_post_agg(e.lo, plan, scope, group_names,
                                       ctx, post_scope), _dt_of(c))
        hi = _coerce(_rewrite_post_agg(e.hi, plan, scope, group_names,
                                       ctx, post_scope), _dt_of(c))
        rng = fcall("And",
                    fcall("GreaterThanOrEqual", c, lo, dtype=BOOL),
                    fcall("LessThanOrEqual", c, hi, dtype=BOOL),
                    dtype=BOOL)
        return fcall("Not", rng, dtype=BOOL) if e.negated else rng
    raise SqlError(
        f"post-aggregation expression {type(e).__name__} must reference "
        f"grouping columns or aggregates")


# ---------------------------------------------------------------------------
# SELECT
# ---------------------------------------------------------------------------

def _lower_select(sel: A.Select, ctx: _Ctx) -> Rel:
    if sel.ctes:
        ctx = _Ctx(catalog=ctx.catalog,
                   ctes={**ctx.ctes, **dict(sel.ctes)},
                   n_parts=ctx.n_parts, counter=ctx.counter,
                   subquery_exec=ctx.subquery_exec,
                   subquery_cache=ctx.subquery_cache,
                   scalar_subst=ctx.scalar_subst)
    if sel.set_ops:
        return _lower_set_ops(sel, ctx)
    if sel.union_all:
        rels = [_lower_select(_strip(sel), ctx)] + \
               [_lower_select(b, ctx) for b in sel.union_all]
        target = _union_target(rels)
        rels = [_align_branch(target, r, ctx) for r in rels]
        out = rels[0].scope.schema()
        node = ForeignNode("UnionExec",
                           children=tuple(r.node for r in rels),
                           output=out)
        rel = Rel(node, Scope([(None, f) for f in out.fields]), False)
        return _order_limit(rel, sel, ctx)

    filters = _conjuncts(sel.where)
    rel = _lower_from(sel.from_, ctx, filters)

    # subquery predicates -> semi/anti joins; the rest filters normally
    residual: List[A.Expr] = []
    for f in filters:
        rel2 = _lower_subquery_pred(f, rel, ctx)
        if rel2 is not None:
            rel = rel2
        else:
            residual.append(f)
    for f in residual:
        fe = _lower_expr(f, rel.scope, ctx)
        rel = Rel(ForeignNode("FilterExec", children=(rel.node,),
                              output=rel.node.output,
                              attrs={"condition": fe}),
                  rel.scope, rel.broadcastable)

    has_aggs = bool(sel.group_by) or sel.having is not None or any(
        not isinstance(i.expr, (A.Star, A.WindowCall)) and
        _has_agg(i.expr) for i in sel.items)
    windows = [i for i in sel.items
               if not isinstance(i.expr, A.Star) and
               _win_calls(i.expr)]

    aggwin = None
    if has_aggs:
        rel, aggwin = _lower_aggregate(sel, rel, ctx,
                                       for_windows=bool(windows))
        if not sel.group_by and not sel.rollup and not windows:
            rel.single_row = True
            rel.broadcastable = True
    elif sel.distinct:
        rel = _lower_distinct(sel, rel, ctx)
    elif not windows:
        rel = _lower_project(sel, rel, ctx)
    if windows:
        rel = _lower_windows(sel, rel, ctx, aggwin)
    return _order_limit(rel, sel, ctx)


def _strip(sel: A.Select) -> A.Select:
    import dataclasses
    return dataclasses.replace(sel, order_by=(), limit=None, ctes=(),
                               union_all=(), set_ops=())


def _distinct_all(rel: Rel, ctx: _Ctx) -> Rel:
    """DISTINCT over every output column (set-op semantics)."""
    fields = [f for _, f in rel.scope.cols]
    grouping = [fcol(f.name, f.dtype) for f in fields]
    node = _two_phase(rel.node, grouping, fields, [], ctx)
    return Rel(node, Scope([(None, f) for f in fields]), False)


def _lct(a: DataType, b: DataType) -> DataType:
    """Least common type for set-op column alignment (the relevant
    slice of Spark's findWiderTypeForTwo): float beats decimal/int,
    decimal beats int, wider int beats narrower."""
    if a.is_decimal and b.is_decimal:
        # max integer digits + max scale (findWiderTypeForTwo), not a
        # lexicographic pick — decimal(12,0) vs (10,2) must widen to
        # (14,2) or the (10,2) side truncates its fraction
        scale = max(a.scale, b.scale)
        ints = max(a.precision - a.scale, b.precision - b.scale)
        if ints + scale > 38:
            # Spark DecimalPrecision.adjustPrecisionScale: when the sum
            # overflows the 38-digit cap, sacrifice SCALE (down to a
            # floor of min(scale, 6)) to preserve integer digits —
            # capping precision while keeping the full scale silently
            # truncated integer digits, overflowing large-decimal joins
            # where Spark would not (ADVICE r5)
            scale = max(38 - ints, min(scale, 6))
        return DataType.decimal(min(ints + scale, 38), scale)
    if a.id == b.id:
        return a
    ints = ("INT8", "INT16", "INT32", "INT64")
    an, bn = a.id.name, b.id.name
    if "FLOAT64" in (an, bn) or {an, bn} <= {"FLOAT32", "FLOAT64"}:
        return F64
    if an.startswith("FLOAT") or bn.startswith("FLOAT"):
        return F64
    if a.is_decimal and (bn in ints):
        return a
    if b.is_decimal and (an in ints):
        return b
    if an in ints and bn in ints:
        return a if ints.index(an) >= ints.index(bn) else b
    return a


def _union_target(rels: List[Rel]) -> List[Field]:
    target = [Field(f.name, f.dtype, f.nullable)
              for _, f in rels[0].scope.cols]
    for r in rels[1:]:
        for j, (_, f) in enumerate(r.scope.cols[:len(target)]):
            t = target[j]
            target[j] = Field(t.name, _lct(t.dtype, f.dtype))
    return target


def _align_branch(target: List[Field], rel: Rel, ctx: _Ctx) -> Rel:
    """Project a set-op branch onto the aligned column names and
    least-common types (q5 unions float sales against cast-to-decimal
    zeros; both engines run the same coercion)."""
    mine = [f for _, f in rel.scope.cols]
    if len(mine) != len(target):
        raise SqlError(
            f"set-op branches have {len(mine)} vs {len(target)} columns")

    def same_type(a: DataType, b: DataType) -> bool:
        # decimals with different precision/scale are different types
        return a.id == b.id and (not a.is_decimal or
                                 (a.precision, a.scale) ==
                                 (b.precision, b.scale))

    if all(a.name == b.name and same_type(a.dtype, b.dtype)
           for a, b in zip(mine, target)):
        return rel
    proj: List[ForeignExpr] = []
    for src, tf in zip(mine, target):
        fe = fcol(src.name, src.dtype, src.nullable)
        if not same_type(src.dtype, tf.dtype):
            fe = fcall("Cast", fe, dtype=tf.dtype)
        proj.append(falias(fe, tf.name))
    out = Schema(tuple(Field(tf.name, tf.dtype) for tf in target))
    node = ForeignNode("ProjectExec", children=(rel.node,), output=out,
                       attrs={"project_list": proj})
    return Rel(node, Scope([(None, f) for f in out.fields]), False)


def _lower_set_ops(sel: A.Select, ctx: _Ctx) -> Rel:
    """General left-associative set-op chain.  UNION = concat +
    distinct; INTERSECT/EXCEPT = distinct left then semi/anti join on
    every column (Spark rewrites them to exactly these joins).  NULL
    keys never match, so NULL rows drop out of INTERSECT — the corpus
    data is non-null on set-op columns."""
    import dataclasses as _dc
    # keep union_all: a parenthesized (A UNION ALL B) INTERSECT C arm
    # carries its inner union in union_all with the intersect chained
    rel = _lower_select(_dc.replace(sel, order_by=(), limit=None,
                                    ctes=(), set_ops=()), ctx)
    for kind, b in sel.set_ops:
        other = _lower_select(b, ctx)
        target = _union_target([rel, other])
        rel = _align_branch(target, rel, ctx)
        other = _align_branch(target, other, ctx)
        if kind in ("union", "union_all"):
            out = rel.scope.schema()
            node = ForeignNode("UnionExec",
                               children=(rel.node, other.node),
                               output=out)
            rel = Rel(node, Scope([(None, f) for f in out.fields]),
                      False)
            if kind == "union":
                rel = _distinct_all(rel, ctx)
        elif kind in ("intersect", "except"):
            rel = _distinct_all(rel, ctx)
            lks = [fcol(f.name, f.dtype) for _, f in rel.scope.cols]
            rks = [fcol(f.name, f.dtype) for _, f in other.scope.cols]
            rel = _semi_anti_join(rel, other, lks, rks,
                                  kind == "except", ctx)
        else:
            raise SqlError(f"unsupported set operation {kind}")
    return _order_limit(rel, sel, ctx)


def _has_agg(e: A.Expr) -> bool:
    found: List[A.Call] = []
    _find_aggs(e, found)
    return bool(found)


def _item_name(item: A.SelectItem, i: int) -> str:
    if item.alias:
        return item.alias.lower()
    if isinstance(item.expr, A.Col):
        return item.expr.name
    return f"col{i}"


def _lower_project(sel: A.Select, rel: Rel, ctx: _Ctx) -> Rel:
    if len(sel.items) == 1 and isinstance(sel.items[0].expr, A.Star):
        return rel
    exprs: List[ForeignExpr] = []
    cols: List[Tuple[Optional[str], Field]] = []
    aliases: List[Tuple[Optional[str], str, Field]] = []
    seen: set = set()
    for i, item in enumerate(sel.items):
        if isinstance(item.expr, A.Star):
            for _, f in rel.scope.cols:
                exprs.append(fcol(f.name, f.dtype, f.nullable))
                cols.append((None, f))
                seen.add(f.name.lower())
            continue
        nm = _item_name(item, i)
        qual = item.expr.table if isinstance(item.expr, A.Col) \
            and not item.alias else None
        fe = _lower_expr(item.expr, rel.scope, ctx)
        dt = _dt_of(fe)
        if nm.lower() in seen:
            # duplicate output name (q39 selects inv1.w_warehouse_sk
            # AND inv2.w_warehouse_sk): rename physically, resolve
            # logically through a scope alias
            pn = ctx.fresh(f"d_{nm}")
            f = Field(pn, dt)
            exprs.append(falias(fe, pn))
            cols.append((qual, f))
            aliases.append((qual, nm.lower(), f))
            continue
        seen.add(nm.lower())
        f = Field(nm, dt)
        need_alias = item.alias or not isinstance(item.expr, A.Col) \
            or (fe.name == "AttributeReference" and fe.value != nm)
        exprs.append(falias(fe, nm) if need_alias else fe)
        cols.append((qual, f))
    out = Schema(tuple(f for _, f in cols))
    node = ForeignNode("ProjectExec", children=(rel.node,), output=out,
                       attrs={"project_list": exprs})
    return Rel(node, Scope(cols, aliases), False)


def _lower_distinct(sel: A.Select, rel: Rel, ctx: _Ctx) -> Rel:
    proj = _lower_project(sel, rel, ctx)
    fields = [f for _, f in proj.scope.cols]
    grouping = [fcol(f.name, f.dtype) for f in fields]
    node = _two_phase(proj.node, grouping, fields, [], ctx)
    return Rel(node, Scope([(None, f) for f in fields]), False)


def _two_phase(child: ForeignNode, grouping, group_fields, entries,
               ctx: _Ctx) -> ForeignNode:
    agg_exprs = [a for _, a, _ in entries]
    agg_names = [n for n, _, _ in entries]
    state_fields = list(group_fields)
    for name, a, out_f in entries:
        fn = a.children[0].name
        if fn == "Average":
            state_fields += [Field(f"{name}#sum", F64),
                             Field(f"{name}#count", I64)]
        elif fn in ("StddevSamp", "VarianceSamp"):
            state_fields += [Field(f"{name}#sum", F64),
                             Field(f"{name}#sumsq", F64),
                             Field(f"{name}#count", I64)]
        elif fn == "Count":
            state_fields.append(Field(f"{name}#count", I64))
        else:
            state_fields.append(Field(f"{name}#{fn.lower()}",
                                      out_f.dtype))
    partial = ForeignNode(
        "HashAggregateExec", children=(child,),
        output=Schema(tuple(state_fields)),
        attrs={"grouping": list(grouping), "aggs": agg_exprs,
               "agg_names": agg_names, "mode": "partial"})
    part_spec = {"mode": "hash", "num_partitions": ctx.n_parts,
                 "expressions": [fcol(f.name, f.dtype)
                                 for f in group_fields]} if grouping \
        else {"mode": "single", "num_partitions": 1}
    exchange = ForeignNode(
        "ShuffleExchangeExec", children=(partial,),
        output=partial.output, attrs={"partitioning": part_spec})
    final_out = Schema(tuple(group_fields) +
                       tuple(f for _, _, f in entries))
    final_grouping = [fcol(f.name, f.dtype) for f in group_fields]
    return ForeignNode(
        "HashAggregateExec", children=(exchange,), output=final_out,
        attrs={"grouping": final_grouping, "aggs": agg_exprs,
               "agg_names": agg_names, "mode": "final"})


@dataclass
class _AggWin:
    """Aggregation context threaded to window lowering when a SELECT
    mixes GROUP BY aggregates with window functions: the final
    projection is deferred until after the WindowExec stack so window
    partition/order/args can reference agg outputs (and the ROLLUP
    grouping id)."""
    plan: "_AggPlan"
    scope: Scope                 # pre-aggregation scope (for slotting)
    group_names: List[Tuple[A.Expr, str]]


def _lower_aggregate(sel: A.Select, rel: Rel, ctx: _Ctx,
                     for_windows: bool = False
                     ) -> Tuple[Rel, Optional[_AggWin]]:
    group_names: List[Tuple[A.Expr, str]] = []
    group_fields: List[Field] = []
    grouping: List[ForeignExpr] = []
    scope = rel.scope
    child = rel.node
    # dedupe grouping expressions (q11 lists d_year twice; Spark's
    # analyzer collapses duplicates)
    group_by: List[A.Expr] = []
    for g in sel.group_by:
        if g not in group_by:
            group_by.append(g)
    needs_pre = any(not isinstance(g, A.Col) for g in group_by)
    if needs_pre:
        pre_exprs: List[ForeignExpr] = []
        pre_cols: List[Tuple[Optional[str], Field]] = []
        for g in group_by:
            if isinstance(g, A.Col):
                continue
            fe = _lower_expr(g, scope, ctx)
            nm = None
            for item in sel.items:
                if item.expr == g and item.alias:
                    nm = item.alias.lower()
            nm = nm or ctx.fresh("grp")
            pre_exprs.append(falias(fe, nm))
            pre_cols.append((None, Field(nm, _dt_of(fe))))
            group_names.append((g, nm))
        for q, f in scope.cols:
            pre_exprs.append(fcol(f.name, f.dtype, f.nullable))
            # keep the qualifier: qualified grouping columns (d.d_year)
            # must still resolve after the pre-projection
            pre_cols.append((q, f))
        out = Schema(tuple(f for _, f in pre_cols))
        child = ForeignNode("ProjectExec", children=(child,),
                            output=out,
                            attrs={"project_list": pre_exprs})
        scope = Scope(pre_cols)
    for g in group_by:
        nm = next((n for gg, n in group_names if gg == g), None)
        if nm is not None:
            f = scope.resolve(nm, None)
        else:
            assert isinstance(g, A.Col)
            f = scope.resolve(g.name, g.table)
            group_names.append((g, f.name))
        grouping.append(fcol(f.name, f.dtype, f.nullable))
        group_fields.append(Field(f.name, f.dtype))

    if sel.rollup:
        # GROUP BY ROLLUP(g1..gN): ExpandExec replicates every row once
        # per prefix, nulling the dropped suffix and tagging
        # spark_grouping_id with bit (n_g-1-j) set when column j is
        # nulled (Spark's convention: MSB = leftmost grouping column;
        # corpus q27r gids 0,1,3) — expand_exec.rs:40
        gset = {f.name for f in group_fields}
        agg_calls: List[A.Call] = []
        for item in sel.items:
            if not isinstance(item.expr, A.WindowCall):
                _find_aggs(item.expr, agg_calls)
        if sel.having is not None:
            _find_aggs(sel.having, agg_calls)
        needed: set = set()
        for c in agg_calls:
            for col_ref in c.args:
                if isinstance(col_ref, A.Star):
                    continue
                for cr in _expr_cols(col_ref):
                    if cr.name in gset:
                        raise SqlError(
                            "aggregating a ROLLUP grouping column "
                            "is not supported yet")
                    needed.add(cr.name.lower())
        n_g = len(group_fields)
        # replicate ONLY the columns the aggregates read — Expand
        # multiplies rows (n_g+1)x, so full-scope width here is pure
        # wasted bandwidth (the corpus narrows before Expand the same
        # way, q27r's pre-projection)
        others = [(q, f) for q, f in scope.cols
                  if f.name not in gset and f.name.lower() in needed]
        gid_field = Field("spark_grouping_id", I64, nullable=False)
        expand_fields = list(group_fields) + [f for _, f in others] + \
            [gid_field]
        projections = []
        for keep in range(n_g, -1, -1):
            gid = 0
            proj: List[ForeignExpr] = []
            for j, f in enumerate(group_fields):
                if j < keep:
                    proj.append(fcol(f.name, f.dtype))
                else:
                    proj.append(flit(None, f.dtype))
                    gid |= 1 << (n_g - 1 - j)
            for _, f in others:
                proj.append(fcol(f.name, f.dtype, f.nullable))
            proj.append(flit(gid, I64))
            projections.append(proj)
        expand_out = Schema(tuple(expand_fields))
        child = ForeignNode("ExpandExec", children=(child,),
                            output=expand_out,
                            attrs={"projections": projections})
        # keep qualifiers on the replicated columns (qualified agg args
        # like ss.ss_quantity must still resolve)
        scope = Scope([(None, f) for f in group_fields] + others +
                      [(None, gid_field)])
        grouping.append(fcol("spark_grouping_id", I64, False))
        group_fields.append(gid_field)

    plan = _AggPlan()
    final_items: List[Tuple[str, A.Expr]] = []
    for i, item in enumerate(sel.items):
        wcs = [] if isinstance(item.expr, A.Star) else \
            _win_calls(item.expr)
        if wcs:
            # aggs used inside window specs/args must be slotted into
            # the aggregate BEFORE the two-phase plan is built; aggs in
            # the surrounding expression (sum(x) * .. / win OVER ..)
            # are found by the pruned _find_aggs below
            win_aggs: List[A.Call] = []
            for w in wcs:
                for a in w.call.args:
                    if not isinstance(a, A.Star):
                        _find_aggs(a, win_aggs)
                for p in w.partition_by:
                    _find_aggs(p, win_aggs)
                for s in w.order_by:
                    _find_aggs(s.expr, win_aggs)
            _find_aggs(item.expr, win_aggs)
            for c in win_aggs:
                plan.slot(c, scope, ctx)
            continue
        nm = _item_name(item, i)
        if isinstance(item.expr, A.Call) and \
                item.expr.name in _AGG_FNS:
            plan.slot(item.expr, scope, ctx, preferred=nm)
        else:
            aggs_in: List[A.Call] = []
            _find_aggs(item.expr, aggs_in)
            for c in aggs_in:
                plan.slot(c, scope, ctx)
        final_items.append((nm, item.expr))
    if sel.having is not None:
        having_aggs: List[A.Call] = []
        _find_aggs(sel.having, having_aggs)
        for c in having_aggs:
            plan.slot(c, scope, ctx)

    node = _two_phase(child, grouping, group_fields, plan.entries, ctx)
    agg_scope = Scope([(None, f) for f in group_fields] +
                      [(None, f) for _, _, f in plan.entries])

    if sel.having is not None:
        fe = _rewrite_post_agg(sel.having, plan, scope, group_names,
                               ctx, agg_scope)
        node = ForeignNode("FilterExec", children=(node,),
                           output=node.output,
                           attrs={"condition": fe})

    if for_windows:
        return (Rel(node, agg_scope, False),
                _AggWin(plan=plan, scope=scope,
                        group_names=group_names))

    exprs: List[ForeignExpr] = []
    fields: List[Field] = []
    trivial = True
    for nm, e in final_items:
        fe = _rewrite_post_agg(e, plan, scope, group_names, ctx,
                               agg_scope, preferred=nm)
        is_passthrough = fe.name == "AttributeReference" and \
            fe.value == nm
        if not is_passthrough:
            trivial = False
        exprs.append(fe if is_passthrough else falias(fe, nm))
        fields.append(Field(nm, _dt_of(fe)))
    agg_out_names = [f.name for f in group_fields] + \
        [f.name for _, _, f in plan.entries]
    if trivial and [f.name for f in fields] == agg_out_names:
        return Rel(node, agg_scope, False), None
    n_visible = len(fields)
    for s in sel.order_by:
        # ORDER BY a grouping column the SELECT list dropped (q12):
        # carry it hidden through the projection; _order_limit projects
        # it away after sorting
        e = s.expr
        if isinstance(e, A.Col) and \
                not any(f.name == e.name for f in fields) and \
                agg_scope.has(e.name, None):
            f = agg_scope.resolve(e.name, None)
            exprs.append(fcol(f.name, f.dtype, f.nullable))
            fields.append(Field(f.name, f.dtype))
    out = Schema(tuple(fields))
    node = ForeignNode("ProjectExec", children=(node,), output=out,
                       attrs={"project_list": exprs})
    return (Rel(node, Scope([(None, f) for f in out.fields]), False,
                visible=n_visible if len(fields) > n_visible else None),
            None)


# ---------------------------------------------------------------------------
# windows / subquery predicates / order-limit
# ---------------------------------------------------------------------------

def _requal(e: A.Expr, scope: Scope) -> A.Expr:
    """Re-scope qualified column refs that an aggregation/projection
    stripped of their qualifier (d.d_year after GROUP BY d.d_year):
    when only the unqualified name survives, use it."""
    if isinstance(e, A.Col) and e.table is not None and \
            not scope.has(e.name, e.table) and scope.has(e.name, None):
        return A.Col(name=e.name)
    return e


def _lower_windows(sel: A.Select, rel: Rel, ctx: _Ctx,
                   aggwin: Optional[_AggWin] = None) -> Rel:
    """Window stage(s) after the optional aggregation: one
    exchange+WindowExec per distinct (PARTITION BY, ORDER BY) spec,
    rank family and agg-over-window both supported.  With `aggwin`
    (SELECT mixing GROUP BY aggregates and windows) every expression
    lowers through the post-aggregation rewriter, so window specs can
    reference agg outputs and grouping()."""
    wins: List[Tuple[str, A.WindowCall]] = []
    for i, item in enumerate(sel.items):
        if isinstance(item.expr, A.Star):
            continue
        wcs = _win_calls(item.expr)
        if isinstance(item.expr, A.WindowCall):
            wins.append((_item_name(item, i), item.expr))
        else:
            # window calls nested inside a larger expression compute
            # under internal names; the final projection substitutes
            for w in wcs:
                wins.append((ctx.fresh("win"), w))

    def lower_e(e: A.Expr) -> ForeignExpr:
        if aggwin is not None:
            return _rewrite_post_agg(e, aggwin.plan, aggwin.scope,
                                     aggwin.group_names, ctx, rel.scope)
        return _lower_expr(_requal(e, rel.scope), rel.scope, ctx)

    # group windows by spec, preserving first-appearance order
    spec_order: List[Tuple] = []
    by_spec: Dict[Tuple, List[Tuple[str, A.WindowCall]]] = {}
    for nm, w in wins:
        key = (w.partition_by, w.order_by)
        if key not in by_spec:
            by_spec[key] = []
            spec_order.append(key)
        by_spec[key].append((nm, w))

    for key in spec_order:
        group = by_spec[key]
        wc: A.WindowCall = group[0][1]
        part = [lower_e(p) for p in wc.partition_by]
        order = [_so(lower_e(s.expr), s) for s in wc.order_by]
        node = rel.node
        if part:
            node = ForeignNode(
                "ShuffleExchangeExec", children=(node,),
                output=node.output,
                attrs={"partitioning": {"mode": "hash",
                                        "num_partitions": ctx.n_parts,
                                        "expressions": part}})
        wexprs = []
        wfields = []
        for nm, w in group:
            if w.call.name in _WINDOW_FNS:
                wexprs.append({"name": nm, "fn": w.call.name,
                               "args": [], "agg": None, "dtype": I32})
                wfields.append(Field(nm, I32))
                ctx.window_subst[id(w)] = fcol(nm, I32)
            elif w.call.name in _AGG_FNS:
                fn = _AGG_FNS[w.call.name]
                arg = None
                if w.call.args and not isinstance(w.call.args[0],
                                                  A.Star):
                    arg = lower_e(w.call.args[0])
                dt = _agg_out_dtype(fn, arg)
                agg = _spark_agg(fn, arg, dt, w.call.distinct)
                wexprs.append({"name": nm, "fn": "agg",
                               "args": [arg] if arg is not None else [],
                               "agg": agg, "dtype": dt})
                wfields.append(Field(nm, dt))
                ctx.window_subst[id(w)] = fcol(nm, dt)
            else:
                raise SqlError(f"unsupported window function "
                               f"{w.call.name}()")
        win_out = Schema(tuple(f for _, f in rel.scope.cols) +
                         tuple(wfields))
        node = ForeignNode(
            "WindowExec", children=(node,), output=win_out,
            attrs={"window_exprs": wexprs, "partition_spec": part,
                   "order_spec": order})
        rel = Rel(node,
                  Scope(rel.scope.cols + [(None, f) for f in wfields],
                        rel.scope.aliases), False)

    scope = rel.scope
    exprs: List[ForeignExpr] = []
    fields: List[Field] = []
    for i, item in enumerate(sel.items):
        nm = _item_name(item, i)
        if isinstance(item.expr, A.WindowCall) or scope.has(nm, None):
            # window outputs AND items an upstream aggregate already
            # computed under this name (SELECT mixing sum(..) with
            # rank() OVER: the agg stage ran first) pass through
            f = scope.resolve(nm, None)
            exprs.append(fcol(f.name, f.dtype))
            fields.append(Field(nm, f.dtype))
        else:
            fe = lower_e(item.expr)
            exprs.append(falias(fe, nm))
            fields.append(Field(nm, _dt_of(fe)))
    n_visible = len(fields)
    for s in sel.order_by:
        # ORDER BY a grouping column the SELECT list dropped (q12):
        # hidden carrier, projected away by _order_limit
        e = s.expr
        if isinstance(e, A.Col) and \
                not any(f.name == e.name for f in fields) and \
                scope.has(e.name, None):
            f = scope.resolve(e.name, None)
            exprs.append(fcol(f.name, f.dtype, f.nullable))
            fields.append(Field(f.name, f.dtype))
    out = Schema(tuple(fields))
    node = ForeignNode("ProjectExec", children=(rel.node,), output=out,
                       attrs={"project_list": exprs})
    return Rel(node, Scope([(None, f) for f in out.fields]), False,
               visible=n_visible if len(fields) > n_visible else None)


def _disjuncts(e: A.Expr) -> List[A.Expr]:
    if isinstance(e, A.Bin) and e.op == "or":
        return _disjuncts(e.left) + _disjuncts(e.right)
    return [e]


def _has_subquery(e: A.Expr) -> bool:
    return any(isinstance(x, (A.Exists, A.InSubquery, A.ScalarSubquery))
               for x in _walk(e)) or \
        isinstance(e, (A.Exists, A.InSubquery, A.ScalarSubquery))


def _outer_cols(e: A.Expr, sub_scope: Scope, outer: Scope) -> List[A.Col]:
    """Columns in `e` that resolve in the OUTER scope but not the
    subquery's own — the correlation references."""
    out = []
    for c in _expr_cols(e):
        if not sub_scope.has(c.name, c.table) and \
                outer.has(c.name, c.table):
            out.append(c)
    return out


def _existence_join(rel: Rel, sub: Rel, lks, rks, name: str,
                    ctx: _Ctx) -> Rel:
    """Left-existence join: keep every left row, add a bool column
    `name` that says whether a right match exists (Spark's
    ExistenceJoin, the join type OR-of-subquery predicates plan to)."""
    ex_field = Field(name, BOOL, nullable=False)
    out = Schema(tuple(f for _, f in rel.scope.cols) + (ex_field,))
    attrs = {"left_keys": lks, "right_keys": rks,
             "join_type": "ExistenceJoin", "existence_name": name}
    if sub.broadcastable:
        bx = ForeignNode("BroadcastExchangeExec", children=(sub.node,),
                         output=sub.node.output)
        node = ForeignNode(
            "BroadcastHashJoinExec", children=(rel.node, bx), output=out,
            attrs={**attrs, "build_side": "right"})
    else:
        node = ForeignNode(
            "SortMergeJoinExec",
            children=(_hash_exchange(rel.node, lks, ctx),
                      _hash_exchange(sub.node, rks, ctx)),
            output=out, attrs=attrs)
    scope = Scope(rel.scope.cols + [(None, ex_field)],
                  rel.scope.aliases)
    return Rel(node, scope, False)


def _restore_scope(rel: Rel, orig: Scope) -> Rel:
    """Project away helper columns (existence flags, decorrelation
    keys), restoring the pre-predicate scope."""
    proj = [fcol(f.name, f.dtype, f.nullable) for _, f in orig.cols]
    node = ForeignNode("ProjectExec", children=(rel.node,),
                       output=orig.schema(),
                       attrs={"project_list": proj})
    return Rel(node, orig, False)


def _lower_or_subquery_pred(f: A.Expr, rel: Rel,
                            ctx: _Ctx) -> Optional[Rel]:
    """OR with subquery disjuncts: each EXISTS / IN-subquery leaf
    becomes an existence join contributing a bool column, then one
    filter ORs the columns together (how Spark plans disjunctive
    subquery predicates — ExistenceJoin instead of semi/anti)."""
    leaves = _disjuncts(f)
    if len(leaves) < 2 or not any(_has_subquery(x) for x in leaves):
        return None
    orig_scope = rel.scope
    conds: List[ForeignExpr] = []
    for leaf in leaves:
        neg = False
        x = leaf
        if isinstance(x, A.Un) and x.op == "not":
            neg = True
            x = x.child
        if isinstance(x, A.InSubquery):
            sub = _lower_select(x.query, ctx)
            if len(sub.scope.cols) != 1:
                raise SqlError("IN subquery must produce one column")
            sub = _avoid_collisions(rel.scope, sub, ctx)
            lk = _lower_expr(x.child, rel.scope, ctx)
            rf = sub.scope.cols[0][1]
            anti = bool(x.negated) != neg
            if anti:
                # three-valued NOT IN inside an OR: a NULL in the
                # subquery makes the arm UNKNOWN for every row, and a
                # NULL probe key can never pass — eager null probe
                # (same policy as the conjunctive NOT IN path below)
                probe = ForeignNode(
                    "GlobalLimitExec",
                    children=(ForeignNode(
                        "FilterExec", children=(sub.node,),
                        output=sub.node.output,
                        attrs={"condition": fcall(
                            "IsNull", fcol(rf.name, rf.dtype),
                            dtype=BOOL)}),),
                    output=sub.node.output, attrs={"limit": 1})
                if ctx.execute_subplan(probe).num_rows > 0:
                    conds.append(flit(False, BOOL))
                    continue
            nm = ctx.fresh("ex")
            rel = _existence_join(rel, sub, [lk],
                                  [fcol(rf.name, rf.dtype)], nm, ctx)
            c: ForeignExpr = fcol(nm, BOOL, False)
            if anti:
                c = fcall("And",
                          fcall("IsNotNull", lk, dtype=BOOL),
                          fcall("Not", c, dtype=BOOL), dtype=BOOL)
            conds.append(c)
        elif isinstance(x, A.Exists):
            sub, lks, rks = _decorrelate_exists(x.query, rel, ctx)
            sub = _avoid_collisions(rel.scope, sub, ctx)
            rks = [fcol(f.name, f.dtype) for _, f in sub.scope.cols]
            nm = ctx.fresh("ex")
            rel = _existence_join(rel, sub, lks, rks, nm, ctx)
            c = fcol(nm, BOOL, False)
            if bool(x.negated) != neg:
                c = fcall("Not", c, dtype=BOOL)
            conds.append(c)
        else:
            conds.append(_lower_expr(leaf, rel.scope, ctx))
    cond = conds[0]
    for c in conds[1:]:
        cond = fcall("Or", cond, c, dtype=BOOL)
    node = ForeignNode("FilterExec", children=(rel.node,),
                       output=rel.node.output,
                       attrs={"condition": cond})
    return _restore_scope(Rel(node, rel.scope, False), orig_scope)


def _decorrelate_exists(sub_sel: A.Select, rel: Rel,
                        ctx: _Ctx) -> Tuple[Rel, List[ForeignExpr],
                                            List[ForeignExpr]]:
    """Pull the correlating equalities out of an EXISTS body; returns
    (lowered subquery projecting the correlation keys, outer keys,
    placeholder right keys — callers re-derive rks after collision
    renames)."""
    outer_eq: List[Tuple[A.Expr, A.Expr]] = []
    residual: List[A.Expr] = []
    sub_scope = _probe_scope(sub_sel, ctx)
    for c in _conjuncts(sub_sel.where):
        if isinstance(c, A.Bin) and c.op == "==":
            a, b = c.left, c.right
            if _refs_only(a, rel.scope) and _refs_only(b, sub_scope) \
                    and _outer_cols(b, sub_scope, rel.scope) == []:
                outer_eq.append((a, b))
                continue
            if _refs_only(b, rel.scope) and _refs_only(a, sub_scope) \
                    and _outer_cols(a, sub_scope, rel.scope) == []:
                outer_eq.append((b, a))
                continue
        residual.append(c)
    if not outer_eq:
        raise SqlError("EXISTS without a correlating equality is "
                       "not supported")
    inner_sel = A.Select(
        items=tuple(A.SelectItem(expr=b, alias=f"__ck{i}")
                    for i, (_, b) in enumerate(outer_eq)),
        from_=sub_sel.from_,
        where=_and_all(residual), ctes=sub_sel.ctes)
    sub = _lower_select(inner_sel, ctx)
    lks = [_lower_expr(a, rel.scope, ctx) for a, _ in outer_eq]
    rks = [fcol(f.name, f.dtype) for _, f in sub.scope.cols]
    return sub, lks, rks


def _subst(e: A.Expr, mapping: List[Tuple[A.Col, A.Expr]]) -> A.Expr:
    """Replace outer-column refs with their inner equivalents (from the
    correlating equalities) — nested subquery bodies are left alone."""
    import dataclasses
    if isinstance(e, A.Col):
        for a, b in mapping:
            if e == a:
                return b
        return e
    if not dataclasses.is_dataclass(e) or \
            isinstance(e, (A.Exists, A.ScalarSubquery, A.InSubquery)):
        return e
    changes = {}
    for fl in dataclasses.fields(e):
        v = getattr(e, fl.name)
        if isinstance(v, A.Expr):
            nv = _subst(v, mapping)
            if nv is not v:
                changes[fl.name] = nv
        elif isinstance(v, tuple):
            nv = tuple(_subst(x, mapping) if isinstance(x, A.Expr)
                       else x for x in v)
            if nv != v:
                changes[fl.name] = nv
    return dataclasses.replace(e, **changes) if changes else e


def _decorrelate_scalar(sq: A.ScalarSubquery, rel: Rel,
                        ctx: _Ctx) -> Optional[Rel]:
    """Decorrelate one correlated scalar subquery (single aggregate,
    no GROUP BY): group the subquery on its correlation keys, join on
    the outer sides, and register the agg output column as the
    subquery's substitution — Spark's
    RewriteCorrelatedScalarSubquery.  Inner join drops outer rows with
    no group, matching NULL-comparison semantics for the agg result.
    Correlating equalities may also live under an OR when every
    disjunct carries them (q41's shape): they factor out, and the
    outer refs in the residual are substituted with their inner
    equivalents."""
    sub_sel = sq.query
    if len(sub_sel.items) != 1 or sub_sel.group_by or \
            not _has_agg(sub_sel.items[0].expr):
        return None
    try:
        sub_scope = _probe_scope(sub_sel, ctx)
    except SqlError:
        return None

    def classify_eq(c):
        if isinstance(c, A.Bin) and c.op == "==":
            a, b = c.left, c.right
            a_out = _outer_cols(a, sub_scope, rel.scope)
            b_out = _outer_cols(b, sub_scope, rel.scope)
            if a_out and not b_out and isinstance(a, A.Col) and all(
                    sub_scope.has(x.name, x.table)
                    for x in _expr_cols(b)):
                return a, b
            if b_out and not a_out and isinstance(b, A.Col) and all(
                    sub_scope.has(x.name, x.table)
                    for x in _expr_cols(a)):
                return b, a
        return None

    corr: List[Tuple[A.Col, A.Expr]] = []
    residual: List[A.Expr] = []
    for c in _conjuncts(sub_sel.where):
        pair = classify_eq(c)
        if pair is not None:
            if pair not in corr:
                corr.append(pair)
            continue
        if isinstance(c, A.Bin) and c.op == "or":
            # factor correlating equalities every disjunct shares
            per = [[classify_eq(x) for x in _conjuncts(d)]
                   for d in _disjuncts(c)]
            common = [p for p in (per[0] or [])
                      if p is not None and
                      all(p in ps for ps in per[1:])]
            for p in common:
                if p not in corr:
                    corr.append(p)
        residual.append(c)
    if not corr:
        return None              # uncorrelated: eager path handles it
    # outer refs surviving in the residual rewrite to their inner
    # equivalents; anything else is a correlation we cannot handle
    residual = [_subst(c, corr) for c in residual]
    for c in residual + [_subst(sub_sel.items[0].expr, corr)]:
        if _outer_cols(c, sub_scope, rel.scope):
            return None
    inner_sel = A.Select(
        items=tuple(A.SelectItem(expr=b, alias=f"__ck{i}")
                    for i, (_, b) in enumerate(corr)) +
        (A.SelectItem(expr=_subst(sub_sel.items[0].expr, corr),
                      alias="__sv"),),
        from_=sub_sel.from_, where=_and_all(residual),
        group_by=tuple(b for _, b in corr), ctes=sub_sel.ctes)
    sub = _lower_select(inner_sel, ctx)
    sub = _avoid_collisions(rel.scope, sub, ctx)
    lks = [_lower_expr(a, rel.scope, ctx) for a, _ in corr]
    rks = [fcol(f.name, f.dtype) for _, f in sub.scope.cols[:-1]]
    # count's empty-group result is 0, not NULL: outer rows with no
    # matching group must survive with 0 (Spark special-cases count in
    # RewriteCorrelatedScalarSubquery via left join + coalesce)
    item = sub_sel.items[0].expr
    is_count = isinstance(item, A.Call) and item.name.lower() == "count"
    sv = sub.scope.cols[-1][1]
    if is_count:
        joined = _join(rel, sub, "left", lks, rks, ctx)
        ctx.scalar_subst[id(sq)] = fcall(
            "Coalesce", fcol(sv.name, sv.dtype), flit(0, sv.dtype))
    else:
        joined = _join(rel, sub, "inner", lks, rks, ctx)
        ctx.scalar_subst[id(sq)] = fcol(sv.name, sv.dtype)
    return joined


def _lower_corr_scalar_cmp(f: A.Expr, rel: Rel,
                           ctx: _Ctx) -> Optional[Rel]:
    """A WHERE conjunct containing correlated scalar subqueries
    anywhere in its expression tree (x > 1.2 * (SELECT avg(..) ..)):
    decorrelate each into a joined column, then lower the conjunct
    with those columns substituted."""
    sqs = [x for x in _walk(f) if isinstance(x, A.ScalarSubquery)]
    if isinstance(f, A.ScalarSubquery):
        sqs.append(f)
    correlated = []
    for sq in sqs:
        try:
            sub_scope = _probe_scope(sq.query, ctx)
        except SqlError:
            continue
        outer = False
        for c in _conjuncts(sq.query.where):
            if _outer_cols(c, sub_scope, rel.scope):
                outer = True
        if outer:
            correlated.append(sq)
    if not correlated:
        return None
    orig_scope = rel.scope
    for sq in correlated:
        nxt = _decorrelate_scalar(sq, rel, ctx)
        if nxt is None:
            return None
        rel = nxt
    cond = _lower_expr(f, rel.scope, ctx)
    node = ForeignNode("FilterExec", children=(rel.node,),
                       output=rel.node.output,
                       attrs={"condition": cond})
    return _restore_scope(Rel(node, rel.scope, False), orig_scope)


def _lower_subquery_pred(f: A.Expr, rel: Rel,
                         ctx: _Ctx) -> Optional[Rel]:
    r = _lower_or_subquery_pred(f, rel, ctx)
    if r is not None:
        return r
    r = _lower_corr_scalar_cmp(f, rel, ctx)
    if r is not None:
        return r
    neg = False
    inner = f
    if isinstance(inner, A.Un) and inner.op == "not":
        neg = True
        inner = inner.child
    if isinstance(inner, A.InSubquery):
        sub = _lower_select(inner.query, ctx)
        if len(sub.scope.cols) != 1:
            raise SqlError("IN subquery must produce one column")
        lk = _lower_expr(inner.child, rel.scope, ctx)
        rf = sub.scope.cols[0][1]
        anti = bool(inner.negated) != neg
        if anti:
            # SQL three-valued NOT IN: any NULL in the subquery makes
            # the predicate UNKNOWN for every row (zero rows out), and
            # a NULL probe key can never pass.  Eager null probe
            # (plan-time, like scalar subqueries), then a null-safe
            # anti join.
            probe = ForeignNode(
                "GlobalLimitExec",
                children=(ForeignNode(
                    "FilterExec", children=(sub.node,),
                    output=sub.node.output,
                    attrs={"condition": fcall(
                        "IsNull", fcol(rf.name, rf.dtype),
                        dtype=BOOL)}),),
                output=sub.node.output, attrs={"limit": 1})
            if ctx.execute_subplan(probe).num_rows > 0:
                false_node = ForeignNode(
                    "FilterExec", children=(rel.node,),
                    output=rel.node.output,
                    attrs={"condition": flit(False, BOOL)})
                return Rel(false_node, rel.scope, rel.broadcastable)
            notnull = ForeignNode(
                "FilterExec", children=(rel.node,),
                output=rel.node.output,
                attrs={"condition": fcall("IsNotNull", lk, dtype=BOOL)})
            rel = Rel(notnull, rel.scope, rel.broadcastable)
        return _semi_anti_join(rel, sub, [lk],
                               [fcol(rf.name, rf.dtype)], anti, ctx)
    if isinstance(inner, A.Exists):
        sub_sel = inner.query
        outer_eq: List[Tuple[A.Expr, A.Expr]] = []
        outer_neq: List[Tuple[A.Expr, A.Expr]] = []
        residual: List[A.Expr] = []
        sub_scope = _probe_scope(sub_sel, ctx)
        for c in _conjuncts(sub_sel.where):
            if isinstance(c, A.Bin) and c.op in ("==", "!="):
                a, b = c.left, c.right
                if _refs_only(a, rel.scope) and _refs_only(b, sub_scope):
                    (outer_eq if c.op == "==" else
                     outer_neq).append((a, b))
                    continue
                if _refs_only(b, rel.scope) and _refs_only(a, sub_scope):
                    (outer_eq if c.op == "==" else
                     outer_neq).append((b, a))
                    continue
            residual.append(c)
        if not outer_eq:
            raise SqlError("EXISTS without a correlating equality is "
                           "not supported")
        anti = bool(inner.negated) != neg
        if outer_neq:
            # correlated inequality (q16: cs1.cs_warehouse_sk <>
            # cs2.cs_warehouse_sk): a differing row exists iff the
            # per-key min or max of the inner side differs from the
            # outer value — group the subquery and compare
            if anti:
                raise SqlError("NOT EXISTS with a correlated "
                               "inequality is not supported")
            items = [A.SelectItem(expr=b, alias=f"__ck{i}")
                     for i, (_, b) in enumerate(outer_eq)]
            for j, (_, ie) in enumerate(outer_neq):
                items.append(A.SelectItem(
                    expr=A.Call(name="min", args=(ie,)),
                    alias=f"__mn{j}"))
                items.append(A.SelectItem(
                    expr=A.Call(name="max", args=(ie,)),
                    alias=f"__mx{j}"))
            inner_sel = A.Select(
                items=tuple(items), from_=sub_sel.from_,
                where=_and_all(residual),
                group_by=tuple(b for _, b in outer_eq),
                ctes=sub_sel.ctes)
            sub = _lower_select(inner_sel, ctx)
            sub = _avoid_collisions(rel.scope, sub, ctx)
            orig_scope = rel.scope
            lks = [_lower_expr(a, rel.scope, ctx) for a, _ in outer_eq]
            n_k = len(outer_eq)
            rks = [fcol(f.name, f.dtype)
                   for _, f in sub.scope.cols[:n_k]]
            joined = _join(rel, sub, "inner", lks, rks, ctx)
            conds = []
            for j, (oe, _) in enumerate(outer_neq):
                o_fe = _lower_expr(oe, joined.scope, ctx)
                mn = sub.scope.cols[n_k + 2 * j][1]
                mx = sub.scope.cols[n_k + 2 * j + 1][1]
                conds.append(fcall(
                    "Or",
                    fcall("Not", fcall("EqualTo", o_fe,
                                       fcol(mn.name, mn.dtype),
                                       dtype=BOOL), dtype=BOOL),
                    fcall("Not", fcall("EqualTo", o_fe,
                                       fcol(mx.name, mx.dtype),
                                       dtype=BOOL), dtype=BOOL),
                    dtype=BOOL))
            cond = conds[0]
            for c in conds[1:]:
                cond = fcall("And", cond, c, dtype=BOOL)
            node = ForeignNode("FilterExec", children=(joined.node,),
                               output=joined.node.output,
                               attrs={"condition": cond})
            return _restore_scope(Rel(node, joined.scope, False),
                                  orig_scope)
        inner_sel = A.Select(
            items=tuple(A.SelectItem(expr=b, alias=f"__ck{i}")
                        for i, (_, b) in enumerate(outer_eq)),
            from_=sub_sel.from_,
            where=_and_all(residual), ctes=sub_sel.ctes)
        sub = _lower_select(inner_sel, ctx)
        lks = [_lower_expr(a, rel.scope, ctx) for a, _ in outer_eq]
        rks = [fcol(f.name, f.dtype) for _, f in sub.scope.cols]
        return _semi_anti_join(rel, sub, lks, rks, anti, ctx)
    return None


def _probe_scope(sel: A.Select, ctx: _Ctx) -> Scope:
    """Scope of a subquery's FROM for decorrelation classification —
    schema-only (no scan/join node construction, no fresh-name burn;
    the real lowering happens once the conjuncts are classified)."""
    return _scope_of_from(sel.from_, ctx)


def _scope_of_from(t: Optional[A.TableRef], ctx: _Ctx) -> Scope:
    if isinstance(t, A.BaseTable):
        if t.name in ctx.ctes or t.name not in ctx.catalog.tables:
            # CTE / unknown: fall back to full lowering (rare path)
            return _lower_from(t, _Ctx(catalog=ctx.catalog,
                                       ctes=ctx.ctes,
                                       n_parts=ctx.n_parts), []).scope
        qual = t.alias or t.name
        return Scope([(qual, f)
                      for f in ctx.catalog.tables[t.name].schema.fields])
    if isinstance(t, A.Join):
        left = _scope_of_from(t.left, ctx)
        right = _scope_of_from(t.right, ctx)
        return Scope(left.cols + right.cols)
    if isinstance(t, A.SubqueryTable):
        rel = _lower_select(t.query, _Ctx(catalog=ctx.catalog,
                                          ctes=ctx.ctes,
                                          n_parts=ctx.n_parts))
        return Scope([(t.alias, f) for _, f in rel.scope.cols])
    raise SqlError("unsupported FROM element in subquery")


def _and_all(cs: List[A.Expr]) -> Optional[A.Expr]:
    if not cs:
        return None
    e = cs[0]
    for c in cs[1:]:
        e = A.Bin(op="and", left=e, right=c)
    return e


def _so(fe: ForeignExpr, s: A.SortItem) -> ForeignExpr:
    return ForeignExpr(
        "SortOrder", children=(fe,),
        attrs={"asc": s.asc,
               "nulls_first": s.asc if s.nulls_first is None
               else s.nulls_first})


def _order_limit(rel: Rel, sel: A.Select, ctx: _Ctx) -> Rel:
    if not sel.order_by and sel.limit is None:
        return rel
    fields = [f for _, f in rel.scope.cols]

    def resolve_order(s: A.SortItem) -> ForeignExpr:
        e = s.expr
        if isinstance(e, A.Lit) and e.kind == "int":
            if not 1 <= e.value <= len(fields):
                raise SqlError(
                    f"ORDER BY ordinal {e.value} out of range 1.."
                    f"{len(fields)}")
            f = fields[e.value - 1]
            return _so(fcol(f.name, f.dtype), s)
        # ORDER BY an expression the SELECT list already computed
        # (ORDER BY sum(x) after GROUP BY): sort on its output column
        for i, item in enumerate(sel.items):
            if item.expr == e:
                nm = _item_name(item, i)
                if rel.scope.has(nm, None):
                    f = rel.scope.resolve(nm, None)
                    return _so(fcol(f.name, f.dtype), s)
        return _so(_lower_expr(_requal(e, rel.scope), rel.scope, ctx),
                   s)

    vis = fields if rel.visible is None else fields[:rel.visible]
    vis_scope = Scope([(None, f) for f in vis]) \
        if rel.visible is not None else rel.scope
    if sel.order_by and sel.limit is not None:
        orders = [resolve_order(s) for s in sel.order_by]
        node = ForeignNode(
            "TakeOrderedAndProjectExec", children=(rel.node,),
            output=vis_scope.schema(),
            attrs={"sort_order": orders, "limit": sel.limit,
                   "project_list": [fcol(f.name, f.dtype)
                                    for f in vis]})
        return Rel(node, vis_scope, False)
    if sel.order_by:
        orders = [resolve_order(s) for s in sel.order_by]
        ex = ForeignNode(
            "ShuffleExchangeExec", children=(rel.node,),
            output=rel.node.output,
            attrs={"partitioning": {"mode": "single",
                                    "num_partitions": 1}})
        node = ForeignNode("SortExec", children=(ex,),
                           output=rel.scope.schema(),
                           attrs={"sort_order": orders})
        if rel.visible is not None:
            node = ForeignNode(
                "ProjectExec", children=(node,),
                output=vis_scope.schema(),
                attrs={"project_list": [fcol(f.name, f.dtype)
                                        for f in vis]})
        return Rel(node, vis_scope, False)
    node = ForeignNode("GlobalLimitExec", children=(rel.node,),
                       output=rel.scope.schema(),
                       attrs={"limit": sel.limit})
    if rel.visible is not None:
        node = ForeignNode(
            "ProjectExec", children=(node,), output=vis_scope.schema(),
            attrs={"project_list": [fcol(f.name, f.dtype)
                                    for f in vis]})
    return Rel(node, vis_scope, False)


# ---------------------------------------------------------------------------
# scalar subqueries (uncorrelated): eager evaluation, Spark-style
# ---------------------------------------------------------------------------

def _eval_scalar_subquery(q: A.Select, ctx: _Ctx):
    key = ("scalar", q)
    if key in ctx.subquery_cache:
        return ctx.subquery_cache[key]
    rel = _lower_select(q, ctx)
    if len(rel.scope.cols) != 1:
        raise SqlError("scalar subquery must produce one column")
    table = ctx.execute_subplan(rel.node)
    if table.num_rows > 1:
        raise SqlError("scalar subquery returned more than one row")
    f = rel.scope.cols[0][1]
    value = table.column(0)[0].as_py() if table.num_rows else None
    ctx.subquery_cache[key] = (value, f.dtype)
    return value, f.dtype


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

def plan_sql(sql: str, catalog, n_parts: int = 4) -> ForeignNode:
    """SQL text -> foreign physical plan over `catalog` (it.datagen
    Catalog or any object with `.tables: {name: TableDef}` and
    `.scan(name, columns=None, pushed_filters=())`)."""
    ast = A.parse_sql(sql)
    ctx = _Ctx(catalog=catalog, n_parts=n_parts)
    return _lower_select(ast, ctx).node
