"""CLI: a standalone SQL shell over the engine.

    python -m auron_tpu.sql --data-dir /tmp/tpcds "select ..."
    python -m auron_tpu.sql --data-dir /tmp/tpcds        # interactive

Queries parse/plan through auron_tpu.sql and execute on the native
engine (conversion strategy + SPMD stage compiler, exactly the corpus
path).  The standalone face of the reference's spark-sql front door.
"""

from __future__ import annotations

import argparse
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser(prog="auron_tpu.sql")
    ap.add_argument("query", nargs="?", default=None,
                    help="SQL text (omit for an interactive shell)")
    ap.add_argument("--data-dir", default="/tmp/auron_tpcds")
    ap.add_argument("--sf", type=float, default=0.01,
                    help="generate TPC-DS subset data at this scale if "
                         "the data dir is empty")
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--explain", action="store_true",
                    help="print the lowered foreign plan instead of "
                         "executing")
    args = ap.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    from auron_tpu.frontend.session import AuronSession
    from auron_tpu.it.datagen import generate
    from auron_tpu.it.oracle import PyArrowEngine
    from auron_tpu.sql import plan_sql
    from auron_tpu.sql.parser import SqlError

    cat = generate(args.data_dir, sf=args.sf)

    def run_one(sql: str) -> int:
        try:
            plan = plan_sql(sql, cat)
        except SqlError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if args.explain:
            _render(plan)
            return 0
        session = AuronSession(foreign_engine=PyArrowEngine())
        t0 = time.perf_counter()
        res = session.execute(plan)
        dt = time.perf_counter() - t0
        print(res.table.to_pandas().to_string(index=False,
                                              max_rows=100))
        print(f"-- {res.table.num_rows} rows in {dt:.3f}s "
              f"(native={'yes' if res.all_native() else 'PARTIAL'}, "
              f"spmd={'yes' if res.spmd else 'no'})")
        return 0

    if args.query:
        return run_one(args.query)
    print("auron sql shell — ; to run, \\q to quit")
    buf: list = []
    for line in sys.stdin:
        if line.strip() == "\\q":
            break
        buf.append(line)
        if line.rstrip().endswith(";"):
            sql = "".join(buf).rstrip().rstrip(";")
            buf = []
            if sql.strip():
                run_one(sql)
    return 0


def _render(node, depth: int = 0) -> None:
    print("  " * depth + node.op)
    for c in node.children:
        _render(c, depth + 1)


if __name__ == "__main__":
    sys.exit(main())
