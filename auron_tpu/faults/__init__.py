"""Config-driven, deterministically-seeded fault injection.

The dynamic counterpart of the static plan verifier (auron_tpu.analysis):
the analyzer proves a plan is well-formed, this module proves the runtime
*recovers* when the world is not.  Named `fault_point(...)` call sites are
threaded through every boundary that can fail in production — shuffle
push/fetch, spill write/read, engine-service dispatch, kafka fetch,
operator execute, SPMD stage launch — and a spec string
(`auron.faults.spec`) arms a subset of them with seeded probabilistic
faults, so chaos sweeps (it/stability.py) are exactly reproducible.

Spec grammar (';'-separated rules)::

    spec  := rule (';' rule)*
    rule  := point ':' kind [':' param (',' param)*]
    param := 'p=' float | 'seed=' int | 'max=' int | 'after=' int
           | 'ms=' float | 'bytes=' int | 'frac=' float
    kind  := 'io' | 'timeout' | 'device' | 'error' | 'latency' | 'mem'

e.g. ``shuffle.push:io:p=0.2,seed=7;spill.write:io:p=0.1``.

`point` matches fault-point names exactly or by `fnmatch` glob
(``shuffle.*``).  `p` is the per-invocation injection probability
(default 1.0), `seed` makes the Bernoulli draw sequence deterministic
per rule, `max` caps the total injections a rule may fire (bounds the
blast radius — a sweep can never storm), and `after` skips the first N
matching invocations (deterministically hit "the second push").

Kinds map to exception families the retry policy (runtime/retry.py)
classifies: `io` -> InjectedIOError (retryable-IO, an OSError),
`timeout` -> InjectedTimeout (a TimeoutError/OSError), `device` ->
InjectedDeviceFault (the retry-then-degrade tier: re-execute, then fall
back from SPMD to the serial path), `error` -> InjectedError (a
deterministic RuntimeError — never retried).  `latency` injects
SLOWNESS, not failure: the fault point sleeps `ms` milliseconds
(default 25) and returns normally — the kind that exercises read
timeouts and shows up as stretched span durations in a traced chaos
run (runtime/tracing.py), never as an error.  `mem` injects MEMORY
PRESSURE, not failure: the fault point reserves `bytes` (or
`frac` of the configured budget, default 0.5) out of the global
MemManager's effective budget, so spillable consumers start spilling
— results must stay bit-identical, and the pressure is visible as
`mem.pressure`/`mem.spill` events in a traced run.  Reservations
persist until `reset_manager` (or `release_reservations`) — use
`max=1` to shrink once rather than per matching call.

With the spec unset (the default) `fault_point` is a no-op check: one
config read, no registry, no RNG — cheap enough for per-push/per-task
call sites (the IT_PERF wall-clock gate holds).
"""

from __future__ import annotations

import fnmatch
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from auron_tpu.config import conf
from auron_tpu.runtime import lockcheck

__all__ = [
    "FaultSpecError", "InjectedFault", "InjectedIOError",
    "InjectedTimeout", "InjectedDeviceFault", "InjectedError",
    "InjectedLatency", "InjectedMemPressure", "FaultRule",
    "FaultRegistry", "fault_point", "active_registry",
    "injection_counts", "reset",
]


class FaultSpecError(ValueError):
    """Malformed `auron.faults.spec` string."""


class InjectedFault(Exception):
    """Marker mixin: every injected exception carries the point name."""

    def __init__(self, point: str, message: str):
        super().__init__(message)
        self.fault_point = point


class InjectedIOError(InjectedFault, OSError):
    """Retryable-IO fault (a lost connection, a short write)."""


class InjectedTimeout(InjectedFault, TimeoutError):
    """Retryable timeout fault (TimeoutError is an OSError)."""


class InjectedDeviceFault(InjectedFault, RuntimeError):
    """Device-tier fault: the retry policy re-executes the task, and the
    SPMD driver degrades to the serial per-partition path when it
    persists (the SpmdGuardTripped(retryable=True) family)."""

    auron_retryable = True


class InjectedError(InjectedFault, RuntimeError):
    """Deterministic fault: classified non-retryable (a poison input
    would fail the same way every attempt)."""


class InjectedLatency:
    """NOT an exception: a latency injection is a sleep performed by the
    registry (outside its lock), visible only as stretched wall time —
    and as span durations when the query is traced."""

    def __init__(self, point: str, seconds: float):
        self.fault_point = point
        self.seconds = seconds


class InjectedMemPressure:
    """NOT an exception: a mem injection reserves bytes out of the global
    MemManager's budget (outside the registry lock), forcing spill
    pressure on every consumer — visible as `mem.pressure`/`mem.spill`
    events when the query is traced, never as an error."""

    def __init__(self, point: str, nbytes: Optional[int], frac: float):
        self.fault_point = point
        self.nbytes = nbytes
        self.frac = frac

    def apply(self) -> None:
        from auron_tpu.memmgr import get_manager
        mgr = get_manager()
        nbytes = self.nbytes if self.nbytes is not None \
            else int(mgr.budget * self.frac)
        mgr.add_reservation(f"fault:{self.fault_point}", nbytes)


_KINDS = {
    "io": InjectedIOError,
    "timeout": InjectedTimeout,
    "device": InjectedDeviceFault,
    "error": InjectedError,
    "latency": None,   # handled in FaultRule.draw (sleep, not raise)
    "mem": None,       # handled in FaultRule.draw (reserve, not raise)
}


@dataclass
class FaultRule:
    """One armed rule; mutable counters live here (lock-guarded by the
    owning registry — call sites run on task-pool threads)."""

    pattern: str
    kind: str
    p: float = 1.0
    seed: int = 0
    max_injections: Optional[int] = None
    after: int = 0
    delay_ms: float = 25.0   # latency kind: injected sleep
    mem_bytes: Optional[int] = None   # mem kind: reservation size
    mem_frac: float = 0.5    # mem kind: budget fraction when bytes unset
    # counters (registry lock held)
    calls: int = 0
    injected: int = 0
    _rng: random.Random = field(default_factory=random.Random, repr=False)

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise FaultSpecError(
                f"unknown fault kind {self.kind!r} for {self.pattern!r} "
                f"(expected one of {sorted(_KINDS)})")
        if not 0.0 <= self.p <= 1.0:
            raise FaultSpecError(
                f"fault probability p={self.p} for {self.pattern!r} "
                f"outside [0, 1]")
        self._rng = random.Random(self.seed)

    def matches(self, point: str) -> bool:
        return point == self.pattern or \
            fnmatch.fnmatchcase(point, self.pattern)

    def draw(self, point: str) -> Optional[InjectedFault]:
        """One matching invocation: advance the deterministic Bernoulli
        stream and return the fault to raise, or None."""
        self.calls += 1
        if self.calls <= self.after:
            return None
        if self.max_injections is not None and \
                self.injected >= self.max_injections:
            return None
        # the draw advances the stream even when p == 1 so `max`/`after`
        # edits never shift sibling rules' sequences (each rule owns its
        # own RNG)
        if self._rng.random() >= self.p:
            return None
        self.injected += 1
        if self.kind == "latency":
            return InjectedLatency(point, self.delay_ms / 1000.0)
        if self.kind == "mem":
            return InjectedMemPressure(point, self.mem_bytes,
                                       self.mem_frac)
        exc_type = _KINDS[self.kind]
        return exc_type(
            point,
            f"injected {self.kind} fault at {point!r} "
            f"(rule {self.pattern!r}, injection #{self.injected})")

    def reset(self) -> None:
        self.calls = 0
        self.injected = 0
        self._rng = random.Random(self.seed)


def parse_spec(spec: str) -> List[FaultRule]:
    """Parse the `auron.faults.spec` grammar; raises FaultSpecError with
    the offending fragment on malformed input."""
    rules: List[FaultRule] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) < 2 or len(parts) > 3 or not parts[0].strip():
            raise FaultSpecError(
                f"bad fault rule {raw!r} (expected "
                f"'point:kind[:p=..,seed=..,max=..,after=..]')")
        kw: Dict[str, object] = {}
        if len(parts) == 3 and parts[2].strip():
            for p in parts[2].split(","):
                if "=" not in p:
                    raise FaultSpecError(
                        f"bad fault param {p!r} in rule {raw!r}")
                key, _, val = p.partition("=")
                key = key.strip()
                try:
                    if key == "p":
                        kw["p"] = float(val)
                    elif key == "seed":
                        kw["seed"] = int(val)
                    elif key == "max":
                        kw["max_injections"] = int(val)
                    elif key == "after":
                        kw["after"] = int(val)
                    elif key == "ms":
                        kw["delay_ms"] = float(val)
                    elif key == "bytes":
                        kw["mem_bytes"] = int(val)
                    elif key == "frac":
                        kw["mem_frac"] = float(val)
                    else:
                        raise FaultSpecError(
                            f"unknown fault param {key!r} in rule {raw!r}")
                except ValueError as e:
                    if isinstance(e, FaultSpecError):
                        raise
                    raise FaultSpecError(
                        f"bad value for {key!r} in rule {raw!r}: {val!r}"
                    ) from e
        rules.append(FaultRule(pattern=parts[0].strip(),
                               kind=parts[1].strip(), **kw))
    return rules


class FaultRegistry:
    """Armed rules for one spec string; counters survive across queries
    of a sweep (reset() starts a fresh deterministic sequence)."""

    def __init__(self, spec: str):
        self.spec = spec
        self.rules = parse_spec(spec)
        self._lock = lockcheck.Lock("faults.registry")

    def check(self, point: str) -> None:
        sleeps = []
        reservations = []
        with self._lock:
            for rule in self.rules:
                if not rule.matches(point):
                    continue
                fault = rule.draw(point)
                if isinstance(fault, InjectedLatency):
                    # sleep OUTSIDE the lock: a latency rule must slow
                    # the matching call site, not serialize every fault
                    # point in the process behind it
                    sleeps.append(fault.seconds)
                elif isinstance(fault, InjectedMemPressure):
                    # applied OUTSIDE the lock: the reservation takes the
                    # MemManager lock, and a consumer spill re-entering a
                    # fault point must never deadlock on the registry
                    reservations.append(fault)
                elif fault is not None:
                    raise fault
        for r in reservations:
            r.apply()
        for s in sleeps:
            # PR 4 deliberately moved this sleep OUTSIDE the registry
            # lock (a latency rule slows the matching call site, never
            # every fault point in the process); the blocked() check
            # pins that — were the sleep hoisted back under _lock, it
            # would fire with "faults.registry" held
            lockcheck.blocked("faults.latency.sleep")
            time.sleep(s)

    def counts(self) -> Dict[str, Tuple[int, int]]:
        """pattern -> (matching calls, injections fired)."""
        with self._lock:
            return {r.pattern: (r.calls, r.injected) for r in self.rules}

    def injected_total(self) -> int:
        with self._lock:
            return sum(r.injected for r in self.rules)

    def reset(self) -> None:
        with self._lock:
            for r in self.rules:
                r.reset()


# one registry per distinct spec string: `conf.scoped` re-entry of the
# same spec keeps the rule counters/RNG streams (a sweep is one
# deterministic sequence), while editing the spec re-arms fresh
_REGISTRIES: Dict[str, FaultRegistry] = {}
_REG_LOCK = lockcheck.Lock("faults.registries")


def _registry_for(spec: str) -> FaultRegistry:
    reg = _REGISTRIES.get(spec)
    if reg is None:
        with _REG_LOCK:
            reg = _REGISTRIES.get(spec)
            if reg is None:
                reg = _REGISTRIES[spec] = FaultRegistry(spec)
    return reg


def fault_point(point: str) -> None:
    """Named injection site.  No-op (one config read) unless
    `auron.faults.spec` arms a rule matching `point`.

    Every fault point is by construction a boundary that can block or
    fail in production (shuffle push/fetch, spill IO, service dispatch,
    kafka RPCs), so each doubles as a blocking-under-lock probe for the
    concurrency checker — one flag read when lockcheck is off."""
    lockcheck.blocked(point)
    spec = conf.get("auron.faults.spec")
    if not spec:
        return
    _registry_for(spec).check(point)


def registry_for(spec: str) -> FaultRegistry:
    """The (cached) registry for a spec string — chaos harness hook."""
    return _registry_for(spec)


def active_registry() -> Optional[FaultRegistry]:
    """The registry for the currently-configured spec, or None."""
    spec = conf.get("auron.faults.spec")
    return _registry_for(spec) if spec else None


def injection_counts() -> Dict[str, Tuple[int, int]]:
    reg = active_registry()
    return reg.counts() if reg is not None else {}


def reset(spec: Optional[str] = None) -> None:
    """Restart the deterministic sequence: the given spec's registry (or
    the active one); with no active spec, drop every cached registry."""
    if spec is not None:
        with _REG_LOCK:
            _REGISTRIES.pop(spec, None)
        return
    reg = active_registry()
    if reg is not None:
        reg.reset()
    else:
        with _REG_LOCK:
            _REGISTRIES.clear()
