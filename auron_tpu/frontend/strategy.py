"""Convertibility tagging over a foreign plan.

Analogue of AuronConvertStrategy (spark-extension/.../
AuronConvertStrategy.scala:38-296): every node gets a convert strategy in
{DEFAULT, ALWAYS_CONVERT, NEVER_CONVERT}; the pass runs (1) a bottom-up
dry-run conversion filling the convertible tag, (2) childOrderingRequired
propagation, (3) the anti-thrash `remove_inefficient_converts` fixpoint
(:201-283), then (4) the per-op AlwaysConvert rules (:122-190).
"""

from __future__ import annotations

import enum
import logging
from typing import Dict, Optional

from auron_tpu import config
from auron_tpu.frontend import converters
from auron_tpu.frontend.foreign import ForeignNode

log = logging.getLogger("auron_tpu.frontend")


class ConvertStrategy(enum.Enum):
    DEFAULT = "default"
    ALWAYS_CONVERT = "always_convert"
    NEVER_CONVERT = "never_convert"


_AGG_OPS = {"HashAggregateExec", "ObjectHashAggregateExec",
            "SortAggregateExec"}


class Tags:
    """Per-node tag store keyed by node identity (the TreeNodeTag
    analogue)."""

    def __init__(self) -> None:
        self.strategy: Dict[int, ConvertStrategy] = {}
        self.convertible: Dict[int, bool] = {}
        self.never_reason: Dict[int, str] = {}
        self.child_ordering_required: Dict[int, bool] = {}

    def is_never_convert(self, n: ForeignNode) -> bool:
        return self.strategy.get(id(n)) is ConvertStrategy.NEVER_CONVERT

    def is_always_convert(self, n: ForeignNode) -> bool:
        return self.strategy.get(id(n)) is ConvertStrategy.ALWAYS_CONVERT

    def set_never(self, n: ForeignNode, reason: str) -> None:
        self.strategy[id(n)] = ConvertStrategy.NEVER_CONVERT
        self.never_reason[id(n)] = reason

    def reason(self, n: ForeignNode) -> Optional[str]:
        return self.never_reason.get(id(n))


def apply(plan: ForeignNode) -> Tags:
    tags = Tags()
    plan.foreach(lambda n: (
        tags.strategy.__setitem__(id(n), ConvertStrategy.DEFAULT),
        tags.convertible.__setitem__(id(n), True)))

    # (1) bottom-up convertibility dry-run (:55-76)
    def probe(n: ForeignNode) -> None:
        reason = converters.dry_run_convertible(n)
        if reason is None:
            tags.convertible[id(n)] = True
        else:
            tags.convertible[id(n)] = False
            tags.set_never(n, reason)
    plan.foreach_up(probe)

    # (2) childOrderingRequired propagation (:86-115): foreign nodes
    # declare per-child ordering requirements; SortExec resets it.
    def fill_ordering(n: ForeignNode) -> None:
        required = n.attrs.get("required_child_ordering")
        if required:
            for child, req in zip(n.children, required):
                if req:
                    tags.child_ordering_required[id(child)] = True
    plan.foreach(fill_ordering)

    def propagate_ordering(n: ForeignNode) -> None:
        if n.op == "SortExec":
            tags.child_ordering_required[id(n)] = False
        elif tags.child_ordering_required.get(id(n)):
            for child in n.children:
                tags.child_ordering_required[id(child)] = True
    plan.foreach(propagate_ordering)

    # (3) anti-thrash fixpoint (:201-283)
    _remove_inefficient_converts(plan, tags)

    # (4) per-op AlwaysConvert decisions (:122-190)
    def is_native(n: ForeignNode) -> bool:
        return tags.is_always_convert(n)

    def decide(n: ForeignNode) -> None:
        if tags.is_never_convert(n) or tags.is_always_convert(n):
            return
        op, ch = n.op, n.children
        always = False
        if op == "ShuffleExchangeExec":
            always = not ch or is_native(ch[0]) or ch[0].op not in _AGG_OPS
        elif op in ("BroadcastExchangeExec", "FileSourceScanExec",
                    "LocalTableScanExec", "SortExec"):
            always = True
        elif op in ("ProjectExec", "FilterExec", "LocalLimitExec",
                    "GlobalLimitExec", "TakeOrderedAndProjectExec",
                    "CollectLimitExec", "ExpandExec", "WindowExec",
                    "WindowGroupLimitExec", "GenerateExec",
                    *_AGG_OPS):
            always = bool(ch) and is_native(ch[0])
        elif op == "UnionExec":
            n_native = sum(1 for c in ch if is_native(c))
            n_never = sum(1 for c in ch if tags.is_never_convert(c))
            always = n_native >= n_never
        elif op in ("SortMergeJoinExec", "ShuffledHashJoinExec"):
            always = any(is_native(c) for c in ch)
        elif op == "BroadcastHashJoinExec":
            always = all(is_native(c) for c in ch)
        elif op in ("DataWritingCommandExec", "InsertIntoHiveTableExec"):
            always = bool(ch) and is_native(ch[0])
        elif converters.ext_convert_supported(n):
            always = True
        if always:
            tags.strategy[id(n)] = ConvertStrategy.ALWAYS_CONVERT
        else:
            tags.set_never(n, f"{op} not marked, default to NeverConvert.")
    plan.foreach_up(decide)
    return tags


def _remove_inefficient_converts(plan: ForeignNode, tags: Tags) -> None:
    """The four anti-thrash rules, iterated to fixpoint: converts that
    would introduce a C2N/N2C transition moving many rows get demoted."""
    finished = False
    while not finished:
        finished = True

        def dont_convert_if(n: ForeignNode, cond: bool, reason: str) -> None:
            nonlocal finished
            if cond and not tags.is_never_convert(n):
                tags.set_never(n, reason)
                finished = False

        def visit(n: ForeignNode) -> None:
            # NonNative -> NativeFilter / NativeAgg: needs a bulk C2N
            if not tags.is_never_convert(n) and \
                    n.op in ("FilterExec", *_AGG_OPS) and n.children:
                dont_convert_if(n, tags.is_never_convert(n.children[0]),
                                f"{n.op}, children is not native.")
            # Agg -> NativeShuffle: next stage likely reads non-natively
            if not tags.is_never_convert(n) and \
                    n.op == "ShuffleExchangeExec" and n.children:
                c = n.children[0]
                dont_convert_if(
                    n, c.op in _AGG_OPS and tags.is_never_convert(c),
                    f"{n.op}, children is not native and children is agg.")
            if tags.is_never_convert(n):
                # NativeExpand/NativeScan -> NonNative: needs a bulk N2C
                for c in n.children:
                    if c.op == "ExpandExec":
                        dont_convert_if(c, not tags.is_never_convert(c),
                                        f"{n.op}, children is nativeExpand.")
                    if c.op == "FileSourceScanExec":
                        dont_convert_if(
                            c, not tags.is_never_convert(c),
                            f"{n.op}, children is nativeParquetScan.")
                    # NonNative -> NativeSort -> NonNative sandwich
                    if c.op == "SortExec" and c.children:
                        dont_convert_if(
                            c,
                            not tags.is_never_convert(c) and
                            tags.is_never_convert(c.children[0]),
                            f"{n.op}, children and parent both are "
                            "not native.")
        plan.foreach(visit)
