"""Engine front-end: foreign-plan intake, convert strategy, converters,
session/driver.  The analogue of the reference's L7-L5 JVM layers
(spark-extension: AuronSparkSessionExtension -> AuronConvertStrategy ->
AuronConverters -> Native* wrappers + NativeRDD), re-hosted as an
engine-agnostic python surface over the same plan-IR wire format.
"""

from auron_tpu.frontend.foreign import (ForeignExpr, ForeignNode, falias,
                                        fcall, fcol, flit)
from auron_tpu.frontend.session import AuronSession, SessionResult

__all__ = [
    "AuronSession", "SessionResult", "ForeignExpr", "ForeignNode",
    "fcol", "flit", "falias", "fcall",
]
