"""Foreign (host-engine) physical plan descriptor.

This is the wire boundary a JVM/engine bridge targets: an engine-agnostic,
JSON-able description of an already-optimized physical plan — the stand-in
for `SparkPlan` on the other side of the reference's JNI boundary
(spark-extension/.../AuronConverters.scala receives SparkPlan trees; we
receive `ForeignNode` trees).  A Spark bridge would serialize each AQE
stage's plan to this form; the standalone driver and tests build it
directly.

Ops use the reference's Spark exec-class vocabulary ("ProjectExec",
"ShuffleExchangeExec", ...) so the convert strategy's per-op rules
(AuronConvertStrategy.scala:122-190) carry over one-to-one.  Expressions
use Spark expression-class names ("Add", "AttributeReference", ...)
mirroring NativeConverters.convertExpr's match cases
(NativeConverters.scala:395-1226).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from auron_tpu.ir.schema import DataType, Field, Schema, TypeId


@dataclass
class ForeignExpr:
    """One node of a foreign expression tree.

    `name` = Spark expression class name.  Payload fields:
    - value/dtype: literals, casts
    - attrs: op-specific scalars (e.g. "pattern", "offset", "field")
    - py_fn: optional pickled python callable used by the UDF fallback
      wrapper when this node itself is not convertible (the analogue of the
      reference round-tripping unconvertible exprs to the JVM,
      NativeConverters.scala:277-324).
    """
    name: str
    children: Tuple["ForeignExpr", ...] = ()
    value: Any = None
    dtype: Optional[DataType] = None
    attrs: Dict[str, Any] = field(default_factory=dict)
    py_fn: Optional[bytes] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"e": self.name}
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        if self.value is not None:
            out["value"] = self.value
        if self.dtype is not None:
            out["dtype"] = _dtype_to_str(self.dtype)
        if self.attrs:
            out["attrs"] = self.attrs
        if self.py_fn is not None:
            import base64
            out["py_fn"] = base64.b64encode(self.py_fn).decode("ascii")
        return out

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ForeignExpr":
        py_fn = None
        if "py_fn" in d:
            import base64
            py_fn = base64.b64decode(d["py_fn"])
        return ForeignExpr(
            name=d["e"],
            children=tuple(ForeignExpr.from_dict(c)
                           for c in d.get("children", [])),
            value=d.get("value"),
            dtype=_dtype_from_str(d["dtype"]) if "dtype" in d else None,
            attrs=d.get("attrs", {}),
            py_fn=py_fn)


@dataclass
class ForeignNode:
    """One node of a foreign physical plan.

    `output` is the node's output schema (attribute name -> type), the
    analogue of SparkPlan.output.  `attrs` carries op-specific payloads
    (exprs, join keys, file groups, limits, partitioning...).
    """
    op: str
    children: Tuple["ForeignNode", ...] = ()
    output: Optional[Schema] = None
    attrs: Dict[str, Any] = field(default_factory=dict)

    # -- traversal (SparkPlan.foreach/foreachUp analogues) ----------------

    def foreach(self, fn) -> None:
        fn(self)
        for c in self.children:
            c.foreach(fn)

    def foreach_up(self, fn) -> None:
        for c in self.children:
            c.foreach_up(fn)
        fn(self)

    def pretty(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.op]
        for c in self.children:
            lines.append(c.pretty(indent + 1))
        return "\n".join(lines)

    # -- serde ------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"op": self.op}
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        if self.output is not None:
            out["output"] = [[f.name, _dtype_to_str(f.dtype), f.nullable]
                             for f in self.output.fields]
        if self.attrs:
            out["attrs"] = _encode_attrs(self.attrs)
        return out

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ForeignNode":
        output = None
        if "output" in d:
            output = Schema(tuple(
                Field(n, _dtype_from_str(t), bool(nl))
                for n, t, nl in d["output"]))
        return ForeignNode(
            op=d["op"],
            children=tuple(ForeignNode.from_dict(c)
                           for c in d.get("children", [])),
            output=output,
            attrs=_decode_attrs(d.get("attrs", {})))

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_json(s: str) -> "ForeignNode":
        return ForeignNode.from_dict(json.loads(s))


# ---------------------------------------------------------------------------
# attr encoding: ForeignExpr values inside attrs are tagged so the whole
# plan round-trips through JSON
# ---------------------------------------------------------------------------

def _encode_attrs(v: Any) -> Any:
    if isinstance(v, ForeignExpr):
        return {"@fexpr": v.to_dict()}
    if isinstance(v, ForeignNode):
        return {"@fnode": v.to_dict()}
    if isinstance(v, DataType):
        return {"@dtype": _dtype_to_str(v)}
    if isinstance(v, Schema):
        return {"@schema": [[f.name, _dtype_to_str(f.dtype), f.nullable]
                            for f in v.fields]}
    if isinstance(v, bytes):
        import base64
        return {"@bytes": base64.b64encode(v).decode("ascii")}
    if isinstance(v, dict):
        return {k: _encode_attrs(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_encode_attrs(x) for x in v]
    return v


def _decode_attrs(v: Any) -> Any:
    if isinstance(v, dict):
        if "@fexpr" in v:
            return ForeignExpr.from_dict(v["@fexpr"])
        if "@fnode" in v:
            return ForeignNode.from_dict(v["@fnode"])
        if "@dtype" in v:
            return _dtype_from_str(v["@dtype"])
        if "@schema" in v:
            return Schema(tuple(Field(n, _dtype_from_str(t), bool(nl))
                                for n, t, nl in v["@schema"]))
        if "@bytes" in v:
            import base64
            return base64.b64decode(v["@bytes"])
        return {k: _decode_attrs(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_attrs(x) for x in v]
    return v


# ---------------------------------------------------------------------------
# compact textual type names (Spark DDL-ish), for the JSON form
# ---------------------------------------------------------------------------

_SIMPLE = {
    TypeId.NULL: "null", TypeId.BOOL: "boolean", TypeId.INT8: "tinyint",
    TypeId.INT16: "smallint", TypeId.INT32: "int", TypeId.INT64: "bigint",
    TypeId.FLOAT32: "float", TypeId.FLOAT64: "double",
    TypeId.STRING: "string", TypeId.BINARY: "binary", TypeId.DATE32: "date",
    TypeId.TIMESTAMP_US: "timestamp",
}
_SIMPLE_REV = {v: k for k, v in _SIMPLE.items()}


def _dtype_to_str(dt: DataType) -> str:
    if dt.id in _SIMPLE:
        return _SIMPLE[dt.id]
    if dt.id == TypeId.DECIMAL:
        return f"decimal({dt.precision},{dt.scale})"
    if dt.id == TypeId.LIST:
        return f"array<{_dtype_to_str(dt.children[0].dtype)}>"
    if dt.id == TypeId.MAP:
        return (f"map<{_dtype_to_str(dt.children[0].dtype)},"
                f"{_dtype_to_str(dt.children[1].dtype)}>")
    if dt.id == TypeId.STRUCT:
        inner = ",".join(f"{f.name}:{_dtype_to_str(f.dtype)}"
                         for f in dt.children)
        return f"struct<{inner}>"
    raise ValueError(f"unsupported dtype {dt}")


def _dtype_from_str(s: str) -> DataType:
    s = s.strip()
    if s in _SIMPLE_REV:
        return DataType(_SIMPLE_REV[s])
    if s.startswith("decimal(") and s.endswith(")"):
        p, sc = s[len("decimal("):-1].split(",")
        return DataType.decimal(int(p), int(sc))
    if s.startswith("array<") and s.endswith(">"):
        return DataType.list_(_dtype_from_str(s[len("array<"):-1]))
    if s.startswith("map<") and s.endswith(">"):
        k, v = _split_top(s[len("map<"):-1])
        return DataType.map_(_dtype_from_str(k), _dtype_from_str(v))
    if s.startswith("struct<") and s.endswith(">"):
        fields = []
        for part in _split_all(s[len("struct<"):-1]):
            name, t = part.split(":", 1)
            fields.append(Field(name, _dtype_from_str(t), True))
        return DataType.struct(tuple(fields))
    raise ValueError(f"cannot parse dtype string {s!r}")


def _split_top(s: str) -> Tuple[str, str]:
    depth = 0
    for i, ch in enumerate(s):
        if ch in "<(":
            depth += 1
        elif ch in ">)":
            depth -= 1
        elif ch == "," and depth == 0:
            return s[:i], s[i + 1:]
    raise ValueError(f"expected two type args in {s!r}")


def _split_all(s: str) -> List[str]:
    out, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch in "<(":
            depth += 1
        elif ch in ">)":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(s[start:i])
            start = i + 1
    if s[start:]:
        out.append(s[start:])
    return out


# -- convenience builders (used by tests and the standalone driver) --------

def fcol(name: str, dtype: DataType, nullable: bool = True) -> ForeignExpr:
    return ForeignExpr("AttributeReference", value=name, dtype=dtype,
                       attrs={"nullable": nullable})


def flit(value: Any, dtype: Optional[DataType] = None) -> ForeignExpr:
    if dtype is None:
        from auron_tpu.ir.expr import _infer_literal_type
        dtype = _infer_literal_type(value)
    return ForeignExpr("Literal", value=value, dtype=dtype)


def falias(child: ForeignExpr, name: str) -> ForeignExpr:
    return ForeignExpr("Alias", children=(child,), value=name)


def fcall(name: str, *children: ForeignExpr, **attrs) -> ForeignExpr:
    dtype = attrs.pop("dtype", None)
    value = attrs.pop("value", None)
    return ForeignExpr(name, children=tuple(children), value=value,
                       dtype=dtype, attrs=attrs)
