"""Front-end session: strategy + conversion + stage-scheduled execution.

The driver-side glue the reference spreads across
AuronSparkSessionExtension.scala (rule injection), NativeRDD.scala /
NativeHelper.scala (per-task native execution), AuronShuffleManager
(exchange materialization) and NativeBroadcastExchangeBase (broadcast
collect): `AuronSession.execute` tags a foreign plan, converts the
convertible sections, then runs the converted tree — native sections
through the task runtime (stage-by-stage across exchange boundaries via
the in-process shuffle service), foreign sections through the pluggable
host engine, with Arrow tables crossing the boundary both ways.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol

import pyarrow as pa

from auron_tpu import config
from auron_tpu.frontend import converters, strategy
from auron_tpu.frontend.converters import (
    BroadcastJob, ConvertContext, ConvertedT, ForeignSource, ForeignWrap,
    ShuffleJob,
)
from auron_tpu.frontend.foreign import ForeignNode
from auron_tpu.ir import plan as P
from auron_tpu.ir.node import Node
from auron_tpu.ir.schema import to_arrow_schema
from auron_tpu.ops.shuffle.writer import InProcessShuffleService
from auron_tpu.runtime.executor import ExecutionResult, execute_plan
from auron_tpu.runtime.metrics import MetricNode
from auron_tpu.runtime.resources import ResourceRegistry

log = logging.getLogger("auron_tpu.frontend")


def _blocks_nbytes(blocks) -> int:
    """Total serialized bytes of a per-partition block-list fetch result
    (the late-bound `nbytes` span arg on shuffle.fetch)."""
    return sum(len(d) for part in blocks for d in part)


class ForeignEngine(Protocol):
    """The host engine executing non-converted plan sections (the role
    Spark itself plays in the reference).  Native child results arrive as
    Arrow tables."""

    def execute(self, node: ForeignNode, child_tables: List[pa.Table]
                ) -> pa.Table:
        ...


@dataclass
class SessionResult:
    table: pa.Table
    converted: ConvertedT = None  # type: ignore[assignment]
    tags: Optional[strategy.Tags] = None
    metrics: List[MetricNode] = field(default_factory=list)
    ctx: Optional[ConvertContext] = None  # exchange/broadcast subtrees
    spmd: bool = False  # executed as one shard_map program over a mesh
    # why the SPMD stage compiler degraded to the serial path, as a
    # rendered analysis diagnostic (None when spmd ran or no mesh)
    spmd_rejection: Optional[str] = None
    # observability (runtime/tracing.py): the per-execute query id, the
    # driver wall time, and — when `auron.trace.enable` was set — the
    # TraceRecorder whose .to_chrome_trace()/.save() export the query's
    # lifecycle spans
    query_id: Optional[str] = None
    wall_s: float = 0.0
    trace: Optional[object] = None   # runtime.tracing.TraceRecorder
    # adaptive execution (runtime/adaptive.py): structured replan
    # decisions and the observed per-exchange size histograms that
    # drove them — the audit trail /queries/<id> and EXPLAIN ANALYZE
    # surface.  exchange_stats is populated whenever the serial
    # exchange path runs (observation is free); aqe_decisions only
    # when auron.adaptive.enable made replanning act on them.
    aqe_decisions: List[dict] = field(default_factory=list)
    exchange_stats: List[dict] = field(default_factory=list)

    def to_pylist(self) -> List[dict]:
        return self.table.to_pylist()

    def explain_analyze(self, normalize: bool = False) -> str:
        """Render the executed plan annotated with the merged per-task
        metric trees (runtime/explain_analyze.py).  `normalize=True`
        yields the run-stable canonical form goldens compare against."""
        from auron_tpu.runtime.explain_analyze import (
            explain_analyze as _ea, metric_totals,
        )
        totals = metric_totals(self.metrics)
        return _ea(self.metrics, query_id=self.query_id,
                   wall_s=self.wall_s, rows=self.table.num_rows,
                   spmd=self.spmd,
                   retries=totals.get("num_retries", 0),
                   fallbacks=totals.get("num_fallbacks", 0),
                   aqe=self.aqe_decisions,
                   normalize=normalize)

    def all_native(self) -> bool:
        """True when no foreign section remains (the
        checkSparkAnswerAndOperator plan-walk assertion,
        AuronQueryTest.scala:29-91).  LocalTableScan C2N sources are
        pass-through, matching the reference's allowance for
        ConvertToNative inputs.  A foreign-only run (auron.enable=false)
        has converted=None and is never 'all native'."""
        return self.converted is not None and \
            not isinstance(self.converted, ForeignWrap) and \
            getattr(self, "_foreign_sections", 0) == 0


class AuronSession:
    def __init__(self, foreign_engine: Optional[ForeignEngine] = None,
                 shuffle_service=None):
        # session-level default: arm the persistent XLA compilation
        # cache on device backends (auron.compile.cache.dir) so every
        # front-end entry point — not just the IT CLI — pays device
        # compiles once across processes
        config.apply_compile_cache()
        self.foreign_engine = foreign_engine
        if shuffle_service is None:
            # conf-selected transport: in-process (default) or a remote
            # shuffle service client (Celeborn/Uniffle analogues)
            from auron_tpu.shuffle_rss import service_from_conf
            shuffle_service = service_from_conf() or \
                InProcessShuffleService()
        self.shuffle_service = shuffle_service
        self._metrics: List[MetricNode] = []
        # durable-shuffle bookkeeping (shuffle_rss/durable.py): rid ->
        # side-car shuffle id for exchanges pushed durably, rids that
        # (also) hold executor-local fallback data, and the sticky
        # degrade flag once the side-car proved unreachable
        self._local_shuffle: Optional[InProcessShuffleService] = None
        self._exchange_sids: Dict[str, str] = {}
        self._exchange_local: set = set()
        self._rss_degraded = False
        # sharded side-cars degrade per SHARD ("host:port"), so a dead
        # shard takes down only the shuffle ids it owns
        self._rss_degraded_shards: set = set()
        self._stream_root: Optional[int] = None
        # adaptive execution (runtime/adaptive.py): per-query replan
        # decisions + observed exchange histograms, and the wall-clock
        # start the stage-boundary re-forecast ages against
        self._aqe_decisions: List[dict] = []
        self._exchange_stats: List[dict] = []
        self._plan_signature: str = ""
        self._wall_start: float = 0.0

    # -- public entry (preColumnarTransitions analogue) -------------------

    def execute(self, plan: ForeignNode,
                mesh=None, mesh_axis: str = "parts",
                query_id: Optional[str] = None) -> SessionResult:
        """Run a foreign plan.  With `mesh`, the converted native tree is
        first offered to the SPMD stage compiler (parallel/stage.py): the
        WHOLE pipeline — exchanges included — compiles to one shard_map
        program riding ICI collectives; plans it cannot express fall back
        to the serial per-partition path transparently.

        Every execute runs under a query scope (runtime/tracing.py): a
        query id (minted fresh, or `query_id` — the serving tier passes
        its submission id so `/queries` rows match `/status` ids)
        correlates log prefixes, span attributes and the query-history
        record; with `auron.trace.enable` set the full lifecycle trace
        lands on `SessionResult.trace`.

        Thread-safety: one execute per session instance at a time (the
        serving scheduler creates a session per query); concurrent
        executes MAY share the process (memory pool, task pool, shuffle
        service are lock-protected, and attribution is contextvar-scoped
        per query)."""
        from auron_tpu.runtime import counters, tracing
        from auron_tpu.runtime.explain_analyze import (
            merge_metric_trees, metric_max, metric_totals,
        )

        scope = tracing.trace_scope(query_id=query_id)
        counters.bump("queries_started")
        # a conversion failure must not record THIS run under the
        # previous run's plan signature
        self._plan_signature = ""
        t0 = time.perf_counter()
        wall_start = time.time()
        self._wall_start = wall_start
        res: Optional[SessionResult] = None
        error: Optional[str] = None
        try:
            with scope, tracing.span("query", cat="query",
                                     query_id=scope.query_id):
                res = self._execute_impl(plan, mesh, mesh_axis)
        except BaseException as e:
            counters.bump("queries_failed")
            error = f"{type(e).__name__}: {e}"
            raise
        finally:
            wall_s = time.perf_counter() - t0
            # per-query attribution sink (tracing.QueryStats): recovery
            # and memory sites bumped the scope's own counters, so the
            # record stays correct with other queries interleaving —
            # the old global-counter diffs credited a query with every
            # concurrent neighbor's retries and spills
            st = scope.stats.snapshot()
            trees = res.metrics if res is not None else []
            # the minimal lifecycle timeline of a direct execute (the
            # serving schedulers patch/record the full queued ->
            # admitted -> ... machine over this)
            timeline = [{"state": "running", "t": wall_start},
                        {"state": "failed" if error else "succeeded",
                         "t": wall_start + wall_s}]
            tracing.record_query(tracing.QueryRecord(
                query_id=scope.query_id, wall_s=wall_s,
                signature=self._plan_signature,
                rows=res.table.num_rows if res is not None else 0,
                spmd=res.spmd if res is not None else False,
                attempts=st.get("attempts", 0),
                retries=st.get("retries", 0),
                fallbacks=st.get("fallbacks", 0),
                error=error, started_at=wall_start,
                metric_totals=metric_totals(trees),
                mem_peak=metric_max(trees, "mem_peak"),
                mem_spills=st.get("mem_spills", 0),
                mem_spill_bytes=st.get("mem_spill_bytes", 0),
                metric_trees=[{"tasks": n, "tree": t.to_dict()}
                              for t, n in merge_metric_trees(trees)],
                timeline=timeline,
                aqe_decisions=list(self._aqe_decisions) or None,
                exchange_stats=list(self._exchange_stats) or None,
                trace=scope.recorder.to_chrome_trace()
                if scope.recorder is not None else None))
        counters.bump("queries_completed")
        res.query_id = scope.query_id
        res.wall_s = wall_s
        res.trace = scope.recorder
        return res

    def _execute_impl(self, plan: ForeignNode, mesh,
                      mesh_axis: str) -> SessionResult:
        from auron_tpu.runtime import tracing
        if not config.ENABLE.get():
            return SessionResult(table=self._run_foreign_only(plan))
        if mesh is None and config.SPMD_SINGLE_DEVICE.get():
            from auron_tpu.parallel.mesh import data_mesh
            mesh = data_mesh(1)
        with tracing.span("plan.convert", cat="plan"):
            tags = strategy.apply(plan)
            ctx = ConvertContext()
            converted = converters.convert_recursively(plan, tags, ctx)
        self._metrics = []
        self._spmd_rejection = None
        self._exchange_sids = {}
        self._exchange_local = set()
        self._aqe_decisions = []
        self._exchange_stats = []
        self._plan_signature = ""
        from auron_tpu.runtime import statshist
        if config.ADAPTIVE_ENABLE.get() or statshist.enabled():
            # the unified cost model keys its live exchange history by
            # plan signature (serving/forecast.py) — computed once
            # here; the durable stats store (runtime/statshist.py)
            # keys its terminal fold by the same signature
            from auron_tpu.serving.forecast import plan_signature
            try:
                self._plan_signature = plan_signature(plan)
            except Exception:
                self._plan_signature = ""
        # result streaming (runtime/result_stream.py): only the ROOT
        # native plan's partitions are the query result — exchange map
        # sides and broadcast subtrees run through the same _run_native
        # machinery and must never publish
        self._stream_root = id(converted) \
            if isinstance(converted, P.PlanNode) else None
        if mesh is not None and isinstance(converted, P.PlanNode):
            from auron_tpu.parallel.stage import (
                SpmdUnsupported, execute_plan_spmd, precheck_plan,
            )
            try:
                # cheap kind-level check BEFORE materializing any foreign
                # source (a fallback must not pay for C2N subtrees twice)
                precheck_plan(converted, ctx)
                sources = {rid: self._source_table(src, ctx)
                           for rid, src in ctx.sources.items()}
                with tracing.span("spmd.execute", cat="spmd"):
                    table = execute_plan_spmd(converted, ctx, mesh,
                                              sources, axis=mesh_axis)
                res = SessionResult(table=table, converted=converted,
                                    tags=tags, ctx=ctx, spmd=True)
                res._foreign_sections = sum(  # type: ignore[attr-defined]
                    1 for s in ctx.sources.values()
                    if s.node.children or
                    s.node.node.op != "LocalTableScanExec")
                return res
            except SpmdUnsupported as e:
                # degradation tier: the serial per-partition path below
                # IS the recovery.  The rejection becomes a structured
                # diagnostic (analysis/spmd.py) — the chaos sweep and
                # refplans report it uniformly — and the fallback is
                # counted (num_fallbacks in the run metrics).
                from auron_tpu.analysis.spmd import rejection_diagnostic
                from auron_tpu.runtime import retry as _retry
                diag = rejection_diagnostic(e, converted)
                log.info("SPMD stage fell back to serial path: %s", diag)
                _retry.add_fallback()
                fb = MetricNode("SpmdFallback")
                fb.add("num_fallbacks", 1)
                self._metrics.append(fb)
                self._spmd_rejection = str(diag)
        try:
            table = self._run_converted(converted, ctx)
        finally:
            # release exchange blocks (local or remote shuffle server —
            # the shuffle-cleanup the reference delegates to Spark's
            # ShuffleManager.unregisterShuffle).  Durable side-car
            # blocks are kept when `auron.rss.defer.cleanup` is set:
            # the fleet deletes them by query tag once the submission
            # is TERMINAL, so a kill -9'd executor's committed map
            # outputs survive for the requeued attempt to resume from.
            for rid in ctx.exchanges:
                self._clear_exchange(rid)
        res = SessionResult(table=table, converted=converted, tags=tags,
                            metrics=self._metrics, ctx=ctx,
                            spmd_rejection=self._spmd_rejection,
                            aqe_decisions=list(self._aqe_decisions),
                            exchange_stats=list(self._exchange_stats))
        # count foreign sections that needed the host engine (local-table
        # sources are data, not computation)
        res._foreign_sections = sum(  # type: ignore[attr-defined]
            1 for s in ctx.sources.values()
            if s.node.children or s.node.node.op != "LocalTableScanExec")
        return res

    # -- foreign-only path (auron.enable=false) ---------------------------

    def _run_foreign_only(self, node: ForeignNode) -> pa.Table:
        engine = self._require_engine()
        child_tables = [self._run_foreign_only(c) for c in node.children]
        return engine.execute(node, child_tables)

    def _require_engine(self) -> ForeignEngine:
        if self.foreign_engine is None:
            raise RuntimeError(
                "plan has non-native sections but no foreign engine is "
                "attached to this AuronSession")
        return self.foreign_engine

    # -- converted-tree execution ----------------------------------------

    def _run_converted(self, c: ConvertedT, ctx: ConvertContext) -> pa.Table:
        if isinstance(c, ForeignWrap):
            engine = self._require_engine()
            child_tables = [self._run_converted(ch, ctx)
                            for ch in c.children]
            return engine.execute(c.node, child_tables)
        return self._run_native(c, ctx)

    def _run_native(self, plan: P.PlanNode, ctx: ConvertContext) -> pa.Table:
        from auron_tpu.runtime import result_stream, tracing
        # stream-root identity is checked BEFORE dependency
        # materialization: with adaptive execution the stage-boundary
        # replan may return a REWRITTEN plan object
        is_stream_root = self._stream_root is not None and \
            id(plan) == self._stream_root
        resources, plan = self._materialize_deps(plan, ctx)
        n_parts = ctx.parts(plan)
        batches: List[pa.RecordBatch] = []
        stream_qid = None
        if is_stream_root:
            qid = tracing.current_query_id()
            if result_stream.active(qid):
                stream_qid = qid

        def run_task(pid: int):
            # the task-retry model above the runtime (the Spark
            # scheduler's role the reference inherits) now lives in
            # run_tasks itself: retryable-classified failures replay
            # with 1 + auron.task.retries attempts against the already-
            # materialized stage inputs (runtime/retry.py)
            res = execute_plan(plan, partition_id=pid,
                               resources=resources,
                               num_partitions=n_parts)
            if stream_qid is not None:
                # the streaming-result drain (?format=arrow&since=N)
                # sees this partition as soon as its task completes —
                # published AFTER the successful return, so a retried
                # task can never double-publish
                result_stream.publish(stream_qid, pid, res.batches)
            return res

        # one runtime per task, tasks in parallel across a thread pool —
        # the analogue of the reference running one native runtime per
        # Spark task across executor cores (rt.rs:76-139).  Each task
        # builds its own operator tree; the shared pieces (resource
        # registry, mem manager) are lock-protected, and jax dispatch is
        # thread-safe.  Results keep partition order.
        from auron_tpu.runtime.task_pool import run_tasks
        results = run_tasks(run_task, range(n_parts))
        for res in results:
            self._metrics.append(res.metrics)
            batches.extend(res.batches)
        if not batches:
            schema = getattr(plan, "schema", None)
            if schema is None:
                # non-leaf IR nodes carry no schema; derive it from the
                # instantiated operator tree
                from auron_tpu.runtime.planner import PhysicalPlanner
                schema = PhysicalPlanner().create_plan(plan).schema
            return pa.Table.from_batches([], schema=to_arrow_schema(schema))
        return pa.Table.from_batches(batches)

    # -- dependency materialization (stage scheduling) --------------------

    def _collect_rids(self, plan: Node, rids: List[str]) -> None:
        if isinstance(plan, (P.IpcReader, P.FFIReader)):
            rids.append(plan.resource_id)
        for c in plan.children_nodes():
            if isinstance(c, Node):
                self._collect_rids(c, rids)

    def _materialize_deps(self, plan: P.PlanNode, ctx: ConvertContext
                          ) -> "tuple[ResourceRegistry, P.PlanNode]":
        """Materialize every dependency of `plan` and return
        (resources, plan).  With `auron.adaptive.enable` off the plan
        comes back unchanged and the materialization order is exactly
        the legacy one (the chaos fault-draw sequences depend on it);
        with it on, every exchange's MAP side completes first, then the
        stage-boundary replanner (runtime/adaptive.py) may rewrite the
        consumer before the reduce-side fetch resources register."""
        from auron_tpu.runtime import adaptive
        resources = ResourceRegistry()
        rids: List[str] = []
        self._collect_rids(plan, rids)
        # a subtree may be referenced from several places (e.g. a union's
        # flattened partition mapping repeats the child) — materialize once
        unique = list(dict.fromkeys(rids))
        if adaptive.enabled() and \
                any(rid in ctx.exchanges for rid in unique):
            return self._materialize_deps_adaptive(plan, ctx, resources,
                                                   unique)
        for rid in unique:
            if rid in ctx.sources:
                self._materialize_source(ctx.sources[rid], ctx, resources)
            elif rid in ctx.broadcasts:
                self._materialize_broadcast(ctx.broadcasts[rid], ctx,
                                            resources)
            elif rid in ctx.exchanges:
                self._materialize_exchange(ctx.exchanges[rid], ctx,
                                           resources)
        return resources, plan

    # -- the adaptive stage boundary (runtime/adaptive.py) ----------------

    def _materialize_deps_adaptive(self, plan: P.PlanNode,
                                   ctx: ConvertContext,
                                   resources: ResourceRegistry,
                                   rids: List[str]
                                   ) -> "tuple[ResourceRegistry, P.PlanNode]":
        """Run every exchange's map side, observe the REAL per-partition
        output sizes, re-plan the consumer, then register reduce-side
        resources per decision (partitioned / broadcast collect /
        coalesced groups / skew fan-out)."""
        import time as _time

        from auron_tpu.runtime import adaptive, tracing
        pending: Dict[str, dict] = {}
        for rid in rids:
            if rid in ctx.sources:
                self._materialize_source(ctx.sources[rid], ctx, resources)
            elif rid in ctx.broadcasts:
                self._materialize_broadcast(ctx.broadcasts[rid], ctx,
                                            resources)
            elif rid in ctx.exchanges:
                pending[rid] = self._adaptive_map_side(
                    ctx.exchanges[rid], ctx)
        stats = {rid: p["stats"] for rid, p in pending.items()
                 if p.get("stats") is not None}
        with tracing.span("aqe.replan", cat="plan",
                          exchanges=len(stats)):
            plan, decisions, actions = adaptive.replan(plan, ctx, stats)
        for d in decisions:
            doc = d.to_dict()
            self._aqe_decisions.append(doc)
            tracing.event("aqe.decision", cat="plan", **doc)
            log.info("aqe: %s %s: %s", d.kind, d.exchange, d.reason)
        for rid, pend in pending.items():
            self._adaptive_fetch(ctx.exchanges[rid], ctx, resources,
                                 pend, actions.get(rid), plan)
        if stats and config.conf.get("auron.adaptive.reforecast.enable"):
            # close the admission loop: re-forecast the running query's
            # reservation from bytes actually observed, so a light
            # query releases early (serving/admission.reforecast via
            # the scheduler-registered hook)
            qid = tracing.current_query_id()
            est = adaptive.stage_mem_estimate(qid, stats.values())
            age = _time.time() - self._wall_start \
                if self._wall_start else 0.0
            new_res = adaptive.stage_boundary_reforecast(qid, est, age)
            if new_res is not None:
                tracing.event("aqe.reforecast", cat="plan",
                              reservation=new_res, estimate=est)
        return resources, plan

    def _adaptive_map_side(self, job: ShuffleJob,
                           ctx: ConvertContext) -> dict:
        """Run ONE exchange's map side (durable commit protocol or
        plain transport) without fetching, returning the observed
        stats and everything the later fetch needs."""
        from auron_tpu.shuffle_rss.durable import (
            DurableShuffleClient, RssUnavailable,
        )
        n_reduce = job.partitioning.num_partitions
        if isinstance(self.shuffle_service, DurableShuffleClient) \
                and not self._rss_degraded_for(job.rid):
            try:
                sid, man, stats = self._durable_map_side(job, ctx)
                self._observe_exchange(job, stats)
                return {"mode": "durable", "sid": sid, "man": man,
                        "stats": stats, "n_reduce": n_reduce}
            except RssUnavailable as e:
                self._note_rss_degrade(job.rid, e)
        service = self._exchange_service(job.rid)
        stats = self._plain_map_side(job, ctx, service)
        self._observe_exchange(job, stats)
        return {"mode": "plain", "service": service, "stats": stats,
                "n_reduce": n_reduce}

    def _adaptive_fetch(self, job: ShuffleJob, ctx: ConvertContext,
                        resources: ResourceRegistry, pend: dict,
                        action, plan: P.PlanNode) -> None:
        """Fetch one exchange's reduce side and register it per the
        replan decision.  The partition count of the (possibly
        rewritten) consumer is refined here when a skew split lands
        fewer parts than planned (block granularity)."""
        from auron_tpu.runtime import adaptive, tracing
        from auron_tpu.shuffle_rss.durable import RssUnavailable
        rid = job.rid
        n_reduce = pend["n_reduce"]
        with tracing.span("shuffle.fetch", cat="shuffle", rid=rid,
                          parts=n_reduce) as sp:
            if pend["mode"] == "durable":
                try:
                    blocks = self._durable_fetch_checked(
                        job, ctx, pend["sid"], pend["man"], n_reduce)
                except RssUnavailable as e:
                    # mirror the legacy degrade tier: the side-car died
                    # between commit and fetch — recompute this
                    # exchange executor-locally (results identical)
                    self._note_rss_degrade(rid, e)
                    service = self._exchange_service(rid)
                    self._plain_map_side(job, ctx, service)
                    blocks = self._plain_fetch(job, service, n_reduce)
            else:
                blocks = self._plain_fetch(job, pend["service"],
                                           n_reduce)
            sp.set_args(nbytes=_blocks_nbytes(blocks))
        if action is None:
            resources.put(rid, PartitionedBlocks(blocks))
            return
        if action.kind == "broadcast":
            # the collected form: ONE chained block stream every probe
            # task shares (the build hash map is built once and cached)
            resources.put(rid, [b for part in blocks for b in part])
        elif action.kind == "coalesce":
            merged = adaptive.merge_partition_groups(blocks,
                                                     action.groups)
            resources.put(rid, PartitionedBlocks(merged))
            ctx.set_parts(plan, len(merged))
        elif action.kind == "skew_split":
            out = adaptive.split_skewed_partition(
                blocks, action.split_pid, action.split_parts)
            resources.put(rid, PartitionedBlocks(out))
            ctx.set_parts(plan, len(out))
            if len(out) == n_reduce:
                log.info("aqe: skew split of %s collapsed (partition "
                         "has a single block run)", rid)

    def _note_rss_degrade(self, rid: str, err: Exception) -> None:
        """Shared degrade bookkeeping (sticky flag + counter + trace
        event + one log line) for the durable->local fallback.  With a
        SHARDED side-car client the stickiness is per shard: only the
        shuffle ids owned by the dead endpoint fall back to local."""
        from auron_tpu.runtime import counters, tracing
        from auron_tpu.shuffle_rss.shard_map import (
            ShardedDurableShuffleClient,
        )
        endpoint = getattr(err, "rss_endpoint", None)
        if endpoint and isinstance(self.shuffle_service,
                                   ShardedDurableShuffleClient):
            self._rss_degraded_shards.add(endpoint)
            scope = f"shard {endpoint}"
        else:
            self._rss_degraded = True
            scope = "this query"
        counters.bump("rss_degrades")
        tracing.event("rss.degrade", cat="shuffle", rid=rid,
                      error=str(err))
        log.warning(
            "durable shuffle degraded to executor-local for %s "
            "(rid %s): %s", scope, rid, err)

    def _rss_degraded_for(self, rid: str) -> bool:
        """Is the durable path out of service for THIS exchange?  The
        global flag covers single side-cars; with a sharded client only
        the owner shard's death counts."""
        if self._rss_degraded:
            return True
        if not self._rss_degraded_shards:
            return False
        from auron_tpu.shuffle_rss.shard_map import (
            ShardedDurableShuffleClient,
        )
        svc = self.shuffle_service
        if not isinstance(svc, ShardedDurableShuffleClient):
            return True
        shard = svc.shard_of(self._durable_sid(rid))
        return f"{shard.host}:{shard.port}" in self._rss_degraded_shards

    def _observe_exchange(self, job: ShuffleJob, stats) -> None:
        """Surface one exchange's observed output: the session list
        (-> SessionResult / QueryRecord / bench JSON), a metric-tree
        marker node (-> EXPLAIN ANALYZE; byte values are canonical-
        volatile), and the unified cost model's live history."""
        self._exchange_stats.append(stats.to_dict())
        mn = MetricNode(f"ExchangeStats[{stats.ordinal()}]")
        mn.add("partitions", stats.num_partitions)
        mn.add("rows_out", stats.total_rows)
        mn.add("bytes_out", stats.total_bytes)
        if stats.partition_bytes:
            mn.add("part_bytes_max", max(stats.partition_bytes))
            mn.add("part_bytes_min", min(stats.partition_bytes))
        self._metrics.append(mn)
        if self._plan_signature:
            from auron_tpu.runtime.adaptive import unified_cost_model
            unified_cost_model().record_exchange(self._plan_signature,
                                                 stats)

    def _source_table(self, src: ForeignSource,
                      ctx: ConvertContext) -> pa.Table:
        is_local_table = (not src.node.children and
                          src.node.node.op == "LocalTableScanExec")
        return self._local_table(src.node.node) if is_local_table \
            else self._run_converted(src.node, ctx)

    def _materialize_source(self, src: ForeignSource, ctx: ConvertContext,
                            resources: ResourceRegistry) -> None:
        """C2N: the foreign engine computes the subtree; its table feeds
        the FFIReader (ConvertToNativeBase.doExecuteNative analogue)."""
        resources.put(src.rid, self._source_table(src, ctx))

    @staticmethod
    def _local_table(node: ForeignNode) -> pa.Table:
        schema = to_arrow_schema(node.output)
        return pa.Table.from_pylist(node.attrs.get("rows", []),
                                    schema=schema)

    def _materialize_broadcast(self, job: BroadcastJob, ctx: ConvertContext,
                               resources: ResourceRegistry) -> None:
        """Broadcast collect: run the build side once (all partitions) and
        serve the IPC bytes to every probe partition
        (NativeBroadcastExchangeBase.collectNative:195-230)."""
        import io

        from auron_tpu.columnar import serde as batch_serde
        from auron_tpu.runtime import tracing
        with tracing.span("broadcast.collect", cat="exchange",
                          rid=job.rid):
            table = self._run_converted(job.child, ctx)
            sink = io.BytesIO()
            # broadcast bytes never leave the process: the local
            # exchange codec policy applies (none by default)
            codec = batch_serde.exchange_codec("local")
            for rb in table.to_batches():
                if rb.num_rows:
                    batch_serde.write_one_batch(rb, sink, codec=codec)
            resources.put(job.rid, sink.getvalue())

    def _materialize_exchange(self, job: ShuffleJob, ctx: ConvertContext,
                              resources: ResourceRegistry) -> None:
        """Shuffle: run the map side through RssShuffleWriter into the
        shuffle service, then register per-reduce block lists
        (AuronShuffleManager.getWriter/getReader analogue).  A durable
        side-car service takes the commit-protocol path (manifest
        consult, stage/map resume, integrity-checked fetch); when the
        side-car is unreachable the exchange DEGRADES to executor-local
        shuffle with a structured diagnostic instead of hanging."""
        from auron_tpu.shuffle_rss.durable import (
            DurableShuffleClient, RssUnavailable,
        )
        if isinstance(self.shuffle_service, DurableShuffleClient) \
                and not self._rss_degraded_for(job.rid):
            try:
                self._materialize_exchange_durable(job, ctx, resources)
                return
            except RssUnavailable as e:
                # the degrade path back to executor-local shuffle: the
                # side-car is down — upstream stages recompute locally,
                # results stay bit-identical, and the diagnostic is
                # structured (counter + trace event + one log line),
                # never a hang (every RPC rode bounded retries)
                self._note_rss_degrade(job.rid, e)
        self._materialize_exchange_via(job, ctx, resources,
                                       self._exchange_service(job.rid))

    def _exchange_service(self, rid: str):
        """The service an executor-local exchange uses: the session's
        own (in-process/celeborn/uniffle), or a lazily-built in-process
        fallback once the durable side-car degraded."""
        from auron_tpu.shuffle_rss.durable import DurableShuffleClient
        if not isinstance(self.shuffle_service, DurableShuffleClient):
            return self.shuffle_service
        if self._local_shuffle is None:
            self._local_shuffle = InProcessShuffleService()
        self._exchange_local.add(rid)
        return self._local_shuffle

    def _clear_exchange(self, rid: str) -> None:
        try:
            if rid in self._exchange_local and \
                    self._local_shuffle is not None:
                self._local_shuffle.clear(rid)
            sid = self._exchange_sids.get(rid)
            if sid is not None:
                # the fleet owns durable cleanup when deferred (it
                # deletes by query tag at TERMINAL state — resume
                # depends on blocks surviving a killed attempt)
                if not config.conf.get("auron.rss.defer.cleanup"):
                    self.shuffle_service.clear(sid)
            elif rid not in self._exchange_local:
                self.shuffle_service.clear(rid)
        except Exception:
            log.warning("failed to clear shuffle %s", rid)

    def _materialize_exchange_via(self, job: ShuffleJob,
                                  ctx: ConvertContext,
                                  resources: ResourceRegistry,
                                  service) -> None:
        from auron_tpu.runtime import tracing
        stats = self._plain_map_side(job, ctx, service)
        self._observe_exchange(job, stats)
        n_reduce = job.partitioning.num_partitions
        with tracing.span("shuffle.fetch", cat="shuffle", rid=job.rid,
                          parts=n_reduce) as sp:
            blocks = self._plain_fetch(job, service, n_reduce)
            sp.set_args(nbytes=_blocks_nbytes(blocks))
            resources.put(job.rid, PartitionedBlocks(blocks))

    def _plain_map_side(self, job: ShuffleJob, ctx: ConvertContext,
                        service):
        """Run the map side against a plain (in-process/remote)
        transport; returns the observed per-partition ExchangeStats."""
        # job.child is always native: convert_recursively runs every
        # foreign subtree through convert_to_native (FFI source) before a
        # converter sees it
        map_deps, map_plan = self._materialize_deps(job.child, ctx)
        map_parts = ctx.parts(map_plan)

        def map_task(map_pid: int):
            writer_rid = f"{job.rid}:writer:{map_pid}"
            map_deps.put(writer_rid,
                         service.rss_writer(job.rid, map_pid))
            writer = P.RssShuffleWriter(child=map_plan,
                                        partitioning=job.partitioning,
                                        rss_resource_id=writer_rid)
            return execute_plan(writer, partition_id=map_pid,
                                resources=map_deps,
                                num_partitions=map_parts)

        # map tasks in parallel, like the reduce-side task pool in
        # _run_native — but ONLY for the in-process shuffle service,
        # whose reads sort blocks by map id; the remote clients
        # (celeborn aggregate buffers, uniffle arrival-order blocks)
        # record pushes in arrival order, so concurrent maps would make
        # reduce-side streams nondeterministic there
        from auron_tpu.runtime import tracing
        from auron_tpu.runtime.task_pool import run_tasks
        with tracing.span("exchange.map", cat="exchange", rid=job.rid,
                          parts=map_parts):
            if isinstance(service, InProcessShuffleService):
                results = run_tasks(map_task, range(map_parts),
                                    "auron-map")
            else:
                results = [map_task(pid) for pid in range(map_parts)]
        for res in results:
            self._metrics.append(res.metrics)
        from auron_tpu.runtime.adaptive import stats_from_map_results
        return stats_from_map_results(job.rid, results,
                                      job.partitioning.num_partitions)

    def _plain_fetch(self, job: ShuffleJob, service,
                     n_reduce: int) -> List[List[bytes]]:
        """Per-partition block lists from a plain transport.  The fetch
        rides the shared retry policy: it is a pure read (the remote
        clients dedup by id, the in-process store is committed), so
        replays after an injected/transport fault are idempotent.
        Pipelined: up to auron.shuffle.pipeline.depth partition fetches
        in flight, results in partition order, the smallest-pid error
        raised first (the sequential loop's error)."""
        from auron_tpu.runtime.retry import (
            RetryPolicy, call_with_retry, task_classify,
        )
        from auron_tpu.shuffle_rss.pipeline import run_windowed
        policy = RetryPolicy.task_policy()

        def fetch_one(pid: int):
            return call_with_retry(
                lambda: service.reduce_blocks(job.rid, pid),
                policy=policy, classify=task_classify,
                label=f"shuffle fetch {job.rid}:{pid}")

        return run_windowed(fetch_one, range(n_reduce))

    # -- the durable side-car exchange (commit protocol + resume) ---------

    def _durable_sid(self, rid: str) -> str:
        """The side-car shuffle id: a STABLE (query tag, exchange
        ordinal) key.  Conversion rids embed a random per-context uid
        for cross-query isolation on shared servers, so a requeued
        attempt would never match them — the tag (`auron.rss.tag`, set
        by the fleet to the front-door query id; else this execute's
        query id) plus the deterministic conversion ordinal is what
        both attempts agree on."""
        from auron_tpu.runtime import tracing
        tag = str(config.conf.get("auron.rss.tag") or "") or \
            tracing.current_query_id() or "untagged"
        return f"{tag}|x{rid.rsplit(':', 1)[-1]}"

    def _materialize_exchange_durable(self, job: ShuffleJob,
                                      ctx: ConvertContext,
                                      resources: ResourceRegistry
                                      ) -> None:
        """The commit-protocol exchange: consult the manifest, SKIP map
        tasks whose outputs a previous attempt already committed (whole
        stages when sealed), run only the uncommitted remainder, seal,
        then fetch with manifest integrity checks — a damaged block
        regenerates exactly its map output (targeted re-dispatch), not
        a blind replay."""
        from auron_tpu.runtime import tracing
        sid, man, stats = self._durable_map_side(job, ctx)
        self._observe_exchange(job, stats)
        n_reduce = job.partitioning.num_partitions
        with tracing.span("shuffle.fetch", cat="shuffle", rid=job.rid,
                          parts=n_reduce) as sp:
            blocks = self._durable_fetch_checked(job, ctx, sid, man,
                                                 n_reduce)
            sp.set_args(nbytes=_blocks_nbytes(blocks))
        resources.put(job.rid, PartitionedBlocks(blocks))

    def _durable_map_side(self, job: ShuffleJob, ctx: ConvertContext):
        """Map half of the commit protocol: manifest consult, run the
        uncommitted remainder, seal.  Returns (sid, manifest, observed
        ExchangeStats) — for a RESUMED stage the per-partition bytes
        come from the manifest's committed ledger, so the replanner
        sees real sizes without the map side ever re-running."""
        from auron_tpu.runtime import adaptive, counters, tracing
        svc = self.shuffle_service
        sid = self._durable_sid(job.rid)
        self._exchange_sids[job.rid] = sid
        map_parts = ctx.parts(job.child)
        resume = bool(config.conf.get("auron.rss.resume.enable"))
        man = svc.manifest(sid) if resume \
            else {"sealed": None, "maps": {}}
        committed = {int(m) for m in man["maps"]}
        to_run = [p for p in range(map_parts) if p not in committed]
        skipped = map_parts - len(to_run)
        if skipped:
            counters.bump("rss_map_tasks_skipped", skipped)
        resumed = not to_run and man["sealed"] == map_parts
        if resumed:
            # the whole map stage is committed: RESUME — reduce fetches
            # from the side-car, the map subtree (and every exchange
            # under it) is never materialized
            counters.bump("rss_stage_skips")
            tracing.event("rss.resume", cat="shuffle", rid=job.rid,
                          sid=sid, maps=map_parts)
            log.info("durable shuffle %s: stage resumed from side-car "
                     "(%d committed map output(s) reused)", sid,
                     map_parts)
        else:
            self._run_durable_map_stage(job, ctx, sid, to_run)
            svc.seal(sid, map_parts)
            man = svc.manifest(sid)
        n_reduce = job.partitioning.num_partitions
        stats = adaptive.stats_from_manifest(job.rid, man, n_reduce)
        stats.resumed = resumed
        stats.rows_known = False
        return sid, man, stats

    def _durable_fetch_checked(self, job: ShuffleJob,
                               ctx: ConvertContext, sid: str, man: dict,
                               n_reduce: int) -> List[List[bytes]]:
        """Fetch half of the commit protocol: integrity-checked fetch
        with ONE targeted-regeneration round for damaged map outputs."""
        from auron_tpu.runtime import counters, tracing
        svc = self.shuffle_service
        map_parts = ctx.parts(job.child)
        blocks, bad = self._durable_fetch(sid, n_reduce, man)
        if bad:
            # missing/corrupt committed block: deterministic, so
            # regenerate those map outputs and fetch once more
            counters.bump("rss_fetch_regens")
            tracing.event("rss.fetch.regen", cat="shuffle",
                          rid=job.rid, sid=sid, maps=sorted(bad))
            log.warning(
                "durable shuffle %s: fetch failed integrity for "
                "map output(s) %s; regenerating via targeted "
                "re-dispatch", sid, sorted(bad))
            self._run_durable_map_stage(
                job, ctx, sid,
                [m for m in sorted(bad) if m < map_parts])
            svc.seal(sid, map_parts)
            man = svc.manifest(sid)
            blocks, bad = self._durable_fetch(sid, n_reduce, man)
            if bad:
                from auron_tpu.shuffle_rss.durable import (
                    FetchFailedError,
                )
                raise FetchFailedError(
                    sid, sorted(bad),
                    detail="regeneration did not converge")
        return blocks

    def _run_durable_map_stage(self, job: ShuffleJob,
                               ctx: ConvertContext, sid: str,
                               pids: List[int]) -> None:
        """Run the listed map tasks against the side-car.  Frames per
        (map, attempt) are isolated and fetch orders by map id, so
        concurrent map tasks stay deterministic (unlike the aggregate/
        block transports)."""
        from auron_tpu.runtime import counters, tracing
        from auron_tpu.runtime.task_pool import run_tasks
        if not pids:
            return
        map_deps, map_plan = self._materialize_deps(job.child, ctx)
        # the commit protocol's map-id space must be attempt-stable, so
        # task count stays the ORIGINAL conversion-time partition count
        # even when a nested adaptive replan coalesced the map plan's
        # own inputs (the surplus map tasks read empty partitions and
        # commit empty outputs — resume math stays consistent)
        map_parts = ctx.parts(job.child)

        def map_task(map_pid: int):
            writer_rid = f"{job.rid}:writer:{map_pid}"
            map_deps.put(writer_rid,
                         self.shuffle_service.rss_writer(sid, map_pid))
            writer = P.RssShuffleWriter(child=map_plan,
                                        partitioning=job.partitioning,
                                        rss_resource_id=writer_rid)
            return execute_plan(writer, partition_id=map_pid,
                                resources=map_deps,
                                num_partitions=map_parts)

        with tracing.span("exchange.map", cat="exchange", rid=job.rid,
                          parts=len(pids), sid=sid):
            results = run_tasks(map_task, pids, "auron-map")
        counters.bump("rss_map_tasks_run", len(pids))
        for res in results:
            self._metrics.append(res.metrics)

    def _durable_fetch(self, sid: str, n_reduce: int, man: dict):
        """Fetch every reduce partition, validating against the
        manifest; returns (per-partition frame lists, bad map ids) so
        ONE regeneration round covers every damaged map output.
        Partition fetches ride the bounded pipeline window (transport
        errors — RssUnavailable — still raise in partition order)."""
        from auron_tpu.shuffle_rss.durable import FetchFailedError
        from auron_tpu.shuffle_rss.pipeline import run_windowed

        def fetch_one(pid: int):
            try:
                return self.shuffle_service.reduce_blocks(
                    sid, pid, expect=man)
            except FetchFailedError as e:
                return e

        blocks: List[List[bytes]] = []
        bad: set = set()
        for got in run_windowed(fetch_one, range(n_reduce)):
            if isinstance(got, FetchFailedError):
                bad.update(got.map_ids)
                blocks.append([])
            else:
                blocks.append(got)
        return blocks, bad


class PartitionedBlocks:
    """Per-reduce-partition block lists behind one resource id."""

    def __init__(self, per_partition: List[List[bytes]]):
        self.per_partition = per_partition

    def for_partition(self, pid: int) -> List[bytes]:
        if pid >= len(self.per_partition):
            return []
        return self.per_partition[pid]
