"""Foreign (Spark-named) expression tree -> IR Expr conversion.

Analogue of NativeConverters.convertExpr/convertExprWithFallback
(spark-extension/.../NativeConverters.scala:325-1226): a per-class-name
dispatch covering ~90 Spark expression kinds, decimal-arithmetic gating,
and a partial-fallback wrapper — where the reference wraps unconvertible
sub-expressions into a JVM-callback `SparkUDFWrapperExpr`
(NativeConverters.scala:277-324), we wrap them into `PyUdfWrapper` when
the foreign node carries a pickled python evaluator.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from auron_tpu import config
from auron_tpu.frontend.foreign import ForeignExpr
from auron_tpu.ir import expr as E
from auron_tpu.ir.expr import AggExpr, Expr, SortExpr
from auron_tpu.ir.schema import DataType


class NotConvertible(Exception):
    """Raised when a foreign expression/plan has no native conversion."""


def _dt(fe: ForeignExpr) -> DataType:
    return fe.dtype if fe.dtype is not None else DataType.null()


# ---------------------------------------------------------------------------
# dispatch table: Spark expression class name -> builder(fe, conv) -> Expr
# `conv` converts a child (with fallback enabled so partial fallback nests).
# ---------------------------------------------------------------------------

_CONVERTERS: Dict[str, Callable[..., Expr]] = {}


def _reg(*names: str):
    def deco(fn):
        for n in names:
            _CONVERTERS[n] = fn
        return fn
    return deco


def _fn(name: str, fe: ForeignExpr, conv, args=None) -> Expr:
    return E.ScalarFunctionCall(
        name=name,
        args=tuple(conv(c) for c in (args if args is not None else fe.children)),
        return_type=_dt(fe))


# -- leaves -----------------------------------------------------------------

@_reg("AttributeReference")
def _attr(fe, conv):
    return E.Column(name=fe.value)


@_reg("BoundReference")
def _bound(fe, conv):
    return E.BoundReference(index=int(fe.value))


@_reg("Literal")
def _literal(fe, conv):
    return E.Literal(value=fe.value, dtype=_dt(fe))


@_reg("Alias")
def _alias(fe, conv):
    # alias name is consumed at the plan level; the expr is transparent
    return conv(fe.children[0])


@_reg("PromotePrecision", "KnownFloatingPointNormalized", "KnownNotNull")
def _transparent(fe, conv):
    return conv(fe.children[0])


@_reg("SparkPartitionID")
def _pid(fe, conv):
    return E.SparkPartitionId()


@_reg("MonotonicallyIncreasingID")
def _monot(fe, conv):
    return E.MonotonicallyIncreasingId()


@_reg("RowNumberLike", "RowNum")
def _rownum(fe, conv):
    return E.RowNum()


@_reg("ScalarSubquery")
def _scalar_subquery(fe, conv):
    # the bridge pre-computes the subquery result and ships it as a value
    # (reference: PhysicalSparkScalarSubqueryWrapperExprNode)
    return E.ScalarSubqueryWrapper(value=fe.value, dtype=_dt(fe))


# -- casts ------------------------------------------------------------------

@_reg("Cast", "AnsiCast")
def _cast(fe, conv):
    return E.Cast(child=conv(fe.children[0]), dtype=_dt(fe))


@_reg("TryCast")
def _try_cast(fe, conv):
    return E.TryCast(child=conv(fe.children[0]), dtype=_dt(fe))


# -- arithmetic / comparison ------------------------------------------------

_BIN_OPS = {
    "Add": "+", "Subtract": "-", "Multiply": "*", "Divide": "/",
    "Remainder": "%", "EqualTo": "==", "LessThan": "<",
    "LessThanOrEqual": "<=", "GreaterThan": ">", "GreaterThanOrEqual": ">=",
    "BitwiseAnd": "&", "BitwiseOr": "|", "BitwiseXor": "^",
    "ShiftLeft": "<<", "ShiftRight": ">>",
}


def _binary(fe, conv):
    if fe.name in ("Add", "Subtract", "Multiply", "Divide") and \
            _dt(fe).is_decimal and not config.DECIMAL_ARITH_ENABLE.get():
        raise NotConvertible("decimal arithmetic disabled by conf")
    return E.BinaryExpr(left=conv(fe.children[0]), op=_BIN_OPS[fe.name],
                        right=conv(fe.children[1]))


for _n in _BIN_OPS:
    _CONVERTERS[_n] = _binary


@_reg("And")
def _and(fe, conv):
    return E.ScAnd(left=conv(fe.children[0]), right=conv(fe.children[1]))


@_reg("Or")
def _or(fe, conv):
    return E.ScOr(left=conv(fe.children[0]), right=conv(fe.children[1]))


@_reg("Not")
def _not(fe, conv):
    return E.Not(child=conv(fe.children[0]))


@_reg("UnaryMinus")
def _neg(fe, conv):
    return E.Negative(child=conv(fe.children[0]))


@_reg("IsNull")
def _is_null(fe, conv):
    return E.IsNull(child=conv(fe.children[0]))


@_reg("IsNotNull")
def _is_not_null(fe, conv):
    return E.IsNotNull(child=conv(fe.children[0]))


@_reg("EqualNullSafe")
def _eq_null_safe(fe, conv):
    l, r = conv(fe.children[0]), conv(fe.children[1])
    both_null = E.ScAnd(left=E.IsNull(child=l), right=E.IsNull(child=r))
    neither = E.ScAnd(left=E.IsNotNull(child=l), right=E.IsNotNull(child=r))
    eq = E.ScAnd(left=neither, right=E.BinaryExpr(left=l, op="==", right=r))
    return E.ScOr(left=both_null, right=eq)


@_reg("In", "InSet")
def _in(fe, conv):
    if fe.name == "InSet":
        vals = tuple(E.Literal(value=v, dtype=_dt(fe.children[0]))
                     for v in fe.attrs.get("hset", ()))
    else:
        vals = tuple(conv(c) for c in fe.children[1:])
    return E.InList(child=conv(fe.children[0]), values=vals,
                    negated=bool(fe.attrs.get("negated", False)))


@_reg("If")
def _if(fe, conv):
    return E.Case(
        branches=(E.WhenThen(when=conv(fe.children[0]),
                             then=conv(fe.children[1])),),
        else_expr=conv(fe.children[2]))


@_reg("CaseWhen")
def _case_when(fe, conv):
    cs = fe.children
    has_else = len(cs) % 2 == 1
    pairs = cs[:-1] if has_else else cs
    branches = tuple(
        E.WhenThen(when=conv(pairs[i]), then=conv(pairs[i + 1]))
        for i in range(0, len(pairs), 2))
    return E.Case(branches=branches,
                  else_expr=conv(cs[-1]) if has_else else None)


@_reg("Like")
def _like(fe, conv):
    return E.Like(child=conv(fe.children[0]), pattern=conv(fe.children[1]),
                  case_insensitive=bool(fe.attrs.get("case_insensitive",
                                                     False)))


def _literal_value(fe, what: str):
    """The native string predicates take a constant pattern (the reference
    converts only literal-pattern StartsWith/EndsWith/Contains,
    NativeConverters.scala); a non-literal must fall back, not silently
    become a constant."""
    if fe.name != "Literal":
        raise NotConvertible(f"{what} requires a literal argument, "
                             f"got {fe.name}")
    return fe.value


@_reg("StartsWith")
def _starts(fe, conv):
    return E.StringStartsWith(
        child=conv(fe.children[0]),
        prefix=_literal_value(fe.children[1], "StartsWith prefix"))


@_reg("EndsWith")
def _ends(fe, conv):
    return E.StringEndsWith(
        child=conv(fe.children[0]),
        suffix=_literal_value(fe.children[1], "EndsWith suffix"))


@_reg("Contains")
def _contains(fe, conv):
    return E.StringContains(
        child=conv(fe.children[0]),
        infix=_literal_value(fe.children[1], "Contains infix"))


# -- simple function-name mappings ------------------------------------------

_SIMPLE_FNS = {
    # math (NativeConverters.scala:826-893)
    "Sqrt": "sqrt", "Sin": "sin", "Cos": "cos", "Tan": "tan", "Asin": "asin",
    "Acos": "acos", "Acosh": "acosh", "Atan": "atan", "Atan2": "atan2",
    "Exp": "exp", "Expm1": "expm1", "Signum": "signum", "Pow": "power",
    "Log2": "log2", "Log10": "log10", "Log": "ln", "Logarithm": "log",
    "Hex": "hex", "Unhex": "unhex", "Factorial": "factorial",
    "IsNaN": "is_nan", "Least": "least", "Greatest": "greatest",
    "Floor": "floor", "Ceil": "ceil", "Abs": "abs",
    "NormalizeNaNAndZero": "normalize_nan_and_zero",
    "UnscaledValue": "unscaled_value",
    # conditional
    "Coalesce": "coalesce", "Nvl": "nvl", "Nvl2": "nvl2", "NullIf": "null_if",
    # strings
    "Lower": "lower", "Upper": "upper", "StringTrim": "trim",
    "StringTrimLeft": "ltrim", "StringTrimRight": "rtrim",
    "StringRepeat": "repeat", "StringSpace": "string_space",
    "StringLPad": "lpad", "StringRPad": "rpad",
    "StringTranslate": "translate", "StringReplace": "replace",
    "InitCap": "initcap", "Levenshtein": "levenshtein",
    "FindInSet": "find_in_set", "Ascii": "ascii", "BitLength": "bit_length",
    "OctetLength": "octet_length", "Chr": "chr", "Reverse": "reverse",
    "Length": "character_length", "Concat": "concat", "ConcatWs": "concat_ws",
    "Substring": "substr", "StringInstr": "strpos",
    "SplitPart": "split_part", "StringSplit": "string_split",
    "RegExpReplace": "regexp_replace", "RegExpExtract": "regexp_extract",
    # datetime
    "MakeDate": "make_date", "Year": "year", "Quarter": "quarter",
    "Month": "month", "DayOfMonth": "day", "DayOfWeek": "day_of_week",
    "WeekOfYear": "week_of_year", "MonthsBetween": "months_between",
    "DateAdd": "date_add", "DateSub": "date_sub", "DateDiff": "datediff",
    "LastDay": "last_day", "NextDay": "next_day",
    "UnixTimestamp": "unix_timestamp", "FromUnixTime": "from_unixtime",
    "TruncDate": "trunc", "TruncTimestamp": "date_trunc",
    # hashes / crypto
    "Md5": "md5", "Crc32": "crc32",
    # json
    "GetJsonObject": "get_json_object",
    # collections
    "CreateArray": "make_array", "CreateMap": "map",
    "MapFromArrays": "map_from_arrays", "StringToMap": "str_to_map",
    "MapConcat": "map_concat", "MapFromEntries": "map_from_entries",
    "SortArray": "sort_array", "Size": "size", "ElementAt": "element_at",
    "ArrayUnion": "array_union",
    # spark numerics
    "MakeDecimal": "make_decimal", "CheckOverflow": "check_overflow",
    "Bin": "bin",
}


def _simple_fn(fe, conv):
    name = _SIMPLE_FNS[fe.name]
    if fe.name in ("Lower", "Upper") and \
            not config.CASE_CONVERT_FUNCTIONS_ENABLE.get():
        raise NotConvertible("case-convert functions disabled by conf")
    if fe.name in ("MakeDecimal", "CheckOverflow") and \
            not config.DECIMAL_ARITH_ENABLE.get():
        raise NotConvertible("decimal arithmetic disabled by conf")
    return _fn(name, fe, conv)


for _n in _SIMPLE_FNS:
    _CONVERTERS[_n] = _simple_fn


@_reg("Hour", "Minute", "Second")
def _dt_extract(fe, conv):
    if not config.DATETIME_EXTRACT_ENABLE.get():
        raise NotConvertible("datetime extract disabled by conf")
    return _fn(fe.name.lower(), fe, conv)


@_reg("Round")
def _round(fe, conv):
    return _fn("round", fe, conv)


@_reg("BRound")
def _bround(fe, conv):
    return _fn("bround", fe, conv)


@_reg("Sha2")
def _sha2(fe, conv):
    bits = _literal_value(fe.children[1], "Sha2 bit length") \
        if len(fe.children) > 1 else 256
    name = {0: "sha256", 224: "sha224", 256: "sha256",
            384: "sha384", 512: "sha512"}.get(bits)
    if name is None:
        raise NotConvertible(f"sha2 bit length {bits}")
    return _fn(name, fe, conv, args=fe.children[:1])


@_reg("Murmur3Hash")
def _murmur3(fe, conv):
    seed = fe.attrs.get("seed", 42)
    return E.ScalarFunctionCall(
        name="murmur3_hash",
        args=tuple(conv(c) for c in fe.children) +
             (E.Literal(value=int(seed), dtype=DataType.int32()),),
        return_type=_dt(fe))


@_reg("XxHash64")
def _xxhash(fe, conv):
    seed = fe.attrs.get("seed", 42)
    return E.ScalarFunctionCall(
        name="xxhash64",
        args=tuple(conv(c) for c in fe.children) +
             (E.Literal(value=int(seed), dtype=DataType.int64()),),
        return_type=_dt(fe))


@_reg("GetArrayItem")
def _get_array_item(fe, conv):
    idx = fe.children[1].value if len(fe.children) > 1 else fe.attrs["ordinal"]
    return E.GetIndexedField(child=conv(fe.children[0]), ordinal=int(idx))


@_reg("GetStructField")
def _get_struct_field(fe, conv):
    return E.GetIndexedField(child=conv(fe.children[0]),
                             ordinal=fe.attrs["name"])


@_reg("GetMapValue")
def _get_map_value(fe, conv):
    key = fe.children[1].value if len(fe.children) > 1 else fe.attrs["key"]
    return E.GetMapValue(child=conv(fe.children[0]), key=key)


@_reg("CreateNamedStruct")
def _named_struct(fe, conv):
    names = tuple(fe.children[i].value for i in range(0, len(fe.children), 2))
    values = tuple(conv(fe.children[i])
                   for i in range(1, len(fe.children), 2))
    return E.NamedStruct(names=names, values=values, return_type=_dt(fe))


@_reg("BloomFilterMightContain")
def _bloom_might_contain(fe, conv):
    return E.BloomFilterMightContain(bloom_filter=conv(fe.children[0]),
                                     value=conv(fe.children[1]))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def convert_expr(fe: ForeignExpr) -> Expr:
    """Strict conversion: NotConvertible on any unsupported node
    (the dry-run path the convert strategy uses)."""
    fn = _CONVERTERS.get(fe.name)
    if fn is None:
        raise NotConvertible(f"expression {fe.name} is not supported yet")
    return fn(fe, convert_expr)


def convert_expr_with_fallback(fe: ForeignExpr) -> Expr:
    """Conversion with per-node UDF fallback
    (NativeConverters.convertExprWithFallback:325-393): an unconvertible
    node that carries a pickled python evaluator becomes a PyUdfWrapper
    over its (recursively converted) children."""
    def conv(c: ForeignExpr) -> Expr:
        return convert_expr_with_fallback(c)

    fn = _CONVERTERS.get(fe.name)
    if fn is not None:
        try:
            return fn(fe, conv)
        except NotConvertible:
            pass
    if fe.py_fn is not None and config.UDF_FALLBACK_ENABLE.get():
        if fe.dtype is None:
            raise NotConvertible(
                f"fallback for {fe.name} requires a declared result type")
        return E.PyUdfWrapper(serialized=fe.py_fn,
                              args=tuple(conv(c) for c in fe.children),
                              return_type=fe.dtype, name=fe.name)
    raise NotConvertible(f"expression {fe.name} is not supported yet")


def convert_sort_order(fe: ForeignExpr) -> SortExpr:
    if fe.name != "SortOrder":
        raise NotConvertible(f"expected SortOrder, got {fe.name}")
    return SortExpr(child=convert_expr_with_fallback(fe.children[0]),
                    asc=bool(fe.attrs.get("asc", True)),
                    nulls_first=bool(fe.attrs.get("nulls_first",
                                                  fe.attrs.get("asc", True))))


# aggregate functions (NativeConverters.convertAggregateExpr:1228-1353)
_AGG_FNS = {
    "Max": "max", "Min": "min", "Sum": "sum", "Average": "avg",
    "StddevSamp": "stddev_samp", "VarianceSamp": "var_samp",
    "Count": "count", "First": "first", "CollectList": "collect_list",
    "CollectSet": "collect_set", "BloomFilterAggregate": "bloom_filter",
    "BrickhouseCollect": "brickhouse_collect",
    "BrickhouseCombineUnique": "brickhouse_combine_unique",
}


def convert_agg_expr(fe: ForeignExpr) -> AggExpr:
    """Foreign AggregateExpression node -> AggExpr.  Shape:
    ForeignExpr("AggregateExpression", children=(fn_node,),
    attrs={distinct}); fn_node.name in _AGG_FNS (or carries py_fn for the
    UDAF fallback, the SparkUDAFWrapper analogue)."""
    if fe.name != "AggregateExpression":
        raise NotConvertible(f"expected AggregateExpression, got {fe.name}")
    agg = fe.children[0]
    distinct = bool(fe.attrs.get("distinct", False))
    if distinct:
        # the engine has no device distinct accumulation; Spark's
        # optimizer rewrites distinct aggregates into two-level group-bys
        # (RewriteDistinctAggregates) before plans reach the converter, so
        # a surviving distinct flag means an unexpected plan shape — fall
        # back rather than silently computing the non-distinct value
        raise NotConvertible("distinct aggregates are not converted")
    if agg.name in _AGG_FNS:
        fn = _AGG_FNS[agg.name]
        if agg.name == "First" and agg.attrs.get("ignore_nulls"):
            fn = "first_ignores_null"
        return AggExpr(
            fn=fn,
            children=tuple(convert_expr_with_fallback(c)
                           for c in agg.children),
            return_type=_dt(agg), distinct=distinct)
    if agg.py_fn is not None and config.UDF_FALLBACK_ENABLE.get():
        return AggExpr(
            fn="udaf",
            children=tuple(convert_expr_with_fallback(c)
                           for c in agg.children),
            return_type=_dt(agg), distinct=distinct, udaf=agg.py_fn)
    raise NotConvertible(f"aggregate {agg.name} is not supported yet")


_JOIN_TYPES = {
    "Inner": "inner", "FullOuter": "full", "LeftOuter": "left",
    "RightOuter": "right", "LeftSemi": "left_semi", "LeftAnti": "left_anti",
    "RightSemi": "right_semi", "RightAnti": "right_anti",
    "ExistenceJoin": "existence", "Cross": "inner",
}


def convert_join_type(name: str) -> str:
    """NativeConverters.convertJoinType:1356 analogue."""
    if name not in _JOIN_TYPES:
        raise NotConvertible(f"join type {name} is not supported yet")
    return _JOIN_TYPES[name]
