"""Ingest REAL Spark `explain formatted` dumps into ForeignNode plans.

The reference's IT harness checks every TPC-DS query's physical plan
against committed golden dumps (dev/auron-it .../tpcds-plan-stability/
spark-3.5/q*.txt, produced by Spark 3.5 + the reference extension and
normalized by PlanStabilityChecker.scala).  Each dump carries the
AQE-wrapped plan with an `== Initial Plan ==` section: the VANILLA Spark
physical plan (Exchange / HashAggregate / SortMergeJoin / Scan parquet
...) exactly as Spark's planner emitted it, plus per-node detail blocks
(Output/Input attribute lists, Condition, Keys/Functions/Results,
Arguments) — i.e. genuinely Spark-authored plan text nobody in this repo
wrote.

This module parses that text and binds it to `ForeignNode` trees — the
same boundary a live JVM bridge would cross (AuronConverters.scala:
186-209 receives SparkPlan; we receive its printed form) — so the
convert strategy, converters, and engine run REAL Spark plans instead of
author-built shapes.  Differential harness: auron_tpu.it.refplans.

Structure:
- `parse_explain(text)` -> `ExplainDump`: section split, tree parse
  (indent-encoded child edges), detail-block parse, subquery index.
- `ExprParser`: Spark's expression-print grammar (attr refs `name#id`
  where `name` may itself be arbitrary expression text, unquoted string
  literals incl. multi-word CHAR-padded ones, `cast(x as type)`,
  CASE WHEN, windowspecdefinition, Subquery refs with embedded commas).
- `ExplainBinder`: per-op lowering to the ForeignNode vocabulary the
  session front door consumes, with type propagation from scan
  ReadSchema through every expression (engine inference rules), the
  partial/final agg pairing convention of it/queries.two_phase_agg, and
  optional adaptation of decimal columns to the generated catalog's
  float64 warehouse (UnscaledValue/MakeDecimal/CheckOverflow collapse,
  exact because the scale factors cancel).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from auron_tpu.frontend.foreign import (ForeignExpr, ForeignNode,
                                        _dtype_from_str, falias, fcall,
                                        fcol, flit)
from auron_tpu.ir.schema import DataType, Field, Schema, TypeId

I32 = DataType.int32()
I64 = DataType.int64()
F64 = DataType.float64()
BOOL = DataType.bool_()
STR = DataType.string()
DATE = DataType.date32()


class ExplainParseError(ValueError):
    pass


class BindError(ValueError):
    pass


# ---------------------------------------------------------------------------
# dump parsing
# ---------------------------------------------------------------------------

@dataclass
class Detail:
    op: str
    kv: Dict[str, str] = dc_field(default_factory=dict)
    lists: Dict[str, List[str]] = dc_field(default_factory=dict)


@dataclass
class ExplainDump:
    root: int                                   # main Initial Plan root
    children: Dict[int, List[int]]              # opid -> child opids
    details: Dict[int, Detail]
    subqueries: Dict[int, int]                  # subquery expr id -> root
    # Initial-plan ref id -> defined id, when AQE renumbering makes an
    # Initial section reference an id whose plan prints under the Final
    # section's id (q14b: ref #114, definition #54)
    subquery_alias: Dict[int, int] = dc_field(default_factory=dict)


_TREE_RE = re.compile(r"^(?P<pre>[\s:+|-]*?)(?:\* )?"
                      r"(?P<name>[A-Za-z][^()]*?(?:\([^)]*\))?) "
                      r"\((?P<id>\d+)\)(?:, .*)?\s*$")
_DETAIL_HDR = re.compile(r"^\((\d+)\) ([^\[\n]+?)(?: \[codegen.*)?$")
_KV_RE = re.compile(r"^([A-Za-z][A-Za-z ]*?)\s*(?:\[(\d+)\])?\s*: (.*)$")
_SUBQ_HDR = re.compile(
    r"^Subquery:\d+ Hosting operator id = \d+ Hosting Expression = "
    r"(?:ReusedSubquery )?Subquery (?:scalar-)?subquery#(\d+)", re.M)


def split_top(s: str, sep: str = ",") -> List[str]:
    """Split on top-level `sep` (depth tracked across () and [])."""
    out, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        elif ch == sep and depth == 0:
            out.append(s[start:i])
            start = i + 1
    out.append(s[start:])
    return [p.strip() for p in out]


def _parse_tree(lines: List[str]) -> Tuple[int, Dict[int, List[int]]]:
    """Indent-encoded tree lines -> (root id, children edges).  Spark's
    formatted explain adds 3 columns per level (`+- ` / `:- ` / `:  `)."""
    root = None
    children: Dict[int, List[int]] = {}
    stack: List[Tuple[int, int]] = []           # (depth, opid)
    base = None
    for ln in lines:
        m = _TREE_RE.match(ln)
        if not m:
            continue
        pre = m.group("pre")
        opid = int(m.group("id"))
        if base is None:
            base = len(pre)
        depth = (len(pre) - base) // 3
        children.setdefault(opid, [])
        while stack and stack[-1][0] >= depth:
            stack.pop()
        if stack:
            children[stack[-1][1]].append(opid)
        elif root is None:
            root = opid
        stack.append((depth, opid))
    if root is None:
        raise ExplainParseError("no tree lines found")
    return root, children


def _initial_tree_lines(chunk: str) -> List[str]:
    """The `== Initial Plan ==` tree lines of one AdaptiveSparkPlan
    chunk (ends at the first blank line)."""
    m = re.search(r"== Initial Plan ==\n(.*?)(?:\n\s*\n|\Z)", chunk,
                  re.S)
    if not m:
        # non-AQE dump: whole chunk is the tree
        m = re.search(r"== Physical Plan ==\n(.*?)(?:\n\s*\n|\Z)", chunk,
                      re.S)
        if not m:
            raise ExplainParseError("no Initial Plan section")
    return m.group(1).splitlines()


def _parse_details(text: str) -> Dict[int, Detail]:
    details: Dict[int, Detail] = {}
    for block in re.split(r"\n\s*\n", text):
        lines = block.strip("\n").splitlines()
        if not lines:
            continue
        hdr = _DETAIL_HDR.match(lines[0].strip())
        if not hdr:
            continue
        opid = int(hdr.group(1))
        d = Detail(op=hdr.group(2).strip())
        for ln in lines[1:]:
            m = _KV_RE.match(ln.strip())
            if not m:
                continue
            key, n, val = m.group(1).strip(), m.group(2), m.group(3)
            if n is not None and val.startswith("[") and val.endswith("]"):
                inner = val[1:-1]
                d.lists[key] = split_top(inner) if inner.strip() else []
            else:
                d.kv[key] = val
        details[opid] = d
    return details


def parse_explain(text: str) -> ExplainDump:
    """Parse one plan-stability dump into its Initial-plan tree, detail
    blocks, and scalar-subquery index."""
    if "more fields" in text:
        # spark.sql.debug.maxToStringFields truncation: the dump does
        # not contain the elided attribute definitions, so downstream
        # references cannot be resolved (q66's 26-column project)
        raise ExplainParseError(
            "dump truncates attribute lists ('... N more fields')")
    # `, [id=#N]` plan-id annotations on Subquery refs sit at top level
    # of expression text and break comma-splitting; they carry no
    # semantics (the subquery id before them is the key)
    text = re.sub(r", \[id=#?\d+\]", "", text)
    parts = re.split(r"^===== Subqueries =====$", text, maxsplit=1,
                     flags=re.M)
    main = parts[0]
    details = _parse_details(text)
    root, children = _parse_tree(_initial_tree_lines(main))
    subqueries: Dict[int, int] = {}
    if len(parts) > 1:
        chunks = re.split(_SUBQ_HDR, parts[1])
        # chunks = [pre, id1, chunk1, id2, chunk2, ...]
        for i in range(1, len(chunks) - 1, 2):
            sid = int(chunks[i])
            chunk = chunks[i + 1]
            if sid in subqueries:
                continue                        # ReusedSubquery repeats
            sroot, sch = _parse_tree(_initial_tree_lines(chunk))
            subqueries[sid] = sroot
            children.update(sch)
    # map Initial-plan subquery refs whose id never got a printed plan
    # to the orphan definition (AQE renumbering): unique unmatched ref
    # <-> unique unmatched definition
    referenced: set = set()
    contexts: Dict[int, set] = {}
    for opid in children:
        d = details.get(opid)
        if d is None:
            continue
        for text in list(d.kv.values()) + [
                x for lst in d.lists.values() for x in lst]:
            for m in re.finditer(r"(?:scalar-)?subquery#(\d+)", text):
                sid = int(m.group(1))
                referenced.add(sid)
                contexts.setdefault(sid, set()).add(
                    re.sub(r"#\d+", "#", text))
    missing = sorted(referenced - set(subqueries))
    orphans = sorted(set(subqueries) - referenced)
    alias: Dict[int, int] = {}
    for mid in missing:
        # same normalized surrounding text as a defined ref => the same
        # subquery printed under a second AQE number (q14b's threshold
        # filter appears in both channel branches as #54 and #114)
        cands = [did for did in subqueries
                 if contexts.get(did, set()) & contexts.get(mid, set())]
        if len(cands) == 1:
            alias[mid] = cands[0]
    still = [m for m in missing if m not in alias]
    if len(still) == 1 and len(orphans) == 1:
        alias[still[0]] = orphans[0]
    return ExplainDump(root=root, children=children, details=details,
                       subqueries=subqueries, subquery_alias=alias)


# ---------------------------------------------------------------------------
# expression text -> ForeignExpr
# ---------------------------------------------------------------------------

_KEYWORDS = {"AND", "OR", "NOT", "IN", "CASE", "WHEN", "THEN", "ELSE",
             "END", "AS", "ASC", "DESC", "NULLS", "FIRST", "LAST", "IS",
             "LIKE"}

_TOKEN_RE = re.compile(r"""
    (?P<date>\d{4}-\d{2}-\d{2})
  | (?P<num>\d+\.\d+(?:[Ee][+-]?\d+)?|\d+(?:[Ee][+-]?\d+)?[LSB]?)
  | (?P<name>[A-Za-z_][A-Za-z0-9_$.\-]*)
  | (?P<hash>\#\d+)
  | (?P<op><=>|<=|>=|!=|=|<|>|\(|\)|\[|\]|,|\+|-|\*|/|%|&|\||\^|\.)
""", re.X)

# dump-printed function name -> Foreign (Spark class) name
_DUMP_FNS = {
    "isnotnull": "IsNotNull", "isnull": "IsNull",
    "substr": "Substring", "substring": "Substring",
    "coalesce": "Coalesce", "round": "Round", "bround": "BRound",
    "date_add": "DateAdd", "date_sub": "DateSub",
    "datediff": "DateDiff", "year": "Year", "month": "Month",
    "quarter": "Quarter", "day": "DayOfMonth",
    "dayofmonth": "DayOfMonth", "dayofweek": "DayOfWeek",
    "abs": "Abs", "least": "Least", "greatest": "Greatest",
    "length": "Length", "char_length": "Length",
    "lower": "Lower", "upper": "Upper", "concat": "Concat",
    "concat_ws": "ConcatWs", "ltrim": "StringTrimLeft",
    "rtrim": "StringTrimRight", "trim": "StringTrim",
    "sqrt": "Sqrt", "power": "Pow", "pow": "Pow", "exp": "Exp",
    "ln": "Log", "log10": "Log10", "floor": "Floor", "ceil": "Ceil",
    "ceiling": "Ceil", "if": "If", "nvl": "Nvl", "nullif": "NullIf",
    "shiftright": "ShiftRight", "shiftleft": "ShiftLeft",
    "promote_precision": "PromotePrecision",
    "knownfloatingpointnormalized": "KnownFloatingPointNormalized",
    "knownnotnull": "KnownNotNull",
    "normalizenanandzero": "NormalizeNaNAndZero",
    "UnscaledValue": "UnscaledValue", "MakeDecimal": "MakeDecimal",
    "CheckOverflow": "CheckOverflow", "unscaledvalue": "UnscaledValue",
    "makedecimal": "MakeDecimal", "checkoverflow": "CheckOverflow",
}

_AGG_DUMP_FNS = {
    "sum": "Sum", "avg": "Average", "count": "Count", "min": "Min",
    "max": "Max", "stddev_samp": "StddevSamp",
    "var_samp": "VarianceSamp", "variance": "VarianceSamp",
    "stddev": "StddevSamp", "first": "First", "collect_list":
    "CollectList", "collect_set": "CollectSet",
}

_CMP = {"=": "EqualTo", "<": "LessThan", ">": "GreaterThan",
        "<=": "LessThanOrEqual", ">=": "GreaterThanOrEqual",
        "<=>": "EqualNullSafe"}
_ARITH = {"+": "Add", "-": "Subtract", "*": "Multiply", "/": "Divide",
          "%": "Remainder"}


@dataclass
class _Tok:
    kind: str
    text: str
    start: int
    end: int


def _lex(s: str) -> List[_Tok]:
    toks, i = [], 0
    n = len(s)
    while i < n:
        if s[i].isspace():
            i += 1
            continue
        m = _TOKEN_RE.match(s, i)
        if not m:
            raise ExplainParseError(f"lex error at {s[i:i+30]!r}")
        kind = m.lastgroup
        toks.append(_Tok(kind, m.group(), m.start(), m.end()))
        i = m.end()
    toks.append(_Tok("eof", "", n, n))
    return toks


class ExprParser:
    """Parses Spark's printed expression grammar against an id->Field
    scope.  `binder` supplies subquery literal resolution and the
    decimal-adaptation policy."""

    def __init__(self, text: str, binder: "ExplainBinder"):
        self.src = text
        self.toks = _lex(text)
        self.i = 0
        self.b = binder

    # -- token helpers -----------------------------------------------------

    def peek(self, k: int = 0) -> _Tok:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "name" and t.text.upper() in words and \
            t.text.upper() in _KEYWORDS

    def eat_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.text in ops

    def eat_op(self, *ops: str) -> Optional[str]:
        if self.at_op(*ops):
            return self.next().text
        return None

    # -- entry -------------------------------------------------------------

    def parse(self) -> ForeignExpr:
        e = self.or_expr()
        if self.peek().kind != "eof":
            raise ExplainParseError(
                f"trailing tokens at {self.src[self.peek().start:][:40]!r}"
                f" in {self.src[:120]!r}")
        return e

    def or_expr(self) -> ForeignExpr:
        e = self.and_expr()
        while self.eat_kw("OR"):
            e = fcall("Or", e, self.and_expr())
        return e

    def and_expr(self) -> ForeignExpr:
        e = self.not_expr()
        while self.eat_kw("AND"):
            e = fcall("And", e, self.not_expr())
        return e

    def not_expr(self) -> ForeignExpr:
        if self.eat_kw("NOT"):
            return fcall("Not", self.not_expr())
        return self.predicate()

    def predicate(self) -> ForeignExpr:
        e = self.add_expr()
        if self.eat_kw("IN"):
            if not self.eat_op("("):
                raise ExplainParseError("expected ( after IN")
            vals = self._in_list(e)
            return fcall("In", e, *vals)
        if self.peek().kind == "name" and self.peek().text == "INSET":
            # InSet prints its values bare and unparenthesized:
            # `x INSET 1200, 1201, 1202 AND ...` — values are literals
            # only, so parse them at unary level (a full operand parse
            # would swallow the trailing AND conjunct into the last
            # value: In(x, ..., And(1202, isnotnull(x))))
            self.next()
            hint = self._type_of(e)
            str_hint = hint is not None and hint.id == TypeId.STRING
            vals: List[ForeignExpr] = []
            while True:
                if str_hint and self._span_is_bare_literal():
                    vals.append(self._raw_string_span())
                else:
                    v = self.unary()
                    if v.name == "Literal":
                        v = self._coerce(v, hint)
                    vals.append(v)
                if not self.at_op(","):
                    break
                self.next()
            return fcall("In", e, *vals)
        if self.at_kw("IS"):
            self.next()
            neg = self.eat_kw("NOT")
            t = self.next()
            if t.text.lower() != "null":
                raise ExplainParseError("expected NULL after IS")
            x = fcall("IsNull", e)
            return fcall("Not", x) if neg else x
        op = self.eat_op("=", "<", ">", "<=", ">=", "<=>", "!=")
        if op:
            rhs = self._operand(self._type_of(e))
            node = fcall(_CMP.get(op, "EqualTo"), e, rhs)
            if op == "!=":
                node = fcall("Not", fcall("EqualTo", e, rhs))
            return node
        if self.eat_kw("LIKE"):
            rhs = self._operand(STR)
            return fcall("Like", e, rhs)
        return e

    _BITS = {"&": "BitwiseAnd", "|": "BitwiseOr", "^": "BitwiseXor"}

    def add_expr(self) -> ForeignExpr:
        e = self.bit_expr()
        while True:
            op = self.eat_op("+", "-")
            if not op:
                return e
            e = fcall(_ARITH[op], e, self.bit_expr())

    def bit_expr(self) -> ForeignExpr:
        e = self.mul_expr()
        while True:
            op = self.eat_op("&", "|", "^")
            if not op:
                return e
            e = fcall(self._BITS[op], e, self.mul_expr())

    def mul_expr(self) -> ForeignExpr:
        e = self.unary()
        while True:
            op = self.eat_op("*", "/", "%")
            if not op:
                return e
            e = fcall(_ARITH[op], e, self.unary())

    def unary(self) -> ForeignExpr:
        if self.eat_op("-"):
            child = self.unary()
            if child.name == "Literal" and isinstance(
                    child.value, (int, float)):
                return flit(-child.value, child.dtype)
            return fcall("UnaryMinus", child)
        return self.primary()

    # -- primaries ---------------------------------------------------------

    def primary(self) -> ForeignExpr:
        start = self.peek().start
        e = self._primary_inner()
        # `<anything>#id` = attribute reference whose NAME is the raw
        # preceding text (aggregate result attrs print this way)
        if self.peek().kind == "hash":
            h = self.next()
            return self.b.ref(int(h.text[1:]),
                              self.src[start:h.start].strip())
        return e

    def _primary_inner(self) -> ForeignExpr:
        t = self.peek()
        if t.kind == "op" and t.text == "(":
            self.next()
            e = self.or_expr()
            if not self.eat_op(")"):
                raise ExplainParseError(
                    f"expected ) at {self.src[self.peek().start:][:50]!r}"
                    f" in {self.src[:140]!r}")
            return e
        if t.kind == "date":
            self.next()
            import datetime
            d = datetime.date.fromisoformat(t.text)
            return flit((d - datetime.date(1970, 1, 1)).days, DATE)
        if t.kind == "num":
            self.next()
            return self._num_lit(t.text)
        if t.kind == "hash":
            # bare `#12` (normalized internal attr)
            self.next()
            return self.b.ref(int(t.text[1:]), "")
        if t.kind == "name":
            up = t.text.upper()
            if up == "CASE":
                return self._case()
            if t.text == "cast" or t.text == "ansi_cast":
                return self._cast()
            if t.text == "Subquery" or t.text == "ReusedSubquery":
                return self._subquery()
            if t.text.lower() == "null":
                self.next()
                return flit(None, DataType.null())
            if t.text.lower() == "true":
                self.next()
                return flit(True, BOOL)
            if t.text.lower() == "false":
                self.next()
                return flit(False, BOOL)
            if self.peek(1).kind == "op" and self.peek(1).text == "(":
                return self._call()
            # bare word: unquoted string literal (Spark prints string
            # literals without quotes); may be multi-word
            return self._bare_string()
        raise ExplainParseError(
            f"unexpected token {t.text!r} in {self.src[:120]!r}")

    def _num_lit(self, text: str) -> ForeignExpr:
        if text and text[-1] in "LSB" :
            v = int(text[:-1])
            return flit(v, I64 if text[-1] == "L" else I32)
        if "." in text or "e" in text.lower():
            return flit(float(text), F64)
        v = int(text)
        return flit(v, I32 if -2**31 <= v < 2**31 else I64)

    def _bare_string(self) -> ForeignExpr:
        """Capture an unquoted string literal up to the next top-level
        delimiter.  CHAR-type literals are right-padded in the dump;
        rstrip to match the unpadded warehouse.  Comparison operators
        terminate the capture; `/` does not (values like "N/A")."""
        start = self.peek().start
        depth = 0
        end = start
        while True:
            t = self.peek()
            if t.kind == "eof":
                end = t.start
                break
            if t.kind == "hash":
                # `word#id` is an attribute ref, not a literal: stop so
                # primary() wraps the consumed span as the ref base
                end = t.start
                break
            if t.kind == "op" and t.text in "([":
                depth += 1
            elif t.kind == "op" and t.text in ")]":
                if depth == 0:
                    end = t.start
                    break
                depth -= 1
            elif depth == 0 and t.kind == "op" and \
                    t.text in (",", "=", "<", ">", "<=", ">=", "<=>",
                               "!="):
                end = t.start
                break
            elif depth == 0 and t.kind == "name" and \
                    t.text.upper() in ("AND", "OR", "THEN", "ELSE", "END",
                                       "WHEN", "ASC", "DESC", "AS", "IN",
                                       "IS", "LIKE"):
                end = t.start
                break
            self.next()
            end = t.end
        if end == start:
            # a lone keyword-looking literal ("OR"egon, "IN"diana):
            # take exactly one token
            t = self.next()
            return flit(t.text, STR)
        return flit(self.src[start:end].rstrip(), STR)

    def _case(self) -> ForeignExpr:
        self.next()                              # CASE
        children: List[ForeignExpr] = []
        while self.eat_kw("WHEN"):
            cond = self.or_expr()
            if not self.eat_kw("THEN"):
                raise ExplainParseError("expected THEN")
            # THEN/ELSE operands share the branch value type
            children.append(cond)
            children.append(self._operand(None))
        if self.eat_kw("ELSE"):
            children.append(self._operand(
                self._type_of(children[1]) if len(children) > 1 else None))
        if not self.eat_kw("END"):
            raise ExplainParseError("expected END")
        # untyped `null` branch values take the type of a typed sibling
        # value (q39: CASE WHEN m=0 THEN null ELSE s/m END must be f64,
        # not null-typed — the device kernel needs a concrete dtype)
        has_else = len(children) % 2 == 1
        value_idx = set(range(1, len(children) - (1 if has_else else 0),
                              2))
        if has_else:
            value_idx.add(len(children) - 1)
        vtype = None
        for i in sorted(value_idx):
            t = self._type_of(children[i])
            if t is not None and t.id != TypeId.NULL:
                vtype = t
                break
        if vtype is not None:
            children = [
                flit(None, vtype)
                if (i in value_idx and c.name == "Literal" and
                    c.value is None and
                    (c.dtype is None or c.dtype.id == TypeId.NULL))
                else c
                for i, c in enumerate(children)]
        return fcall("CaseWhen", *children)

    def _cast(self) -> ForeignExpr:
        self.next()                              # cast
        if not self.eat_op("("):
            raise ExplainParseError("expected ( after cast")
        child = self.or_expr()
        if not self.eat_kw("AS"):
            raise ExplainParseError("expected AS in cast")
        dtype = self._type_name()
        if not self.eat_op(")"):
            raise ExplainParseError("expected ) after cast")
        return self.b.adapt_cast(child, dtype)

    def _type_name(self) -> DataType:
        t = self.next()
        name = t.text
        if self.at_op("("):                      # decimal(p,s)
            self.next()
            args = []
            while not self.eat_op(")"):
                args.append(self.next().text)
                self.eat_op(",")
            name = f"{name}({','.join(args)})"
        return _dtype_from_str(name)

    def _call(self) -> ForeignExpr:
        t = self.next()                          # fn name
        self.next()                              # (
        name = t.text
        # aggregate printed inside Functions lists
        prefix = None
        for p in ("partial_", "merge_", "final_"):
            if name.startswith(p):
                prefix, name = p[:-1], name[len(p):]
                break
        args: List[ForeignExpr] = []
        distinct = False
        if self.peek().kind == "name" and self.peek().text == "distinct":
            self.next()
            distinct = True
        while not self.eat_op(")"):
            if self.peek().kind == "eof":
                raise ExplainParseError("unterminated call")
            if self.at_op(","):
                # an empty argument slot: Spark prints string literals
                # unquoted, so concat(a, ", ", b) renders as `a, , , b`
                # and concat(a, ",", b) as `a, ,, b` (literal comma
                # adjacent to the separator)
                lit_tok = self.next()
                sep = self.peek()
                if sep.kind == "op" and sep.text == "," and \
                        sep.start == lit_tok.end:
                    args.append(flit(",", STR))
                else:
                    args.append(flit(", ", STR))
                self.eat_op(",")
                continue
            # no coercion hint: positional args have heterogeneous types
            # (substr(str, 1, 5)); bare-word captures still yield strings
            args.append(self._operand(None, stop_paren=True))
            if self.eat_op(",") and self.at_op(")"):
                # trailing empty slot: an empty-STRING literal printed
                # as nothing (`coalesce(c_last_name#8, )`, the null-safe
                # join-key idiom)
                args.append(flit("", STR))
        if name in _AGG_DUMP_FNS or prefix is not None:
            return self.b.agg_expr(_AGG_DUMP_FNS.get(name, name), args,
                                   distinct=distinct, prefix=prefix)
        if name == "windowspecdefinition":
            return ForeignExpr("__windowspec__",
                               children=tuple(args))
        if name.endswith("$"):                   # unboundedpreceding$()
            return ForeignExpr("__frame__", value=name)
        if name == "specifiedwindowframe":
            return ForeignExpr("__frame__", children=tuple(args))
        if name in ("hashpartitioning", "rangepartitioning"):
            return ForeignExpr("__part__", value=name,
                               children=tuple(args))
        if name in ("rank", "dense_rank", "row_number", "percent_rank",
                    "cume_dist", "ntile", "lead", "lag", "nth_value"):
            return ForeignExpr("__winfn__", value=name,
                               children=tuple(args))
        if name == "date_add" or name == "date_sub":
            return fcall(_DUMP_FNS[name], *args)
        fname = _DUMP_FNS.get(name)
        if fname is None:
            # exact Foreign name already (CheckOverflow etc. print as-is)
            fname = name
        return self.b.adapt_fn(fname, args)

    def _subquery(self) -> ForeignExpr:
        if self.peek().text == "ReusedSubquery":
            self.next()                          # ReusedSubquery Subquery..
        if self.peek().text == "Subquery":
            self.next()
        t = self.next()                          # (scalar-)subquery#ID? or
        sid = None
        if t.kind == "name":                     # 'subquery' / 'scalar-subquery'
            h = self.next()
            if h.kind != "hash":
                raise ExplainParseError("expected #id after subquery")
            sid = int(h.text[1:])
        elif t.kind == "hash":
            sid = int(t.text[1:])
        else:
            raise ExplainParseError("bad subquery ref")
        # optional ", [id=#N]"
        if self.at_op(","):
            save = self.i
            self.next()
            if self.at_op("["):
                while not self.eat_op("]"):
                    if self.peek().kind == "eof":
                        raise ExplainParseError("unterminated [id=..]")
                    self.next()
            else:
                self.i = save
        field_name = None
        if self.at_op("."):
            # struct-field access on a multi-column single-row subquery:
            # `Subquery subquery#2, [id=#3].count(1)` picks the output
            # column named count(1)
            self.next()
            start = self.peek().start
            t = self.next()
            if t.kind != "name":
                raise ExplainParseError("expected field after subquery.")
            end = t.end
            if self.at_op("("):
                depth = 0
                while True:
                    t2 = self.next()
                    if t2.kind == "eof":
                        raise ExplainParseError("unterminated field ref")
                    if t2.kind == "op" and t2.text == "(":
                        depth += 1
                    elif t2.kind == "op" and t2.text == ")":
                        depth -= 1
                        if depth == 0:
                            end = t2.end
                            break
            field_name = self.src[start:end]
        return self.b.subquery_literal(sid, field_name)

    # -- operands with literal coercion ------------------------------------

    def _operand(self, hint: Optional[DataType],
                 stop_paren: bool = False) -> ForeignExpr:
        """Parse an operand; a bare word (or word sequence) is an
        unquoted string literal, coerced to `hint` when sensible.
        A string-typed hint lets keyword-looking values ("OR"egon)
        through as literals."""
        t = self.peek()
        str_hint = hint is not None and hint.id == TypeId.STRING
        if str_hint and self._span_is_bare_literal():
            return self._raw_string_span()
        kw_ok = t.text.upper() not in _KEYWORDS
        if t.kind == "name" and kw_ok and \
                not (self.peek(1).kind == "op" and
                     self.peek(1).text == "(") and \
                self.peek(1).kind != "hash" and \
                t.text not in ("cast", "null", "true", "false",
                               "Subquery", "ReusedSubquery", "distinct"):
            lit = self._bare_string()
            return self._coerce(lit, hint)
        e = self.or_expr()
        if e.name == "Literal":
            e = self._coerce(e, hint)
        return e

    _SPAN_STOPS = ("AND", "OR", "THEN", "ELSE", "END", "WHEN", "ASC",
                   "DESC", "AS", "IS")

    def _span_scan(self) -> Tuple[int, bool]:
        """Lookahead to the operand's top-level delimiter.  Returns
        (token index after the span, span contains attr refs / calls /
        subqueries — i.e. must be parsed as an expression)."""
        j = self.i
        depth = 0
        has_expr = False
        while True:
            t = self.toks[min(j, len(self.toks) - 1)]
            if t.kind == "eof":
                return j, has_expr
            if t.kind == "op" and t.text in "([":
                depth += 1
            elif t.kind == "op" and t.text in ")]":
                if depth == 0:
                    return j, has_expr
                depth -= 1
            elif depth == 0 and t.kind == "op" and t.text == ",":
                return j, has_expr
            elif depth == 0 and t.kind == "name" and \
                    t.text.upper() in self._SPAN_STOPS:
                return j, has_expr
            if t.kind == "hash":
                has_expr = True
            if t.kind == "name" and t.text in ("cast", "Subquery",
                                               "ReusedSubquery", "null"):
                has_expr = True
            j += 1

    def _span_is_bare_literal(self) -> bool:
        end, has_expr = self._span_scan()
        return end > self.i and not has_expr

    def _raw_string_span(self) -> ForeignExpr:
        """Consume the whole operand span as one unquoted string value
        (handles ">10000", "N/A", "United States", "OR"egon)."""
        end, _ = self._span_scan()
        start = self.toks[self.i].start
        stop = self.toks[end - 1].end if end > self.i else start
        while self.i < end:
            self.next()
        return flit(self.src[start:stop].rstrip(), STR)

    def _in_list(self, child: ForeignExpr) -> List[ForeignExpr]:
        hint = self._type_of(child)
        vals: List[ForeignExpr] = []
        if hint is not None and hint.id == TypeId.STRING:
            # raw element capture: state codes collide with keywords
            # ("IN", "OR"), values may be multi-word / contain slashes
            depth = 0
            start = self.peek().start
            while True:
                t = self.peek()
                if t.kind == "eof":
                    raise ExplainParseError("unterminated IN list")
                if t.kind == "op" and t.text in "([":
                    depth += 1
                elif t.kind == "op" and t.text == ")":
                    if depth == 0:
                        if self.src[start:t.start].strip():
                            vals.append(flit(
                                self.src[start:t.start].rstrip(), STR))
                        self.next()
                        return vals
                    depth -= 1
                elif t.kind == "op" and t.text == "]":
                    depth -= 1
                elif t.kind == "op" and t.text == "," and depth == 0:
                    vals.append(flit(self.src[start:t.start].rstrip(),
                                     STR))
                    self.next()
                    start = self.peek().start
                    continue
                self.next()
        while not self.eat_op(")"):
            if self.peek().kind == "eof":
                raise ExplainParseError("unterminated IN list")
            vals.append(self._operand(hint))
            self.eat_op(",")
        return vals

    def _coerce(self, lit: ForeignExpr, hint: Optional[DataType]
                ) -> ForeignExpr:
        if hint is None or lit.value is None or lit.dtype == hint:
            return lit
        try:
            if hint.id in (TypeId.INT8, TypeId.INT16, TypeId.INT32,
                           TypeId.INT64):
                return flit(int(lit.value), hint)
            if hint.id in (TypeId.FLOAT32, TypeId.FLOAT64):
                return flit(float(lit.value), hint)
            if hint.id == TypeId.DECIMAL:
                return flit(float(lit.value), F64)
            if hint.id == TypeId.STRING:
                v = lit.value
                if isinstance(v, float) and v == int(v):
                    v = int(v)
                return flit(str(v), STR)
            if hint.id == TypeId.DATE32 and isinstance(lit.value, str):
                import datetime
                d = datetime.date.fromisoformat(lit.value.strip())
                return flit((d - datetime.date(1970, 1, 1)).days, DATE)
        except (ValueError, TypeError):
            pass
        return lit

    def _type_of(self, e: ForeignExpr) -> Optional[DataType]:
        return self.b.type_of(e)


# ---------------------------------------------------------------------------
# binder
# ---------------------------------------------------------------------------

# TPC-DS column prefix -> table name (longest match wins)
_PREFIX_TABLES = {
    "ss_": "store_sales", "sr_": "store_returns", "cs_": "catalog_sales",
    "cr_": "catalog_returns", "ws_": "web_sales", "wr_": "web_returns",
    "inv_": "inventory", "d_": "date_dim", "t_": "time_dim",
    "i_": "item", "s_": "store", "c_": "customer",
    "ca_": "customer_address", "cd_": "customer_demographics",
    "hd_": "household_demographics", "ib_": "income_band",
    "w_": "warehouse", "sm_": "ship_mode", "r_": "reason",
    "p_": "promotion", "cc_": "call_center", "cp_": "catalog_page",
    "web_": "web_site", "wp_": "web_page",
}


def _infer_table(cols: Sequence[str]) -> Optional[str]:
    best = None
    for c in cols:
        for pre in sorted(_PREFIX_TABLES, key=len, reverse=True):
            if c.startswith(pre):
                t = _PREFIX_TABLES[pre]
                if best is None:
                    best = t
                break
    return best


class ExplainBinder:
    """Binds a parsed dump to a ForeignNode plan.

    catalog: it.datagen.Catalog for real file groups (execution);
        None fabricates scan paths (conversion-level validation only).
    adapt: rewrite decimal columns/wrappers to the catalog's float64
        warehouse types (defaults to catalog is not None).
    subquery_eval: callback(plan: ForeignNode) -> scalar python value,
        used to splice scalar subqueries as literals (the engine's
        sql front door does the same, sql/lower.py).
    """

    def __init__(self, dump: ExplainDump, catalog=None,
                 adapt: Optional[bool] = None, n_parts: int = 4,
                 subquery_eval: Optional[
                     Callable[[ForeignNode], Any]] = None,
                 default_limit: int = 100):
        self.dump = dump
        self.cat = catalog
        self.adapt = (catalog is not None) if adapt is None else adapt
        self.n_parts = n_parts
        self.subquery_eval = subquery_eval
        self.default_limit = default_limit
        self.fields: Dict[int, Field] = {}
        self._subq_memo: Dict[int, ForeignExpr] = {}
        self._bound: Dict[int, ForeignNode] = {}
        # column name -> ReadSchema decimal scale, recorded when adapt
        # mode replaces a decimal scan column with the catalog's float
        self._orig_scale: Dict[str, int] = {}

    # -- public ------------------------------------------------------------

    def bind(self) -> ForeignNode:
        return self._bind(self.dump.root, parent=None)

    # -- scope helpers -----------------------------------------------------

    def ref(self, fid: int, base: str) -> ForeignExpr:
        f = self.fields.get(fid)
        if f is None:
            raise BindError(f"unknown attribute #{fid} ({base!r})")
        return fcol(f.name, f.dtype)

    def define(self, fid: int, base: str, dtype: DataType,
               fresh: bool = False) -> Field:
        name = f"{base}#{fid}" if base else f"_#{fid}"
        if fresh and fid in self.fields:
            # plan-stability normalization reuses attr ids across plan
            # branches (q70 scans `store` twice, both printing
            # s_state#13): a fresh SOURCE definition must not collide
            # with the earlier branch's column when the branches join
            self._dup = getattr(self, "_dup", 0) + 1
            name = f"{name}@{self._dup}"
        f = Field(name, dtype)
        self.fields[fid] = f
        return f

    def type_of(self, e: ForeignExpr) -> Optional[DataType]:
        if e.dtype is not None:
            return e.dtype
        try:
            return self._infer(e)
        except Exception:                        # noqa: BLE001
            return None

    def _infer(self, fe: ForeignExpr) -> DataType:
        """Engine-rule type inference: Foreign -> IR -> infer_type."""
        from auron_tpu.exprs.typing import infer_type
        from auron_tpu.frontend import expr_convert as EC
        names: Dict[str, Field] = {}

        def collect(x: ForeignExpr):
            if x.name == "AttributeReference":
                names[x.value] = Field(x.value, x.dtype,
                                       bool(x.attrs.get("nullable", True)))
            for c in x.children:
                collect(c)
        collect(fe)
        schema = Schema(tuple(names.values()))
        ir = EC.convert_expr(fe)
        return infer_type(ir, schema)

    def infer_or(self, fe: ForeignExpr, fallback: DataType) -> DataType:
        if fe.dtype is not None:
            return fe.dtype
        try:
            return self._infer(fe)
        except Exception:                        # noqa: BLE001
            return fallback

    # -- decimal adaptation ------------------------------------------------

    def adapt_cast(self, child: ForeignExpr, dtype: DataType
                   ) -> ForeignExpr:
        if self.adapt and dtype.id == TypeId.DECIMAL:
            dtype = F64
            ct = self.type_of(child)
            if ct is not None and ct.id in (TypeId.FLOAT64,):
                return child                     # float->decimal: no-op
        return fcall("Cast", child, dtype=dtype)

    def _dropped_scale(self, fe: ForeignExpr) -> Optional[int]:
        """Max ReadSchema decimal scale among referenced columns whose
        decimal type adapt mode replaced with float."""
        best: Optional[int] = None
        if fe.name == "AttributeReference":
            s = self._orig_scale.get(fe.value)
            if s is not None:
                best = s
        for c in fe.children:
            s = self._dropped_scale(c)
            if s is not None and (best is None or s > best):
                best = s
        return best

    def adapt_fn(self, fname: str, args: List[ForeignExpr]) -> ForeignExpr:
        if self.adapt and fname in ("CheckOverflow", "PromotePrecision"):
            return args[0]
        if self.adapt and fname == "UnscaledValue":
            # true semantics on the float warehouse: x * 10^s (the
            # plan's later / 10^s — a MakeDecimal OR a bare literal
            # divide like `avg(UnscaledValue(p)) / 100.0` — then
            # cancels exactly; a plain identity broke the literal form)
            s = self._dropped_scale(args[0])
            if s:
                return fcall("Multiply", args[0],
                             flit(float(10 ** s), F64), dtype=F64)
            return args[0]
        if self.adapt and fname == "MakeDecimal":
            s = int(args[2].value) if len(args) > 2 else 0
            if s:
                return fcall("Divide", args[0],
                             flit(float(10 ** s), F64), dtype=F64)
            return args[0]
        if fname == "CheckOverflow":
            # second arg is a DecimalType(p,s) spec printed as a call
            args = args[:1]
            return fcall(fname, *args)
        if fname == "MakeDecimal":
            c = args[0]
            p = int(args[1].value) if len(args) > 1 else 38
            s = int(args[2].value) if len(args) > 2 else 0
            return ForeignExpr("MakeDecimal", children=(c,),
                               dtype=DataType.decimal(p, s))
        if fname == "Round" and len(args) == 1:
            args.append(flit(0, I32))
        return fcall(fname, *args)

    # -- aggregates --------------------------------------------------------

    def agg_expr(self, fn: str, args: List[ForeignExpr], distinct: bool,
                 prefix: Optional[str]) -> ForeignExpr:
        rt = self._agg_return_type(fn, args)
        node = ForeignExpr(fn, children=tuple(args), dtype=rt)
        return ForeignExpr("AggregateExpression", children=(node,),
                           attrs={"distinct": distinct,
                                  "_prefix": prefix or ""})

    def _agg_return_type(self, fn: str, args: List[ForeignExpr]
                         ) -> DataType:
        at = self.type_of(args[0]) if args else None
        if fn == "Count":
            return I64
        if fn in ("StddevSamp", "VarianceSamp"):
            return F64
        if fn in ("Min", "Max", "First"):
            return at or F64
        if fn == "Average":
            if at is not None and at.id == TypeId.DECIMAL:
                return DataType.decimal(min(at.precision + 4, 38),
                                        min(at.scale + 4, 38))
            return F64
        if fn == "Sum":
            if at is None:
                return F64
            if at.id == TypeId.DECIMAL:
                return DataType.decimal(min(at.precision + 10, 38),
                                        at.scale)
            if at.id in (TypeId.INT8, TypeId.INT16, TypeId.INT32,
                         TypeId.INT64):
                return I64
            return F64
        return at or F64

    def subquery_literal(self, sid: int,
                         field_name: Optional[str] = None) -> ForeignExpr:
        sid = self.dump.subquery_alias.get(sid, sid)
        memo = self._subq_memo.get((sid, field_name))
        if memo is not None:
            return memo
        root = self.dump.subqueries.get(sid)
        if root is None and len(self.dump.subqueries) == 1:
            # plan-stability dumps omit duplicate subquery definitions:
            # q44's two branches reference #12 and #39 but print one
            # plan (the Final section's ReusedSubquery confirms they
            # are the same query) — reuse the single definition
            root = next(iter(self.dump.subqueries.values()))
        if root is None:
            if self.subquery_eval is not None:
                raise BindError(f"subquery#{sid} has no plan section")
            lit = flit(0, F64)              # conversion-only placeholder
            self._subq_memo[(sid, field_name)] = lit
            return lit
        plan = self._bind(root, parent=None)
        col = 0
        if field_name is not None and plan.output is not None:
            for i, f in enumerate(plan.output.fields):
                base = f.name.rsplit("#", 1)[0]
                if base == field_name:
                    col = i
                    break
        dtype = plan.output.fields[col].dtype if plan.output and \
            plan.output.fields else F64
        if self.adapt and dtype.id == TypeId.DECIMAL:
            dtype = F64
        if self.subquery_eval is not None:
            value = self.subquery_eval(plan, col)
            if dtype.id == TypeId.DECIMAL:
                dtype = F64
            lit = flit(value, dtype)
        else:
            lit = flit(0, dtype) if dtype.id != TypeId.STRING \
                else flit("", STR)
        self._subq_memo[(sid, field_name)] = lit
        return lit

    # -- parsing entry points ---------------------------------------------

    def expr(self, text: str) -> ForeignExpr:
        return ExprParser(text, self).parse()

    @staticmethod
    def merge_items(items: List[str]) -> List[str]:
        """Re-join list items that were split apart by commas inside an
        unquoted folded string literal (`DHL,BARIAN AS ship_carriers#33`
        splits at the literal's comma): every real item ends with #id."""
        out: List[str] = []
        acc: Optional[str] = None
        for it in items:
            cur = it if acc is None else f"{acc},{it}"
            if re.search(r"#\d+$", cur.strip()):
                out.append(cur)
                acc = None
            else:
                acc = cur
        if acc is not None:
            out.append(acc)
        return out

    def _out_item(self, text: str) -> Tuple[ForeignExpr, int, str]:
        """One Output-list item: `expr AS base#id` or `base#id` or `#id`.
        Returns (expr, id, base)."""
        parts = split_top(text, sep="\x00")      # no-op, keep raw
        raw = parts[0]
        # split on last top-level " AS "
        depth = 0
        as_pos = None
        i = 0
        while i < len(raw):
            ch = raw[i]
            if ch in "([":
                depth += 1
            elif ch in ")]":
                depth -= 1
            elif depth == 0 and raw.startswith(" AS ", i):
                as_pos = i
            i += 1
        if as_pos is not None:
            expr_text, alias = raw[:as_pos], raw[as_pos + 4:]
            m = re.match(r"^(.*)#(\d+)$", alias.strip(), re.S)
            if not m:
                raise ExplainParseError(f"alias without id: {alias!r}")
            try:
                e = self.expr(expr_text)
            except ExplainParseError:
                if "#" not in expr_text and "(" not in expr_text:
                    # folded string literal containing commas
                    e = flit(expr_text.rstrip(), STR)
                else:
                    raise
            return e, int(m.group(2)), m.group(1)
        m = re.match(r"^(.*?)#(\d+)$", raw.strip(), re.S)
        if not m:
            raise ExplainParseError(f"output item without id: {raw!r}")
        return None, int(m.group(2)), m.group(1)  # plain attr

    # -- node binding ------------------------------------------------------

    def _bind(self, opid: int, parent: Optional[int]) -> ForeignNode:
        if opid in self._bound:
            return self._bound[opid]
        d = self.dump.details.get(opid)
        if d is None:
            raise BindError(f"no detail block for op ({opid})")
        op = d.op.split("[")[0].strip()
        kids = self.dump.children.get(opid, [])
        fn = getattr(self, "_op_" + re.sub(r"[^A-Za-z]", "_",
                                           op.split()[0]), None)
        if fn is None:
            raise BindError(f"unsupported op {op!r} ({opid})")
        node = fn(opid, d, kids, parent)
        self._bound[opid] = node
        return node

    def _child(self, kids: List[int], opid: int) -> ForeignNode:
        if len(kids) != 1:
            raise BindError(f"expected 1 child, got {len(kids)}")
        return self._bind(kids[0], opid)

    # Scan parquet ---------------------------------------------------------

    def _op_Scan(self, opid, d: Detail, kids, parent) -> ForeignNode:
        out_items = d.lists.get("Output", [])
        bases, ids = [], []
        for item in out_items:
            m = re.match(r"^(.*?)#(\d+)$", item)
            if not m:
                raise BindError(f"scan output item {item!r}")
            bases.append(m.group(1))
            ids.append(int(m.group(2)))
        schema_s = d.kv.get("ReadSchema", "")
        dtypes: Dict[str, DataType] = {}
        if schema_s.startswith("struct<"):
            st = _dtype_from_str(schema_s)
            for f in st.children:
                dtypes[f.name] = f.dtype
        table = _infer_table(bases)
        cat_t = None
        if self.cat is not None and table in self.cat.tables:
            cat_t = self.cat.tables[table]
            cat_fields = {f.name: f for f in cat_t.schema.fields}
        fields = []          # renamed (name#id) fields
        bare_fields = []     # parquet column names the scan reads
        for base, fid in zip(bases, ids):
            dt = dtypes.get(base, F64)
            orig = dt
            if cat_t is not None:
                cf = cat_fields.get(base)
                if cf is None:
                    raise BindError(
                        f"column {base} not in generated {table}")
                dt = cf.dtype
            elif self.adapt and dt.id == TypeId.DECIMAL:
                dt = F64
            f = self.define(fid, base, dt, fresh=True)
            if self.adapt and orig.id == TypeId.DECIMAL and \
                    dt.id != TypeId.DECIMAL:
                # remember the dropped scale so UnscaledValue keeps its
                # true x * 10^s meaning over the float column
                self._orig_scale[f.name] = orig.scale
            fields.append(f)
            bare_fields.append(Field(base, dt))
        out = Schema(tuple(fields))
        bare_out = Schema(tuple(bare_fields))
        if cat_t is not None:
            n = min(self.n_parts, len(cat_t.chunks))
            groups: List[List[str]] = [[] for _ in range(n)]
            for i, path in enumerate(cat_t.chunks):
                groups[i % n].append(path)
        else:
            groups = [[f"/nonexistent/{table or 'tbl'}.parquet"]]
        node = ForeignNode(
            "FileSourceScanExec", output=bare_out,
            attrs={"format": "parquet",
                   "file_groups": [list(g) for g in groups],
                   "pushed_filters": [],
                   "_table": table})
        # fully-pushed predicates with no Filter parent must be applied
        # (on the bare names, below the rename)
        parent_op = (self.dump.details[parent].op
                     if parent is not None and
                     parent in self.dump.details else "")
        pushed = d.lists.get("PushedFilters", [])
        if pushed and not parent_op.startswith("Filter"):
            conds = [self._pushed_filter(p,
                                         dict(zip(bases, bare_fields)))
                     for p in pushed]
            conds = [c for c in conds if c is not None]
            if conds:
                cond = conds[0]
                for c in conds[1:]:
                    cond = fcall("And", cond, c)
                node = ForeignNode("FilterExec", children=(node,),
                                   output=bare_out,
                                   attrs={"condition": cond})
        # rename bare parquet columns to the plan's attr-id names
        node = ForeignNode(
            "ProjectExec", children=(node,), output=out,
            attrs={"project_list": [
                falias(fcol(b.name, b.dtype), f.name)
                for b, f in zip(bare_fields, fields)]})
        return node

    def _pushed_filter(self, text: str, by_base: Dict[str, Field]
                       ) -> Optional[ForeignExpr]:
        """Source-filter syntax: IsNotNull(col), EqualTo(col,lit), ..."""
        m = re.match(r"^([A-Za-z]+)\((.*)\)$", text.strip())
        if not m:
            return None
        op, inner = m.group(1), m.group(2)
        args = split_top(inner)

        def col(a: str) -> Optional[ForeignExpr]:
            f = by_base.get(a.strip())
            return None if f is None else fcol(f.name, f.dtype)

        def lit_for(c: ForeignExpr, a: str) -> ForeignExpr:
            dt = c.dtype
            a = a.strip()
            if dt.id == TypeId.STRING:
                return flit(a.rstrip(), STR)
            if dt.id in (TypeId.FLOAT32, TypeId.FLOAT64) or \
                    dt.id == TypeId.DECIMAL:
                return flit(float(a), F64)
            if dt.id == TypeId.DATE32:
                import datetime
                d0 = datetime.date.fromisoformat(a)
                return flit((d0 - datetime.date(1970, 1, 1)).days, DATE)
            return flit(int(a), dt)

        if op in ("IsNotNull", "IsNull"):
            c = col(args[0])
            return None if c is None else fcall(op, c)
        if op in ("EqualTo", "GreaterThan", "GreaterThanOrEqual",
                  "LessThan", "LessThanOrEqual"):
            c = col(args[0])
            return None if c is None else fcall(op, c, lit_for(c, args[1]))
        if op == "In":
            c = col(args[0])
            if c is None:
                return None
            inner2 = args[1].strip()
            if inner2.startswith("[") and inner2.endswith("]"):
                inner2 = inner2[1:-1]
            vals = [lit_for(c, v) for v in split_top(inner2)]
            return fcall("In", c, *vals)
        if op in ("Or", "And"):
            a = self._pushed_filter(args[0], by_base)
            b = self._pushed_filter(args[1], by_base)
            if a is None or b is None:
                return None
            return fcall(op, a, b)
        if op == "Not":
            a = self._pushed_filter(args[0], by_base)
            return None if a is None else fcall("Not", a)
        return None                              # unknown: drop (perf only)

    # Filter ---------------------------------------------------------------

    def _op_Filter(self, opid, d: Detail, kids, parent) -> ForeignNode:
        child = self._child(kids, opid)
        cond = self.expr(d.kv.get("Condition", "true"))
        return ForeignNode("FilterExec", children=(child,),
                           output=child.output,
                           attrs={"condition": cond})

    # Project --------------------------------------------------------------

    def _op_Project(self, opid, d: Detail, kids, parent) -> ForeignNode:
        child = self._child(kids, opid)
        items = self.merge_items(d.lists.get("Output", []))
        if not items and d.kv.get("Output", "").strip() in ("[]", ""):
            # zero-column project (`Output: []`): Spark keeps row COUNT
            # only (feeding count(1)); carry one constant column so the
            # engine's batches preserve cardinality
            one = falias(flit(1, I32), "__rowtag")
            return ForeignNode(
                "ProjectExec", children=(child,),
                output=Schema((Field("__rowtag", I32),)),
                attrs={"project_list": [one]})
        exprs: List[ForeignExpr] = []
        fields: List[Field] = []
        seen_fids: Dict[int, int] = {}
        for item in items:
            e, fid, base = self._out_item(item)
            if e is None:                        # plain attr passthrough
                f = self.fields.get(fid)
                if f is None:
                    raise BindError(f"unknown attr #{fid} in project")
                n_seen = seen_fids.get(fid, 0)
                seen_fids[fid] = n_seen + 1
                if n_seen:
                    # Spark plans may carry the same attribute twice in
                    # one projection (q70's window-prep `[s_state#13,
                    # s_state#13, ...]`); alias the repeat so name-based
                    # consumers keep a unique schema (refs by id keep
                    # resolving to the first copy)
                    alias = Field(f"{f.name}@dup{n_seen}", f.dtype)
                    exprs.append(falias(fcol(f.name, f.dtype),
                                        alias.name))
                    fields.append(alias)
                    continue
                exprs.append(fcol(f.name, f.dtype))
                fields.append(f)
            elif e.name == "named_struct":
                # q9's subquery root packs its aggregates into ONE
                # struct (`named_struct(count(1), count(1)#52, ...)`);
                # unwrap to plain columns so the host oracle runs it
                # and Subquery field access matches by base name
                for i in range(1, len(e.children), 2):
                    v = e.children[i]
                    if v.name != "AttributeReference":
                        raise BindError("named_struct value is not an "
                                        "attribute")
                    exprs.append(fcol(v.value, v.dtype))
                    fields.append(Field(v.value, v.dtype))
            else:
                dt = self.infer_or(e, F64)
                f = self.define(fid, base, dt)
                exprs.append(falias(e, f.name))
                fields.append(f)
        return ForeignNode("ProjectExec", children=(child,),
                           output=Schema(tuple(fields)),
                           attrs={"project_list": exprs})

    # Sort -----------------------------------------------------------------

    def _sort_order(self, item: str) -> ForeignExpr:
        m = re.match(r"^(.*?)\s+(ASC|DESC)(?:\s+NULLS\s+(FIRST|LAST))?$",
                     item.strip(), re.S)
        if m:
            e = self.expr(m.group(1))
            asc = m.group(2) == "ASC"
            nf = m.group(3)
            nulls_first = (nf == "FIRST") if nf else asc
        else:
            e = self.expr(item)
            asc, nulls_first = True, True
        return ForeignExpr("SortOrder", children=(e,),
                           attrs={"asc": asc, "nulls_first": nulls_first})

    def _op_Sort(self, opid, d: Detail, kids, parent) -> ForeignNode:
        child = self._child(kids, opid)
        args = d.kv.get("Arguments", "[]")
        lists = self._bracket_lists(args)
        orders = [self._sort_order(x) for x in (lists[0] if lists else [])]
        return ForeignNode("SortExec", children=(child,),
                           output=child.output,
                           attrs={"sort_order": orders})

    # Exchange -------------------------------------------------------------

    def _op_Exchange(self, opid, d: Detail, kids, parent) -> ForeignNode:
        child = self._child(kids, opid)
        args = d.kv.get("Arguments", "SinglePartition")
        spec = self._partitioning(args)
        return ForeignNode("ShuffleExchangeExec", children=(child,),
                           output=child.output,
                           attrs={"partitioning": spec})

    def _partitioning(self, args: str) -> Dict[str, Any]:
        head = split_top(args)[0]
        if head.startswith("SinglePartition"):
            return {"mode": "single", "num_partitions": 1}
        m = re.match(r"^(hashpartitioning|rangepartitioning|"
                     r"RoundRobinPartitioning)\((.*)\)$", head, re.S)
        if not m:
            raise BindError(f"partitioning {head!r}")
        kind, inner = m.group(1), m.group(2)
        parts = split_top(inner)
        n = int(parts[-1]) if parts and parts[-1].strip().isdigit() else 1
        n = min(n, self.n_parts)
        if kind == "RoundRobinPartitioning":
            return {"mode": "round_robin", "num_partitions": n}
        if kind == "hashpartitioning":
            exprs = [self.expr(p) for p in parts[:-1]]
            return {"mode": "hash", "num_partitions": n,
                    "expressions": exprs}
        orders = [self._sort_order(p) for p in parts[:-1]]
        return {"mode": "range", "num_partitions": n,
                "sort_orders": orders}

    # HashAggregate ---------------------------------------------------------

    def _op_HashAggregate(self, opid, d: Detail, kids, parent
                          ) -> ForeignNode:
        child = self._child(kids, opid)
        keys = d.lists.get("Keys", [])
        funcs = d.lists.get("Functions", [])
        results = self.merge_items(d.lists.get("Results", []))
        grouping: List[ForeignExpr] = []
        group_fields: List[Field] = []
        for k in keys:
            e = self.expr(k)
            if e.name != "AttributeReference":
                # expression grouping key: alias it inline
                dt = self.infer_or(e, F64)
                e = falias(e, f"_gk{len(grouping)}")
                group_fields.append(Field(f"_gk{len(grouping)}", dt))
            else:
                group_fields.append(Field(e.value, e.dtype))
            grouping.append(e)
        aggs = [self.expr(f) for f in funcs]
        prefixes = {a.attrs.get("_prefix", "") for a in aggs}
        has_distinct = any(a.attrs.get("distinct") for a in aggs)
        if "merge" in prefixes or has_distinct:
            # Spark's count(distinct) rewrite: levels above the dedup
            # level re-aggregate partial states.  Finalizing the level
            # below early is equivalent (sum of sums, count of the
            # now-unique dedup keys), so rewrite this level's aggs over
            # the child agg's finalized output attrs.
            aggs, mode = self._distinct_level_aggs(kids, aggs, funcs)
        elif "partial" in prefixes and prefixes == {"partial"}:
            mode = "partial"
        else:
            mode = "final" if self._has_partial_below(kids[0]) \
                else "single"
        if mode == "partial":
            agg_names = [f"agg{i}" for i in range(len(aggs))]
            state_fields = list(group_fields)
            for name, a in zip(agg_names, aggs):
                state_fields += self._state_fields(name, a)
            node = ForeignNode(
                "HashAggregateExec", children=(child,),
                output=Schema(tuple(state_fields)),
                attrs={"grouping": grouping, "aggs": aggs,
                       "agg_names": agg_names, "mode": "partial"})
            return node
        # final / single: the canonical agg result attrs come from the
        # `Aggregate Attributes` list; `Results` is Spark's trailing
        # resultExpressions projection over [keys..., agg attrs...]
        agg_fields: List[Field] = []
        agg_names: List[str] = []
        attr_items = d.lists.get("Aggregate Attributes", [])
        for j, a in enumerate(aggs):
            dtype = a.children[0].dtype or F64
            if self.adapt and dtype.id == TypeId.DECIMAL:
                dtype = F64
            if j < len(attr_items):
                m = re.match(r"^(.*?)#(\d+)$", attr_items[j], re.S)
                if m:
                    f = self.define(int(m.group(2)), m.group(1), dtype)
                else:
                    f = Field(f"agg{j}", dtype)
            else:
                f = Field(f"agg{j}", dtype)
            agg_fields.append(f)
            agg_names.append(f.name)
        if mode == "final":
            self._retrofit_partial(kids[0], agg_names, aggs)
        agg_out = Schema(tuple(group_fields) + tuple(agg_fields))
        node = ForeignNode(
            "HashAggregateExec", children=(child,), output=agg_out,
            attrs={"grouping": grouping, "aggs": aggs,
                   "agg_names": agg_names, "mode": mode})
        # trailing projection when Results is not the identity list
        if results:
            exprs: List[ForeignExpr] = []
            res_fields: List[Field] = []
            identity = True
            seen_fids: Dict[int, int] = {}
            for i, item in enumerate(results):
                e, fid, base = self._out_item(item)
                if e is None:
                    f = self.fields.get(fid)
                    if f is None and i < len(agg_out.fields):
                        # unknown plain id: a state-column id from a
                        # PartialMerge level (`sum#26`) — alias it to
                        # the positional finalized attr
                        f = agg_out.fields[i]
                        self.fields[fid] = f
                    elif f is None:
                        f = self.define(fid, base, F64)
                    n_seen = seen_fids.get(fid, 0)
                    seen_fids[fid] = n_seen + 1
                    if n_seen:
                        # repeated attr in Results (q70's window prep):
                        # alias the copy so the schema stays unique
                        alias = Field(f"{f.name}@dup{n_seen}", f.dtype)
                        exprs.append(falias(fcol(f.name, f.dtype),
                                            alias.name))
                        res_fields.append(alias)
                        identity = False
                        continue
                    exprs.append(fcol(f.name, f.dtype))
                    res_fields.append(f)
                    if i >= len(agg_out.fields) or \
                            agg_out.fields[i].name != f.name:
                        identity = False
                else:
                    dt = self.infer_or(e, F64)
                    f = self.define(fid, base, dt)
                    exprs.append(falias(e, f.name))
                    res_fields.append(f)
                    identity = False
            if not identity:
                node = ForeignNode(
                    "ProjectExec", children=(node,),
                    output=Schema(tuple(res_fields)),
                    attrs={"project_list": exprs})
        return node

    def _find_bound_agg(self, opid: int) -> Optional[ForeignNode]:
        d = self.dump.details.get(opid)
        if d is None:
            return None
        head = d.op.split()[0]
        if head in ("HashAggregate", "ObjectHashAggregate",
                    "SortAggregate"):
            n = self._bound.get(opid)
            while n is not None and n.op == "ProjectExec":
                n = n.children[0] if n.children else None
            return n if n is not None and \
                n.op == "HashAggregateExec" else None
        if head in ("Exchange", "Sort", "Project", "Filter",
                    "AQEShuffleRead", "ShuffleQueryStage",
                    "InputAdapter"):
            kids = self.dump.children.get(opid, [])
            return self._find_bound_agg(kids[0]) if kids else None
        return None

    def _distinct_level_aggs(self, kids, aggs: List[ForeignExpr],
                             funcs: List[str]
                             ) -> Tuple[List[ForeignExpr], str]:
        """Aggs for a level of Spark's distinct rewrite (merge_* and/or
        *(distinct ..) functions), re-aggregating the finalized child
        agg instead of merging partial state."""
        below = self._find_bound_agg(kids[0])
        if below is None:
            raise BindError("merge/distinct agg without an agg below")
        if below.attrs.get("mode") == "partial":
            # ordinary final level: reuse the partial's (possibly
            # rewritten) aggs so partial/final state naming aligns
            return list(below.attrs["aggs"]), "final"
        by_base: Dict[str, Field] = {}
        for f in below.output.fields:
            by_base.setdefault(f.name.rsplit("#", 1)[0], f)
            by_base.setdefault(f.name, f)
        group_names = set()
        for g in below.attrs.get("grouping", []):
            if g.name in ("AttributeReference", "Alias"):
                group_names.add(g.value)
        new_aggs: List[ForeignExpr] = []
        for a, ftext in zip(aggs, funcs):
            fn_node = a.children[0]
            prefix = a.attrs.get("_prefix", "")
            if not a.attrs.get("distinct"):
                base = ftext.strip()
                for p in ("merge_", "final_", "partial_"):
                    if base.startswith(p):
                        base = base[len(p):]
                f = by_base.get(base)
                if f is None:
                    raise BindError(f"no child agg attr for {base!r}")
                col = fcol(f.name, f.dtype)
                fn = fn_node.name
                if fn == "Count":                # merged counts sum up
                    new_aggs.append(self.agg_expr("Sum", [col], False,
                                                  None))
                elif fn in ("Sum", "Min", "Max"):
                    new_aggs.append(self.agg_expr(fn, [col], False,
                                                  None))
                else:
                    raise BindError(
                        f"cannot re-aggregate {fn} over merged state")
            else:
                # X(distinct k): k must be a dedup key of the level
                # below, where rows are already unique per k
                arg = fn_node.children[0] if fn_node.children else None
                if arg is None or arg.name != "AttributeReference" or \
                        arg.value not in group_names:
                    raise BindError("distinct argument is not a dedup "
                                    "key of the level below")
                new_aggs.append(self.agg_expr(fn_node.name, [arg],
                                              False, None))
        mode = "partial" if any(
            a.attrs.get("_prefix") == "partial" for a in aggs) else (
                "final" if self._has_partial_below(kids[0]) else "single")
        return new_aggs, mode

    def _state_fields(self, name: str, a: ForeignExpr) -> List[Field]:
        fn = a.children[0].name
        rt = a.children[0].dtype or F64
        if self.adapt and rt.id == TypeId.DECIMAL:
            rt = F64
        if fn == "Average":
            return [Field(f"{name}#sum", F64), Field(f"{name}#count", I64)]
        if fn in ("StddevSamp", "VarianceSamp"):
            return [Field(f"{name}#sum", F64),
                    Field(f"{name}#sumsq", F64),
                    Field(f"{name}#count", I64)]
        if fn == "Count":
            return [Field(f"{name}#count", I64)]
        return [Field(f"{name}#{fn.lower()}", rt)]

    def _has_partial_below(self, opid: int) -> bool:
        d = self.dump.details.get(opid)
        if d is None:
            return False
        if d.op.startswith("HashAggregate") or \
                d.op.startswith("ObjectHashAggregate") or \
                d.op.startswith("SortAggregate"):
            funcs = d.lists.get("Functions", [])
            return any(f.strip().startswith("partial_") for f in funcs) \
                or not funcs
        if d.op.split()[0] in ("Exchange", "Sort", "AQEShuffleRead",
                               "ShuffleQueryStage", "InputAdapter",
                               "Project"):
            kids = self.dump.children.get(opid, [])
            return bool(kids) and self._has_partial_below(kids[0])
        return False

    def _retrofit_partial(self, opid: int, agg_names: List[str],
                          final_aggs: List[ForeignExpr]) -> None:
        """Rename the partial agg's state columns (and intervening
        exchange outputs) to the final agg's naming so the engine's
        partial/final state convention lines up (two_phase_agg)."""
        node = self._bound.get(opid)
        d = self.dump.details.get(opid)
        if node is None or d is None:
            return
        if node.op == "HashAggregateExec" and \
                node.attrs.get("mode") == "partial":
            n_group = len(node.attrs.get("grouping", []))
            group_fields = list(node.output.fields[:n_group])
            state_fields = list(group_fields)
            for name, a in zip(agg_names, node.attrs["aggs"]):
                state_fields += self._state_fields(name, a)
            node.attrs["agg_names"] = list(agg_names)
            node.output = Schema(tuple(state_fields))
            return
        kids = self.dump.children.get(opid, [])
        if kids:
            self._retrofit_partial(kids[0], agg_names, final_aggs)
            child_node = self._bound.get(kids[0])
            if child_node is not None and node.op in (
                    "ShuffleExchangeExec", "SortExec"):
                node.output = child_node.output

    # Joins ----------------------------------------------------------------

    def _op_SortMergeJoin(self, opid, d: Detail, kids, parent
                          ) -> ForeignNode:
        left = self._bind(kids[0], opid)
        right = self._bind(kids[1], opid)
        lk = [self.expr(k) for k in d.lists.get("Left keys", [])]
        rk = [self.expr(k) for k in d.lists.get("Right keys", [])]
        jt = d.kv.get("Join type", "Inner").strip()
        cond_s = d.kv.get("Join condition", "None").strip()
        cond = None if cond_s in ("None", "") else self.expr(cond_s)
        existence_name = None
        if jt.startswith("ExistenceJoin"):
            m = re.match(r"ExistenceJoin\((.*?)#(\d+)\)", jt)
            jt = "ExistenceJoin"
            if m:
                fid = int(m.group(2))
                f = self.define(fid, m.group(1), BOOL)
                existence_name = f.name
        out_fields: List[Field]
        if jt in ("Inner", "LeftOuter", "RightOuter", "FullOuter"):
            out_fields = list(left.output.fields) + \
                list(right.output.fields)
        elif jt == "ExistenceJoin":
            out_fields = list(left.output.fields) + \
                [Field(existence_name or "exists", BOOL)]
        else:
            out_fields = list(left.output.fields)
        attrs: Dict[str, Any] = {
            "left_keys": lk, "right_keys": rk, "join_type": jt}
        if existence_name:
            attrs["existence_name"] = existence_name
        node = ForeignNode("SortMergeJoinExec", children=(left, right),
                           output=Schema(tuple(out_fields)), attrs=attrs)
        if cond is not None:
            if jt == "Inner":
                # Inner join + condition == join then filter
                node = ForeignNode("FilterExec", children=(node,),
                                   output=node.output,
                                   attrs={"condition": cond})
            else:
                attrs["condition"] = cond        # converter will fall back
        return node

    def _op_CartesianProduct(self, opid, d: Detail, kids, parent
                             ) -> ForeignNode:
        """All-pairs join of (tiny) aggregate sides: broadcast join on a
        constant key, the shape the engine's SQL front door plans for
        1x1 cartesians (sql/lower.py)."""
        left = self._bind(kids[0], opid)
        right = self._bind(kids[1], opid)
        bx = ForeignNode("BroadcastExchangeExec", children=(right,),
                         output=right.output)
        out = Schema(tuple(list(left.output.fields) +
                           list(right.output.fields)))
        one = flit(1, I32)
        node = ForeignNode(
            "BroadcastHashJoinExec", children=(left, bx), output=out,
            attrs={"left_keys": [one], "right_keys": [one],
                   "join_type": "Inner", "build_side": "right"})
        cond_s = d.kv.get("Join condition", "None").strip()
        if cond_s not in ("None", ""):
            cond = self.expr(cond_s)
            node = ForeignNode("FilterExec", children=(node,),
                               output=out, attrs={"condition": cond})
        return node

    # Union ----------------------------------------------------------------

    def _op_Union(self, opid, d: Detail, kids, parent) -> ForeignNode:
        children = [self._bind(k, opid) for k in kids]
        first = children[0]
        # union output attrs = parent's Input list (fresh ids), types
        # positional from the first child
        fields: List[Field] = []
        parent_d = self.dump.details.get(parent) if parent is not None \
            else None
        items = parent_d.lists.get("Input", []) if parent_d else []
        if len(items) != len(first.output.fields):
            items = []
        if items:
            for item, cf in zip(items, first.output.fields):
                m = re.match(r"^(.*?)#(\d+)$", item)
                if m and int(m.group(2)) not in self.fields:
                    fields.append(self.define(int(m.group(2)),
                                              m.group(1), cf.dtype))
                elif m:
                    fields.append(self.fields[int(m.group(2))])
                else:
                    fields.append(cf)
        else:
            fields = list(first.output.fields)
        return ForeignNode("UnionExec", children=tuple(children),
                           output=Schema(tuple(fields)))

    # Window ---------------------------------------------------------------

    def _op_Window(self, opid, d: Detail, kids, parent) -> ForeignNode:
        child = self._child(kids, opid)
        lists = self._bracket_lists(d.kv.get("Arguments", ""))
        wexprs = lists[0] if lists else []
        part = lists[1] if len(lists) > 1 else []
        order = lists[2] if len(lists) > 2 else []
        # two-list form is ambiguous: [exprs], [partition] vs
        # [exprs], [order] — sort-order items carry ASC/DESC
        if len(lists) == 2 and part and all(
                re.search(r"\s(ASC|DESC)\b", p) for p in part):
            order, part = part, []
        window_exprs = []
        fields = list(child.output.fields)
        for item in wexprs:
            w, fid, base = self._window_item(item)
            f = self.define(fid, base, w["_dtype"])
            w = {k: v for k, v in w.items() if not k.startswith("_")}
            w["name"] = f.name
            if w.get("fn") != "agg":
                w["dtype"] = f.dtype
            window_exprs.append(w)
            fields.append(f)
        return ForeignNode(
            "WindowExec", children=(child,),
            output=Schema(tuple(fields)),
            attrs={"window_exprs": window_exprs,
                   "partition_spec": [self.expr(p) for p in part],
                   "order_spec": [self._sort_order(o) for o in order]})

    _WIN_RANKS = {"rank": "rank", "dense_rank": "dense_rank",
                  "row_number": "row_number",
                  "percent_rank": "percent_rank",
                  "cume_dist": "cume_dist", "ntile": "ntile"}

    def _window_item(self, item: str) -> Tuple[Dict[str, Any], int, str]:
        m = re.match(r"^(.*) AS (.*?)#(\d+)$", item, re.S)
        if not m:
            raise BindError(f"window item without alias: {item!r}")
        body, base, fid = m.group(1), m.group(2), int(m.group(3))
        # body = <fnexpr> windowspecdefinition(...)
        wm = re.match(r"^(.*?)\s+windowspecdefinition\(.*\)$", body, re.S)
        fn_text = wm.group(1) if wm else body
        e = self.expr(fn_text)
        if e.name == "__winfn__":
            fn = self._WIN_RANKS.get(e.value, e.value)
            dt = F64 if fn in ("percent_rank", "cume_dist") else I32
            return ({"fn": fn, "args": [], "_dtype": dt}, fid, base)
        if e.name == "AggregateExpression":
            dt = e.children[0].dtype or F64
            if self.adapt and dt.id == TypeId.DECIMAL:
                dt = F64
            return ({"fn": "agg", "agg": e, "_dtype": dt}, fid, base)
        # plain expression windowed (first/last/lead/lag unsupported)
        raise BindError(f"window function {fn_text!r} unsupported")

    # WindowGroupLimit ------------------------------------------------------

    def _op_WindowGroupLimit(self, opid, d: Detail, kids, parent
                             ) -> ForeignNode:
        child = self._child(kids, opid)
        args = d.kv.get("Arguments", "")
        lists = self._bracket_lists(args)
        tail = args[args.rfind("]") + 1:] if "]" in args else args
        tail_parts = [p for p in split_top(tail) if p]
        rank_fn = "row_number"
        k = 1
        for p in tail_parts:
            pm = re.match(r"^(rank|dense_rank|row_number)\(", p.strip())
            if pm:
                rank_fn = pm.group(1)
            elif p.strip().isdigit():
                k = int(p.strip())
        if len(lists) > 1:
            part, order = lists[0], lists[1]
        else:
            part, order = [], (lists[0] if lists else [])
        return ForeignNode(
            "WindowGroupLimitExec", children=(child,),
            output=child.output,
            attrs={"partition_spec": [self.expr(p) for p in part],
                   "order_spec": [self._sort_order(o) for o in order],
                   "limit": k, "rank_like_function": rank_fn})

    # Expand ---------------------------------------------------------------

    def _op_Expand(self, opid, d: Detail, kids, parent) -> ForeignNode:
        child = self._child(kids, opid)
        args = d.kv.get("Arguments", "")
        lists = self._bracket_lists(args, nested=True)
        if len(lists) < 2:
            raise BindError("expand arguments")
        proj_lists, out_items = lists[0], lists[1]
        # output fields: names from out_items; types from first
        # projection row (grouping id -> bigint)
        first_row = [self.expr(x) for x in split_top(
            proj_lists[0][1:-1])] if proj_lists else []
        fields: List[Field] = []
        for i, item in enumerate(out_items):
            m = re.match(r"^(.*?)#(\d+)$", item)
            if not m:
                raise BindError(f"expand output {item!r}")
            base, fid = m.group(1), int(m.group(2))
            if base == "spark_grouping_id":
                dt = I64
            elif i < len(first_row):
                dt = self.infer_or(first_row[i], F64)
            else:
                dt = F64
            fields.append(self.define(fid, base, dt))
        projections = []
        for row in proj_lists:
            exprs = []
            for i, x in enumerate(split_top(row[1:-1])):
                e = self.expr(x)
                if e.name == "Literal" and e.value is None:
                    e = flit(None, fields[i].dtype)
                exprs.append(e)
            projections.append(exprs)
        return ForeignNode("ExpandExec", children=(child,),
                           output=Schema(tuple(fields)),
                           attrs={"projections": projections})

    # TakeOrderedAndProject -------------------------------------------------

    def _op_TakeOrderedAndProject(self, opid, d: Detail, kids, parent
                                  ) -> ForeignNode:
        child = self._child(kids, opid)
        args = d.kv.get("Arguments", "")
        lists = self._bracket_lists(args)
        head = split_top(args)[0].strip()
        limit = int(head) if head.isdigit() else self.default_limit
        orders = [self._sort_order(x) for x in (lists[0] if lists else [])]
        proj_items = self.merge_items(lists[1]) if len(lists) > 1 else []
        exprs, fields = [], []
        for item in proj_items:
            e, fid, base = self._out_item(item)
            if e is None:
                f = self.fields[fid]
                exprs.append(fcol(f.name, f.dtype))
                fields.append(f)
            else:
                dt = self.infer_or(e, F64)
                f = self.define(fid, base, dt)
                exprs.append(falias(e, f.name))
                fields.append(f)
        if not exprs:
            fields = list(child.output.fields)
            exprs = [fcol(f.name, f.dtype) for f in fields]
        return ForeignNode(
            "TakeOrderedAndProjectExec", children=(child,),
            output=Schema(tuple(fields)),
            attrs={"sort_order": orders, "limit": limit,
                   "project_list": exprs})

    # limits (rare at Initial roots) ---------------------------------------

    def _op_CollectLimit(self, opid, d: Detail, kids, parent
                         ) -> ForeignNode:
        child = self._child(kids, opid)
        args = d.kv.get("Arguments", "")
        head = split_top(args)[0].strip()
        limit = int(head) if head.isdigit() else self.default_limit
        return ForeignNode("CollectLimitExec", children=(child,),
                           output=child.output, attrs={"limit": limit})

    _op_GlobalLimit = _op_CollectLimit
    _op_LocalLimit = _op_CollectLimit

    # helpers --------------------------------------------------------------

    def _bracket_lists(self, s: str, nested: bool = False
                       ) -> List[List[str]]:
        """Top-level [..] groups of an Arguments string -> item lists.
        nested=True keeps second-level [..] items intact (Expand)."""
        out: List[List[str]] = []
        depth = 0
        start = None
        for i, ch in enumerate(s):
            if ch == "[":
                if depth == 0:
                    start = i + 1
                depth += 1
            elif ch == "]":
                depth -= 1
                if depth == 0 and start is not None:
                    inner = s[start:i]
                    out.append([x for x in split_top(inner) if x])
                    start = None
            elif ch == "(" and depth == 0:
                depth += 1000                    # skip call args at top
            elif ch == ")" and depth >= 1000:
                depth -= 1000
        return out


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def bind_explain(text: str, catalog=None, adapt: Optional[bool] = None,
                 n_parts: int = 4,
                 subquery_eval: Optional[Callable[[ForeignNode], Any]]
                 = None) -> ForeignNode:
    """Parse + bind a Spark explain dump into a ForeignNode plan."""
    dump = parse_explain(text)
    binder = ExplainBinder(dump, catalog=catalog, adapt=adapt,
                           n_parts=n_parts, subquery_eval=subquery_eval)
    return binder.bind()
