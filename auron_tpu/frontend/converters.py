"""Foreign physical plan -> native IR plan conversion.

Analogue of AuronConverters (spark-extension/.../AuronConverters.scala):
`convert_node` is the per-op dispatch (convertSparkPlan:209-416 + the 24
convert*Exec methods :418-1131); `convert_recursively` mirrors
convertSparkPlanRecursively:186-209, inserting ConvertToNative (FFIReader)
transitions under native parents with foreign children and leaving
foreign sections intact (the N2C direction) for the host engine.

Exchanges do not nest in the converted tree: a converted
ShuffleExchangeExec / BroadcastExchangeExec becomes an `IpcReader` leaf
plus an entry in `ConvertContext.exchanges` / `.broadcasts` that the
driver (frontend.session) materializes — exactly how the reference splits
stages at exchange boundaries via NativeShuffleExchangeExec /
NativeBroadcastExchangeExec and re-enters through ipc_reader_exec.rs.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from auron_tpu import config
from auron_tpu.frontend import expr_convert as EC
from auron_tpu.frontend.expr_convert import NotConvertible
from auron_tpu.frontend.foreign import ForeignExpr, ForeignNode
from auron_tpu.ir import expr as E
from auron_tpu.ir import plan as P
from auron_tpu.ir.schema import DataType, Field, Schema


@dataclass
class ShuffleJob:
    """A converted ShuffleExchangeExec: the driver runs `child` as a map
    stage partitioned by `partitioning`, then serves reduce-side blocks
    under resource id `rid`."""
    rid: str
    child: "ConvertedT" = None  # type: ignore[assignment]
    partitioning: P.Partitioning = None  # type: ignore[assignment]
    schema: Schema = None  # type: ignore[assignment]


@dataclass
class BroadcastJob:
    """A converted BroadcastExchangeExec: the driver collects `child` once
    (all partitions) into IPC bytes under resource id `rid`
    (NativeBroadcastExchangeBase.collectNative:195 analogue)."""
    rid: str
    child: "ConvertedT" = None  # type: ignore[assignment]
    schema: Schema = None  # type: ignore[assignment]


@dataclass
class ForeignSource:
    """A C2N transition: the foreign engine executes `node` and feeds its
    Arrow batches into an FFIReader under resource id `rid`
    (ConvertToNativeBase.scala:64-99 analogue)."""
    rid: str
    node: "ForeignWrap" = None  # type: ignore[assignment]


@dataclass
class ForeignWrap:
    """A plan section left to the host engine; children may be native
    sections whose results enter the engine as Arrow tables."""
    node: ForeignNode = None  # type: ignore[assignment]
    children: List["ConvertedT"] = field(default_factory=list)


ConvertedT = Union[P.PlanNode, ForeignWrap]


class ConvertContext:
    def __init__(self) -> None:
        import uuid
        self._ids = itertools.count()
        # resource ids are globally unique so concurrent queries (or
        # sequential queries against a shared remote shuffle server) can
        # never observe each other's blocks
        self._uid = uuid.uuid4().hex[:8]
        self.exchanges: Dict[str, ShuffleJob] = {}
        self.broadcasts: Dict[str, BroadcastJob] = {}
        self.sources: Dict[str, ForeignSource] = {}
        # partition count of each converted native node, keyed by identity
        self.n_parts: Dict[int, int] = {}

    def fresh(self, prefix: str) -> str:
        return f"{prefix}:{self._uid}:{next(self._ids)}"

    def parts(self, plan: P.PlanNode) -> int:
        return self.n_parts.get(id(plan), 1)

    def set_parts(self, plan: P.PlanNode, n: int) -> P.PlanNode:
        self.n_parts[id(plan)] = max(1, n)
        return plan


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _schema(node: ForeignNode) -> Schema:
    if node.output is None:
        raise NotConvertible(f"{node.op} carries no output schema")
    return node.output


def _split_conjunction(fe: ForeignExpr) -> List[ForeignExpr]:
    if fe.name == "And":
        return _split_conjunction(fe.children[0]) + \
            _split_conjunction(fe.children[1])
    return [fe]


def _named_exprs(fexprs) -> Tuple[Tuple[E.Expr, ...], Tuple[str, ...]]:
    """projectList conversion: Alias carries the name; a bare attribute
    keeps its own name."""
    exprs, names = [], []
    for fe in fexprs:
        if fe.name == "Alias":
            names.append(fe.value)
        elif fe.name == "AttributeReference":
            names.append(fe.value)
        else:
            raise NotConvertible(
                f"top-level project expression {fe.name} lacks a name")
        exprs.append(EC.convert_expr_with_fallback(fe))
    return tuple(exprs), tuple(names)


def _native_schema_of(plan: P.PlanNode) -> Optional[Schema]:
    """Exact runtime output schema of a converted subtree (e.g. the state
    layout a partial agg emits), derived by instantiating the operator
    tree — keeps exchange wire schemas honest regardless of what the
    foreign plan declared."""
    try:
        from auron_tpu.runtime.planner import PhysicalPlanner
        return PhysicalPlanner().create_plan(plan).schema
    except Exception:
        return None


def convert_partitioning(spec: Dict[str, Any]) -> P.Partitioning:
    mode = spec.get("mode", "single")
    if mode not in ("hash", "round_robin", "single", "range"):
        raise NotConvertible(f"partitioning mode {mode}")
    exprs = tuple(EC.convert_expr_with_fallback(e)
                  for e in spec.get("expressions", ()))
    orders = tuple(EC.convert_sort_order(s)
                   for s in spec.get("sort_orders", ()))
    return P.Partitioning(
        mode=mode, num_partitions=int(spec.get("num_partitions", 1)),
        expressions=exprs, sort_orders=orders,
        range_bounds=tuple(tuple(b) for b in spec.get("range_bounds", ())))


def _op_enabled(flag: str) -> None:
    if not config.conf.get(f"auron.enable.{flag}"):
        raise NotConvertible(f"native {flag} disabled by conf")


# ---------------------------------------------------------------------------
# per-op converters.  Each takes (node, native_children, ctx) where
# native_children are already-converted native child plans (C2N inserted).
# ---------------------------------------------------------------------------

_PLAN_CONVERTERS: Dict[str, Callable[..., P.PlanNode]] = {}


def _plan(name: str):
    def deco(fn):
        _PLAN_CONVERTERS[name] = fn
        return fn
    return deco


@_plan("FileSourceScanExec")
def _scan(node: ForeignNode, children, ctx: ConvertContext) -> P.PlanNode:
    fmt = node.attrs.get("format", "parquet")
    groups = tuple(
        P.FileGroup(paths=tuple(g)) for g in node.attrs.get("file_groups", ()))
    if not groups:
        raise NotConvertible("scan without file groups")
    schema = _schema(node)
    predicate = None
    pushed = node.attrs.get("pushed_filters", ())
    if pushed:
        conv = [EC.convert_expr(p) for p in pushed]
        predicate = conv[0]
        for p in conv[1:]:
            predicate = E.ScAnd(left=predicate, right=p)
    part_schema = node.attrs.get("partition_schema")
    part_values = tuple(tuple(v) for v in node.attrs.get(
        "partition_values", ()))
    if fmt == "parquet":
        _op_enabled("parquet.scan")
        plan = P.ParquetScan(schema=schema, file_groups=groups,
                             predicate=predicate,
                             partition_schema=part_schema,
                             partition_values=part_values)
    elif fmt == "orc":
        _op_enabled("orc.scan")
        plan = P.OrcScan(schema=schema, file_groups=groups,
                         predicate=predicate)
    else:
        raise NotConvertible(f"scan format {fmt}")
    ctx.set_parts(plan, len(groups))
    if predicate is not None and \
            config.conf.get("auron.adaptive.fuse.adjacency.enable"):
        # the PR 3 follow-up: pushdown hides filter/projection chains
        # from the fuser (the scan predicate swallows the filter).  When
        # the unified cost model says re-evaluating the pushed filter is
        # cheaper than the fusion it unlocks, keep it ALSO as an
        # explicit Filter node above the scan — the scan predicate still
        # prunes IO, the filter re-applies device-side (idempotent, so
        # value-identical), and the fuser sees an adjacent chain.
        # Chosen by cost (SystemML's fusion-plan exemplar), not greedily.
        preds = tuple(EC.convert_expr(p) for p in pushed)
        from auron_tpu.runtime.adaptive import unified_cost_model
        if unified_cost_model().filter_adjacency_pays(preds, schema):
            plan = ctx.set_parts(
                P.Filter(child=plan, predicates=preds), len(groups))
    return plan


@_plan("LocalTableScanExec")
def _local_table_scan(node, children, ctx) -> P.PlanNode:
    rid = ctx.fresh("local_table")
    schema = _schema(node)
    src = ForeignSource(rid=rid, node=ForeignWrap(node=node))
    ctx.sources[rid] = src
    return ctx.set_parts(P.FFIReader(schema=schema, resource_id=rid), 1)


@_plan("ProjectExec")
def _project(node, children, ctx) -> P.PlanNode:
    _op_enabled("project")
    exprs, names = _named_exprs(node.attrs["project_list"])
    return ctx.set_parts(
        P.Projection(child=children[0], exprs=exprs, names=names),
        ctx.parts(children[0]))


@_plan("FilterExec")
def _filter(node, children, ctx) -> P.PlanNode:
    _op_enabled("filter")
    preds = tuple(EC.convert_expr_with_fallback(p)
                  for p in _split_conjunction(node.attrs["condition"]))
    return ctx.set_parts(P.Filter(child=children[0], predicates=preds),
                         ctx.parts(children[0]))


@_plan("SortExec")
def _sort(node, children, ctx) -> P.PlanNode:
    _op_enabled("sort")
    orders = tuple(EC.convert_sort_order(s)
                   for s in node.attrs["sort_order"])
    return ctx.set_parts(P.Sort(child=children[0], sort_exprs=orders),
                         ctx.parts(children[0]))


@_plan("LocalLimitExec")
def _local_limit(node, children, ctx) -> P.PlanNode:
    _op_enabled("limit")
    return ctx.set_parts(
        P.Limit(child=children[0], limit=int(node.attrs["limit"]),
                offset=int(node.attrs.get("offset", 0))),
        ctx.parts(children[0]))


@_plan("GlobalLimitExec")
@_plan("CollectLimitExec")
def _global_limit(node, children, ctx) -> P.PlanNode:
    """Global limit over a multi-partition child: per-partition pre-limit,
    single-partition exchange, then the real limit+offset (CollectLimit's
    gather-to-one shape)."""
    _op_enabled("limit")
    limit = int(node.attrs["limit"])
    offset = int(node.attrs.get("offset", 0))
    child = children[0]
    if ctx.parts(child) > 1:
        local = ctx.set_parts(
            P.Limit(child=child, limit=limit + offset, offset=0),
            ctx.parts(child))
        rid = ctx.fresh("shuffle")
        schema = _native_schema_of(local) or _schema(node)
        ctx.exchanges[rid] = ShuffleJob(
            rid=rid, child=local,
            partitioning=P.Partitioning(mode="single", num_partitions=1),
            schema=schema)
        child = ctx.set_parts(P.IpcReader(schema=schema, resource_id=rid),
                              1)
    return ctx.set_parts(P.Limit(child=child, limit=limit, offset=offset),
                         1)


@_plan("TakeOrderedAndProjectExec")
def _take_ordered(node, children, ctx) -> P.PlanNode:
    """Global top-K: per-partition sort+limit, single-partition exchange,
    final merge sort+limit (NativeTakeOrderedBase's two-stage shape)."""
    _op_enabled("sort")
    orders = tuple(EC.convert_sort_order(s)
                   for s in node.attrs["sort_order"])
    limit = int(node.attrs["limit"])
    offset = int(node.attrs.get("offset", 0))
    merged_child = children[0]
    if ctx.parts(children[0]) > 1:
        local = ctx.set_parts(
            P.Sort(child=children[0], sort_exprs=orders,
                   fetch_limit=limit + offset),
            ctx.parts(children[0]))
        rid = ctx.fresh("shuffle")
        schema = _native_schema_of(local) or _schema(node)
        ctx.exchanges[rid] = ShuffleJob(
            rid=rid, child=local,
            partitioning=P.Partitioning(mode="single", num_partitions=1),
            schema=schema)
        merged_child = ctx.set_parts(
            P.IpcReader(schema=schema, resource_id=rid), 1)
    sort = P.Sort(child=merged_child, sort_exprs=orders,
                  fetch_limit=limit, fetch_offset=offset)
    exprs, names = _named_exprs(node.attrs["project_list"])
    return ctx.set_parts(P.Projection(child=sort, exprs=exprs, names=names),
                         1)


@_plan("HashAggregateExec")
@_plan("ObjectHashAggregateExec")
@_plan("SortAggregateExec")
def _agg(node, children, ctx) -> P.PlanNode:
    _op_enabled("agg")
    grouping, grouping_names = _named_exprs(node.attrs.get("grouping", ()))
    aggs = tuple(EC.convert_agg_expr(a) for a in node.attrs.get("aggs", ()))
    return ctx.set_parts(
        P.Agg(child=children[0],
              exec_mode=node.attrs.get("mode", "single"),
              grouping=grouping, grouping_names=grouping_names,
              aggs=aggs, agg_names=tuple(node.attrs.get("agg_names", ())),
              supports_partial_skipping=bool(
                  node.attrs.get("supports_partial_skipping", False))),
        ctx.parts(children[0]))


@_plan("ExpandExec")
def _expand(node, children, ctx) -> P.PlanNode:
    _op_enabled("expand")
    schema = _schema(node)
    child_schema = _native_schema_of(children[0])

    def conv(e: ForeignExpr, declared: DataType) -> E.Expr:
        x = EC.convert_expr_with_fallback(e)
        # grouping-set projections must hit the declared output types
        # exactly (e.g. int32 literal 0 under a bigint grouping-id column)
        if child_schema is not None:
            from auron_tpu.exprs.typing import infer_type
            try:
                if infer_type(x, child_schema) != declared:
                    return E.Cast(child=x, dtype=declared)
            except Exception:
                pass
        return x

    projections = tuple(
        tuple(conv(e, f.dtype) for e, f in zip(proj, schema.fields))
        for proj in node.attrs["projections"])
    return ctx.set_parts(
        P.Expand(child=children[0], projections=projections,
                 names=schema.names(),
                 types=tuple(f.dtype for f in schema.fields)),
        ctx.parts(children[0]))


@_plan("WindowExec")
def _window(node, children, ctx) -> P.PlanNode:
    _op_enabled("window")
    funcs = []
    for w in node.attrs.get("window_exprs", ()):
        # shape: {"name": out_name, "fn": fn_name, "args": [fexpr...],
        #         "agg": AggregateExpression fexpr (fn == "agg")}
        agg = None
        if w.get("agg") is not None:
            agg = EC.convert_agg_expr(w["agg"])
            rt = agg.return_type
        else:
            # per-function defaults (Spark: rank family is IntegerType,
            # percent_rank/cume_dist are DoubleType); value functions
            # (lead/lag/nth_value/...) have data-dependent types and must
            # declare one
            rt = w.get("dtype")
            if rt is None:
                if w["fn"] in ("percent_rank", "cume_dist"):
                    rt = DataType.float64()
                elif w["fn"] in ("row_number", "rank", "dense_rank"):
                    rt = DataType.int32()
                else:
                    raise NotConvertible(
                        f"window function {w['fn']} requires a dtype")
        funcs.append(P.WindowFuncCall(
            fn=w["fn"],
            args=tuple(EC.convert_expr_with_fallback(a)
                       for a in w.get("args", ())),
            agg=agg, return_type=rt, name=w["name"]))
    part_by = tuple(EC.convert_expr_with_fallback(e)
                    for e in node.attrs.get("partition_spec", ()))
    order_by = tuple(EC.convert_sort_order(s)
                     for s in node.attrs.get("order_spec", ()))
    return ctx.set_parts(
        P.Window(child=children[0], window_funcs=tuple(funcs),
                 partition_by=part_by, order_by=order_by),
        ctx.parts(children[0]))


@_plan("WindowGroupLimitExec")
def _window_group_limit(node, children, ctx) -> P.PlanNode:
    _op_enabled("window")
    part_by = tuple(EC.convert_expr_with_fallback(e)
                    for e in node.attrs.get("partition_spec", ()))
    order_by = tuple(EC.convert_sort_order(s)
                     for s in node.attrs.get("order_spec", ()))
    limit = P.WindowGroupLimit(
        k=int(node.attrs["limit"]),
        rank_fn=node.attrs.get("rank_like_function", "row_number"))
    return ctx.set_parts(
        P.Window(child=children[0], window_funcs=(), partition_by=part_by,
                 order_by=order_by, group_limit=limit,
                 output_window_cols=False),
        ctx.parts(children[0]))


@_plan("GenerateExec")
def _generate(node, children, ctx) -> P.PlanNode:
    _op_enabled("generate")
    gen = node.attrs["generator"]         # ForeignExpr
    gen_map = {"Explode": "explode", "PosExplode": "posexplode",
               "JsonTuple": "json_tuple"}
    udtf = None
    if gen.name in gen_map:
        generator = gen_map[gen.name]
    elif gen.py_fn is not None and config.UDF_FALLBACK_ENABLE.get():
        generator, udtf = "udtf", gen.py_fn
    else:
        raise NotConvertible(f"generator {gen.name} is not supported yet")
    out_names = tuple(node.attrs["generator_output_names"])
    out_types = tuple(node.attrs["generator_output_types"])
    required = tuple(int(i) for i in node.attrs.get(
        "required_child_output", ()))
    return ctx.set_parts(
        P.Generate(child=children[0], generator=generator,
                   args=tuple(EC.convert_expr_with_fallback(a)
                              for a in gen.children),
                   generator_output_names=out_names,
                   generator_output_types=out_types,
                   required_child_output=required,
                   outer=bool(node.attrs.get("outer", False)), udtf=udtf),
        ctx.parts(children[0]))


@_plan("UnionExec")
def _union(node, children, ctx) -> P.PlanNode:
    _op_enabled("union")
    schema = _schema(node)
    # flattened partition mapping (proto:542-552): output partitions are
    # the concatenation of every child's partitions, so each child
    # partition is read exactly once
    inputs = []
    out_pid = 0
    for c in children:
        for q in range(ctx.parts(c)):
            inputs.append(P.UnionInput(child=c, partition=q,
                                       out_partition=out_pid))
            out_pid += 1
    return ctx.set_parts(
        P.Union(inputs=tuple(inputs), schema=schema,
                num_partitions=out_pid, cur_partition=0),
        out_pid)


def _join_on(node) -> P.JoinOn:
    return P.JoinOn(
        left_keys=tuple(EC.convert_expr_with_fallback(k)
                        for k in node.attrs["left_keys"]),
        right_keys=tuple(EC.convert_expr_with_fallback(k)
                         for k in node.attrs["right_keys"]))


def _check_no_condition(node) -> None:
    if node.attrs.get("condition") is not None:
        raise NotConvertible(
            f"{node.op} with post-join condition is not supported yet")


@_plan("SortMergeJoinExec")
def _smj(node, children, ctx) -> P.PlanNode:
    if config.FORCE_SHUFFLED_HASH_JOIN.get():
        # rewrite the planned SMJ into a shuffled hash join — what the
        # reference achieves by patching Spark's planner bytecode
        # (ForceApplyShuffledHashJoinInjector.java).  "Prefer when both
        # are legal": if SHJ conversion is not possible (disabled,
        # unsupported shape) fall through to the normal SMJ path.
        try:
            return _shj(node, children, ctx)
        except NotConvertible:
            pass
    _op_enabled("smj")
    _check_no_condition(node)
    jt = EC.convert_join_type(node.attrs.get("join_type", "Inner"))
    nkeys = len(node.attrs["left_keys"])
    on = _join_on(node)

    def ensure_sorted(child: P.PlanNode, keys) -> P.PlanNode:
        # EnsureRequirements analogue: the streaming SMJ consumes
        # key-sorted inputs (childOrderingRequired tag,
        # AuronConvertStrategy.scala:41-47); a real engine plan carries
        # explicit SortExec children, a synthetic plan may not
        want = tuple(E.SortExpr(child=k, asc=True, nulls_first=True)
                     for k in keys)
        if isinstance(child, P.Sort) and child.sort_exprs[:nkeys] == want:
            return child
        return ctx.set_parts(P.Sort(child=child, sort_exprs=want),
                             ctx.parts(child))

    return ctx.set_parts(
        P.SortMergeJoin(
            left=ensure_sorted(children[0], on.left_keys),
            right=ensure_sorted(children[1], on.right_keys),
            on=on, join_type=jt,
            sort_options=tuple((True, True) for _ in range(nkeys)),
            existence_output_name=node.attrs.get("existence_name",
                                                 "exists")),
        max(ctx.parts(children[0]), ctx.parts(children[1])))


@_plan("ShuffledHashJoinExec")
def _shj(node, children, ctx) -> P.PlanNode:
    _op_enabled("shj")
    _check_no_condition(node)
    jt = EC.convert_join_type(node.attrs.get("join_type", "Inner"))
    return ctx.set_parts(
        P.HashJoin(left=children[0], right=children[1], on=_join_on(node),
                   join_type=jt,
                   build_side=node.attrs.get("build_side", "right"),
                   existence_output_name=node.attrs.get("existence_name",
                                                        "exists")),
        max(ctx.parts(children[0]), ctx.parts(children[1])))


@_plan("BroadcastHashJoinExec")
def _bhj(node, children, ctx) -> P.PlanNode:
    _op_enabled("bhj")
    _check_no_condition(node)
    jt = EC.convert_join_type(node.attrs.get("join_type", "Inner"))
    side = node.attrs.get("build_side", "right")
    on = _join_on(node)
    build_idx = 1 if side == "right" else 0
    build_keys = on.right_keys if side == "right" else on.left_keys
    cache_id = ctx.fresh("bhm")
    built = P.BroadcastJoinBuildHashMap(
        child=children[build_idx], keys=build_keys, cache_id=cache_id)
    ctx.set_parts(built, ctx.parts(children[build_idx]))
    pair = [children[0], children[1]]
    pair[build_idx] = built
    probe_parts = ctx.parts(children[1 - build_idx])
    return ctx.set_parts(
        P.BroadcastJoin(left=pair[0], right=pair[1], on=on, join_type=jt,
                        broadcast_side=side,
                        cached_build_hash_map_id=cache_id,
                        existence_output_name=node.attrs.get(
                            "existence_name", "exists")),
        probe_parts)


@_plan("ShuffleExchangeExec")
def _shuffle_exchange(node, children, ctx) -> P.PlanNode:
    _op_enabled("shuffle")
    part = convert_partitioning(node.attrs["partitioning"])
    rid = ctx.fresh("shuffle")
    schema = _native_schema_of(children[0]) or _schema(node)
    ctx.exchanges[rid] = ShuffleJob(rid=rid, child=children[0],
                                    partitioning=part, schema=schema)
    return ctx.set_parts(P.IpcReader(schema=schema, resource_id=rid),
                         part.num_partitions)


@_plan("BroadcastExchangeExec")
def _broadcast_exchange(node, children, ctx) -> P.PlanNode:
    rid = ctx.fresh("broadcast")
    schema = _native_schema_of(children[0]) or _schema(node)
    ctx.broadcasts[rid] = BroadcastJob(rid=rid, child=children[0],
                                       schema=schema)
    return ctx.set_parts(P.IpcReader(schema=schema, resource_id=rid), 1)


@_plan("DataWritingCommandExec")
def _data_writing(node, children, ctx) -> P.PlanNode:
    fmt = node.attrs.get("format", "parquet")
    out_dir = node.attrs["output_dir"]
    part_cols = tuple(node.attrs.get("partition_cols", ()))
    if fmt == "parquet":
        _op_enabled("parquet.sink")
        plan = P.ParquetSink(child=children[0], output_dir=out_dir,
                             partition_cols=part_cols,
                             compression=node.attrs.get("compression",
                                                        "zstd"))
    elif fmt == "orc":
        _op_enabled("orc.sink")
        plan = P.OrcSink(child=children[0], output_dir=out_dir,
                         partition_cols=part_cols,
                         compression=node.attrs.get("compression", "zstd"))
    else:
        raise NotConvertible(f"sink format {fmt}")
    return ctx.set_parts(plan, ctx.parts(children[0]))


@_plan("InsertIntoHiveTableExec")
def _insert_into_hive(node, children, ctx) -> P.PlanNode:
    """Hive insert glue (NativeParquetInsertIntoHiveTableBase /
    NativeOrcInsertIntoHiveTableBase analogue): the command carries the
    table's storage descriptor; static partition values extend the
    output path, dynamic partition columns flow to the sink's
    partitioned write."""
    storage = node.attrs.get("storage", {})
    fmt = str(storage.get("format", node.attrs.get("format",
                                                   "parquet"))).lower()
    if "orc" in fmt:
        fmt = "orc"
    elif "parquet" in fmt or fmt in ("hive", ""):
        fmt = "parquet"
    else:
        raise NotConvertible(f"hive serde format {fmt!r}")
    location = storage.get("location") or node.attrs.get("output_dir")
    if not location:
        raise NotConvertible("hive table without a location")
    # static partitions become path segments (k=v), Hive layout
    static_parts = node.attrs.get("static_partitions", {}) or {}
    out_dir = location
    for k, v in static_parts.items():
        out_dir = f"{out_dir}/{k}={v}"
    dyn_cols = tuple(node.attrs.get("dynamic_partition_cols", ()) or ())
    compression = storage.get("compression",
                              node.attrs.get("compression", "zstd"))
    if fmt == "parquet":
        _op_enabled("parquet.sink")
        plan: P.PlanNode = P.ParquetSink(
            child=children[0], output_dir=out_dir,
            partition_cols=dyn_cols, compression=compression)
    else:
        _op_enabled("orc.sink")
        plan = P.OrcSink(child=children[0], output_dir=out_dir,
                         partition_cols=dyn_cols,
                         compression=compression)
    return ctx.set_parts(plan, ctx.parts(children[0]))


# ---------------------------------------------------------------------------
# external convert providers (thirdparty SPI; AuronConvertProvider.scala:27
# + ServiceLoader discovery at AuronConverters.scala:108-112)
# ---------------------------------------------------------------------------

class ConvertProvider:
    """Extension hook: table formats (Iceberg/Paimon/Hudi) register one of
    these to claim foreign scan nodes."""

    def is_supported(self, node: ForeignNode) -> bool:
        raise NotImplementedError

    def convert(self, node: ForeignNode, children, ctx: ConvertContext
                ) -> P.PlanNode:
        raise NotImplementedError


_EXT_PROVIDERS: List[ConvertProvider] = []


def register_provider(p: ConvertProvider) -> None:
    _EXT_PROVIDERS.append(p)


def unregister_provider(p: ConvertProvider) -> None:
    try:
        _EXT_PROVIDERS.remove(p)
    except ValueError:
        pass


def ext_convert_supported(node: ForeignNode) -> bool:
    return any(p.is_supported(node) for p in _EXT_PROVIDERS)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def convert_node(node: ForeignNode, native_children: List[P.PlanNode],
                 ctx: ConvertContext) -> P.PlanNode:
    """Strict single-node conversion given native children."""
    for p in _EXT_PROVIDERS:
        if p.is_supported(node):
            return p.convert(node, native_children, ctx)
    fn = _PLAN_CONVERTERS.get(node.op)
    if fn is None:
        raise NotConvertible(f"{node.op} is not supported yet")
    return fn(node, native_children, ctx)


def dry_run_convertible(node: ForeignNode) -> Optional[str]:
    """Convertibility probe for the strategy pass: children are assumed
    native.  Returns None if convertible, else the reason."""
    ctx = ConvertContext()
    placeholders = []
    for c in node.children:
        schema = c.output if c.output is not None else Schema(())
        ph = P.FFIReader(schema=schema, resource_id="__dryrun__")
        placeholders.append(ctx.set_parts(ph, 1))
    try:
        convert_node(node, placeholders, ctx)
        return None
    except NotConvertible as e:
        return str(e)
    except Exception as e:  # converter bug surfaces as non-convertible
        return f"{type(e).__name__}: {e}"


def convert_to_native(converted: ConvertedT, ctx: ConvertContext
                      ) -> P.PlanNode:
    """C2N insertion (AuronConverters.convertToNative:1132): a foreign
    subtree under a native parent enters through an FFIReader."""
    if not isinstance(converted, ForeignWrap):
        return converted
    node = converted.node
    schema = node.output if node.output is not None else Schema(())
    rid = ctx.fresh("c2n")
    ctx.sources[rid] = ForeignSource(rid=rid, node=converted)
    reader = P.FFIReader(schema=schema, resource_id=rid)
    return ctx.set_parts(reader, 1)


def convert_recursively(node: ForeignNode, tags, ctx: ConvertContext
                        ) -> ConvertedT:
    """convertSparkPlanRecursively:186-209 analogue, driven by the
    strategy's tags (frontend.strategy.Tags)."""
    converted_children = [convert_recursively(c, tags, ctx)
                          for c in node.children]
    if tags.is_always_convert(node):
        native_children = [convert_to_native(c, ctx)
                           for c in converted_children]
        return convert_node(node, native_children, ctx)
    return ForeignWrap(node=node, children=converted_children)
