#!/usr/bin/env bash
# CI lint gate (fast, no accelerator needed):
#   1. static-analyze every golden plan document in tests/golden_plans
#      (python -m auron_tpu.analysis; exit 2 on any error diagnostic)
#   2. byte-compile the whole tree (syntax-error floor, always available)
#   3. ruff (pyflakes-tier rules, see ruff.toml) when installed — the
#      container image does not bake it in, so it is gated, not required
#
# Regenerate the golden set after intentional plan changes with:
#   python -m auron_tpu.analysis --regen-golden
#
# The same checks run inside the tier-1 suite (tests/test_analysis.py::
# test_golden_corpus_lints_clean and test_tools_lint_script), so CI that
# only runs pytest still gets the gate.
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m auron_tpu.analysis --quiet "$@"

python -m compileall -q auron_tpu tests tools bench.py

if command -v ruff >/dev/null 2>&1; then
    ruff check auron_tpu tests tools
else
    echo "lint_plans.sh: ruff not installed; plan lint + compileall ran, source lint skipped" >&2
fi
echo "lint_plans.sh: ok"
