#!/usr/bin/env bash
# CI adaptive-execution gate (CPU, no accelerator needed):
#   1. run a skewed + tiny-partition corpus mix through the serial
#      exchange path twice per query — auron.adaptive.enable off, then
#      on (forced thresholds so every decision family fires on the
#      small data)
#   2. assert EVERY AQE-on result is value-identical to its AQE-off
#      run, every rewritten plan passed the analyzer (a failed rewrite
#      would have been dropped and the decision would be missing), the
#      forced-decision microbenches hold (coalescing reduces the
#      reduce-task count; broadcast conversion removes the build
#      side's partition-indexed fetch), and an interleaved in-process
#      A/B on the coalesce-sensitive query shows no regression
#   3. dump the Prometheus snapshot and prom_assert
#      auron_adaptive_{broadcast,coalesce,skew_split}_total >= 1
#
# The same check runs inside the suite (tests/test_adaptive.py::
# test_tools_aqe_check_script, marked slow), mirroring how
# rss_check.sh / fleet_check.sh are wired.
set -euo pipefail
cd "$(dirname "$0")/.."
source tools/prom_assert.sh
PROM_OUT="$(mktemp)"
export PROM_OUT
trap 'rm -f "$PROM_OUT"' EXIT

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import os
import tempfile
import time

from auron_tpu import config
from auron_tpu.frontend import AuronSession, ForeignNode, fcol
from auron_tpu.ir.schema import DataType, Field, Schema
from auron_tpu.it import compare, datagen, queries
from auron_tpu.it.oracle import PyArrowEngine
from auron_tpu.runtime import counters

I64 = DataType.int64()
F64 = DataType.float64()
S = Schema((Field("k", I64), Field("v", F64)))

SERIAL = {"auron.spmd.singleDevice.enable": False,
          "auron.force.shuffled.hash.join": True}
AQE = {**SERIAL, "auron.adaptive.enable": True,
       "auron.adaptive.target.partition.bytes": 1 << 20,
       "auron.adaptive.skew.factor": 2.0,
       "auron.adaptive.skew.min.partition.bytes": 1024}

catalog = datagen.generate(
    tempfile.mkdtemp(prefix="auron-aqe-check-"), sf=0.002)


def run(plan, overlay):
    with config.conf.scoped(overlay):
        return AuronSession(foreign_engine=PyArrowEngine()).execute(plan)


def check_same(name, plan, off, on):
    err = compare.compare_tables(
        on.table, off.table, ordered=compare.plan_is_ordered(plan))
    assert err is None, f"{name}: AQE-on deviates: {err}"


# -- corpus mix: tiny partitions force broadcast + coalesce ---------------
fired = set()
for name in ("q01", "q42"):
    plan = queries.build(name, catalog)
    off = run(plan, SERIAL)
    on = run(plan, AQE)
    check_same(name, plan, off, on)
    fired.update(d["kind"] for d in on.aqe_decisions)
    print(f"{name}: decisions="
          f"{[(d['kind'], d['exchange']) for d in on.aqe_decisions]}")

# -- synthetic skew: a hot key over a row-local consumer ------------------
hot = [ForeignNode("LocalTableScanExec", output=S, attrs={"rows": [
    {"k": 7 if i % 4 else (i % 97), "v": float(i)}
    for i in range(c * 4000, (c + 1) * 4000)]}) for c in range(4)]
union = ForeignNode("UnionExec", children=tuple(hot), output=S)
ex = ForeignNode(
    "ShuffleExchangeExec", children=(union,), output=S,
    attrs={"partitioning": {"mode": "hash", "num_partitions": 4,
                            "expressions": [fcol("k", I64)]}})
skew_plan = ForeignNode(
    "ProjectExec", children=(ex,), output=S,
    attrs={"project_list": [fcol("k", I64), fcol("v", F64)]})
skew_conf = {**AQE, "auron.adaptive.broadcast.enable": False,
             "auron.adaptive.coalesce.enable": False,
             "auron.adaptive.target.partition.bytes": 1 << 18}
off = run(skew_plan, SERIAL)
on = run(skew_plan, skew_conf)
check_same("skew", skew_plan, off, on)
fired.update(d["kind"] for d in on.aqe_decisions)
assert {"broadcast", "coalesce", "skew_split"} <= fired, \
    f"decision families missing: fired={fired}"

# -- forced-decision microbenches ----------------------------------------
from auron_tpu.runtime.explain_analyze import merge_metric_trees

DIM = Schema((Field("k2", I64), Field("w", F64)))
left = ForeignNode("LocalTableScanExec", output=S, attrs={
    "rows": [{"k": i % 40, "v": float(i)} for i in range(2000)]})
right = ForeignNode("LocalTableScanExec", output=DIM, attrs={
    "rows": [{"k2": i, "w": float(i)} for i in range(40)]})


def hash_ex(child, key, n=8):
    return ForeignNode(
        "ShuffleExchangeExec", children=(child,), output=child.output,
        attrs={"partitioning": {"mode": "hash", "num_partitions": n,
                                "expressions": [fcol(key, I64)]}})


join = ForeignNode(
    "ShuffledHashJoinExec",
    children=(hash_ex(left, "k"), hash_ex(right, "k2")),
    output=S.concat(DIM),
    attrs={"left_keys": [fcol("k", I64)],
           "right_keys": [fcol("k2", I64)],
           "join_type": "Inner", "build_side": "right"})


def n_shuffle_readers(res):
    def walk(n):
        n._settle()
        yield n
        for c in n.children:
            yield from walk(c)
    return sum(1 for t in res.metrics for node in walk(t)
               if node.name.startswith("IpcReaderExec")
               and node.values.get("shuffle_read_bytes"))


off = run(join, SERIAL)
on = run(join, {**AQE, "auron.adaptive.coalesce.enable": False,
                "auron.adaptive.skew.enable": False})
check_same("join", join, off, on)
assert any(d["kind"] == "broadcast" for d in on.aqe_decisions)
assert n_shuffle_readers(on) < n_shuffle_readers(off), \
    "broadcast conversion did not remove the build-side fetch"
print(f"broadcast microbench: partitioned fetch readers "
      f"{n_shuffle_readers(off)} -> {n_shuffle_readers(on)}")


def reduce_tasks(res, prefix):
    return sum(n for t, n in merge_metric_trees(res.metrics)
               if t.name.startswith(prefix))


from auron_tpu.frontend import fcall
from auron_tpu.frontend.foreign import ForeignExpr

aggs = [ForeignExpr("AggregateExpression",
                    children=(fcall("Sum", fcol("v", F64), dtype=F64),))]
partial = ForeignNode(
    "HashAggregateExec", children=(left,),
    output=Schema((Field("k", I64), Field("s#sum", F64))),
    attrs={"grouping": [fcol("k", I64)], "aggs": aggs,
           "agg_names": ["s"], "mode": "partial"})
agg_plan = ForeignNode(
    "HashAggregateExec", children=(hash_ex(partial, "k"),),
    output=Schema((Field("k", I64), Field("s", F64))),
    attrs={"grouping": [fcol("k", I64)], "aggs": aggs,
           "agg_names": ["s"], "mode": "final"})
coal_conf = {**AQE, "auron.adaptive.broadcast.enable": False,
             "auron.adaptive.skew.enable": False}
off = run(agg_plan, SERIAL)
on = run(agg_plan, coal_conf)
check_same("agg", agg_plan, off, on)
assert any(d["kind"] == "coalesce" for d in on.aqe_decisions)
t_off, t_on = reduce_tasks(off, "AggExec"), reduce_tasks(on, "AggExec")
assert t_on < t_off, \
    f"coalescing did not reduce reduce-task count ({t_off} -> {t_on})"
print(f"coalesce microbench: reduce tasks {t_off} -> {t_on}")

# -- interleaved A/B: no regression on the coalesce-sensitive shape ------
for _ in range(2):                       # warm both paths
    run(agg_plan, SERIAL)
    run(agg_plan, coal_conf)
t_offs, t_ons = [], []
for _ in range(3):                       # alternate to ride load swings
    t0 = time.perf_counter()
    run(agg_plan, SERIAL)
    t_offs.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    run(agg_plan, coal_conf)
    t_ons.append(time.perf_counter() - t0)
off_s, on_s = min(t_offs), min(t_ons)
ratio = off_s / max(on_s, 1e-9)
print(f"aqe A/B (interleaved, best-of-3): off={off_s * 1e3:.0f}ms "
      f"on={on_s * 1e3:.0f}ms speedup={ratio:.2f}x")
assert on_s <= off_s * 1.3, \
    f"AQE-on regressed: {on_s:.3f}s vs {off_s:.3f}s off"

snap = counters.snapshot()
for key in ("adaptive_broadcast", "adaptive_coalesce",
            "adaptive_skew_split"):
    print(f"{key}_total={snap.get(key, 0)}")

from auron_tpu.runtime import profiling

with open(os.environ["PROM_OUT"], "w") as f:
    f.write(profiling._prometheus_text())
print("AQE_CHECK_DRIVER_OK")
EOF

prom_assert_ge "$PROM_OUT" auron_adaptive_broadcast_total 1
prom_assert_ge "$PROM_OUT" auron_adaptive_coalesce_total 1
prom_assert_ge "$PROM_OUT" auron_adaptive_skew_split_total 1
echo "aqe_check: OK"
