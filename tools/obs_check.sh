#!/usr/bin/env bash
# CI fleet-observability gate (CPU, no accelerator needed) — the
# tracing promotion of tools/rss_check.sh:
#   1. spawn a 2-executor fleet WITH the durable-shuffle side-car and
#      TRACING ON (`auron.trace.enable`): every dispatch overlay
#      propagates trace context, workers/side-car record spans
#      locally, the driver harvests them over heartbeats and stitches
#      ONE Chrome trace per query with clock-aligned per-process lanes
#   2. POST four concurrent /submit requests (IT-corpus queries)
#   3. kill -9 the busiest executor mid-flight (the injected worker
#      death)
#   4. assert: every query succeeds; the requeued query's stitched
#      trace VALIDATES and contains spans from >= 3 processes with
#      the dead victim flagged `incomplete`; /events names the worker
#      death with the affected query ids; /queries/<id> serves the
#      harvested per-operator metric trees + lifecycle timeline; the
#      latency histograms and trace-drop counter are on /metrics
#
# The same check runs inside the suite (tests/test_fleet_observability
# .py::test_tools_obs_check_script, marked slow), mirroring how
# rss_check.sh / fleet_check.sh are wired.
set -euo pipefail
cd "$(dirname "$0")/.."
source tools/prom_assert.sh
PROM_OUT="$(mktemp)"
export PROM_OUT
trap 'rm -f "$PROM_OUT"' EXIT

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import os
import signal
import tempfile
import threading
import time
import urllib.request

from auron_tpu import faults
from auron_tpu.config import conf
from auron_tpu.it import datagen
from auron_tpu.memmgr.manager import reset_manager
from auron_tpu.runtime import tracing
from auron_tpu.serving import FleetManager, QueryServer, register_catalog

SF = 0.002
NAMES = ["q01", "q42", "q01", "q42"]

catalog = datagen.generate(
    tempfile.mkdtemp(prefix="auron-obs-check-"), sf=SF)
register_catalog(SF, catalog)

# latency-only worker chaos keeps queries in flight long enough for
# the kill to land mid-query (and for heartbeat harvests to drain the
# victim's spans before it dies)
worker_spec = "op.execute:latency:p=0.5,ms=150,max=60,seed=11"
worker_conf = {"auron.spmd.singleDevice.enable": False,
               "auron.faults.spec": worker_spec,
               "auron.task.retries": 2,
               "auron.retry.backoff.base.ms": 1.0,
               "auron.retry.backoff.max.ms": 10.0,
               "auron.serving.preempt.watermark": 0.0,
               "auron.serving.max.concurrent": 4}
hb = 1.5
scope = {"auron.retry.backoff.base.ms": 1.0,
         "auron.retry.backoff.max.ms": 10.0,
         "auron.net.timeout.seconds": 10.0,
         "auron.fleet.heartbeat.seconds": hb,
         "auron.fleet.death.probes": 3,
         "auron.admission.default.forecast.bytes": 1 << 20,
         "auron.serving.max.concurrent": 4,
         "auron.trace.enable": True}


def post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.load(r)


def get(url):
    with urllib.request.urlopen(url, timeout=300) as r:
        return r.read()


with conf.scoped(scope):
    reset_manager(1 << 30)
    fleet = FleetManager.spawn(2, conf_map=worker_conf,
                               budget_bytes=1 << 29, rss_sidecar=True)
    srv = QueryServer(scheduler=fleet).start()
    try:
        qids = {}
        errs = []

        def submit(i, name):
            try:
                doc = post(srv.url + "/submit",
                           {"corpus": name, "sf": SF,
                            "priority": 1 + (i % 3)})
                qids[i] = (name, doc["query_id"])
            except Exception as e:   # noqa: BLE001
                errs.append((name, repr(e)))

        threads = [threading.Thread(target=submit, args=(i, n))
                   for i, n in enumerate(NAMES)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        assert len(qids) == len(NAMES)

        # kill -9 the busiest executor once it is running work
        victim = survivor = None
        deadline = time.time() + 120
        while time.time() < deadline:
            snap = fleet.fleet_snapshot()
            busy = sorted(snap.items(), key=lambda kv: -kv[1]["inflight"])
            eid, doc = busy[0]
            if doc["inflight"] >= 2 and doc["load"].get("running", 0) >= 1:
                victim, survivor = eid, busy[1][0]
                break
            time.sleep(0.1)
        assert victim is not None, fleet.fleet_snapshot()
        victim_qids = [q for _, q in qids.values()
                       if fleet.get(q).executor_id == victim
                       and not fleet.get(q).done.is_set()]
        os.kill(fleet._handles[victim].endpoint.pid, signal.SIGKILL)

        for _, (name, qid) in sorted(qids.items()):
            assert fleet.wait(qid, timeout=600), \
                f"{name} did not finish: {fleet.status(qid)}"
            st = json.loads(get(srv.url + f"/status/{qid}"))
            assert st["state"] == "succeeded", (name, st)
            assert st["timeline"][-1]["state"] == "succeeded", st

        # the flight recorder names the injected death + its victims
        evs = json.loads(get(srv.url + "/events"))["events"]
        deaths = [e for e in evs if e["kind"] == "worker.death"]
        assert deaths, f"no worker.death on /events: {evs}"
        death = deaths[-1]
        assert death["attrs"]["executor"] == victim, death
        assert set(victim_qids) <= set(death["query_ids"]), \
            (victim_qids, death)
        requeues = [e for e in evs if e["kind"] == "query.requeue"]
        assert requeues and deaths[0]["seq"] < requeues[-1]["seq"]

        # ONE stitched trace per query: validate the requeued query's
        requeued = [q for q in victim_qids
                    if fleet.status(q)["requeues"] >= 1]
        assert requeued, "the killed executor's queries never requeued"
        q = requeued[0]
        doc = json.loads(get(srv.url + f"/queries/{q}/trace"))
        errors = tracing.validate_chrome_trace(doc)
        assert errors == [], errors
        other = doc["otherData"]
        assert other["stitched"] is True, other
        pids = {e["pid"] for e in doc["traceEvents"]
                if e.get("ph") in ("X", "i")}
        assert len(pids) >= 3, \
            f"stitched trace spans fewer than 3 processes: {pids}"
        assert victim in other["incomplete"], other
        names = {e["name"] for e in doc["traceEvents"]}
        assert "fleet.dispatch" in names and \
            "event.query.requeue" in names, sorted(names)[:40]

        # /queries/<id>: harvested per-operator trees + timeline
        det = json.loads(get(srv.url + f"/queries/{q}?format=json"))
        assert det["analyzed"] and "output_rows" in det["analyzed"]
        assert det["timeline"][-1]["state"] == "succeeded"
        assert "queued" in det["state_durations"]

        with open(os.environ["PROM_OUT"], "w") as f:
            f.write(get(srv.url + "/metrics").decode())
        print(f"obs_check: {len(NAMES)}/{len(NAMES)} queries traced; "
              f"executor {victim} killed -9 mid-flight; stitched "
              f"trace for {q} spans {len(pids)} processes "
              f"(victim flagged incomplete), worker death on /events "
              f"with {len(death['query_ids'])} affected query id(s)")
    finally:
        procs = [h.endpoint.proc for h in fleet._handles.values()
                 if getattr(h.endpoint, "proc", None) is not None]
        sc = fleet._sidecar.proc
        srv.stop()
        for p in procs:
            assert p.poll() is not None, "worker process leaked"
        assert sc.proc.poll() is not None, "side-car process leaked"
        reset_manager()
        faults.reset()
EOF

prom_assert_contains "$PROM_OUT" \
  "auron_query_wall_seconds_bucket" \
  "auron_query_queue_wait_seconds_count" \
  "auron_trace_dropped_events_total" \
  "auron_fleet_worker_trace_dropped_events_total"
prom_assert_ge "$PROM_OUT" auron_fleet_deaths_total 1
prom_assert_ge "$PROM_OUT" auron_query_wall_seconds_count 1

echo "obs_check.sh: ok"
