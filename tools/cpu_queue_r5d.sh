#!/bin/bash
# Round-5 CPU queue tail: after the sf10 rung, regenerate the
# per-commit gate corpus (IT_PERF) with the final engine, then deepen
# the real-plan differential to sf=0.1.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/cpu_queue_r5.log
echo "$(date -u +%H:%M:%S) queue5 armed" >> "$LOG"
while pgrep -f "python -m auron_tpu.it --sf 10" > /dev/null; do
  sleep 120
done
echo "$(date -u +%H:%M:%S) [5] IT_PERF regen" >> "$LOG"
nice -n 10 timeout 14400 python -m auron_tpu.it --sf 0.1 \
  --data-dir /tmp/auron_tpcds_01 --perf-factor 3 \
  --json IT_PERF.json > /tmp/it_perf_regen.out 2>&1
echo "$(date -u +%H:%M:%S) [5] rc=$?" >> "$LOG"
echo "$(date -u +%H:%M:%S) [6] refplans sf0.1" >> "$LOG"
for i in 1 2 3; do
  nice -n 10 timeout 14400 python -m auron_tpu.it.refplans --sf 0.1 \
    --data-dir /tmp/auron_tpcds_01 --resume \
    --json IT_REFPLANS_SF01.json > /tmp/refplans_sf01.out 2>&1
  rc=$?
  echo "$(date -u +%H:%M:%S) [6] pass $i rc=$rc" >> "$LOG"
  [ "$rc" = "0" ] && break
done
echo "$(date -u +%H:%M:%S) queue5 done" >> "$LOG"
