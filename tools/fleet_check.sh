#!/usr/bin/env bash
# CI fleet-survival gate (CPU, no accelerator needed) — the
# multi-process promotion of tools/overload_check.sh:
#   1. spawn a 2-executor fleet (worker PROCESSES running the slim
#      executor server, serving/executor_endpoint.py) behind one
#      QueryServer + admission ledger, with io+latency faults injected
#      inside the workers AND on the fleet RPC boundary
#   2. POST six concurrent /submit requests (IT-corpus queries)
#   3. kill -9 the busiest executor mid-flight
#   4. assert the death is detected, every in-flight query is requeued
#      on the surviving executor, EVERY query still succeeds with
#      results value-identical to its solo fault-free run, the
#      admission ledger drains, auron_fleet_requeues_total /
#      auron_fleet_executor_up are visible on /metrics, and no worker
#      process outlives the fleet
#
# The same check runs inside the suite (tests/test_fleet.py::
# test_tools_fleet_check_script, marked slow), mirroring how
# overload_check.sh / serve_check.sh are wired.
set -euo pipefail
cd "$(dirname "$0")/.."
source tools/prom_assert.sh
PROM_OUT="$(mktemp)"
PROM_NEEDLES="$(mktemp)"
export PROM_OUT PROM_NEEDLES
trap 'rm -f "$PROM_OUT" "$PROM_NEEDLES"' EXIT

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import os
import signal
import tempfile
import threading
import time
import urllib.request

import pyarrow as pa

from auron_tpu import faults
from auron_tpu.config import conf
from auron_tpu.frontend.session import AuronSession
from auron_tpu.it import datagen, queries
from auron_tpu.it.oracle import PyArrowEngine
from auron_tpu.memmgr.manager import reset_manager
from auron_tpu.serving import FleetManager, QueryServer, register_catalog

SF = 0.002
NAMES = ["q01", "q42", "q01", "q42", "q01", "q42"]

catalog = datagen.generate(
    tempfile.mkdtemp(prefix="auron-fleet-check-"), sf=SF)
register_catalog(SF, catalog)


def canon(t):
    t = t.combine_chunks()
    return t.sort_by([(n, "ascending") for n in t.column_names]) \
        if t.num_rows and t.num_columns else t


serial = {"auron.spmd.singleDevice.enable": False}
baselines = {}
with conf.scoped(serial):
    for name in set(NAMES):
        s = AuronSession(foreign_engine=PyArrowEngine())
        baselines[name] = canon(s.execute(queries.build(name, catalog)).table)

worker_spec = ("shuffle.push:io:p=0.05,max=6,seed=7;"
               "shuffle.push:latency:p=0.15,seed=5,ms=4;"
               "op.execute:latency:p=0.5,ms=150,max=60,seed=11")
worker_conf = {**serial,
               "auron.faults.spec": worker_spec,
               "auron.task.retries": 2,
               "auron.retry.backoff.base.ms": 1.0,
               "auron.retry.backoff.max.ms": 10.0,
               "auron.serving.preempt.watermark": 0.0,
               "auron.serving.max.concurrent": 4}
driver_spec = ("fleet.dispatch:io:p=0.25,max=2,seed=5;"
               "fleet.result:io:p=0.2,max=2,seed=9;"
               "fleet.heartbeat:latency:p=0.3,ms=10,seed=3")
faults.reset(driver_spec)
hb = 1.5
scope = {"auron.faults.spec": driver_spec,
         "auron.retry.backoff.base.ms": 1.0,
         "auron.retry.backoff.max.ms": 10.0,
         "auron.net.timeout.seconds": 10.0,
         "auron.fleet.heartbeat.seconds": hb,
         "auron.fleet.death.probes": 3,
         "auron.admission.default.forecast.bytes": 1 << 20,
         "auron.serving.max.concurrent": 4}


def post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.load(r)


def get(url):
    with urllib.request.urlopen(url, timeout=300) as r:
        return r.read()


with conf.scoped(scope):
    reset_manager(1 << 30)
    fleet = FleetManager.spawn(2, conf_map=worker_conf,
                               budget_bytes=1 << 29)
    srv = QueryServer(scheduler=fleet).start()
    try:
        qids = {}
        errs = []

        def submit(i, name):
            try:
                doc = post(srv.url + "/submit",
                           {"corpus": name, "sf": SF,
                            "priority": 1 + (i % 3)})
                qids[i] = (name, doc["query_id"])
            except Exception as e:   # noqa: BLE001
                errs.append((name, repr(e)))

        threads = [threading.Thread(target=submit, args=(i, n))
                   for i, n in enumerate(NAMES)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        assert len(qids) == len(NAMES)

        # kill -9 the busiest executor once it is actually running work
        victim = None
        deadline = time.time() + 120
        while time.time() < deadline:
            snap = fleet.fleet_snapshot()
            busy = sorted(snap.items(), key=lambda kv: -kv[1]["inflight"])
            eid, doc = busy[0]
            if doc["inflight"] >= 2 and doc["load"].get("running", 0) >= 1:
                victim, survivor = eid, busy[1][0]
                break
            time.sleep(0.1)
        assert victim is not None, fleet.fleet_snapshot()
        victim_qids = [qid for _, qid in qids.values()
                       if fleet.get(qid).executor_id == victim
                       and not fleet.get(qid).done.is_set()]
        os.kill(fleet._handles[victim].endpoint.pid, signal.SIGKILL)
        t_kill = time.monotonic()

        detect_s = None
        while time.monotonic() - t_kill < 30:
            if fleet.fleet_snapshot()[victim]["state"] == "dead":
                detect_s = time.monotonic() - t_kill
                break
            time.sleep(0.05)
        assert detect_s is not None, "death never declared"
        assert detect_s <= 3 * hb + hb / 2, \
            f"death took {detect_s:.2f}s (> 3 heartbeats of {hb}s)"

        for i, (name, qid) in sorted(qids.items()):
            assert fleet.wait(qid, timeout=600), \
                f"{name} did not finish: {fleet.status(qid)}"
            st = json.loads(get(srv.url + f"/status/{qid}"))
            assert st["state"] == "succeeded", (name, st)
            res = json.loads(get(srv.url + f"/result/{qid}"))
            assert not res["truncated"]
            got = canon(pa.Table.from_pylist(
                res["rows"], schema=baselines[name].schema))
            assert got.equals(baselines[name]), \
                f"{name} served result diverged from its solo run"

        assert fleet.fleet_snapshot()[victim]["state"] == "dead"
        requeued = [q for q in victim_qids
                    if fleet.status(q)["requeues"] >= 1]
        assert requeued, "the killed executor's queries never requeued"
        for q in requeued:
            st = fleet.status(q)
            assert st["executor"] != victim, st
            assert victim in st["excluded_executors"], st
        assert fleet.admission.held_bytes() == 0

        # Prometheus assertions: shared tools/prom_assert.sh helper —
        # the run-dependent victim label travels via the needle file
        with open(os.environ["PROM_OUT"], "w") as f:
            f.write(get(srv.url + "/metrics").decode())
        with open(os.environ["PROM_NEEDLES"], "w") as f:
            f.write(f'auron_fleet_executor_up{{executor="{victim}"}} 0\n')
        print(f"fleet_check: {len(NAMES)}/{len(NAMES)} queries "
              f"value-identical to solo runs; executor {victim} killed "
              f"-9 mid-flight, {len(requeued)} query(ies) requeued on "
              f"{survivor} (death detected {detect_s:.1f}s after kill)")
    finally:
        procs = [h.endpoint.proc for h in fleet._handles.values()
                 if getattr(h.endpoint, "proc", None) is not None]
        srv.stop()
        for p in procs:
            assert p.poll() is not None, "worker process leaked"
        reset_manager()
        faults.reset()
EOF

prom_assert_contains "$PROM_OUT" \
  "auron_fleet_requeues_total" \
  "auron_fleet_deaths_total"
prom_assert_needles "$PROM_OUT" "$PROM_NEEDLES"
prom_assert_ge "$PROM_OUT" auron_fleet_requeues_total 1

echo "fleet_check.sh: ok"
