#!/usr/bin/env bash
# CI durable-shuffle gate (CPU, no accelerator needed) — the resume
# promotion of tools/fleet_check.sh:
#   1. spawn a 2-executor fleet WITH the durable-shuffle side-car
#      process (auron.rss.sidecar.enable): every dispatch routes its
#      exchanges through `python -m auron_tpu.shuffle_rss.server` via
#      the commit protocol (shuffle_rss/durable.py)
#   2. POST six concurrent /submit requests (IT-corpus queries)
#   3. kill -9 the busiest executor once >= 1 of its queries' stages
#      is committed+sealed on the side-car
#   4. assert the death is detected, the requeued queries RESUME
#      (auron_fleet_worker_rss_stage_skips_total >= 1 on /metrics, and
#      the sealed stage's cumulative side-car commit total stays flat
#      — its map tasks never re-ran), EVERY query succeeds with
#      results value-identical to its solo fault-free run, zero
#      task-retry budget consumed anywhere, the side-car ledger is
#      cleaned at terminal states, auron_rss_sidecar_up is 1, and no
#      worker or side-car process outlives the fleet
#
# The same check runs inside the suite (tests/test_durable_shuffle.py::
# test_tools_rss_check_script, marked slow), mirroring how
# fleet_check.sh / overload_check.sh are wired.
set -euo pipefail
cd "$(dirname "$0")/.."
source tools/prom_assert.sh
PROM_OUT="$(mktemp)"
export PROM_OUT
trap 'rm -f "$PROM_OUT"' EXIT

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import os
import signal
import tempfile
import threading
import time
import urllib.request

import pyarrow as pa

from auron_tpu import faults
from auron_tpu.config import conf
from auron_tpu.frontend.session import AuronSession
from auron_tpu.it import datagen, queries
from auron_tpu.it.oracle import PyArrowEngine
from auron_tpu.memmgr.manager import reset_manager
from auron_tpu.runtime import counters
from auron_tpu.serving import FleetManager, QueryServer, register_catalog

SF = 0.002
NAMES = ["q01", "q42", "q01", "q42", "q01", "q42"]

catalog = datagen.generate(
    tempfile.mkdtemp(prefix="auron-rss-check-"), sf=SF)
register_catalog(SF, catalog)


def canon(t):
    t = t.combine_chunks()
    return t.sort_by([(n, "ascending") for n in t.column_names]) \
        if t.num_rows and t.num_columns else t


serial = {"auron.spmd.singleDevice.enable": False}
baselines = {}
with conf.scoped(serial):
    for name in set(NAMES):
        s = AuronSession(foreign_engine=PyArrowEngine())
        baselines[name] = canon(s.execute(queries.build(name, catalog)).table)

# worker chaos: latency only — the zero-retries assertion covers every
# worker, and io faults would consume retry budget by design
worker_spec = ("op.execute:latency:p=0.5,ms=150,max=60,seed=11;"
               "rss.push:latency:p=0.2,ms=3,max=40,seed=5")
worker_conf = {**serial,
               "auron.faults.spec": worker_spec,
               "auron.task.retries": 2,
               "auron.retry.backoff.base.ms": 1.0,
               "auron.retry.backoff.max.ms": 10.0,
               "auron.serving.preempt.watermark": 0.0,
               "auron.serving.max.concurrent": 4}
driver_spec = ("fleet.dispatch:io:p=0.25,max=2,seed=5;"
               "fleet.result:io:p=0.2,max=2,seed=9")
faults.reset(driver_spec)
hb = 1.5
scope = {"auron.faults.spec": driver_spec,
         "auron.retry.backoff.base.ms": 1.0,
         "auron.retry.backoff.max.ms": 10.0,
         "auron.net.timeout.seconds": 10.0,
         "auron.fleet.heartbeat.seconds": hb,
         "auron.fleet.death.probes": 3,
         "auron.admission.default.forecast.bytes": 1 << 20,
         "auron.serving.max.concurrent": 4}


def post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.load(r)


def get(url):
    with urllib.request.urlopen(url, timeout=300) as r:
        return r.read()


t_retried0 = counters.get("tasks_retried")
with conf.scoped(scope):
    reset_manager(1 << 30)
    fleet = FleetManager.spawn(2, conf_map=worker_conf,
                               budget_bytes=1 << 29, rss_sidecar=True)
    control = fleet._sidecar.control
    srv = QueryServer(scheduler=fleet).start()
    try:
        qids = {}
        errs = []

        def submit(i, name):
            try:
                doc = post(srv.url + "/submit",
                           {"corpus": name, "sf": SF,
                            "priority": 1 + (i % 3)})
                qids[i] = (name, doc["query_id"])
            except Exception as e:   # noqa: BLE001
                errs.append((name, repr(e)))

        threads = [threading.Thread(target=submit, args=(i, n))
                   for i, n in enumerate(NAMES)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        assert len(qids) == len(NAMES)

        # kill -9 the busiest executor once one of its in-flight
        # queries has a committed+sealed stage on the side-car
        victim = survivor = None
        resumed_qid = sealed_sid = None
        commits_before = None
        deadline = time.time() + 180
        while time.time() < deadline:
            snap = fleet.fleet_snapshot()
            busy = sorted(snap.items(), key=lambda kv: -kv[1]["inflight"])
            eid, doc = busy[0]
            if doc["inflight"] >= 2 and doc["load"].get("running", 0) >= 1:
                inflight = [q for _, q in qids.values()
                            if fleet.get(q).executor_id == eid
                            and not fleet.get(q).done.is_set()]
                stats = control.stats()
                for q in inflight:
                    for sid, sdoc in stats["shuffles"].items():
                        if sid.startswith(f"{q}|") and \
                                sdoc["sealed"] is not None and \
                                sdoc["maps"] >= sdoc["sealed"]:
                            victim, survivor = eid, busy[1][0]
                            resumed_qid, sealed_sid = q, sid
                            commits_before = \
                                stats["totals"][sid]["commits"]
                            break
                    if victim:
                        break
            if victim:
                break
            time.sleep(0.1)
        assert victim is not None, (fleet.fleet_snapshot(),
                                    control.stats())
        victim_qids = [q for _, q in qids.values()
                       if fleet.get(q).executor_id == victim
                       and not fleet.get(q).done.is_set()]
        os.kill(fleet._handles[victim].endpoint.pid, signal.SIGKILL)
        t_kill = time.monotonic()

        detect_s = None
        while time.monotonic() - t_kill < 30:
            if fleet.fleet_snapshot()[victim]["state"] == "dead":
                detect_s = time.monotonic() - t_kill
                break
            time.sleep(0.05)
        assert detect_s is not None, "death never declared"

        for i, (name, qid) in sorted(qids.items()):
            assert fleet.wait(qid, timeout=600), \
                f"{name} did not finish: {fleet.status(qid)}"
            st = json.loads(get(srv.url + f"/status/{qid}"))
            assert st["state"] == "succeeded", (name, st)
            res = json.loads(get(srv.url + f"/result/{qid}"))
            assert not res["truncated"]
            got = canon(pa.Table.from_pylist(
                res["rows"], schema=baselines[name].schema))
            assert got.equals(baselines[name]), \
                f"{name} served result diverged from its solo run"

        requeued = [q for q in victim_qids
                    if fleet.status(q)["requeues"] >= 1]
        assert requeued, "the killed executor's queries never requeued"

        # RESUME, not recompute: >= 1 stage skipped on the survivor
        # (visible on /metrics via the fleet-aggregated worker
        # counters — asserted by the shared tools/prom_assert.sh
        # helper after this block) and the sealed stage's commit
        # total never moved
        prom = get(srv.url + "/metrics").decode()
        with open(os.environ["PROM_OUT"], "w") as f:
            f.write(prom)
        lines = [ln for ln in prom.splitlines()
                 if ln.startswith("auron_fleet_worker_rss_stage_skips"
                                  "_total ")]
        skips = int(lines[0].split()[-1]) if lines else 0
        post_stats = control.stats(prefix=f"{resumed_qid}|")
        assert post_stats["totals"][sealed_sid]["commits"] == \
            commits_before, "map tasks re-ran for the sealed stage"

        # side-car ledger cleaned at terminal states
        for _, qid in qids.values():
            assert not control.stats(prefix=f"{qid}|")["shuffles"], qid

        # zero retry budget consumed: driver + every worker
        wt = fleet.fleet_counter_totals()
        assert counters.get("tasks_retried") - t_retried0 == 0
        assert wt.get("tasks_retried", 0) == 0
        assert fleet.admission.held_bytes() == 0
        print(f"rss_check: {len(NAMES)}/{len(NAMES)} queries "
              f"value-identical to solo runs; executor {victim} killed "
              f"-9 mid-flight, {len(requeued)} query(ies) requeued on "
              f"{survivor}, {skips} stage(s) RESUMED from the side-car "
              f"(sealed stage commit total flat at {commits_before}; "
              f"death detected {detect_s:.1f}s after kill)")
    finally:
        procs = [h.endpoint.proc for h in fleet._handles.values()
                 if getattr(h.endpoint, "proc", None) is not None]
        sc = fleet._sidecar.proc
        srv.stop()
        for p in procs:
            assert p.poll() is not None, "worker process leaked"
        assert sc.proc.poll() is not None, "side-car process leaked"
        reset_manager()
        faults.reset()
EOF

prom_assert_contains "$PROM_OUT" \
  "auron_fleet_worker_rss_stage_skips_total" \
  "auron_rss_sidecar_up 1" \
  "auron_fleet_deaths_total" \
  "auron_rss_cleanups_total"
prom_assert_ge "$PROM_OUT" auron_fleet_worker_rss_stage_skips_total 1

echo "rss_check.sh: ok"
