#!/usr/bin/env bash
# CI kernel-strategy gate (CPU-only, deterministic), the ISSUE 7 sibling
# of chaos_check.sh / mem_check.sh:
#
#   1. the kernel-equivalence property suite (radix pack-sort vs
#      np.lexsort, partitioned probe vs double searchsorted, one-hot
#      group reduce vs scatter, sort spill-merge invariant) must pass;
#   2. the strategy microbench (python -m auron_tpu.ops.strategy) must
#      show the `auto` pick beating or tying the legacy kernel on the
#      profiled shapes — a regression that makes `auto` the SLOWER
#      choice fails the gate instead of silently shipping.
#
# Usage: tools/kernel_check.sh [extra python -m auron_tpu.ops.strategy args]
#   AURON_KERNEL_CHECK_ROWS shrinks the microbench shape (CI boxes).
set -euo pipefail
cd "$(dirname "$0")/.."

ROWS=${AURON_KERNEL_CHECK_ROWS:-$((1 << 21))}

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m pytest tests/test_kernel_strategies.py -q -m 'not slow' \
    -p no:cacheprovider -p no:randomly

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m auron_tpu.ops.strategy --rows "$ROWS" "$@"

echo "kernel_check.sh: ok"
