#!/bin/bash
# Round-5 CPU queue, final form: refsql (in-flight) -> refplans resume
# loop -> refsql resume loop -> full pytest suite -> sf10 rung.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/cpu_queue_r5.log
echo "$(date -u +%H:%M:%S) queue4 start" >> "$LOG"
while pgrep -f "python -m auron_tpu.it.refsql" > /dev/null; do sleep 60; done
for i in 1 2 3 4 5 6; do
  nice -n 10 timeout 10800 python -m auron_tpu.it.refplans --sf 0.01 \
    --resume --json IT_REFPLANS.json > /tmp/refplans_full.out 2>&1
  rc=$?
  echo "$(date -u +%H:%M:%S) refplans pass $i rc=$rc" >> "$LOG"
  [ "$rc" = "0" ] && break
done
for i in 1 2 3; do
  nice -n 10 timeout 10800 python -m auron_tpu.it.refsql --sf 0.01 \
    --resume --json IT_REFSQL.json > /tmp/refsql_full.out 2>&1
  rc=$?
  echo "$(date -u +%H:%M:%S) refsql resume $i rc=$rc" >> "$LOG"
  [ "$rc" = "0" ] && break
done
echo "$(date -u +%H:%M:%S) full pytest" >> "$LOG"
nice -n 10 timeout 7200 python -m pytest tests/ -q \
  > /tmp/pytest_full.out 2>&1
echo "$(date -u +%H:%M:%S) pytest rc=$? ($(tail -1 /tmp/pytest_full.out | head -c 70))" >> "$LOG"
echo "$(date -u +%H:%M:%S) sf10" >> "$LOG"
nice -n 10 timeout 43200 python -m auron_tpu.it --sf 10 \
  --data-dir /tmp/auron_tpcds_sf10 --perf-factor 3 \
  --json IT_SF10.json > /tmp/it_sf10.out 2>&1
echo "$(date -u +%H:%M:%S) sf10 rc=$?" >> "$LOG"
echo "$(date -u +%H:%M:%S) queue4 done" >> "$LOG"
