#!/usr/bin/env bash
# CI overload-survival gate (CPU, no accelerator needed):
#   1. start a QueryServer over a TINY memory budget with watermark
#      preemption armed (auron.serving.preempt.*) and io+latency+mem
#      faults injected
#   2. POST six concurrent /submit requests (IT-corpus queries)
#   3. assert >= 1 preemption fired (kill-and-requeue), every query
#      still succeeds with results value-identical to its solo
#      fault-free run, every admission reservation drained, and the
#      auron_preemptions_total / auron_requeues_total Prometheus
#      counters are present on /metrics
#
# The same check runs inside the suite (tests/test_overload.py::
# test_tools_overload_check_script, marked slow), mirroring how
# serve_check.sh / chaos_check.sh are wired.
set -euo pipefail
cd "$(dirname "$0")/.."
source tools/prom_assert.sh
PROM_OUT="$(mktemp)"
export PROM_OUT
trap 'rm -f "$PROM_OUT"' EXIT

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import os
import threading
import urllib.request

from auron_tpu import faults
from auron_tpu.config import conf
from auron_tpu.frontend.session import AuronSession
from auron_tpu.it import datagen, queries
from auron_tpu.it.oracle import PyArrowEngine
from auron_tpu.memmgr.manager import reset_manager
from auron_tpu.serving import QueryServer, register_catalog

import tempfile

SF = 0.002
NAMES = ["q01", "q03", "q42", "q03", "q42", "q01"]

catalog = datagen.generate(
    tempfile.mkdtemp(prefix="auron-overload-check-"), sf=SF)
register_catalog(SF, catalog)


def canon(t):
    t = t.combine_chunks()
    return t.sort_by([(n, "ascending") for n in t.column_names]) \
        if t.num_rows and t.num_columns else t


serial = {"auron.spmd.singleDevice.enable": False}
baselines = {}
with conf.scoped(serial):
    for name in set(NAMES):
        s = AuronSession(foreign_engine=PyArrowEngine())
        baselines[name] = canon(s.execute(queries.build(name, catalog)).table)

# tiny pool + low watermark + bounded faults: six concurrent queries
# MUST cross the preemption watermark while >= 2 run
spec = ("shuffle.push:io:p=0.05,max=6,seed=7;"
        "shuffle.push:latency:p=0.1,seed=5,ms=3;"
        "op.execute:mem:bytes=65536,max=2,seed=9")
faults.reset(spec)
budget = 2 << 20
scope = {**serial,
         "auron.faults.spec": spec,
         "auron.task.retries": 2,
         "auron.retry.backoff.base.ms": 1.0,
         "auron.retry.backoff.max.ms": 10.0,
         "auron.memory.spill.min.trigger.bytes": 1024,
         "auron.serving.max.concurrent": 6,
         "auron.admission.default.forecast.bytes": 131072,
         "auron.serving.preempt.watermark": 0.5,
         "auron.serving.preempt.cooldown.seconds": 3.0,
         "auron.serving.preempt.max.per.query": 1,
         "auron.admission.aging.seconds": 5.0}


def post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.load(r)


def get(url):
    with urllib.request.urlopen(url, timeout=300) as r:
        return r.read()


with conf.scoped(scope):
    reset_manager(budget)
    srv = QueryServer().start()
    try:
        qids = {}
        errs = []

        def submit(i, name):
            try:
                doc = post(srv.url + "/submit",
                           {"corpus": name, "sf": SF,
                            "priority": 1 + (i % 3)})
                qids[i] = (name, doc["query_id"])
            except Exception as e:   # noqa: BLE001
                errs.append((name, repr(e)))

        threads = [threading.Thread(target=submit, args=(i, n))
                   for i, n in enumerate(NAMES)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        assert len(qids) == len(NAMES)

        for i, (name, qid) in sorted(qids.items()):
            assert srv.scheduler.wait(qid, timeout=600), \
                f"{name} did not finish"
            st = json.loads(get(srv.url + f"/status/{qid}"))
            assert st["state"] == "succeeded", (name, st)
            res = json.loads(get(srv.url + f"/result/{qid}"))
            assert not res["truncated"]
            import pyarrow as pa
            got = canon(pa.Table.from_pylist(
                res["rows"], schema=baselines[name].schema))
            assert got.equals(baselines[name]), \
                f"{name} served result diverged from its solo run"

        stats = json.loads(get(srv.url + "/scheduler"))
        preemptions = stats["preemptions"]
        assert preemptions >= 1, \
            f"tight budget never forced a preemption: {stats}"
        assert srv.scheduler.admission.held_bytes() == 0
        # Prometheus assertions: shared tools/prom_assert.sh helper
        with open(os.environ["PROM_OUT"], "w") as f:
            f.write(get(srv.url + "/metrics").decode())
        print(f"overload_check: {len(NAMES)}/{len(NAMES)} queries "
              f"value-identical to solo runs through {preemptions} "
              f"preemption(s)")
    finally:
        srv.stop()
        reset_manager()
        faults.reset()
EOF

prom_assert_contains "$PROM_OUT" \
  "auron_preemptions_total" \
  "auron_requeues_total"
prom_assert_ge "$PROM_OUT" auron_preemptions_total 1

echo "overload_check.sh: ok"
