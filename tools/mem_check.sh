#!/usr/bin/env bash
# CI memory-observability gate (CPU, no accelerator needed):
#   1. run a tier-1 TPC-DS query traced under a tiny memory budget
#      (serial path so consumers register) and dump the Chrome trace
#   2. validate that mem.pressure / mem.spill event families appear
#      with consumer attribution
#   3. start the profiling server, force an attributed spill, and
#      validate the /memory payload + the Prometheus memory gauges
#   4. check the committed spill-sort EXPLAIN ANALYZE golden via the
#      pytest hook
#
# The same checks run inside the suite (tests/test_memory_observability
# .py::test_tools_mem_check_script, marked slow), mirroring how
# lint_plans.sh / chaos_check.sh / trace_check.sh are wired.
set -euo pipefail
cd "$(dirname "$0")/.."

out_dir=$(mktemp -d /tmp/auron_mem_check.XXXXXX)
trap 'rm -rf "$out_dir"' EXIT

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m auron_tpu.trace run \
    --query q01 --sf 0.002 --serial \
    --budget 20000 --spill-trigger 1024 \
    -o "$out_dir/q01.mem.trace.json"

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - "$out_dir/q01.mem.trace.json" <<'EOF'
import json
import sys

doc = json.load(open(sys.argv[1]))
events = [e for e in doc["traceEvents"] if isinstance(e, dict)]
pressure = [e for e in events if e.get("name") == "mem.pressure"]
spills = [e for e in events if e.get("name") == "mem.spill"]
assert pressure, "no mem.pressure events in tiny-budget traced run"
assert spills, "no mem.spill events in tiny-budget traced run"
fracs = [e["args"]["fraction"] for e in pressure]
assert fracs == sorted(fracs), f"watermark events not monotone: {fracs}"
for e in spills:
    args = e.get("args", {})
    assert args.get("consumer") and args.get("path") in (
        "arbitration", "self", "fallback"), f"unattributed spill: {e}"
print(f"mem_check: {len(pressure)} pressure events "
      f"(fractions {fracs}), {len(spills)} attributed spills")
EOF

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import urllib.request

from auron_tpu.config import conf
from auron_tpu.memmgr.manager import MemConsumer, reset_manager
from auron_tpu.runtime import profiling


class C(MemConsumer):
    def spill(self):
        freed = self.mem_used
        self.update_mem_used(0)
        return freed


with conf.scoped({"auron.memory.spill.min.trigger.bytes": 1}):
    mgr = reset_manager(1000)
    c = mgr.register_consumer(C("SortExec"))
    c.update_mem_used(1500)
    mgr.unregister_consumer(c)

srv = profiling.ProfilingServer().start()
try:
    with urllib.request.urlopen(srv.url + "/memory", timeout=30) as r:
        doc = json.load(r)
    assert {"pool", "consumers", "consumer_totals", "spills"} <= set(doc)
    assert doc["pool"]["num_spills"] == 1
    assert doc["pool"]["peak_used"] == 1500
    assert [c["fraction"] for c in doc["pool"]["watermarks_crossed"]] \
        == doc["pool"]["watermark_fractions"]
    (rec,) = doc["spills"]["records"]
    assert rec["consumer"] == "SortExec" and rec["freed_bytes"] == 1500
    with urllib.request.urlopen(srv.url + "/metrics", timeout=30) as r:
        text = r.read().decode()
    for needle in ("auron_mem_peak_bytes 1500",
                   "auron_mem_spill_bytes_total 1500",
                   'auron_mem_consumer_peak_bytes{consumer="SortExec"}'):
        assert needle in text, f"missing {needle!r} in /metrics"
    print("mem_check: /memory payload + Prometheus gauges ok")
finally:
    srv.stop()
    reset_manager()
EOF

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m pytest -q \
    -p no:cacheprovider \
    tests/test_memory_observability.py::test_explain_analyze_memory_columns_and_golden

echo "mem_check.sh: ok"
