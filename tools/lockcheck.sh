#!/usr/bin/env bash
# CI concurrency-correctness gate (CPU-only, fast):
#   1. the STATIC pass — raw-lock registry bypass lint, static
#      lock-order graph vs the committed golden
#      (tests/golden_plans/lock_order.txt), lexically-blocking calls
#      under locks — must report 0 unwaived errors;
#   2. the golden graph must be CYCLE-FREE and in sync (drift fails
#      with a regen hint, exactly like the plan goldens);
#   3. the DYNAMIC suite — cycle/re-entrancy/waiver units, the
#      static/dynamic cross-check and the shutdown-race hammer — runs
#      under `auron.lockcheck.enable` (forced on by tests/conftest.py).
#
# Regen after intentional lock-graph changes:
#   python -m auron_tpu.analysis --concurrency --regen-golden
#
# Usage: tools/lockcheck.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m auron_tpu.analysis --concurrency

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m pytest tests/test_lockcheck.py -q -m 'not slow' \
    -p no:cacheprovider "$@"

echo "lockcheck.sh: ok"
