#!/usr/bin/env bash
# CI wire-protocol contract gate (CPU-only, fast):
#   1. the STATIC pass — server dispatch ladders vs the wirecheck
#      command registry (both directions), client request literals
#      inside the contract, transports on named fault points + the ONE
#      shared retry policy, idempotency-vs-replay audit (the mechanized
#      MCOMMIT/push_id check), raw struct framing lint, and the
#      committed wire manifest (tests/golden_plans/wire_manifest.txt)
#      — must report 0 unwaived errors;
#   2. the CONFORMANCE suite — registry/schema/version-handshake units,
#      server in-band answers, static-pass self-tests — runs under
#      `auron.wirecheck.enable` (forced on by tests/conftest.py);
#   3. the FUZZ fast subset — the deterministic malformed-frame matrix
#      against all three servers (structured error or clean close,
#      no pinned handler threads);
#   4. the COST-CONTRACT A/B — framed push/fetch roundtrips with
#      wirecheck off vs on must move bit-identical bytes, with the
#      checked path inside the noise gate of the unchecked one.
#
# Regen after intentional protocol changes:
#   python -m auron_tpu.analysis --protocol --regen-golden
#
# Usage: tools/wirecheck.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m auron_tpu.analysis --protocol

# conformance + fuzz fast subsets, minus THIS script's own pytest
# wrapper (the randomized 200-frame sweep stays behind -m slow)
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m pytest tests/test_wirecheck.py tests/test_wire_fuzz.py \
    -q -m 'not slow' \
    --deselect tests/test_wirecheck.py::test_tools_wirecheck_script \
    -p no:cacheprovider "$@"

# cost-contract A/B: interleaved best-of-3 framed roundtrip batches,
# wirecheck OFF (the shipped default) vs ON (the suite's mode).  Bytes
# must be identical; the ON path must sit inside the OFF path's noise
# (gated at 1.3x like tools/aqe_check.sh — CI wall clock jitters far
# above the ~0% steady-state delta, which is printed for trend eyes).
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import time

from auron_tpu.runtime import wirecheck
from auron_tpu.shuffle_rss import ShuffleServer
from auron_tpu.shuffle_rss.server import recv_msg, send_msg
import socket

payload = bytes(range(256)) * 256          # 64 KiB


def batch(addr, shuffle, n=60):
    s = socket.create_connection(addr, timeout=10)
    try:
        for i in range(n):
            send_msg(s, {"cmd": "push", "shuffle": shuffle,
                         "partition": i % 4, "len": len(payload)},
                     payload)
            resp, _ = recv_msg(s)
            assert resp["ok"] is True, resp
        out = b""
        for p in range(4):
            send_msg(s, {"cmd": "fetch", "shuffle": shuffle,
                         "partition": p})
            resp, data = recv_msg(s)
            assert resp["ok"] is True, resp
            out += data
        return out
    finally:
        s.close()


with ShuffleServer() as srv:
    addr = srv.address
    wirecheck.configure(enabled=True, raise_on_violation=True)
    on_bytes = batch(addr, "warm_on")
    wirecheck.configure(enabled=False)
    off_bytes = batch(addr, "warm_off")
    assert on_bytes == off_bytes, "checked frame path is not bit-identical"

    t_offs, t_ons = [], []
    for i in range(3):
        wirecheck.configure(enabled=False)
        t0 = time.perf_counter()
        batch(addr, f"off{i}")
        t_offs.append(time.perf_counter() - t0)
        wirecheck.configure(enabled=True)
        t0 = time.perf_counter()
        batch(addr, f"on{i}")
        t_ons.append(time.perf_counter() - t0)
    off_s, on_s = min(t_offs), min(t_ons)
    delta = (on_s - off_s) / max(off_s, 1e-9) * 100.0
    print(f"wirecheck A/B (interleaved, best-of-3): off={off_s * 1e3:.1f}ms "
          f"on={on_s * 1e3:.1f}ms delta={delta:+.1f}%")
    assert on_s <= off_s * 1.3, \
        f"wirecheck ON regressed the wire path: {on_s:.4f}s vs {off_s:.4f}s"
print("WIRECHECK_AB_OK")
EOF

echo "wirecheck.sh: ok"
