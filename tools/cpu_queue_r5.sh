#!/bin/bash
# Round-5 CPU artifact queue, take 2 (single-core box; strictly serial,
# niced so revived-tunnel chip work preempts).  Runs everything itself:
#  1. wait for any in-flight refplans process to exit
#  2. resume the refplans sweep into IT_REFPLANS.json (crash-safe)
#  3. IT_REFSQL.json  - the reference's own SQL suite
#  4. IT_SF10.json    - full sf=10 rung: zero exclusions, warm
#     best-of-2, perf gate armed at 3x (the sf=1 policy)
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/cpu_queue_r5.log
echo "$(date -u +%H:%M:%S) queue2 start" >> "$LOG"
while pgrep -f "python -m auron_tpu.it.refplans" > /dev/null; do
  sleep 60
done
echo "$(date -u +%H:%M:%S) [2] refplans resume" >> "$LOG"
nice -n 10 timeout 10800 python -m auron_tpu.it.refplans --sf 0.01 \
  --resume --json IT_REFPLANS.json > /tmp/refplans_full.out 2>&1
echo "$(date -u +%H:%M:%S) [2] rc=$?" >> "$LOG"
echo "$(date -u +%H:%M:%S) [3] refsql" >> "$LOG"
nice -n 10 timeout 10800 python -m auron_tpu.it.refsql --sf 0.01 \
  --json IT_REFSQL.json > /tmp/refsql_full.out 2>&1
echo "$(date -u +%H:%M:%S) [3] rc=$?" >> "$LOG"
echo "$(date -u +%H:%M:%S) [4] sf10" >> "$LOG"
nice -n 10 timeout 43200 python -m auron_tpu.it --sf 10 \
  --data-dir /tmp/auron_tpcds_sf10 --perf-factor 3 \
  --json IT_SF10.json > /tmp/it_sf10.out 2>&1
echo "$(date -u +%H:%M:%S) [4] rc=$?" >> "$LOG"
echo "$(date -u +%H:%M:%S) queue2 done" >> "$LOG"
