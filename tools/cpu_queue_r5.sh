#!/bin/bash
# Round-5 CPU artifact queue (single-core box: strictly serialized,
# niced so any revived-tunnel chip work preempts).
#  1. wait for the in-flight refplans sweep (IT_REFPLANS.json)
#  2. IT_REFSQL.json  - the reference's own SQL suite, warm recorded
#  3. IT_SF10.json    - full sf=10 ladder rung: zero exclusions, warm
#     best-of-2, perf gate armed at 3x (the sf=1 policy)
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/cpu_queue_r5.log
echo "$(date -u +%H:%M:%S) queue start" >> "$LOG"

while pgrep -f "auron_tpu.it.refplans --sf 0.01 --json IT_REFPLANS" \
    > /dev/null; do
  sleep 60
done
echo "$(date -u +%H:%M:%S) refplans done; refsql" >> "$LOG"
nice -n 10 timeout 10800 python -m auron_tpu.it.refsql --sf 0.01 \
  --json IT_REFSQL.json > /tmp/refsql_full.out 2>&1
echo "$(date -u +%H:%M:%S) refsql rc=$?; sf10" >> "$LOG"
nice -n 10 timeout 43200 python -m auron_tpu.it --sf 10 \
  --data-dir /tmp/auron_tpcds_sf10 --perf-factor 3 \
  --json IT_SF10.json > /tmp/it_sf10.out 2>&1
echo "$(date -u +%H:%M:%S) sf10 rc=$?" >> "$LOG"
echo "$(date -u +%H:%M:%S) queue done" >> "$LOG"
