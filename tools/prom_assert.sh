#!/usr/bin/env bash
# Shared Prometheus-text assertions for the tools/*_check.sh CI gates.
#
# Each gate's Python driver dumps the final /metrics scrape to a file
# (usually "$PROM_OUT"), optionally plus a needle file of
# run-dependent lines (victim executor labels etc.); the gate then
# sources this helper and asserts on the dump — ONE implementation of
# the grep-based metric checks instead of four copies.
#
#   prom_assert_contains FILE NEEDLE...   every NEEDLE is a literal
#                                         substring of FILE
#   prom_assert_needles FILE NEEDLE_FILE  every non-empty line of
#                                         NEEDLE_FILE appears in FILE
#   prom_assert_ge FILE METRIC MIN        the first sample line
#                                         `METRIC <value>` has
#                                         value >= MIN

prom_assert_contains() {
  local file=$1 needle
  shift
  for needle in "$@"; do
    if ! grep -qF -- "$needle" "$file"; then
      echo "prom_assert: missing '$needle' in $file" >&2
      return 1
    fi
  done
}

prom_assert_needles() {
  local file=$1 needles=$2 line
  while IFS= read -r line; do
    [ -n "$line" ] || continue
    if ! grep -qF -- "$line" "$file"; then
      echo "prom_assert: missing '$line' in $file" >&2
      return 1
    fi
  done < "$needles"
}

prom_assert_ge() {
  local file=$1 metric=$2 min=$3 value
  value=$(awk -v m="$metric" '$1 == m { print $2; exit }' "$file")
  if [ -z "$value" ]; then
    echo "prom_assert: no sample for $metric in $file" >&2
    return 1
  fi
  if ! awk -v v="$value" -v m="$min" 'BEGIN { exit !(v + 0 >= m + 0) }'
  then
    echo "prom_assert: $metric = $value < $min" >&2
    return 1
  fi
}
