#!/usr/bin/env bash
# CI compilation-hygiene gate (CPU-only, fast):
#   1. the STATIC pass — raw-jit registry bypass lint, host
#      materialization inside jitted bodies (bounded call closure),
#      traced-parameter casts, mutable-capture, strategy-fingerprint
#      cache keys, config-knob lint vs the registry + CONFIG.md — must
#      report 0 unwaived errors;
#   2. the HYGIENE suite — trace-probe/storm/waiver/transfer units,
#      the committed compile manifest
#      (tests/golden_plans/compile_manifest.txt) vs a fresh canonical
#      q01+q03 run, and the second-run-compiles-zero regression —
#      runs under `auron.jitcheck.enable` (forced on by
#      tests/conftest.py).
#
# Regen after intentional compile-path changes:
#   python -m auron_tpu.analysis --compilation --regen-golden
#
# Usage: tools/jitcheck.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m auron_tpu.analysis --compilation

# the whole hygiene suite minus THIS script's own pytest wrapper (the
# manifest + second-run-compiles-zero goldens moved behind -m slow in
# the PR 10 tier-1 re-split, but this nightly gate still runs them)
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m pytest tests/test_jitcheck.py -q \
    --deselect tests/test_jitcheck.py::test_tools_jitcheck_script \
    -p no:cacheprovider "$@"

echo "jitcheck.sh: ok"
