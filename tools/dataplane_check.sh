#!/usr/bin/env bash
# CI data-plane gate (CPU, no accelerator needed) — PR 14's promotion
# of tools/rss_check.sh to the zero-copy exchange plane:
#   1. spawn a 2-executor fleet WITH the durable-shuffle side-car,
#      wire format v2 + pid fusion + push/fetch PIPELINING all ON
#      (auron.shuffle.pipeline.depth=4 — the defaults, pinned so the
#      gate cannot silently hollow out)
#   2. POST six concurrent /submit requests (IT-corpus queries)
#   3. kill -9 the busiest executor MID-STREAM (>= 1 of its queries'
#      stages committed+sealed on the side-car, pushes in flight)
#   4. assert the requeued queries RESUME (stage-skip counters, flat
#      side-car commit totals), EVERY query succeeds value-identical
#      to its solo fault-free run, zero task-retry budget consumed,
#      the STREAMED Arrow result (?format=arrow, chunked IPC) decodes
#      byte-for-byte to the same rows the JSON representation serves,
#      and the new exchange byte counters are visible on /metrics
#      (auron_fleet_worker_shuffle_bytes_pushed/fetched_total — the
#      workers push, so the driver sees them via heartbeat counter
#      aggregation).
#
# The same check runs inside the suite (tests/test_dataplane.py::
# test_tools_dataplane_check_script, marked slow), mirroring how
# rss_check.sh / fleet_check.sh are wired.
set -euo pipefail
cd "$(dirname "$0")/.."
source tools/prom_assert.sh
PROM_OUT="$(mktemp)"
export PROM_OUT
trap 'rm -f "$PROM_OUT"' EXIT

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import os
import signal
import tempfile
import threading
import time
import urllib.request

import pyarrow as pa

from auron_tpu import faults
from auron_tpu.config import conf
from auron_tpu.frontend.session import AuronSession
from auron_tpu.it import datagen, queries
from auron_tpu.it.oracle import PyArrowEngine
from auron_tpu.memmgr.manager import reset_manager
from auron_tpu.runtime import counters
from auron_tpu.serving import FleetManager, QueryServer, register_catalog

SF = 0.002
NAMES = ["q01", "q42", "q01", "q42", "q01", "q42"]

catalog = datagen.generate(
    tempfile.mkdtemp(prefix="auron-dataplane-check-"), sf=SF)
register_catalog(SF, catalog)


def canon(t):
    t = t.combine_chunks()
    return t.sort_by([(n, "ascending") for n in t.column_names]) \
        if t.num_rows and t.num_columns else t


serial = {"auron.spmd.singleDevice.enable": False}
baselines = {}
with conf.scoped(serial):
    for name in set(NAMES):
        s = AuronSession(foreign_engine=PyArrowEngine())
        baselines[name] = canon(s.execute(queries.build(name, catalog)).table)

# the data plane pinned ON (they are the defaults — pinning keeps the
# gate honest if a default ever flips): v2 wire format, pid fusion,
# pipelined push/fetch.  Worker chaos latency-only: the zero-retries
# assertion covers every worker, and pipelined pushes must overlap the
# injected delays without reordering anything.
worker_conf = {**serial,
               "auron.serde.format.version": 2,
               "auron.shuffle.pid.fuse.enable": True,
               "auron.shuffle.pipeline.depth": 4,
               "auron.faults.spec":
                   "op.execute:latency:p=0.5,ms=150,max=60,seed=11;"
                   "rss.push:latency:p=0.2,ms=3,max=40,seed=5",
               "auron.task.retries": 2,
               "auron.retry.backoff.base.ms": 1.0,
               "auron.retry.backoff.max.ms": 10.0,
               "auron.serving.preempt.watermark": 0.0,
               "auron.serving.max.concurrent": 4}
hb = 1.5
scope = {"auron.retry.backoff.base.ms": 1.0,
         "auron.retry.backoff.max.ms": 10.0,
         "auron.net.timeout.seconds": 10.0,
         "auron.fleet.heartbeat.seconds": hb,
         "auron.fleet.death.probes": 3,
         "auron.admission.default.forecast.bytes": 1 << 20,
         "auron.serving.max.concurrent": 4}


def post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.load(r)


def get(url):
    with urllib.request.urlopen(url, timeout=300) as r:
        return r.read()


t_retried0 = counters.get("tasks_retried")
with conf.scoped(scope):
    reset_manager(1 << 30)
    fleet = FleetManager.spawn(2, conf_map=worker_conf,
                               budget_bytes=1 << 29, rss_sidecar=True)
    control = fleet._sidecar.control
    srv = QueryServer(scheduler=fleet).start()
    try:
        qids = {}
        errs = []

        def submit(i, name):
            try:
                doc = post(srv.url + "/submit",
                           {"corpus": name, "sf": SF,
                            "priority": 1 + (i % 3)})
                qids[i] = (name, doc["query_id"])
            except Exception as e:   # noqa: BLE001
                errs.append((name, repr(e)))

        threads = [threading.Thread(target=submit, args=(i, n))
                   for i, n in enumerate(NAMES)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        assert len(qids) == len(NAMES)

        # kill -9 the busiest executor once one of its in-flight
        # queries has a committed+sealed stage on the side-car — the
        # pipelined pushes of its OTHER stages are mid-stream
        victim = survivor = None
        resumed_qid = sealed_sid = None
        commits_before = None
        deadline = time.time() + 180
        while time.time() < deadline:
            snap = fleet.fleet_snapshot()
            busy = sorted(snap.items(), key=lambda kv: -kv[1]["inflight"])
            eid, doc = busy[0]
            if doc["inflight"] >= 2 and doc["load"].get("running", 0) >= 1:
                inflight = [q for _, q in qids.values()
                            if fleet.get(q).executor_id == eid
                            and not fleet.get(q).done.is_set()]
                stats = control.stats()
                for q in inflight:
                    for sid, sdoc in stats["shuffles"].items():
                        if sid.startswith(f"{q}|") and \
                                sdoc["sealed"] is not None and \
                                sdoc["maps"] >= sdoc["sealed"]:
                            victim, survivor = eid, busy[1][0]
                            resumed_qid, sealed_sid = q, sid
                            commits_before = \
                                stats["totals"][sid]["commits"]
                            break
                    if victim:
                        break
            if victim:
                break
            time.sleep(0.1)
        assert victim is not None, (fleet.fleet_snapshot(),
                                    control.stats())
        victim_qids = [q for _, q in qids.values()
                       if fleet.get(q).executor_id == victim
                       and not fleet.get(q).done.is_set()]
        os.kill(fleet._handles[victim].endpoint.pid, signal.SIGKILL)
        t_kill = time.monotonic()

        detect_s = None
        while time.monotonic() - t_kill < 30:
            if fleet.fleet_snapshot()[victim]["state"] == "dead":
                detect_s = time.monotonic() - t_kill
                break
            time.sleep(0.05)
        assert detect_s is not None, "death never declared"

        for i, (name, qid) in sorted(qids.items()):
            assert fleet.wait(qid, timeout=600), \
                f"{name} did not finish: {fleet.status(qid)}"
            st = json.loads(get(srv.url + f"/status/{qid}"))
            assert st["state"] == "succeeded", (name, st)
            res = json.loads(get(srv.url + f"/result/{qid}"))
            assert not res["truncated"]
            got = canon(pa.Table.from_pylist(
                res["rows"], schema=baselines[name].schema))
            assert got.equals(baselines[name]), \
                f"{name} served result diverged from its solo run"
            # the STREAMED Arrow result decodes to the same rows the
            # JSON representation serves (chunked IPC, no row cap)
            raw = get(srv.url + f"/result/{qid}?format=arrow")
            streamed = pa.ipc.open_stream(pa.py_buffer(raw)).read_all()
            assert streamed.num_rows == res["num_rows"]
            assert streamed.to_pylist() == res["rows"], \
                f"{name} streamed-Arrow rows != JSON rows"

        requeued = [q for q in victim_qids
                    if fleet.status(q)["requeues"] >= 1]
        assert requeued, "the killed executor's queries never requeued"

        prom = get(srv.url + "/metrics").decode()
        with open(os.environ["PROM_OUT"], "w") as f:
            f.write(prom)
        post_stats = control.stats(prefix=f"{resumed_qid}|")
        assert post_stats["totals"][sealed_sid]["commits"] == \
            commits_before, "map tasks re-ran for the sealed stage"

        # side-car ledger cleaned at terminal states
        for _, qid in qids.values():
            assert not control.stats(prefix=f"{qid}|")["shuffles"], qid

        # zero retry budget consumed: driver + every worker
        wt = fleet.fleet_counter_totals()
        assert counters.get("tasks_retried") - t_retried0 == 0
        assert wt.get("tasks_retried", 0) == 0
        assert wt.get("shuffle_bytes_pushed", 0) > 0, \
            "workers reported no pushed exchange bytes"
        assert wt.get("shuffle_bytes_fetched", 0) > 0, \
            "workers reported no fetched exchange bytes"
        assert fleet.admission.held_bytes() == 0
        print(f"dataplane_check: {len(NAMES)}/{len(NAMES)} queries "
              f"value-identical to solo runs with v2+pidfuse+pipeline "
              f"on; executor {victim} killed -9 mid-stream, "
              f"{len(requeued)} query(ies) requeued and RESUMED "
              f"(sealed stage commit total flat at {commits_before}; "
              f"death detected {detect_s:.1f}s after kill); streamed "
              f"Arrow results row-equal to JSON; workers pushed "
              f"{wt.get('shuffle_bytes_pushed', 0)}B / fetched "
              f"{wt.get('shuffle_bytes_fetched', 0)}B")
    finally:
        procs = [h.endpoint.proc for h in fleet._handles.values()
                 if getattr(h.endpoint, "proc", None) is not None]
        sc = fleet._sidecar.proc
        srv.stop()
        for p in procs:
            assert p.poll() is not None, "worker process leaked"
        assert sc.proc.poll() is not None, "side-car process leaked"
        reset_manager()
        faults.reset()
EOF

prom_assert_contains "$PROM_OUT" \
  "auron_fleet_worker_shuffle_bytes_pushed_total" \
  "auron_fleet_worker_shuffle_bytes_fetched_total" \
  "auron_fleet_worker_rss_stage_skips_total" \
  "auron_shuffle_bytes_pushed_total" \
  "auron_rss_sidecar_up 1"
prom_assert_ge "$PROM_OUT" auron_fleet_worker_shuffle_bytes_pushed_total 1
prom_assert_ge "$PROM_OUT" auron_fleet_worker_shuffle_bytes_fetched_total 1
prom_assert_ge "$PROM_OUT" auron_fleet_worker_rss_stage_skips_total 1

echo "dataplane_check.sh: ok"
