#!/usr/bin/env bash
# CI perf-regression gate (CPU-only), the ISSUE 19 member of the
# tools/*_check.sh family:
#
#   1. the perfscope unit suite must pass (estimator units, reservoir
#      bounds, the /rooflines + Prometheus surfaces, the calibration
#      round-trip, the disarmed zero-ledger claim);
#   2. the OFF-default claim must hold: an interleaved warm q01 serial
#      A/B with perfscope disarmed vs armed stays bit-identical and the
#      armed overhead stays under AURON_PERF_MAX_OVERHEAD (default 2%);
#   3. achieved per-site bandwidth on a warm q01 run must hold the
#      committed floors in tests/golden_plans/perf_baseline.json within
#      the baseline's tolerance band — a kernel that silently lost an
#      integer factor of bandwidth fails the gate instead of shipping.
#
# Usage: tools/perf_check.sh [--regen-golden]
#   --regen-golden rewrites the floor baseline from this machine's run.
#   AURON_PERF_CHECK_SF shrinks the corpus scale factor (CI boxes).
set -euo pipefail
cd "$(dirname "$0")/.."

SF=${AURON_PERF_CHECK_SF:-0.002}
MAX_OVERHEAD=${AURON_PERF_MAX_OVERHEAD:-0.02}
BASELINE=tests/golden_plans/perf_baseline.json

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m pytest tests/test_perfscope.py -q -m 'not slow' \
    -p no:cacheprovider -p no:randomly

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m auron_tpu.perfscope ab --query q01 --sf "$SF" --serial \
    --reps 5 --max-overhead "$MAX_OVERHEAD"

if [[ "${1:-}" == "--regen-golden" ]]; then
    JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
        python -m auron_tpu.perfscope check --query q01 --sf "$SF" \
        --serial --baseline "$BASELINE" --regen-golden
else
    JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
        python -m auron_tpu.perfscope check --query q01 --sf "$SF" \
        --serial --baseline "$BASELINE"
fi

echo "perf_check.sh: ok"
