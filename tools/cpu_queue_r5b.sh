#!/bin/bash
# Round-5 CPU artifact queue, take 3: wait for the in-flight refsql,
# then loop refplans --resume until the sweep is complete (each pass a
# fresh process; vm.max_map_count raised + periodic jax.clear_caches
# bound the JIT mmap growth), then the full sf=10 rung.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/cpu_queue_r5.log
echo "$(date -u +%H:%M:%S) queue3 start" >> "$LOG"
while pgrep -f "python -m auron_tpu.it.refsql" > /dev/null; do
  sleep 60
done
echo "$(date -u +%H:%M:%S) [3b] refsql finished" >> "$LOG"
for i in 1 2 3 4 5 6; do
  nice -n 10 timeout 10800 python -m auron_tpu.it.refplans --sf 0.01 \
    --resume --json IT_REFPLANS.json > /tmp/refplans_full.out 2>&1
  rc=$?
  n=$(python3 -c "import json;d=json.load(open('IT_REFPLANS.json'));print(d['queries'],d['ok'])" 2>/dev/null)
  echo "$(date -u +%H:%M:%S) [3b] refplans pass $i rc=$rc -> $n" >> "$LOG"
  if [ "$rc" = "0" ]; then break; fi
done
echo "$(date -u +%H:%M:%S) [4] sf10" >> "$LOG"
nice -n 10 timeout 43200 python -m auron_tpu.it --sf 10 \
  --data-dir /tmp/auron_tpcds_sf10 --perf-factor 3 \
  --json IT_SF10.json > /tmp/it_sf10.out 2>&1
echo "$(date -u +%H:%M:%S) [4] rc=$?" >> "$LOG"
echo "$(date -u +%H:%M:%S) queue3 done" >> "$LOG"
