#!/usr/bin/env bash
# CI chaos gate (CPU-only, deterministic): run a small TPC-DS sweep
# twice — fault-free and under a seeded fault-injection spec
# (auron.faults.spec) — and require
#   1. bit-identical results,
#   2. bounded attempts (<= 3x the fault-free task count: no retry
#      storms),
#   3. at least one fault actually injected (a renamed fault point must
#      not hollow the gate out silently).
#
# The sweep is exactly reproducible: per-rule seeded Bernoulli streams
# plus task parallelism pinned to 1 (auron_tpu/faults, it/stability.py).
# Heavier sweeps (the full tier-1 subset at p=0.05) run under
# `pytest -m slow` (tests/test_chaos.py) — this script is the fast
# always-on gate, wired like tools/lint_plans.sh.
#
# Usage: tools/chaos_check.sh [extra python -m auron_tpu.it.stability args]
#   e.g. tools/chaos_check.sh --queries q03,q42 --json /tmp/chaos.json
set -euo pipefail
cd "$(dirname "$0")/.."

SPEC=${AURON_CHAOS_SPEC:-"shuffle.push:io:p=0.2,seed=7;shuffle.fetch:io:p=0.2,seed=11;spill.write:io:p=0.2,seed=3"}

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m auron_tpu.it.stability --chaos "$SPEC" "$@"

echo "chaos_check.sh: ok"
