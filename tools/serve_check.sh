#!/usr/bin/env bash
# CI serving gate (CPU, no accelerator needed):
#   1. start a QueryServer (the profiling HTTP server promoted to a
#      submission endpoint) over a small memory budget with admission
#      knobs tight enough that four concurrent submissions cannot all
#      be admitted at once
#   2. POST four concurrent /submit requests (IT-corpus queries), wait
#      via /status, fetch /result
#   3. assert every query succeeds with results value-identical to its
#      solo fault-free run, and that the admission gate visibly QUEUED
#      at least one submission (/scheduler events + the Prometheus
#      auron_admission_queued_total counter)
#
# The same check runs inside the suite (tests/test_serving.py::
# test_tools_serve_check_script, marked slow), mirroring how
# chaos_check.sh / mem_check.sh are wired.
set -euo pipefail
cd "$(dirname "$0")/.."
source tools/prom_assert.sh
PROM_OUT="$(mktemp)"
export PROM_OUT
trap 'rm -f "$PROM_OUT"' EXIT

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import os
import threading
import urllib.request

from auron_tpu.config import conf
from auron_tpu.frontend.session import AuronSession
from auron_tpu.it import datagen, queries
from auron_tpu.it.oracle import PyArrowEngine
from auron_tpu.memmgr.manager import reset_manager
from auron_tpu.serving import QueryServer, register_catalog

import tempfile

SF = 0.002
NAMES = ["q01", "q03", "q42", "q55"]

catalog = datagen.generate(
    tempfile.mkdtemp(prefix="auron-serve-check-"), sf=SF)
register_catalog(SF, catalog)

# solo fault-free baselines (value-identical gate)
def canon(t):
    t = t.combine_chunks()
    return t.sort_by([(n, "ascending") for n in t.column_names]) \
        if t.num_rows and t.num_columns else t

serial = {"auron.spmd.singleDevice.enable": False}
baselines = {}
with conf.scoped(serial):
    for name in NAMES:
        s = AuronSession(foreign_engine=PyArrowEngine())
        baselines[name] = canon(s.execute(queries.build(name, catalog)).table)

# small budget + tight admission: forecasts of 45% of the budget against
# a 0.8 cap mean at most two queries hold reservations at once, so four
# concurrent submissions MUST produce >= 1 admission-queue event
budget = 32 << 20
scope = {**serial,
         "auron.serving.max.concurrent": 4,
         "auron.admission.default.forecast.bytes": int(budget * 0.45),
         "auron.admission.memory.fraction": 0.8,
         "auron.memory.spill.min.trigger.bytes": 64 << 10,
         # this gate is about ADMISSION; preemption has its own gate
         # (tools/overload_check.sh)
         "auron.serving.preempt.watermark": 0.0}

def post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.load(r)

def get(url):
    with urllib.request.urlopen(url, timeout=300) as r:
        return r.read()

with conf.scoped(scope):
    reset_manager(budget)
    srv = QueryServer().start()
    try:
        qids = {}
        errs = []

        def submit(name):
            try:
                doc = post(srv.url + "/submit",
                           {"corpus": name, "sf": SF})
                qids[name] = doc["query_id"]
            except Exception as e:   # noqa: BLE001
                errs.append((name, repr(e)))

        threads = [threading.Thread(target=submit, args=(n,))
                   for n in NAMES]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        assert len(qids) == 4

        for name, qid in qids.items():
            assert srv.scheduler.wait(qid, timeout=600), \
                f"{name} did not finish"
            st = json.loads(get(srv.url + f"/status/{qid}"))
            assert st["state"] == "succeeded", (name, st)
            res = json.loads(get(srv.url + f"/result/{qid}"))
            assert not res["truncated"]
            import pyarrow as pa
            got = canon(pa.Table.from_pylist(
                res["rows"], schema=baselines[name].schema))
            assert got.equals(baselines[name]), \
                f"{name} served result diverged from its solo run"

        stats = json.loads(get(srv.url + "/scheduler"))
        queued = stats["admission"]["events"]["queued"]
        assert queued >= 1, f"admission gate never queued: {stats}"
        # the Prometheus assertions live in tools/prom_assert.sh —
        # dump the final scrape for the shared bash helper
        with open(os.environ["PROM_OUT"], "w") as f:
            f.write(get(srv.url + "/metrics").decode())
        print(f"serve_check: 4/4 queries value-identical to solo runs, "
              f"{queued} admission-queue event(s)")
    finally:
        srv.stop()
        reset_manager()
EOF

prom_assert_contains "$PROM_OUT" \
  "auron_admission_queued_total" \
  "auron_admission_admitted_total" \
  "auron_queries_submitted_total 4"
prom_assert_ge "$PROM_OUT" auron_admission_queued_total 1

echo "serve_check.sh: ok"
