#!/usr/bin/env bash
# CI durable-statistics gate (CPU, no accelerator needed) — the PR 19
# member of the tools/*_check.sh family, and the cross-restart proof:
#
#   1. the statshist unit suite must pass (fold/EMA/regression units,
#      torn-tail tolerance, concurrent appenders, compaction bounds,
#      the /signatures + /regressions + baseline-diff surfaces);
#   2. process A runs q01 three times with `auron.stats.store.dir`
#      armed, then is killed -9 (appends are per-terminal: a crash
#      must lose nothing already folded);
#   3. a FRESH process B over the same store must — BEFORE its first
#      run — show a store-seeded forecast for q01's signature on
#      /scheduler (provenance "store") and a non-empty CostModel
#      per-exchange history (the learned-initial-plan feed);
#   4. process B then submits ONE fault-slowed q01: it must produce
#      exactly one `query.regression` flight-recorder event naming the
#      regressed dimensions, a row on /regressions, and the
#      auron_query_regressions_total series on /metrics;
#   5. the OFF-default claim: an interleaved warm q01 serial A/B with
#      the store unarmed vs armed stays bit-identical and the armed
#      overhead stays under AURON_STATS_MAX_OVERHEAD (default 2%).
#
# The same check runs inside the suite (tests/test_statshist.py::
# test_tools_stats_check_script, marked slow), mirroring how
# perf_check.sh / obs_check.sh are wired.
set -euo pipefail
cd "$(dirname "$0")/.."
source tools/prom_assert.sh

SF=${AURON_STATS_CHECK_SF:-0.002}
MAX_OVERHEAD=${AURON_STATS_MAX_OVERHEAD:-0.02}
PROM_OUT="$(mktemp)"
STORE_DIR="$(mktemp -d)"
DATA_DIR="$(mktemp -d)"
export PROM_OUT STORE_DIR DATA_DIR SF MAX_OVERHEAD
trap 'rm -f "$PROM_OUT"; rm -rf "$STORE_DIR" "$DATA_DIR"' EXIT

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
    python -m pytest tests/test_statshist.py -q -m 'not slow' \
    -p no:cacheprovider -p no:randomly

# ---- process A: arm the store, run q01 x3, signal, get killed -9 ----
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF' &
import os
import time

from auron_tpu.config import conf
from auron_tpu.frontend.session import AuronSession
from auron_tpu.it import datagen, queries
from auron_tpu.it.oracle import PyArrowEngine

sf = float(os.environ["SF"])
catalog = datagen.generate(os.environ["DATA_DIR"], sf=sf)
plan = queries.build("q01", catalog)
with conf.scoped({"auron.spmd.singleDevice.enable": False}):
    # warm-up with the store DISARMED: first-run compiles must not
    # poison the EMA baseline the regression half of the gate rides
    AuronSession(foreign_engine=PyArrowEngine()).execute(plan)
    conf.set("auron.stats.store.dir", os.environ["STORE_DIR"])
    for i in range(3):
        res = AuronSession(foreign_engine=PyArrowEngine()).execute(plan)
        print(f"stats_check[A]: q01 run {i + 1}/3 "
              f"({res.wall_s * 1e3:.0f}ms, {res.table.num_rows} rows)",
              flush=True)
# every terminal already appended durably — nothing to flush. Signal
# readiness and idle: the parent kill -9s this process (the crash half
# of the crash-safety claim).
open(os.path.join(os.environ["STORE_DIR"], "A_READY"), "w").close()
time.sleep(600)
EOF
A_PID=$!

for _ in $(seq 1 600); do
    [ -f "$STORE_DIR/A_READY" ] && break
    if ! kill -0 "$A_PID" 2>/dev/null; then
        echo "stats_check: process A died before folding q01" >&2
        wait "$A_PID" || true
        exit 1
    fi
    sleep 0.5
done
[ -f "$STORE_DIR/A_READY" ] || {
    echo "stats_check: process A never signalled readiness" >&2; exit 1; }
kill -9 "$A_PID" 2>/dev/null || true
wait "$A_PID" 2>/dev/null || true
echo "stats_check: process A killed -9 after 3 armed q01 runs"

# ---- process B: fresh process, same store — seed proof + regression ----
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import os
import urllib.request

from auron_tpu.config import conf
from auron_tpu.it import datagen, queries
from auron_tpu.runtime import adaptive
from auron_tpu.serving import QueryServer, register_catalog
from auron_tpu.serving.forecast import plan_signature

sf = float(os.environ["SF"])
conf.set("auron.stats.store.dir", os.environ["STORE_DIR"])
# the injected slowdown below is ~1.5-2x, not the default 2x factor
conf.set("auron.stats.regression.factor", 1.25)
catalog = datagen.generate(os.environ["DATA_DIR"], sf=sf)  # manifest reuse
register_catalog(sf, catalog)
sig = plan_signature(queries.build("q01", catalog))


def get(url):
    with urllib.request.urlopen(url, timeout=300) as r:
        return r.read()


def post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.load(r)


srv = QueryServer().start()
try:
    # BEFORE the first run: the admission forecast for q01's signature
    # must already exist, marked as store-seeded
    sched = json.loads(get(srv.url + "/scheduler"))
    fc = sched["admission"]["forecasts"]
    assert sig in fc, (sig, sorted(fc))
    assert fc[sig]["provenance"] == "store", fc[sig]
    assert fc[sig]["max_peak"] > 0, fc[sig]
    # ... and the CostModel's per-exchange history is non-empty (the
    # learned-initial-plan feed) before stage 1 ever runs here
    hist = adaptive.unified_cost_model().snapshot()
    seeded = {k: v for k, v in hist.items() if k.startswith(sig + ":")}
    assert seeded, (sig, hist)
    print(f"stats_check[B]: fresh process store-seeded BEFORE first "
          f"run — forecast {fc[sig]['max_peak']}B (provenance store), "
          f"{len(seeded)} exchange histogram(s) for {sig}")

    # ONE deliberately slowed q01 (per-query fault overlay): must land
    # exactly one query.regression event naming the dimensions
    doc = post(srv.url + "/submit",
               {"corpus": "q01", "sf": sf,
                "conf": {"auron.spmd.singleDevice.enable": False,
                         "auron.faults.spec":
                             "op.execute:latency:p=1.0,ms=150,max=200",
                         "auron.task.retries": 2}})
    qid = doc["query_id"]
    assert srv.scheduler.wait(qid, timeout=600)
    st = json.loads(get(srv.url + f"/status/{qid}"))
    assert st["state"] == "succeeded", st

    evs = json.loads(get(srv.url + "/events"))["events"]
    regs = [e for e in evs if e["kind"] == "query.regression"]
    assert len(regs) == 1, regs
    assert regs[0]["query_ids"] == [qid], regs[0]
    dims = regs[0]["attrs"]["dims"]
    assert "wall_s" in dims, regs[0]
    rows = json.loads(get(srv.url + "/regressions?format=json"))
    rows = rows["regressions"]
    assert len(rows) == 1 and rows[0]["query_id"] == qid, rows
    sigdoc = json.loads(get(srv.url + f"/signatures/{sig}?format=json"))
    assert sigdoc["regressions"] == 1, sigdoc
    print(f"stats_check[B]: slowed q01 ({qid}) raised exactly one "
          f"query.regression ({', '.join(dims)}) — on /events, "
          f"/regressions and /signatures/{sig}")

    with open(os.environ["PROM_OUT"], "w") as f:
        f.write(get(srv.url + "/metrics").decode())
finally:
    srv.stop()
EOF

prom_assert_contains "$PROM_OUT" \
  'auron_query_regressions_total{kind="wall_s"}' \
  "auron_stats_store_bytes"
prom_assert_ge "$PROM_OUT" auron_stats_store_signatures 1

# ---- OFF-default bit-identity + <2% armed overhead (interleaved) ----
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import os
import sys
import tempfile
import time

from auron_tpu.config import conf
from auron_tpu.frontend.session import AuronSession
from auron_tpu.it import datagen, queries
from auron_tpu.it.oracle import PyArrowEngine

sf = float(os.environ["SF"])
max_overhead = float(os.environ["MAX_OVERHEAD"])
catalog = datagen.generate(os.environ["DATA_DIR"], sf=sf)
plan = queries.build("q01", catalog)
armed = {"auron.spmd.singleDevice.enable": False,
         "auron.stats.store.dir": tempfile.mkdtemp(prefix="auron-ab-")}
off = {"auron.spmd.singleDevice.enable": False}


def run(scope):
    with conf.scoped(scope):
        return AuronSession(foreign_engine=PyArrowEngine()).execute(plan)


# warm BOTH paths first so compiles never land in a measured rep
base = run(off)
a0 = run(armed)
if not base.table.equals(a0.table):
    print("stats ab: armed run is NOT bit-identical to unarmed",
          file=sys.stderr)
    sys.exit(1)
t_off, t_on = [], []
for _ in range(5):
    t0 = time.perf_counter()
    run(off)
    t_off.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    run(armed)
    t_on.append(time.perf_counter() - t0)
med_off = sorted(t_off)[len(t_off) // 2]
med_on = sorted(t_on)[len(t_on) // 2]
ratio = med_on / med_off if med_off > 0 else 1.0
print(f"stats ab: q01 x5 interleaved warm — unarmed "
      f"{med_off * 1e3:.1f}ms, armed {med_on * 1e3:.1f}ms, overhead "
      f"ratio {ratio:.4f} (results identical)")
if ratio > 1.0 + max_overhead:
    print(f"stats ab: armed overhead {ratio - 1.0:.2%} exceeds "
          f"{max_overhead:.0%}", file=sys.stderr)
    sys.exit(1)
EOF

echo "stats_check.sh: ok"
