#!/usr/bin/env bash
# CI multi-host fleet gate (CPU, no accelerator needed) — the
# multi-host promotion of tools/rss_check.sh:
#   1. spawn a 2-"host" topology on DISTINCT loopback addresses
#      (127.0.0.1 = "local", 127.0.0.2 = "remote"): one executor
#      worker + one durable side-car SHARD per host, every wire
#      authenticated (`auron.net.auth.secret` via its env fallback —
#      the secret never rides argv or dispatch overlays)
#   2. POST six concurrent /submit requests (IT-corpus queries)
#   3. kill -9 the REMOTE worker once one of its in-flight queries has
#      a committed+sealed stage on a side-car shard, AND kill -9 the
#      OTHER shard (not the one holding that sealed stage)
#   4. assert both deaths are detected, the requeued queries RESUME
#      (auron_fleet_worker_rss_stage_skips_total >= 1 and the sealed
#      stage's cumulative commit total stays flat — its map tasks
#      never re-ran on the surviving shard), EVERY query succeeds with
#      results value-identical to its solo fault-free run (shuffles
#      owned by the dead shard degrade to executor-local, never
#      corrupt), auth never refused a legitimate frame
#      (auron_wire_rejects_total stays 0), the surviving shard's
#      ledger is cleaned at terminal states, and no worker or side-car
#      process outlives the fleet
#
# The same check runs inside the suite (tests/test_multihost.py::
# test_tools_multihost_check_script, marked slow), mirroring how
# rss_check.sh / fleet_check.sh are wired.
set -euo pipefail
cd "$(dirname "$0")/.."
source tools/prom_assert.sh
PROM_OUT="$(mktemp)"
export PROM_OUT
trap 'rm -f "$PROM_OUT"' EXIT

# auth ON for the whole topology: the shared secret travels by env
# fallback ONLY (never argv, never conf overlays) — the driver, both
# workers and both side-car shards read it from their own environment
export AURON_TPU_AURON_NET_AUTH_SECRET="multihost-gate-secret"

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import os
import signal
import tempfile
import threading
import time
import urllib.request

import pyarrow as pa

from auron_tpu.config import conf
from auron_tpu.frontend.session import AuronSession
from auron_tpu.it import datagen, queries
from auron_tpu.it.oracle import PyArrowEngine
from auron_tpu.memmgr.manager import reset_manager
from auron_tpu.runtime import counters
from auron_tpu.serving import FleetManager, QueryServer, register_catalog
from auron_tpu.serving.executor_endpoint import ProcessExecutor
from auron_tpu.shuffle_rss.shard_map import shard_for
from auron_tpu.shuffle_rss.sidecar import SidecarProcess

SF = 0.002
NAMES = ["q01", "q42", "q01", "q42", "q01", "q42"]
REMOTE = "127.0.0.2"       # second loopback address = the "remote host"

assert os.environ.get("AURON_TPU_AURON_NET_AUTH_SECRET"), \
    "the gate runs with auth ON"

catalog = datagen.generate(
    tempfile.mkdtemp(prefix="auron-mh-check-"), sf=SF)
register_catalog(SF, catalog)


def canon(t):
    t = t.combine_chunks()
    return t.sort_by([(n, "ascending") for n in t.column_names]) \
        if t.num_rows and t.num_columns else t


serial = {"auron.spmd.singleDevice.enable": False}
baselines = {}
with conf.scoped(serial):
    for name in set(NAMES):
        s = AuronSession(foreign_engine=PyArrowEngine())
        baselines[name] = canon(s.execute(queries.build(name, catalog)).table)

# worker chaos: latency only, to keep queries in flight long enough to
# catch the remote worker with a sealed stage (the kills are the chaos)
worker_conf = {**serial,
               "auron.faults.spec":
                   "op.execute:latency:p=0.5,ms=150,max=60,seed=11",
               "auron.task.retries": 2,
               "auron.retry.backoff.base.ms": 1.0,
               "auron.retry.backoff.max.ms": 10.0,
               "auron.serving.preempt.watermark": 0.0,
               "auron.serving.max.concurrent": 4}
remote_conf = {**worker_conf, "auron.net.bind.host": REMOTE}
scope = {"auron.retry.backoff.base.ms": 1.0,
         "auron.retry.backoff.max.ms": 10.0,
         "auron.net.timeout.seconds": 10.0,
         "auron.fleet.heartbeat.seconds": 1.5,
         "auron.fleet.death.probes": 3,
         "auron.admission.default.forecast.bytes": 1 << 20,
         "auron.serving.max.concurrent": 4}


def post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.load(r)


def get(url):
    with urllib.request.urlopen(url, timeout=300) as r:
        return r.read()


rejects0 = counters.get("wire_rejects")
with conf.scoped(scope):
    reset_manager(1 << 30)
    # the 2-"host" topology: spawn each piece explicitly — the
    # FleetManager.spawn() convenience covers the one-host case
    eps, shards = [], []
    try:
        eps.append(ProcessExecutor.spawn(
            "w-local", conf_map=worker_conf, budget_bytes=1 << 28))
        eps.append(ProcessExecutor.spawn(
            "w-remote", conf_map=remote_conf, budget_bytes=1 << 28))
        shards.append(SidecarProcess.spawn(shard=0))
        shards.append(SidecarProcess.spawn(host=REMOTE, shard=1))
    except BaseException:
        for p in eps + shards:
            p.kill()
        raise
    # the "remote" pieces really advertised the remote address
    assert eps[1].host == REMOTE, eps[1].host
    assert shards[1].host == REMOTE, shards[1].host
    fleet = FleetManager(endpoints=eps, rss_sidecar=shards,
                         budget_bytes=1 << 29)
    controls = [sc.control for sc in fleet._sidecars]
    srv = QueryServer(scheduler=fleet).start()
    try:
        qids = {}
        errs = []

        def submit(i, name):
            try:
                doc = post(srv.url + "/submit",
                           {"corpus": name, "sf": SF,
                            "priority": 1 + (i % 3)})
                qids[i] = (name, doc["query_id"])
            except Exception as e:   # noqa: BLE001
                errs.append((name, repr(e)))

        threads = [threading.Thread(target=submit, args=(i, n))
                   for i, n in enumerate(NAMES)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        assert len(qids) == len(NAMES)

        # wait until the REMOTE worker holds an in-flight query with a
        # committed+sealed stage on a side-car shard
        resumed_qid = sealed_sid = owner = None
        commits_before = None
        deadline = time.time() + 180
        while time.time() < deadline:
            inflight = [q for _, q in qids.values()
                        if fleet.get(q).executor_id == "w-remote"
                        and not fleet.get(q).done.is_set()]
            for shard_i, control in enumerate(controls):
                stats = control.stats()
                for q in inflight:
                    for sid, sdoc in stats["shuffles"].items():
                        if sid.startswith(f"{q}|") and \
                                sdoc["sealed"] is not None and \
                                sdoc["maps"] >= sdoc["sealed"]:
                            resumed_qid, sealed_sid = q, sid
                            owner = shard_i
                            commits_before = \
                                stats["totals"][sid]["commits"]
                    if resumed_qid:
                        break
                if resumed_qid:
                    break
            if resumed_qid:
                break
            time.sleep(0.1)
        assert resumed_qid is not None, \
            [c.stats() for c in controls]
        assert owner == shard_for(sealed_sid, len(shards))
        victim_qids = [q for _, q in qids.values()
                       if fleet.get(q).executor_id == "w-remote"
                       and not fleet.get(q).done.is_set()]

        # kill -9 the remote worker AND the shard NOT holding the
        # sealed stage (shuffles it owns degrade to executor-local)
        doomed_shard = 1 - owner
        os.kill(eps[1].pid, signal.SIGKILL)
        os.kill(fleet._sidecars[doomed_shard].proc.pid,
                signal.SIGKILL)
        t_kill = time.monotonic()
        detect_w = detect_s = None
        while time.monotonic() - t_kill < 30:
            if detect_w is None and \
                    fleet.fleet_snapshot()["w-remote"]["state"] == "dead":
                detect_w = time.monotonic() - t_kill
            if detect_s is None and not fleet.rss_sidecar_up():
                detect_s = time.monotonic() - t_kill
            if detect_w is not None and detect_s is not None:
                break
            time.sleep(0.05)
        assert detect_w is not None, "worker death never declared"
        assert detect_s is not None, "shard death never declared"
        sc_states = fleet.stats()["fleet"]["rss_sidecars"]
        assert sc_states[doomed_shard]["state"] == "dead"
        assert sc_states[owner]["state"] != "dead", \
            "the sealed stage's owner shard must survive"

        for i, (name, qid) in sorted(qids.items()):
            assert fleet.wait(qid, timeout=600), \
                f"{name} did not finish: {fleet.status(qid)}"
            st = json.loads(get(srv.url + f"/status/{qid}"))
            assert st["state"] == "succeeded", (name, st)
            res = json.loads(get(srv.url + f"/result/{qid}"))
            assert not res["truncated"]
            got = canon(pa.Table.from_pylist(
                res["rows"], schema=baselines[name].schema))
            assert got.equals(baselines[name]), \
                f"{name} served result diverged from its solo run"

        requeued = [q for q in victim_qids
                    if fleet.status(q)["requeues"] >= 1]
        assert requeued, "the killed worker's queries never requeued"

        # RESUME, not recompute: the sealed stage's cumulative commit
        # total on the SURVIVING shard never moved (its map tasks were
        # skipped, not re-run); >= 1 stage skip is asserted on /metrics
        # by the shared prom helper after this block
        post_stats = controls[owner].stats(prefix=f"{resumed_qid}|")
        assert post_stats["totals"][sealed_sid]["commits"] == \
            commits_before, "map tasks re-ran for the sealed stage"

        # surviving shard's ledger cleaned at terminal states
        for _, qid in qids.values():
            assert not controls[owner].stats(
                prefix=f"{qid}|")["shuffles"], qid

        # auth never refused a legitimate frame anywhere: driver-side
        # counter flat here, fleet-wide total 0 on /metrics below
        assert counters.get("wire_rejects") - rejects0 == 0
        prom = get(srv.url + "/metrics").decode()
        with open(os.environ["PROM_OUT"], "w") as f:
            f.write(prom)
        lines = [ln for ln in prom.splitlines()
                 if ln.startswith("auron_fleet_worker_rss_stage_skips"
                                  "_total ")]
        skips = int(lines[0].split()[-1]) if lines else 0
        print(f"multihost_check: {len(NAMES)}/{len(NAMES)} queries "
              f"value-identical to solo runs with auth ON across 2 "
              f"hosts; remote worker + shard {doomed_shard} killed -9 "
              f"mid-flight (detected {detect_w:.1f}s/{detect_s:.1f}s), "
              f"{len(requeued)} query(ies) requeued, {skips} stage(s) "
              f"RESUMED from surviving shard {owner} (sealed commit "
              f"total flat at {commits_before})")
    finally:
        srv.stop()
        for ep in eps:
            assert ep.proc.poll() is not None, "worker process leaked"
        for sc in shards:
            assert sc.proc.poll() is not None, "side-car process leaked"
        reset_manager()
EOF

prom_assert_contains "$PROM_OUT" \
  "auron_wire_rejects_total 0" \
  "auron_fleet_worker_rss_stage_skips_total" \
  "auron_fleet_deaths_total"
prom_assert_ge "$PROM_OUT" auron_fleet_worker_rss_stage_skips_total 1

echo "multihost_check.sh: ok"
