#!/usr/bin/env bash
# CI trace gate (CPU, no accelerator needed):
#   1. run a tier-1 TPC-DS query with tracing ON through the serial
#      path (shuffle/task spans materialize) and a latency fault armed,
#      dumping Chrome-trace JSON (`python -m auron_tpu.trace run`
#      validates the schema before writing)
#   2. re-validate the dumped file through the standalone validator
#   3. check the committed EXPLAIN ANALYZE goldens via the pytest hook
#      (tests/test_observability.py; regen with AURON_REGEN_GOLDEN=1)
#
# The same checks run inside the suite (tests/test_observability.py::
# test_tools_trace_check_script, marked slow), mirroring how
# lint_plans.sh / chaos_check.sh are wired.
set -euo pipefail
cd "$(dirname "$0")/.."

out_dir=$(mktemp -d /tmp/auron_trace_check.XXXXXX)
trap 'rm -rf "$out_dir"' EXIT

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m auron_tpu.trace run \
    --query q01 --sf 0.002 --serial \
    --faults 'shuffle.push:latency:ms=20,max=2,seed=3' \
    -o "$out_dir/q01.trace.json" --analyze

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m auron_tpu.trace validate \
    "$out_dir/q01.trace.json"

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m pytest -q \
    -p no:cacheprovider \
    tests/test_observability.py::test_explain_analyze_golden_q03 \
    tests/test_observability.py::test_explain_analyze_fused_fragment_boundary

echo "trace_check.sh: ok"
