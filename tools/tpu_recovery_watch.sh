#!/bin/bash
# TPU tunnel recovery watcher (round-5 ops tool).
#
# The shared axon tunnel dies or wedges mid-session (rounds 2-5); chip
# evidence must be banked the moment it revives.  Loop: cheap probe →
# on success run the banked-evidence sequence, one chip process at a
# time (the tunnel starves concurrent clients):
#   1. bench spmd worker  — banks the stage-program compile into
#      .jax_cache so the driver's end-of-round bench warm-compiles
#   2. full bench.py      — the canonical BENCH_r5-shaped artifact
#   3. 64M-row MFU profile — TPU_PROFILE_r05.json roofline numbers
#   4. sf0.1 IT corpus on tpu — IT_TPU_r05.json per-query chip times
# Logs to /tmp/tpu_recovery.log; artifacts land in the repo root.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_recovery.log
echo "$(date -u +%H:%M:%S) watcher armed" >> "$LOG"

probe() {
  timeout 150 python -c "
import jax, jax.numpy as jnp
jax.devices()
x = jnp.ones((256, 256)); (x @ x).block_until_ready()
print('probe-ok')
" 2>/dev/null | grep -q probe-ok
}

export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=2

while true; do
  if probe; then
    echo "$(date -u +%H:%M:%S) tunnel alive - banking evidence" >> "$LOG"
    echo "$(date -u +%H:%M:%S) [1/4] spmd worker" >> "$LOG"
    timeout 4500 python bench.py --worker spmd \
      > /tmp/r5_spmd_worker.out 2>&1
    echo "$(date -u +%H:%M:%S) [1/4] rc=$? cache=$(ls .jax_cache | wc -l)" >> "$LOG"
    echo "$(date -u +%H:%M:%S) [2/4] full bench" >> "$LOG"
    timeout 2400 python bench.py > /tmp/r5_bench_full.out 2>&1
    echo "$(date -u +%H:%M:%S) [2/4] rc=$?" >> "$LOG"
    echo "$(date -u +%H:%M:%S) [3/4] profile 64M" >> "$LOG"
    AURON_PROFILE_ROWS=$((1<<26)) timeout 3600 python bench.py \
      --worker profile > /tmp/r5_profile64m.out 2>&1
    echo "$(date -u +%H:%M:%S) [3/4] rc=$?" >> "$LOG"
    echo "$(date -u +%H:%M:%S) [4/4] IT sf0.1 on tpu" >> "$LOG"
    timeout 7200 python -m auron_tpu.it --sf 0.1 --platform tpu \
      --mesh 1 --json IT_TPU_r05.json > /tmp/r5_it_tpu.out 2>&1
    echo "$(date -u +%H:%M:%S) [4/4] rc=$?" >> "$LOG"
    echo "$(date -u +%H:%M:%S) sequence done" >> "$LOG"
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) tunnel down" >> "$LOG"
  sleep 300
done
