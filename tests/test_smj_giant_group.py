"""Streaming-SMJ giant-group escape (VERDICT r4 weak #7): the build
window materializes at most auron.smj.window.max.rows; a single-key
window past the cap (the degenerate all-ties shape) switches to the
bounded set-logic/cross-product path, with other keys joined normally.
Differential: every flavor must produce exactly what the same join
yields with the cap disabled.  (conf.rs SMJ_FALLBACK_* role.)"""

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu import conf
from auron_tpu.ir import plan as P
from auron_tpu.ir.expr import SortExpr, col
from auron_tpu.ir.plan import JoinOn
from auron_tpu.ir.schema import from_arrow_schema
from auron_tpu.runtime.executor import execute_plan
from auron_tpu.runtime.resources import ResourceRegistry

FLAVORS = ("inner", "left", "right", "full", "left_semi", "left_anti",
           "right_semi", "right_anti", "existence")


def _tables(all_ties: bool, seed=7):
    """BOTH sides carry a giant tied group on key 5 (the build side is
    the right table for most flavors, the left for right_semi/anti — a
    giant group on each side exercises the cap wherever the build
    lands), plus a few normal keys in the mixed shape."""
    giant_l, giant_r = 300, 400      # >> the test cap of 64
    if all_ties:
        lk = np.full(giant_l, 5)
        rk = np.full(giant_r, 5)
    else:
        lk = np.concatenate([np.full(giant_l, 5), [1, 2, 2, 9],
                             [3]])          # 3 only on left
        rk = np.concatenate([np.full(giant_r, 5), [2, 2, 9, 9],
                             [4]])          # 4 only on right
    lt = pa.table({
        "k": np.sort(lk).astype(np.int64),
        "lv": np.arange(len(lk), dtype=np.int64)})
    rt = pa.table({
        "k2": np.sort(rk).astype(np.int64),
        "rv": np.arange(len(rk), dtype=np.int64) * 10})
    return lt, rt


def _smj_plan(lt, rt, flavor):
    left = P.FFIReader(schema=from_arrow_schema(lt.schema),
                       resource_id="L")
    right = P.FFIReader(schema=from_arrow_schema(rt.schema),
                        resource_id="R")
    return P.SortMergeJoin(
        left=left, right=right,
        on=JoinOn(left_keys=(col("k"),), right_keys=(col("k2"),)),
        join_type=flavor)


def _run(plan, lt, rt, chunk=50):
    res = ResourceRegistry()
    res.put("L", lt.to_batches(max_chunksize=chunk))
    res.put("R", rt.to_batches(max_chunksize=chunk))
    return execute_plan(plan, resources=res).to_pylist()


def _canon(rows):
    return sorted(tuple(sorted((k, repr(v)) for k, v in r.items()))
                  for r in rows)


@pytest.mark.parametrize("flavor", FLAVORS)
@pytest.mark.parametrize("all_ties", [True, False])
def test_giant_group_matches_uncapped(flavor, all_ties):
    lt, rt = _tables(all_ties)
    plan = _smj_plan(lt, rt, flavor)
    with conf.scoped({"auron.smj.window.max.rows": 0}):
        want = _run(plan, lt, rt)
    with conf.scoped({"auron.smj.window.max.rows": 64}):
        got = _run(plan, lt, rt)
    assert _canon(got) == _canon(want), \
        f"{flavor} all_ties={all_ties}: {len(got)} vs {len(want)} rows"


def test_escape_actually_triggers():
    """The capped run must take the escape path (metrics counter) — a
    silent non-trigger would make the differential vacuous."""
    from auron_tpu.ops.joins.exec import SortMergeJoinExec
    lt, rt = _tables(all_ties=True)
    counted = []
    orig = SortMergeJoinExec._join_giant_group

    def spy(self, *a, **kw):
        counted.append(1)
        return orig(self, *a, **kw)

    SortMergeJoinExec._join_giant_group = spy
    try:
        with conf.scoped({"auron.smj.window.max.rows": 64}):
            _run(_smj_plan(lt, rt, "inner"), lt, rt)
    finally:
        SortMergeJoinExec._join_giant_group = orig
    assert counted, "cap=64 with a 400-row tied group never escaped"


def test_null_key_giant_group():
    """A giant NULL-key group: equi-joins must match nothing; outer
    flavors null-extend."""
    n = 300
    lt = pa.table({"k": pa.array([None] * n + [1, 2], type=pa.int64()),
                   "lv": np.arange(n + 2, dtype=np.int64)})
    rt = pa.table({"k2": pa.array([None] * 250 + [2], type=pa.int64()),
                   "rv": np.arange(251, dtype=np.int64)})
    for flavor in ("inner", "left", "full", "left_semi", "left_anti"):
        plan = _smj_plan(lt, rt, flavor)
        with conf.scoped({"auron.smj.window.max.rows": 0}):
            want = _run(plan, lt, rt)
        with conf.scoped({"auron.smj.window.max.rows": 64}):
            got = _run(plan, lt, rt)
        assert _canon(got) == _canon(want), flavor


def test_giant_group_fuzz_tiny_budget():
    """Randomized all-ties-heavy corpora under a tiny window cap AND a
    tiny memory-manager budget (so cursor buffers actually spill under
    pressure while the escape iterates): results must match the
    uncapped, unconstrained run for every flavor drawn."""
    from auron_tpu.memmgr.manager import reset_manager
    rng = np.random.default_rng(123)
    for trial in range(4):
        giant = int(rng.integers(150, 400))
        n_other = int(rng.integers(0, 20))
        lk = np.concatenate([np.full(giant, 50),
                             rng.integers(0, 8, n_other)])
        rk = np.concatenate([np.full(int(rng.integers(100, 300)), 50),
                             rng.integers(0, 8, n_other)])
        lt = pa.table({"k": np.sort(lk).astype(np.int64),
                       "lv": np.arange(len(lk), dtype=np.int64)})
        rt = pa.table({"k2": np.sort(rk).astype(np.int64),
                       "rv": np.arange(len(rk), dtype=np.int64)})
        flavor = FLAVORS[int(rng.integers(0, len(FLAVORS)))]
        plan = _smj_plan(lt, rt, flavor)
        with conf.scoped({"auron.smj.window.max.rows": 0}):
            want = _run(plan, lt, rt, chunk=33)
        try:
            with conf.scoped({"auron.smj.window.max.rows": 48,
                              "auron.memory.budget.bytes": 64 * 1024,
                              "auron.memory.spill.min.trigger.bytes":
                                  4096}):
                reset_manager()
                got = _run(plan, lt, rt, chunk=33)
        finally:
            reset_manager()
        assert _canon(got) == _canon(want), \
            f"trial {trial} flavor={flavor} giant={giant}"
