"""Exact 64-bit FLOAT64 ordering/hashing on f64-demoting backends
(VERDICT r4 missing #8 / weak #5).

The TPU backend demotes f64 to f32 granularity, so round<=4 sort keys
ordered doubles at f32 granularity — a semantics divergence from the
oracle (and Spark, sort_exec.rs key-prefix encoding is 64-bit exact).
The fix: ingest captures the exact IEEE-754 bit pattern host-side as a
uint64 sidecar (`DeviceColumn.bits`), key encoding orders by it, and
device-computed doubles widen losslessly from their f32 bits via pure
integer ops.  These tests run on CPU and simulate the demotion by
constructing columns whose `data` is f32-rounded while `bits` is exact —
precisely the state a TPU ingest produces.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from auron_tpu import conf
from auron_tpu.columnar.batch import Batch, DeviceColumn
from auron_tpu.ir.schema import DataType, Field, Schema
from auron_tpu.ops.sort_keys import (
    encode_key_column,
    f32_bits_to_f64_bits,
    f64_bits_of_column,
    f64_exact_bits_enabled,
    lexsort_indices,
    order_encode_f64_bits,
)


def _f64col(vals, bits=None, validity=None):
    vals = np.asarray(vals, np.float64)
    cap = len(vals)
    v = np.ones(cap, bool) if validity is None else np.asarray(validity)
    b = None if bits is None else jnp.asarray(np.asarray(bits, np.uint64))
    return DeviceColumn(DataType.float64(), jnp.asarray(vals),
                        jnp.asarray(v), b)


# ---------------------------------------------------------------------------
# the widening kernel: exact for every float32
# ---------------------------------------------------------------------------

SPECIAL_F32_BITS = np.array([
    0x00000000,  # +0
    0x80000000,  # -0
    0x00000001,  # min subnormal
    0x80000001,  # -min subnormal
    0x007FFFFF,  # max subnormal
    0x807FFFFF,
    0x00800000,  # min normal
    0x80800000,
    0x3F800000,  # 1.0
    0xBF800000,  # -1.0
    0x7F7FFFFF,  # max finite
    0xFF7FFFFF,
    0x7F800000,  # +inf
    0xFF800000,  # -inf
    0x7FC00000,  # canonical qNaN
    0xFFC00000,
    0x7F800001,  # sNaN payload
    0x7FABCDEF,  # NaN payload
], dtype=np.uint32)


def test_widen_matches_hardware_conversion():
    rng = np.random.default_rng(7)
    rand = rng.integers(0, 2 ** 32, size=20000, dtype=np.uint32)
    bits32 = np.concatenate([SPECIAL_F32_BITS, rand])
    want = bits32.view(np.float32).astype(np.float64).view(np.uint64)
    got = np.asarray(f32_bits_to_f64_bits(jnp.asarray(bits32)))
    # NaNs: numpy's f32->f64 cast canonicalizes payloads on some
    # platforms; hardware semantics shift the payload by 29.  Compare
    # non-NaN bit-exactly and NaNs structurally.
    f32v = bits32.view(np.float32)
    isnan = np.isnan(f32v)
    assert (got[~isnan] == want[~isnan]).all()
    exp = (got[isnan] >> 52) & 0x7FF
    assert (exp == 0x7FF).all()
    assert ((got[isnan] & ((1 << 52) - 1)) != 0).all()  # still NaN


def test_widen_preserves_order_and_bits_space():
    # ordering of widened f32 bits == ordering of the f64 values
    rng = np.random.default_rng(3)
    vals = rng.standard_normal(5000).astype(np.float32)
    b32 = vals.view(np.uint32)
    wide = np.asarray(f32_bits_to_f64_bits(jnp.asarray(b32)))
    enc = np.asarray(order_encode_f64_bits(jnp.asarray(wide)))
    order = np.argsort(enc, kind="stable")
    assert (np.diff(vals[order].astype(np.float64)) >= 0).all()


# ---------------------------------------------------------------------------
# simulated TPU demotion: exact bits beat f32-granular data
# ---------------------------------------------------------------------------

def _adversarial_ties():
    """Doubles that collide at f32 granularity but differ at f64."""
    base = np.array([1.0, -1.0, 3.141592653589793, 1e300, -1e-300, 0.0],
                    np.float64)
    eps = np.array([0.0, 1e-13, -1e-13, 5e-14, 2.5e-13, 7.5e-14], np.float64)
    vals = (base[:, None] * (1.0 + eps[None, :])).reshape(-1)
    rng = np.random.default_rng(11)
    rng.shuffle(vals)
    return vals


def test_exact_bits_order_matches_oracle_under_demotion():
    vals = _adversarial_ties()
    demoted = vals.astype(np.float32).astype(np.float64)
    # sanity: demotion actually collides some distinct doubles
    assert len(np.unique(demoted)) < len(np.unique(vals))
    col = _f64col(demoted, bits=vals.view(np.uint64))
    with conf.scoped({"auron.sort.f64.exactbits": "on"}):
        words = encode_key_column(col, asc=True, nulls_first=True)
        perm = np.asarray(lexsort_indices(words, len(vals), len(vals)))
    got = vals[perm]
    want = np.sort(vals, kind="stable")
    assert (got.view(np.uint64) == want.view(np.uint64)).all()


def test_f32_granularity_would_diverge():
    # the legacy path (bits ignored) CANNOT recover the f64 order — the
    # adversarial corpus has real power
    vals = _adversarial_ties()
    demoted = vals.astype(np.float32).astype(np.float64)
    col = _f64col(demoted, bits=None)
    with conf.scoped({"auron.sort.f64.exactbits": "off"}):
        words = encode_key_column(col, asc=True, nulls_first=True)
        perm = np.asarray(lexsort_indices(words, len(vals), len(vals)))
    got = vals[perm]
    want = np.sort(vals, kind="stable")
    assert not (got.view(np.uint64) == want.view(np.uint64)).all()


def test_desc_and_nulls_with_bits():
    vals = _adversarial_ties()
    validity = np.ones(len(vals), bool)
    validity[3] = validity[17] = False
    col = _f64col(vals.astype(np.float32).astype(np.float64),
                  bits=vals.view(np.uint64), validity=validity)
    with conf.scoped({"auron.sort.f64.exactbits": "on"}):
        words = encode_key_column(col, asc=False, nulls_first=False)
        perm = np.asarray(lexsort_indices(words, len(vals), len(vals)))
    live = vals[validity]
    got = vals[perm]
    # nulls last, then descending by value
    n_null = (~validity).sum()
    body = got[:-n_null]
    want = np.sort(live, kind="stable")[::-1]
    assert (body.view(np.uint64) == want.view(np.uint64)).all()
    assert set(perm[-n_null:].tolist()) == {3, 17}


# ---------------------------------------------------------------------------
# sidecar lifecycle
# ---------------------------------------------------------------------------

def test_ingest_attaches_and_output_reconstructs():
    vals = _adversarial_ties()
    schema = Schema((Field("x", DataType.float64()),))
    with conf.scoped({"auron.sort.f64.exactbits": "on"}):
        assert f64_exact_bits_enabled()
        b = Batch.from_numpy(schema, [vals])
        col = b.columns[0]
        assert col.bits is not None
        assert (np.asarray(col.bits)[:len(vals)] == vals.view(np.uint64)).all()
        rb = b.to_arrow()
    out = rb.column(0).to_numpy(zero_copy_only=False)
    assert (out.view(np.uint64) == vals.view(np.uint64)).all()


def test_bits_follow_gather_and_head():
    vals = _adversarial_ties()
    col = _f64col(vals, bits=vals.view(np.uint64))
    idx = jnp.asarray(np.arange(len(vals))[::-1].copy())
    g = col.gather(idx, jnp.ones(len(vals), bool))
    assert (np.asarray(g.bits) == vals[::-1].view(np.uint64)).all()
    schema = Schema((Field("x", DataType.float64()),))
    b = Batch(schema, [col], len(vals), len(vals)).head(5)
    hb = np.asarray(b.columns[0].bits)
    assert (hb[:5] == vals[:5].view(np.uint64)).all()
    assert (hb[5:] == 0).all()


def test_concat_widens_missing_parts():
    from auron_tpu.columnar.batch import concat_device_columns
    exact = _f64col(np.array([1.0 + 1e-13]), bits=np.array(
        [np.float64(1.0 + 1e-13)]).view(np.uint64))
    computed = _f64col(np.array([2.5]))  # no bits: f32-exact value
    with conf.scoped({"auron.sort.f64.exactbits": "on"}):
        cat = concat_device_columns([exact, computed])
    assert cat.bits is not None
    got = np.asarray(cat.bits)
    assert got[0] == np.float64(1.0 + 1e-13).view(np.uint64)
    assert got[1] == np.float64(2.5).view(np.uint64)


def test_pytree_roundtrip_with_and_without_bits():
    vals = np.array([1.5, -2.5])
    for col in (_f64col(vals), _f64col(vals, bits=vals.view(np.uint64))):
        leaves, treedef = jax.tree_util.tree_flatten(col)
        back = jax.tree_util.tree_unflatten(treedef, leaves)
        assert (back.bits is None) == (col.bits is None)
        out = jax.jit(lambda c: c)(col)
        assert (out.bits is None) == (col.bits is None)


# ---------------------------------------------------------------------------
# hashing: exact bits == Spark value hash; widened == stored value hash
# ---------------------------------------------------------------------------

def test_hash_bits_matches_value_hash():
    from auron_tpu.exprs.hashing import hash_column, hash_f64_bits
    vals = np.concatenate([_adversarial_ties(), [-0.0, 0.0]])
    seed = jnp.full(len(vals), np.uint32(42), jnp.uint32)
    col_plain = _f64col(vals)
    with conf.scoped({"auron.sort.f64.exactbits": "off"}):
        want = np.asarray(hash_column(col_plain, seed))
    got = np.asarray(hash_f64_bits(jnp.asarray(vals.view(np.uint64)), seed))
    assert (got == want).all()


def test_hash_column_consistent_between_ingested_and_computed():
    # same VALUE must land in the same shuffle partition whether its
    # column carries exact bits or not (f32-exact values only — computed
    # columns on TPU can't hold anything finer)
    from auron_tpu.exprs.hashing import hash_column
    vals = np.array([1.0, 2.5, -3.25, 0.0, 1e30], np.float64)
    seed = jnp.full(len(vals), np.uint32(42), jnp.uint32)
    with conf.scoped({"auron.sort.f64.exactbits": "on"}):
        h_bits = np.asarray(hash_column(
            _f64col(vals, bits=vals.view(np.uint64)), seed))
        h_plain = np.asarray(hash_column(_f64col(vals), seed))
    assert (h_bits == h_plain).all()


# ---------------------------------------------------------------------------
# host mirror consistency (range bounds / spill merges)
# ---------------------------------------------------------------------------

def test_host_mirror_f32_matches_device_words():
    from auron_tpu.ops.sort import _np_encode_key

    class HV:
        def __init__(self, vals, dtype):
            self.vals = vals
            self.mask = np.ones(len(vals), bool)
            self.dtype = dtype

    rng = np.random.default_rng(5)
    vals = np.concatenate([
        rng.standard_normal(1000).astype(np.float32),
        np.array([0.0, -0.0, np.inf, -np.inf, 1e-40, -1e-40], np.float32),
    ])
    dcol = DeviceColumn(DataType.float32(), jnp.asarray(vals),
                        jnp.ones(len(vals), bool))
    dwords = encode_key_column(dcol, asc=True, nulls_first=True)
    hwords = _np_encode_key(HV(vals, DataType.float32()), True, True)
    # both sides emit [null_rank, value_word]
    assert (np.asarray(dwords[1]) == hwords[1]).all()


def test_host_mirror_f64_matches_device_words_with_bits():
    from auron_tpu.ops.sort import _np_encode_key

    class HV:
        def __init__(self, vals, dtype):
            self.vals = vals
            self.mask = np.ones(len(vals), bool)
            self.dtype = dtype

    vals = _adversarial_ties()
    col = _f64col(vals.astype(np.float32).astype(np.float64),
                  bits=vals.view(np.uint64))
    with conf.scoped({"auron.sort.f64.exactbits": "on"}):
        dwords = encode_key_column(col, asc=True, nulls_first=True)
    hwords = _np_encode_key(HV(vals, DataType.float64()), True, True)
    assert (np.asarray(dwords[1]) == hwords[1]).all()


# ---------------------------------------------------------------------------
# end-to-end: sort through the engine with forced bits
# ---------------------------------------------------------------------------

def test_engine_sort_with_forced_bits_matches_plain():
    import pyarrow as pa

    from auron_tpu.ir import plan as P
    from auron_tpu.ir.expr import SortExpr, col
    from auron_tpu.ir.schema import from_arrow_schema
    from auron_tpu.runtime.executor import execute_plan
    from auron_tpu.runtime.resources import ResourceRegistry

    rng = np.random.default_rng(23)
    vals = np.concatenate([_adversarial_ties(),
                           rng.standard_normal(500)])

    def run():
        t = pa.table({"x": vals})
        res = ResourceRegistry()
        res.put("T", t.to_batches(max_chunksize=64))
        src = P.FFIReader(schema=from_arrow_schema(t.schema),
                          resource_id="T")
        node = P.Sort(child=src, sort_exprs=(SortExpr(child=col("x")),))
        return execute_plan(node, resources=res).to_table() \
            .column(0).combine_chunks().to_numpy(zero_copy_only=False)

    with conf.scoped({"auron.sort.f64.exactbits": "on"}):
        got = run()
    with conf.scoped({"auron.sort.f64.exactbits": "off"}):
        want = run()
    assert (got.view(np.uint64) == want.view(np.uint64)).all()
    assert (got.view(np.uint64)
            == np.sort(vals, kind="stable").view(np.uint64)).all()
