"""In-process Kafka broker speaking the real wire protocol (Metadata v1,
ListOffsets v1, Fetch v4) over TCP — the test peer for the wire-protocol
consumer, playing the role a containerized broker plays in the
reference's kafka workflow CI."""

from __future__ import annotations

import socketserver
import struct
import threading
from typing import Dict, List, Optional, Tuple

from auron_tpu.streaming.kafka_client import (
    API_FETCH, API_LIST_OFFSETS, API_METADATA, _Reader, _Writer,
    encode_record_batch,
)

# topic -> partition -> list of (timestamp_delta, key, value)
TopicData = Dict[str, Dict[int, List[Tuple[int, Optional[bytes],
                                           Optional[bytes]]]]]


class MockKafkaBroker:
    def __init__(self, data: TopicData, codec_id: int = 0,
                 batch_rows: int = 3):
        self.data = data
        self.codec_id = codec_id
        self.batch_rows = batch_rows
        broker = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        raw = broker._recv_frame(self.request)
                        resp = broker._dispatch(raw)
                        self.request.sendall(
                            struct.pack(">i", len(resp)) + resp)
                except (ConnectionError, EOFError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server(("127.0.0.1", 0), Handler)
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> "MockKafkaBroker":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    @staticmethod
    def _recv_frame(sock) -> bytes:
        hdr = b""
        while len(hdr) < 4:
            chunk = sock.recv(4 - len(hdr))
            if not chunk:
                raise EOFError
            hdr += chunk
        (n,) = struct.unpack(">i", hdr)
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise EOFError
            buf += chunk
        return bytes(buf)

    def _dispatch(self, frame: bytes) -> bytes:
        r = _Reader(frame)
        api_key = r.i16()
        api_version = r.i16()
        corr = r.i32()
        r.string()              # client id
        body = frame[r.o:]
        w = _Writer()
        w.i32(corr)
        if api_key == API_METADATA:
            self._metadata(_Reader(body), w)
        elif api_key == API_LIST_OFFSETS:
            self._list_offsets(_Reader(body), w)
        elif api_key == API_FETCH:
            self._fetch(_Reader(body), w, api_version)
        else:
            raise ValueError(f"mock broker: api {api_key} unsupported")
        return bytes(w.b)

    def _metadata(self, r: _Reader, w: _Writer) -> None:
        n = r.i32()
        topics = [r.string() for _ in range(n)] if n >= 0 else \
            list(self.data)
        host, port = self._server.server_address[:2]
        w.i32(1)                # brokers
        w.i32(0)                # node id
        w.string(host)
        w.i32(port)
        w.string(None)          # rack
        w.i32(0)                # controller
        w.i32(len(topics))
        for t in topics:
            parts = self.data.get(t, {})
            w.i16(0 if t in self.data else 3)   # UNKNOWN_TOPIC
            w.string(t)
            w.i8(0)
            w.i32(len(parts))
            for pid in sorted(parts):
                w.i16(0)
                w.i32(pid)
                w.i32(0)        # leader = node 0
                w.i32(0)        # replicas
                w.i32(0)        # isr

    def _list_offsets(self, r: _Reader, w: _Writer) -> None:
        r.i32()                 # replica id
        out = []
        for _ in range(r.i32()):
            topic = r.string()
            for _p in range(r.i32()):
                pid = r.i32()
                ts = r.i64()
                n = len(self.data.get(topic, {}).get(pid, []))
                out.append((topic, pid, 0 if ts == -2 else n))
        w.i32(len({t for t, _, _ in out}))
        for topic, pid, off in out:
            w.string(topic)
            w.i32(1)
            w.i32(pid)
            w.i16(0)
            w.i64(-1)
            w.i64(off)

    def _fetch(self, r: _Reader, w: _Writer, version: int) -> None:
        r.i32()                 # replica
        r.i32()                 # max wait
        r.i32()                 # min bytes
        r.i32()                 # max bytes
        r.i8()                  # isolation
        reqs = []
        for _ in range(r.i32()):
            topic = r.string()
            for _p in range(r.i32()):
                pid = r.i32()
                off = r.i64()
                r.i32()         # partition max bytes
                reqs.append((topic, pid, off))
        w.i32(0)                # throttle
        w.i32(len({t for t, _, _ in reqs}))
        for topic, pid, off in reqs:
            rows = self.data.get(topic, {}).get(pid, [])
            w.string(topic)
            w.i32(1)
            w.i32(pid)
            w.i16(0)
            w.i64(len(rows))    # high watermark
            w.i64(len(rows))    # last stable offset
            w.i32(0)            # aborted
            record_set = b""
            base = int(off)
            while base < len(rows):
                chunk = rows[base:base + self.batch_rows]
                record_set += encode_record_batch(
                    base, chunk, first_ts=1_700_000_000_000,
                    codec_id=self.codec_id)
                base += len(chunk)
            w.bytes_(record_set)
