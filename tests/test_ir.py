"""IR construction + serde roundtrip tests (golden-file style)."""

import math

from auron_tpu.ir import serde
from auron_tpu.ir.expr import (
    AggExpr, BinaryExpr, Case, Cast, Column, InList, IsNull, Like, Literal,
    ScalarFunctionCall, ScAnd, SortExpr, WhenThen, col, lit,
)
from auron_tpu.ir.plan import (
    Agg, BroadcastJoin, FileGroup, Filter, JoinOn, Limit, ParquetScan,
    Partitioning, Projection, ShuffleWriter, Sort, TaskDefinition, Union,
    UnionInput, plan_children, walk,
)
from auron_tpu.ir.schema import DataType, Field, Schema


def make_schema():
    return Schema.of(
        Field("id", DataType.int64(), nullable=False),
        Field("name", DataType.string()),
        Field("price", DataType.decimal(12, 2)),
        Field("ts", DataType.timestamp_us()),
        Field("tags", DataType.list_(DataType.string())),
    )


def make_plan():
    schema = make_schema()
    scan = ParquetScan(schema=schema, file_groups=(FileGroup(paths=("/tmp/x.parquet",)),),
                       projection=(0, 1, 2))
    filt = Filter(child=scan, predicates=(
        ScAnd(left=BinaryExpr(left=col("id"), op=">", right=lit(10)),
              right=Like(child=col("name"), pattern=lit("a%"))),
    ))
    proj = Projection(child=filt,
                      exprs=(col("id"),
                             ScalarFunctionCall(name="upper", args=(col("name"),),
                                                return_type=DataType.string())),
                      names=("id", "uname"))
    agg = Agg(child=proj, exec_mode="partial", grouping=(col("uname"),),
              grouping_names=("uname",),
              aggs=(AggExpr(fn="sum", children=(col("id"),),
                            return_type=DataType.int64()),),
              agg_names=("sum_id",))
    sw = ShuffleWriter(child=agg,
                       partitioning=Partitioning(mode="hash", num_partitions=8,
                                                 expressions=(col("uname"),)))
    return TaskDefinition(plan=sw, stage_id=3, partition_id=1, num_partitions=8)


def test_schema_basics():
    s = make_schema()
    assert len(s) == 5
    assert s.index_of("NAME") == 1  # case-insensitive default
    assert s.field("price").dtype.is_decimal
    assert repr(s.field("tags").dtype) == "list<string>"


def test_serde_roundtrip():
    td = make_plan()
    td2 = serde.roundtrip(td)
    assert td2 == td
    # JSON stability: canonical form equal after double roundtrip
    assert serde.to_json(td2) == serde.to_json(td)


def test_serde_special_floats():
    e = InList(child=col("x"), values=(lit(float("nan")), lit(float("inf")), lit(1.5)))
    e2 = serde.roundtrip(e)
    assert math.isnan(e2.values[0].value)
    assert math.isinf(e2.values[1].value)
    assert e2.values[2].value == 1.5


def test_serde_bytes_and_case():
    e = Case(branches=(WhenThen(when=IsNull(child=col("a")), then=lit(0)),),
             else_expr=Cast(child=col("a"), dtype=DataType.int64()))
    assert serde.roundtrip(e) == e


def test_walk_and_children():
    td = make_plan()
    kinds = [p.kind for p in walk(td.plan)]
    assert kinds == ["shuffle_writer", "agg", "projection", "filter", "parquet_scan"]


def test_union_walk_through_wrappers():
    schema = Schema.of(Field("a", DataType.int32()))
    leaf1 = ParquetScan(schema=schema)
    leaf2 = ParquetScan(schema=schema)
    u = Union(inputs=(UnionInput(child=leaf1), UnionInput(child=leaf2)),
              schema=schema, num_partitions=1)
    assert len(plan_children(u)) == 2
    assert len(list(walk(u))) == 3


def test_transform_up():
    plan = make_plan().plan
    # rewrite every column named "uname" to "u2"
    def rw(n):
        if isinstance(n, Column) and n.name == "uname":
            return Column(name="u2")
        return n
    plan2 = plan.transform_up(rw)
    cols = [n for p in walk(plan2) for n in _all_exprs(p) if isinstance(n, Column)]
    assert all(c.name != "uname" for c in cols)
    assert any(c.name == "u2" for c in cols)


def _all_exprs(node):
    """Every Node reachable from `node` (not descending into child plans)."""
    from auron_tpu.ir.plan import PlanNode
    out = []
    stack = [c for c in node.children_nodes() if not isinstance(c, PlanNode)]
    while stack:
        n = stack.pop()
        out.append(n)
        stack.extend(c for c in n.children_nodes() if not isinstance(c, PlanNode))
    return out


def test_transform_up_nested_tuples():
    # Expand.projections is a tuple-of-tuples: transform_up must reach inside
    from auron_tpu.ir.plan import Expand
    e = Expand(child=ParquetScan(schema=make_schema()),
               projections=((col("a"), lit(1)), (col("a"), lit(2))))
    e2 = e.transform_up(lambda n: Column(name="b")
                        if isinstance(n, Column) and n.name == "a" else n)
    assert all(p[0].name == "b" for p in e2.projections)


def test_binary_envelope_codecs():
    td = make_plan()
    for codec in ("zstd", "zlib", "raw"):
        data = serde.serialize(td, codec=codec)
        assert serde.deserialize(data) == td
