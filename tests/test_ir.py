"""IR construction + serde roundtrip tests (golden-file style)."""

import math

from auron_tpu.ir import serde
from auron_tpu.ir.expr import (
    AggExpr, BinaryExpr, Case, Cast, Column, InList, IsNull, Like, Literal,
    ScalarFunctionCall, ScAnd, SortExpr, WhenThen, col, lit,
)
from auron_tpu.ir.plan import (
    Agg, BroadcastJoin, FileGroup, Filter, JoinOn, Limit, ParquetScan,
    Partitioning, Projection, ShuffleWriter, Sort, TaskDefinition, Union,
    UnionInput, plan_children, walk,
)
from auron_tpu.ir.schema import DataType, Field, Schema


def make_schema():
    return Schema.of(
        Field("id", DataType.int64(), nullable=False),
        Field("name", DataType.string()),
        Field("price", DataType.decimal(12, 2)),
        Field("ts", DataType.timestamp_us()),
        Field("tags", DataType.list_(DataType.string())),
    )


def make_plan():
    schema = make_schema()
    scan = ParquetScan(schema=schema, file_groups=(FileGroup(paths=("/tmp/x.parquet",)),),
                       projection=(0, 1, 2))
    filt = Filter(child=scan, predicates=(
        ScAnd(left=BinaryExpr(left=col("id"), op=">", right=lit(10)),
              right=Like(child=col("name"), pattern=lit("a%"))),
    ))
    proj = Projection(child=filt,
                      exprs=(col("id"),
                             ScalarFunctionCall(name="upper", args=(col("name"),),
                                                return_type=DataType.string())),
                      names=("id", "uname"))
    agg = Agg(child=proj, exec_mode="partial", grouping=(col("uname"),),
              grouping_names=("uname",),
              aggs=(AggExpr(fn="sum", children=(col("id"),),
                            return_type=DataType.int64()),),
              agg_names=("sum_id",))
    sw = ShuffleWriter(child=agg,
                       partitioning=Partitioning(mode="hash", num_partitions=8,
                                                 expressions=(col("uname"),)))
    return TaskDefinition(plan=sw, stage_id=3, partition_id=1, num_partitions=8)


def test_schema_basics():
    s = make_schema()
    assert len(s) == 5
    assert s.index_of("NAME") == 1  # case-insensitive default
    assert s.field("price").dtype.is_decimal
    assert repr(s.field("tags").dtype) == "list<string>"


def test_serde_roundtrip():
    td = make_plan()
    td2 = serde.roundtrip(td)
    assert td2 == td
    # JSON stability: canonical form equal after double roundtrip
    assert serde.to_json(td2) == serde.to_json(td)


def test_serde_special_floats():
    e = InList(child=col("x"), values=(lit(float("nan")), lit(float("inf")), lit(1.5)))
    e2 = serde.roundtrip(e)
    assert math.isnan(e2.values[0].value)
    assert math.isinf(e2.values[1].value)
    assert e2.values[2].value == 1.5


def test_serde_bytes_and_case():
    e = Case(branches=(WhenThen(when=IsNull(child=col("a")), then=lit(0)),),
             else_expr=Cast(child=col("a"), dtype=DataType.int64()))
    assert serde.roundtrip(e) == e


def test_walk_and_children():
    td = make_plan()
    kinds = [p.kind for p in walk(td.plan)]
    assert kinds == ["shuffle_writer", "agg", "projection", "filter", "parquet_scan"]


def test_union_walk_through_wrappers():
    schema = Schema.of(Field("a", DataType.int32()))
    leaf1 = ParquetScan(schema=schema)
    leaf2 = ParquetScan(schema=schema)
    u = Union(inputs=(UnionInput(child=leaf1), UnionInput(child=leaf2)),
              schema=schema, num_partitions=1)
    assert len(plan_children(u)) == 2
    assert len(list(walk(u))) == 3


def test_transform_up():
    plan = make_plan().plan
    # rewrite every column named "uname" to "u2"
    def rw(n):
        if isinstance(n, Column) and n.name == "uname":
            return Column(name="u2")
        return n
    plan2 = plan.transform_up(rw)
    cols = [n for p in walk(plan2) for n in _all_exprs(p) if isinstance(n, Column)]
    assert all(c.name != "uname" for c in cols)
    assert any(c.name == "u2" for c in cols)


def _all_exprs(node):
    """Every Node reachable from `node` (not descending into child plans)."""
    from auron_tpu.ir.plan import PlanNode
    out = []
    stack = [c for c in node.children_nodes() if not isinstance(c, PlanNode)]
    while stack:
        n = stack.pop()
        out.append(n)
        stack.extend(c for c in n.children_nodes() if not isinstance(c, PlanNode))
    return out


def test_transform_up_nested_tuples():
    # Expand.projections is a tuple-of-tuples: transform_up must reach inside
    from auron_tpu.ir.plan import Expand
    e = Expand(child=ParquetScan(schema=make_schema()),
               projections=((col("a"), lit(1)), (col("a"), lit(2))))
    e2 = e.transform_up(lambda n: Column(name="b")
                        if isinstance(n, Column) and n.name == "a" else n)
    assert all(p[0].name == "b" for p in e2.projections)


def test_binary_envelope_codecs():
    td = make_plan()
    for codec in ("zstd", "zlib", "raw"):
        data = serde.serialize(td, codec=codec)
        assert serde.deserialize(data) == td


# ---------------------------------------------------------------------------
# value-tag decoding (nan / ±inf / bytes) — ir/node.py:_decode
# ---------------------------------------------------------------------------

def test_decode_value_tags_explicit():
    import pytest
    from auron_tpu.ir.node import _decode, _encode

    assert math.isnan(_decode({"@float": "nan"}))
    assert _decode({"@float": "inf"}) == float("inf")
    assert _decode({"@float": "-inf"}) == float("-inf")
    assert _decode({"@bytes": "AAEC"}) == b"\x00\x01\x02"
    # encode->decode closes over every special value
    for v in (float("nan"), float("inf"), float("-inf"), -0.0, 1.5,
              b"\xff\x00raw"):
        got = _decode(_encode(v))
        if isinstance(v, float) and math.isnan(v):
            assert math.isnan(got)
        else:
            assert got == v
    # a corrupt tag must raise, not silently decode to nan
    with pytest.raises(ValueError):
        _decode({"@float": "Inf"})
    with pytest.raises(ValueError):
        _decode({"@float": "1e999"})


def test_serde_negative_infinity_literal():
    e = lit(float("-inf"))
    e2 = serde.roundtrip(e)
    assert e2.value == float("-inf") and e2.value < 0


# ---------------------------------------------------------------------------
# registry-wide serde coverage: every @register-ed kind round-trips, both
# default-constructed and with representative field values; a new kind
# without a rich sample fails this test loudly.
# ---------------------------------------------------------------------------

import pytest as _pytest

from auron_tpu.ir import expr as E
from auron_tpu.ir import plan as P
from auron_tpu.ir.node import _REGISTRY


def _rich_samples():
    i64, f64, s = DataType.int64(), DataType.float64(), DataType.string()
    c = col("a")
    wt = WhenThen(when=IsNull(child=c), then=lit(1))
    scan = ParquetScan(schema=make_schema(),
                       file_groups=(FileGroup(paths=("/tmp/a", "/tmp/b"),
                                              ranges=((0, 10), (10, 20))),),
                       projection=(0, 2), predicate=BinaryExpr(
                           left=c, op=">", right=lit(1)))
    part = Partitioning(mode="hash", num_partitions=8, expressions=(c,))
    jon = JoinOn(left_keys=(c,), right_keys=(col("b"),))
    wfc = P.WindowFuncCall(fn="row_number", return_type=i64, name="rn")
    return {
        "column": c,
        "bound_reference": E.BoundReference(index=2),
        "literal": lit(-0.5),
        "binary": BinaryExpr(left=c, op="%", right=lit(3)),
        "is_null": IsNull(child=c),
        "is_not_null": E.IsNotNull(child=c),
        "not": E.Not(child=IsNull(child=c)),
        "negative": E.Negative(child=c),
        "cast": Cast(child=c, dtype=DataType.decimal(12, 2)),
        "try_cast": E.TryCast(child=c, dtype=i64),
        "when_then": wt,
        "case": Case(branches=(wt,), else_expr=lit(0)),
        "in_list": InList(child=c, values=(lit(float("nan")),
                                           lit(float("-inf"))),
                          negated=True),
        "scalar_function": ScalarFunctionCall(name="upper", args=(c,),
                                              return_type=s),
        "like": Like(child=c, pattern=lit("a%"), negated=True,
                     case_insensitive=True),
        "sc_and": ScAnd(left=IsNull(child=c), right=lit(True)),
        "sc_or": E.ScOr(left=lit(False), right=IsNull(child=c)),
        "sort_expr": SortExpr(child=c, asc=False, nulls_first=False),
        "agg_expr": AggExpr(fn="sum", children=(c,), return_type=i64,
                            distinct=True, udaf=b"\x80pickle"),
        "py_udf_wrapper": E.PyUdfWrapper(serialized=b"\x00blob", args=(c,),
                                         return_type=f64, name="f"),
        "wire_udf": E.WireUdf(name="w", params=("x",),
                              body=BinaryExpr(left=col("x"), op="*",
                                              right=lit(2)),
                              args=(c,)),
        "wire_udaf": E.WireUdaf(name="wavg", params=("x",),
                                slot_names=("s", "n"),
                                slot_ops=("sum", "count"),
                                slot_types=(f64, i64),
                                updates=(col("x"), lit(1)),
                                finalize=BinaryExpr(left=col("s"), op="/",
                                                    right=col("n"))),
        "wire_udtf": E.WireUdtf(name="wt", params=("x",),
                                rows=((col("x"), lit(1)),
                                      (col("x"), lit(2))),
                                whens=(None, IsNull(child=col("x")))),
        "scalar_subquery": E.ScalarSubqueryWrapper(value=1.5, dtype=f64),
        "get_indexed_field": E.GetIndexedField(child=c, ordinal="f0"),
        "get_map_value": E.GetMapValue(child=c, key="k"),
        "named_struct": E.NamedStruct(names=("x", "y"), values=(c, lit(1)),
                                      return_type=DataType.struct(
                                          (Field("x", i64),
                                           Field("y", i64)))),
        "string_starts_with": E.StringStartsWith(child=c, prefix="p"),
        "string_ends_with": E.StringEndsWith(child=c, suffix="s"),
        "string_contains": E.StringContains(child=c, infix="i"),
        "row_num": E.RowNum(),
        "partition_id": E.SparkPartitionId(),
        "monotonically_increasing_id": E.MonotonicallyIncreasingId(),
        "bloom_filter_might_contain": E.BloomFilterMightContain(
            bloom_filter=col("bf"), value=c),
        # plan nodes ------------------------------------------------------
        "partitioning": part,
        "file_group": FileGroup(paths=("/x",), ranges=((1, 2),)),
        "parquet_scan": scan,
        "orc_scan": P.OrcScan(schema=make_schema(), projection=(1,),
                              positional_evolution=True),
        "kafka_scan": P.KafkaScan(schema=make_schema(), topic="t",
                                  assignment_json='{"partitions":[]}',
                                  value_format="json",
                                  bootstrap_servers="h:9092",
                                  mock_data=(1, "x")),
        "ipc_reader": P.IpcReader(schema=make_schema(), resource_id="r"),
        "ffi_reader": P.FFIReader(schema=make_schema(), resource_id="r"),
        "empty_partitions": P.EmptyPartitions(schema=make_schema(),
                                              num_partitions=3),
        "projection": Projection(child=scan, exprs=(c,), names=("a",)),
        "filter": Filter(child=scan, predicates=(IsNull(child=c),)),
        "sort": Sort(child=scan, sort_exprs=(SortExpr(child=c),),
                     fetch_limit=10, fetch_offset=2),
        "limit": Limit(child=scan, limit=5, offset=1),
        "agg": Agg(child=scan, exec_mode="partial", grouping=(c,),
                   grouping_names=("a",),
                   aggs=(AggExpr(fn="avg", children=(col("id"),),
                                 return_type=DataType.float64()),),
                   agg_names=("avg_id",),
                   supports_partial_skipping=True),
        "expand": P.Expand(child=scan, projections=((c, lit(1)),
                                                    (c, lit(2))),
                           names=("a", "g"), types=(i64, i64)),
        "window_group_limit": P.WindowGroupLimit(k=3, rank_fn="rank"),
        "window_func_call": wfc,
        "window": P.Window(child=scan, window_funcs=(wfc,),
                           partition_by=(c,),
                           order_by=(SortExpr(child=c),),
                           group_limit=P.WindowGroupLimit(k=2)),
        "generate": P.Generate(child=scan, generator="explode", args=(c,),
                               generator_output_names=("g",),
                               generator_output_types=(s,),
                               required_child_output=(0, 1), outer=True,
                               udtf=b"\x80gen"),
        "rename_columns": P.RenameColumns(child=scan,
                                          names=("a", "b", "c")),
        "coalesce_batches": P.CoalesceBatches(child=scan,
                                              target_batch_size=8192),
        "debug": P.Debug(child=scan, debug_id="d1"),
        "join_on": jon,
        "sort_merge_join": P.SortMergeJoin(left=scan, right=scan, on=jon,
                                           join_type="left",
                                           sort_options=((True, False),)),
        "hash_join": P.HashJoin(left=scan, right=scan, on=jon,
                                join_type="inner", build_side="left"),
        "broadcast_join_build_hash_map": P.BroadcastJoinBuildHashMap(
            child=scan, keys=(c,), cache_id="bhm1"),
        "broadcast_join": BroadcastJoin(left=scan, right=scan, on=jon,
                                        join_type="existence",
                                        broadcast_side="right",
                                        cached_build_hash_map_id="bhm1",
                                        existence_output_name="ex"),
        "union_input": UnionInput(child=scan, partition=1,
                                  out_partition=2),
        "union": Union(inputs=(UnionInput(child=scan),),
                       schema=make_schema(), num_partitions=4,
                       cur_partition=1),
        "shuffle_writer": ShuffleWriter(child=scan, partitioning=part,
                                        output_data_file="/tmp/d",
                                        output_index_file="/tmp/i"),
        "rss_shuffle_writer": P.RssShuffleWriter(child=scan,
                                                 partitioning=part,
                                                 rss_resource_id="rss1"),
        "ipc_writer": P.IpcWriter(child=scan, resource_id="r2"),
        "parquet_sink": P.ParquetSink(child=scan, output_dir="/tmp/o",
                                      partition_cols=("a",),
                                      compression="zstd",
                                      props=(("k", "v"),)),
        "orc_sink": P.OrcSink(child=scan, output_dir="/tmp/o",
                              partition_cols=("a",), compression="zlib"),
        "task_definition": make_plan(),
        # pipeline-fragment fusion (runtime/fusion.py) ---------------------
        "fragment_input": P.FragmentInput(schema=make_schema()),
        "fused_fragment": P.FusedFragment(
            child=scan,
            body=Projection(
                child=Filter(child=P.FragmentInput(schema=make_schema()),
                             predicates=(IsNull(child=c),)),
                exprs=(c,), names=("a",)),
            schema=Schema((Field("a", i64),))),
    }


def test_registry_rich_samples_cover_every_kind():
    """Adding an IR node kind without extending _rich_samples fails HERE,
    loudly, instead of silently shipping an untested serde surface."""
    missing = set(_REGISTRY) - set(_rich_samples())
    extra = set(_rich_samples()) - set(_REGISTRY)
    assert not missing, f"kinds without a serde sample: {sorted(missing)}"
    assert not extra, f"samples for unknown kinds: {sorted(extra)}"


@_pytest.mark.parametrize("kind", sorted(_REGISTRY))
def test_registry_serde_roundtrip(kind):
    cls = _REGISTRY[kind]
    # default construction: every field has a safe default
    node = cls()
    assert serde.from_json(serde.to_json(node)) == node
    # representative values: JSON-stable double roundtrip
    rich = _rich_samples().get(kind)
    if rich is not None:
        j = serde.to_json(rich)
        back = serde.from_json(j)
        assert serde.to_json(back) == j
        assert type(back) is cls


# ---------------------------------------------------------------------------
# iterative traversal: deep plans must not hit the recursion limit
# ---------------------------------------------------------------------------

def test_walk_deep_plan_iterative():
    import sys
    depth = sys.getrecursionlimit() * 3
    node = ParquetScan(schema=make_schema())
    for _ in range(depth):
        node = Filter(child=node, predicates=())
    assert sum(1 for _ in walk(node)) == depth + 1
    assert len(plan_children(node)) == 1


def test_serde_decimal_literal():
    # found by the serde-roundtrip analyzer pass: Decimal literal values
    # (p>18 hybrid plans) had no JSON encoding at all
    from decimal import Decimal
    e = lit(Decimal("100000000000000000001.000042"),
            DataType.decimal(27, 6))
    e2 = serde.from_json(serde.to_json(e))
    assert e2 == e and isinstance(e2.value, Decimal)
