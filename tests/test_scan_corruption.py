"""`auron.ignore.corrupted.files` coverage (PR 2 follow-up): the knob
has been wired at ops/scan/parquet.py and ops/scan/orc.py since the
fault harness landed, but no test ever fed either reader a broken file.
Both readers, both polarities, two corruption shapes (truncated valid
file, arbitrary garbage bytes), plus the good-files-still-read contract
and multi-file groups where only the middle file is bad."""

import os

import pyarrow as pa
import pyarrow.orc as orc
import pyarrow.parquet as pq
import pytest

from auron_tpu.config import conf
from auron_tpu.ir import plan as P
from auron_tpu.ir.schema import from_arrow_schema
from auron_tpu.runtime.executor import execute_plan

ROWS = [{"id": i, "name": f"r{i}"} for i in range(100)]


def _write_good(path: str, fmt: str) -> None:
    table = pa.Table.from_pylist(ROWS)
    if fmt == "parquet":
        pq.write_table(table, path)
    else:
        orc.write_table(table, path)


def _truncate(src: str, dst: str) -> None:
    with open(src, "rb") as f:
        blob = f.read()
    with open(dst, "wb") as f:
        f.write(blob[: len(blob) // 3])   # footer gone: unreadable


def _garbage(dst: str) -> None:
    with open(dst, "wb") as f:
        f.write(b"\x00\xff not a columnar file \x13\x37" * 64)


def _scan_plan(fmt: str, paths, schema) -> P.PlanNode:
    group = P.FileGroup(paths=tuple(paths))
    if fmt == "parquet":
        return P.ParquetScan(schema=schema, file_groups=(group,))
    return P.OrcScan(schema=schema, file_groups=(group,))


@pytest.fixture(params=["parquet", "orc"])
def corpus(request, tmp_path):
    fmt = request.param
    good = str(tmp_path / f"good.{fmt}")
    good2 = str(tmp_path / f"good2.{fmt}")
    truncated = str(tmp_path / f"trunc.{fmt}")
    garbage = str(tmp_path / f"garbage.{fmt}")
    _write_good(good, fmt)
    _write_good(good2, fmt)
    _truncate(good, truncated)
    _garbage(garbage)
    schema = from_arrow_schema(pa.Table.from_pylist(ROWS).schema)
    return fmt, schema, good, good2, truncated, garbage


def test_corrupted_file_raises_by_default(corpus):
    fmt, schema, good, _good2, truncated, garbage = corpus
    for bad in (truncated, garbage):
        with pytest.raises(Exception):
            execute_plan(_scan_plan(fmt, [bad], schema))


def test_corrupted_file_skipped_when_ignored(corpus):
    """With the knob on, broken files are skipped and the good files in
    the same group still stream — including a bad file in the MIDDLE of
    the group (the skip must continue, not abort the loop)."""
    fmt, schema, good, good2, truncated, garbage = corpus
    with conf.scoped({"auron.ignore.corrupted.files": True}):
        # bad-only group: empty result, no error
        res = execute_plan(_scan_plan(fmt, [truncated, garbage], schema))
        assert res.to_table().num_rows == 0
        # good + bad + good: both good files' rows survive
        res = execute_plan(
            _scan_plan(fmt, [good, garbage, good2], schema))
        table = res.to_table()
        assert table.num_rows == 2 * len(ROWS)
        ids = sorted(table.column("id").to_pylist())
        assert ids == sorted(r["id"] for r in ROWS for _ in range(2))


def test_corrupted_file_off_fails_even_with_good_neighbors(corpus):
    fmt, schema, good, _good2, _truncated, garbage = corpus
    with conf.scoped({"auron.ignore.corrupted.files": False}):
        with pytest.raises(Exception):
            execute_plan(_scan_plan(fmt, [good, garbage], schema))


def test_missing_file_respects_ignore_knob(corpus):
    """A vanished split is operationally the same failure class as a
    corrupt one: skipped when ignoring, raised otherwise."""
    fmt, schema, good, _g2, _t, _g = corpus
    missing = os.path.join(os.path.dirname(good), f"gone.{fmt}")
    with conf.scoped({"auron.ignore.corrupted.files": True}):
        res = execute_plan(_scan_plan(fmt, [missing, good], schema))
        assert res.to_table().num_rows == len(ROWS)
    with pytest.raises(Exception):
        execute_plan(_scan_plan(fmt, [missing, good], schema))
