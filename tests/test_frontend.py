"""Front-end tests: foreign-plan conversion strategy + session execution.

The differential pattern mirrors AuronQueryTest.checkSparkAnswerAndOperator
(AuronQueryTest.scala:29-91): run the plan once with auron.enable=false
through the toy foreign engine (the oracle), once through the session, and
assert (a) identical results, (b) that every operator went native.
"""

import pickle

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu import config
from auron_tpu.frontend import (AuronSession, ForeignExpr, ForeignNode,
                                falias, fcall, fcol, flit)
from auron_tpu.frontend import strategy
from auron_tpu.frontend.converters import ForeignWrap
from auron_tpu.ir.schema import DataType, Field, Schema

I64 = DataType.int64()
F64 = DataType.float64()
STR = DataType.string()


# ---------------------------------------------------------------------------
# toy foreign engine: executes the ops our tests leave non-native
# ---------------------------------------------------------------------------

class ToyEngine:
    """Pandas-ish oracle over foreign nodes (the role Spark plays)."""

    def execute(self, node: ForeignNode, child_tables):
        op = node.op
        if op == "LocalTableScanExec":
            import auron_tpu.ir.schema as S
            return pa.Table.from_pylist(
                node.attrs.get("rows", []),
                schema=S.to_arrow_schema(node.output))
        if op == "OpaqueRowOpExec":
            # an op the converter can never claim: multiplies column
            # `target` by 3 on the host
            t = child_tables[0]
            target = node.attrs["target"]
            col = pa.compute.multiply(t[target], 3)
            return t.set_column(t.schema.get_field_index(target), target,
                                col)
        raise NotImplementedError(f"toy engine cannot run {op}")


def local_table(rows, schema: Schema) -> ForeignNode:
    return ForeignNode("LocalTableScanExec", output=schema,
                       attrs={"rows": rows})


def canon(rows):
    def norm(v):
        if isinstance(v, float):
            return round(v, 9)
        return v
    return sorted([tuple((k, v is None, str(norm(v)))
                         for k, v in sorted(r.items())) for r in rows])


def check(plan: ForeignNode, expect_all_native=True):
    """Differential: session vs foreign-only oracle."""
    session = AuronSession(foreign_engine=ToyEngine())
    res = session.execute(plan)
    with config.conf.scoped({"auron.enable": False}):
        oracle_session = AuronSession(foreign_engine=_OracleEngine())
        oracle = oracle_session.execute(plan)
    assert canon(res.to_pylist()) == canon(oracle.to_pylist())
    if expect_all_native:
        assert res.all_native(), \
            f"plan has foreign sections: {type(res.converted)}"
    return res


class _OracleEngine(ToyEngine):
    """Full-plan oracle: interprets every foreign op via the IR reference
    interpreter by round-tripping through conversion with all gates off."""

    def execute(self, node: ForeignNode, child_tables):
        try:
            return super().execute(node, child_tables)
        except NotImplementedError:
            pass
        import reference_engine
        from auron_tpu.frontend import converters
        from auron_tpu.frontend.expr_convert import NotConvertible
        from auron_tpu.ir import plan as P
        from auron_tpu.ir.schema import from_arrow_schema
        from auron_tpu.runtime.resources import ResourceRegistry
        # convert this single node with FFI readers over child tables
        ctx = converters.ConvertContext()
        res = ResourceRegistry()
        children = []
        for i, t in enumerate(child_tables):
            rid = f"oracle:{i}"
            res.put(rid, t)
            ph = P.FFIReader(schema=from_arrow_schema(t.schema),
                             resource_id=rid)
            children.append(ctx.set_parts(ph, 1))
        if node.op == "ShuffleExchangeExec":
            return child_tables[0]  # exchange is an identity over rows
        if node.op == "BroadcastExchangeExec":
            return child_tables[0]
        native = converters.convert_node(node, children, ctx)
        rows = reference_engine.run_plan(native, res, partition_id=0)
        import auron_tpu.ir.schema as S
        try:
            from auron_tpu.runtime.planner import PhysicalPlanner
            schema = S.to_arrow_schema(
                PhysicalPlanner().create_plan(native).schema)
            return pa.Table.from_pylist(rows, schema=schema)
        except Exception:
            return pa.Table.from_pylist(rows)


# ---------------------------------------------------------------------------
# data
# ---------------------------------------------------------------------------

def sales_rows(n=500, seed=3):
    rng = np.random.default_rng(seed)
    return [{"k": int(rng.integers(0, 12)),
             "v": float(np.round(rng.normal(50, 20), 3)),
             "s": ["red", "green", "blue"][int(rng.integers(0, 3))]}
            for _ in range(n)]


SALES = Schema((Field("k", I64), Field("v", F64), Field("s", STR)))


# ---------------------------------------------------------------------------
# tests
# ---------------------------------------------------------------------------

def test_foreign_plan_json_roundtrip():
    plan = ForeignNode(
        "ProjectExec",
        children=(local_table(sales_rows(5), SALES),),
        output=Schema((Field("k2", I64),)),
        attrs={"project_list": [
            falias(fcall("Add", fcol("k", I64), flit(1)), "k2")]})
    back = ForeignNode.from_json(plan.to_json())
    assert back.op == "ProjectExec"
    assert back.attrs["project_list"][0].name == "Alias"
    assert back.output.names() == ("k2",)
    assert back.children[0].attrs["rows"][:2] == sales_rows(5)[:2]


def test_project_filter_native():
    src = local_table(sales_rows(), SALES)
    filt = ForeignNode(
        "FilterExec", children=(src,), output=SALES,
        attrs={"condition": fcall(
            "And",
            fcall("GreaterThan", fcol("v", F64), flit(30.0)),
            fcall("IsNotNull", fcol("s", STR)))})
    proj = ForeignNode(
        "ProjectExec", children=(filt,),
        output=Schema((Field("k", I64), Field("v2", F64))),
        attrs={"project_list": [
            fcol("k", I64),
            falias(fcall("Multiply", fcol("v", F64), flit(2.0)), "v2")]})
    res = check(proj)
    assert len(res.to_pylist()) > 0


def test_sort_limit_native():
    src = local_table(sales_rows(), SALES)
    sort = ForeignNode(
        "SortExec", children=(src,), output=SALES,
        attrs={"sort_order": [
            ForeignExpr("SortOrder", children=(fcol("v", F64),),
                        attrs={"asc": False, "nulls_first": False})]})
    lim = ForeignNode("GlobalLimitExec", children=(sort,), output=SALES,
                      attrs={"limit": 7})
    res = check(lim)
    got = [r["v"] for r in res.to_pylist()]
    assert got == sorted(got, reverse=True) and len(got) == 7


def test_partial_shuffle_final_agg():
    """The canonical two-phase agg: partial -> hash exchange -> final
    (the shape every TPC-DS group-by stage takes)."""
    src = local_table(sales_rows(800), SALES)
    agg_exprs = [
        ForeignExpr("AggregateExpression",
                    children=(fcall("Sum", fcol("v", F64), dtype=F64),)),
        ForeignExpr("AggregateExpression",
                    children=(fcall("Count", fcol("v", F64), dtype=I64),)),
        ForeignExpr("AggregateExpression",
                    children=(fcall("Average", fcol("v", F64), dtype=F64),)),
    ]
    partial = ForeignNode(
        "HashAggregateExec", children=(src,),
        output=Schema((Field("k", I64), Field("sv#sum", F64),
                       Field("cv#count", I64), Field("av#sum", F64),
                       Field("av#count", I64))),
        attrs={"grouping": [fcol("k", I64)], "aggs": agg_exprs,
               "agg_names": ["sv", "cv", "av"], "mode": "partial"})
    exchange = ForeignNode(
        "ShuffleExchangeExec", children=(partial,), output=partial.output,
        attrs={"partitioning": {
            "mode": "hash", "num_partitions": 4,
            "expressions": [fcol("k", I64)]}})
    final = ForeignNode(
        "HashAggregateExec", children=(exchange,),
        output=Schema((Field("k", I64), Field("sv", F64), Field("cv", I64),
                       Field("av", F64))),
        attrs={"grouping": [fcol("k", I64)], "aggs": agg_exprs,
               "agg_names": ["sv", "cv", "av"], "mode": "final"})
    session = AuronSession(foreign_engine=ToyEngine())
    res = session.execute(final)
    rows = {r["k"]: r for r in res.to_pylist()}
    # direct oracle
    import collections
    agg = collections.defaultdict(list)
    for r in sales_rows(800):
        agg[r["k"]].append(r["v"])
    assert set(rows) == set(agg)
    for k, vs in agg.items():
        assert rows[k]["cv"] == len(vs)
        assert abs(rows[k]["sv"] - sum(vs)) < 1e-6
        assert abs(rows[k]["av"] - sum(vs) / len(vs)) < 1e-9
    assert res.all_native()


def test_broadcast_hash_join():
    dim_schema = Schema((Field("k", I64), Field("name", STR)))
    dim = local_table([{"k": i, "name": f"cat{i}"} for i in range(12)],
                      dim_schema)
    bx = ForeignNode("BroadcastExchangeExec", children=(dim,),
                     output=dim_schema)
    fact = local_table(sales_rows(300), SALES)
    join = ForeignNode(
        "BroadcastHashJoinExec", children=(fact, bx),
        output=SALES.concat(dim_schema),
        attrs={"left_keys": [fcol("k", I64)],
               "right_keys": [fcol("k", I64)],
               "join_type": "Inner", "build_side": "right"})
    session = AuronSession(foreign_engine=ToyEngine())
    res = session.execute(join)
    rows = res.to_pylist()
    assert len(rows) == 300
    assert all(r["name"] == f"cat{r['k']}" for r in rows)
    assert res.all_native()


def test_sort_merge_join_via_exchanges():
    left = local_table(sales_rows(200, seed=1), SALES)
    right_schema = Schema((Field("k", I64), Field("w", F64)))
    right = local_table(
        [{"k": i % 12, "w": float(i)} for i in range(24)], right_schema)

    def exchange(child, keys_schema):
        return ForeignNode(
            "ShuffleExchangeExec", children=(child,), output=child.output,
            attrs={"partitioning": {
                "mode": "hash", "num_partitions": 3,
                "expressions": [fcol("k", I64)]}})

    join = ForeignNode(
        "SortMergeJoinExec",
        children=(exchange(left, SALES), exchange(right, right_schema)),
        output=SALES.concat(right_schema),
        attrs={"left_keys": [fcol("k", I64)],
               "right_keys": [fcol("k", I64)], "join_type": "Inner"})
    session = AuronSession(foreign_engine=ToyEngine())
    res = session.execute(join)
    rows = res.to_pylist()
    assert len(rows) == 200 * 2  # each k in 0..11 appears twice in right
    assert res.all_native()


def test_mixed_plan_foreign_section():
    """An inconvertible op in the middle: N2C under it, C2N above it."""
    src = local_table(sales_rows(100), SALES)
    proj = ForeignNode(
        "ProjectExec", children=(src,), output=SALES,
        attrs={"project_list": [fcol("k", I64), fcol("v", F64),
                                fcol("s", STR)]})
    opaque = ForeignNode("OpaqueRowOpExec", children=(proj,), output=SALES,
                         attrs={"target": "v"})
    # strategy's anti-thrash rule: a lone filter over a non-native child
    # stays foreign; use sort (AlwaysConvert even over non-native child)
    sort = ForeignNode(
        "SortExec", children=(opaque,), output=SALES,
        attrs={"sort_order": [
            ForeignExpr("SortOrder", children=(fcol("v", F64),))]})
    session = AuronSession(foreign_engine=ToyEngine())
    res = session.execute(sort)
    rows = res.to_pylist()
    expect = sorted((r["v"] * 3 for r in sales_rows(100)))
    got = [r["v"] for r in rows]
    assert np.allclose(got, expect)
    assert not res.all_native()


def test_strategy_inefficient_filter_stays_foreign():
    """removeInefficientConverts: Filter over a never-convert child is
    demoted (AuronConvertStrategy.scala:214-222)."""
    src = local_table(sales_rows(50), SALES)
    opaque = ForeignNode("OpaqueRowOpExec", children=(src,), output=SALES,
                         attrs={"target": "v"})
    filt = ForeignNode(
        "FilterExec", children=(opaque,), output=SALES,
        attrs={"condition": fcall("GreaterThan", fcol("v", F64),
                                  flit(0.0))})
    tags = strategy.apply(filt)
    assert tags.is_never_convert(filt)
    assert "not native" in tags.reason(filt)


def _weird_udf(k):
    # row-wise evaluation (host_eval's UDF contract)
    return int(k) * 2 + 1


def test_udf_fallback_expression():
    """Unconvertible expr w/ pickled evaluator -> PyUdfWrapper
    (SparkUDFWrapperExpr analogue)."""
    weird = _weird_udf
    src = local_table(sales_rows(60), SALES)
    proj = ForeignNode(
        "ProjectExec", children=(src,),
        output=Schema((Field("wk", I64),)),
        attrs={"project_list": [falias(
            ForeignExpr("MysteryUdf", children=(fcol("k", I64),),
                        dtype=I64, py_fn=pickle.dumps(weird)), "wk")]})
    session = AuronSession(foreign_engine=ToyEngine())
    res = session.execute(proj)
    rows = res.to_pylist()
    assert [r["wk"] for r in rows] == \
        [r["k"] * 2 + 1 for r in sales_rows(60)]
    assert res.all_native()


def test_master_switch_disables_conversion():
    src = local_table([{"k": 1, "v": 2.0, "s": "x"}], SALES)
    filt = ForeignNode(
        "FilterExec", children=(src,), output=SALES,
        attrs={"condition": fcall("GreaterThan", fcol("v", F64),
                                  flit(1.0))})
    with config.conf.scoped({"auron.enable": False}):
        res = AuronSession(foreign_engine=_OracleEngine()).execute(filt)
    assert res.to_pylist() == [{"k": 1, "v": 2.0, "s": "x"}]
    assert res.converted is None


def test_per_op_disable_switch():
    src = local_table(sales_rows(30), SALES)
    sort = ForeignNode(
        "SortExec", children=(src,), output=SALES,
        attrs={"sort_order": [
            ForeignExpr("SortOrder", children=(fcol("v", F64),))]})
    with config.conf.scoped({"auron.enable.sort": False}):
        tags = strategy.apply(sort)
        assert tags.is_never_convert(sort)
        assert "disabled by conf" in tags.reason(sort)


def test_expand_window_take_ordered():
    src = local_table(sales_rows(120), SALES)
    expand = ForeignNode(
        "ExpandExec", children=(src,),
        output=Schema((Field("k", I64), Field("v", F64), Field("g", I64))),
        attrs={"projections": [
            [fcol("k", I64), fcol("v", F64), flit(0)],
            [fcol("k", I64), fcol("v", F64), flit(1)]]})
    res = check(expand)
    assert len(res.to_pylist()) == 240

    win = ForeignNode(
        "WindowExec", children=(src,),
        output=SALES.concat(Schema((Field("rn", I64),))),
        attrs={"window_exprs": [
            {"name": "rn", "fn": "row_number", "dtype": I64}],
            "partition_spec": [fcol("k", I64)],
            "order_spec": [ForeignExpr("SortOrder",
                                       children=(fcol("v", F64),))]})
    res = check(win)
    by_k = {}
    for r in res.to_pylist():
        by_k.setdefault(r["k"], []).append(r)
    for rows in by_k.values():
        rows.sort(key=lambda r: r["rn"])
        vs = [r["v"] for r in rows]
        assert vs == sorted(vs)


def test_force_shuffled_hash_join_rewrites_smj():
    """auron.force.shuffled.hash.join converts planned SMJs into shuffled
    hash joins (ForceApplyShuffledHashJoinInjector analogue)."""
    from auron_tpu.ir import plan as P

    left = local_table(sales_rows(60, seed=2), SALES)
    right_schema = Schema((Field("k", I64), Field("w", F64)))
    right = local_table([{"k": i % 12, "w": float(i)} for i in range(12)],
                        right_schema)

    def exchange(child):
        return ForeignNode(
            "ShuffleExchangeExec", children=(child,), output=child.output,
            attrs={"partitioning": {"mode": "hash", "num_partitions": 2,
                                    "expressions": [fcol("k", I64)]}})

    join = ForeignNode(
        "SortMergeJoinExec", children=(exchange(left), exchange(right)),
        output=SALES.concat(right_schema),
        attrs={"left_keys": [fcol("k", I64)],
               "right_keys": [fcol("k", I64)], "join_type": "Inner"})
    with config.conf.scoped({"auron.force.shuffled.hash.join": True}):
        session = AuronSession(foreign_engine=ToyEngine())
        res = session.execute(join)
    assert isinstance(res.converted, P.HashJoin), type(res.converted)
    assert len(res.to_pylist()) == 60
    assert res.all_native()


def test_force_shj_falls_back_to_smj_when_shj_disabled():
    """Forced SHJ with the SHJ converter disabled must still convert the
    planned SMJ natively (prefer-when-legal semantics)."""
    from auron_tpu.ir import plan as P

    left = local_table(sales_rows(30, seed=4), SALES)
    right_schema = Schema((Field("k", I64), Field("w", F64)))
    right = local_table([{"k": i % 12, "w": float(i)} for i in range(12)],
                        right_schema)

    def exchange(child):
        return ForeignNode(
            "ShuffleExchangeExec", children=(child,), output=child.output,
            attrs={"partitioning": {"mode": "hash", "num_partitions": 2,
                                    "expressions": [fcol("k", I64)]}})

    join = ForeignNode(
        "SortMergeJoinExec", children=(exchange(left), exchange(right)),
        output=SALES.concat(right_schema),
        attrs={"left_keys": [fcol("k", I64)],
               "right_keys": [fcol("k", I64)], "join_type": "Inner"})
    with config.conf.scoped({"auron.force.shuffled.hash.join": True,
                             "auron.enable.shj": False}):
        session = AuronSession(foreign_engine=ToyEngine())
        res = session.execute(join)
    assert isinstance(res.converted, P.SortMergeJoin), type(res.converted)
    assert len(res.to_pylist()) == 30
    assert res.all_native()


def test_task_retry_model(monkeypatch):
    """A failed partition task re-executes (auron.task.retries): the
    scheduler-level retry the reference inherits from Spark.  Since the
    shared retry policy (runtime/retry.py) landed, only RETRYABLE
    failures (transient IO, device faults) replay — deterministic errors
    ferry immediately regardless of the budget."""
    import auron_tpu.frontend.session as sess_mod
    from auron_tpu.config import conf
    from auron_tpu.frontend.session import AuronSession
    from auron_tpu.it.oracle import PyArrowEngine

    real = sess_mod.execute_plan
    fails = {"n": 1}
    fault = {"type": ConnectionError}

    def flaky(plan, partition_id=0, num_partitions=1, resources=None):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise fault["type"]("injected transient task failure")
        return real(plan, partition_id=partition_id,
                    num_partitions=num_partitions, resources=resources)

    monkeypatch.setattr(sess_mod, "execute_plan", flaky)
    rows = [{"a": i, "b": float(i)} for i in range(50)]
    plan = ForeignNode(
        "LocalTableScanExec",
        output=Schema((Field("a", I64), Field("b", F64))),
        attrs={"rows": rows})
    # pin the serial walk: this tests the per-partition task retry loop,
    # which the SPMD stage path (default since round 4) bypasses
    with conf.scoped({"auron.task.retries": 1,
                      "auron.retry.backoff.base.ms": 1.0,
                      "auron.spmd.singleDevice.enable": False}):
        res = AuronSession(foreign_engine=PyArrowEngine()).execute(plan)
    assert res.table.num_rows == 50
    # with retries off the same failure propagates
    fails["n"] = 1
    with conf.scoped({"auron.task.retries": 0,
                      "auron.spmd.singleDevice.enable": False}):
        with pytest.raises(ConnectionError, match="injected"):
            AuronSession(foreign_engine=PyArrowEngine()).execute(plan)
    # a DETERMINISTIC failure never replays, even with budget to spare
    fails["n"] = 1
    fault["type"] = RuntimeError
    with conf.scoped({"auron.task.retries": 3,
                      "auron.spmd.singleDevice.enable": False}):
        with pytest.raises(RuntimeError, match="injected"):
            AuronSession(foreign_engine=PyArrowEngine()).execute(plan)
    assert fails["n"] == 0          # raised once, not retried


def test_insert_into_hive_table_conversion(tmp_path):
    """Hive insert glue (NativeParquetInsertIntoHiveTableBase analogue):
    the command converts to a native parquet sink at the table location,
    static partitions extend the path, dynamic partition columns drive a
    partitioned write."""
    import pyarrow.parquet as pq

    rows = [{"k": i % 3, "v": float(i)} for i in range(60)]
    schema = Schema((Field("k", I64), Field("v", F64)))
    scan = local_table(rows, schema)
    loc = str(tmp_path / "warehouse" / "t")
    insert = ForeignNode(
        "InsertIntoHiveTableExec", children=(scan,), output=schema,
        attrs={"storage": {"format": "hive.parquet", "location": loc},
               "static_partitions": {"ds": "2026-07-30"},
               "dynamic_partition_cols": ["k"]})
    session = AuronSession(foreign_engine=ToyEngine())
    res = session.execute(insert)
    assert res.all_native(), "hive insert did not convert"
    back = pq.read_table(loc + "/ds=2026-07-30")
    assert back.num_rows == 60
    # dynamic partition dirs exist (k=0/1/2 hive layout)
    import os
    subdirs = sorted(os.listdir(loc + "/ds=2026-07-30"))
    assert any(d.startswith("k=") for d in subdirs), subdirs


def test_single_device_conf_rides_stage_compiler():
    """auron.spmd.singleDevice.enable: a mesh-less session offers the
    plan to the stage compiler on a 1-device mesh (one compiled program),
    producing the same rows as the serial walk, and repeat executes hit
    the compiled-program cache."""
    from auron_tpu import conf

    src = local_table(sales_rows(500), SALES)
    agg_exprs = [
        ForeignExpr("AggregateExpression",
                    children=(fcall("Sum", fcol("v", F64), dtype=F64),))]
    partial = ForeignNode(
        "HashAggregateExec", children=(src,),
        output=Schema((Field("k", I64), Field("sv#sum", F64))),
        attrs={"grouping": [fcol("k", I64)], "aggs": agg_exprs,
               "agg_names": ["sv"], "mode": "partial"})
    exchange = ForeignNode(
        "ShuffleExchangeExec", children=(partial,), output=partial.output,
        attrs={"partitioning": {
            "mode": "hash", "num_partitions": 4,
            "expressions": [fcol("k", I64)]}})
    final = ForeignNode(
        "HashAggregateExec", children=(exchange,),
        output=Schema((Field("k", I64), Field("sv", F64))),
        attrs={"grouping": [fcol("k", I64)], "aggs": agg_exprs,
               "agg_names": ["sv"], "mode": "final"})

    # default ON since round 4: the stage path IS the engine path; the
    # serial walk is reached by disabling it
    with conf.scoped({"auron.spmd.singleDevice.enable": False}):
        serial = AuronSession(foreign_engine=ToyEngine()).execute(final)
    assert not serial.spmd
    from auron_tpu.parallel import stage as S
    session = AuronSession(foreign_engine=ToyEngine())
    staged = session.execute(final)
    assert staged.spmd
    n_programs = len(S._PROGRAM_CACHE)
    again = session.execute(final)
    # the re-converted plan must hit the compiled-program cache (rid
    # canonicalization) — a recompile would add a new entry
    assert again.spmd and len(S._PROGRAM_CACHE) == n_programs

    def canon(res):
        return sorted((r["k"], round(r["sv"], 6))
                      for r in res.to_pylist())
    assert canon(staged) == canon(serial) == canon(again)
