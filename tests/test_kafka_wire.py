"""Wire-protocol Kafka consumer tests: a real TCP broker (mock, speaking
the actual Kafka binary protocol) drives the client end-to-end — the
analogue of the reference's kafka CI workflow, without a container."""

import json

import pytest

from kafka_broker import MockKafkaBroker
from auron_tpu.streaming.kafka_client import (
    EARLIEST, KafkaRecord, KafkaWireClient, KafkaWireConsumer, crc32c,
    encode_record_batch, parse_fetch_response, parse_record_batches,
)


def rows_for(n, pid):
    return [(i, f"k{i}".encode(), json.dumps(
        {"id": pid * 1000 + i, "v": i * 0.5}).encode()) for i in range(n)]


def test_crc32c_vectors():
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8A9136AA
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"123456789") == 0xE3069283


def test_record_batch_roundtrip_and_truncation():
    rows = [(i, f"k{i}".encode(), f"v{i}".encode()) for i in range(5)]
    raw = encode_record_batch(10, rows)
    recs = list(parse_record_batches(raw, partition=0))
    assert [r.offset for r in recs] == [10, 11, 12, 13, 14]
    assert recs[0].key == b"k0" and recs[4].value == b"v4"
    # a truncated trailing batch (max_bytes cut) is ignored, not an error
    recs2 = list(parse_record_batches(raw + raw[:20], partition=0))
    assert len(recs2) == 5
    # corrupted payload trips the crc check
    bad = bytearray(raw)
    bad[-1] ^= 0xFF
    with pytest.raises(ValueError, match="crc32c"):
        list(parse_record_batches(bytes(bad), partition=0))


@pytest.mark.parametrize("codec_id", [0, 1, 4])  # none, gzip, zstd
def test_fetch_end_to_end(codec_id):
    broker = MockKafkaBroker(
        {"events": {0: rows_for(7, 0), 1: rows_for(4, 1)}},
        codec_id=codec_id).start()
    try:
        cli = KafkaWireClient(broker.address)
        leaders = cli.metadata("events")
        assert set(leaders) == {0, 1}
        addr = leaders[0]
        assert cli.list_offset(addr, "events", 0, EARLIEST) == 0
        recs, hwm, next_off = cli.fetch(addr, "events", 0, offset=0)
        assert hwm == 7 and [r.offset for r in recs] == list(range(7))
        assert next_off == 7
        # offset resume: fetch from 5
        recs2, _, _ = cli.fetch(addr, "events", 0, offset=5)
        assert [r.offset for r in recs2] == [5, 6]
        cli.close()
    finally:
        broker.stop()


def test_control_batches_advance_offset():
    """Transaction-marker control batches are skipped but still advance
    the consumer past their offsets (a bare skip would strand the drain
    loop behind the first marker)."""
    data = encode_record_batch(0, [(0, b"k0", b"v0")])
    marker = encode_record_batch(1, [(0, b"\x00\x00\x00\x00", b"")],
                                 control=True)
    after = encode_record_batch(2, [(0, b"k2", b"v2"), (1, b"k3", b"v3")])
    recs, next_off = parse_fetch_response(data + marker + after, 0)
    assert [r.offset for r in recs] == [0, 2, 3]
    assert next_off == 4


def test_kafka_scan_exec_wire_consumer():
    """KafkaScanExec with bootstrap_servers set consumes through the
    wire-protocol client and lands JSON rows as a device batch."""
    from auron_tpu.ir.schema import DataType, Field, Schema
    from auron_tpu.ops.base import TaskContext
    from auron_tpu.ops.scan.kafka import KafkaScanExec
    from auron_tpu.runtime.resources import ResourceRegistry

    broker = MockKafkaBroker(
        {"t1": {0: rows_for(6, 0), 1: rows_for(3, 1)}}).start()
    try:
        schema = Schema((Field("id", DataType.int64()),
                         Field("v", DataType.float64())))
        op = KafkaScanExec(schema, "t1", value_format="json",
                           bootstrap_servers=broker.address)
        ctx = TaskContext(resources=ResourceRegistry())
        out = [b.to_arrow() for b in op.execute(ctx)]
        rows = [r for rb in out for r in rb.to_pylist()]
        assert len(rows) == 9
        ids = sorted(r["id"] for r in rows)
        assert ids == [0, 1, 2, 3, 4, 5, 1000, 1001, 1002]
    finally:
        broker.stop()


def test_wire_consumer_assignment_offsets():
    """The front-end's partition/offset assignment bounds consumption
    (kafka_scan_exec.rs:243-247 contract)."""
    broker = MockKafkaBroker({"t2": {0: rows_for(10, 0)}}).start()
    try:
        consumer = KafkaWireConsumer(broker.address, "t2")
        vals = list(consumer({"partitions": {"0": 4},
                              "end_offsets": {"0": 8}}))
        ids = [json.loads(v)["id"] for v in vals]
        assert ids == [4, 5, 6, 7]
    finally:
        broker.stop()


def test_injected_fetch_fault_retries_with_fresh_correlation():
    """Injected io faults on the fetch path ride the shared retry
    policy; every replay allocates a fresh correlation id so responses
    can never cross-match."""
    from auron_tpu import faults
    from auron_tpu.config import conf

    broker = MockKafkaBroker({"tf": {0: rows_for(6, 0)}}).start()
    try:
        spec = ("kafka.fetch:io:p=1,max=1,seed=5;"
                "kafka.metadata:io:p=1,max=1,seed=6")
        faults.reset(spec)
        with conf.scoped({"auron.faults.spec": spec,
                          "auron.retry.backoff.base.ms": 1.0,
                          "auron.retry.max.attempts": 6}):
            cli = KafkaWireClient(broker.address)
            leaders = cli.metadata("tf")
            addr = leaders[0]
            records, hwm, _next = cli.fetch(addr, "tf", 0, 0)
            cli.close()
        assert [r.value for r in records] == \
            [value for _ts, _key, value in rows_for(6, 0)]
        assert faults.registry_for(spec).injected_total() > 0
    finally:
        broker.stop()
