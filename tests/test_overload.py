"""Overload-survival tests (the PR 10 layer):

- per-query memory ledger + budgets inside MemManager (consumers carry
  the ambient query tag, a query over `auron.memory.query.budget.bytes`
  spills its OWN memory even under a healthy pool, and is KILLED past
  the spill grace),
- the `query` spill-victim strategy (arbitration charges the most-over-
  budget query, not the global best-rate consumer),
- preemptive kill-and-requeue: `task_pool.preempt_query` -> the
  scheduler requeues the submission with its original conf overlay;
  preemption counters/trace events/QueryRecord.preemptions surface it,
- requeue-vs-retry accounting: QueryCancelled is deterministic — it
  never consumes an `auron.task.retries` budget and never carries the
  `auron_retry_exhausted` marker,
- priority aging (`auron.admission.aging.seconds`) so requeued and
  long-queued submissions cannot starve,
- `Retry-After` on shed / queue-timeout HTTP responses,
- THE acceptance stress: 10 concurrent fault-injected queries under a
  budget tight enough to force >= 1 preemption — every result
  bit-identical to its solo fault-free run, every reservation released,
  no leaked consumers, all driver threads joined.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pyarrow as pa
import pytest

from auron_tpu.config import conf
from auron_tpu.it.datagen import generate
from auron_tpu.memmgr import manager as mem_manager
from auron_tpu.memmgr.manager import MemConsumer, reset_manager
from auron_tpu.runtime import counters, task_pool, tracing
from auron_tpu.runtime.task_pool import QueryCancelled, run_tasks
from auron_tpu.serving import QueryScheduler, QueryServer, register_catalog

SF = 0.002


@pytest.fixture(scope="module")
def catalog(tmp_path_factory):
    cat = generate(str(tmp_path_factory.mktemp("overload_tpcds")), sf=SF,
                   fact_chunks=3)
    register_catalog(SF, cat)
    return cat


@pytest.fixture(autouse=True)
def _fresh_world():
    """Overload tests mutate process singletons; leave clean defaults
    behind (incl. the memmgr kill/pressure hooks)."""
    yield
    from auron_tpu import faults
    faults.reset()
    mem_manager.set_kill_hook(None)
    mem_manager.clear_pressure_hook()
    reset_manager()
    task_pool.reset_pool()


def _canon(table: pa.Table) -> pa.Table:
    t = table.combine_chunks()
    if t.num_rows and t.num_columns:
        t = t.sort_by([(n, "ascending") for n in t.column_names])
    return t


class _Spilly(MemConsumer):
    """Spills everything it holds."""

    def __init__(self, name):
        super().__init__(name)
        self.spill_calls = 0

    def spill(self) -> int:
        self.spill_calls += 1
        freed = self.mem_used
        self.update_mem_used(0)
        return freed


class _Sticky(MemConsumer):
    """Spills nothing (a consumer with no reclaimable state)."""

    def __init__(self, name):
        super().__init__(name)
        self.spill_calls = 0

    def spill(self) -> int:
        self.spill_calls += 1
        return 0


# ---------------------------------------------------------------------------
# per-query ledger + budgets (memmgr/manager.py)
# ---------------------------------------------------------------------------

def test_query_ledger_tracks_usage_peak_and_drain():
    mgr = reset_manager(1 << 30)
    with tracing.trace_scope("qled"):
        c = MemConsumer("op", spillable=False)
        mgr.register_consumer(c)
        c.update_mem_used(1000)
        c.update_mem_used(700)
    # the consumer keeps its tag after the scope exits
    c.update_mem_used(400)
    ledger = mgr.query_ledger()
    assert ledger["qled"]["used"] == 400
    assert ledger["qled"]["peak"] == 1000
    assert mgr.query_usage("qled") == 400
    mgr.unregister_consumer(c)
    assert mgr.query_usage("qled") == 0
    # anonymous consumers never enter the ledger
    a = MemConsumer("anon", spillable=False)
    mgr.register_consumer(a)
    a.update_mem_used(50)
    assert set(mgr.query_ledger()) == {"qled"}
    mgr.unregister_consumer(a)


def test_query_budget_spills_own_consumer_under_healthy_pool():
    """A query over its per-query budget spills its OWN memory even when
    the shared pool is far under budget — and never a neighbor inside
    its budget."""
    mgr = reset_manager(1 << 30)
    with conf.scoped({"auron.memory.query.budget.bytes": 1000,
                      "auron.memory.spill.min.trigger.bytes": 1,
                      "auron.memory.query.kill.grace.spills": 0}):
        with tracing.trace_scope("qneighbor"):
            b = _Spilly("b")
            mgr.register_consumer(b)
            b.update_mem_used(500)         # inside budget
        with tracing.trace_scope("qbig"):
            a = _Spilly("a")
            mgr.register_consumer(a)
            a.update_mem_used(2000)        # over the per-query budget
    assert a.spill_calls == 1
    assert b.spill_calls == 0
    assert mgr.query_usage("qbig") == 0
    assert mgr.query_usage("qneighbor") == 500
    assert mgr.num_spills == 1
    mgr.unregister_consumer(a)
    mgr.unregister_consumer(b)


def test_query_budget_zero_disables_enforcement():
    mgr = reset_manager(1 << 30)
    with conf.scoped({"auron.memory.spill.min.trigger.bytes": 1}):
        with tracing.trace_scope("qfree"):
            a = _Spilly("a")
            mgr.register_consumer(a)
            a.update_mem_used(10 << 20)
    assert a.spill_calls == 0              # ledgered, not enforced
    assert mgr.query_usage("qfree") == 10 << 20
    mgr.unregister_consumer(a)


def test_query_kill_fires_once_past_grace():
    mgr = reset_manager(1 << 30)
    kills = []
    mem_manager.set_kill_hook(lambda qid, why: kills.append((qid, why)))
    with conf.scoped({"auron.memory.query.budget.bytes": 1000,
                      "auron.memory.spill.min.trigger.bytes": 1,
                      "auron.memory.query.kill.grace.spills": 2}):
        with tracing.trace_scope("qkill"):
            c = _Sticky("s")
            mgr.register_consumer(c)
            c.update_mem_used(2000)        # spill #1 (frees nothing)
            assert kills == []             # inside grace
            c.update_mem_used(2100)        # spill #2 -> grace exhausted
        assert len(kills) == 1
        qid, why = kills[0]
        assert qid == "qkill" and "budget" in why
        with tracing.trace_scope("qkill"):
            c.update_mem_used(2200)        # still over: no second kill
        assert len(kills) == 1
    assert mgr.query_ledger()["qkill"]["kills"] == 1
    mgr.unregister_consumer(c)


def test_query_victim_strategy_ranks_by_overage():
    mgr = reset_manager(1 << 30)
    with tracing.trace_scope("qA"):
        a = _Spilly("a")
        mgr.register_consumer(a)
        a.update_mem_used(300)
    with tracing.trace_scope("qB"):
        b = _Spilly("b")
        mgr.register_consumer(b)
        b.update_mem_used(400)
    anon = _Spilly("anon")
    mgr.register_consumer(anon)
    anon.update_mem_used(10_000)           # huge but query-less
    with conf.scoped({"auron.memory.spill.victim.strategy": "query"}):
        # no per-query budget: overage degrades to per-query usage;
        # anonymous consumers sink below every real query
        assert mgr._pick_spill_victim([a, b, anon]) is b
        with conf.scoped({"auron.memory.query.budget.bytes": 350}):
            # qA overage -50, qB overage +50
            assert mgr._pick_spill_victim([a, b]) is b
    # default 'rate' strategy still works with the ledger present
    assert mgr._pick_spill_victim([a, b]) in (a, b)
    for c in (a, b, anon):
        mgr.unregister_consumer(c)


def test_query_strategy_arbitration_end_to_end():
    """Pool pressure with the `query` strategy spills a consumer of the
    most-over-budget query."""
    mgr = reset_manager(1000)
    with conf.scoped({"auron.memory.spill.victim.strategy": "query",
                      "auron.memory.spill.min.trigger.bytes": 1,
                      "auron.memory.query.kill.grace.spills": 0}):
        with tracing.trace_scope("qsmall"):
            a = _Spilly("a")
            mgr.register_consumer(a)
            a.update_mem_used(400)
        with tracing.trace_scope("qlarge"):
            b = _Spilly("b")
            mgr.register_consumer(b)
            b.update_mem_used(700)         # pool 1100 > 1000: arbitrate
    assert b.spill_calls == 1 and a.spill_calls == 0
    mgr.unregister_consumer(a)
    mgr.unregister_consumer(b)


# ---------------------------------------------------------------------------
# preemption plumbing (task_pool + retry accounting)
# ---------------------------------------------------------------------------

def test_preempt_query_idempotent_and_counted():
    p0 = counters.get("preemptions")
    assert task_pool.preempt_query("qp1", "pressure") is True
    assert task_pool.preempt_query("qp1", "again") is False
    assert counters.get("preemptions") == p0 + 1
    assert task_pool.is_cancelled("qp1")
    assert task_pool.preempt_reason("qp1") == "pressure"
    task_pool.clear_cancelled("qp1")
    assert task_pool.preempt_reason("qp1") is None
    assert not task_pool.is_cancelled("qp1")
    # plain cancellation carries no preemption reason
    task_pool.cancel_query("qp2")
    assert task_pool.preempt_reason("qp2") is None
    task_pool.clear_cancelled("qp2")


def test_query_cancelled_is_deterministic_never_exhausted():
    """Satellite pin: QueryCancelled consumes NO retry budget and never
    trips the exhausted marker — a requeued query re-arms every tier
    fresh."""
    from auron_tpu.runtime.retry import (
        RetryPolicy, call_with_retry, is_retryable, stats_snapshot,
        task_classify,
    )
    exc = QueryCancelled("q")
    assert not is_retryable(exc)
    assert not task_classify(exc)
    # the declaration beats even a (bogus) retryable flag
    exc.auron_retryable = True
    assert not is_retryable(exc)
    assert not task_classify(exc)

    calls = []
    s0 = stats_snapshot()

    def boom():
        calls.append(1)
        raise QueryCancelled("q")

    with pytest.raises(QueryCancelled) as ei:
        call_with_retry(boom, policy=RetryPolicy(max_attempts=5))
    s1 = stats_snapshot()
    assert len(calls) == 1, "QueryCancelled must never be re-attempted"
    assert s1["retries"] == s0["retries"]
    assert s1["exhausted"] == s0["exhausted"]
    assert not getattr(ei.value, "auron_retry_exhausted", False)


def test_preempted_run_tasks_consumes_no_task_retries():
    task_pool.reset_pool()
    with conf.scoped({"auron.task.parallelism": 2,
                      "auron.task.retries": 3}):
        r0 = counters.get("tasks_retried")
        task_pool.preempt_query("qpre", "test preemption")
        try:
            with tracing.trace_scope("qpre"):
                with pytest.raises(QueryCancelled) as ei:
                    run_tasks(lambda i: i, range(4))
            assert "preempted" in str(ei.value)
            assert counters.get("tasks_retried") == r0
        finally:
            task_pool.clear_cancelled("qpre")


def test_preemption_emits_trace_event():
    task_pool.reset_pool()
    scope = tracing.trace_scope(
        "qev", recorder=tracing.TraceRecorder("qev"))
    try:
        with scope:
            task_pool.preempt_query("qev", "pressure test")
            with pytest.raises(QueryCancelled):
                run_tasks(lambda i: i, [1, 2])
        events = [s for s in scope.recorder.snapshot()
                  if s.name == "query.preempt"]
        assert events, "preemption must land in the victim's trace"
        assert events[0].args["reason"] == "pressure test"
    finally:
        task_pool.clear_cancelled("qev")


# ---------------------------------------------------------------------------
# scheduler kill-and-requeue + aging
# ---------------------------------------------------------------------------

class _FakeResult:
    def __init__(self, table):
        self.table = table
        self.wall_s = 0.01
        self.metrics = []


class _BlockFirst:
    """Per-query: the FIRST execute blocks until the query is cancelled
    or `release` is set; re-executes return immediately.  Runs under
    the query scope so /queries attribution is real."""

    def __init__(self):
        self.runs = {}
        self.release = threading.Event()

    def execute(self, plan, mesh=None, mesh_axis="parts", query_id=None):
        first = query_id not in self.runs
        self.runs[query_id] = self.runs.get(query_id, 0) + 1
        with tracing.trace_scope(query_id=query_id):
            if first:
                deadline = time.time() + 20
                while time.time() < deadline and \
                        not self.release.is_set():
                    if task_pool.is_cancelled(query_id):
                        raise QueryCancelled(query_id)
                    time.sleep(0.01)
            # record a history row like the real session does (the
            # scheduler patches .preemptions onto it)
            tracing.record_query(tracing.QueryRecord(
                query_id=query_id, wall_s=0.01, rows=3))
            return _FakeResult(pa.table({"x": [1, 2, 3]}))


def _tiny_plan(rows=3, tag="t"):
    from auron_tpu.frontend.foreign import ForeignNode, fcol
    from auron_tpu.ir.schema import DataType, Field, Schema
    schema = Schema((Field("x", DataType.int64()),))
    scan = ForeignNode("LocalTableScanExec", output=schema,
                       attrs={"rows": [{"x": i} for i in range(rows)]})
    return ForeignNode("ProjectExec", children=(scan,), output=schema,
                       attrs={"exprs": (fcol("x", DataType.int64()),),
                              "tag": tag})


def _wait_running(sched, qid, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if sched.status(qid)["state"] == "running":
            return True
        time.sleep(0.01)
    return False


def test_scheduler_requeues_preempted_query():
    sess = _BlockFirst()
    sched = QueryScheduler(session_factory=lambda: sess)
    rq0 = counters.get("requeues")
    cc0 = counters.get("queries_cancelled")
    qid = sched.submit(_tiny_plan(), conf={"auron.batch.size": 2048})
    assert _wait_running(sched, qid)
    assert task_pool.preempt_query(qid, "unit-test pressure")
    assert sched.wait(qid, timeout=30)
    st = sched.status(qid)
    assert st["state"] == "succeeded", st
    assert st["preemptions"] == 1
    assert sess.runs[qid] == 2                   # killed once, rerun once
    assert counters.get("requeues") == rq0 + 1
    # a preemption is NOT a cancellation
    assert counters.get("queries_cancelled") == cc0
    # the /queries record surfaces the preemption count
    rec = tracing.find_query(qid)
    assert rec is not None and rec.preemptions == 1
    assert rec.error is None
    # reservation fully released, preempt mark cleared
    assert sched.admission.held_bytes() == 0
    assert task_pool.preempt_reason(qid) is None
    assert sched.stats()["preemptions"] == 1


def test_scheduler_preemption_cap_fails_query():
    sess = _BlockFirst()
    sched = QueryScheduler(session_factory=lambda: sess)
    with conf.scoped({"auron.serving.preempt.max.per.query": 0}):
        qid = sched.submit(_tiny_plan())
        assert _wait_running(sched, qid)
        task_pool.preempt_query(qid, "over budget")
        assert sched.wait(qid, timeout=30)
        st = sched.status(qid)
    assert st["state"] == "failed"
    assert "killed after 1 preemptions" in st["error"]
    assert sched.admission.held_bytes() == 0


def test_on_pressure_picks_lowest_priority_most_over_forecast():
    sess = _BlockFirst()
    with conf.scoped({"auron.serving.preempt.watermark": 0.9,
                      "auron.serving.preempt.cooldown.seconds": 0.0,
                      "auron.serving.max.concurrent": 2}):
        sched = QueryScheduler(session_factory=lambda: sess)
        q_low = sched.submit(_tiny_plan(tag="low"), priority=1)
        q_high = sched.submit(_tiny_plan(tag="high"), priority=5)
        assert _wait_running(sched, q_low)
        assert _wait_running(sched, q_high)
        sched._on_pressure(1000, 1000)
        assert task_pool.preempt_reason(q_low) is not None
        assert task_pool.preempt_reason(q_high) is None
        # the victim observes the kill, requeues, and re-runs to
        # completion BEFORE the release (its second run returns
        # immediately); then the survivor is released
        assert sched.wait(q_low, timeout=30)
        sess.release.set()
        assert sched.wait(q_high, timeout=30)
        assert sched.status(q_low)["state"] == "succeeded"
        assert sched.status(q_high)["state"] == "succeeded"
        assert sched.status(q_low)["preemptions"] == 1
        sched.shutdown()


def test_on_pressure_never_preempts_lone_query():
    sess = _BlockFirst()
    with conf.scoped({"auron.serving.preempt.watermark": 0.9,
                      "auron.serving.preempt.cooldown.seconds": 0.0}):
        sched = QueryScheduler(session_factory=lambda: sess)
        qid = sched.submit(_tiny_plan())
        assert _wait_running(sched, qid)
        sched._on_pressure(10**9, 1)
        assert task_pool.preempt_reason(qid) is None
        sess.release.set()
        assert sched.wait(qid, timeout=30)
        assert sched.status(qid)["state"] == "succeeded"
        sched.shutdown()


def test_priority_aging_unstarves_queued_submission():
    """With aging on, an old low-priority submission overtakes a fresh
    high-priority one; with aging off it would wait forever behind it."""
    from auron_tpu.serving.scheduler import Submission
    sub = Submission(query_id="q", plan=None, conf={}, priority=1,
                     signature="s")
    assert sub.effective_priority(0.0) == 1           # aging off
    sub.queued_since = time.time() - 10.0
    assert sub.effective_priority(2.0) == 1 + 5
    assert sub.effective_priority(0.001) == 64        # clamped

    sess = _BlockFirst()
    log = []

    class _Logger(_BlockFirst):
        def execute(self, plan, mesh=None, mesh_axis="parts",
                    query_id=None):
            log.append(query_id)
            return _FakeResult(pa.table({"x": [1]}))

    runner = _Logger()
    with conf.scoped({"auron.serving.max.concurrent": 1,
                      "auron.admission.aging.seconds": 2.0}):
        sched = QueryScheduler(session_factory=lambda: sess)
        blocker = sched.submit(_tiny_plan(tag="blk"))
        assert _wait_running(sched, blocker)
        q_old_low = sched.submit(_tiny_plan(tag="old"), priority=1)
        q_new_high = sched.submit(_tiny_plan(tag="new"), priority=3)
        # simulate a long queue wait: the low-priority submission has
        # aged 10s -> effective 1 + 5 = 6 > 3
        sched.get(q_old_low).queued_since -= 10.0
        sched._session_factory = lambda: runner
        sess.release.set()
        for q in (blocker, q_old_low, q_new_high):
            assert sched.wait(q, timeout=30)
        sched.shutdown()
    assert log == [q_old_low, q_new_high], log


# ---------------------------------------------------------------------------
# Retry-After (shed / queue timeout)
# ---------------------------------------------------------------------------

def _http(url, method="GET", doc=None):
    """(status, headers, json) without raising on HTTP errors."""
    data = json.dumps(doc).encode() if doc is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"}, method=method)
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, dict(r.headers), \
                json.loads(r.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read() or b"null")


def test_drain_estimate_bounds():
    from auron_tpu.serving import AdmissionController
    ctl = AdmissionController()
    est = ctl.drain_estimate_s(0)
    assert 1.0 <= est <= 600.0
    assert ctl.drain_estimate_s(10_000) <= 600.0


def test_retry_after_on_shed_and_unfinished_result():
    sess = _BlockFirst()
    srv = QueryServer(session_factory=lambda: sess).start()
    try:
        with conf.scoped({"auron.admission.queue.max": 1,
                          "auron.serving.max.concurrent": 1}):
            code, _, doc = _http(srv.url + "/submit", "POST",
                                 {"plan": _tiny_plan().to_dict()})
            assert code == 200
            qid = doc["query_id"]
            assert _wait_running(srv.scheduler, qid)
            # one waiter fills the queue; the next submission sheds
            code, _, doc2 = _http(srv.url + "/submit", "POST",
                                  {"plan": _tiny_plan().to_dict()})
            assert code == 200
            q_wait = doc2["query_id"]
            code, headers, doc = _http(srv.url + "/submit", "POST",
                                       {"plan": _tiny_plan().to_dict()})
            assert code == 429
            assert int(headers["Retry-After"]) >= 1
            assert doc["retry_after_s"] >= 1.0
            # an unfinished /result carries the hint too
            code, headers, doc = _http(srv.url + f"/result/{qid}")
            assert code == 409
            assert int(headers["Retry-After"]) >= 1
            sess.release.set()
            assert srv.scheduler.wait(qid, timeout=30)
            assert srv.scheduler.wait(q_wait, timeout=30)
            # finished results carry no Retry-After
            code, headers, _ = _http(srv.url + f"/result/{qid}")
            assert code == 200 and "Retry-After" not in headers
    finally:
        srv.stop()


def test_retry_after_on_queue_timeout_result():
    sess = _BlockFirst()
    srv = QueryServer(session_factory=lambda: sess).start()
    try:
        with conf.scoped({"auron.serving.max.concurrent": 1,
                          "auron.admission.queue.timeout.seconds": 0.2}):
            q_run = srv.scheduler.submit(_tiny_plan())
            assert _wait_running(srv.scheduler, q_run)
            q_wait = srv.scheduler.submit(_tiny_plan())
            assert srv.scheduler.wait(q_wait, timeout=10)
            st = srv.scheduler.status(q_wait)
            assert st["state"] == "failed" and "timeout" in st["error"]
            code, headers, doc = _http(srv.url + f"/result/{q_wait}")
            assert code == 409
            assert int(headers["Retry-After"]) >= 1
            sess.release.set()
            srv.scheduler.wait(q_run, timeout=30)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# THE acceptance stress: 10 concurrent fault-injected queries with a
# budget tight enough to force >= 1 preemption
# ---------------------------------------------------------------------------

SERIAL_SCOPE = {
    # serial per-partition path: per-operator metric trees + memory
    # consumers register (the SPMD stage program has neither)
    "auron.spmd.singleDevice.enable": False,
}


def _solo_baselines(names, catalog):
    from auron_tpu.frontend.session import AuronSession
    from auron_tpu.it import queries
    from auron_tpu.it.oracle import PyArrowEngine
    out = {}
    with conf.scoped(SERIAL_SCOPE):
        for name in set(names):
            session = AuronSession(foreign_engine=PyArrowEngine())
            out[name] = _canon(
                session.execute(queries.build(name, catalog)).table)
    return out


@pytest.mark.slow
def test_overload_stress_preempt_requeue_bit_identical(catalog):
    """THE acceptance gate: 10 concurrent queries under io+latency+mem
    faults against a tiny shared pool with watermark preemption armed —
    at least one query is preempted and requeued, EVERY query's final
    result is bit-identical to its solo fault-free run, per-query
    ledger entries drain to zero, no consumer leaks, no admission
    reservation survives, and every driver thread exits."""
    from auron_tpu import faults
    from auron_tpu.it import queries
    from auron_tpu.runtime import profiling
    from auron_tpu.serving.scheduler import default_session_factory

    names = ["q03", "q42", "q01", "q03", "q42",
             "q01", "q03", "q42", "q01", "q03"]
    baselines = _solo_baselines(names, catalog)

    # io rules carry max= bounds (the PR 6 lesson): the gate tests
    # recovery + preemption, not unbounded adversity
    spec = ("shuffle.push:io:p=0.06,max=8,seed=7;"
            "shuffle.fetch:io:p=0.06,max=8,seed=11;"
            "scan.parquet.open:io:p=0.04,max=6,seed=19;"
            "shuffle.push:latency:p=0.1,seed=5,ms=4;"
            "op.execute:mem:bytes=65536,max=2,seed=9")
    faults.reset(spec)
    stress_scope = {
        **SERIAL_SCOPE,
        "auron.faults.spec": spec,
        "auron.task.retries": 2,
        "auron.retry.backoff.base.ms": 1.0,
        "auron.retry.backoff.max.ms": 10.0,
        # tiny shared pool: ten queries fight for ~2MB and spill
        "auron.memory.spill.min.trigger.bytes": 1024,
        "auron.serving.max.concurrent": 10,
        "auron.admission.default.forecast.bytes": 131072,
        # the overload-survival layer under test: preempt at half the
        # effective budget (the tiny pool crosses it early and often),
        # at most one kill-and-requeue per query, spaced >= 3s
        "auron.serving.preempt.watermark": 0.5,
        "auron.serving.preempt.cooldown.seconds": 3.0,
        "auron.serving.preempt.max.per.query": 1,
        "auron.admission.aging.seconds": 5.0,
    }
    task_pool.reset_pool()
    tracing.clear_history()
    p0 = counters.get("preemptions")
    r0 = counters.get("requeues")
    with conf.scoped(stress_scope):
        mgr = reset_manager(2 << 20)
        sched = QueryScheduler(session_factory=default_session_factory)
        qids = [sched.submit(queries.build(n, catalog),
                             priority=1 + (i % 3))
                for i, n in enumerate(names)]
        assert len(set(qids)) == 10
        for qid in qids:
            assert sched.wait(qid, timeout=600), sched.status(qid)
        sched.shutdown()

    # the sweep must actually have injected (hollow-gate guard)
    reg = faults.registry_for(spec)
    assert reg.injected_total() > 0, reg.counts()

    # >= 1 preemption was forced, and every preemption that requeued
    # came back: bit-identical results below prove re-execution safety
    preemptions = counters.get("preemptions") - p0
    requeues = counters.get("requeues") - r0
    assert preemptions >= 1, \
        "the tight budget must force at least one preemption"
    assert requeues >= 1
    assert sum(s.num_preemptions
               for s in (sched.get(q) for q in qids)) >= 1

    for qid, name in zip(qids, names):
        st = sched.status(qid)
        assert st["state"] == "succeeded", (name, st)
        table = _canon(sched.result(qid))
        assert table.equals(baselines[name]), \
            f"{name} ({qid}) diverged from its solo fault-free run"
        rec = tracing.find_query(qid)
        assert rec is not None, f"no /queries record for {qid}"
        assert rec.rows == sched.result(qid).num_rows
        assert rec.error is None
        # QueryRecord surfaces the kill-and-requeue count
        assert rec.preemptions == st["preemptions"]

    # every reservation released: no admission holds, no admission:*
    # label left on the manager (fault 'mem' reservations persist by
    # design until reset_manager)
    assert sched.admission.held_bytes() == 0
    assert not any(label.startswith("admission:")
                   for label in mgr._reservations)
    # per-query ledger drained to zero, no leaked consumers
    ledger = mgr.query_ledger()
    assert sum(ent["used"] for ent in ledger.values()) == 0, ledger
    assert mgr.stats()["num_consumers"] == 0
    # preemption marks all cleared
    assert all(task_pool.preempt_reason(q) is None for q in qids)

    # counters visible on /metrics (prometheus text), on the scheduler
    # stats, and as query.preempt in at least one victim's trace
    prom = profiling._prometheus_text()
    assert "auron_preemptions_total" in prom
    assert "auron_requeues_total" in prom
    pre_line = [ln for ln in prom.splitlines()
                if ln.startswith("auron_preemptions_total")][0]
    assert int(pre_line.split()[-1]) >= 1
    victims = [q for q in qids if sched.get(q).num_preemptions]
    assert victims
    for q in victims:
        rec = tracing.find_query(q)
        assert rec.preemptions >= 1

    # all driver threads joined (requeues spawn fresh ones per run)
    deadline = time.time() + 10
    while time.time() < deadline:
        drivers = [t for t in threading.enumerate()
                   if t.name.startswith("auron-driver-")]
        if not drivers:
            break
        time.sleep(0.05)
    assert not drivers, f"driver threads alive: {drivers}"


@pytest.mark.slow
def test_tools_overload_check_script():
    """tools/overload_check.sh is the CI overload gate; keep it green
    from pytest (mirrors serve_check wiring)."""
    import os
    import shutil
    import subprocess
    import sys
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "overload_check.sh")
    if not os.path.exists(script) or shutil.which("bash") is None:
        pytest.skip("overload script or bash unavailable")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(sys.path))
    out = subprocess.run(["bash", script], capture_output=True,
                         text=True, timeout=540, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
