"""Fleet observability (PR 13): cross-process trace stitching,
harvest-RPC loss tolerance, distributed EXPLAIN ANALYZE records,
lifecycle timelines and the flight recorder.

- stitch_traces units: skewed fake clocks still yield a monotone
  merged trace whose lanes never precede their wire-parent anchor;
  incomplete/truncation flags propagate.
- TraceRecorder.drain()/drain_since(): the incremental-export cursor
  contract (return-and-clear, cursor-acknowledged frees, re-served
  unacked spans).
- A traced fleet query against a real executor server produces ONE
  stitched driver-side Chrome trace + QueryRecord with the worker's
  harvested metric trees and the full lifecycle timeline.
- Harvest loss (dead/broken worker): the query still completes, the
  stitched trace is flagged `incomplete` — never a hang.
- Cross-process conservation: worker-reported retries on the driver's
  /queries record equal the task-retry counter delta.
- Executor death writes `worker.death`/`query.requeue` flight-recorder
  events naming the affected query ids.
"""

import threading
import time

import pytest

from auron_tpu import faults
from auron_tpu.config import conf
from auron_tpu.frontend.foreign import ForeignNode
from auron_tpu.ir.schema import DataType, Field, Schema
from auron_tpu.memmgr import manager as mem_manager
from auron_tpu.memmgr.manager import reset_manager
from auron_tpu.runtime import counters, events, task_pool, tracing
from auron_tpu.serving import FleetManager, ProcessExecutor
from auron_tpu.serving.executor_endpoint import ExecutorServer
from auron_tpu.serving.scheduler import default_session_factory

FAST_FLEET_CONF = {
    "auron.fleet.heartbeat.seconds": 0.1,
    "auron.retry.backoff.base.ms": 1.0,
    "auron.retry.backoff.max.ms": 5.0,
    "auron.net.timeout.seconds": 5.0,
}


@pytest.fixture(autouse=True)
def _fresh_world():
    yield
    faults.reset()
    mem_manager.reset_hooks()
    reset_manager()
    task_pool.reset_pool()


def _scan_plan(tag="t", rows=3):
    schema = Schema((Field("x", DataType.int64()),))
    return ForeignNode("LocalTableScanExec", output=schema,
                       attrs={"rows": [{"x": i} for i in range(rows)],
                              "tag": tag})


def _start_server(executor_id="e1", session_factory=None):
    srv = ExecutorServer(
        session_factory=session_factory or default_session_factory,
        executor_id=executor_id).start()
    return srv, ProcessExecutor(executor_id, *srv.address)


# ---------------------------------------------------------------------------
# stitching units (fake clocks, no processes)
# ---------------------------------------------------------------------------

def _lane(pid, wall_base, offset_s, anchor_us, names,
          step_us=10.0, dur_us=5.0):
    spans = []
    for i, name in enumerate(names):
        spans.append({"name": name, "cat": "c",
                      "ts_us": wall_base * 1e6 + i * step_us,
                      "dur_us": dur_us, "tid": 7, "thread": "w"})
    return {"label": f"lane-{pid}", "pid": pid, "spans": spans,
            "dropped": 0, "offset_s": offset_s, "anchor_us": anchor_us}


def test_stitch_skewed_clocks_monotone_and_anchored():
    """A worker clock running 100s AHEAD and a side-car clock 50s
    BEHIND both land on the driver timeline: offsets undo the skew,
    and each lane is clamped so no span precedes its dispatch
    anchor."""
    base = tracing.TraceRecorder("q1")
    t0 = time.perf_counter_ns()
    base.add("fleet.dispatch", "fleet", t0, 2000, {"executor": "e0"})
    doc = base.to_chrome_trace()
    wall = base.wall_start
    fast = _lane(101, wall + 100.0, 100.0, 500.0, ["a", "b", "c"])
    slow = _lane(102, wall - 50.0, -50.0, 800.0, ["d", "e"])
    st = tracing.stitch_traces(doc, [fast, slow])
    assert tracing.validate_chrome_trace(st) == []
    by_pid = {}
    for ev in st["traceEvents"]:
        if ev.get("ph") in ("X", "i"):
            by_pid.setdefault(ev["pid"], []).append(ev["ts"])
    # lane-internal order preserved, monotone, and >= the anchor
    for pid, anchor in ((101, 500.0), (102, 800.0)):
        ts = by_pid[pid]
        assert ts == sorted(ts)
        assert all(t >= anchor for t in ts), (pid, ts)
    # offsets actually cancelled the skew: with perfect offsets the
    # two lanes land within the same few ms as the driver span, not
    # 100s/50s away
    drv_ts = by_pid[list(by_pid)[0]]
    assert max(max(v) for v in by_pid.values()) < 1e6, by_pid
    assert st["otherData"]["stitched"] is True
    assert st["otherData"]["incomplete"] == []
    assert drv_ts  # driver lane survived


def test_stitch_incomplete_and_truncation_flags():
    base = tracing.TraceRecorder("q2")
    doc = base.to_chrome_trace()
    lane = _lane(103, base.wall_start, 0.0, None, ["a"])
    lane["dropped"] = 4
    st = tracing.stitch_traces(doc, [lane], incomplete=["exec-9"])
    assert st["otherData"]["incomplete"] == ["exec-9"]
    assert st["otherData"]["dropped_events"] == 4
    assert st["otherData"]["trace_truncated"] is True
    assert tracing.validate_chrome_trace(st) == []


def test_stitch_negative_shift_clamps_to_zero():
    """A lane with no anchor whose shifted times would go negative is
    clamped to ts >= 0 (validate requires non-negative ts)."""
    base = tracing.TraceRecorder("q3")
    doc = base.to_chrome_trace()
    lane = _lane(104, base.wall_start - 5.0, 0.0, None, ["a", "b"])
    st = tracing.stitch_traces(doc, [lane])
    assert tracing.validate_chrome_trace(st) == []
    ts = [ev["ts"] for ev in st["traceEvents"]
          if ev.get("ph") in ("X", "i")]
    assert min(ts) >= 0.0


# ---------------------------------------------------------------------------
# incremental drain (the PR 4 streaming-trace follow-up)
# ---------------------------------------------------------------------------

def test_recorder_drain_returns_and_clears():
    rec = tracing.TraceRecorder("qd", max_events=100)
    for i in range(4):
        rec.add(f"s{i}", "c", 1000 + i, 10, None)
    spans, nxt = rec.drain()
    assert [s.name for s in spans] == ["s0", "s1", "s2", "s3"]
    assert nxt == 4
    assert rec.snapshot() == []
    rec.add("s4", "c", 2000, 10, None)
    spans, nxt = rec.drain()
    assert [s.name for s in spans] == ["s4"] and nxt == 5


def test_recorder_drain_since_cursor_ack():
    """drain_since frees only ACKNOWLEDGED spans: a repeated poll with
    the same cursor re-serves the unacked tail (lost-response
    tolerance), an advanced cursor frees it."""
    rec = tracing.TraceRecorder("qc", max_events=100)
    for i in range(3):
        rec.add(f"s{i}", "c", 1000 + i, 10, None)
    spans, first, nxt = rec.drain_since(0)
    assert len(spans) == 3 and first == 0 and nxt == 3
    # same cursor again: nothing freed, same spans re-served
    spans2, first2, _ = rec.drain_since(0)
    assert [s.name for s in spans2] == [s.name for s in spans]
    assert first2 == 0
    # acked: freed, new spans continue the sequence
    rec.add("s3", "c", 2000, 10, None)
    spans3, first3, nxt3 = rec.drain_since(3)
    assert [s.name for s in spans3] == ["s3"]
    assert first3 == 3 and nxt3 == 4
    # capacity is reclaimed by draining (long-running queries)
    assert len(rec.snapshot()) == 1


def test_drop_cap_counts_warns_once_and_flags_export(caplog):
    """Satellite bugfix: the event cap no longer drops silently — the
    per-recorder count, the process counter, the exported
    trace_truncated flag and ONE warning per query all fire."""
    import logging
    c0 = counters.get("trace_dropped_events")
    rec = tracing.TraceRecorder("qcap", max_events=2)
    with caplog.at_level(logging.WARNING, logger="auron_tpu.tracing"):
        for i in range(5):
            rec.add(f"s{i}", "c", 1000 + i, 10, None)
    assert rec.dropped == 3
    assert counters.get("trace_dropped_events") - c0 == 3
    warns = [r for r in caplog.records
             if "auron.trace.max.events" in r.getMessage()]
    assert len(warns) == 1
    doc = rec.to_chrome_trace()
    assert doc["otherData"]["trace_truncated"] is True
    assert doc["otherData"]["dropped_events"] == 3
    # draining reopens capacity and further spans record again
    rec.drain()
    rec.add("late", "c", 9000, 10, None)
    assert [s.name for s in rec.snapshot()] == ["late"]


# ---------------------------------------------------------------------------
# fleet end-to-end: stitched record, timelines, conservation, loss
# ---------------------------------------------------------------------------

def test_traced_fleet_query_stitched_record_and_metric_trees():
    """A traced fleet query against a real executor server yields a
    driver-side QueryRecord whose trace is ONE validated stitched doc
    (driver + worker lanes), whose metric trees are the worker's
    harvested per-operator merge (serial path => non-empty, same
    structure as local execution), and whose timeline walks
    submitted -> queued -> admitted -> dispatched -> running ->
    succeeded."""
    srv, ep = _start_server("e1")
    fleet = None
    try:
        with conf.scoped({**FAST_FLEET_CONF,
                          "auron.trace.enable": True}):
            fleet = FleetManager(endpoints=[ep])
            qid = fleet.submit(
                _scan_plan("traced"),
                conf={"auron.spmd.singleDevice.enable": False})
            assert fleet.wait(qid, timeout=60), fleet.status(qid)
            st = fleet.status(qid)
            assert st["state"] == "succeeded", st
            assert [e["state"] for e in st["timeline"]] == [
                "submitted", "queued", "admitted", "dispatched",
                "running", "succeeded"]
            assert set(st["state_durations"]) == set(
                e["state"] for e in st["timeline"])
            rec = tracing.find_query(qid)
            assert rec is not None, "no driver-side QueryRecord"
            assert rec.trace is not None
            assert rec.trace["otherData"]["stitched"] is True
            assert rec.trace["otherData"]["incomplete"] == []
            assert tracing.validate_chrome_trace(rec.trace) == []
            names = {e["name"] for e in rec.trace["traceEvents"]}
            # driver lane + worker lane span families both present
            assert "fleet.dispatch" in names
            assert "plan.convert" in names and "query" in names
            # distributed EXPLAIN ANALYZE: worker metric trees arrived
            assert rec.metric_trees, "no harvested metric trees"
            from auron_tpu.runtime.explain_analyze import (
                render_analyzed_dicts,
            )
            text = render_analyzed_dicts(rec.metric_trees)
            assert "output_rows" in text
            assert rec.timeline[-1]["state"] == "succeeded"
    finally:
        if fleet is not None:
            fleet.shutdown(wait=True)
        srv.stop()


def test_untraced_fleet_query_still_records_metric_trees():
    """Distributed EXPLAIN ANALYZE does not require tracing: the
    terminal harvest ships the worker's QueryRecord summary either
    way, so /queries/<id> works for fleet queries with tracing off."""
    srv, ep = _start_server("e1")
    fleet = None
    try:
        with conf.scoped(FAST_FLEET_CONF):
            fleet = FleetManager(endpoints=[ep])
            qid = fleet.submit(
                _scan_plan("plain"),
                conf={"auron.spmd.singleDevice.enable": False})
            assert fleet.wait(qid, timeout=60), fleet.status(qid)
            assert fleet.status(qid)["state"] == "succeeded"
            rec = tracing.find_query(qid)
            assert rec is not None
            assert rec.trace is None          # tracing was off
            assert rec.metric_trees           # trees still harvested
            assert rec.rows == 3
    finally:
        if fleet is not None:
            fleet.shutdown(wait=True)
        srv.stop()


def test_cross_process_retry_conservation():
    """The conservation gate extended across the dispatch boundary:
    the retries on the driver's harvested /queries record equal the
    worker's task-retry counter delta (here exactly two injected
    op.execute failures with a 2-retry budget)."""
    srv, ep = _start_server("e1")
    fleet = None
    spec = "op.execute:io:p=1,max=2,seed=3"
    retried0 = counters.get("tasks_retried")
    try:
        with conf.scoped(FAST_FLEET_CONF):
            fleet = FleetManager(endpoints=[ep])
            qid = fleet.submit(
                _scan_plan("conserve"),
                conf={"auron.spmd.singleDevice.enable": False,
                      "auron.faults.spec": spec,
                      "auron.task.retries": 2,
                      "auron.retry.backoff.base.ms": 1.0,
                      "auron.retry.backoff.max.ms": 5.0})
            assert fleet.wait(qid, timeout=60), fleet.status(qid)
            assert fleet.status(qid)["state"] == "succeeded"
            rec = tracing.find_query(qid)
            assert rec is not None
            retried = counters.get("tasks_retried") - retried0
            assert retried >= 1
            assert rec.retries == retried, (rec.retries, retried)
    finally:
        faults.reset(spec)
        if fleet is not None:
            fleet.shutdown(wait=True)
        srv.stop()


class _HarvestlessExecutor(ProcessExecutor):
    """A remote executor whose harvest RPC always dies — the
    loss-tolerance surface (a worker that crashes between completion
    and harvest looks exactly like this)."""

    def harvest(self, ids):
        raise ConnectionError("harvest wire down")


def test_harvest_loss_marks_trace_incomplete_never_hangs():
    srv = ExecutorServer(session_factory=default_session_factory,
                         executor_id="e1").start()
    ep = _HarvestlessExecutor("e1", *srv.address)
    fleet = None
    try:
        with conf.scoped({**FAST_FLEET_CONF,
                          "auron.trace.enable": True}):
            fleet = FleetManager(endpoints=[ep])
            qid = fleet.submit(
                _scan_plan("lossy"),
                conf={"auron.spmd.singleDevice.enable": False})
            t0 = time.monotonic()
            assert fleet.wait(qid, timeout=60), fleet.status(qid)
            assert time.monotonic() - t0 < 30, "harvest loss hung"
            assert fleet.status(qid)["state"] == "succeeded"
            rec = tracing.find_query(qid)
            assert rec is not None and rec.trace is not None
            # the worker's lane never arrived: flagged, not silent
            assert "e1" in rec.trace["otherData"]["incomplete"]
            assert tracing.validate_chrome_trace(rec.trace) == []
            # no worker record harvested => driver record falls back
            # to the status fields
            assert rec.rows == 3
    finally:
        if fleet is not None:
            fleet.shutdown(wait=True)
        srv.stop()


def test_death_emits_flight_recorder_events():
    """An executor death lands in the flight recorder as
    `worker.death` naming the affected query ids, followed by
    `query.requeue` — the /events postmortem trail."""
    from test_fleet import _BlockingFactory
    blocky = _BlockingFactory()
    srv1, ep1 = _start_server("e1", session_factory=blocky)
    srv2, ep2 = _start_server("e2", session_factory=blocky)
    fleet = None
    seq0 = (events.snapshot()[-1]["seq"]
            if events.snapshot() else 0)
    try:
        with conf.scoped({**FAST_FLEET_CONF,
                          "auron.fleet.heartbeat.seconds": 0.15,
                          "auron.fleet.death.probes": 2,
                          "auron.net.timeout.seconds": 2.0}):
            fleet = FleetManager(endpoints=[ep1, ep2])
            qids = [fleet.submit(_scan_plan(f"t{i}")) for i in range(4)]
            assert blocky.started.wait(30)
            deadline = time.time() + 10
            on_e1 = []
            while time.time() < deadline:
                on_e1 = [q for q in qids
                         if fleet.get(q).executor_id == "e1"
                         and not fleet.get(q).done.is_set()]
                if on_e1:
                    break
                time.sleep(0.02)
            assert on_e1, "nothing routed to e1"
            srv1.stop()
            blocky.release.set()
            for q in qids:
                assert fleet.wait(q, timeout=30), fleet.status(q)
            deaths = events.snapshot(since=seq0, kind="worker.death")
            assert deaths, "no worker.death event"
            ev = deaths[-1]
            assert ev["attrs"]["executor"] == "e1"
            assert set(on_e1) <= set(ev["query_ids"]), (on_e1, ev)
            requeues = events.snapshot(since=seq0, kind="query.requeue")
            assert {q for e in requeues for q in e["query_ids"]} >= \
                set(on_e1)
            # ordering: the death precedes its requeues
            assert deaths[0]["seq"] < requeues[-1]["seq"]
    finally:
        blocky.release.set()
        if fleet is not None:
            fleet.shutdown(wait=True)
        srv2.stop()


@pytest.mark.slow
def test_tools_obs_check_script():
    """tools/obs_check.sh is the CI fleet-observability gate; keep it
    green from pytest (mirrors rss_check wiring)."""
    import os
    import shutil
    import subprocess
    import sys
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "obs_check.sh")
    if not os.path.exists(script) or shutil.which("bash") is None:
        pytest.skip("obs script or bash unavailable")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(sys.path))
    out = subprocess.run(["bash", script], capture_output=True,
                         text=True, timeout=540, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
