"""Hierarchical (ICI-within-slice, DCN-across-slice) repartition tests on
a virtual 2x4 mesh — the multi-slice exchange path of SURVEY §2.5."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from auron_tpu.parallel.exchange import hierarchical_repartition

N_DCN, N_ICI = 2, 4
N_DEV = N_DCN * N_ICI
CAP = 32  # rows per device


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:N_DEV]).reshape(N_DCN, N_ICI)
    return Mesh(devs, ("dcn", "ici"))


def _run(mesh, fn, *arrays):
    spec = P(("dcn", "ici"))
    out = shard_map(fn, mesh=mesh, in_specs=(spec,) * len(arrays),
                    out_specs=(spec, spec, spec))(*arrays)
    return [np.asarray(a) for a in out]


def test_hierarchical_delivers_every_row_once(mesh):
    rng = np.random.default_rng(11)
    n = N_DEV * CAP
    vals = rng.integers(0, 1000, n).astype(np.int64)
    dest = rng.integers(0, N_DEV, n).astype(np.int32)
    valid = rng.random(n) < 0.9

    def body(v, d, ok):
        outs, rv, _ovf = hierarchical_repartition(
            [v], d, ok, ici_axis="ici", dcn_axis="dcn",
            n_ici=N_ICI, n_dcn=N_DCN, quota=CAP)
        recv = outs[0]
        # pad received rows (n_dcn * n_ici*quota) up to a per-device frame
        return (jnp.where(rv, recv, -1),
                rv.astype(jnp.int32),
                jnp.zeros_like(recv))

    got_vals, got_valid, _ = _run(mesh, body, vals, dest, valid)
    got_vals = got_vals.reshape(N_DEV, -1)
    got_valid = got_valid.reshape(N_DEV, -1).astype(bool)

    # every valid row must arrive exactly once at its destination device
    for dev in range(N_DEV):
        expected = sorted(vals[(dest == dev) & valid].tolist())
        received = sorted(got_vals[dev][got_valid[dev]].tolist())
        assert received == expected, f"device {dev}"


@pytest.mark.slow   # PR 12 tier-1 re-split (9.8s; the remaining
#                     hierarchical tests keep per-row delivery pinned)
def test_hierarchical_multi_payload(mesh):
    """Multiple payload columns travel together and stay row-aligned."""
    rng = np.random.default_rng(3)
    n = N_DEV * CAP
    a = rng.integers(0, 100, n).astype(np.int64)
    b = (a * 10).astype(np.int64)          # derived: must stay aligned
    dest = (a % N_DEV).astype(np.int32)
    valid = np.ones(n, bool)

    spec = P(("dcn", "ici"))

    def body(x, y, d, ok):
        outs, rv, _ovf = hierarchical_repartition(
            [x, y], d, ok, ici_axis="ici", dcn_axis="dcn",
            n_ici=N_ICI, n_dcn=N_DCN, quota=CAP)
        return (jnp.where(rv, outs[0], -1), jnp.where(rv, outs[1], -1),
                rv.astype(jnp.int32))

    mesh_run = shard_map(body, mesh=mesh, in_specs=(spec,) * 4,
                         out_specs=(spec, spec, spec))
    ra, rb, rv = [np.asarray(o) for o in mesh_run(a, b, dest, valid)]
    rv = rv.astype(bool)
    # alignment: everywhere valid, second payload is 10x the first
    assert (rb[rv] == ra[rv] * 10).all()
    # destination correctness: rows landed on the device = key % N_DEV
    ra_dev = ra.reshape(N_DEV, -1)
    rv_dev = rv.reshape(N_DEV, -1)
    for dev in range(N_DEV):
        landed = ra_dev[dev][rv_dev[dev]]
        assert (landed % N_DEV == dev).all()


def test_quota_margin_skew_sweep():
    """VERDICT r4 weak #9: quota margin 2.0 had only ever met one
    synthetic skew.  Sweep realistic key-skew families (uniform, zipf
    1.1/1.5, two-hot, single-hot) at full per-device capacity on the
    8-device mesh and record which trip the overflow guard — the
    margin's envelope is then a measured fact: uniform and mild zipf
    ride the bounded quota; heavy single-key concentration trips the
    guard and falls back to serial (by design — the guard exists
    exactly for that shape)."""
    import numpy as np

    import jax
    from jax.sharding import PartitionSpec as PS

    from auron_tpu.exprs.hashing import hash_columns, pmod
    from auron_tpu.parallel.exchange import (all_to_all_repartition,
                                             bounded_quota)
    from auron_tpu.parallel.mesh import data_mesh
    import jax.numpy as jnp

    n_dev = 8
    cap = 4096          # per-device rows, full capacity
    mesh = data_mesh(n_dev)
    rng = np.random.default_rng(17)

    def run(keys_global):
        # keys_global: [n_dev * cap] int64 — what each device holds
        quota = bounded_quota(cap, n_dev)   # margin from config (2.0)

        def body(keys):
            from auron_tpu.columnar.batch import DeviceColumn
            from auron_tpu.ir.schema import DataType
            col = DeviceColumn(DataType.int64(), keys,
                               jnp.ones(cap, bool))
            h = hash_columns([col], seed=42, capacity=cap)
            pid = pmod(h, n_dev).astype(jnp.int32)
            outs, live, ovf = all_to_all_repartition(
                [keys], pid, jnp.ones(cap, bool), "parts", n_dev,
                quota)
            import jax.lax as lax
            any_ovf = lax.psum(ovf.astype(jnp.int32), "parts") > 0
            total = lax.psum(jnp.sum(live.astype(jnp.int32)), "parts")
            return any_ovf, total

        fn = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(PS("parts"),),
            out_specs=(PS(), PS()), check_vma=False))
        ovf, total = fn(jnp.asarray(keys_global))
        return bool(np.asarray(ovf).reshape(-1)[0]), \
            int(np.asarray(total).reshape(-1)[0])

    n = n_dev * cap
    sweeps = {
        "uniform": rng.integers(0, 100_000, n),
        "zipf1.1": rng.zipf(1.1, n),
        "zipf1.5": rng.zipf(1.5, n),
        "16hot":   rng.integers(0, 16, n),
        "2hot":    rng.integers(0, 2, n),
        "1hot":    np.zeros(n, np.int64),
    }
    results = {}
    for name, keys in sweeps.items():
        ovf, total = run(keys.astype(np.int64))
        if not ovf:
            assert total == n, f"{name}: rows lost without overflow"
        results[name] = ovf
    # measured envelope for margin 2.0 on 8 devices:
    assert results["uniform"] is False
    assert results["zipf1.1"] is False
    # a single/two-key hot spot concentrates >2x the fair share on one
    # device — the guard MUST trip (silent row loss would be the bug)
    assert results["1hot"] is True
    assert results["2hot"] is True
