"""Hierarchical (ICI-within-slice, DCN-across-slice) repartition tests on
a virtual 2x4 mesh — the multi-slice exchange path of SURVEY §2.5."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from auron_tpu.parallel.exchange import hierarchical_repartition

N_DCN, N_ICI = 2, 4
N_DEV = N_DCN * N_ICI
CAP = 32  # rows per device


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:N_DEV]).reshape(N_DCN, N_ICI)
    return Mesh(devs, ("dcn", "ici"))


def _run(mesh, fn, *arrays):
    spec = P(("dcn", "ici"))
    out = shard_map(fn, mesh=mesh, in_specs=(spec,) * len(arrays),
                    out_specs=(spec, spec, spec))(*arrays)
    return [np.asarray(a) for a in out]


def test_hierarchical_delivers_every_row_once(mesh):
    rng = np.random.default_rng(11)
    n = N_DEV * CAP
    vals = rng.integers(0, 1000, n).astype(np.int64)
    dest = rng.integers(0, N_DEV, n).astype(np.int32)
    valid = rng.random(n) < 0.9

    def body(v, d, ok):
        outs, rv, _ovf = hierarchical_repartition(
            [v], d, ok, ici_axis="ici", dcn_axis="dcn",
            n_ici=N_ICI, n_dcn=N_DCN, quota=CAP)
        recv = outs[0]
        # pad received rows (n_dcn * n_ici*quota) up to a per-device frame
        return (jnp.where(rv, recv, -1),
                rv.astype(jnp.int32),
                jnp.zeros_like(recv))

    got_vals, got_valid, _ = _run(mesh, body, vals, dest, valid)
    got_vals = got_vals.reshape(N_DEV, -1)
    got_valid = got_valid.reshape(N_DEV, -1).astype(bool)

    # every valid row must arrive exactly once at its destination device
    for dev in range(N_DEV):
        expected = sorted(vals[(dest == dev) & valid].tolist())
        received = sorted(got_vals[dev][got_valid[dev]].tolist())
        assert received == expected, f"device {dev}"


def test_hierarchical_multi_payload(mesh):
    """Multiple payload columns travel together and stay row-aligned."""
    rng = np.random.default_rng(3)
    n = N_DEV * CAP
    a = rng.integers(0, 100, n).astype(np.int64)
    b = (a * 10).astype(np.int64)          # derived: must stay aligned
    dest = (a % N_DEV).astype(np.int32)
    valid = np.ones(n, bool)

    spec = P(("dcn", "ici"))

    def body(x, y, d, ok):
        outs, rv, _ovf = hierarchical_repartition(
            [x, y], d, ok, ici_axis="ici", dcn_axis="dcn",
            n_ici=N_ICI, n_dcn=N_DCN, quota=CAP)
        return (jnp.where(rv, outs[0], -1), jnp.where(rv, outs[1], -1),
                rv.astype(jnp.int32))

    mesh_run = shard_map(body, mesh=mesh, in_specs=(spec,) * 4,
                         out_specs=(spec, spec, spec))
    ra, rb, rv = [np.asarray(o) for o in mesh_run(a, b, dest, valid)]
    rv = rv.astype(bool)
    # alignment: everywhere valid, second payload is 10x the first
    assert (rb[rv] == ra[rv] * 10).all()
    # destination correctness: rows landed on the device = key % N_DEV
    ra_dev = ra.reshape(N_DEV, -1)
    rv_dev = rv.reshape(N_DEV, -1)
    for dev in range(N_DEV):
        landed = ra_dev[dev][rv_dev[dev]]
        assert (landed % N_DEV == dev).all()
