"""Chaos sweeps: TPC-DS queries under injected faults must produce
bit-identical results with bounded attempts (it/stability.chaos_sweep),
plus the SPMD-rejection lint that reports degradations as structured
diagnostics.  The heavy full-tier-1-subset sweep is `slow` (the 870s
tier-1 budget); the fast sweeps here keep the gate armed in tier-1."""

import pytest

from auron_tpu.it.datagen import generate
from auron_tpu.it.stability import chaos_sweep

# the acceptance spec shape: io faults on shuffle push/fetch and spill
# write.  Probabilities are higher than the nightly 0.05 so the SMALL
# fast sweep still provably injects; seeds pin the Bernoulli streams.
FAST_SPEC = ("shuffle.push:io:p=0.2,seed=7;"
             "shuffle.fetch:io:p=0.2,seed=11;"
             "spill.write:io:p=0.2,seed=3")


@pytest.fixture(scope="module")
def catalog(tmp_path_factory):
    return generate(str(tmp_path_factory.mktemp("chaos_tpcds")), sf=0.002,
                    fact_chunks=3)


# PR 5 tier-1 budget split: the two heaviest fast sweeps (19.5s + 11.7s
# measured) ride the nightly -m slow lane; op_device_fault_retries stays
# as the in-gate chaos smoke and the full tier-1-subset p=0.05 sweep
# below was already slow
@pytest.mark.slow
def test_chaos_sweep_io_faults_identical_and_bounded(catalog):
    # q55's seed-7 stream exhausts one push budget mid-sweep, so this
    # also covers the task-tier replay over an exhausted RPC tier
    report = chaos_sweep(["q03", "q55"], catalog, FAST_SPEC)
    assert report.ok, report.render()
    # the sweep must actually inject (a renamed fault point would
    # otherwise hollow the gate out silently) and every query must
    # recover to the bit-identical table
    assert report.injected_total() > 0, report.render()
    assert all(r.identical for r in report.results), report.render()
    # recovery happened through the retry tier, visibly
    assert report.num_retries > 0, report.render()
    # no retry storms: attempts bounded by 3x the fault-free task count
    assert report.attempts_fault <= 3 * report.attempts_baseline, \
        report.render()
    # report plumbing (run-report JSON shape)
    d = report.to_dict()
    assert set(d) >= {"spec", "results", "injected", "num_retries",
                      "num_fallbacks", "attempts_baseline",
                      "attempts_fault", "ok"}
    row = d["results"][0]
    assert set(row) >= {"name", "ok", "identical", "attempts_baseline",
                        "attempts_fault"}
    assert "num_retries" in report.render()


@pytest.mark.slow
def test_chaos_sweep_device_fault_degrades_to_serial(catalog):
    """A persistent device fault in the SPMD stage program must degrade
    to the serial per-partition path (num_fallbacks) and still produce
    the fault-free answer — and the degradation surfaces as a
    structured spmd-stage diagnostic on the result (SessionResult
    .spmd_rejection -> ChaosQueryResult/QueryResult), uniform with the
    static lints."""
    report = chaos_sweep(
        ["q03"], catalog, "stage.execute:device:p=1,seed=3",
        serial=False)
    assert report.ok, report.render()
    assert report.num_fallbacks >= 1, report.render()
    assert report.results[0].identical
    rej = report.results[0].spmd_rejection
    assert rej is not None and "spmd-stage" in rej and \
        "device fault" in rej


@pytest.mark.slow   # PR 12 tier-1 re-split (13.9s; nightly via
#                     chaos_check + the slow sweeps keep the gate)
def test_chaos_sweep_op_device_fault_retries(catalog):
    """A transient device fault at operator execute is re-executed by
    the executor's retry tier (num_retries), no degradation needed."""
    report = chaos_sweep(
        ["q42"], catalog, "op.execute:device:p=1,max=1,seed=5")
    assert report.ok, report.render()
    assert report.num_retries >= 1, report.render()


@pytest.mark.slow   # PR 12 tier-1 re-split (12.4s; chaos_check + the
#                     slow sweeps + the scan-site unit tests keep it)
def test_chaos_sweep_scan_faults_identical(catalog):
    """PR 2 follow-up closed: the parquet reader carries named
    fault_point sites (scan.parquet.open / scan.parquet.read — OUTSIDE
    the corrupted-file catch, so injected io faults reach the retry
    tier instead of being swallowed as skipped files).  A latency +
    io-faulted scan profile must still produce bit-identical results
    with the delays only visible as wall time."""
    report = chaos_sweep(
        ["q42"], catalog,
        "scan.parquet.open:io:p=0.3,max=4,seed=3;"
        "scan.parquet.read:latency:p=0.5,seed=9,ms=2")
    assert report.ok, report.render()
    assert report.injected_total() > 0, report.render()
    assert all(r.identical for r in report.results), report.render()
    assert report.num_retries > 0, report.render()


def test_orc_scan_fault_sites_armed(tmp_path):
    """The orc reader's named sites (scan.orc.open / scan.orc.read)
    inject like every other fault point: io raises a retryable
    InjectedIOError, latency sleeps and leaves the rows identical."""
    import pyarrow as pa
    from pyarrow import orc

    from auron_tpu import faults
    from auron_tpu.config import conf
    from auron_tpu.ir.plan import FileGroup
    from auron_tpu.ir.schema import DataType, Field, Schema
    from auron_tpu.ops.base import TaskContext
    from auron_tpu.ops.scan.orc import OrcScanExec

    path = str(tmp_path / "t.orc")
    orc.write_table(pa.table({"x": list(range(10))}), path)
    schema = Schema((Field("x", DataType.int64()),))

    def scan_rows():
        op = OrcScanExec(schema, (FileGroup(paths=(path,)),))
        return [r for b in op.execute(TaskContext())
                for r in b.to_arrow().to_pylist()]

    baseline = scan_rows()
    assert [r["x"] for r in baseline] == list(range(10))

    io_spec = "scan.orc.open:io:p=1,max=1,seed=1"
    faults.reset(io_spec)
    with conf.scoped({"auron.faults.spec": io_spec}):
        with pytest.raises(faults.InjectedIOError):
            scan_rows()
        assert scan_rows() == baseline       # max=1: replay recovers

    lat_spec = "scan.orc.read:latency:p=1,max=2,seed=1,ms=1"
    faults.reset(lat_spec)
    with conf.scoped({"auron.faults.spec": lat_spec}):
        assert scan_rows() == baseline       # slowness, not failure
    assert faults.registry_for(lat_spec).injected_total() > 0
    faults.reset()


@pytest.mark.slow
def test_chaos_sweep_tier1_subset_p005(catalog):
    """The acceptance-gate sweep: the tier-1 TPC-DS subset under p=0.05
    faults on shuffle.push / shuffle.fetch / spill.write — bit-identical
    results, attempts <= 3x task count."""
    from test_tpcds_it import _TIER1_QUERIES
    spec = ("shuffle.push:io:p=0.05,seed=7;"
            "shuffle.fetch:io:p=0.05,seed=11;"
            "spill.write:io:p=0.05,seed=3")
    report = chaos_sweep(sorted(_TIER1_QUERIES), catalog, spec)
    assert report.ok, report.render()
    assert report.injected_total() > 0
    assert report.attempts_fault <= 3 * report.attempts_baseline


# ---------------------------------------------------------------------------
# SPMD rejection lint (analysis/spmd.py)
# ---------------------------------------------------------------------------

def _non_colocated_smj():
    from auron_tpu.ir import plan as P
    from auron_tpu.ir.expr import col
    from auron_tpu.ir.schema import DataType, Field, Schema
    schema = Schema((Field("k", DataType.int64()),))
    return P.SortMergeJoin(
        left=P.FFIReader(schema=schema, resource_id="L"),
        right=P.FFIReader(schema=schema, resource_id="R"),
        on=P.JoinOn(left_keys=(col("k"),), right_keys=(col("k"),)),
        join_type="inner")


def test_lint_spmd_reports_rejections_as_diagnostics():
    from auron_tpu.analysis.spmd import PASS_ID, lint_spmd
    res = lint_spmd(_non_colocated_smj(), None)
    assert len(res.diagnostics) == 1
    d = res.diagnostics[0]
    assert d.severity == "warning" and d.pass_id == PASS_ID
    assert d.node_kind == "sort_merge_join"
    assert "hash-colocated" in d.message
    assert res.ok   # warnings degrade, they don't fail verification


def test_lint_spmd_clean_plan_is_empty():
    from auron_tpu.analysis.spmd import lint_spmd
    from auron_tpu.ir import plan as P
    from auron_tpu.ir.schema import DataType, Field, Schema
    schema = Schema((Field("k", DataType.int64()),))
    plan = P.Filter(child=P.FFIReader(schema=schema, resource_id="T"),
                    predicates=())
    assert lint_spmd(plan, None).diagnostics == []


def test_rejection_diagnostic_from_exception():
    from auron_tpu.analysis.spmd import PASS_ID, rejection_diagnostic
    from auron_tpu.parallel.stage import SpmdUnsupported
    d = rejection_diagnostic(SpmdUnsupported("operator not "
                                             "SPMD-compilable: generate"),
                             _non_colocated_smj())
    assert d.pass_id == PASS_ID and d.severity == "warning"
    assert "generate" in d.message


@pytest.mark.slow
def test_tools_chaos_script():
    """tools/chaos_check.sh is the CI chaos gate; keep it green from
    pytest so a pipeline that only runs the suite still exercises it
    (slow: it spins its own catalog + sweep in a subprocess)."""
    import os
    import shutil
    import subprocess
    import sys
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "chaos_check.sh")
    if not os.path.exists(script) or shutil.which("bash") is None:
        pytest.skip("chaos script or bash unavailable")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(sys.path))
    out = subprocess.run(["bash", script, "--queries", "q03,q42"],
                         capture_output=True, text=True, timeout=500,
                         env=env)
    assert out.returncode == 0, out.stdout + out.stderr
