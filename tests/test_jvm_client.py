"""JVM host driving the engine boundary (VERDICT r3 #8).

Two layers:
- the Arrow-IPC byte algorithms the Java client transliterates
  (template splice + minimal flatbuffer reader) validate here against
  REAL pyarrow streams — these always run;
- the end-to-end Java client (compile with javac, drive the live TCP
  service, verify results incl. a wire_udf plan) runs when a JDK is
  present (gated, like the reference's JVM-first CI).
"""

import shutil
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu.jvm import ipc_template as T

JAVA_SRC = __file__.rsplit("/", 2)[0] + "/auron_tpu/jvm/AuronEngineClient.java"


def test_template_splice_readable_by_pyarrow():
    schema_msg, batch_meta, body_len, eos = T.ipc_segments(1000)
    k = (np.arange(1000) % 8).astype(np.int64)
    v = k * 1.5 + 1.0
    stream = T.splice_body(schema_msg, batch_meta, eos, k, v, body_len)
    [rb] = list(pa.ipc.open_stream(stream))
    assert rb.num_rows == 1000
    assert rb.column("k").to_pylist() == k.tolist()
    assert np.allclose(rb.column("v").to_numpy(), v)


def test_flatbuffer_reader_parses_pyarrow_stream():
    out = pa.record_batch({
        "k": pa.array([1, 2, None], type=pa.int64()),
        "s": pa.array([1.5, None, 3.25]),
        "c": pa.array([10, 20, 30], type=pa.int64())})
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, out.schema) as w:
        w.write_batch(out)
        w.write_batch(out)               # multi-batch stream
    ks, ss, cs = T.read_ksc_result(sink.getvalue().to_pybytes())
    assert ks.tolist() == [1, 2, 0] * 2
    assert cs.tolist() == [10, 20, 30] * 2
    assert np.allclose(ss, [1.5, 0.0, 3.25] * 2)


@pytest.mark.skipif(shutil.which("javac") is None or
                    shutil.which("java") is None,
                    reason="no JDK in this environment")
def test_java_client_drives_engine_service(tmp_path):
    from auron_tpu.service.engine import EngineServer

    T.write_templates(str(tmp_path / "tmpl"))
    subprocess.run(["javac", "-d", str(tmp_path), JAVA_SRC], check=True)
    server = EngineServer().start()
    try:
        host, port = server.address
        out = subprocess.run(
            ["java", "-cp", str(tmp_path), "AuronEngineClient",
             host, str(port), str(tmp_path / "tmpl")],
            capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "JVM_CLIENT_OK" in out.stdout
    finally:
        server.stop()
