"""Columnar substrate tests: arrow<->device roundtrip, padding invariants,
gather/concat, compressed IPC serde."""

import io
from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu.columnar.batch import Batch, DeviceColumn, DeviceStringColumn, \
    HostColumn, bucket_capacity, bucket_width, concat_batches
from auron_tpu.columnar import serde
from auron_tpu.ir.schema import DataType, Field, Schema


def test_buckets():
    assert bucket_capacity(0) == 1024
    assert bucket_capacity(1024) == 1024
    assert bucket_capacity(1025) == 2048
    assert bucket_width(1) == 8
    assert bucket_width(9) == 16
    assert bucket_width(300) == 256  # clamped to largest bucket


def _sample_rb():
    return pa.record_batch({
        "i32": pa.array([1, None, 3, -4], type=pa.int32()),
        "i64": pa.array([10, 20, None, 2**40], type=pa.int64()),
        "f64": pa.array([1.5, float("nan"), None, -0.0], type=pa.float64()),
        "b": pa.array([True, None, False, True], type=pa.bool_()),
        "s": pa.array(["hello", "", None, "wörld"], type=pa.utf8()),
        "dec": pa.array([Decimal("1.25"), None, Decimal("-3.50"),
                         Decimal("99.99")], type=pa.decimal128(10, 2)),
        "d": pa.array([0, 1, None, 19000], type=pa.int32()).cast(pa.date32()),
        "ts": pa.array([0, 1_000_000, None, -5], type=pa.int64()).cast(
            pa.timestamp("us")),
        "lst": pa.array([[1, 2], None, [], [3]], type=pa.list_(pa.int64())),
    })


def assert_rows_equal(exp_rows, got_rows):
    assert len(exp_rows) == len(got_rows)
    for e, g in zip(exp_rows, got_rows):
        for k in e:
            if isinstance(e[k], float) and e[k] != e[k]:
                assert g[k] != g[k], k  # NaN preserved
            else:
                assert g[k] == e[k], (k, e[k], g[k])


def test_arrow_roundtrip():
    rb = _sample_rb()
    b = Batch.from_arrow(rb)
    assert b.num_rows == 4 and b.capacity == 1024
    # types normalize (e.g. utf8 -> large_utf8); compare via pylist
    assert_rows_equal(rb.to_pylist(), b.to_arrow().to_pylist())


def test_padding_invariants():
    rb = _sample_rb()
    b = Batch.from_arrow(rb)
    i32 = b.columns[0]
    assert isinstance(i32, DeviceColumn)
    assert not np.asarray(i32.validity)[4:].any()
    assert (np.asarray(i32.data)[4:] == 0).all()
    # null slot is zeroed (canonical)
    assert np.asarray(i32.data)[1] == 0
    s = b.columns[4]
    assert isinstance(s, DeviceStringColumn)
    assert np.asarray(s.lengths)[2] == 0  # null string
    lst = b.columns[8]
    assert isinstance(lst, HostColumn)


def test_gather():
    rb = _sample_rb()
    b = Batch.from_arrow(rb)
    import jax.numpy as jnp
    idx = jnp.zeros(1024, dtype=jnp.int32).at[0].set(3).at[1].set(0).at[2].set(2)
    g = b.gather(idx, 3)
    rows = g.to_pylist()
    assert rows[0]["i32"] == -4 and rows[1]["i32"] == 1 and rows[2]["i32"] == 3
    assert rows[0]["s"] == "wörld"
    assert rows[2]["s"] is None  # null propagated through gather
    assert rows[2]["lst"] == []


def test_head_and_concat():
    rb = _sample_rb()
    b = Batch.from_arrow(rb)
    h = b.head(2)
    assert h.num_rows == 2
    assert len(h.to_pylist()) == 2
    c = concat_batches(b.schema, [h, b])
    assert c.num_rows == 6
    rows = c.to_pylist()
    assert rows[0]["s"] == "hello" and rows[2]["s"] == "hello"
    assert rows[5]["dec"] == Decimal("99.99")


def test_from_numpy():
    schema = Schema.of(Field("x", DataType.int64()), Field("y", DataType.float64()),
                       Field("s", DataType.string()))
    b = Batch.from_numpy(schema, [np.arange(5), np.linspace(0, 1, 5),
                                  np.array(["a", "bb", "ccc", "", "ddddé"])])
    rows = b.to_pylist()
    assert rows[4]["s"] == "ddddé"
    assert rows[2]["x"] == 2


def test_long_string_host_fallback():
    long = "x" * 5000
    rb = pa.record_batch({"s": pa.array([long, "short"])})
    b = Batch.from_arrow(rb)
    assert isinstance(b.columns[0], HostColumn)
    assert b.to_pylist()[0]["s"] == long


def test_ipc_serde_roundtrip():
    rb = _sample_rb()
    for codec in ("zstd", "zlib", "lz4", "none"):
        data = serde.serialize_batches([rb, rb], codec=codec)
        out = serde.deserialize_batches(data)
        assert len(out) == 2
        assert_rows_equal(rb.to_pylist(), out[0].to_pylist())
    assert serde.deserialize_batches(b"") == []


def test_ipc_serde_truncated():
    data = serde.serialize_batches([_sample_rb()])
    with pytest.raises(EOFError):
        serde.deserialize_batches(data[:-3])


def test_empty_batch():
    schema = Schema.of(Field("x", DataType.int64()), Field("s", DataType.string()))
    b = Batch.empty(schema)
    assert b.num_rows == 0
    assert b.to_arrow().num_rows == 0
    c = concat_batches(schema, [])
    assert c.num_rows == 0
