"""Differential tests: device expression compiler vs host oracle.

The load-bearing test idea from the reference (AuronQueryTest.
checkSparkAnswerAndOperator runs every query with the engine on and off and
compares): here every expression is evaluated by the jitted device path and
the numpy/pyarrow host path over the same batch, results must agree.
"""

import math

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu.columnar.batch import Batch
from auron_tpu.exprs.compiler import build_evaluator, device_capable
from auron_tpu.exprs import host_eval
from auron_tpu.exprs.typing import infer_type
from auron_tpu.ir import expr as E
from auron_tpu.ir.expr import col, lit
from auron_tpu.ir.schema import DataType, Field, Schema, from_arrow_schema


def make_batch(n=200, seed=0):
    rng = np.random.default_rng(seed)
    i32 = rng.integers(-1000, 1000, n).astype(np.int32)
    i64 = rng.integers(-10**12, 10**12, n).astype(np.int64)
    f64 = np.where(rng.random(n) < 0.1, np.nan, rng.normal(0, 100, n))
    f64 = np.where(rng.random(n) < 0.05, 0.0, f64)
    words = np.array(["apple", "Banana", "cherry pie", "", "дом", "x" * 20,
                      "prefix_mid_suffix", "  pad  "], dtype=object)
    s = words[rng.integers(0, len(words), n)]
    days = rng.integers(-3000, 20000, n).astype(np.int32)
    ts = rng.integers(-10**14, 2 * 10**15, n).astype(np.int64)
    b = rng.random(n) < 0.5

    def nullify(arr, p=0.15):
        m = rng.random(n) >= p
        return arr, m

    cols, masks = {}, {}
    for name, arr in [("i32", i32), ("i64", i64), ("f64", f64), ("s", s),
                      ("d", days), ("ts", ts), ("b", b)]:
        a, m = nullify(arr)
        cols[name] = a
        masks[name] = m
    rb = pa.record_batch({
        "i32": pa.array(cols["i32"], mask=~masks["i32"]),
        "i64": pa.array(cols["i64"], mask=~masks["i64"]),
        "f64": pa.array(cols["f64"], mask=~masks["f64"]),
        "s": pa.array([v if m else None
                       for v, m in zip(cols["s"], masks["s"])], type=pa.utf8()),
        "d": pa.array(cols["d"], mask=~masks["d"]).cast(pa.date32()),
        "ts": pa.array(cols["ts"], mask=~masks["ts"]).cast(pa.timestamp("us")),
        "b": pa.array(cols["b"], mask=~masks["b"]),
    })
    return rb


def check_expr(expr, rb=None, rtol=1e-9, expect_device=None):
    rb = rb if rb is not None else make_batch()
    schema = from_arrow_schema(rb.schema)
    batch = Batch.from_arrow(rb)
    if expect_device is not None:
        assert device_capable(expr, schema, frozenset()) == expect_device, \
            f"device_capable mismatch for {expr}"
    ev = build_evaluator([expr], schema)
    [dev_col] = ev(batch)
    from auron_tpu.columnar.arrow_interop import column_to_arrow
    dt = infer_type(expr, schema)
    got = column_to_arrow(dev_col.dtype if hasattr(dev_col, "dtype") else dt,
                          dev_col, batch.num_rows).to_pylist()
    exp = host_eval.evaluate_arrow(expr, rb, schema).to_pylist()
    assert len(got) == len(exp)
    for i, (g, e) in enumerate(zip(got, exp)):
        if e is None or g is None:
            assert g == e, f"row {i}: device={g!r} host={e!r} expr={expr}"
        elif isinstance(e, float):
            if math.isnan(e):
                assert isinstance(g, float) and math.isnan(g), f"row {i}"
            else:
                assert g == pytest.approx(e, rel=rtol, abs=1e-9), \
                    f"row {i}: device={g!r} host={e!r}"
        else:
            assert g == e, f"row {i}: device={g!r} host={e!r} expr={expr}"


# ---------------------------------------------------------------------------

def test_arithmetic():
    check_expr(E.BinaryExpr(left=col("i32"), op="+", right=col("i64")))
    check_expr(E.BinaryExpr(left=col("i32"), op="*", right=lit(3)))
    check_expr(E.BinaryExpr(left=col("f64"), op="-", right=col("i32")))
    check_expr(E.BinaryExpr(left=col("i64"), op="%", right=lit(7)))
    check_expr(E.BinaryExpr(left=col("i64"), op="%", right=lit(0)))  # -> null
    check_expr(E.BinaryExpr(left=col("i32"), op="/", right=col("i32")))


def test_division_semantics():
    # int / int -> double; divide by zero -> null (non-ANSI Spark)
    rb = pa.record_batch({"a": pa.array([10, 7, -9, None], type=pa.int32()),
                          "b": pa.array([3, 0, 2, 5], type=pa.int32())})
    check_expr(E.BinaryExpr(left=col("a"), op="/", right=col("b")), rb)


def test_comparisons_nan():
    check_expr(E.BinaryExpr(left=col("f64"), op=">", right=lit(0.0)))
    check_expr(E.BinaryExpr(left=col("f64"), op="==", right=col("f64")))
    check_expr(E.BinaryExpr(left=col("f64"), op="<=", right=col("f64")))
    check_expr(E.BinaryExpr(left=col("i32"), op="<=>", right=col("i32")))


def test_logic_kleene():
    a = E.BinaryExpr(left=col("i32"), op=">", right=lit(0))
    b = E.BinaryExpr(left=col("f64"), op="<", right=lit(50.0))
    check_expr(E.BinaryExpr(left=a, op="and", right=b))
    check_expr(E.BinaryExpr(left=a, op="or", right=b))
    check_expr(E.ScAnd(left=a, right=b))
    check_expr(E.Not(child=a))


def test_null_checks():
    check_expr(E.IsNull(child=col("s")))
    check_expr(E.IsNotNull(child=col("i64")))


def test_case_when():
    e = E.Case(branches=(
        E.WhenThen(when=E.BinaryExpr(left=col("i32"), op=">", right=lit(100)),
                   then=lit(1)),
        E.WhenThen(when=E.BinaryExpr(left=col("i32"), op=">", right=lit(0)),
                   then=lit(2)),
    ), else_expr=lit(3))
    check_expr(e)


def test_case_null_first_branch():
    """A null-literal FIRST branch must not poison the Case dtype to
    its bool placeholder (q39's cov: CASE WHEN m=0 THEN null ELSE s/m
    END came back as a 1-byte column declared f64)."""
    from auron_tpu.ir.schema import DataType
    e = E.Case(branches=(
        E.WhenThen(when=E.BinaryExpr(left=col("f64"), op="==",
                                     right=lit(0.0)),
                   then=lit(None, DataType.null())),
    ), else_expr=E.BinaryExpr(left=col("f64"), op="*", right=lit(2.0)))
    check_expr(e)
    # string flavor: null branch beside a string else
    e2 = E.Case(branches=(
        E.WhenThen(when=E.BinaryExpr(left=col("i32"), op=">",
                                     right=lit(10 ** 9)),
                   then=lit(None, DataType.null())),
    ), else_expr=col("s"))
    check_expr(e2)


def test_case_branch_type_promotion():
    """An int THEN beside a float ELSE promotes to float (q39's
    `CASE mean WHEN 0 THEN 0 ELSE stdev/mean END > 1` truncated the
    ratios to int and dropped every row)."""
    from auron_tpu.exprs.typing import infer_type
    from auron_tpu.ir.schema import DataType
    e = E.Case(branches=(
        E.WhenThen(when=E.BinaryExpr(left=col("f64"), op="==",
                                     right=lit(0.0)),
                   then=lit(0)),
    ), else_expr=E.BinaryExpr(left=col("f64"), op="/", right=lit(3.0)))
    rb = make_batch()
    from auron_tpu.ir.schema import from_arrow_schema
    assert infer_type(e, from_arrow_schema(rb.schema)) == \
        DataType.float64()
    check_expr(e)


def test_in_list():
    check_expr(E.InList(child=col("i32"), values=(lit(1), lit(2), lit(500))))
    check_expr(E.InList(child=col("s"), values=(lit("apple"), lit("дом")),
                        negated=True))


def test_casts_device():
    check_expr(E.Cast(child=col("i64"), dtype=DataType.int32()))
    check_expr(E.Cast(child=col("f64"), dtype=DataType.int64()))
    check_expr(E.Cast(child=col("i32"), dtype=DataType.float64()))
    check_expr(E.Cast(child=col("i64"), dtype=DataType.string()),
               expect_device=True)
    check_expr(E.Cast(child=col("b"), dtype=DataType.string()))
    check_expr(E.Cast(child=col("ts"), dtype=DataType.date32()))
    check_expr(E.Cast(child=col("d"), dtype=DataType.timestamp_us()))


def test_cast_string_host_island():
    rb = pa.record_batch({"s": pa.array(["12", "-3", "bad", " 4 ", None,
                                         "1.5", "99999999999999999999"])})
    check_expr(E.Cast(child=col("s"), dtype=DataType.int32()), rb,
               expect_device=False)
    check_expr(E.Cast(child=col("s"), dtype=DataType.float64()), rb)


def test_string_predicates():
    check_expr(E.StringStartsWith(child=col("s"), prefix="ap"),
               expect_device=True)
    check_expr(E.StringEndsWith(child=col("s"), suffix="pie"))
    check_expr(E.StringContains(child=col("s"), infix="mid"))
    check_expr(E.Like(child=col("s"), pattern=lit("%pie%")),
               expect_device=True)
    check_expr(E.Like(child=col("s"), pattern=lit("a_ple")),
               expect_device=False)  # underscore -> host regex


def test_string_case():
    f = E.ScalarFunctionCall
    # default: exact unicode on host
    check_expr(f(name="upper", args=(col("s"),)), expect_device=False)
    # ASCII fast path opt-in: device kernel on ASCII data
    from auron_tpu.config import conf
    rb = pa.record_batch({"s": pa.array(["Abc", "XYZ", "", None, "a1!"])})
    with conf.scoped({"auron.string.ascii.case.enable": True}):
        check_expr(f(name="upper", args=(col("s"),)), rb, expect_device=True)
        check_expr(f(name="lower", args=(col("s"),)), rb)


def test_string_functions():
    f = E.ScalarFunctionCall
    check_expr(f(name="octet_length", args=(col("s"),)))
    check_expr(f(name="character_length", args=(col("s"),)))
    check_expr(f(name="substr", args=(col("s"), lit(2), lit(3))))
    check_expr(f(name="substr", args=(col("s"), lit(-3), lit(2))))
    check_expr(f(name="concat", args=(col("s"), lit("!"), col("s"))))
    check_expr(f(name="trim", args=(col("s"),)))
    check_expr(f(name="ltrim", args=(col("s"),)))
    check_expr(f(name="reverse", args=(col("s"),)),
               rb=pa.record_batch({"s": pa.array(["abc", "", "a", None])}))
    check_expr(f(name="strpos", args=(col("s"), lit("e"))))
    check_expr(f(name="repeat", args=(col("s"), lit(2))),
               rb=pa.record_batch({"s": pa.array(["ab", "", None])}))
    check_expr(f(name="lpad", args=(col("s"), lit(8), lit("*"))),
               rb=pa.record_batch({"s": pa.array(["ab", "longerthan8", None])}))
    check_expr(f(name="rpad", args=(col("s"), lit(8), lit("xy"))),
               rb=pa.record_batch({"s": pa.array(["ab", "longerthan8", None])}))
    check_expr(f(name="ascii", args=(col("s"),)))
    check_expr(f(name="left", args=(col("s"), lit(3))))
    check_expr(f(name="right", args=(col("s"), lit(3))))


def test_math_functions():
    f = E.ScalarFunctionCall
    for name in ("abs", "sqrt", "exp", "ln", "sin", "cos", "floor", "ceil",
                 "signum"):
        check_expr(f(name=name, args=(col("f64"),)))
    check_expr(f(name="power", args=(col("f64"), lit(2.0))))
    check_expr(f(name="round", args=(col("f64"), lit(2))))
    check_expr(f(name="is_nan", args=(col("f64"),)))
    check_expr(f(name="factorial", args=(E.Cast(child=E.BinaryExpr(
        left=col("i32"), op="%", right=lit(25)), dtype=DataType.int32()),)))


def test_log_null_semantics():
    """Spark UnaryLogExpression / Logarithm: NULL outside the domain
    (x<=0, base<=0); base==1 allowed -> ±Inf/NaN by IEEE division."""
    f = E.ScalarFunctionCall
    rb = pa.record_batch({"x": pa.array([2.0, 0.0, -3.0, 1.0, None]),
                          "b": pa.array([10.0, 2.0, 2.0, 1.0, 2.0])})
    for name in ("ln", "log10", "log2"):
        check_expr(f(name=name, args=(col("x"),)), rb)
    check_expr(f(name="log", args=(col("x"),)), rb)
    check_expr(f(name="log", args=(col("b"), col("x"))), rb)
    # explicit value assertions (not just device/host agreement)
    schema = from_arrow_schema(rb.schema)
    got = host_eval.evaluate_arrow(
        f(name="log", args=(col("b"), col("x"))), rb, schema).to_pylist()
    assert got[0] == pytest.approx(math.log(2.0) / math.log(10.0))
    assert got[1] is None and got[2] is None      # x <= 0 -> NULL
    assert math.isnan(got[3])      # base==1, x==1: ln(1)/ln(1) = 0/0 = NaN
    assert got[4] is None
    got_ln = host_eval.evaluate_arrow(
        f(name="ln", args=(col("x"),)), rb, schema).to_pylist()
    assert got_ln[1] is None and got_ln[2] is None


def test_conditional_functions():
    f = E.ScalarFunctionCall
    check_expr(f(name="coalesce", args=(col("i32"), col("i64"), lit(0))))
    check_expr(f(name="nvl", args=(col("f64"), lit(0.0))))
    check_expr(f(name="nvl2", args=(col("i32"), lit(1), lit(2))))
    check_expr(f(name="null_if", args=(col("i32"), lit(5))))
    check_expr(f(name="least", args=(col("i32"), lit(0))))
    check_expr(f(name="greatest", args=(col("i32"), col("i32"), lit(10))))


def test_date_functions():
    f = E.ScalarFunctionCall
    for name in ("year", "quarter", "month", "day", "day_of_week",
                 "week_of_year"):
        check_expr(f(name=name, args=(col("d"),)))
    for name in ("hour", "minute", "second"):
        check_expr(f(name=name, args=(col("ts"),)))
    check_expr(f(name="last_day", args=(col("d"),)))
    check_expr(f(name="date_add", args=(col("d"), lit(30))))
    check_expr(f(name="datediff", args=(col("d"), lit(100))))
    check_expr(E.BinaryExpr(left=col("d"), op="-", right=col("d")))


def test_date_arith():
    check_expr(E.BinaryExpr(left=col("d"), op="+", right=lit(10)))


def test_rownum_partition_exprs():
    check_expr(E.RowNum())
    check_expr(E.SparkPartitionId())
    check_expr(E.MonotonicallyIncreasingId())


def test_hash_functions():
    f = E.ScalarFunctionCall
    check_expr(f(name="murmur3_hash", args=(col("i32"), col("i64"))))
    check_expr(f(name="murmur3_hash", args=(col("s"),)))
    check_expr(f(name="murmur3_hash", args=(col("f64"),)))
    check_expr(f(name="xxhash64", args=(col("i64"),)))


def test_murmur3_spark_golden():
    """Golden vectors generated with Spark Murmur3_x86_32 / XxHash64
    (same vectors the reference asserts in spark_hash.rs tests)."""
    from auron_tpu.native.bindings import murmur3_32, xxhash64
    i32 = lambda v: (v).to_bytes(4, "little", signed=True)  # noqa: E731
    i64 = lambda v: (v).to_bytes(8, "little", signed=True)  # noqa: E731
    assert murmur3_32(i32(1), 42) == -559580957
    assert murmur3_32(i32(2), 42) == 1765031574
    assert murmur3_32(i32(3), 42) == -1823081949
    assert (murmur3_32(i64(1), 42) & 0xFFFFFFFF) == 0x99f0149d
    assert (murmur3_32(i64(0), 42) & 0xFFFFFFFF) == 0x9c67b85d
    for s, exp in [("hello", 3286402344), ("bar", 2486176763),
                   ("", 142593372), ("😁", 885025535), ("天地", 2395000894)]:
        assert (murmur3_32(s.encode(), 42) & 0xFFFFFFFF) == exp
    as_i64 = lambda x: x if x < 2**63 else x - 2**64  # noqa: E731
    assert as_i64(xxhash64(i64(1), 42)) == -7001672635703045582
    assert as_i64(xxhash64(b"", 42)) == -7444071767201028348
    assert as_i64(xxhash64(b"hello", 42)) == -4367754540140381902


def test_murmur3_device_spark_golden():
    """Device jnp murmur3 matches the same Spark golden vectors."""
    import jax.numpy as jnp
    from auron_tpu.columnar.batch import Batch
    from auron_tpu.exprs import hashing as H
    schema = Schema.of(Field("x", DataType.int32()),
                       Field("y", DataType.int64()),
                       Field("s", DataType.string()))
    b = Batch.from_numpy(schema, [np.array([1, 2, 3], np.int32),
                                  np.array([1, 0, -1], np.int64),
                                  np.array(["hello", "", "天地"])])
    hx = np.asarray(H.hash_columns([b.columns[0]], seed=42))[:3]
    assert list(hx) == [-559580957, 1765031574, -1823081949]
    hy = np.asarray(H.hash_columns([b.columns[1]], seed=42))[:3]
    assert [h & 0xFFFFFFFF for h in hy.tolist()] == [0x99f0149d, 0x9c67b85d,
                                                     0xc8008529]
    hs = np.asarray(H.hash_columns([b.columns[2]], seed=42))[:3]
    assert [h & 0xFFFFFFFF for h in hs.tolist()] == [3286402344, 142593372,
                                                     2395000894]


def test_host_island_regex():
    f = E.ScalarFunctionCall
    check_expr(f(name="regexp_replace",
                 args=(col("s"), lit("[aeiou]"), lit("*")),
                 return_type=DataType.string()), expect_device=False)
    check_expr(f(name="md5", args=(col("s"),),
                 return_type=DataType.string()))


def test_get_json_object():
    rb = pa.record_batch({"j": pa.array(
        ['{"a": {"b": 1}, "c": [1,2,3]}', '{"a": 2}', "not json", None,
         '{"c": [{"d": "x"}]}'])})
    f = E.ScalarFunctionCall
    check_expr(f(name="get_json_object", args=(col("j"), lit("$.a.b")),
                 return_type=DataType.string()), rb)
    check_expr(f(name="get_json_object", args=(col("j"), lit("$.c[1]")),
                 return_type=DataType.string()), rb)
    check_expr(f(name="get_json_object", args=(col("j"), lit("$.c[0].d")),
                 return_type=DataType.string()), rb)


def _sample_udf(a, b):
    return (a or 0) * 2 + (b or 0)


def test_py_udf_wrapper():
    import pickle
    expr = E.PyUdfWrapper(serialized=pickle.dumps(_sample_udf),
                          args=(col("i32"), col("i32")),
                          return_type=DataType.int64())
    check_expr(expr, expect_device=False)


def test_decimal_ops():
    from decimal import Decimal
    rb = pa.record_batch({
        "p": pa.array([Decimal("1.25"), Decimal("-3.10"), None,
                       Decimal("99.99")], type=pa.decimal128(10, 2)),
    })
    check_expr(E.BinaryExpr(left=col("p"), op="+", right=col("p")), rb)
    check_expr(E.BinaryExpr(left=col("p"), op=">", right=lit(0)), rb)
    check_expr(E.Cast(child=col("p"), dtype=DataType.float64()), rb)
    check_expr(E.Cast(child=col("p"), dtype=DataType.decimal(10, 3)), rb)
    f = E.ScalarFunctionCall
    check_expr(f(name="unscaled_value", args=(col("p"),)), rb)


def test_bloom_filter_roundtrip():
    from auron_tpu.ops.agg.bloom import BloomFilter, optimal_num_bits
    vals = np.arange(100, dtype=np.int64)
    bf = BloomFilter(optimal_num_bits(100), 5)
    bf.put_values(vals, DataType.int64(), np.ones(100, bool))
    rb = pa.record_batch({"x": pa.array([5, 50, 1000, 2000, None],
                                        type=pa.int64())})
    expr = E.BloomFilterMightContain(
        bloom_filter=E.Literal(value=bf.to_bytes(), dtype=DataType.binary()),
        value=col("x"))
    schema = from_arrow_schema(rb.schema)
    batch = Batch.from_arrow(rb)
    ev = build_evaluator([expr], schema)
    [out] = ev(batch)
    got = np.asarray(out.data)[:5]
    assert got[0] and got[1]          # members always hit
    assert not got[2] and not got[3]  # very likely miss
    # host path agrees
    hv = host_eval.evaluate(expr, rb, schema)
    assert list(hv.vals[:2]) == [True, True]


def test_negative_decimal_rescale():
    """Regression: HALF_UP rescale must operate on magnitude (review
    finding: -2.4 -> -4 with the floor-division pattern)."""
    from decimal import Decimal
    rb = pa.record_batch({"p": pa.array(
        [Decimal("-2.4"), Decimal("-2.5"), Decimal("2.5"), Decimal("-0.4")],
        type=pa.decimal128(5, 1))})
    check_expr(E.Cast(child=col("p"), dtype=DataType.decimal(5, 0)), rb)
    got = None
    schema = from_arrow_schema(rb.schema)
    batch = Batch.from_arrow(rb)
    [out] = build_evaluator(
        [E.Cast(child=col("p"), dtype=DataType.decimal(5, 0))], schema)(batch)
    vals = np.asarray(out.data)[:4].tolist()
    assert vals == [-2, -3, 3, 0]


def test_int64_min_to_string():
    rb = pa.record_batch({"x": pa.array([-2**63, 2**63 - 1, 0, -1],
                                        type=pa.int64())})
    check_expr(E.Cast(child=col("x"), dtype=DataType.string()), rb)


def test_trim_chars_host_fallback():
    rb = pa.record_batch({"s": pa.array(["xxabcx", "abc", None])})
    f = E.ScalarFunctionCall
    e = f(name="ltrim", args=(col("s"), lit("x")),
          return_type=DataType.string())
    check_expr(e, rb, expect_device=False)


def test_least_promotion():
    rb = pa.record_batch({"a": pa.array([1, 2, None], type=pa.int32()),
                          "b": pa.array([2**40, -2**40, 5], type=pa.int64())})
    f = E.ScalarFunctionCall
    check_expr(f(name="least", args=(col("a"), col("b"))), rb)
    check_expr(f(name="greatest", args=(col("a"), col("b"))), rb)


# ---------------------------------------------------------------------------
# wire_udf: the wire-registerable (expression-tree-body) UDF
# ---------------------------------------------------------------------------

def _affine_udf(arg):
    """udf(x) = x * 2 + 1 — the restricted-expression-language UDF a
    foreign host ships over the engine service (ir/expr.py WireUdf; the
    C++ twin lives in native/engine_client.cpp step 5)."""
    return E.WireUdf(
        name="affine", params=("x",),
        body=E.BinaryExpr(
            left=E.BinaryExpr(left=col("x"), op="*", right=lit(2.0)),
            op="+", right=lit(1.0)),
        args=(arg,))


def test_wire_udf_device_host_agree():
    # nulls propagate through the body's arithmetic; device == host
    check_expr(_affine_udf(col("f64")), expect_device=True)
    check_expr(_affine_udf(col("i32")), expect_device=True)


def test_wire_udf_nested_and_multi_param():
    dist2 = E.WireUdf(
        name="dist2", params=("a", "b"),
        body=E.BinaryExpr(
            left=E.BinaryExpr(left=col("a"), op="*", right=col("a")),
            op="+",
            right=E.BinaryExpr(left=col("b"), op="*", right=col("b"))),
        args=(col("i32"), _affine_udf(col("f64"))))
    check_expr(dist2, expect_device=True)


def test_wire_udf_host_body_falls_back():
    # a body needing the host path (string upper without the ascii
    # opt-in) makes the whole call a host island — still correct
    up = E.WireUdf(
        name="up", params=("t",),
        body=E.ScalarFunctionCall(name="upper", args=(col("t"),),
                                  return_type=DataType.string()),
        args=(col("s"),))
    from auron_tpu.config import conf
    with conf.scoped({"auron.string.ascii.case.enable": False}):
        check_expr(up, expect_device=False)


def test_wire_udf_param_arity_mismatch_rejected():
    bad = E.WireUdf(name="bad", params=("x", "y"),
                    body=col("x"), args=(col("i32"),))
    rb = make_batch()
    schema = from_arrow_schema(rb.schema)
    assert not device_capable(bad, schema, frozenset())
    with pytest.raises(TypeError, match="params"):
        infer_type(bad, schema)


def test_wire_udf_bound_reference_body_positional():
    # a body referencing params by ORDINAL must bind to the argument
    # values on both paths — the host path used to read the enclosing
    # batch's column at that index instead (ADVICE r4): with args=f64
    # and enclosing column 0 = i32, the divergence is loud
    by_ordinal = E.WireUdf(
        name="bref", params=("x",),
        body=E.BinaryExpr(left=E.BoundReference(index=0), op="+",
                          right=lit(1.0)),
        args=(col("f64"),))
    check_expr(by_ordinal, expect_device=True)
    # out-of-range ordinal: loud host error, not an enclosing-batch read
    import auron_tpu.exprs.host_eval as host_eval_mod
    rb = make_batch()
    schema = from_arrow_schema(rb.schema)
    bad = E.WireUdf(name="oob", params=("x",),
                    body=E.BoundReference(index=3), args=(col("f64"),))
    with pytest.raises(IndexError, match="out of range"):
        host_eval_mod.evaluate_arrow(bad, rb, schema)


def test_wire_udf_case_sensitive_param_dups():
    from auron_tpu.config import conf
    aA = E.WireUdf(
        name="aA", params=("a", "A"),
        body=E.BinaryExpr(left=col("a"), op="-", right=col("A")),
        args=(col("f64"), col("i32")))
    # case-insensitive (default): ('a','A') collide -> rejected
    with pytest.raises(TypeError, match="duplicate param"):
        infer_type(aA, from_arrow_schema(make_batch().schema))
    # case-sensitive: distinct params, resolved per-case on both paths
    with conf.scoped({"auron.case.sensitive": True}):
        check_expr(aA, expect_device=True)


def test_wire_udf_serde_roundtrip():
    from auron_tpu.ir import plan as P
    from auron_tpu.ir import serde
    u = _affine_udf(col("f64"))
    td = P.TaskDefinition(
        plan=P.Projection(
            child=P.FFIReader(
                schema=Schema((Field("f64", DataType.float64()),)),
                resource_id="s"),
            exprs=(u,), names=("u",)),
        stage_id=0, partition_id=0, num_partitions=1, host_threads=0)
    assert serde.deserialize(serde.serialize(td)) == td


def test_wire_udf_rides_the_spmd_mesh():
    # fully device-capable -> compiles into the shard_map stage program
    import jax
    from auron_tpu.ir import plan as P
    from auron_tpu.parallel.mesh import data_mesh
    from auron_tpu.parallel.stage import execute_plan_spmd

    if jax.default_backend() != "cpu" or len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    n = 4000
    rng = np.random.default_rng(3)
    t = pa.table({"k": rng.integers(0, 16, n).astype(np.int64),
                  "v": rng.normal(0, 1, n).astype(np.float64)})
    plan = P.Projection(
        child=P.FFIReader(schema=from_arrow_schema(t.schema),
                          resource_id="t"),
        exprs=(col("k"), _affine_udf(col("v"))), names=("k", "u"))

    class _C:
        exchanges: dict = {}
        broadcasts: dict = {}
    out = execute_plan_spmd(plan, _C(), data_mesh(8), {"t": t})
    got = np.asarray(out.column("u").to_pylist())
    want = t.column("v").to_numpy() * 2.0 + 1.0
    assert out.num_rows == n
    assert np.allclose(np.sort(got), np.sort(want))


def test_wire_udf_null_and_zero_arg_shapes():
    # NULL-typed argument: propagates as all-null, host == device
    nullarg = E.WireUdf(name="n", params=("x",),
                        body=E.BinaryExpr(left=col("x"), op="+",
                                          right=lit(1.0)),
                        args=(E.Literal(value=None),))
    check_expr(nullarg)
    # zero-arg UDF: a constant over every row (host path must not
    # collapse to a 0-row synthetic batch)
    const = E.WireUdf(name="c", params=(), body=lit(7.5), args=())
    rb = make_batch(n=13)
    schema = from_arrow_schema(rb.schema)
    hv = host_eval.evaluate_arrow(const, rb, schema)
    assert hv.to_pylist() == [7.5] * 13
    check_expr(const, rb)


def test_wire_udf_wire_validation():
    rb = make_batch()
    schema = from_arrow_schema(rb.schema)
    # duplicate params (incl. case-insensitive collision) are rejected
    for params in (("x", "x"), ("a", "A")):
        dup = E.WireUdf(name="d", params=params, body=col("x"),
                        args=(col("i32"), col("i64")))
        assert not device_capable(dup, schema, frozenset())
        with pytest.raises(TypeError, match="duplicate"):
            infer_type(dup, schema)
    # a wire message without a body is a typed validation error, not an
    # AttributeError from deep inside analysis
    nobody = E.WireUdf(name="nb", params=(), body=None, args=())
    assert not device_capable(nobody, schema, frozenset())
    with pytest.raises(TypeError, match="body"):
        infer_type(nobody, schema)
