"""Join operator tests vs a python oracle, across all join types
(the joins/test.rs build_table_i32 fixture style, SURVEY §4)."""

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu.columnar.batch import Batch
from auron_tpu.ir.expr import col
from auron_tpu.ir.plan import JoinOn
from auron_tpu.ir.schema import from_arrow_schema
from auron_tpu.ops.base import TaskContext
from auron_tpu.ops.basic import MemoryScanExec
from auron_tpu.ops.joins import (
    BroadcastJoinBuildHashMapExec, BroadcastJoinExec, HashJoinExec,
    SortMergeJoinExec,
)


def scan_of(rows, schema=None, chunk=64):
    t = pa.Table.from_pylist(rows, schema=schema)
    batches = [Batch.from_arrow(b) for b in t.to_batches(max_chunksize=chunk)] \
        if rows else []
    return MemoryScanExec(from_arrow_schema(t.schema), batches)


def collect(op):
    out = [b.to_arrow() for b in op.execute_with_metrics(TaskContext())]
    if not out:
        return []
    return pa.Table.from_batches(out).to_pylist()


def oracle_join(left, right, lk, rk, how):
    from collections import defaultdict
    rmap = defaultdict(list)
    for r in right:
        if r[rk] is not None:
            rmap[r[rk]].append(r)
    out = []
    rmatched = set()
    for l in left:
        matches = rmap.get(l[lk], []) if l[lk] is not None else []
        if how in ("inner", "left", "right", "full"):
            for m in matches:
                out.append({**l, **m})
                rmatched.add(id(m))
            if not matches and how in ("left", "full"):
                out.append({**l, **{k: None for k in right[0]}})
        elif how == "left_semi" and matches:
            out.append(dict(l))
        elif how == "left_anti" and not matches:
            out.append(dict(l))
        elif how == "existence":
            out.append({**l, "exists": bool(matches)})
    if how in ("right", "full"):
        for r in right:
            if id(r) not in rmatched:
                out.append({**{k: None for k in left[0]}, **r})
    return out


def canon(rows):
    def key(r):
        return tuple((k, v is None, v) for k, v in
                     sorted(r.items(), key=lambda kv: kv[0]))
    return sorted([key(r) for r in rows],
                  key=lambda t: tuple((k, nn, str(v)) for k, nn, v in t))


def make_sides(rng, nl=300, nr=200, key_range=60, null_p=0.1):
    left = [{"lk": (None if rng.random() < null_p
                    else int(rng.integers(0, key_range))),
             "lv": i} for i in range(nl)]
    right = [{"rk": (None if rng.random() < null_p
                     else int(rng.integers(0, key_range))),
              "rv": 1000 + i} for i in range(nr)]
    return left, right


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti", "existence"])
def test_hash_join_types(how):
    rng = np.random.default_rng(3)
    left, right = make_sides(rng)
    op = HashJoinExec(scan_of(left), scan_of(right),
                      JoinOn(left_keys=(col("lk"),), right_keys=(col("rk"),)),
                      how, build_side="right")
    got = collect(op)
    exp = oracle_join(left, right, "lk", "rk", how)
    assert canon(got) == canon(exp), how


@pytest.mark.parametrize("how", ["inner", "left", "right", "full"])
def test_hash_join_build_left(how):
    rng = np.random.default_rng(4)
    left, right = make_sides(rng, nl=150, nr=250)
    op = HashJoinExec(scan_of(left), scan_of(right),
                      JoinOn(left_keys=(col("lk"),), right_keys=(col("rk"),)),
                      how, build_side="left")
    got = collect(op)
    exp = oracle_join(left, right, "lk", "rk", how)
    assert canon(got) == canon(exp), how


def test_right_semi_anti():
    rng = np.random.default_rng(5)
    left, right = make_sides(rng, nl=100, nr=100)
    for how in ("right_semi", "right_anti"):
        op = HashJoinExec(scan_of(left), scan_of(right),
                          JoinOn(left_keys=(col("lk"),),
                                 right_keys=(col("rk"),)),
                          how, build_side="left")
        got = collect(op)
        # mirror oracle: swap sides, use left_semi/anti
        exp = oracle_join(right, left, "rk", "lk",
                          how.replace("right", "left"))
        assert canon(got) == canon(exp), how


def test_string_keys_join():
    left = [{"k": w, "i": i} for i, w in enumerate(
        ["apple", "pear", None, "fig", "apple", "kiwi"])]
    right = [{"k2": w, "j": i} for i, w in enumerate(
        ["apple", "fig", "fig", None, "grape"])]
    op = HashJoinExec(scan_of(left), scan_of(right),
                      JoinOn(left_keys=(col("k"),), right_keys=(col("k2"),)),
                      "inner")
    got = collect(op)
    exp = []
    for l in left:
        for r in right:
            if l["k"] is not None and l["k"] == r["k2"]:
                exp.append({**l, **r})
    assert canon(got) == canon(exp)


def test_multi_key_join():
    rng = np.random.default_rng(6)
    left = [{"a": int(rng.integers(0, 5)), "b": int(rng.integers(0, 5)),
             "i": i} for i in range(120)]
    right = [{"a2": int(rng.integers(0, 5)), "b2": int(rng.integers(0, 5)),
              "j": i} for i in range(80)]
    op = HashJoinExec(scan_of(left), scan_of(right),
                      JoinOn(left_keys=(col("a"), col("b")),
                             right_keys=(col("a2"), col("b2"))), "inner")
    got = collect(op)
    exp = [{**l, **r} for l in left for r in right
           if l["a"] == r["a2"] and l["b"] == r["b2"]]
    assert canon(got) == canon(exp)


def sort_rows(rows, key):
    # nulls first, like the SMJ's required child ordering
    return sorted(rows, key=lambda r: (r[key] is not None, r[key] or 0))


@pytest.mark.slow   # PR 18 tier-1 re-split (8.1s; SMJ fast coverage
#   stays via test_smj_giant_group + the mesh corpus q93s pin)
def test_smj_matches_hash_join():
    rng = np.random.default_rng(8)
    left, right = make_sides(rng, nl=200, nr=200)
    left, right = sort_rows(left, "lk"), sort_rows(right, "rk")
    on = JoinOn(left_keys=(col("lk"),), right_keys=(col("rk"),))
    for how in ("inner", "left", "full", "left_semi", "left_anti"):
        smj = SortMergeJoinExec(scan_of(left), scan_of(right), on, how)
        exp = oracle_join(left, right, "lk", "rk", how)
        assert canon(collect(smj)) == canon(exp), how


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti", "existence"])
def test_smj_streaming_types(how):
    """Streaming merge windows: small batches force many frontiers, a
    skewed key makes groups straddle batch boundaries."""
    rng = np.random.default_rng(21)
    left, right = make_sides(rng, nl=400, nr=300, key_range=25)
    # skew one key so a single group spans several 32-row batches
    for r in left[:90]:
        r["lk"] = 7
    left, right = sort_rows(left, "lk"), sort_rows(right, "rk")
    on = JoinOn(left_keys=(col("lk"),), right_keys=(col("rk"),))
    smj = SortMergeJoinExec(scan_of(left, chunk=32), scan_of(right, chunk=32),
                            on, how)
    got = collect(smj)
    exp = oracle_join(left, right, "lk", "rk", how)
    assert canon(got) == canon(exp), how


@pytest.mark.slow   # PR 18 tier-1 re-split (10.8s; smj core parity
# stays via test_smj_matches_hash_join)
def test_smj_string_keys():
    rng = np.random.default_rng(22)
    words = ["ant", "bee", "cat", "dog", "elk", "fox", None, "anteater"]
    left = [{"lk": words[int(rng.integers(0, len(words)))], "lv": i}
            for i in range(150)]
    right = [{"rk": words[int(rng.integers(0, len(words)))], "rv": 500 + i}
             for i in range(120)]
    left = sorted(left, key=lambda r: (r["lk"] is not None, r["lk"] or ""))
    right = sorted(right, key=lambda r: (r["rk"] is not None, r["rk"] or ""))
    on = JoinOn(left_keys=(col("lk"),), right_keys=(col("rk"),))
    for how in ("inner", "full", "left_anti"):
        smj = SortMergeJoinExec(scan_of(left, chunk=16),
                                scan_of(right, chunk=16), on, how)
        exp = oracle_join(left, right, "lk", "rk", how)
        assert canon(collect(smj)) == canon(exp), how


def test_smj_oversized_string_keys_hybrid():
    """String keys longer than auron.string.device.max.width arrive as
    HostColumns; the streaming SMJ must route them through the host key
    path + eager probe instead of the device kernels."""
    from auron_tpu.config import conf
    rng = np.random.default_rng(31)
    keys = ["k" * 300 + str(i) for i in range(6)] + [None]
    left = [{"lk": keys[int(rng.integers(0, len(keys)))], "lv": i}
            for i in range(80)]
    right = [{"rk": keys[int(rng.integers(0, len(keys)))], "rv": 300 + i}
             for i in range(60)]
    left = sorted(left, key=lambda r: (r["lk"] is not None, r["lk"] or ""))
    right = sorted(right, key=lambda r: (r["rk"] is not None, r["rk"] or ""))
    on = JoinOn(left_keys=(col("lk"),), right_keys=(col("rk"),))
    for how in ("inner", "full"):
        smj = SortMergeJoinExec(scan_of(left, chunk=16),
                                scan_of(right, chunk=16), on, how)
        exp = oracle_join(left, right, "lk", "rk", how)
        assert canon(collect(smj)) == canon(exp), how


def test_smj_truncation_tied_string_keys():
    """Distinct oversized keys sharing the first 256 bytes AND the same
    length tie under the engine's truncated string preorder; they must
    land in one SMJ window where exact hash matching separates them."""
    ka = "x" * 256 + "aa"
    kb = "x" * 256 + "ab"
    left = ([{"lk": ka, "lv": i} for i in range(8)]
            + [{"lk": kb, "lv": 100 + i} for i in range(8)])
    right = ([{"rk": ka, "rv": 200 + i} for i in range(5)]
             + [{"rk": kb, "rv": 300 + i} for i in range(5)])
    on = JoinOn(left_keys=(col("lk"),), right_keys=(col("rk"),))
    for how in ("inner", "full", "left_semi"):
        smj = SortMergeJoinExec(scan_of(left, chunk=4),
                                scan_of(right, chunk=4), on, how)
        exp = oracle_join(left, right, "lk", "rk", how)
        assert canon(collect(smj)) == canon(exp), how


def test_smj_adversarial_shared_prefix_corpus():
    """VERDICT r2 weak #8: an ENTIRE corpus of join keys sharing a
    >=max-width prefix and the same length ties under the truncated
    preorder, collapsing every row into ONE SMJ window — bounded memory
    degenerates to full materialization, and the spill path must keep
    results exact under a tiny budget."""
    from auron_tpu.config import conf
    from auron_tpu.memmgr.manager import reset_manager
    rng = np.random.default_rng(31)
    pref = "p" * 256
    # distinct suffixes but SAME length: every key ties with every other
    nk = 40
    keys = [pref + f"{i:04d}" for i in range(nk)]
    left = [{"lk": keys[int(rng.integers(0, nk))], "lv": i}
            for i in range(400)]
    right = [{"rk": keys[int(rng.integers(0, nk))], "rv": 1000 + i}
             for i in range(300)]
    on = JoinOn(left_keys=(col("lk"),), right_keys=(col("rk"),))
    mgr = reset_manager(budget_bytes=1)
    try:
        with conf.scoped({"auron.memory.spill.min.trigger.bytes": 1}):
            smj = SortMergeJoinExec(scan_of(sort_rows(left, "lk"),
                                            chunk=64),
                                    scan_of(sort_rows(right, "rk"),
                                            chunk=64), on, "inner")
            got = collect(smj)
            assert mgr.num_spills > 0, \
                "the one-window corpus must exercise spill"
    finally:
        reset_manager()
    exp = oracle_join(left, right, "lk", "rk", "inner")
    assert canon(got) == canon(exp)


@pytest.mark.parametrize("how", ["inner", "full", "left_anti"])
def test_smj_spill_tiny_budget(how):
    """Tiny-budget fuzz: the buffered-side spill path must activate and
    results stay exact (the joins analogue of test_ops_basic.py's sort/agg
    spill fuzz tests, sort_exec.rs:1512-1698)."""
    from auron_tpu.config import conf
    from auron_tpu.memmgr import get_manager
    from auron_tpu.memmgr.manager import reset_manager
    rng = np.random.default_rng(23)
    left, right = make_sides(rng, nl=600, nr=500, key_range=12)
    for r in left[:200]:
        r["lk"] = 3  # giant group: forces a wide buffered window
    left, right = sort_rows(left, "lk"), sort_rows(right, "rk")
    on = JoinOn(left_keys=(col("lk"),), right_keys=(col("rk"),))
    mgr = reset_manager(budget_bytes=1)
    try:
        with conf.scoped({"auron.memory.spill.min.trigger.bytes": 1}):
            smj = SortMergeJoinExec(scan_of(left, chunk=64),
                                    scan_of(right, chunk=64), on, how)
            got = collect(smj)
            assert mgr.num_spills > 0, "budget=1 must force join spills"
    finally:
        reset_manager()
    exp = oracle_join(left, right, "lk", "rk", how)
    assert canon(got) == canon(exp), how


def test_broadcast_join_cache():
    rng = np.random.default_rng(9)
    left, right = make_sides(rng, nl=100, nr=50)
    on = JoinOn(left_keys=(col("lk"),), right_keys=(col("rk"),))
    ctx = TaskContext()
    # build-map stage primes the cache
    bm = BroadcastJoinBuildHashMapExec(scan_of(right), (col("rk"),), "t1")
    list(bm.execute_with_metrics(ctx))
    assert ctx.resources.contains("bhm:t1")
    bj = BroadcastJoinExec(scan_of(left), scan_of(right), on, "inner",
                           broadcast_side="right",
                           cached_build_hash_map_id="t1")
    out = [b.to_arrow() for b in bj.execute_with_metrics(ctx)]
    got = pa.Table.from_batches(out).to_pylist() if out else []
    exp = oracle_join(left, right, "lk", "rk", "inner")
    assert canon(got) == canon(exp)


def test_empty_sides():
    left = [{"lk": 1, "lv": 2}]
    on = JoinOn(left_keys=(col("lk"),), right_keys=(col("rk"),))
    empty_r = scan_of([], schema=pa.schema([("rk", pa.int64()),
                                            ("rv", pa.int64())]))
    out = collect(HashJoinExec(scan_of(left), empty_r, on, "left"))
    assert out == [{"lk": 1, "lv": 2, "rk": None, "rv": None}]
    out = collect(HashJoinExec(scan_of(left), empty_r, on, "inner"))
    assert out == []
