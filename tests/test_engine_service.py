"""Out-of-process engine boundary tests (JniBridge analogue): a separate
engine process driven over the socket with serialized plans + Arrow
resources, mirroring how AuronCallNativeWrapper drives native execution
(AuronCallNativeWrapper.java:78-183)."""

import json
import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pytest

from auron_tpu.ir import plan as P
from auron_tpu.ir import serde as ir_serde
from auron_tpu.ir.expr import AggExpr, col, lit
from auron_tpu.ir.schema import from_arrow_schema
from auron_tpu.service import EngineClient, EngineServer
from auron_tpu.service.engine import RemoteExecutionError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_table(n=400):
    rng = np.random.default_rng(17)
    return pa.Table.from_pylist(
        [{"g": int(rng.integers(0, 10)), "v": float(rng.normal())}
         for _ in range(n)])


def agg_plan(table, resource="T"):
    from auron_tpu.ir import expr as E
    from auron_tpu.ir.schema import DataType
    src = P.FFIReader(schema=from_arrow_schema(table.schema),
                      resource_id=resource)
    filt = P.Filter(child=src, predicates=(
        E.BinaryExpr(left=col("v"), op=">", right=lit(-1.0)),))
    return P.Agg(
        child=filt, exec_mode="single",
        grouping=(col("g"),), grouping_names=("g",),
        aggs=(AggExpr(fn="sum", children=(col("v"),),
                      return_type=DataType.float64()),
              AggExpr(fn="count", children=(col("v"),),
                      return_type=DataType.int64())),
        agg_names=("sv", "cv"))


def canon(rows):
    return sorted((r["g"], round(r["sv"], 6), r["cv"]) for r in rows)


def expected(table):
    rows = table.to_pylist()
    agg = {}
    for r in rows:
        if r["v"] > -1.0:
            s, c = agg.get(r["g"], (0.0, 0))
            agg[r["g"]] = (s + r["v"], c + 1)
    return sorted((g, round(s, 6), c) for g, (s, c) in agg.items())


def test_engine_service_in_thread():
    table = make_table()
    server = EngineServer().start()
    try:
        host, port = server.address
        with EngineClient(host, port) as cli:
            assert cli.ping()
            cli.put_arrow("T", table)
            td = P.TaskDefinition(plan=agg_plan(table), partition_id=0,
                                  num_partitions=1)
            out = cli.execute(ir_serde.serialize(td))
            assert canon(out.to_pylist()) == expected(table)
            assert cli.last_metrics  # metrics tree ferried back
    finally:
        server.stop()


def test_engine_service_error_ferry_keeps_connection():
    table = make_table(50)
    server = EngineServer().start()
    try:
        host, port = server.address
        with EngineClient(host, port) as cli:
            td = P.TaskDefinition(plan=agg_plan(table, resource="missing"))
            with pytest.raises(RemoteExecutionError) as ei:
                cli.execute(td)
            assert ei.value.remote_traceback
            # the channel survives a ferried failure (rt.rs:207-238)
            assert cli.ping()
            cli.put_arrow("T", table)
            out = cli.execute(P.TaskDefinition(plan=agg_plan(table)))
            assert canon(out.to_pylist()) == expected(table)
    finally:
        server.stop()


def test_engine_service_resource_upcall():
    """Mid-execution resource upcall: the engine misses a resource, asks
    the driving host on the same channel, and the host streams it inline
    (the JavaClasses getResource / ArrowFFIExporter flow)."""
    table = make_table(200)
    server = EngineServer().start()
    try:
        host, port = server.address
        with EngineClient(host, port) as cli:
            served = []

            def lazy_source():
                served.append(True)
                return table

            cli.provide("T", lazy_source)
            out = cli.execute(P.TaskDefinition(plan=agg_plan(table)))
            assert served, "engine never issued the upcall"
            assert canon(out.to_pylist()) == expected(table)
            # second execute: resource now cached server-side, no upcall
            served.clear()
            out = cli.execute(P.TaskDefinition(plan=agg_plan(table)))
            assert not served
            assert canon(out.to_pylist()) == expected(table)
    finally:
        server.stop()


def test_engine_service_subprocess():
    """A real foreign process: spawn the service, drive a plan over the
    socket end-to-end."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "auron_tpu.service.engine", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        cwd=REPO, text=True)
    try:
        line = proc.stdout.readline()
        info = json.loads(line)
        assert info["event"] == "listening"
        table = make_table()
        with EngineClient(info["host"], info["port"], timeout=900.0) as cli:
            assert cli.ping()
            cli.put_arrow("T", table)
            td = P.TaskDefinition(plan=agg_plan(table))
            out = cli.execute(ir_serde.serialize(td))
            assert canon(out.to_pylist()) == expected(table)
            cli.shutdown_server()
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_engine_client_retries_injected_dispatch_fault():
    """A server-side dispatch fault severs the connection; the client's
    retry reconnects and replays the (idempotent) control call."""
    from auron_tpu import faults
    from auron_tpu.config import conf
    table = make_table(50)
    server = EngineServer().start()
    try:
        host, port = server.address
        with EngineClient(host, port) as cli:
            assert cli.ping()
            spec = "service.dispatch:io:p=1,max=1,seed=1"
            faults.reset(spec)
            with conf.scoped({"auron.faults.spec": spec,
                              "auron.retry.backoff.base.ms": 1.0}):
                cli.put_arrow("T", table)   # dropped once, then replayed
            assert faults.registry_for(spec).injected_total() == 1
            out = cli.execute(P.TaskDefinition(plan=agg_plan(table)))
            assert canon(out.to_pylist()) == expected(table)
    finally:
        server.stop()


def test_engine_client_retries_injected_client_fault_on_execute():
    """An injected client-side fault before the first result batch
    replays the execute on a fresh connection."""
    from auron_tpu import faults
    from auron_tpu.config import conf
    table = make_table(50)
    server = EngineServer().start()
    try:
        host, port = server.address
        with EngineClient(host, port) as cli:
            cli.put_arrow("T", table)
            spec = "service.call:io:p=1,max=1,seed=1"
            faults.reset(spec)
            with conf.scoped({"auron.faults.spec": spec,
                              "auron.retry.backoff.base.ms": 1.0}):
                out = cli.execute(P.TaskDefinition(plan=agg_plan(table)))
            assert canon(out.to_pylist()) == expected(table)
    finally:
        server.stop()


def test_engine_server_read_timeout_disconnects_idle_client():
    """A half-dead client is disconnected after the read timeout instead
    of pinning a handler thread; the client's next call transparently
    reconnects."""
    import time

    from auron_tpu.config import conf
    with conf.scoped({"auron.service.read.timeout.seconds": 0.2}):
        server = EngineServer().start()
        try:
            host, port = server.address
            with EngineClient(host, port) as cli:
                assert cli.ping()
                first_sock = cli._sock
                time.sleep(0.6)       # idle past the server read timeout
                with conf.scoped({"auron.retry.backoff.base.ms": 1.0}):
                    assert cli.ping()  # reconnected under the hood
                assert cli._sock is not first_sock
        finally:
            server.stop()
